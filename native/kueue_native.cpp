// kueue_native — the hot-path runtime core in C++.
//
// The reference's control plane is compiled Go (SURVEY.md §2); the
// TPU build keeps JAX/XLA for the batched solver and uses this native
// library for the serving-path data structures around it:
//
//  - a keyed binary heap with the pending-queue ordering
//    (priority desc, timestamp asc — pkg/queue/cluster_queue.go:413-426
//    and pkg/util/heap), push-or-update / delete-by-key / pop;
//  - cohort quota-tree math over flat arrays (subtreeQuota /
//    available / addUsage bubble-up — pkg/cache/resource_node.go),
//    the CPU mirror of ops/quota.py for small host-side problems.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- heap

struct HeapEntry {
  int64_t key;
  int64_t priority;   // higher pops first
  int64_t timestamp;  // lower pops first among equal priorities
  int64_t seq;        // FIFO tie-break for full determinism
};

struct Heap {
  std::vector<HeapEntry> items;              // binary heap
  std::unordered_map<int64_t, size_t> index; // key -> position
  int64_t next_seq = 0;
};

static bool heap_less(const HeapEntry& a, const HeapEntry& b) {
  // "a pops before b"
  if (a.priority != b.priority) return a.priority > b.priority;
  if (a.timestamp != b.timestamp) return a.timestamp < b.timestamp;
  return a.seq < b.seq;
}

static void heap_swap(Heap* h, size_t i, size_t j) {
  std::swap(h->items[i], h->items[j]);
  h->index[h->items[i].key] = i;
  h->index[h->items[j].key] = j;
}

static void sift_up(Heap* h, size_t i) {
  while (i > 0) {
    size_t parent = (i - 1) / 2;
    if (!heap_less(h->items[i], h->items[parent])) break;
    heap_swap(h, i, parent);
    i = parent;
  }
}

static void sift_down(Heap* h, size_t i) {
  size_t n = h->items.size();
  for (;;) {
    size_t left = 2 * i + 1, right = 2 * i + 2, best = i;
    if (left < n && heap_less(h->items[left], h->items[best])) best = left;
    if (right < n && heap_less(h->items[right], h->items[best])) best = right;
    if (best == i) break;
    heap_swap(h, i, best);
    i = best;
  }
}

Heap* heap_new() { return new Heap(); }

void heap_free(Heap* h) { delete h; }

int heap_len(const Heap* h) { return static_cast<int>(h->items.size()); }

int heap_contains(const Heap* h, int64_t key) {
  return h->index.count(key) ? 1 : 0;
}

// Push a new entry or update an existing one (PushOrUpdate). Updates
// take a fresh seq — the Python fallback's push_or_update re-pushes the
// entry, so among exact rank ties an updated entry pops AFTER its
// peers; the two implementations must order identically.
void heap_push(Heap* h, int64_t key, int64_t priority, int64_t timestamp) {
  auto it = h->index.find(key);
  if (it != h->index.end()) {
    size_t i = it->second;
    h->items[i].priority = priority;
    h->items[i].timestamp = timestamp;
    h->items[i].seq = h->next_seq++;
    sift_up(h, i);
    sift_down(h, i);
    return;
  }
  HeapEntry e{key, priority, timestamp, h->next_seq++};
  h->items.push_back(e);
  h->index[key] = h->items.size() - 1;
  sift_up(h, h->items.size() - 1);
}

// Push only if absent (PushIfNotPresent). Returns 1 if pushed.
int heap_push_if_not_present(Heap* h, int64_t key, int64_t priority,
                             int64_t timestamp) {
  if (h->index.count(key)) return 0;
  heap_push(h, key, priority, timestamp);
  return 1;
}

int heap_delete_key(Heap* h, int64_t key) {
  auto it = h->index.find(key);
  if (it == h->index.end()) return 0;
  size_t i = it->second;
  size_t last = h->items.size() - 1;
  if (i != last) heap_swap(h, i, last);
  h->index.erase(h->items.back().key);
  h->items.pop_back();
  if (i < h->items.size()) {
    sift_up(h, i);
    sift_down(h, i);
  }
  return 1;
}

// Pop the head; returns its key or -1 when empty.
int64_t heap_pop(Heap* h) {
  if (h->items.empty()) return -1;
  int64_t key = h->items[0].key;
  heap_delete_key(h, key);
  return key;
}

int64_t heap_peek(const Heap* h) {
  return h->items.empty() ? -1 : h->items[0].key;
}

// ------------------------------------------------------ quota tree math
//
// Flat layout shared with ops/quota.py: N nodes (CQs then cohorts),
// FR flavor-resource cells, parent[i] = parent node or -1, order =
// node indices sorted deepest-level-first (callers precompute).
// NO_LIMIT sentinel matches ops/quota.py (1<<60).

static const int64_t NO_LIMIT = 1ll << 60;

static inline int64_t guaranteed_of(int64_t subtree, int64_t lending) {
  if (lending < NO_LIMIT) {
    int64_t g = subtree - lending;
    return g > 0 ? g : 0;
  }
  return 0;
}

// subtreeQuota + guaranteedQuota (resource_node.go:157-193).
void quota_subtree(const int32_t* parent, const int32_t* order, int n, int fr,
                   const int64_t* nominal, const int64_t* lending,
                   int64_t* subtree, int64_t* guaranteed) {
  std::memcpy(subtree, nominal, sizeof(int64_t) * n * fr);
  for (int oi = 0; oi < n; ++oi) {
    int i = order[oi];
    int p = parent[i];
    for (int j = 0; j < fr; ++j) {
      int64_t g = guaranteed_of(subtree[i * fr + j], lending[i * fr + j]);
      guaranteed[i * fr + j] = g;
      if (p >= 0) subtree[p * fr + j] += subtree[i * fr + j] - g;
    }
  }
  // guaranteed of roots computed above in the same pass (order covers
  // every node; roots simply have no parent write)
}

// Usage tree from leaf usage (bubble-up of over-guaranteed amounts).
void quota_usage_tree(const int32_t* parent, const int32_t* order, int n,
                      int fr, const int64_t* guaranteed,
                      const int64_t* local_usage, int64_t* usage) {
  std::memcpy(usage, local_usage, sizeof(int64_t) * n * fr);
  for (int oi = 0; oi < n; ++oi) {
    int i = order[oi];
    int p = parent[i];
    if (p < 0) continue;
    for (int j = 0; j < fr; ++j) {
      int64_t over = usage[i * fr + j] - guaranteed[i * fr + j];
      if (over > 0) usage[p * fr + j] += over;
    }
  }
}

// available() for ONE node (resource_node.go:89-104), walking the
// ancestor path root-down. path = [node, parent, ..., root, -1...].
void quota_available_node(const int32_t* path, int path_len, int fr,
                          const int64_t* subtree, const int64_t* guaranteed,
                          const int64_t* borrowing, const int64_t* usage,
                          int64_t* out) {
  int depth = 0;
  while (depth < path_len && path[depth] >= 0) depth++;
  if (depth == 0) {  // empty path: nothing available, no OOB read
    for (int j = 0; j < fr; ++j) out[j] = 0;
    return;
  }
  for (int j = 0; j < fr; ++j) {
    int root = path[depth - 1];
    int64_t avail = subtree[root * fr + j] - usage[root * fr + j];
    for (int d = depth - 2; d >= 0; --d) {
      int i = path[d];
      int64_t stored = subtree[i * fr + j] - guaranteed[i * fr + j];
      int64_t used = usage[i * fr + j] - guaranteed[i * fr + j];
      if (used < 0) used = 0;
      int64_t clamped = avail;
      if (borrowing[i * fr + j] < NO_LIMIT) {
        int64_t with_max = stored - used + borrowing[i * fr + j];
        if (with_max < clamped) clamped = with_max;
      }
      int64_t local = guaranteed[i * fr + j] - usage[i * fr + j];
      if (local < 0) local = 0;
      avail = local + clamped;
    }
    out[j] = avail;
  }
}

// addUsage bubble-up for one node (resource_node.go:123-144).
// sign=+1 add, -1 remove. Mutates the full usage tree in place.
void quota_add_usage(const int32_t* path, int path_len, int fr,
                     const int64_t* guaranteed, const int64_t* delta, int sign,
                     int64_t* usage) {
  std::vector<int64_t> d(delta, delta + fr);
  for (int j = 0; j < fr; ++j) d[j] *= sign;
  int depth = 0;
  while (depth < path_len && path[depth] >= 0) depth++;
  for (int lvl = 0; lvl < depth; ++lvl) {
    int i = path[lvl];
    for (int j = 0; j < fr; ++j) {
      int64_t old_u = usage[i * fr + j];
      int64_t new_u = old_u + d[j];
      usage[i * fr + j] = new_u;
      int64_t g = guaranteed[i * fr + j];
      int64_t over_old = old_u - g > 0 ? old_u - g : 0;
      int64_t over_new = new_u - g > 0 ? new_u - g : 0;
      d[j] = over_new - over_old;
    }
  }
}

}  // extern "C"
