"""Benchmark: one batched admission cycle on the accelerator.

Scenario sized to the north star in BASELINE.json — 1k ClusterQueues in
a 2-level cohort forest, a full cycle of nominated heads (one per CQ,
padded to 1024), 4 flavor candidates x 4 requested cells each — and
measures end-to-end device latency of ``solve_cycle`` (phase-1 vmapped
flavor classification + phase-2 scan conflict resolution), the TPU
re-expression of the reference hot path
``pkg/scheduler/scheduler.go:176-310``.

Baseline: the north-star budget of 100 ms per scheduling cycle
(BASELINE.json "north_star"; the Go reference's measured cycle
histogram is `admission_attempt_duration_seconds`). vs_baseline is the
speedup factor: baseline_ms / measured_ms (>1 = faster than budget).

Prints exactly ONE JSON line.
"""

from __future__ import annotations

import json
import time

import numpy as np

N_CQ = 1000
N_COHORT = 50
FR = 32
W = 1024  # heads per cycle (padded); reference admits <= one head per CQ
K = 4  # flavor candidates per head
C = 4  # requested (flavor,resource) cells per candidate
BASELINE_MS = 100.0
REPS = 30


def build_problem(seed: int = 0):
    from kueue_tpu._jax import jnp
    from kueue_tpu.ops.assign_kernel import HeadsBatch, build_paths
    from kueue_tpu.ops.quota import NO_LIMIT, QuotaTree

    rng = np.random.default_rng(seed)
    n = N_CQ + N_COHORT
    parent = np.full(n, -1, dtype=np.int32)
    parent[:N_CQ] = N_CQ + rng.integers(0, N_COHORT, size=N_CQ)
    level_mask = np.zeros((2, n), dtype=bool)
    level_mask[0, N_CQ:] = True  # cohort roots at depth 0
    level_mask[1, :N_CQ] = True  # ClusterQueues at depth 1

    nominal = np.zeros((n, FR), dtype=np.int64)
    nominal[:N_CQ] = rng.integers(50, 500, size=(N_CQ, FR))
    limits = np.full((n, FR), NO_LIMIT, dtype=np.int64)

    tree = QuotaTree(
        parent=jnp.asarray(parent),
        level_mask=jnp.asarray(level_mask),
        nominal=jnp.asarray(nominal),
        lending_limit=jnp.asarray(limits),
        borrowing_limit=jnp.asarray(limits),
    )
    paths = jnp.asarray(build_paths(parent, 1))

    local_usage = np.zeros((n, FR), dtype=np.int64)
    local_usage[:N_CQ] = rng.integers(0, 200, size=(N_CQ, FR))

    cq_row = np.full(W, -1, dtype=np.int32)
    cq_row[:N_CQ] = np.arange(N_CQ)
    cells = np.full((W, K, C), -1, dtype=np.int32)
    qty = np.zeros((W, K, C), dtype=np.int64)
    valid = np.zeros((W, K), dtype=bool)
    cells[:N_CQ] = rng.integers(0, FR, size=(N_CQ, K, C))
    qty[:N_CQ] = rng.integers(1, 60, size=(N_CQ, K, C))
    valid[:N_CQ] = True
    batch = HeadsBatch(
        cq_row=jnp.asarray(cq_row),
        cells=jnp.asarray(cells),
        qty=jnp.asarray(qty),
        valid=jnp.asarray(valid),
        priority=jnp.asarray(rng.integers(0, 100, size=W).astype(np.int64)),
        timestamp=jnp.asarray(np.arange(W, dtype=np.int64)),
        no_reclaim=jnp.asarray(np.zeros(W, dtype=bool)),
    )
    return tree, jnp.asarray(local_usage), batch, paths


def main():
    import jax

    from kueue_tpu.ops.assign_kernel import solve_cycle_jit

    tree, local_usage, batch, paths = build_problem()

    # warmup / compile (host fetch forces real completion — on some
    # experimental platforms block_until_ready returns at enqueue time)
    out = solve_cycle_jit(tree, local_usage, batch, paths)
    np.asarray(out.admitted)

    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = solve_cycle_jit(tree, local_usage, batch, paths)
        np.asarray(out.admitted)  # device->host sync
        times.append((time.perf_counter() - t0) * 1e3)
    ms = float(np.median(times))

    print(
        json.dumps(
            {
                "metric": f"admission_cycle_latency ({W} heads x {N_CQ} CQs, K={K}, FR={FR})",
                "value": round(ms, 3),
                "unit": "ms/cycle",
                "vs_baseline": round(BASELINE_MS / ms, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
