"""Benchmark: the north-star drain through the production pipeline.

Scenario per BASELINE.md's north star: 50k pending workloads across
1k ClusterQueues (50 cohorts, 8 flavors per CQ, borrowing enabled),
drained to quiescence. The measurement covers the ENTIRE pipeline the
framework runs for a bulk backlog:

  real model objects -> candidate lowering (core/solver.lower_heads,
  memoized templates) -> per-CQ queue packing (core/drain.plan_drain)
  -> multi-cycle device drain (ops/drain_kernel.solve_drain: phase-1
  vmapped flavor classification + segmented phase-2 conflict
  resolution per cycle, heads re-popped each cycle) -> ONE device
  fetch -> decision map-back.

Reported value is wall-clock milliseconds per scheduling cycle
(total / cycles executed), the same unit as the reference's
`admission_attempt_duration_seconds` histogram and the 100 ms/cycle
north-star budget (reference hot path:
``pkg/scheduler/scheduler.go:176-310``). vs_baseline is the speedup
factor: baseline_ms / measured_ms (>1 = faster than budget).

Decision parity of this exact pipeline with the sequential host
scheduler is asserted in tests/test_drain.py.

Prints exactly ONE JSON line — ALWAYS, regardless of backend health.
``python bench.py`` runs a wedge-proof driver: a bounded-timeout
subprocess probe decides whether the remote-attached TPU backend is
alive (the tunnel has been observed to hang ``jax.devices()``
indefinitely), then the benchmark payload runs in a subprocess with its
own timeout. If the TPU is wedged or dies mid-run, the payload is
re-run pinned to CPU and the emitted line carries
``{"backend": "cpu-fallback", "tpu_error": "..."}`` instead of a stack
trace; a healthy run carries ``{"backend": "tpu"}``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

PROBE_TIMEOUT_S = 150
PAYLOAD_TIMEOUT_S = 2400

N_CQ = 1000
N_COHORT = 50
N_FLAVORS = 8
WL_PER_CQ = 50  # 50k total
BASELINE_MS = 100.0

# ---- per-stage repetition spread ----
# Helpers record their raw rep times here so every stage's JSON can
# report the median-of-reps PLUS min/max spread — the ±15% tunnel
# variance documented in BENCH_NOTES_r05.md makes single-shot numbers
# unreliable, and the spread makes run-to-run noise visible in the
# artifact itself. Stages run one-per-subprocess, so the module global
# is effectively per-stage.
_REP_TIMES: dict = {}


def _note_times(key: str, times_s) -> None:
    _REP_TIMES[key] = [float(t) for t in times_s]


def _spread_of(key: str, scale: float = 1e3):
    """{"reps", "median", "min", "max"} of a recorded rep series,
    scaled (default seconds -> ms); None when the helper didn't run."""
    ts = _REP_TIMES.get(key)
    if not ts:
        return None
    return {
        "reps": len(ts),
        "median": round(float(np.median(ts)) * scale, 3),
        "min": round(min(ts) * scale, 3),
        "max": round(max(ts) * scale, 3),
    }


def build_cluster(rng):
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.core.cache import Cache
    from kueue_tpu.core.queue_manager import QueueManager
    from kueue_tpu.utils.clock import FakeClock

    clock = FakeClock(0.0)
    cache = Cache()
    mgr = QueueManager(clock)
    flavors = [f"fl-{i}" for i in range(N_FLAVORS)]
    for f in flavors + ["gpu-fl"]:
        cache.add_or_update_flavor(ResourceFlavor(name=f))
    for i in range(N_CQ):
        name = f"cq-{i}"
        quotas = tuple(
            FlavorQuotas.build(
                f,
                {
                    "cpu": (
                        str(int(rng.integers(8, 64))),
                        str(int(rng.integers(8, 32))),  # borrowingLimit
                        None,
                    ),
                    "memory": (
                        f"{int(rng.integers(16, 128))}Gi",
                        f"{int(rng.integers(16, 64))}Gi",
                        None,
                    ),
                },
            )
            for f in flavors
        )
        # second resource group (single accelerator flavor): ~a third
        # of the backlog requests gpus, so the drain's per-group cursor
        # vectors and cartesian candidates run at full 50k scale
        gpu_quota = (
            FlavorQuotas.build(
                "gpu-fl",
                {"gpu": (str(int(rng.integers(4, 16))),
                         str(int(rng.integers(2, 8))), None)},
            ),
        )
        cq = ClusterQueue(
            name=name,
            cohort=f"cohort-{i % N_COHORT}",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu", "memory"), quotas),
                ResourceGroup(("gpu",), gpu_quota),
            ),
        )
        cache.add_or_update_cluster_queue(cq)
        mgr.add_cluster_queue(cq)
        mgr.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{name}", cluster_queue=name)
        )
    return cache, mgr


def build_backlog(rng):
    from kueue_tpu.models import Workload
    from kueue_tpu.models.workload import PodSet

    pending = []
    n = N_CQ * WL_PER_CQ
    prios = rng.integers(0, 4, size=n) * 50
    cpus = rng.integers(1, 16, size=n)
    mems = rng.integers(1, 32, size=n)
    gpus = rng.integers(1, 3, size=n)
    wants_gpu = rng.random(size=n) < 0.33
    counts = rng.integers(1, 5, size=n)
    for i in range(n):
        cq = f"cq-{i % N_CQ}"
        requests = {"cpu": str(cpus[i]), "memory": f"{mems[i]}Gi"}
        if wants_gpu[i]:
            requests["gpu"] = str(gpus[i])  # second resource group
        # single-podset backlog: at this contention level a multi-podset
        # mix makes thousands of heads PendingFlavors spinners (the
        # reference's immediate-requeue semantics never decide them), so
        # the headline drain stays fully decidable; multi-podset drains
        # are covered by tests/test_drain.py TestDrainMultiPodset
        wl = Workload(
            namespace="ns",
            name=f"w{i}",
            queue_name=f"lq-{cq}",
            priority=int(prios[i]),
            creation_time=float(i),
            pod_sets=(PodSet.build("main", int(counts[i]), requests),),
        )
        pending.append((wl, cq))
    # per-CQ heap order: priority desc, timestamp asc
    pending.sort(key=lambda t: (t[1], -t[0].priority, t[0].creation_time))
    return pending


def contended_drain_bench(rng, mesh=None):
    """Contended drain with CROSS-CQ cohort reclamation: per 10-CQ
    cohort, five "hoarder" ClusterQueues sit saturated ABOVE their
    nominal quota (borrowing from the cohort; they never preempt), and
    five "reclaimer" CQs hold a higher-priority backlog that can only
    start by reclaiming that borrowed capacity (preemption.go:480-524)
    — plus within-CQ preemption of the reclaimers' own victims, and
    drain-admitted workloads becoming reclaim candidates themselves
    (part-B pool slots). The WHOLE multi-cycle drain — the strategy
    ladder with borrowWithinCohort thresholds, in-cycle fits re-checks,
    cross-CQ evictions, and follow-up admissions — runs on the device
    in ONE dispatch + ONE fetch (ops/drain_kernel.solve_drain_preempt).
    Decision parity with the sequential host scheduler is asserted in
    tests/test_drain.py TestPreemptDrainCohortReclaim. With ``mesh``
    the per-queue tensors shard across devices (the --sharded A/B).
    Returns (ms/cycle, cycles, admitted, evicted, decision_sig)."""
    import time

    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        Preemption,
        ResourceFlavor,
        Workload,
        WorkloadConditionType,
    )
    from kueue_tpu.models.cluster_queue import BorrowWithinCohort, ResourceGroup
    from kueue_tpu.models.constants import (
        BorrowWithinCohortPolicy,
        PreemptionPolicy,
        ReclaimWithinCohortPolicy,
    )
    from kueue_tpu.models.workload import PodSet
    from kueue_tpu.core.cache import Cache
    from kueue_tpu.core.drain import run_drain_preempt
    from kueue_tpu.core.queue_manager import QueueManager, queue_order_timestamp
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.core.workload_info import make_admission
    from kueue_tpu.utils.clock import FakeClock

    n_cq, cohort_size = 1000, 10
    hoarder_victims, reclaimer_victims, wl_per_reclaimer = 8, 4, 10
    clock = FakeClock(0.0)
    cache = Cache()
    mgr = QueueManager(clock)
    cache.add_or_update_flavor(ResourceFlavor(name="default"))
    for i in range(n_cq):
        name = f"ccq-{i}"
        hoarder = (i % cohort_size) < cohort_size // 2
        if hoarder:
            prem = Preemption()  # never preempts; a pure reclaim target
        else:
            prem = Preemption(
                within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
                reclaim_within_cohort=(
                    ReclaimWithinCohortPolicy.ANY
                    if i % 2
                    else ReclaimWithinCohortPolicy.LOWER_PRIORITY
                ),
                borrow_within_cohort=(
                    BorrowWithinCohort(
                        policy=BorrowWithinCohortPolicy.LOWER_PRIORITY,
                        max_priority_threshold=60,
                    )
                    if i % 3 == 0
                    else BorrowWithinCohort()
                ),
            )
        cq = ClusterQueue(
            name=name,
            cohort=f"ccohort-{i // cohort_size}",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",),
                    (FlavorQuotas.build("default", {"cpu": "16"}),),
                ),
            ),
            preemption=prem,
        )
        cache.add_or_update_cluster_queue(cq)
        mgr.add_cluster_queue(cq)
        mgr.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{name}", cluster_queue=name)
        )
        # hoarders: 8 x 3 = 24 > nominal 16 (borrowing 8 from the
        # cohort); reclaimers: 4 x 2 = 8 (room for their own backlog)
        n_vic = hoarder_victims if hoarder else reclaimer_victims
        v_cpu = "3" if hoarder else "2"
        for v in range(n_vic):
            wl = Workload(
                namespace="ns", name=f"victim-{i}-{v}",
                queue_name=f"lq-{name}", priority=int(rng.integers(0, 40)),
                pod_sets=(PodSet.build("main", 1, {"cpu": v_cpu}),),
            )
            wl.admission = make_admission(name, {"main": {"cpu": "default"}}, wl)
            wl.set_condition(
                WorkloadConditionType.QUOTA_RESERVED, True,
                reason="QuotaReserved", now=float(v),
            )
            cache.add_or_update_workload(wl)
        if not hoarder:
            for w in range(wl_per_reclaimer):
                mgr.add_or_update_workload(
                    Workload(
                        namespace="ns", name=f"pre-{i}-{w}",
                        queue_name=f"lq-{name}",
                        priority=50 + 10 * int(rng.integers(0, 6)),
                        creation_time=float(i * wl_per_reclaimer + w),
                        pod_sets=(
                            PodSet.build(
                                "main", 1, {"cpu": str(int(rng.integers(2, 8)))}
                            ),
                        ),
                    )
                )
    pending = []
    for cq_name, pq in mgr.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            pending.append((wl, cq_name))
    ts_fn = lambda wl: queue_order_timestamp(wl, mgr._ts_policy)  # noqa: E731

    snapshot = take_snapshot(cache)
    run_drain_preempt(
        snapshot, pending, cache.flavors, timestamp_fn=ts_fn,
        search_width=64, mesh=mesh,
    )

    times = []
    for _ in range(3):
        snapshot = take_snapshot(cache)
        t0 = time.perf_counter()
        outcome = run_drain_preempt(
            snapshot, pending, cache.flavors, timestamp_fn=ts_fn,
            search_width=64, mesh=mesh,
        )
        times.append(time.perf_counter() - t0)
    assert not outcome.fallback and not outcome.truncated
    assert outcome.preempted and outcome.admitted
    # cross-CQ reclaim actually fired: hoarders never preempt, so any
    # eviction of a hoarder victim was a reclaim by another CQ
    hoarder_evictions = sum(
        1
        for _, cq_name, _ in outcome.preempted
        if (int(cq_name.split("-")[1]) % cohort_size) < cohort_size // 2
    )
    assert hoarder_evictions > 0, "no cross-CQ reclaim in contended bench"
    _note_times("contended", [t / outcome.cycles for t in times])
    sig = (
        frozenset(
            (wl.name, cq, cyc) for wl, cq, _, cyc in outcome.admitted
        ),
        frozenset((wl.name, cq, cyc) for wl, cq, cyc in outcome.preempted),
        frozenset(wl.name for wl, _ in outcome.parked),
        outcome.cycles,
    )
    return (
        float(np.median(times)) * 1e3 / outcome.cycles,
        outcome.cycles,
        len(outcome.admitted),
        len(outcome.preempted),
        sig,
    )


def _build_drain_loop_rt(mode, seed, chunk=16, megaloop="off"):
    """The seeded 50k ClusterRuntime environment the pipeline and
    megaloop stages share (identical objects per seed, so admitted
    sets are comparable across modes by construction)."""
    from kueue_tpu.controllers import ClusterRuntime
    from kueue_tpu.core.scheduler import _LatencyEstimate
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.workload import PodSet

    class _OpenGate(_LatencyEstimate):
        # pin the latency gate open: these stages measure the drain
        # path itself, not the gate's host-vs-drain routing
        @property
        def value(self):
            return None

    rng2 = np.random.default_rng(seed)
    rt = ClusterRuntime(
        bulk_drain_threshold=256,
        drain_pipeline=mode,
        pipeline_chunk_cycles=chunk,
        drain_megaloop=megaloop,
        drain_gate=_OpenGate(),
    )
    # measured A/B: no sampled divergence re-solves in the window
    rt.guard.config.divergence_check_every = 0
    flavors = [f"fl-{i}" for i in range(N_FLAVORS)]
    for f in flavors:
        rt.add_flavor(ResourceFlavor(name=f))
    for i in range(N_CQ):
        quotas = tuple(
            FlavorQuotas.build(
                f,
                {
                    "cpu": (
                        str(int(rng2.integers(8, 64))),
                        str(int(rng2.integers(8, 32))),
                        None,
                    ),
                    "memory": (
                        f"{int(rng2.integers(16, 128))}Gi",
                        f"{int(rng2.integers(16, 64))}Gi",
                        None,
                    ),
                },
            )
            for f in flavors
        )
        rt.add_cluster_queue(
            ClusterQueue(
                name=f"pcq-{i}",
                cohort=f"pcohort-{i % N_COHORT}",
                namespace_selector={},
                resource_groups=(ResourceGroup(("cpu", "memory"), quotas),),
            )
        )
        rt.add_local_queue(
            LocalQueue(
                namespace="ns", name=f"plq-{i}", cluster_queue=f"pcq-{i}"
            )
        )
    n = N_CQ * WL_PER_CQ
    prios = rng2.integers(0, 4, size=n) * 50
    cpus = rng2.integers(1, 16, size=n)
    mems = rng2.integers(1, 32, size=n)
    counts = rng2.integers(1, 5, size=n)
    for j in range(n):
        rt.add_workload(
            Workload(
                namespace="ns",
                name=f"pw{j}",
                queue_name=f"plq-{j % N_CQ}",
                priority=int(prios[j]),
                creation_time=float(j),
                pod_sets=(
                    PodSet.build(
                        "main",
                        int(counts[j]),
                        {"cpu": str(cpus[j]), "memory": f"{mems[j]}Gi"},
                    ),
                ),
            )
        )
    rt.reconcile_once()
    return rt


def _drain_once(rt):
    import time

    t0 = time.perf_counter()
    res = rt.bulk_drain()
    dt = time.perf_counter() - t0
    assert res is not None, "bulk drain did not run"
    return dt


def _admitted_of(rt):
    return frozenset(
        k for k, wl in rt.workloads.items() if wl.has_quota_reservation
    )


def pipelined_drain_bench(rng):
    """Pipelined vs serial drain LOOP at the 50k north-star scale,
    through the PRODUCTION path (ClusterRuntime.bulk_drain): chunked
    rounds of 16 kernel cycles each, where the pipelined mode launches
    round t+1's encode+solve against a speculative snapshot (the
    kernel-reported final usage) while the host applies round t —
    journal-less apply, audit + events + runtime mutation included —
    and commits the prefetch only after the conflict check proves the
    speculation exact (core/pipeline.py). The serial mode runs the
    IDENTICAL rounds without prefetch, so the delta is pure overlap.
    Admitted sets are asserted identical. Returns
    (serial_s, pipelined_s, PipelineStats, n_admitted)."""
    build = _build_drain_loop_rt
    drain = _drain_once
    admitted_of = _admitted_of

    seed = int(rng.integers(1 << 30))
    _stage("pipeline: warmup (compile every chunk shape)")
    drain(build("serial", seed))
    _stage("pipeline: serial loop measured")
    rt_s = build("serial", seed)
    serial_s = drain(rt_s)
    _stage("pipeline: double-buffered loop measured")
    rt_p = build("on", seed)
    pipe_s = drain(rt_p)
    assert admitted_of(rt_s) == admitted_of(rt_p), (
        "pipelined drain changed decisions"
    )
    stats = rt_p.pipeline
    assert stats.rounds >= 2 and stats.prefetches >= 1, stats.to_dict()
    _note_times(
        "pipeline",
        [
            t.total_s
            for t in rt_p.scheduler.last_traces
            if t.resolution == "drain"
        ],
    )
    return serial_s, pipe_s, stats, len(admitted_of(rt_p))


def megaloop_drain_bench(rng):
    """Serial vs pipelined vs MEGALOOP drain loop on the seeded 50k
    backlog, through the production path (ClusterRuntime.bulk_drain).
    Chunk 4 — finer-grained rounds are exactly where the per-round
    dispatch floor dominates and where the fusion pays: the serial
    loop dispatches once per round, the pipelined loop still
    dispatches once per round (overlapped), the megaloop fuses up to
    K rounds per dispatch (ops/megaloop_kernel) with the host
    journal-less-applying the batched round-stamped log behind it.
    Admitted sets asserted identical across ALL THREE modes. Returns
    (serial_s, pipelined_s, megaloop_s, serial_dispatches,
    megaloop_dispatches, MegaloopStats, n_admitted)."""
    CHUNK = 4
    build = _build_drain_loop_rt
    drain = _drain_once
    admitted_of = _admitted_of

    seed = int(rng.integers(1 << 30))
    _stage("megaloop: warmup (compile chunk + fused shapes)")
    drain(build("serial", seed, chunk=CHUNK))
    drain(build("on", seed, chunk=CHUNK, megaloop="16"))
    _stage("megaloop: serial loop measured")
    rt_s = build("serial", seed, chunk=CHUNK)
    serial_s = drain(rt_s)
    _stage("megaloop: pipelined loop measured")
    rt_p = build("on", seed, chunk=CHUNK)
    pipe_s = drain(rt_p)
    _stage("megaloop: fused loop measured")
    rt_m = build("on", seed, chunk=CHUNK, megaloop="16")
    mega_s = drain(rt_m)
    assert admitted_of(rt_s) == admitted_of(rt_p), (
        "pipelined drain changed decisions"
    )
    assert admitted_of(rt_s) == admitted_of(rt_m), (
        "megaloop drain changed decisions"
    )
    stats = rt_m.megaloop
    # one dispatch per serial round vs one per fused launch
    serial_dispatches = rt_s.pipeline.rounds
    mega_dispatches = stats.launches
    assert stats.rounds == rt_s.pipeline.rounds, (
        stats.to_dict(), rt_s.pipeline.to_dict(),
    )
    assert mega_dispatches >= 1
    _note_times(
        "megaloop",
        [
            t.total_s
            for t in rt_m.scheduler.last_traces
            if t.resolution == "drain"
        ],
    )
    return (
        serial_s, pipe_s, mega_s, serial_dispatches, mega_dispatches,
        stats, len(admitted_of(rt_m)),
    )


def fair_victim_search_bench(rng):
    """Fair-sharing victim search, batched: N preempt-mode heads across
    borrowing cohorts resolved in ONE device dispatch
    (ops/fair_preempt_kernel), vs the host tournament running the same
    searches sequentially (preemption.go:372-463). Returns
    (device_ms, host_ms, n_heads)."""
    import time

    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        Preemption,
        ResourceFlavor,
        Workload,
        WorkloadConditionType,
    )
    from kueue_tpu.models.cluster_queue import FairSharing, ResourceGroup
    from kueue_tpu.models.constants import (
        PreemptionPolicy,
        ReclaimWithinCohortPolicy,
    )
    from kueue_tpu.models.workload import PodSet
    from kueue_tpu.core.cache import Cache
    from kueue_tpu.core.flavor_assigner import FlavorAssigner, Mode
    from kueue_tpu.core.preempt_batch import batched_fair_get_targets
    from kueue_tpu.core.preemption import Preemptor
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.core.workload_info import make_admission
    from kueue_tpu.utils.clock import FakeClock

    n_cohorts, cqs_per_cohort, victims_per_cq = 64, 4, 6
    cache = Cache()
    cache.add_or_update_flavor(ResourceFlavor(name="default"))
    prem = Preemption(
        within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
        reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
    )
    cq_names = []
    for ci in range(n_cohorts):
        for qi in range(cqs_per_cohort):
            name = f"fcq-{ci}-{qi}"
            cq_names.append(name)
            cache.add_or_update_cluster_queue(
                ClusterQueue(
                    name=name,
                    cohort=f"fco-{ci}",
                    namespace_selector={},
                    resource_groups=(
                        ResourceGroup(
                            ("cpu",),
                            (FlavorQuotas.build("default", {"cpu": "8"}),),
                        ),
                    ),
                    preemption=prem,
                    fair_sharing=FairSharing(
                        weight_milli=int(rng.choice([500, 1000, 2000]))
                    ),
                )
            )
            # over-admit so CQs borrow from the cohort
            for v in range(victims_per_cq):
                wl = Workload(
                    namespace="ns", name=f"fv-{ci}-{qi}-{v}",
                    queue_name=f"lq-{name}",
                    priority=int(rng.integers(0, 30)),
                    creation_time=float(v),
                    pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
                )
                wl.admission = make_admission(
                    name, {"main": {"cpu": "default"}}, wl
                )
                wl.set_condition(
                    WorkloadConditionType.QUOTA_RESERVED, True,
                    reason="QuotaReserved", now=float(v),
                )
                cache.add_or_update_workload(wl)
    snapshot = take_snapshot(cache)
    assigner = FlavorAssigner(snapshot, cache.flavors, enable_fair_sharing=True)
    items = []
    for i, name in enumerate(cq_names):
        wl = Workload(
            namespace="ns", name=f"fh-{i}", queue_name=f"lq-{name}",
            priority=100, creation_time=1000.0 + i,
            pod_sets=(
                PodSet.build("main", 1, {"cpu": str(int(rng.integers(4, 8)))}),
            ),
        )
        a = assigner.assign(wl, name)
        if a.representative_mode() == Mode.PREEMPT:
            items.append((wl, name, a))
    preemptor = Preemptor(FakeClock(0.0), enable_fair_sharing=True)
    batched_fair_get_targets(snapshot, items, preemptor)  # warm compile
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = batched_fair_get_targets(snapshot, items, preemptor)
        times.append(time.perf_counter() - t0)
    # strategy gates legitimately reject many heads; just require the
    # batch to be non-trivially productive
    assert sum(1 for t in out if t) >= len(items) // 8
    t0 = time.perf_counter()
    for wl, name, a in items:
        preemptor.get_targets(wl, name, a, snapshot)
    host_s = time.perf_counter() - t0
    _note_times("fair", times)
    return float(np.median(times)) * 1e3, host_s * 1e3, len(items)


def tas_placement_bench(rng):
    """50k-pod gang placement over a 3-level topology (block -> rack ->
    hostname): TASFlavorSnapshot's two-phase fit
    (tas_flavor_snapshot.go:394-690) — vectorized leaf CountIn + the
    greedy level search. Returns (ms per placement, leaves, pods)."""
    import time

    from kueue_tpu.models.resource_flavor import ResourceFlavor as RF
    from kueue_tpu.models.topology import Topology, TopologyLevel
    from kueue_tpu.models.workload import PodSetTopologyRequest
    from kueue_tpu.tas.cache import Node, TASFlavorCache
    from kueue_tpu.tas.snapshot import TASPodSetRequest
    from kueue_tpu.resources import requests_from_spec

    levels = ("block", "rack", "kubernetes.io/hostname")
    n_blocks, racks_per_block, hosts_per_rack = 8, 16, 8  # 1024 hosts
    flavor = RF(name="tas", topology_name="topo")
    topo = Topology(name="topo", levels=tuple(TopologyLevel(k) for k in levels))
    fc = TASFlavorCache(flavor, topo)
    for b in range(n_blocks):
        for r in range(racks_per_block):
            for h in range(hosts_per_rack):
                name = f"n{b}-{r}-{h}"
                fc.add_or_update_node(
                    Node(
                        name=name,
                        labels={
                            "block": f"b{b}",
                            "rack": f"r{b}-{r}",
                            "kubernetes.io/hostname": name,
                        },
                        allocatable=requests_from_spec(
                            {"cpu": "64", "pods": "64"}
                        ),
                    )
                )
    n_pods = 50_000  # 1024 hosts x 64 pods = 65,536 slots
    req = TASPodSetRequest(
        podset_name="main",
        count=n_pods,
        single_pod_requests=requests_from_spec({"cpu": "1"}),
        topology_request=PodSetTopologyRequest(
            mode="Preferred", level="block"
        ),
    )
    snap = fc.snapshot()
    out = snap.find_topology_assignments([req])  # warm (freeze etc.)
    assert not out.failure_reason
    assert sum(d.count for d in out.assignments["main"].domains) == n_pods
    times = []
    for _ in range(3):
        snap = fc.snapshot()
        t0 = time.perf_counter()
        snap.find_topology_assignments([req])
        times.append(time.perf_counter() - t0)
    n_leaves = n_blocks * racks_per_block * hosts_per_rack
    _note_times("tas", times)
    return float(np.median(times)) * 1e3, n_leaves, n_pods


def fair_drain_bench(rng):
    """Bulk FAIR-SHARING drain: the DRS cohort tournament ordering every
    admission, entirely on device (ops/drain_kernel.solve_drain_fair)
    vs the host fair iterator driving the same cycles (each pop
    recomputes every remaining head's path-DRS —
    fair_sharing_iterator.go:33-120). Decision parity asserted here and
    in tests/test_drain.py TestDrainFairSharing. Returns
    (device_s, host_s, n_pending, cycles)."""
    import time

    from kueue_tpu.core.cache import Cache
    from kueue_tpu.core.drain import run_drain
    from kueue_tpu.core.preemption import Preemptor
    from kueue_tpu.core.queue_manager import QueueManager, queue_order_timestamp
    from kueue_tpu.core.scheduler import Scheduler
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import FairSharing, ResourceGroup
    from kueue_tpu.models.workload import PodSet
    from kueue_tpu.utils.clock import FakeClock

    n_cq, cohort_size, wl_per_cq = 100, 10, 5
    weights = [500, 1000, 1000, 2000]

    def build():
        clock = FakeClock(0.0)
        cache = Cache()
        mgr = QueueManager(clock)
        cache.add_or_update_flavor(ResourceFlavor(name="default"))
        w_rng = np.random.default_rng(7)
        for i in range(n_cq):
            name = f"fcq-{i}"
            cq = ClusterQueue(
                name=name,
                cohort=f"fcohort-{i // cohort_size}",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (FlavorQuotas.build("default", {"cpu": "8"}),),
                    ),
                ),
                fair_sharing=FairSharing(
                    weight_milli=weights[int(w_rng.integers(0, len(weights)))]
                ),
            )
            cache.add_or_update_cluster_queue(cq)
            mgr.add_cluster_queue(cq)
            mgr.add_local_queue(
                LocalQueue(namespace="ns", name=f"lq-{name}", cluster_queue=name)
            )
            for w in range(wl_per_cq):
                mgr.add_or_update_workload(
                    Workload(
                        namespace="ns", name=f"fwl-{i}-{w}",
                        queue_name=f"lq-{name}",
                        priority=int(w_rng.integers(0, 3)) * 10,
                        creation_time=float(i * wl_per_cq + w),
                        pod_sets=(
                            PodSet.build(
                                "main", 1,
                                {"cpu": str(int(w_rng.integers(2, 7)))},
                            ),
                        ),
                    )
                )
        return clock, cache, mgr

    # device
    clock, cache, mgr = build()
    pending = []
    for cq_name, pq in mgr.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            pending.append((wl, cq_name))
    ts_fn = lambda wl: queue_order_timestamp(wl, mgr._ts_policy)  # noqa: E731
    snapshot = take_snapshot(cache)
    run_drain(
        snapshot, pending, cache.flavors, timestamp_fn=ts_fn,
        fair_sharing=True,
    )  # warmup (compile)
    times = []
    for _ in range(3):
        snapshot = take_snapshot(cache)
        t0 = time.perf_counter()
        outcome = run_drain(
            snapshot, pending, cache.flavors, timestamp_fn=ts_fn,
            fair_sharing=True,
        )
        times.append(time.perf_counter() - t0)
    assert not outcome.fallback and not outcome.truncated
    dev_admitted = {wl.name for wl, _, _, _ in outcome.admitted}

    # host fair iterator driving the same drain
    clock, cache, mgr = build()
    sched = Scheduler(
        queues=mgr, cache=cache, clock=clock, preemptor=Preemptor(clock),
        use_solver=False, fair_sharing=True,
    )
    host_admitted = set()
    t0 = time.perf_counter()
    for _ in range(400):
        if not any(
            pq.pending_active() > 0 for pq in mgr.cluster_queues.values()
        ):
            break
        res = sched.schedule()
        host_admitted.update(e.workload.name for e in res.admitted)
    host_s = time.perf_counter() - t0
    assert dev_admitted == host_admitted, "fair drain decision divergence"
    _note_times("fair_drain", times)
    return float(np.median(times)), host_s, len(pending), outcome.cycles


def fair_preempt_drain_bench(rng):
    """Bulk fair-sharing drain WITH fair preemption — the production
    fair-cohort config: borrowing victims saturate every cohort and the
    backlog can only start through the in-kernel fair victim tournament
    (ops/drain_kernel.solve_drain_fair_preempt; parity
    tests/test_drain.py TestFairPreemptDrain) vs the host fair
    scheduler with evictions applied between cycles. Returns
    (device_s, host_s, n_pending, cycles, evicted)."""
    import time

    from kueue_tpu.core.cache import Cache
    from kueue_tpu.core.drain import run_drain_fair_preempt
    from kueue_tpu.core.preemption import Preemptor
    from kueue_tpu.core.queue_manager import QueueManager, queue_order_timestamp
    from kueue_tpu.core.scheduler import Scheduler
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.core.workload_info import make_admission
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        Preemption,
        ResourceFlavor,
        Workload,
        WorkloadConditionType,
    )
    from kueue_tpu.models.cluster_queue import FairSharing, ResourceGroup
    from kueue_tpu.models.constants import (
        PreemptionPolicy,
        ReclaimWithinCohortPolicy,
    )
    from kueue_tpu.models.workload import PodSet
    from kueue_tpu.utils.clock import FakeClock

    n_cq, cohort_size, wl_per_cq, victims_per_cq = 60, 6, 4, 3
    weights = [500, 1000, 1000, 2000]
    prem = Preemption(
        within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
        reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
    )

    def build():
        clock = FakeClock(0.0)
        cache = Cache()
        mgr = QueueManager(clock)
        cache.add_or_update_flavor(ResourceFlavor(name="default"))
        w_rng = np.random.default_rng(11)
        t = 0.0
        for i in range(n_cq):
            name = f"fpcq-{i}"
            cq = ClusterQueue(
                name=name,
                cohort=f"fpcohort-{i // cohort_size}",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (FlavorQuotas.build("default", {"cpu": "8"}),),
                    ),
                ),
                fair_sharing=FairSharing(
                    weight_milli=weights[int(w_rng.integers(0, len(weights)))]
                ),
                preemption=prem,
            )
            cache.add_or_update_cluster_queue(cq)
            mgr.add_cluster_queue(cq)
            mgr.add_local_queue(
                LocalQueue(namespace="ns", name=f"lq-{name}", cluster_queue=name)
            )
            # every other CQ hoards: admitted victims borrowing above
            # nominal make the cohort DRS-positive (fair reclaim bait)
            if i % 2 == 0:
                for v in range(victims_per_cq):
                    t += 1.0
                    wl = Workload(
                        namespace="ns", name=f"fpv-{i}-{v}",
                        queue_name=f"lq-{name}",
                        priority=int(w_rng.integers(0, 2)) * 5,
                        creation_time=t,
                        pod_sets=(PodSet.build("main", 1, {"cpu": "5"}),),
                    )
                    wl.admission = make_admission(
                        name, {"main": {"cpu": "default"}}, wl
                    )
                    wl.set_condition(
                        WorkloadConditionType.QUOTA_RESERVED, True,
                        reason="QuotaReserved", now=t,
                    )
                    cache.add_or_update_workload(wl)
            for w in range(wl_per_cq):
                t += 1.0
                mgr.add_or_update_workload(
                    Workload(
                        namespace="ns", name=f"fpwl-{i}-{w}",
                        queue_name=f"lq-{name}",
                        priority=20 + int(w_rng.integers(0, 3)) * 10,
                        creation_time=t,
                        pod_sets=(
                            PodSet.build(
                                "main", 1,
                                {"cpu": str(int(w_rng.integers(2, 6)))},
                            ),
                        ),
                    )
                )
        return clock, cache, mgr

    # device: one dispatch decides admissions + fair evictions
    clock, cache, mgr = build()
    pending = []
    for cq_name, pq in mgr.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            pending.append((wl, cq_name))
    ts_fn = lambda wl: queue_order_timestamp(wl, mgr._ts_policy)  # noqa: E731
    snapshot = take_snapshot(cache)
    run_drain_fair_preempt(
        snapshot, pending, cache.flavors, timestamp_fn=ts_fn
    )  # warmup (compile)
    times = []
    for _ in range(3):
        snapshot = take_snapshot(cache)
        t0 = time.perf_counter()
        outcome = run_drain_fair_preempt(
            snapshot, pending, cache.flavors, timestamp_fn=ts_fn
        )
        times.append(time.perf_counter() - t0)
    assert not outcome.fallback and not outcome.truncated
    dev_admitted = {wl.name for wl, _, _, _ in outcome.admitted}
    dev_evicted = {wl.name for wl, _, _ in outcome.preempted}
    assert dev_evicted, "fair-preempt bench evicted nothing"

    # host: fair scheduler + fair preemptor, evictions applied between
    # cycles (the reconciler round trip compressed to cycle boundaries)
    clock, cache, mgr = build()
    sched = Scheduler(
        queues=mgr, cache=cache, clock=clock,
        preemptor=Preemptor(clock, enable_fair_sharing=True),
        use_solver=False, fair_sharing=True,
    )
    host_admitted, host_evicted = set(), set()
    t0 = time.perf_counter()
    for _ in range(600):
        progressed = False
        if any(
            pq.pending_active() > 0 for pq in mgr.cluster_queues.values()
        ):
            progressed = True
        res = sched.schedule()
        host_admitted.update(e.workload.name for e in res.admitted)
        victims = [
            t_.workload.workload
            for e in res.preempting
            for t_ in e.preemption_targets
        ]
        for wl in victims:
            if wl.name in host_evicted:
                continue
            host_evicted.add(wl.name)
            cq_name = wl.admission.cluster_queue
            cache.delete_workload(wl)
            mgr.queue_associated_inadmissible_workloads_after(cq_name)
            progressed = True
        if not progressed:
            break
    host_s = time.perf_counter() - t0
    assert dev_admitted == host_admitted, "fair-preempt decision divergence"
    assert dev_evicted == host_evicted, "fair-preempt eviction divergence"
    _note_times("fair_preempt_drain", times)
    return (
        float(np.median(times)), host_s, len(pending), outcome.cycles,
        len(dev_evicted),
    )


def interactive_cycle_bench(rng, n_heads=512):
    """The INTERACTIVE dispatch path (one scheduler cycle's nomination
    batch) with device-resident quota tensors vs the old ship-everything
    dispatch (core/solver.ResidentCycleState): between cycles only
    changed usage rows + the heads batch transfer. Reports the measured
    per-dispatch latency of both and the auto-gate crossover head count
    (the head count where the device dispatch beats the measured host
    flavor-walk, scheduler._solver_enabled). Returns
    (resident_ms, fresh_ms, host_per_head_ms, crossover_heads)."""
    import time

    from kueue_tpu.core.flavor_assigner import FlavorAssigner
    from kueue_tpu.core.queue_manager import queue_order_timestamp
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.core.solver import (
        ResidentCycleState,
        dispatch_lowered,
        lower_heads,
    )

    cache, mgr = build_cluster(rng)
    pending = build_backlog(rng)[: n_heads]
    ts_fn = lambda wl: queue_order_timestamp(wl, mgr._ts_policy)  # noqa: E731

    snapshot = take_snapshot(cache)
    lowered = lower_heads(snapshot, pending, cache.flavors, timestamp_fn=ts_fn)

    # host flavor walk, per head (the auto-gate's other arm)
    assigner = FlavorAssigner(snapshot, cache.flavors)
    t0 = time.perf_counter()
    for wl, cq_name in pending:
        assigner.assign(wl, cq_name)
    host_per_head_ms = (time.perf_counter() - t0) * 1e3 / len(pending)

    # fresh-ship dispatch (tree + usage + heads every cycle)
    dispatch_lowered(snapshot, lowered)  # warmup/compile
    fresh = []
    for _ in range(5):
        t0 = time.perf_counter()
        dispatch_lowered(snapshot, lowered)
        fresh.append(time.perf_counter() - t0)
    fresh_ms = float(np.median(fresh)) * 1e3

    # resident dispatch: usage mutates a few rows between cycles (an
    # admission's worth), as production cycles do
    resident = ResidentCycleState()
    dispatch_lowered(snapshot, lowered, resident=resident)  # full upload
    res = []
    for i in range(5):
        snapshot.local_usage[i % 7, 0] += 1  # delta: one changed row
        t0 = time.perf_counter()
        dispatch_lowered(snapshot, lowered, resident=resident)
        res.append(time.perf_counter() - t0)
    resident_ms = float(np.median(res)) * 1e3
    crossover = resident_ms / max(host_per_head_ms, 1e-9)
    _note_times("interactive", res)
    return resident_ms, fresh_ms, host_per_head_ms, crossover


def tas_drain_bench(rng):
    """TAS-heavy drain: 10k gang workloads with MIXED-MODE topology
    requests (Required / Preferred with level relaxation /
    Unconstrained) over a 1024-host topology (16 blocks x 8 racks x 8
    hosts), the WHOLE backlog decided in ONE device dispatch —
    nomination placement, in-cycle re-validation and leaf charging all
    in kernel (ops/drain_kernel.solve_drain_tas; parity
    tests/test_tas_drain.py incl. TestTASDrainWidenedScope).
    Returns (ms/cycle, cycles, admitted, n_pending)."""
    import time

    from kueue_tpu.core.cache import Cache
    from kueue_tpu.core.drain import run_drain_tas
    from kueue_tpu.core.queue_manager import QueueManager, queue_order_timestamp
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.topology import Topology, TopologyLevel
    from kueue_tpu.models.workload import PodSet, PodSetTopologyRequest
    from kueue_tpu.tas import Node, TASCache
    from kueue_tpu.utils.clock import FakeClock

    BLOCK = "cloud.google.com/topology-block"
    RACK = "cloud.google.com/topology-rack"
    HOST = "kubernetes.io/hostname"
    n_blocks, racks_per_block, hosts_per_rack = 16, 8, 8
    n_cq, wl_per_cq = 100, 100

    cache = Cache()
    mgr = QueueManager(FakeClock(0.0))
    topo = Topology(
        name="default",
        levels=(TopologyLevel(BLOCK), TopologyLevel(RACK), TopologyLevel(HOST)),
    )
    flavor = ResourceFlavor(name="tas-flavor", topology_name="default")
    tas = TASCache()
    tas.add_or_update_topology(topo)
    cache.add_or_update_topology(topo)
    cache.add_or_update_flavor(flavor)
    tas.add_or_update_flavor(flavor)
    for b in range(n_blocks):
        for r in range(racks_per_block):
            for h in range(hosts_per_rack):
                tas.add_or_update_node(
                    Node(
                        name=f"n-{b}-{r}-{h}",
                        labels={
                            BLOCK: f"b{b}",
                            RACK: f"b{b}-r{r}",
                            HOST: f"h-{b}-{r}-{h}",
                        },
                        allocatable={"cpu": 8000, "pods": 32},
                    )
                )
    cache.tas_cache = tas
    levels = [RACK, RACK, BLOCK, HOST]
    for i in range(n_cq):
        name = f"tcq-{i}"
        cq = ClusterQueue(
            name=name,
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",),
                    (FlavorQuotas.build("tas-flavor", {"cpu": "9999"}),),
                ),
            ),
        )
        cache.add_or_update_cluster_queue(cq)
        mgr.add_cluster_queue(cq)
        mgr.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{name}", cluster_queue=name)
        )
        for w in range(wl_per_cq):
            mode = ("Required", "Preferred", "Unconstrained")[
                int(rng.integers(0, 3))
            ]
            tr = PodSetTopologyRequest(
                mode=mode,
                level=(
                    None
                    if mode == "Unconstrained"
                    else levels[int(rng.integers(0, len(levels)))]
                ),
            )
            mgr.add_or_update_workload(
                Workload(
                    namespace="ns", name=f"twl-{i}-{w}",
                    queue_name=f"lq-{name}",
                    priority=int(rng.integers(0, 3)) * 10,
                    creation_time=float(i * wl_per_cq + w),
                    pod_sets=(
                        PodSet.build(
                            "main", int(rng.integers(2, 17)),
                            {"cpu": str(int(rng.integers(1, 3)))},
                            topology_request=tr,
                        ),
                    ),
                )
            )
    pending = []
    for cq_name, pq in mgr.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            pending.append((wl, cq_name))
    ts_fn = lambda wl: queue_order_timestamp(wl, mgr._ts_policy)  # noqa: E731
    snapshot = take_snapshot(cache)
    run_drain_tas(snapshot, pending, cache.flavors, tas, timestamp_fn=ts_fn)
    times = []
    for _ in range(3):
        snapshot = take_snapshot(cache)
        t0 = time.perf_counter()
        outcome = run_drain_tas(
            snapshot, pending, cache.flavors, tas, timestamp_fn=ts_fn
        )
        times.append(time.perf_counter() - t0)
    assert not outcome.fallback, "TAS drain bench must have zero fallback"
    assert not outcome.truncated and outcome.admitted
    _note_times("tas_drain", [t / outcome.cycles for t in times])
    return (
        float(np.median(times)) * 1e3 / outcome.cycles,
        outcome.cycles,
        len(outcome.admitted),
        len(pending),
    )


def planner_bench(rng, n_cq=50, wl_per_cq=10, n_scenarios=128, reps=5):
    """What-if capacity planner: an n_scenarios quota-sweep over an
    (n_cq x wl_per_cq)-pending snapshot, the whole sweep solved in ONE
    vmapped device launch (ops/plan_kernel.solve_scenarios) vs the same
    scenarios as sequential cycle-solver dispatches. Each CQ is its own
    cohort root, so the vmapped phase-2 scan stays shallow; one
    workload per CQ is quota-rejected at baseline so the sweep has
    something to fix. Returns (batched_ms_per_scenario,
    sequential_ms_per_scenario, n_admitting_scenarios, n_pending)."""
    import time

    from kueue_tpu._jax import jnp
    from kueue_tpu.core.cache import Cache
    from kueue_tpu.core.queue_manager import QueueManager
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.workload import PodSet
    from kueue_tpu.ops.assign_kernel import solve_cycle_segmented_packed_jit
    from kueue_tpu.ops.quota import QuotaTree
    from kueue_tpu.planner import Planner
    from kueue_tpu.utils.clock import FakeClock

    cache = Cache()
    mgr = QueueManager(FakeClock(0.0))
    cache.add_or_update_flavor(ResourceFlavor(name="default"))
    for i in range(n_cq):
        name = f"pcq-{i}"
        cq = ClusterQueue(
            name=name,
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",), (FlavorQuotas.build("default", {"cpu": "8"}),)
                ),
            ),
        )
        cache.add_or_update_cluster_queue(cq)
        mgr.add_cluster_queue(cq)
        mgr.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{name}", cluster_queue=name)
        )
        for w in range(wl_per_cq):
            # last head per CQ is oversized: quota-rejected at baseline
            cpu = "16" if w == wl_per_cq - 1 else "1"
            mgr.add_or_update_workload(
                Workload(
                    namespace="ns", name=f"pwl-{i}-{w}",
                    queue_name=f"lq-{name}",
                    priority=0,
                    creation_time=float(i * wl_per_cq + w),
                    pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
                )
            )
    # K/C sized to the backlog (1 flavor x 1 resource + pods): the
    # phase-1 gathers scale with S*W*K*C, so padded candidate slots are
    # pure memory traffic; both the batched and the sequential
    # reference below lower with the same shapes
    planner = Planner(cache=cache, queues=mgr, max_candidates=2, max_cells=3)
    sweep = []
    si = 0
    while len(sweep) < n_scenarios:
        cq_name = f"pcq-{si % n_cq}"
        delta = (4000, 8000, 16000)[si % 3]  # +4 never admits the 16-cpu head
        sweep.extend(
            Planner.quota_sweep(cq_name, "default", "cpu", [delta])
        )
        sweep[-1] = type(sweep[-1])(
            name=f"{sweep[-1].name}#{si}", deltas=sweep[-1].deltas
        )
        si += 1

    planner.plan(scenarios=sweep, include_reasons="none")  # warmup/compile
    times, sweep_times = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        report = planner.plan(scenarios=sweep, include_reasons="none")
        times.append(time.perf_counter() - t0)
        sweep_times.append(report.sweep_s)
    assert report.launches == 1, "sweep must be one batched launch"
    n_admitting = sum(1 for s in report.scenarios if s.newly_admitted)
    assert n_admitting > 0, "sweep must contain admitting scenarios"
    # batched cost per scenario = the sweep window (quota-array stack,
    # ONE vmapped launch, host decode); the shared setup (snapshot,
    # backlog, lowering) is excluded from BOTH sides of the comparison —
    # the sequential loop below gets the same prebuilt batch for free
    batched_ms = float(np.median(sweep_times)) * 1e3 / (n_scenarios + 1)
    plan_total_ms = float(np.median(times)) * 1e3 / (n_scenarios + 1)

    # sequential reference: the SAME scenarios as one cycle-solver
    # dispatch each (jit-cached after the first), on the same backend
    from kueue_tpu.core.encode import encode_snapshot
    from kueue_tpu.core.solver import _bucket, lower_heads, pack_heads
    from kueue_tpu.ops.assign_kernel import build_paths, build_roots

    snapshot = take_snapshot(cache)
    heads = planner.backlog(snapshot)
    lowered = lower_heads(
        snapshot, heads, cache.flavors, max_candidates=2, max_cells=3
    )
    enc = encode_snapshot(snapshot)
    roots = build_roots(enc.parent)
    paths = jnp.asarray(build_paths(enc.parent, enc.max_depth))
    batch_np, seg_id, n_segments, n_steps = pack_heads(
        lowered, roots, _bucket(len(lowered.heads))
    )
    batch = type(batch_np)(*(jnp.asarray(x) for x in batch_np))
    seg = jnp.asarray(seg_id)
    level_mask = jnp.asarray(enc.level_mask)
    parent = jnp.asarray(enc.parent)
    usage = jnp.asarray(enc.local_usage)
    lend = jnp.asarray(enc.lending_limit)
    bor = jnp.asarray(enc.borrowing_limit)

    def one_scenario(nominal_np):
        tree = QuotaTree(
            parent=parent, level_mask=level_mask,
            nominal=jnp.asarray(nominal_np),
            lending_limit=lend, borrowing_limit=bor,
        )
        return np.asarray(
            solve_cycle_segmented_packed_jit(
                tree, usage, batch, paths, seg,
                n_segments=n_segments, n_steps=n_steps,
            )
        )

    from kueue_tpu.resources import FlavorResource

    nominals = []
    for scen in sweep:
        nom = enc.nominal.copy()
        d = scen.deltas[0]
        r = snapshot.row(d.node)
        j = snapshot.fr_index[FlavorResource(d.flavor, d.resource)]
        nom[r, j] += d.delta
        nominals.append(nom)
    one_scenario(nominals[0])  # warmup/compile
    t0 = time.perf_counter()
    for nom in nominals:
        one_scenario(nom)
    sequential_ms = (time.perf_counter() - t0) * 1e3 / n_scenarios
    return batched_ms, plan_total_ms, sequential_ms, n_admitting, len(heads)


def journal_bench(rng, n_cq=40, wl_per_cq=40, fsync_policy="interval"):
    """Write-ahead-journal overhead on the ClusterRuntime admission
    path: the SAME seeded backlog drained to quiescence with the
    journal off (baseline) and on (the given fsync policy, a tmpdir
    journal), full production hooks — workload-add WAL records plus
    per-admission event records. Returns (baseline_ms_per_cycle,
    journal_ms_per_cycle, appends, journal_wall_s, admitted) with an
    identical-admitted-set assertion, so the hot-path cost of
    durability is tracked release over release."""
    import shutil
    import tempfile
    import time

    from kueue_tpu.controllers import ClusterRuntime
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.workload import PodSet
    from kueue_tpu.storage import Journal
    from kueue_tpu.utils.clock import FakeClock

    prios = rng.integers(0, 4, size=n_cq * wl_per_cq) * 10
    cpus = rng.integers(1, 4, size=n_cq * wl_per_cq)

    def run(journal_dir):
        rt = ClusterRuntime(
            clock=FakeClock(0.0), use_solver=False,
            bulk_drain_threshold=None,
        )
        journal = None
        if journal_dir is not None:
            journal = Journal(journal_dir, fsync_policy=fsync_policy).open()
            rt.attach_journal(journal)
        rt.add_flavor(ResourceFlavor(name="default"))
        for i in range(n_cq):
            name = f"jcq-{i}"
            rt.add_cluster_queue(
                ClusterQueue(
                    name=name,
                    namespace_selector={},
                    resource_groups=(
                        ResourceGroup(
                            ("cpu",),
                            (FlavorQuotas.build("default", {"cpu": "24"}),),
                        ),
                    ),
                )
            )
            rt.add_local_queue(
                LocalQueue(namespace="ns", name=f"lq-{name}", cluster_queue=name)
            )
        for k in range(n_cq * wl_per_cq):
            rt.add_workload(
                Workload(
                    namespace="ns", name=f"jwl-{k}",
                    queue_name=f"lq-jcq-{k % n_cq}",
                    priority=int(prios[k]),
                    creation_time=float(k),
                    pod_sets=(PodSet.build("main", 1, {"cpu": str(cpus[k])}),),
                )
            )
        t0 = time.perf_counter()
        while True:
            # drain in bounded chunks so a deep backlog fully admits
            if rt.run_until_idle(max_iterations=50) < 50:
                break
        wall = time.perf_counter() - t0
        cycles = rt.scheduler.scheduling_cycle
        admitted = frozenset(
            k for k, wl in rt.workloads.items() if wl.is_admitted
        )
        appends = journal.stats().appends if journal is not None else 0
        if journal is not None:
            journal.close()
        return wall, cycles, admitted, appends

    base_wall, base_cycles, base_admitted, _ = run(None)
    jdir = tempfile.mkdtemp(prefix="kueue-journal-bench-")
    try:
        j_wall, j_cycles, j_admitted, appends = run(jdir)
    finally:
        shutil.rmtree(jdir, ignore_errors=True)
    assert base_admitted == j_admitted, "journaling changed decisions"
    baseline_ms = base_wall * 1e3 / max(base_cycles, 1)
    journal_ms = j_wall * 1e3 / max(j_cycles, 1)
    return baseline_ms, journal_ms, appends, j_wall, len(j_admitted)


def soak_bench(
    rng,
    wall_budget_s=20.0,
    windows=4,
    rate_per_s=300.0,
    n_cq=8,
    quota_cpu=128,
    dt_s=0.1,
    checkpoint_every_s=2.0,
    anchor_every=8,
    segment_max_bytes=256 * 1024,
    scale_live=(10_000, 100_000),
    scale_touch=64,
):
    """Sustained-operation soak (the million-workload state plane's
    acceptance harness): Poisson arrival + completion churn through the
    full durable stack — WriteGateway ingest, WAL journal, periodic
    DELTA checkpoints (storage/checkpoint.DeltaCheckpointer) whose
    commits compact the journal, and a journal-tailing replica runtime
    (JournalTailer over LocalTailSource) — under a FakeClock so the
    simulated timeline is deterministic while wall time bounds the run.

    The run is sliced into ``windows`` equal wall-time windows and each
    window captures the signals that must stay FLAT for indefinite
    operation: process RSS, journal bytes/segments (checkpoint-driven
    compaction must reclaim), delta-checkpoint duration (O(changed),
    not O(live)), live object count, replica cursor lag, and the PR-13
    SLOTracker's admission-attainment verdict.

    A separate scale proof pins the delta-checkpoint complexity claim:
    the SAME ``scale_touch``-object churn is delta-checkpointed against
    ``scale_live[0]`` and ``scale_live[1]`` live workloads; the
    duration ratio must track the churn (≈1x), not the 10x live ratio.

    Returns a dict of soak + scale results; the leader and replica
    workload keysets are asserted convergent at the end.
    """
    import shutil
    import tempfile
    import time

    from kueue_tpu import serialization as ser
    from kueue_tpu.controllers import ClusterRuntime
    from kueue_tpu.gateway import WriteGateway
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.workload import PodSet
    from kueue_tpu.server import KueueServer
    from kueue_tpu.storage import DeltaCheckpointer, Journal
    from kueue_tpu.storage import JournalTailer, LocalTailSource
    from kueue_tpu.utils.clock import FakeClock

    def rss_mb():
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1]) / 1024.0
        except OSError:
            pass
        return 0.0

    def build_rt(tmp, clock):
        rt = ClusterRuntime(
            clock=clock, use_solver=False, bulk_drain_threshold=None
        )
        journal = Journal(
            os.path.join(tmp, "journal"),
            fsync_policy="interval",
            segment_max_bytes=segment_max_bytes,
        ).open()
        rt.attach_journal(journal)
        rt.add_flavor(ResourceFlavor(name="default"))
        for i in range(n_cq):
            name = f"scq-{i}"
            rt.add_cluster_queue(
                ClusterQueue(
                    name=name,
                    namespace_selector={},
                    resource_groups=(
                        ResourceGroup(
                            ("cpu",),
                            (FlavorQuotas.build(
                                "default", {"cpu": str(quota_cpu)}),),
                        ),
                    ),
                )
            )
            rt.add_local_queue(
                LocalQueue(
                    namespace="soak", name=f"lq-{name}", cluster_queue=name
                )
            )
        return rt, journal

    def wl_dict(k, now):
        return ser.workload_to_dict(
            Workload(
                namespace="soak", name=f"swl-{k}",
                queue_name=f"lq-scq-{k % n_cq}",
                priority=int(rng.integers(0, 4)) * 10,
                creation_time=float(now),
                pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
            )
        )

    # ---- churn phase ----
    tmp = tempfile.mkdtemp(prefix="kueue-soak-")
    results: dict = {}
    try:
        clock = FakeClock(0.0)
        rt, journal = build_rt(tmp, clock)
        state_dir = os.path.join(tmp, "state")
        os.makedirs(state_dir, exist_ok=True)
        ckpt = DeltaCheckpointer(
            state_dir, anchor_every=anchor_every
        ).open()
        rt.checkpointer = ckpt
        rt.slo.configure(default_target_s=30.0)
        gateway = WriteGateway(
            max_batch=4096, max_queue=65536, clock=clock
        )
        srv = KueueServer(
            runtime=rt, auto_reconcile=True, gateway=gateway
        )
        # shared-volume replica: tails the journal incrementally and —
        # when a checkpoint's compaction trims past its cursor — re-
        # anchors from the DELTA CHAIN directory (the production
        # design: "leader compaction forces a checkpoint re-anchor")
        tailer = JournalTailer(
            LocalTailSource(
                os.path.join(tmp, "journal"),
                state_path=state_dir,
                now_fn=clock.now,
            ),
            now_fn=clock.now,
        )
        tailer.ensure_runtime()

        lam = rate_per_s * dt_s
        window_wall = wall_budget_s / max(1, windows)
        window_stats = []
        delta_ms_all = []
        arrived = completed = 0
        seq = 0
        last_ckpt_sim = 0.0
        journal_mb_peak = 0.0
        segments_peak = 0
        t_start = time.perf_counter()
        for w in range(windows):
            w_deadline = t_start + (w + 1) * window_wall
            delta_ms_win = []
            while time.perf_counter() < w_deadline:
                now = clock.now()
                for _ in range(int(rng.poisson(lam))):
                    try:
                        gateway._enqueue("workloads", wl_dict(seq, now))
                        seq += 1
                        arrived += 1
                    except Exception:  # noqa: BLE001 — shed under burst
                        pass
                # completion churn: finished workloads leave the system
                # entirely (quota release + object delete, both WAL'd)
                with srv.lock:
                    admitted = [
                        wl for wl in rt.workloads.values() if wl.is_admitted
                    ]
                    n_done = min(len(admitted), int(rng.poisson(lam)))
                    for i in rng.permutation(len(admitted))[:n_done]:
                        # delete releases the quota reservation and
                        # WALs the tombstone — the full object
                        # lifecycle the retention bounds must survive
                        rt.delete_workload(admitted[int(i)])
                        completed += 1
                clock.advance(dt_s)
                gateway.flush_once()
                if clock.now() - last_ckpt_sim >= checkpoint_every_s:
                    last_ckpt_sim = clock.now()
                    with srv.lock:
                        prep = ckpt.prepare(rt)
                    if ckpt.commit(prep) and ckpt.last_kind == "delta":
                        delta_ms_win.append(ckpt.last_duration_s * 1e3)
                # the leader's interval fsync would land within one
                # poll period of real time; the tick IS that period
                journal.sync()
                tailer.poll_once()
                st = journal.stats()
                journal_mb_peak = max(journal_mb_peak, st.bytes / 2**20)
                segments_peak = max(segments_peak, st.segments)
            st = journal.stats()
            rt.slo.refresh()
            slo_rep = rt.slo.report()
            delta_ms_all.extend(delta_ms_win)
            window_stats.append({
                "rss_mb": round(rss_mb(), 1),
                "journal_mb": round(st.bytes / 2**20, 3),
                "journal_segments": st.segments,
                "reclaimed_mb": round(st.reclaimed_bytes / 2**20, 3),
                "live": len(rt.workloads),
                "replica_lag_records": st.last_seq - tailer.applied_seq,
                "replica_resyncs": tailer.resyncs,
                "ckpt_delta_p95_ms": round(
                    _p(delta_ms_win, 95), 3) if delta_ms_win else None,
                "slo_attainment_min": min(
                    (e["attainment"] for e in slo_rep["clusterQueues"]),
                    default=1.0,
                ),
                "slo_degraded": slo_rep["degraded"],
            })
        # final convergence check: flush + checkpoint + catch the
        # replica up, then the two runtimes must hold the same objects
        gateway.flush_once()
        ckpt.checkpoint(rt)
        journal.sync()
        for _ in range(64):
            tailer.poll_once()
            if tailer.applied_seq >= journal.last_seq:
                break
        leader_keys = set(rt.workloads)
        with tailer.lock:
            replica_keys = set(tailer.runtime.workloads)
        assert leader_keys == replica_keys, (
            f"replica diverged: {len(leader_keys ^ replica_keys)} keys"
        )
        journal.close()
        results.update({
            "windows": window_stats,
            "arrived": arrived,
            "completed": completed,
            "rss_mb_first": window_stats[0]["rss_mb"],
            "rss_mb_last": window_stats[-1]["rss_mb"],
            "journal_mb_peak": round(journal_mb_peak, 3),
            "journal_segments_peak": segments_peak,
            "reclaimed_mb": window_stats[-1]["reclaimed_mb"],
            "ckpt_delta_p95_ms": round(
                _p(delta_ms_all, 95), 3) if delta_ms_all else None,
            "replica_converged": True,
            "chain_files": ckpt.status()["chainFiles"],
        })
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # ---- scale proof: delta cost tracks churn, not live count ----
    scale = []
    for n_live in scale_live:
        tmp = tempfile.mkdtemp(prefix="kueue-soak-scale-")
        try:
            clock = FakeClock(0.0)
            rt, journal = build_rt(tmp, clock)
            state_dir = os.path.join(tmp, "state")
            os.makedirs(state_dir, exist_ok=True)
            ckpt = DeltaCheckpointer(state_dir, anchor_every=1 << 30).open()
            for k in range(n_live):
                rt.add_workload(
                    Workload(
                        namespace="soak", name=f"lwl-{k}",
                        queue_name=f"lq-scq-{k % n_cq}",
                        priority=0, creation_time=float(k),
                        pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
                    )
                )
            ckpt.checkpoint(rt)  # the anchor: O(live), once
            anchor_s = ckpt.last_duration_s
            # the same small churn at every scale
            import dataclasses

            for k in range(scale_touch):
                wl = rt.workloads[f"soak/lwl-{k}"]
                rt.add_workload(dataclasses.replace(wl, priority=50))
            ckpt.checkpoint(rt)
            assert ckpt.last_kind == "delta", ckpt.status()
            scale.append({
                "live": n_live,
                "anchor_ms": round(anchor_s * 1e3, 3),
                "delta_ms": round(ckpt.last_duration_s * 1e3, 3),
                "delta_objects": ckpt.last_objects,
            })
            journal.close()
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    small, large = scale[0], scale[-1]
    results["scale"] = scale
    results["scale_ratio_delta"] = round(
        large["delta_ms"] / max(small["delta_ms"], 1e-6), 2
    )
    results["scale_ratio_anchor"] = round(
        large["anchor_ms"] / max(small["anchor_ms"], 1e-6), 2
    )
    results["scale_ratio_live"] = round(
        large["live"] / max(small["live"], 1), 2
    )
    return results


def _p(values, q):
    """Percentile without numpy dependence on call sites (values may
    be a plain list)."""
    if not values:
        return 0.0
    vs = sorted(values)
    idx = min(len(vs) - 1, int(round((q / 100.0) * (len(vs) - 1))))
    return float(vs[idx])


def failover_bench(rng, n_cq=16, wl_per_phase=256, k_div=16):
    """Self-healing hot path (core/guard.py): steady-state cycle
    latency vs. cycle latency during an injected device outage
    (solver.device_raise armed → circuit opens → host-mirror cycles)
    and after re-probe recovery, plus the sampled-divergence-check
    overhead at K=k_div vs K=0. Asserts the loop keeps admitting under
    the outage, nothing is contained/aborted, and the final admitted
    set equals a host-only (forced-mirror) run of the same backlog.

    Returns (steady_ms, outage_ms, recovered_ms, div_overhead_pct,
    admitted, failovers)."""
    import time

    from kueue_tpu.controllers import ClusterRuntime
    from kueue_tpu.core.guard import GuardConfig
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.workload import PodSet
    from kueue_tpu.testing import faults
    from kueue_tpu.utils.clock import FakeClock

    prios = rng.integers(0, 4, size=8 * wl_per_phase) * 10
    cpus = rng.integers(1, 4, size=8 * wl_per_phase)
    wl_seq = [0]

    def build(mode: str, k: int):
        rt = ClusterRuntime(
            clock=FakeClock(0.0),
            use_solver=True,
            bulk_drain_threshold=None,
            guard_config=GuardConfig(
                mode=mode, divergence_check_every=k, base_backoff_s=5.0
            ),
        )
        rt.add_flavor(ResourceFlavor(name="default"))
        for i in range(n_cq):
            name = f"fcq-{i}"
            rt.add_cluster_queue(
                ClusterQueue(
                    name=name,
                    namespace_selector={},
                    resource_groups=(
                        ResourceGroup(
                            ("cpu",),
                            (FlavorQuotas.build("default", {"cpu": "4096"}),),
                        ),
                    ),
                )
            )
            rt.add_local_queue(
                LocalQueue(namespace="ns", name=f"lq-{name}", cluster_queue=name)
            )
        return rt

    def feed(rt, n):
        for _ in range(n):
            j = wl_seq[0]
            wl_seq[0] += 1
            rt.add_workload(
                Workload(
                    namespace="ns", name=f"fwl-{j}",
                    queue_name=f"lq-fcq-{j % n_cq}",
                    priority=int(prios[j % len(prios)]),
                    creation_time=float(j),
                    pod_sets=(
                        PodSet.build(
                            "main", 1, {"cpu": str(cpus[j % len(cpus)])}
                        ),
                    ),
                )
            )

    def run_phase(rt, n_cycles, agg=np.median):
        times = []
        for _ in range(n_cycles):
            t0 = time.perf_counter()
            rt.schedule_once()
            times.append(time.perf_counter() - t0)
        return float(agg(times)) * 1e3

    cycles_per_phase = wl_per_phase // n_cq
    rt = build("auto", k_div)
    faults.reset()
    # warmup (jit compile) + steady state
    wl_seq[0] = 0
    feed(rt, wl_per_phase)
    run_phase(rt, 2)
    steady_ms = run_phase(rt, cycles_per_phase - 2)

    # injected device outage: every launch raises until disarmed; the
    # breaker opens after its threshold and cycles run on the mirror
    feed(rt, wl_per_phase)

    def _raise():
        raise RuntimeError("injected device fault (bench)")

    faults.arm("solver.device_raise", action=_raise)
    outage_ms = run_phase(rt, cycles_per_phase)
    assert rt.guard.breaker.state in ("open", "half_open"), (
        "outage did not open the circuit"
    )
    faults.disarm("solver.device_raise")

    # recovery: let the backoff lapse; the next cycle is the half-open
    # probe and the device path closes again
    rt.clock.advance(3600.0)
    feed(rt, wl_per_phase)
    recovered_ms = run_phase(rt, cycles_per_phase)
    assert rt.guard.breaker.state == "closed", "re-probe did not recover"
    assert rt.guard.contained_cycles == 0, "a cycle aborted"
    failovers = rt.guard.failovers
    admitted = frozenset(
        k for k, wl in rt.workloads.items() if wl.is_admitted
    )
    assert len(admitted) == 3 * wl_per_phase, "loop stopped admitting"

    # host-only authority run over the SAME workload sequence
    host_rt = build("host", 0)
    wl_seq[0] = 0
    feed(host_rt, 3 * wl_per_phase)
    while True:
        if host_rt.run_until_idle(max_iterations=50) < 50:
            break
    host_admitted = frozenset(
        k for k, wl in host_rt.workloads.items() if wl.is_admitted
    )
    assert admitted == host_admitted, "failover changed decisions"

    # divergence-check overhead at K=k_div, measured EXACTLY: the guard
    # accumulates the wall time of every sampled check (mirror re-solve
    # + compare); the ratio against total cycle wall time is the
    # overhead — an A/B sweep at these cycle times is dominated by
    # process-lifetime drift (turbo/GC), which dwarfs the real cost
    r = build("auto", k_div)
    wl_seq[0] = 0
    feed(r, 2 * wl_per_phase)
    run_phase(r, 2)  # warmup (compile)
    check_s0 = r.guard.divergence_check_s
    t0 = time.perf_counter()
    for _ in range(2 * cycles_per_phase - 2):
        r.schedule_once()
    total_s = time.perf_counter() - t0
    check_s = r.guard.divergence_check_s - check_s0
    assert r.guard.divergence_checks >= 1, "sweep never hit a check"
    div_overhead_pct = (
        check_s / (total_s - check_s) * 100 if total_s > check_s else 0.0
    )
    return (
        steady_ms, outage_ms, recovered_ms, div_overhead_pct,
        len(admitted), failovers,
    )


def federation_bench(rng, n_workers=3, n_wl=120, worker_cpu=200):
    """MultiKueue federation stage: 3 in-process worker control planes
    behind a FederationDispatcher, a seeded backlog submitted to the
    manager. Reports (a) dispatch fan-out latency — the first pass that
    mirrors the whole backlog to every ranked worker — and (b)
    federated admission throughput to convergence. Under no faults the
    federated admitted set must equal the best single-cluster run (here
    every worker is identical and the backlog fits one worker, so
    "best" is the reference worker admitting everything)."""
    from kueue_tpu.admissionchecks.multikueue import MultiKueueCluster
    from kueue_tpu.controllers import ClusterRuntime
    from kueue_tpu.federation import FederationDispatcher
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.workload import PodSet
    from kueue_tpu.utils.clock import FakeClock

    clock = FakeClock(0.0)

    def build_worker():
        rt = ClusterRuntime(clock=clock, use_solver=False)
        rt.add_flavor(ResourceFlavor(name="default"))
        rt.add_cluster_queue(
            ClusterQueue(
                name="cq",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (
                            FlavorQuotas.build(
                                "default", {"cpu": str(worker_cpu)}
                            ),
                        ),
                    ),
                ),
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
        )
        return rt

    def backlog():
        return [
            Workload(
                namespace="ns",
                name=f"fed-{i:04d}",
                queue_name="lq",
                priority=int(rng.integers(0, 5)),
                pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
            )
            for i in range(n_wl)
        ]

    assert n_wl <= worker_cpu, "backlog must fit one worker (reference run)"
    workers = {f"w{i}": build_worker() for i in range(n_workers)}
    manager = ClusterRuntime(clock=clock)
    dispatcher = FederationDispatcher(
        manager,
        clusters={
            name: MultiKueueCluster(name=name, runtime=rt)
            for name, rt in workers.items()
        },
        drive_inprocess=False,
    )
    for wl in backlog():
        manager.add_workload(wl)

    # (a) dispatch fan-out: ONE federation pass mirrors the whole
    # backlog to every ranked worker (no worker scheduling yet)
    t0 = time.perf_counter()
    dispatcher.step()
    fanout_s = time.perf_counter() - t0
    mirrored = sum(len(rt.workloads) for rt in workers.values())
    assert mirrored >= n_wl, f"fan-out mirrored only {mirrored} copies"

    # (b) admission throughput: drive manager + workers to convergence
    dispatcher.drive_inprocess = True
    t1 = time.perf_counter()
    for _ in range(50):
        manager.run_until_idle()
        admitted = {
            key
            for key, wl in manager.workloads.items()
            if wl.is_admitted
        }
        if len(admitted) == n_wl:
            break
    total_s = time.perf_counter() - t1
    assert len(admitted) == n_wl, f"only {len(admitted)}/{n_wl} admitted"

    # reference: the best single-cluster run (identical worker, same
    # backlog submitted directly) — federated set must match it
    ref = build_worker()
    for wl in backlog():
        ref.add_workload(wl)
    for _ in range(50):
        ref.run_until_idle()
        ref_admitted = {
            key for key, wl in ref.workloads.items() if wl.is_admitted
        }
        if len(ref_admitted) == n_wl:
            break
    assert admitted == ref_admitted, (
        f"federated admitted set diverged from the single-cluster "
        f"reference: {sorted(admitted ^ ref_admitted)[:5]}..."
    )
    # every control plane consistent after the run
    for name, rt in workers.items():
        violations = rt.check_invariants()
        assert not violations, f"worker {name}: {violations}"
    # exactly one copy (the winner's) per workload survives
    for key in admitted:
        holders = [n for n, rt in workers.items() if key in rt.workloads]
        assert len(holders) == 1, f"{key} held by {holders}"
    return (
        fanout_s * 1e3,
        n_wl / total_s,
        mirrored,
        len(admitted),
    )


def federation_churn_bench(
    rng, n_workers=3, n_wl=90, worker_cpu=40, churn_rounds=3
):
    """Membership-churn stage (the elastic capacity plane's federation
    half): a live federation under a full backlog while workers JOIN at
    runtime and loaded workers are DRAINED and REMOVED (drain-ahead
    scale-down: deposed winners re-dispatch onto surviving capacity
    under the fencing protocol). Measures per-deposed-placement
    readmission latency — drain issued to admitted-again on a survivor.
    Exactly-once admission and per-plane invariants asserted through
    every round. Returns (joins, drains, readmit_p95_ms, n_readmitted,
    admitted)."""
    from kueue_tpu.admissionchecks.multikueue import MultiKueueCluster
    from kueue_tpu.controllers import ClusterRuntime
    from kueue_tpu.federation import FederationDispatcher
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.workload import PodSet
    from kueue_tpu.utils.clock import FakeClock

    clock = FakeClock(0.0)

    def build_worker():
        rt = ClusterRuntime(clock=clock, use_solver=False)
        rt.add_flavor(ResourceFlavor(name="default"))
        rt.add_cluster_queue(
            ClusterQueue(
                name="cq",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (
                            FlavorQuotas.build(
                                "default", {"cpu": str(worker_cpu)}
                            ),
                        ),
                    ),
                ),
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
        )
        return rt

    # every drain is preceded by a join, so the backlog always fits
    # the constant-size roster of survivors
    assert n_wl <= n_workers * worker_cpu, "drain must fit survivors"
    planes = {f"cw{i}": build_worker() for i in range(n_workers)}
    manager = ClusterRuntime(clock=clock)
    dispatcher = FederationDispatcher(
        manager,
        clusters={
            name: MultiKueueCluster(name=name, runtime=rt)
            for name, rt in planes.items()
        },
        drive_inprocess=True,
    )
    for i in range(n_wl):
        manager.add_workload(
            Workload(
                namespace="ns",
                name=f"churn-{i:04d}",
                queue_name="lq",
                priority=int(rng.integers(0, 5)),
                pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
            )
        )

    def admitted_keys():
        return {
            key
            for key, wl in manager.workloads.items()
            if wl.is_admitted
        }

    def settle(want=n_wl):
        for _ in range(80):
            manager.run_until_idle()
            clock.advance(1.0)
            if len(admitted_keys()) == want:
                return
        raise AssertionError(
            f"only {len(admitted_keys())}/{want} admitted after churn"
        )

    settle()
    joins = drains = 0
    next_id = n_workers
    readmit_ms = []
    victims = sorted(planes)[:churn_rounds]
    for victim in victims:
        # scale-up join first so the drain always has headroom to land on
        name = f"cw{next_id}"
        next_id += 1
        rt = build_worker()
        planes[name] = rt
        dispatcher.add_worker(MultiKueueCluster(name=name, runtime=rt))
        joins += 1
        # drain-ahead scale-down of a loaded worker
        deposed_keys = {
            key
            for key, st in dispatcher.states.items()
            if st.winner == victim and not st.finished
        }
        t0 = time.perf_counter()
        dispatcher.drain_worker(victim)
        drains += 1
        outstanding = set(deposed_keys)
        for _ in range(80):
            if not outstanding:
                break
            manager.run_until_idle()
            clock.advance(1.0)
            landed = {k for k in outstanding if manager.workloads[k].is_admitted}
            if landed:
                dt_ms = (time.perf_counter() - t0) * 1e3
                readmit_ms.extend(dt_ms for _ in landed)
                outstanding -= landed
        assert not outstanding, (
            f"{len(outstanding)} placements never readmitted after "
            f"draining {victim}"
        )
        assert dispatcher.remove_worker(victim)
        removed = planes.pop(victim)
        settle()
        # the removed plane holds no live copy of anything readmitted
        still_held = deposed_keys & set(removed.workloads)
        live = {k for k in still_held if not removed.workloads[k].is_finished}
        assert not live, f"{victim} still holds {sorted(live)[:5]}"
        # exactly one surviving copy per placement
        for key in admitted_keys():
            holders = [
                n for n, rt in planes.items() if key in rt.workloads
            ]
            assert len(holders) == 1, f"{key} held by {holders}"
        for name, rt in planes.items():
            violations = rt.check_invariants()
            assert not violations, f"worker {name}: {violations}"
    assert len(admitted_keys()) == n_wl
    readmit_ms.sort()
    p95 = (
        readmit_ms[min(len(readmit_ms) - 1, int(0.95 * len(readmit_ms)))]
        if readmit_ms
        else 0.0
    )
    return joins, drains, p95, len(readmit_ms), len(admitted_keys())


def grayfail_bench(rng, n_workers=12, n_wl=180, worker_cpu=200, fanout=2):
    """Gray-failure A/B (PR 20): a 12-worker federation with ONE
    limping worker — every exchange answers just under the CURRENT
    per-call deadline (LatencyTransport deadline_fraction=0.99, the
    adversarial gray worker a fixed timeout can never catch) — run
    twice over the same seeded backlog:

      A (fixed):    adaptive_deadlines=False, hedging=False, health
                    plane neutralized (degrade_min_samples too high to
                    ever trip) — the pre-PR-20 configuration; every
                    exchange to the limper costs 9.9 simulated
                    seconds, forever, and ranking keeps dispatching
                    onto it.
      B (adaptive): defaults — the latency health plane degrades the
                    limper into probation (no NEW dispatches, existing
                    placements keep syncing), adaptive deadlines clamp
                    the per-call budget, hedged dispatch covers the
                    detection window under the <=5% budget.

    Reports fleet-wide dispatch p95 (RecordingTransport outside the
    chaos wrapper — exactly what the dispatcher observed) and
    admissions per simulated second for both phases, plus phase B's
    hedge rate. Both phases and a healthy-fleet reference must admit
    the IDENTICAL workload set exactly once — immunity must not cost
    correctness."""
    from kueue_tpu.admissionchecks.multikueue import MultiKueueCluster
    from kueue_tpu.admissionchecks.multikueue_transport import (
        InProcessTransport,
    )
    from kueue_tpu.controllers import ClusterRuntime
    from kueue_tpu.federation import FederationDispatcher
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.workload import PodSet
    from kueue_tpu.testing import faults
    from kueue_tpu.testing.chaos import LatencyTransport, RecordingTransport
    from kueue_tpu.utils.clock import FakeClock

    def build_worker(clock):
        rt = ClusterRuntime(clock=clock, use_solver=False)
        rt.add_flavor(ResourceFlavor(name="default"))
        rt.add_cluster_queue(
            ClusterQueue(
                name="cq",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (
                            FlavorQuotas.build(
                                "default", {"cpu": str(worker_cpu)}
                            ),
                        ),
                    ),
                ),
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
        )
        return rt

    priorities = [int(p) for p in rng.integers(0, 5, size=n_wl)]

    def backlog():
        return [
            Workload(
                namespace="ns",
                name=f"gray-{i:04d}",
                queue_name="lq",
                priority=priorities[i],
                pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
            )
            for i in range(n_wl)
        ]

    def run(limping, adaptive):
        faults.reset()
        clock = FakeClock(0.0)
        sink = []  # fleet-wide observed exchange latencies (sim s)
        clusters = {}
        for i in range(n_workers):
            name = f"w{i:02d}"
            inner = InProcessTransport(build_worker(clock))
            if limping and i == 0:
                inner = LatencyTransport(
                    inner, clock, deadline_fraction=0.99
                )
            clusters[name] = MultiKueueCluster(
                name=name,
                transport=RecordingTransport(inner, clock, sink=sink),
            )
        manager = ClusterRuntime(clock=clock)
        dispatcher = FederationDispatcher(
            manager,
            clusters=clusters,
            fanout=fanout,
            drive_inprocess=True,
            adaptive_deadlines=adaptive,
            hedging=adaptive,
            # the baseline is pre-PR-20: no latency health plane at
            # all — neutralize degradation so probation can't quietly
            # route around the limper in the A phase
            health_plane_kw=(
                None if adaptive else {"degrade_min_samples": 10**9}
            ),
        )
        for wl in backlog():
            manager.add_workload(wl)
        t0 = clock.now()
        admitted = set()
        for _ in range(80):
            manager.run_until_idle()
            admitted = {
                key
                for key, wl in manager.workloads.items()
                if wl.is_admitted
            }
            if len(admitted) == n_wl:
                break
            clock.advance(5.0)  # let heartbeats / probation holds move
        assert len(admitted) == n_wl, (
            f"only {len(admitted)}/{n_wl} admitted "
            f"(limping={limping} adaptive={adaptive})"
        )
        elapsed = max(clock.now() - t0, 1e-9)
        # exactly one live copy per admitted workload across the fleet
        for key in admitted:
            holders = [
                n
                for n, c in clusters.items()
                if key in c.runtime.workloads
                and not c.runtime.workloads[key].is_finished
            ]
            assert len(holders) == 1, f"{key} held by {holders}"
        sink.sort()
        p95 = (
            sink[min(len(sink) - 1, int(0.95 * len(sink)))]
            if sink
            else 0.0
        )
        return {
            "admitted": admitted,
            "dispatch_p95_ms": p95 * 1e3,
            "admissions_per_s": n_wl / elapsed,
            "hedge_rate": dispatcher.worker_health.hedge_rate(),
            "exchanges": len(sink),
        }

    ref = run(limping=False, adaptive=True)
    fixed = run(limping=True, adaptive=False)
    adaptive = run(limping=True, adaptive=True)
    assert fixed["admitted"] == ref["admitted"], (
        "fixed-config admitted set diverged from the healthy reference"
    )
    assert adaptive["admitted"] == ref["admitted"], (
        "adaptive-config admitted set diverged from the healthy "
        "reference — gray-failure immunity must not cost correctness"
    )
    assert adaptive["dispatch_p95_ms"] <= fixed["dispatch_p95_ms"], (
        f"adaptive dispatch p95 {adaptive['dispatch_p95_ms']:.0f}ms "
        f"did not beat fixed {fixed['dispatch_p95_ms']:.0f}ms"
    )
    assert adaptive["hedge_rate"] <= 0.05 + 1e-9, (
        f"hedge rate {adaptive['hedge_rate']:.4f} blew the 5% budget"
    )
    return fixed, adaptive, ref


def trace_bench(rng):
    """Always-on tracing overhead at the 50k north-star scale: the
    IDENTICAL seeded backlog drained to quiescence through
    ClusterRuntime bulk rounds with the distributed tracer enabled vs
    disabled (``ClusterRuntime(tracing=...)``). Admitted sets are
    asserted bit-identical (tracing must never influence decisions).
    The <2 % acceptance budget is asserted on the tracer's EXACT
    self-accounted in-drain time (``tracer.self_time_s`` — the
    guard.divergence_check_s pattern): a wall-clock A/B on a shared
    1-core host swings ±20 % run-to-run (allocator/cgroup noise),
    which would make the assertion measure the neighbors, not the
    tracer; the wall delta is still measured and reported. Returns
    (off_s, on_s, overhead_pct, n_spans, n_admitted)."""
    import time

    from kueue_tpu.controllers import ClusterRuntime
    from kueue_tpu.core.scheduler import _LatencyEstimate
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.workload import PodSet

    class _OpenGate(_LatencyEstimate):
        @property
        def value(self):
            return None

    def build(tracing, seed):
        rng2 = np.random.default_rng(seed)
        rt = ClusterRuntime(
            bulk_drain_threshold=256,
            drain_pipeline="on",
            pipeline_chunk_cycles=16,
            drain_gate=_OpenGate(),
            tracing=tracing,
        )
        rt.guard.config.divergence_check_every = 0
        flavors = [f"fl-{i}" for i in range(N_FLAVORS)]
        for f in flavors:
            rt.add_flavor(ResourceFlavor(name=f))
        for i in range(N_CQ):
            quotas = tuple(
                FlavorQuotas.build(
                    f,
                    {
                        "cpu": (str(int(rng2.integers(8, 64))), None, None),
                        "memory": (
                            f"{int(rng2.integers(16, 128))}Gi", None, None
                        ),
                    },
                )
                for f in flavors
            )
            rt.add_cluster_queue(
                ClusterQueue(
                    name=f"tcq-{i}",
                    cohort=f"tcohort-{i % N_COHORT}",
                    namespace_selector={},
                    resource_groups=(ResourceGroup(("cpu", "memory"), quotas),),
                )
            )
            rt.add_local_queue(
                LocalQueue(
                    namespace="ns", name=f"tlq-{i}", cluster_queue=f"tcq-{i}"
                )
            )
        n = N_CQ * WL_PER_CQ
        prios = rng2.integers(0, 4, size=n) * 50
        cpus = rng2.integers(1, 16, size=n)
        mems = rng2.integers(1, 32, size=n)
        for j in range(n):
            rt.add_workload(
                Workload(
                    namespace="ns",
                    name=f"tw{j}",
                    queue_name=f"tlq-{j % N_CQ}",
                    priority=int(prios[j]),
                    creation_time=float(j),
                    pod_sets=(
                        PodSet.build(
                            "main", 1,
                            {"cpu": str(cpus[j]), "memory": f"{mems[j]}Gi"},
                        ),
                    ),
                )
            )
        rt.reconcile_once()
        return rt

    def drain(rt):
        t0 = time.perf_counter()
        res = rt.bulk_drain()
        dt = time.perf_counter() - t0
        assert res is not None, "bulk drain did not run"
        return dt

    def admitted_of(rt):
        return frozenset(
            k for k, wl in rt.workloads.items() if wl.has_quota_reservation
        )

    def measure(tracing):
        # measurement hygiene: nothing from the previous run may stay
        # alive (two 50k runtimes resident at once skews the host's
        # allocator enough to masquerade as tracer overhead), and each
        # drain starts from a collected heap
        import gc

        rt = build(tracing, seed)
        rt.tracer.self_time_s = 0.0  # account the DRAIN only
        gc.collect()
        dt = drain(rt)
        adm = admitted_of(rt)
        extra = None
        if tracing:
            assert rt.tracer.open_spans("cycle") == [], (
                "drain left half-open cycle spans"
            )
            extra = (len(rt.tracer), rt.tracer.self_time_s)
        del rt
        gc.collect()
        return dt, adm, extra

    seed = int(rng.integers(1 << 30))
    _stage("trace: warmup (compile every chunk shape, both modes)")
    measure(False)
    measure(True)
    _stage("trace: baseline (tracing off)")
    off_s, adm_off, _ = measure(False)
    _stage("trace: measured (tracing on)")
    on_s, adm_on, (n_spans, self_time_s) = measure(True)
    assert adm_off == adm_on, "tracing changed admission decisions"
    overhead_pct = self_time_s / max(on_s, 1e-9) * 100
    assert overhead_pct < 2.0, (
        f"tracing overhead {overhead_pct:.2f}% exceeds the 2% budget "
        f"(tracer self-time {self_time_s:.3f}s in a {on_s:.3f}s drain)"
    )
    return off_s, on_s, overhead_pct, n_spans, len(adm_on)


def serve_bench(
    rng,
    duration_s=3.0,
    rate_per_s=1200.0,
    n_writers=96,
    n_readers=2,
    n_cq=8,
    quota_cpu=64,
):
    """Scaled serving-tier A/B (the gateway acceptance guardrail): an
    open-loop Poisson arrival stream (perf/generator.ArrivalProcess) at
    ``rate_per_s`` is POSTed by ``n_writers`` concurrent writer threads
    against a live journaled leader whose admission runs on a dedicated
    loop (identical in both phases, so the A/B isolates the WRITE
    path) — phase A with the gateway OFF (every POST takes the serving
    lock individually, contending with the admission passes), phase B
    with the WriteGateway coalescing writes (one lock critical section
    + one group-committed journal sync + one recorder wake per flush
    window, per-tenant token buckets shedding with 429; the writers'
    KueueClient honors Retry-After with capped jittered backoff). A
    journal-tailing READ REPLICA subprocess is attached in BOTH phases
    (identical serving surface) with reader threads on it. Reports sustained ingest
    throughput (accepted POSTs/s over the ingest wall), POST round-trip
    (enqueue) latency percentiles, decision latency, shed percentage,
    read QPS offloaded, and max replica staleness; each phase's
    drained leader and caught-up replica state dumps are asserted
    byte-identical (the convergence acceptance check), and the A/B
    must show >=2x sustained ingest or >=2x lower p95 enqueue latency.

    Host nomination path on purpose: the measured surface is serving +
    journal + replication; a one-off device compile landing in phase A
    would bias the A/B. The replica runs as a SEPARATE PROCESS
    (``python -m kueue_tpu.server --replica-of``) — the production
    topology."""
    import socket
    import tempfile
    import threading

    from kueue_tpu import serialization as ser
    from kueue_tpu.controllers import ClusterRuntime
    from kueue_tpu.gateway import TenantLimiter, WriteGateway
    from kueue_tpu.perf.generator import ArrivalProcess, arrival_stream
    from kueue_tpu.server import KueueServer
    from kueue_tpu.server.client import KueueClient
    from kueue_tpu.storage import Journal

    def cq_dict(name):
        return {
            "name": name,
            "namespaceSelector": {},
            "resourceGroups": [
                {
                    "coveredResources": ["cpu"],
                    "flavors": [
                        {
                            "name": "default",
                            "resources": [
                                {"name": "cpu",
                                 "nominalQuota": str(quota_cpu)}
                            ],
                        }
                    ],
                }
            ],
        }

    proc = ArrivalProcess(
        rate_per_s=rate_per_s, duration_s=duration_s, process="poisson"
    )

    def run_phase(batching: bool, phase_rng) -> dict:
        tmp = tempfile.mkdtemp(prefix="kueue-serve-")
        rt = ClusterRuntime(use_solver=False, bulk_drain_threshold=None)
        journal = Journal(os.path.join(tmp, "journal")).open()
        rt.attach_journal(journal)
        from kueue_tpu.models import LocalQueue, ResourceFlavor

        rt.add_flavor(ResourceFlavor(name="default"))
        lq_names = []
        for i in range(n_cq):
            rt.add_cluster_queue(ser.cq_from_dict(cq_dict(f"cq-{i}")))
            lq = LocalQueue(
                namespace="perf", name=f"lq-{i}", cluster_queue=f"cq-{i}"
            )
            rt.add_local_queue(lq)
            lq_names.append(lq.name)
        gateway = None
        if batching:
            # tenant budget: 2x each LocalQueue's balanced share of the
            # stream — a Poisson burst can trip it (shed + client
            # retry-after backoff engage), steady traffic flows.
            # reconcile=False: admission cadence is the dedicated loop
            # below in BOTH phases, so the A/B isolates the WRITE path
            # (per-request serving-lock acquisition + journal fsync +
            # recorder wake vs one of each per flush window)
            gateway = WriteGateway(
                flush_interval_s=0.002,
                max_batch=1024,
                max_queue=8192,
                reconcile=False,
                limiter=TenantLimiter(
                    2.0 * rate_per_s / n_cq,
                    burst=2.0 * rate_per_s / n_cq,
                ),
            )
        srv = KueueServer(runtime=rt, auto_reconcile=False, gateway=gateway)
        port = srv.start()
        leader_url = f"http://127.0.0.1:{port}"
        with socket.socket() as s:  # pre-pick a free port
            s.bind(("127.0.0.1", 0))
            rport = s.getsockname()[1]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        rep_proc = subprocess.Popen(
            [
                sys.executable, "-m", "kueue_tpu.server",
                "--replica-of", leader_url,
                "--port", str(rport),
                "--replica-poll-interval", "0.05",
                "--replica-id", "bench-replica",
            ],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        read_url = f"http://127.0.0.1:{rport}"
        probe = KueueClient(read_url, timeout=2.0)
        deadline = time.perf_counter() + 60.0
        while time.perf_counter() < deadline:
            try:
                if not probe.healthz().get("replication", {}).get(
                    "lastError"
                ):
                    break
            except Exception:  # noqa: BLE001 — still booting
                pass
            time.sleep(0.2)
        else:
            rep_proc.kill()
            raise RuntimeError("replica subprocess never became healthy")

        stream = arrival_stream(proc, lq_names, phase_rng)
        stop = threading.Event()
        stats_lock = threading.Lock()
        submit_ts: dict = {}
        post_lat: list = []  # POST round trip (the enqueue latency)
        admit_lat: list = []  # submit -> Admitted (decision latency)
        due: dict = {}  # key -> wall time its service completes
        seen_admitted: set = set()
        accepted = [0]
        post_failures = [0]
        throttled = [0]
        reads = [0] * n_readers
        read_errors = [0]
        max_lag = [0.0]
        rep_status: dict = {}
        next_arrival = [0]
        t_start = time.perf_counter()

        def writer_loop():
            # shed writes retry with capped jittered Retry-After
            # backoff (the KueueClient 429 contract)
            client = KueueClient(
                leader_url, timeout=30.0, max_429_retries=8,
                backoff_base_s=0.02, backoff_cap_s=0.5,
            )
            while True:
                with stats_lock:
                    i = next_arrival[0]
                    next_arrival[0] += 1
                if i >= len(stream):
                    break
                gw = stream[i]
                delay = gw.creation_s - (time.perf_counter() - t_start)
                if delay > 0:
                    time.sleep(delay)
                d = ser.workload_to_dict(gw.workload)
                d.setdefault("labels", {})["bench/runtime-s"] = str(
                    gw.runtime_s
                )
                key = f"perf/{gw.workload.name}"
                t0 = time.perf_counter()
                submit_ts[key] = t0
                try:
                    client.apply("workloads", d)
                except Exception:  # noqa: BLE001 — a write the backoff
                    # could not land (shed past the retry budget)
                    with stats_lock:
                        post_failures[0] += 1
                    submit_ts.pop(key, None)
                    continue
                lat = time.perf_counter() - t0
                with stats_lock:
                    accepted[0] += 1
                    post_lat.append(lat)
            with stats_lock:
                throttled[0] += client.throttled_total

        def completion_loop():
            # the admission loop (identical in both phases — the A/B
            # measures the WRITE path): one run_until_idle pass, then
            # decision-latency tracking + service completion (finished
            # workloads release quota)
            while not stop.is_set():
                now = time.perf_counter()
                with srv.lock:
                    srv.runtime.run_until_idle()
                    for key, wl in list(srv.runtime.workloads.items()):
                        if wl.is_admitted and key not in seen_admitted:
                            seen_admitted.add(key)
                            if key in submit_ts:
                                admit_lat.append(now - submit_ts[key])
                            due[key] = now + float(
                                wl.labels.get("bench/runtime-s", 0.2)
                                if wl.labels else 0.2
                            )
                    for key, t_done in list(due.items()):
                        if now >= t_done:
                            wl = srv.runtime.workloads.get(key)
                            if wl is not None:
                                srv.runtime.delete_workload(wl)
                            due.pop(key, None)
                stop.wait(0.005)

        def lag_sampler():
            client = KueueClient(read_url, timeout=2.0)
            while not stop.is_set():
                try:
                    detail = client.healthz().get("replication", {})
                    rep_status.update(detail)
                    max_lag[0] = max(
                        max_lag[0], float(detail.get("lagSeconds", 0.0))
                    )
                except Exception:  # noqa: BLE001 — sampler only
                    pass
                stop.wait(0.2)

        def reader_loop(idx: int):
            client = KueueClient(read_url, timeout=5.0)
            i = 0
            while not stop.is_set():
                try:
                    if i % 3 == 2:
                        client.healthz()
                    else:
                        client.pending_workloads_cq(f"cq-{i % n_cq}")
                    reads[idx] += 1
                except Exception:  # noqa: BLE001 — count and continue
                    read_errors[0] += 1
                i += 1

        writers = [
            threading.Thread(target=writer_loop, daemon=True)
            for _ in range(n_writers)
        ]
        aux = [threading.Thread(target=completion_loop, daemon=True)]
        aux += [
            threading.Thread(target=reader_loop, args=(i,), daemon=True)
            for i in range(n_readers)
        ]
        aux.append(threading.Thread(target=lag_sampler, daemon=True))
        for t in writers + aux:
            t.start()
        for t in writers:
            t.join(timeout=300)
        wall_ingest = time.perf_counter() - t_start
        # drain the tail: stop arrivals, admit everything accepted
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline:
            with srv.lock:
                srv.runtime.run_until_idle()
                backlog = sum(
                    1
                    for wl in srv.runtime.workloads.values()
                    if not wl.is_admitted
                )
            if backlog == 0 and len(seen_admitted) >= accepted[0]:
                break
            time.sleep(0.02)
        stop.set()
        for t in aux:
            t.join(timeout=10)
        gw_stats = gateway.status() if gateway is not None else {}
        shed_total = sum(gw_stats.get("shed", {}).values())
        # quiescent convergence: replica caught up to the leader's
        # journal head serves a byte-identical state dump
        probe = KueueClient(read_url, timeout=5.0)
        deadline = time.perf_counter() + 15.0
        while time.perf_counter() < deadline:
            try:
                detail = probe.healthz().get("replication", {})
                if detail.get("appliedSeq", -1) >= journal.last_seq:
                    rep_status.update(detail)
                    break
            except Exception:  # noqa: BLE001 — keep waiting
                pass
            time.sleep(0.1)
        records_applied = rep_status.get("recordsApplied")
        leader_state = json.dumps(
            KueueClient(leader_url).state(), sort_keys=True
        )
        replica_state = json.dumps(probe.state(), sort_keys=True)
        converged = leader_state == replica_state
        rep_proc.terminate()
        rep_proc.wait(timeout=15)
        srv.stop()
        journal.close()

        def pct(samples, p):
            vals = sorted(x * 1e3 for x in samples)
            if not vals:
                return None
            return round(vals[min(len(vals) - 1, int(p * len(vals)))], 3)

        attempts = accepted[0] + shed_total
        return {
            "submitted": len(stream),
            "accepted": accepted[0],
            "post_failures": post_failures[0],
            "admitted": len(seen_admitted),
            "ingest_per_s": round(accepted[0] / max(wall_ingest, 1e-9), 1),
            "ingest_wall_s": round(wall_ingest, 3),
            "enqueue_p50_ms": pct(post_lat, 0.50),
            "enqueue_p95_ms": pct(post_lat, 0.95),
            "decision_p50_ms": pct(admit_lat, 0.50),
            "decision_p95_ms": pct(admit_lat, 0.95),
            "shed_429s": shed_total,
            "client_throttled": throttled[0],
            "shed_pct": round(
                100.0 * shed_total / attempts, 2
            ) if attempts else 0.0,
            "gateway": {
                k: gw_stats.get(k)
                for k in ("batches", "lastBatch", "maxBatchSeen",
                          "applied", "shed")
            } if gw_stats else None,
            "read_qps": round(sum(reads) / max(wall_ingest, 1e-9), 1),
            "read_errors": read_errors[0],
            "max_lag_s": round(max_lag[0], 3),
            "records_applied": records_applied,
            "converged": converged,
        }

    _stage("serve: phase A (gateway off — per-request serial ingest)")
    base = run_phase(False, np.random.default_rng(rng.integers(1 << 30)))
    _stage("serve: phase B (gateway on — coalesced batched ingest)")
    batched = run_phase(True, np.random.default_rng(rng.integers(1 << 30)))
    for name, phase in (("A", base), ("B", batched)):
        assert phase["converged"], (
            f"serve phase {name}: replica state dump != leader state "
            "dump at quiescence"
        )
        assert phase["max_lag_s"] < 2.0, (
            f"serve phase {name}: replica staleness {phase['max_lag_s']}s "
            "exceeds the 2s bound"
        )
        assert phase["admitted"] == phase["accepted"], (
            f"serve phase {name} did not drain to quiescence "
            f"({phase['admitted']} admitted of {phase['accepted']})"
        )
    ingest_ratio = batched["ingest_per_s"] / max(base["ingest_per_s"], 1e-9)
    p95_ratio = (
        base["enqueue_p95_ms"] / max(batched["enqueue_p95_ms"], 1e-9)
        if base["enqueue_p95_ms"] and batched["enqueue_p95_ms"]
        else 0.0
    )
    assert ingest_ratio >= 2.0 or p95_ratio >= 2.0, (
        f"gateway batching A/B below the 2x acceptance bar: ingest "
        f"{batched['ingest_per_s']} vs {base['ingest_per_s']}/s "
        f"({ingest_ratio:.2f}x), enqueue p95 {batched['enqueue_p95_ms']} "
        f"vs {base['enqueue_p95_ms']} ms ({p95_ratio:.2f}x)"
    )
    return base, batched


def policy_drain_bench(rng, n_cq=48, wl_per_cq=64, reps=6, hint_s=600.0):
    """Admission-policy overhead + benefit (kueue_tpu/policy): ONE
    seeded heterogeneous backlog — every CQ walks a slow flavor before
    a fast one, workloads declare 2-4x throughput on fast — drained
    under the default first-fit policy and under Gavel scoring
    (arXiv:2008.09213). The scored kernel is the SAME program either
    way (first-fit ships an all-zero score tensor), so the measured
    overhead is the policy compilation + score transfer; the benefit
    is measured on the shipped virtual-time forecaster (the planner's
    ``policy`` scenario kind): makespan + mean time-to-admission of
    Gavel vs FIFO over the same backlog.

    Returns (ff_ms_per_cycle, gavel_ms_per_cycle, n_pending, admitted,
    makespan_improvement_pct, tta_improvement_pct)."""
    import time

    from kueue_tpu.core.cache import Cache
    from kueue_tpu.core.drain import run_drain
    from kueue_tpu.core.queue_manager import QueueManager, queue_order_timestamp
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.workload import PodSet
    from kueue_tpu.policy import THROUGHPUT_LABEL_PREFIX, resolve_policy
    from kueue_tpu.utils.clock import FakeClock

    clock = FakeClock(0.0)
    cache = Cache()
    mgr = QueueManager(clock)
    cache.add_or_update_flavor(ResourceFlavor(name="slow"))
    cache.add_or_update_flavor(ResourceFlavor(name="fast"))
    w_rng = np.random.default_rng(int(rng.integers(1 << 30)))
    t = 0.0
    for i in range(n_cq):
        name = f"pcq-{i}"
        cq = ClusterQueue(
            name=name,
            cohort=None,
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",),
                    (
                        FlavorQuotas.build(
                            "slow",
                            {"cpu": (str(int(w_rng.integers(8, 24))), None, None)},
                        ),
                        FlavorQuotas.build(
                            "fast",
                            {"cpu": (str(int(w_rng.integers(8, 24))), None, None)},
                        ),
                    ),
                ),
            ),
        )
        cache.add_or_update_cluster_queue(cq)
        mgr.add_cluster_queue(cq)
        mgr.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{name}", cluster_queue=name)
        )
        for wi in range(wl_per_cq):
            t += 1.0
            # quantized throughput classes (realistic fleets declare a
            # handful of job-type profiles, and quantization keeps the
            # score-row compile cache hot)
            tput = round(float(w_rng.uniform(2.0, 4.0)), 1)
            mgr.add_or_update_workload(
                Workload(
                    namespace="ns",
                    name=f"pwl-{i}-{wi}",
                    queue_name=f"lq-{name}",
                    creation_time=t,
                    labels={THROUGHPUT_LABEL_PREFIX + "fast": f"{tput:.1f}"},
                    pod_sets=(
                        PodSet.build(
                            "main", 1,
                            {"cpu": str(int(w_rng.integers(2, 8)))},
                        ),
                    ),
                )
            )

    pending = [
        (wl, cq_name)
        for cq_name, pq in mgr.cluster_queues.items()
        for wl in pq.snapshot_sorted()
    ]
    snapshot = take_snapshot(cache)
    ts_fn = lambda wl: queue_order_timestamp(wl, mgr._ts_policy)  # noqa: E731
    gavel = resolve_policy("gavel")

    from kueue_tpu.core.drain import plan_drain

    # warmup both paths (one compiled program — first-fit ships an
    # all-zero score tensor through the same scored kernels)
    ff_out = run_drain(
        snapshot, pending, cache.flavors, timestamp_fn=ts_fn, policy=None
    )
    gv_out = run_drain(
        snapshot, pending, cache.flavors, timestamp_fn=ts_fn, policy=gavel
    )
    # INTERLEAVED reps: this box's wall-clock drifts minute-to-minute,
    # so back-to-back blocks would charge the drift to whichever
    # policy ran second; alternating reps exposes both to the same
    # noise and MIN-of-reps reads the shared floor. The plan/lowering
    # phase is timed alone per policy: subtracting it isolates the
    # KERNEL overhead (solve + transfer + fetch) from the host-side
    # score compilation, which amortizes over a whole pipelined launch
    # in production.
    plan_ff, plan_gv, tot_ff, tot_gv = [], [], [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        plan_drain(snapshot, pending, cache.flavors, timestamp_fn=ts_fn)
        plan_ff.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        plan_drain(
            snapshot, pending, cache.flavors, timestamp_fn=ts_fn,
            policy=gavel,
        )
        plan_gv.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        ff_out = run_drain(
            snapshot, pending, cache.flavors, timestamp_fn=ts_fn
        )
        tot_ff.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        gv_out = run_drain(
            snapshot, pending, cache.flavors, timestamp_fn=ts_fn,
            policy=gavel,
        )
        tot_gv.append(time.perf_counter() - t0)

    def _per_cycle(times, plan_ts, outcome):
        cycles = max(outcome.cycles, 1)
        total_s = float(min(times)) / cycles
        kernel_s = max(float(min(times)) - float(min(plan_ts)), 1e-9) / cycles
        return total_s, kernel_s

    ff_s, ff_k = _per_cycle(tot_ff, plan_ff, ff_out)
    gv_s, gv_k = _per_cycle(tot_gv, plan_gv, gv_out)
    _note_times("policy_first_fit", tot_ff)
    _note_times("policy_gavel", tot_gv)
    # admitted counts may legitimately differ a little (the scored
    # flavor choice changes the packing); the BENEFIT comparison below
    # is throughput-aware, which is the metric Gavel optimizes

    # benefit: the shipped virtual-time forecaster over the same
    # backlog (planner ``policy`` scenario kind)
    from kueue_tpu.planner.engine import Planner
    from kueue_tpu.planner.scenarios import PlanScenario, PolicyDelta

    planner = Planner(cache=cache, queues=mgr, clock=clock)
    report = planner.plan(
        scenarios=[PlanScenario(name="gavel", deltas=(PolicyDelta("gavel"),))],
        forecast=True,
        runtime_hint=lambda wl: hint_s,
        use_device=True,
    )
    base_fc = report.baseline.forecast or {}
    gv_scen = report.scenario("gavel")
    gv_fc = (gv_scen.forecast if gv_scen is not None else None) or {}
    mk_base, mk_gv = base_fc.get("makespan", 0.0), gv_fc.get("makespan", 0.0)
    tta_base, tta_gv = base_fc.get("mean", 0.0), gv_fc.get("mean", 0.0)
    mk_pct = (1.0 - mk_gv / mk_base) * 100 if mk_base > 0 else 0.0
    tta_pct = (1.0 - tta_gv / tta_base) * 100 if tta_base > 0 else 0.0
    return (
        (ff_s * 1e3, ff_k * 1e3), (gv_s * 1e3, gv_k * 1e3), len(pending),
        (len(ff_out.admitted), len(gv_out.admitted)),
        mk_pct, tta_pct,
    )


def _stage_serve() -> dict:
    base, batched = serve_bench(np.random.default_rng(14))
    ingest_ratio = (
        batched["ingest_per_s"] / max(base["ingest_per_s"], 1e-9)
    )
    p95_ratio = (
        base["enqueue_p95_ms"] / max(batched["enqueue_p95_ms"], 1e-9)
        if base["enqueue_p95_ms"] and batched["enqueue_p95_ms"]
        else None
    )
    return {
        "serve_metric": (
            "gateway_batched_ingest_ab (open-loop Poisson arrivals at "
            "1200/s of mixed 1/5-cpu workloads POSTed by 96 concurrent "
            "writers against a journaled leader; phase "
            "A per-request serial ingest, phase B WriteGateway "
            "coalescing [one lock section + group-committed journal "
            "sync + one recorder wake per 2ms flush window, per-tenant "
            "token buckets shedding 429, writers honoring Retry-After; "
            "identical dedicated admission loop in both phases]; a "
            "journal-tailing read replica subprocess "
            "attached in BOTH phases with 2 reader threads; "
            "leader+replica state dumps asserted byte-identical at "
            "quiescence per phase; >=2x ingest-or-p95 asserted; "
            f"{batched['admitted']} admitted in phase B)"
        ),
        # headline: sustained accepted-write throughput with batching
        # on — the number "serving heavy traffic" is gated on
        "serve_value": batched["ingest_per_s"],
        "serve_unit": "workloads/s (sustained ingest, gateway batching)",
        "serve_ingest_per_s": batched["ingest_per_s"],
        "serve_shed_pct": batched["shed_pct"],
        "serve_ingest_speedup": round(ingest_ratio, 2),
        "serve_enqueue_p50_ms": batched["enqueue_p50_ms"],
        "serve_enqueue_p95_ms": batched["enqueue_p95_ms"],
        "serve_enqueue_p95_speedup": (
            round(p95_ratio, 2) if p95_ratio is not None else None
        ),
        "serve_decision_p50_ms": batched["decision_p50_ms"],
        "serve_decision_p95_ms": batched["decision_p95_ms"],
        "serve_admissions_per_s": batched["ingest_per_s"],
        "serve_gateway": batched["gateway"],
        "serve_accepted": batched["accepted"],
        "serve_submitted": batched["submitted"],
        "serve_post_failures": batched["post_failures"],
        "serve_client_throttled": batched["client_throttled"],
        "serve_read_qps": batched["read_qps"],
        "serve_reads_offloaded_per_s": batched["read_qps"],
        "serve_max_lag_s": batched["max_lag_s"],
        "serve_records_applied": batched["records_applied"],
        "serve_host_cores": os.cpu_count(),
        "serve_read_errors": batched["read_errors"],
        "serve_baseline": {
            "ingest_per_s": base["ingest_per_s"],
            "enqueue_p50_ms": base["enqueue_p50_ms"],
            "enqueue_p95_ms": base["enqueue_p95_ms"],
            "decision_p50_ms": base["decision_p50_ms"],
            "read_qps": base["read_qps"],
        },
    }


def _stage(msg: str):
    """Progress marker on STDERR (the driver only parses stdout JSON);
    lets a timed-out payload show which stage it died in."""
    print(f"[bench +{time.perf_counter() - _T0:.1f}s] {msg}", file=sys.stderr, flush=True)


_T0 = time.perf_counter()


def _stage_policy() -> dict:
    ff, gv, n_pending, admitted, mk_pct, tta_pct = policy_drain_bench(
        np.random.default_rng(21)
    )
    ff_ms, ff_kernel_ms = ff
    gv_ms, gv_kernel_ms = gv
    # the scored KERNEL is the identical program under both policies
    # (first-fit = all-zero scores), so its solve+transfer+fetch cost
    # — total minus the separately-timed plan/lowering phase, where
    # the host-side score compilation lives — more than 10% apart is
    # a kernel regression, not noise
    overhead_pct = (
        (gv_kernel_ms / ff_kernel_ms - 1.0) * 100 if ff_kernel_ms > 0 else 0.0
    )
    total_overhead_pct = (gv_ms / ff_ms - 1.0) * 100 if ff_ms > 0 else 0.0
    assert overhead_pct < 10.0, (
        f"scored-kernel overhead {overhead_pct:.1f}% >= 10% vs first-fit "
        f"({gv_kernel_ms:.3f} vs {ff_kernel_ms:.3f} kernel ms/cycle)"
    )
    assert mk_pct > 0, f"gavel did not improve forecast makespan ({mk_pct}%)"
    ff_admitted, gv_admitted = admitted
    return {
        "policy_metric": (
            f"policy_scored_drain ({n_pending}-pending heterogeneous "
            f"backlog, slow/fast flavors with declared throughput, "
            f"drained under first-fit vs gavel; {ff_admitted} vs "
            f"{gv_admitted} admitted; virtual-time forecast benefit "
            "via the planner policy scenario)"
        ),
        "policy_value": round(gv_ms, 3),
        "policy_unit": "ms/cycle",
        "policy_admitted": {"firstFit": ff_admitted, "gavel": gv_admitted},
        "policy_first_fit_ms_per_cycle": round(ff_ms, 3),
        "policy_kernel_ms_per_cycle": round(gv_kernel_ms, 3),
        "policy_first_fit_kernel_ms_per_cycle": round(ff_kernel_ms, 3),
        "policy_overhead_pct": round(overhead_pct, 1),
        "policy_total_overhead_pct": round(total_overhead_pct, 1),
        "policy_makespan_improvement_pct": round(mk_pct, 1),
        "policy_tta_improvement_pct": round(tta_pct, 1),
        "policy_spread": _spread_of("policy_gavel"),
    }


def _stage_headline() -> dict:
    from kueue_tpu.core.drain import run_drain
    from kueue_tpu.core.snapshot import take_snapshot

    rng = np.random.default_rng(0)
    cache, mgr = build_cluster(rng)
    pending = build_backlog(rng)

    snapshot = take_snapshot(cache)

    # one full warmup at identical shapes (jit compile; the cache keys
    # are static shapes, so the measured run reuses the executable)
    _stage("headline drain: warmup (compile)")
    run_drain(snapshot, pending, cache.flavors, max_cells=3)

    reps = 3
    times = []
    for _ in range(reps):
        snapshot = take_snapshot(cache)
        t0 = time.perf_counter()
        outcome = run_drain(snapshot, pending, cache.flavors, max_cells=3)
        times.append(time.perf_counter() - t0)
    total_s = float(np.median(times))

    n_total = len(pending)
    n_admitted = len(outcome.admitted)
    assert not outcome.fallback, "bench backlog must be fully representable"
    assert outcome.cycles > 0 and n_admitted > 0
    _note_times("headline", [t / outcome.cycles for t in times])
    ms_per_cycle = total_s * 1e3 / outcome.cycles
    return {
        "metric": (
            f"full_drain_cycle_latency ({n_total // 1000}k pending x "
            f"{N_CQ} CQs, {N_COHORT} cohorts, K={N_FLAVORS}, 2 RGs, "
            f"{outcome.cycles} cycles, {n_admitted} admitted, "
            "lowering included)"
        ),
        "value": round(ms_per_cycle, 3),
        "unit": "ms/cycle",
        "vs_baseline": round(BASELINE_MS / ms_per_cycle, 2),
        "spread_ms": _spread_of("headline"),
    }


def _stage_pipeline() -> dict:
    serial_s, pipe_s, stats, admitted = pipelined_drain_bench(
        np.random.default_rng(13)
    )
    speedup = serial_s / max(pipe_s, 1e-9)
    return {
        "pipeline_metric": (
            f"pipelined_full_drain_wall_clock ({N_CQ * WL_PER_CQ // 1000}k "
            f"pending x {N_CQ} CQs "
            "drained to quiescence through ClusterRuntime bulk rounds "
            "of 16 kernel cycles: double-buffered loop [next round's "
            "encode+solve prefetched on a speculative snapshot during "
            "the host apply, conflict-checked at commit] vs the serial "
            f"loop on identical inputs, {stats.rounds} rounds, "
            f"{admitted} admitted, admitted sets asserted identical; "
            f"serial {round(serial_s, 2)} s)"
        ),
        "pipeline_value": round(pipe_s, 3),
        "pipeline_unit": "s (full pipelined drain)",
        "pipeline_serial_s": round(serial_s, 3),
        "pipeline_speedup_vs_serial": round(speedup, 2),
        "pipeline_overlap_ratio": round(stats.overlap_ratio, 3),
        "pipeline_rounds": stats.rounds,
        "pipeline_prefetch_commits": stats.commits,
        "pipeline_prefetch_discards": stats.discards,
        "pipeline_round_spread_ms": _spread_of("pipeline"),
    }


def _stage_megaloop() -> dict:
    (serial_s, pipe_s, mega_s, serial_d, mega_d, stats, admitted) = (
        megaloop_drain_bench(np.random.default_rng(17))
    )
    d = stats.to_dict()
    return {
        "megaloop_metric": (
            f"megaloop_full_drain_wall_clock ({N_CQ * WL_PER_CQ // 1000}k "
            f"pending x {N_CQ} CQs drained to quiescence through "
            "ClusterRuntime bulk rounds of 4 kernel cycles: fused "
            "K-rounds-per-dispatch megaloop [round-stamped decision "
            "log applied by the host trailing the device, per-round "
            "conflict checks] vs the pipelined and serial loops on "
            f"identical inputs; {d['rounds']} rounds in "
            f"{mega_d} dispatches vs {serial_d} serial dispatches, "
            f"{admitted} admitted, admitted sets asserted identical "
            "across all three modes; serial "
            f"{round(serial_s, 2)} s, pipelined {round(pipe_s, 2)} s)"
        ),
        "megaloop_value": round(mega_s, 3),
        "megaloop_unit": "s (full fused drain)",
        "megaloop_serial_s": round(serial_s, 3),
        "megaloop_pipelined_s": round(pipe_s, 3),
        "megaloop_speedup_vs_serial": round(serial_s / max(mega_s, 1e-9), 2),
        "megaloop_dispatches_per_drain": mega_d,
        "megaloop_serial_dispatches": serial_d,
        "megaloop_dispatch_reduction": round(serial_d / max(mega_d, 1), 2),
        "megaloop_rounds_per_launch": d["roundsPerLaunch"],
        "megaloop_truncations": d["truncations"],
        "megaloop_round_spread_ms": _spread_of("megaloop"),
    }


def _stage_contended() -> dict:
    from kueue_tpu.core.drain import _PANEL_TUNER

    cd_ms, cd_cycles, cd_admitted, cd_evicted, _sig = contended_drain_bench(
        np.random.default_rng(1)
    )
    return {
        "contended_metric": (
            "contended_drain_cycle_latency (5k pending, 1000 CQs "
            "in 100 cohorts: hoarders saturated above nominal, "
            "reclaimers cross-CQ-reclaiming them in-kernel "
            f"(strategy ladder + bwc thresholds), {cd_cycles} "
            f"cycles, {cd_admitted} admitted, {cd_evicted} "
            "preempted, one dispatch)"
        ),
        "contended_value": round(cd_ms, 3),
        "contended_unit": "ms/cycle",
        "contended_vs_baseline": round(BASELINE_MS / cd_ms, 2),
        "contended_spread_ms": _spread_of("contended"),
        # panel-ladder attribution: which width schedule the online
        # tuner converged to, and how often the exactness escape fired
        "contended_panel": {
            "widths": list(_PANEL_TUNER.widths_for(64)),
            "escalations": _PANEL_TUNER.escalations,
            "solves": _PANEL_TUNER.solves,
        },
    }


def _stage_tas() -> dict:
    tas_ms, tas_leaves, tas_pods = tas_placement_bench(
        np.random.default_rng(2)
    )
    return {
        "tas_metric": (
            f"tas_gang_placement ({tas_pods // 1000}k pods, "
            f"3-level topology, {tas_leaves} hosts, two-phase fit)"
        ),
        "tas_value": round(tas_ms, 3),
        "tas_unit": "ms/placement",
        "tas_vs_baseline": round(BASELINE_MS / tas_ms, 2),
        "tas_spread_ms": _spread_of("tas"),
    }


def _stage_fair() -> dict:
    fair_ms, fair_host_ms, fair_heads = fair_victim_search_bench(
        np.random.default_rng(3)
    )
    return {
        "fair_metric": (
            f"fair_victim_search ({fair_heads} preempt heads over "
            f"64 borrowing cohorts, batched tournament, one "
            f"dispatch; host tournament {round(fair_host_ms, 1)} ms)"
        ),
        "fair_value": round(fair_ms, 3),
        "fair_unit": "ms/batch",
        # one interactive dispatch carries the ~140ms tunnel round trip
        # on remote-attached TPUs; the honest comparison for this batch
        # is against the host tournament doing the same searches
        # sequentially
        "fair_vs_baseline": round(BASELINE_MS / fair_ms, 2),
        "fair_speedup_vs_host": round(fair_host_ms / fair_ms, 1),
        "fair_spread_ms": _spread_of("fair"),
    }


def _stage_fair_drain() -> dict:
    fd_s, fd_host_s, fd_pending, fd_cycles = fair_drain_bench(
        np.random.default_rng(4)
    )
    return {
        "fair_drain_metric": (
            f"fair_sharing_drain ({fd_pending} pending x 100 CQs "
            f"in 10 cohorts, in-kernel DRS tournament ordering, "
            f"{fd_cycles} cycles; host fair iterator "
            f"{round(fd_host_s * 1e3, 1)} ms)"
        ),
        "fair_drain_value": round(fd_s * 1e3, 3),
        "fair_drain_unit": "ms/drain",
        "fair_drain_speedup_vs_host": round(fd_host_s / max(fd_s, 1e-9), 1),
        "fair_drain_spread_ms": _spread_of("fair_drain"),
    }


def _stage_fair_preempt_drain() -> dict:
    fp_s, fp_host_s, fp_pending, fp_cycles, fp_evicted = (
        fair_preempt_drain_bench(np.random.default_rng(5))
    )
    return {
        "fair_preempt_drain_metric": (
            f"fair_preempt_drain ({fp_pending} pending x 60 CQs in "
            f"10 fair cohorts saturated by borrowing victims, "
            f"in-kernel fair victim tournament + DRS ordering, "
            f"{fp_cycles} cycles, {fp_evicted} evicted, one "
            f"dispatch; host fair scheduler "
            f"{round(fp_host_s * 1e3, 1)} ms)"
        ),
        "fair_preempt_drain_value": round(fp_s * 1e3, 3),
        "fair_preempt_drain_unit": "ms/drain",
        "fair_preempt_drain_speedup_vs_host": round(
            fp_host_s / max(fp_s, 1e-9), 1
        ),
        "fair_preempt_drain_spread_ms": _spread_of("fair_preempt_drain"),
    }


def _stage_interactive() -> dict:
    resident_ms, fresh_ms, host_ms, crossover = interactive_cycle_bench(
        np.random.default_rng(7)
    )
    return {
        "interactive_metric": (
            "interactive_cycle_dispatch (512-head nomination batch over "
            "1000 CQs; device-resident quota tensors vs ship-everything; "
            f"fresh dispatch {round(fresh_ms, 1)} ms, host flavor walk "
            f"{round(host_ms, 3)} ms/head)"
        ),
        "interactive_value": round(resident_ms, 3),
        "interactive_unit": "ms/dispatch",
        "interactive_fresh_ms": round(fresh_ms, 3),
        "interactive_host_ms_per_head": round(host_ms, 4),
        # the auto-gate picks the device above this head count
        "interactive_crossover_heads": round(crossover, 1),
        "interactive_spread_ms": _spread_of("interactive"),
    }


def _stage_planner() -> dict:
    pl_ms, pl_total_ms, pl_seq_ms, pl_admitting, pl_pending = planner_bench(
        np.random.default_rng(8)
    )
    return {
        "planner_metric": (
            f"planner_scenario_sweep (128-scenario quota sweep over a "
            f"{pl_pending}-pending snapshot, one vmapped launch, "
            f"{pl_admitting} scenarios admit a previously rejected "
            f"workload; sequential cycle-solver dispatches "
            f"{round(pl_seq_ms, 2)} ms/scenario)"
        ),
        "planner_value": round(pl_ms, 3),
        "planner_unit": "ms/scenario",
        "planner_scenarios_per_s": round(1e3 / pl_ms, 1) if pl_ms > 0 else None,
        "planner_plan_total_ms_per_scenario": round(pl_total_ms, 3),
        "planner_sequential_ms_per_scenario": round(pl_seq_ms, 3),
        "planner_speedup_vs_sequential": round(pl_seq_ms / max(pl_ms, 1e-9), 2),
        "planner_admitting_scenarios": pl_admitting,
    }


def _stage_journal() -> dict:
    base_ms, j_ms, appends, j_wall, admitted = journal_bench(
        np.random.default_rng(9)
    )
    overhead_pct = (j_ms / base_ms - 1.0) * 100 if base_ms > 0 else 0.0
    return {
        "journal_metric": (
            "journal_admission_overhead (1600-workload backlog drained "
            "through ClusterRuntime with the write-ahead journal on "
            f"[fsync=interval] vs off; {appends} records, {admitted} "
            "admitted, identical decisions asserted)"
        ),
        "journal_value": round(j_ms, 3),
        "journal_unit": "ms/cycle",
        "journal_baseline_ms_per_cycle": round(base_ms, 3),
        "journal_overhead_pct": round(overhead_pct, 1),
        "journal_appends_per_s": (
            round(appends / j_wall, 1) if j_wall > 0 else None
        ),
    }


def _stage_soak() -> dict:
    wall_s = float(os.environ.get("KUEUE_BENCH_SOAK_S", "20"))
    live = tuple(
        int(x)
        for x in os.environ.get(
            "KUEUE_BENCH_SOAK_LIVE", "10000,100000"
        ).split(",")
    )
    r = soak_bench(
        np.random.default_rng(19), wall_budget_s=wall_s, scale_live=live
    )
    w0, wN = r["windows"][0], r["windows"][-1]
    return {
        "soak_metric": (
            "soak_delta_checkpoint_latency (Poisson arrival+completion "
            f"churn through gateway+journal+replica for {wall_s:.0f}s "
            f"wall across {len(r['windows'])} windows; "
            f"{r['arrived']} arrived, {r['completed']} completed, "
            "RSS/journal/checkpoint-duration flat, replica convergent; "
            f"scale proof {live[0]} vs {live[-1]} live)"
        ),
        "soak_value": r["ckpt_delta_p95_ms"],
        "soak_unit": "ms (delta checkpoint p95 under churn)",
        "soak_windows": r["windows"],
        "soak_rss_mb_first": r["rss_mb_first"],
        "soak_rss_mb_last": r["rss_mb_last"],
        "soak_journal_mb_peak": r["journal_mb_peak"],
        "soak_journal_segments_peak": r["journal_segments_peak"],
        "soak_reclaimed_mb": r["reclaimed_mb"],
        "soak_ckpt_delta_p95_ms": r["ckpt_delta_p95_ms"],
        "soak_live_last": wN["live"],
        "soak_replica_lag_last": wN["replica_lag_records"],
        "soak_slo_attainment_min": min(
            w["slo_attainment_min"] for w in r["windows"]
        ),
        "soak_slo_degraded": any(
            w["slo_degraded"] for w in r["windows"]
        ),
        "soak_rss_growth_pct": round(
            (wN["rss_mb"] / w0["rss_mb"] - 1.0) * 100
            if w0["rss_mb"] else 0.0, 1,
        ),
        "soak_scale": r["scale"],
        "soak_ckpt_scale_ratio": r["scale_ratio_delta"],
        "soak_scale_ratio_live": r["scale_ratio_live"],
    }


def _stage_trace() -> dict:
    off_s, on_s, overhead_pct, n_spans, admitted = trace_bench(
        np.random.default_rng(11)
    )
    return {
        "trace_metric": (
            f"tracing_admission_overhead ({N_CQ * WL_PER_CQ // 1000}k "
            "pending drained to quiescence through ClusterRuntime bulk "
            "rounds with the distributed tracer on vs off; "
            f"{n_spans} spans recorded, {admitted} admitted, "
            "bit-identical admitted sets asserted, <2% budget asserted "
            "on the tracer's exact self-accounted in-drain time; "
            f"baseline {round(off_s, 3)} s)"
        ),
        "trace_value": round(on_s * 1e3, 3),
        "trace_unit": "ms (full traced drain)",
        "trace_baseline_ms": round(off_s * 1e3, 3),
        "trace_overhead_pct": round(overhead_pct, 2),
        "trace_wall_delta_pct": round((on_s / max(off_s, 1e-9) - 1) * 100, 2),
        "trace_spans": n_spans,
    }


def _stage_failover() -> dict:
    steady, outage, recovered, div_pct, admitted, failovers = failover_bench(
        np.random.default_rng(11)
    )
    return {
        "failover_metric": (
            "solver_failover_cycle_latency (16-CQ interactive cycles: "
            "steady device path vs. injected device outage [circuit "
            "open, host-mirror authority] vs. after half-open re-probe "
            f"recovery; {admitted} admitted across the run, "
            f"{failovers} failovers, decisions == host-only run "
            "asserted)"
        ),
        "failover_value": round(outage, 3),
        "failover_unit": "ms/cycle (during outage)",
        "failover_steady_ms_per_cycle": round(steady, 3),
        "failover_recovered_ms_per_cycle": round(recovered, 3),
        "failover_divergence_overhead_pct": round(div_pct, 1),
    }


def _stage_federation() -> dict:
    fanout_ms, admissions_per_s, mirrored, admitted = federation_bench(
        np.random.default_rng(12)
    )
    # fan-out scaling capture: the REAL dispatcher + global rescore
    # loop at N in-process workers (`bench.py --federation N`; default
    # 50 — the ROADMAP's 50+ floor)
    from kueue_tpu.perf.multikueue import run_federation_scale

    n = int(os.environ.get("KUEUE_BENCH_FED_WORKERS", "50"))
    _stage(f"federation: {n}-worker fan-out scale capture")
    scale = run_federation_scale(n_workers=n)
    assert scale.admitted == scale.total, (
        f"scale run admitted {scale.admitted}/{scale.total}"
    )
    return {
        "federation_metric": (
            "federation_dispatch_fanout_latency (3 in-process worker "
            "control planes behind the FederationDispatcher, 120-deep "
            f"seeded backlog: {mirrored} copies mirrored in one pass; "
            f"{admitted} admitted exactly once across the federation, "
            "federated admitted set == best single-cluster reference "
            "asserted, per-worker invariants clean)"
        ),
        "federation_value": round(fanout_ms, 3),
        "federation_unit": "ms (fan-out pass)",
        "federation_admissions_per_s": round(admissions_per_s, 1),
        "federation_scale_detail": (
            f"{scale.n_workers} workers x {scale.total} workloads "
            f"through the real dispatcher (fanout 1, heterogeneous "
            f"capacity): all admitted exactly once in {scale.passes} "
            f"passes / {scale.wall_s:.1f}s wall; first full fan-out "
            f"pass {scale.fanout_pass_ms:.0f} ms; {scale.rescore_passes} "
            f"global rescores (scoring {scale.rescore_ms_per_cycle:.1f} "
            f"ms/cycle, aggregation {scale.aggregate_ms_per_cycle:.0f} "
            f"ms/cycle), {scale.rebalances} rebalances, "
            f"{scale.retractions_acked} retractions acked"
        ),
        "federation_workers": scale.n_workers,
        "federation_dispatches_per_s": round(scale.dispatches_per_s, 1),
        "federation_rescore_ms": round(scale.rescore_ms_per_cycle, 2),
        "federation_rebalances": scale.rebalances,
    }


def _stage_federation_churn() -> dict:
    joins, drains, p95_ms, n_readmit, admitted = federation_churn_bench(
        np.random.default_rng(18)
    )
    return {
        "federation_churn_metric": (
            "federation_membership_churn_readmit_latency (live "
            "federation under a 90-deep backlog; per round one worker "
            "joins at runtime and one loaded worker is drain-ahead "
            f"removed: {joins} joins / {drains} drains, {n_readmit} "
            f"deposed placements readmitted on survivors, {admitted} "
            "admitted exactly once throughout, per-plane invariants "
            "clean every round)"
        ),
        "federation_churn_value": round(p95_ms, 3),
        "federation_churn_unit": "ms (drain-to-readmit p95)",
        "federation_churn_joins": joins,
        "federation_churn_drains": drains,
        "federation_churn_readmit_p95_ms": round(p95_ms, 3),
    }


def _stage_grayfail() -> dict:
    fixed, adaptive, ref = grayfail_bench(np.random.default_rng(20))
    speedup = (
        adaptive["admissions_per_s"] / fixed["admissions_per_s"]
        if fixed["admissions_per_s"]
        else 0.0
    )
    return {
        "grayfail_metric": (
            "grayfail_adaptive_dispatch_p95 (12-worker federation, one "
            "limping worker answering at 0.99x the per-call deadline; "
            "same seeded 180-deep backlog run fixed-timeout vs "
            "adaptive+hedged: fleet-wide dispatch p95 "
            f"{fixed['dispatch_p95_ms']:.0f}ms -> "
            f"{adaptive['dispatch_p95_ms']:.0f}ms, admissions/sim-s "
            f"{fixed['admissions_per_s']:.2f} -> "
            f"{adaptive['admissions_per_s']:.2f} ({speedup:.1f}x), "
            f"hedge rate {adaptive['hedge_rate']:.4f} <= 0.05 budget, "
            "admitted sets bit-identical to the healthy-fleet "
            "reference in both phases)"
        ),
        "grayfail_value": round(adaptive["dispatch_p95_ms"], 3),
        "grayfail_unit": "ms (dispatch p95, adaptive+hedged)",
        "grayfail_fixed_p95_ms": round(fixed["dispatch_p95_ms"], 3),
        "grayfail_adaptive_p95_ms": round(adaptive["dispatch_p95_ms"], 3),
        "grayfail_fixed_admissions_per_s": round(
            fixed["admissions_per_s"], 3
        ),
        "grayfail_adaptive_admissions_per_s": round(
            adaptive["admissions_per_s"], 3
        ),
        "grayfail_speedup": round(speedup, 2),
        "grayfail_hedge_rate": round(adaptive["hedge_rate"], 4),
    }


def sharded_drain_bench():
    """1-device vs mesh A/B on the 50k plain drain: the same backlog
    (headline seed) solved through ``run_drain`` single-device and
    under the full local mesh, admitted/parked/cycle decisions asserted
    bit-for-bit equal via the pipeline's outcome signature. Returns
    (t_1dev_s, t_mesh_s, cycles, n_admitted, n_devices)."""
    import time

    import jax

    from kueue_tpu.core.drain import run_drain
    from kueue_tpu.core.pipeline import outcome_signature
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.parallel import make_mesh

    n_dev = len(jax.devices())
    assert n_dev >= 2, (
        f"--sharded needs >=2 devices, have {n_dev} (on CPU the driver "
        "forces 8 virtual devices via "
        "--xla_force_host_platform_device_count)"
    )
    mesh = make_mesh(n_dev)
    rng = np.random.default_rng(0)  # the headline seed: same backlog
    cache, _mgr = build_cluster(rng)
    pending = build_backlog(rng)

    def run(mesh_, label):
        _stage(f"sharded: {label} warmup (compile)")
        run_drain(
            take_snapshot(cache), pending, cache.flavors, max_cells=3,
            mesh=mesh_,
        )
        _stage(f"sharded: {label} measured")
        times = []
        for _ in range(3):
            snapshot = take_snapshot(cache)
            t0 = time.perf_counter()
            out = run_drain(
                snapshot, pending, cache.flavors, max_cells=3, mesh=mesh_
            )
            times.append(time.perf_counter() - t0)
        _note_times(f"sharded_{label}", [t / out.cycles for t in times])
        return float(np.median(times)), out

    t1, out1 = run(None, "1-device")
    tm, outm = run(mesh, f"{n_dev}-device mesh")
    assert outcome_signature(out1) == outcome_signature(outm), (
        "sharded drain changed decisions"
    )
    assert out1.admitted and out1.cycles > 0
    return t1, tm, out1.cycles, len(out1.admitted), n_dev


def _stage_sharded() -> dict:
    t1, tm, cycles, admitted, n_dev = sharded_drain_bench()
    # contended drain A/B: same seed -> identical env; decisions
    # asserted equal across 1-device and mesh
    _stage("sharded: contended 1-device")
    c1_ms, c_cycles, c_adm, c_evi, sig1 = contended_drain_bench(
        np.random.default_rng(1)
    )
    from kueue_tpu.parallel import make_mesh

    _stage(f"sharded: contended {n_dev}-device mesh")
    cm_ms, _, _, _, sigm = contended_drain_bench(
        np.random.default_rng(1), mesh=make_mesh(n_dev)
    )
    assert sig1 == sigm, "sharded contended drain changed decisions"
    ms_1dev = t1 * 1e3 / cycles
    ms_mesh = tm * 1e3 / cycles
    from kueue_tpu.parallel.harness import last_panel_schedule

    return {
        "sharded_metric": (
            f"sharded_drain_cycle_latency ({N_CQ * WL_PER_CQ // 1000}k "
            f"pending x {N_CQ} CQs drained under a wl={n_dev} device "
            f"mesh vs 1 device, admitted sets asserted bit-for-bit "
            f"equal, {cycles} cycles, {admitted} admitted; plus the "
            f"contended reclaim drain A/B [{c_cycles} cycles, {c_adm} "
            f"admitted, {c_evi} preempted, decisions equal])"
        ),
        "sharded_value": round(ms_mesh, 3),
        "sharded_unit": "ms/cycle (mesh)",
        "sharded_1dev_ms_per_cycle": round(ms_1dev, 3),
        "sharded_speedup": round(ms_1dev / max(ms_mesh, 1e-9), 2),
        "sharded_n_devices": n_dev,
        "sharded_vs_baseline": round(BASELINE_MS / ms_mesh, 2),
        "sharded_spread_ms": _spread_of(f"sharded_{n_dev}-device mesh"),
        "sharded_1dev_spread_ms": _spread_of("sharded_1-device"),
        "contended_sharded_ms_per_cycle": round(cm_ms, 3),
        "contended_1dev_ms_per_cycle": round(c1_ms, 3),
        "contended_sharded_speedup": round(c1_ms / max(cm_ms, 1e-9), 2),
        # the probe-gated narrow-panel schedule the mesh ran under
        "sharded_panel_schedule": last_panel_schedule() or None,
    }


def _stage_tas_drain() -> dict:
    td_ms, td_cycles, td_admitted, td_pending = tas_drain_bench(
        np.random.default_rng(6)
    )
    return {
        "tas_drain_metric": (
            f"tas_drain ({td_pending // 1000}k mixed-mode gangs "
            "(Required/Preferred/Unconstrained) over 1024 hosts, "
            f"in-kernel placement, {td_cycles} cycles, "
            f"{td_admitted} admitted, zero fallback)"
        ),
        "tas_drain_value": round(td_ms, 3),
        "tas_drain_unit": "ms/cycle",
        "tas_drain_vs_baseline": round(BASELINE_MS / td_ms, 2),
        "tas_drain_spread_ms": _spread_of("tas_drain"),
    }


# stage registry, driver execution order. Each stage is independently
# runnable in its own subprocess (own deterministic seed) so a wedged
# TPU tunnel mid-bench loses ONE stage, not the whole record.
STAGES = {
    "headline": _stage_headline,
    "pipeline": _stage_pipeline,
    "megaloop": _stage_megaloop,
    "sharded": _stage_sharded,
    "contended": _stage_contended,
    "tas": _stage_tas,
    "fair": _stage_fair,
    "fair_drain": _stage_fair_drain,
    "fair_preempt_drain": _stage_fair_preempt_drain,
    "tas_drain": _stage_tas_drain,
    "interactive": _stage_interactive,
    "planner": _stage_planner,
    "journal": _stage_journal,
    "soak": _stage_soak,
    "failover": _stage_failover,
    "federation": _stage_federation,
    "federation_churn": _stage_federation_churn,
    "grayfail": _stage_grayfail,
    "serve": _stage_serve,
    "trace": _stage_trace,
    "policy": _stage_policy,
}

# ---- the BENCH_*.json compact-line contract ----
# Stages that can run alone (SINGLE_STAGE_MODES) publish their headline
# through the "<stage>_value"/"<stage>_metric"/"<stage>_unit" triple;
# finalize_headline() promotes the first present one into the top-level
# value/metric/unit so the compact last line ALWAYS carries headline_ms
# + backend. compact_line() then folds the per-stage extras in. Both
# are pure functions over the record dict — tests/test_bench_schema.py
# lints every registered mode against the contract, so a new stage
# cannot silently drift from it.
HEADLINE_FALLBACK_STAGES = (
    "policy",
    "planner",
    "journal",
    "soak",
    "failover",
    "pipeline",
    "megaloop",
    "federation",
    "federation_churn",
    "grayfail",
    "sharded",
    "serve",
    "trace",
)

# record key -> compact-line key (folded in order; a single-stage run
# carries exactly its own extras)
COMPACT_EXTRAS = (
    ("planner_scenarios_per_s", "scenarios_per_s"),
    ("journal_appends_per_s", "appends_per_s"),
    ("soak_rss_mb_last", "rss_mb"),
    ("soak_journal_mb_peak", "journal_mb"),
    ("soak_ckpt_delta_p95_ms", "ckpt_p95_ms"),
    ("soak_ckpt_scale_ratio", "ckpt_scale_ratio"),
    ("failover_divergence_overhead_pct", "divergence_overhead_pct"),
    ("federation_admissions_per_s", "admissions_per_s"),
    ("federation_dispatches_per_s", "dispatches_per_s"),
    ("federation_rescore_ms", "rescore_ms"),
    ("federation_rebalances", "rebalances"),
    ("federation_churn_joins", "joins"),
    ("federation_churn_drains", "drains"),
    ("federation_churn_readmit_p95_ms", "readmit_p95_ms"),
    ("grayfail_adaptive_p95_ms", "grayfail_p95_ms"),
    ("grayfail_speedup", "grayfail_speedup"),
    ("grayfail_hedge_rate", "hedge_rate"),
    ("pipeline_speedup_vs_serial", "pipeline_speedup"),
    ("megaloop_speedup_vs_serial", "megaloop_speedup"),
    ("megaloop_dispatches_per_drain", "dispatches_per_drain"),
    ("sharded_n_devices", "n_devices"),
    ("sharded_speedup", "sharded_speedup"),
    ("serve_admissions_per_s", "admissions_per_s"),
    ("serve_ingest_per_s", "ingest_per_s"),
    ("serve_shed_pct", "shed_pct"),
    ("serve_read_qps", "read_qps"),
    ("serve_max_lag_s", "max_lag_s"),
    ("trace_overhead_pct", "trace_overhead_pct"),
    ("policy_overhead_pct", "policy_overhead_pct"),
    ("policy_makespan_improvement_pct", "makespan_improvement_pct"),
)

# CLI flag -> the stage list it runs (one-stage modes)
SINGLE_STAGE_MODES = {
    "--planner": ["planner"],
    "--journal": ["journal"],
    "--soak": ["soak"],
    "--failover": ["failover"],
    "--pipeline": ["pipeline"],
    "--megaloop": ["megaloop"],
    "--sharded": ["sharded"],
    "--federation": ["federation"],
    "--churn": ["federation_churn"],
    "--grayfail": ["grayfail"],
    "--serve": ["serve"],
    "--trace": ["trace"],
    "--policy": ["policy"],
}


def finalize_headline(record: dict) -> dict:
    """Promote a single-stage run's metric triple to the headline slot
    (no-op when the headline stage ran); guarantee the value/metric/
    unit keys exist even when every stage failed."""
    for name in HEADLINE_FALLBACK_STAGES:
        if "value" in record:
            break
        if f"{name}_value" in record:
            record.setdefault("metric", record.get(f"{name}_metric"))
            record.setdefault("value", record[f"{name}_value"])
            record.setdefault("unit", record.get(f"{name}_unit"))
    if "value" not in record:
        # the HEADLINE stage failed but others succeeded: keep every
        # completed stage's metrics (stage isolation's whole point) and
        # mark the headline fields as missing
        record.setdefault("metric", "full_drain_cycle_latency (stage failed)")
        record.setdefault("value", None)
        record.setdefault("unit", "ms/cycle")
        record.setdefault("vs_baseline", None)
    return record


def compact_line(record: dict) -> dict:
    """The tail-truncation-proof last line: always headline_ms +
    backend, plus whichever per-stage extras the record carries."""
    compact = {
        "headline_ms": record.get("value"),
        "backend": record.get("backend"),
    }
    for src, dst in COMPACT_EXTRAS:
        if src in record:
            compact[dst] = record[src]
    return compact


def payload_main(stage_names=None):
    record = {}
    for name in stage_names or list(STAGES):
        _stage(name)
        record.update(STAGES[name]())
    _stage("done; emitting")
    print(json.dumps(record))


def _run_payload(force_cpu: bool, stage: "str | None" = None, timeout_s=None):
    """Run the benchmark payload (or one stage) in a subprocess with a
    hard timeout.

    Returns (parsed_record | None, error_string | None). A subprocess
    (not a thread) because a wedged TPU runtime blocks in C++ where no
    Python-level timeout can interrupt it.
    """
    env = dict(os.environ)
    cmd = [sys.executable, os.path.abspath(__file__), "--payload"]
    if stage is not None:
        cmd += ["--stage", stage]
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
        cmd.append("--force-cpu")
        if stage == "sharded":
            # the sharded A/B needs >=2 devices: on CPU force 8 virtual
            # ones (the tier-1 test mesh), set before the payload's
            # first JAX import; real accelerators use real devices
            flags = env.get("XLA_FLAGS", "")
            if "--xla_force_host_platform_device_count" not in flags:
                env["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
    timeout_s = timeout_s or PAYLOAD_TIMEOUT_S
    try:
        p = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return None, f"payload timed out after {timeout_s}s"
    if p.returncode != 0:
        tail = (p.stderr or p.stdout or "").strip().splitlines()
        # last line that looks like the actual exception — JAX appends
        # a traceback-filtering NOTICE after the real error, and axon
        # logs INFO/WARN lines; neither names the failure
        noise = (
            "For simplicity, JAX has removed",
            "Set JAX_TRACEBACK_FILTERING",
            "--------------------",
        )
        for line in reversed(tail):
            s = line.strip()
            if not s or any(s.startswith(n) for n in noise):
                continue
            return None, s[:400]
        return None, f"payload rc={p.returncode}"
    for line in reversed((p.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, "payload produced no JSON line"


def _probe_backend():
    """Bounded-timeout probe: is a non-CPU JAX backend importable and
    responsive? Returns (platform | None, error | None). Runs in a
    subprocess so a wedged tunnel cannot hang the driver."""
    code = (
        "import jax\n"
        "d = jax.devices()\n"
        "import jax.numpy as jnp\n"
        "x = (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()\n"
        "print('PLATFORM', d[0].platform, len(d))\n"
    )
    try:
        p = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        return None, f"backend probe hung >{PROBE_TIMEOUT_S}s (tunnel wedged)"
    if p.returncode != 0:
        tail = (p.stderr or "").strip().splitlines()
        return None, (tail[-1][:400] if tail else f"probe rc={p.returncode}")
    for line in (p.stdout or "").splitlines():
        if line.startswith("PLATFORM"):
            platform = line.split()[1]
            if platform == "cpu":
                return None, "probe resolved to the cpu backend (no TPU attached)"
            return platform, None
    return None, "probe printed no platform"


def driver_main(stage_names=None):
    """Stage-isolated wedge-proof driver.

    Each stage runs in its OWN subprocess with its own timeout: a TPU
    tunnel that wedges (or a compile that dies) mid-bench costs one
    stage, and that stage re-runs CPU-forced — the emitted record keeps
    TPU numbers for every stage that finished on hardware. Two
    mechanisms stop a dead tunnel from burning the whole budget: a
    stage TIMEOUT flips the driver to CPU for all remaining stages (a
    wedge never heals mid-run, and killing a client mid-dispatch can
    deepen it), and a global TPU time budget does the same."""
    platform, tpu_error = _probe_backend()
    record: dict = {}
    stage_backend: dict = {}
    errors: dict = {}
    tpu_on = platform is not None
    t_start = time.perf_counter()
    for name in stage_names or list(STAGES):
        if tpu_on and (time.perf_counter() - t_start) > TPU_BUDGET_S:
            tpu_on = False
            errors.setdefault("_budget", f"TPU budget {TPU_BUDGET_S}s spent")
        frag = None
        if tpu_on:
            frag, err = _run_payload(
                force_cpu=False, stage=name, timeout_s=STAGE_TIMEOUT_S
            )
            if frag is None:
                errors[name] = err
                if err and "timed out" in err:
                    # wedged tunnel: stop poking it (a killed client
                    # mid-dispatch makes the wedge worse)
                    tpu_on = False
        if frag is not None:
            stage_backend[name] = "tpu"
        else:
            frag, err2 = _run_payload(
                force_cpu=True, stage=name, timeout_s=STAGE_TIMEOUT_S
            )
            if frag is not None:
                stage_backend[name] = "cpu"
            else:
                stage_backend[name] = "error"
                errors[name] = ((errors.get(name) or "") + " | cpu: " + str(err2))[:400]
        if frag is not None:
            record.update(frag)

    done = [b for b in stage_backend.values() if b in ("tpu", "cpu")]
    if not done:
        # Even total failure must yield one parseable line, never a trace.
        print(
            json.dumps(
                {
                    "metric": "full_drain_cycle_latency",
                    "value": None,
                    "unit": "ms/cycle",
                    "vs_baseline": None,
                    "backend": "error",
                    "tpu_error": tpu_error,
                    "stage_backend": stage_backend,
                    "errors": errors,
                }
            )
        )
        print(json.dumps({"headline_ms": None, "backend": "error"}))
        sys.exit(1)
    finalize_headline(record)
    n_tpu = sum(1 for b in stage_backend.values() if b == "tpu")
    if n_tpu == len(stage_backend):
        record["backend"] = "tpu"
        record["backend_platform"] = platform
    elif n_tpu > 0:
        record["backend"] = f"mixed ({n_tpu}/{len(stage_backend)} stages on tpu)"
        record["backend_platform"] = platform
    else:
        record["backend"] = "cpu-fallback"
    record["stage_backend"] = stage_backend
    if tpu_error or errors:
        record["tpu_error"] = tpu_error or next(iter(errors.values()))
    print(json.dumps(record))
    # compact headline LAST: the BENCH artifact is tail-truncated, so
    # the final line must always carry the essential numbers even when
    # the full record above gets cut
    print(json.dumps(compact_line(record)))


TPU_BUDGET_S = 1800
STAGE_TIMEOUT_S = 600


if __name__ == "__main__":
    if "--payload" in sys.argv:
        if "--force-cpu" in sys.argv:
            import jax

            # The image's sitecustomize pins an experimental TPU platform
            # at interpreter startup, so JAX_PLATFORMS=cpu alone is not
            # enough — force the config back after import.
            jax.config.update("jax_platforms", "cpu")
        stage_names = None
        if "--stage" in sys.argv:
            stage_names = [sys.argv[sys.argv.index("--stage") + 1]]
        payload_main(stage_names)
    else:
        # one-stage modes (--planner, --journal, --failover,
        # --pipeline, --sharded, --federation, --serve): the stage's
        # metric triple becomes the headline (finalize_headline) and
        # its COMPACT_EXTRAS ride the compact last line — e.g. --serve
        # emits {"headline_ms", "backend", "admissions_per_s",
        # "read_qps", "max_lag_s"}. The registry is linted in
        # tests/test_bench_schema.py.
        for flag, stages in SINGLE_STAGE_MODES.items():
            if flag in sys.argv:
                if flag == "--federation":
                    if "--churn" in sys.argv:
                        # `--federation --churn`: run the membership-
                        # churn stage instead of the steady-roster one
                        stages = ["federation_churn"]
                    else:
                        # `--federation N` sizes the fan-out scale
                        # capture (worker count); propagated to the
                        # payload subprocess through the environment
                        i = sys.argv.index(flag)
                        if i + 1 < len(sys.argv) and sys.argv[i + 1].isdigit():
                            os.environ["KUEUE_BENCH_FED_WORKERS"] = (
                                sys.argv[i + 1]
                            )
                elif flag == "--soak":
                    # `--soak N` sizes the churn wall budget (seconds);
                    # propagated to the payload subprocess through the
                    # environment (KUEUE_BENCH_SOAK_LIVE sizes the
                    # scale proof's live counts)
                    i = sys.argv.index(flag)
                    if i + 1 < len(sys.argv) and sys.argv[i + 1].isdigit():
                        os.environ["KUEUE_BENCH_SOAK_S"] = sys.argv[i + 1]
                driver_main(stages)
                break
        else:
            driver_main()
