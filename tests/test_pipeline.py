"""The double-buffered (pipelined) drain loop — core/pipeline.py +
ClusterRuntime._pipelined_bulk_drain.

The load-bearing property: the pipelined loop produces the BIT-FOR-BIT
same admitted set, journal record sequence and audit records as the
serial loop on the same inputs — the speculation is a pure latency
optimization, never a semantic one. The chaos suite extends the
tests/test_guard.py pattern to the two new fault points
(``cycle.prefetch_launched``, ``cycle.commit_pre_apply``): a crash in
either window, followed by journal recovery and a rerun, converges to
the serial loop's admitted set — a prefetched decision is never
shipped stale.
"""

import json

import numpy as np
import pytest

from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.core.guard import SolverGuard
from kueue_tpu.core.scheduler import _LatencyEstimate
from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import ResourceGroup
from kueue_tpu.models.workload import PodSet
from kueue_tpu.storage import Journal, recover
from kueue_tpu.testing import faults
from kueue_tpu.utils.clock import FakeClock

N_CQ = 6
N_WL = 90
THRESHOLD = 16
CHUNK = 2  # tiny chunks -> many rounds -> many prefetch windows


class _OpenGate(_LatencyEstimate):
    """Latency gate pinned open: these tests exercise the drain path
    itself, not the host-vs-drain routing heuristic."""

    @property
    def value(self):
        return None


def _bare_rt(mode="on", chunk=CHUNK):
    rt = ClusterRuntime(
        clock=FakeClock(0.0),
        bulk_drain_threshold=THRESHOLD,
        drain_pipeline=mode,
        pipeline_chunk_cycles=chunk,
        drain_gate=_OpenGate(),
    )
    rt.guard.config.divergence_check_every = 0
    return rt


def build_rt(seed, mode, journal_dir=None, chunk=CHUNK):
    """A seeded plain-scope environment deep enough that the chunked
    loop runs many rounds (per-CQ depth 15, chunk 2)."""
    rt = _bare_rt(mode, chunk)
    journal = None
    if journal_dir is not None:
        journal = Journal(str(journal_dir)).open()
        rt.attach_journal(journal)
    rng = np.random.default_rng(seed)
    rt.add_flavor(ResourceFlavor(name="default"))
    for i in range(N_CQ):
        rt.add_cluster_queue(
            ClusterQueue(
                name=f"cq-{i}",
                cohort=f"c-{i % 2}",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (
                            FlavorQuotas.build(
                                "default",
                                {
                                    "cpu": (
                                        str(int(rng.integers(10, 30))),
                                        "8",
                                        None,
                                    )
                                },
                            ),
                        ),
                    ),
                ),
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{i}", cluster_queue=f"cq-{i}")
        )
    for j in range(N_WL):
        rt.add_workload(
            Workload(
                namespace="ns",
                name=f"w{j}",
                queue_name=f"lq-{j % N_CQ}",
                priority=int(rng.integers(0, 4)) * 10,
                creation_time=float(j),
                pod_sets=(
                    PodSet.build(
                        "main", 1, {"cpu": str(int(rng.integers(1, 6)))}
                    ),
                ),
            )
        )
    return rt, journal


def admitted(rt):
    return frozenset(
        k for k, wl in rt.workloads.items() if wl.has_quota_reservation
    )


def parked(rt):
    return frozenset(
        key
        for pq in rt.queues.cluster_queues.values()
        for key in pq.inadmissible
    )


def journal_sequence(journal_dir):
    j = Journal(str(journal_dir)).open()
    try:
        return [
            (r.type, json.dumps(r.data, sort_keys=True))
            for r in j.records()
        ]
    finally:
        j.close()


def audit_dump(rt):
    # traceId is a per-process random identifier (kueue_tpu/tracing),
    # not part of the decision: strip it before the bit-for-bit compare
    def strip(d):
        d.pop("traceId", None)
        return d

    return {
        key: [strip(r.to_dict()) for r in rt.audit.for_workload(key)]
        for key in rt.audit.keys()
    }


class TestPipelinedEqualsSerial:
    """The bit-for-bit property over seeded traces: decisions, journal
    record sequence and audit trail identical with prefetch on/off.
    Tier-1 keeps 3 deterministic seeds; the wide sweep is @slow
    (tier-1 runtime headroom — the megaloop suite rides the same
    budget)."""

    TIER1_SEEDS = range(3)

    @pytest.mark.parametrize("seed", TIER1_SEEDS)
    def test_decisions_journal_audit_identical(self, tmp_path, seed):
        rt_s, j_s = build_rt(seed, "serial", tmp_path / "s")
        rt_p, j_p = build_rt(seed, "on", tmp_path / "p")
        rt_s.run_until_idle(max_iterations=60)
        rt_p.run_until_idle(max_iterations=60)
        assert admitted(rt_s) == admitted(rt_p)
        assert parked(rt_s) == parked(rt_p)
        assert admitted(rt_p), "vacuous trace: nothing admitted"
        # the pipeline actually engaged and every prefetch resolved
        assert rt_p.pipeline.rounds > 1
        assert rt_p.pipeline.prefetches >= 1
        assert (
            rt_p.pipeline.commits + rt_p.pipeline.discards
            == rt_p.pipeline.prefetches
        )
        assert rt_s.pipeline.prefetches == 0  # serial mode never speculates
        assert not rt_s.check_invariants() and not rt_p.check_invariants()
        j_s.close()
        j_p.close()
        assert journal_sequence(tmp_path / "s") == journal_sequence(
            tmp_path / "p"
        )
        assert audit_dump(rt_s) == audit_dump(rt_p)

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", range(3, 10))
    def test_decisions_journal_audit_identical_wide(self, tmp_path, seed):
        self.test_decisions_journal_audit_identical(tmp_path, seed)

    def test_one_shot_mode_matches_decisions(self, tmp_path):
        # drain_pipeline="off" (the pre-pipeline single dispatch) must
        # agree on the admitted set too — chunking is decision-neutral
        rt_p, _ = build_rt(7, "on")
        rt_o, _ = build_rt(7, "off")
        rt_p.run_until_idle(max_iterations=60)
        rt_o.run_until_idle(max_iterations=60)
        assert admitted(rt_p) == admitted(rt_o)
        assert parked(rt_p) == parked(rt_o)
        assert rt_o.pipeline.rounds == 0  # one-shot path bypasses it

    def test_overlap_accounting(self):
        rt, _ = build_rt(3, "on")
        rt.run_until_idle(max_iterations=60)
        s = rt.pipeline
        assert s.commits >= 1
        assert 0.0 < s.overlap_ratio <= 1.0
        assert s.inflight == 0  # nothing left in flight at quiescence
        d = s.to_dict()
        assert d["rounds"] == s.rounds and "overlapRatio" in d

    def test_prefetch_spans_on_cycle_traces(self):
        rt, _ = build_rt(3, "on")
        rt.run_until_idle(max_iterations=60)
        drains = [
            t for t in rt.scheduler.last_traces if t.resolution == "drain"
        ]
        assert drains
        for t in drains:
            assert "solve" in t.spans and "apply" in t.spans
            assert "prefetch" in t.spans and "commit" in t.spans
        # pipeline metrics mirrored
        reg = rt.metrics.registry
        text = reg.expose() if hasattr(reg, "expose") else ""
        if text:
            assert "kueue_pipeline_overlap_ratio" in text
            assert "kueue_pipeline_prefetch_discards_total" in text
            assert "kueue_pipeline_inflight" in text


class TestConflictDiscard:
    def test_invalidated_speculation_is_discarded_not_shipped(self):
        """Mutating queue state during the apply (a workload deleted
        under the drain's feet) must invalidate the speculative launch:
        the prefetch is discarded, the round re-solves from the real
        snapshot, and the final decisions match the serial loop run
        against the same interference."""

        def run(mode):
            rt, _ = build_rt(5, mode)
            orig = rt._apply_drain_outcome
            state = {"fired": False}

            def interfering_apply(outcome, snapshot):
                res = orig(outcome, snapshot)
                if not state["fired"] and outcome.undecided:
                    # delete one still-undecided workload mid-loop: the
                    # real post-apply backlog no longer matches the
                    # speculated one
                    state["fired"] = True
                    wl, _cq = outcome.undecided[0]
                    rt.delete_workload(wl)
                return res

            rt._apply_drain_outcome = interfering_apply
            rt.run_until_idle(max_iterations=60)
            assert state["fired"], "interference never triggered"
            return rt

        rt_p = run("on")
        rt_s = run("serial")
        assert rt_p.pipeline.discards >= 1
        assert admitted(rt_p) == admitted(rt_s)
        assert not rt_p.check_invariants()


class TestPipelineChaos:
    """Crash-at-every-new-fault-point x occurrence sweep (the
    tests/test_guard.py chaos pattern): recovery from the journal plus
    a rerun converges to the fault-free serial admitted set."""

    POINTS = ("cycle.prefetch_launched", "cycle.commit_pre_apply")

    @pytest.mark.parametrize("point", POINTS)
    @pytest.mark.parametrize("occurrence", [0, 1, 2])
    def test_crash_recover_converge(self, tmp_path, point, occurrence):
        ref, j_ref = build_rt(0, "serial", tmp_path / "ref")
        ref.run_until_idle(max_iterations=60)
        ref_admitted = admitted(ref)
        j_ref.close()

        rt, j = build_rt(0, "on", tmp_path / "j")
        faults.arm(point, "crash", skip=occurrence)
        crashed = False
        try:
            rt.run_until_idle(max_iterations=60)
        except faults.InjectedCrash:
            crashed = True
        finally:
            faults.reset()
        j.close()
        if not crashed:
            pytest.fail(f"{point} occurrence {occurrence} never fired")

        # recovery: replay the journal into a bare runtime, then finish
        rt2 = _bare_rt("on")
        res = recover(None, str(tmp_path / "j"), runtime=rt2, strict=True)
        rt2.attach_journal(res.journal)
        rt2.run_until_idle(max_iterations=60)
        assert admitted(rt2) == ref_admitted
        assert parked(rt2) == parked(ref)
        assert not rt2.check_invariants()

    def test_points_registered(self):
        for p in self.POINTS:
            assert p in faults.FAULT_POINTS


class TestGuardCoversPrefetch:
    def test_async_deadline_counts_against_breaker(self):
        """A prefetched launch that answers past the device deadline is
        discarded and strikes the breaker — the deadline window covers
        launch -> fetch, not just the blocking call."""
        clock = FakeClock(0.0)
        guard = SolverGuard(clock=clock)
        guard.config.device_deadline_s = 5.0
        launch = guard.device_launch(lambda: "handle", label="prefetch")
        clock.advance(10.0)  # the apply "took too long"; fetch is late
        out = guard.device_join(launch, lambda h: h + ":fetched")
        assert out.result is None
        assert guard.breaker.consecutive_failures == 1

    def test_async_within_deadline_succeeds(self):
        clock = FakeClock(0.0)
        guard = SolverGuard(clock=clock)
        launch = guard.device_launch(lambda: 41, label="prefetch")
        clock.advance(1.0)
        out = guard.device_join(launch, lambda h: h + 1)
        assert out.result == 42
        assert guard.device_solves == 1

    def test_launch_raise_contained(self):
        guard = SolverGuard(clock=FakeClock(0.0))

        def boom():
            raise RuntimeError("bad dispatch")

        launch = guard.device_launch(boom, label="prefetch")
        assert launch.failed
        out = guard.device_join(launch, lambda h: h)
        assert out.result is None
        assert guard.failovers == 1

    def test_drain_divergence_quarantines(self):
        guard = SolverGuard(clock=FakeClock(0.0))
        events = []
        guard.record_event = lambda reason, msg: events.append(reason)
        host = guard.check_drain_divergence(
            {"admitted": ["a"]},
            lambda: ("HOST_OUTCOME", {"admitted": ["b"]}),
            heads=3,
        )
        assert host == "HOST_OUTCOME"
        assert guard.breaker.quarantined
        assert guard.divergences == 1
        assert "SolverDiverged" in events
        assert guard.last_divergence["surface"] == "drain-prefetch"

    def test_drain_divergence_agreement_is_free(self):
        guard = SolverGuard(clock=FakeClock(0.0))
        sig = {"admitted": ["a"]}
        assert (
            guard.check_drain_divergence(sig, lambda: (None, dict(sig)), 1)
            is None
        )
        assert not guard.breaker.quarantined

    def test_sampling_schedule(self):
        guard = SolverGuard(clock=FakeClock(0.0))
        guard.config.divergence_check_every = 4
        hits = [n for n in range(1, 13) if guard.should_sample_drain(n)]
        assert hits == [4, 8, 12]
        guard.config.divergence_check_every = 0
        assert not guard.should_sample_drain(4)

    def test_sampled_rounds_verified_in_loop(self):
        """K=1: every committed prefetch re-solves on the numpy mirror;
        agreement keeps the device path closed and decisions stand."""
        rt, _ = build_rt(2, "on")
        rt.guard.config.divergence_check_every = 1
        rt.run_until_idle(max_iterations=60)
        assert rt.pipeline.commits >= 1
        assert rt.guard.divergence_checks >= 1
        assert rt.guard.divergences == 0
        assert not rt.guard.breaker.quarantined
        ref, _ = build_rt(2, "serial")
        ref.run_until_idle(max_iterations=60)
        assert admitted(rt) == admitted(ref)


class TestPipelineStatsLocking:
    """kueuelint lock-discipline satellite: PipelineStats is written by
    the drain thread and rendered by request threads, so every
    mutation goes through a locked ``note_*`` method and ``to_dict``
    snapshots atomically."""

    def test_note_api_totals(self):
        from kueue_tpu.core.pipeline import PipelineStats

        st = PipelineStats()
        st.note_solve(0.5)
        st.note_prefetch()
        st.note_apply(1.0, overlapped=True)
        st.note_apply(1.0, overlapped=False)
        st.note_commit()
        st.note_discard()
        st.set_inflight(1)
        d = st.to_dict()
        assert d["rounds"] == 2 and d["prefetches"] == 1
        assert d["commits"] == 1 and d["discards"] == 1
        assert d["inflight"] == 1
        assert d["overlapRatio"] == 0.5
        assert st.overlap_ratio == 0.5

    def test_to_dict_never_tears_mid_round(self):
        """apply_s and overlapped_apply_s move together inside one
        note_apply: a concurrent to_dict must never observe the ratio
        above 1.0 (the torn state a field-at-a-time writer exposed)."""
        import threading

        from kueue_tpu.core.pipeline import PipelineStats

        st = PipelineStats()
        errors = []
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                d = st.to_dict()
                if d["overlapRatio"] > 1.0:
                    errors.append(d)

        t = threading.Thread(target=reader)
        t.start()
        try:
            for _ in range(3000):
                st.note_apply(1e-4, overlapped=True)
        finally:
            stop.set()
            t.join()
        assert not errors, errors[:3]
