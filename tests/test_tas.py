"""Topology-Aware Scheduling tests.

Behavior mirrored from pkg/cache/tas_flavor_snapshot_test.go scenarios:
two-phase fit (bottom-up counts, level search, minimize-domains),
required/preferred/unconstrained modes, BestFit vs LeastFree profiles,
taint filtering, hostname-lowest assignments, multi-podset assumed
usage, and the scheduler integration path.
"""

import numpy as np
import pytest

from kueue_tpu import features
from kueue_tpu.models import ClusterQueue, LocalQueue, ResourceFlavor, Workload
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.resource_flavor import Taint, Toleration
from kueue_tpu.models.topology import Topology, TopologyLevel
from kueue_tpu.models.workload import PodSet, PodSetTopologyRequest
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.queue_manager import QueueManager
from kueue_tpu.core.scheduler import Scheduler
from kueue_tpu.tas import Node, TASCache, TASFlavorSnapshot, TASManager, TASPodSetRequest
from kueue_tpu.utils.clock import Clock

BLOCK, RACK, HOST = "cloud/block", "cloud/rack", "kubernetes.io/hostname"


def make_snapshot(levels=(BLOCK, RACK, HOST), nodes=None, tolerations=()):
    snap = TASFlavorSnapshot("default", levels, tolerations=tolerations)
    for labels, alloc, *rest in nodes or []:
        taints = rest[0] if rest else ()
        snap.add_node(labels, alloc, taints)
    snap.freeze()
    return snap


def node(block, rack, host, cpu=4, pods=110):
    return (
        {BLOCK: block, RACK: rack, HOST: host},
        {"cpu": cpu * 1000, "memory": 16 << 30, "pods": pods},
    )


def req(count, cpu=1000, mode="Required", level=RACK, name="main", implied=False):
    tr = None
    if mode is not None:
        tr = PodSetTopologyRequest(
            mode=mode, level=None if mode == "Unconstrained" else level
        )
    return TASPodSetRequest(
        podset_name=name,
        count=count,
        single_pod_requests={"cpu": cpu},
        topology_request=tr,
        implied=implied,
    )


DEFAULT_NODES = [
    node("b1", "r1", "h1"),
    node("b1", "r1", "h2"),
    node("b1", "r2", "h3"),
    node("b2", "r3", "h4"),
    node("b2", "r3", "h5"),
    node("b2", "r3", "h6"),
]


class TestFindTopologyAssignment:
    def test_required_rack_fits(self):
        snap = make_snapshot(nodes=DEFAULT_NODES)
        ta, reason = snap.find_topology_assignment(req(8, mode="Required"), {})
        assert reason == ""
        # r1 has 2 hosts x 4cpu = 8 pods of 1cpu; BestFit picks the
        # smallest fitting rack: r1 (8) over r3 (12)
        assert ta.levels == (HOST,)
        assert sorted(d.values[0] for d in ta.domains) == ["h1", "h2"]
        assert sum(d.count for d in ta.domains) == 8

    def test_required_rack_no_fit(self):
        snap = make_snapshot(nodes=DEFAULT_NODES)
        ta, reason = snap.find_topology_assignment(req(13, mode="Required"), {})
        assert ta is None
        assert "allows to fit only 12 out of 13" in reason

    def test_required_block_fits_two_racks(self):
        snap = make_snapshot(nodes=DEFAULT_NODES)
        ta, reason = snap.find_topology_assignment(
            req(12, mode="Required", level=BLOCK), {}
        )
        assert reason == ""
        # b1 and b2 tie at 12; the tie-break is level-values order -> b1,
        # whose racks r1 (8) + r2 (4) are consumed largest-first
        assert sorted(d.values[0] for d in ta.domains) == ["h1", "h2", "h3"]

    def test_preferred_falls_back_up_a_level(self):
        snap = make_snapshot(nodes=DEFAULT_NODES)
        # no rack fits 13, but block b2 can't either (12); falls to
        # multi-domain at block level (b1=12 + b2=12 >= 13)
        ta, reason = snap.find_topology_assignment(
            req(16, mode="Preferred", level=RACK), {}
        )
        assert reason == ""
        assert sum(d.count for d in ta.domains) == 16

    def test_preferred_too_big_fails(self):
        snap = make_snapshot(nodes=DEFAULT_NODES)
        ta, reason = snap.find_topology_assignment(
            req(25, mode="Preferred", level=RACK), {}
        )
        assert ta is None
        assert "allows to fit only 24 out of 25" in reason

    def test_unconstrained_picks_hosts_directly(self):
        snap = make_snapshot(nodes=DEFAULT_NODES)
        ta, reason = snap.find_topology_assignment(req(2, mode="Unconstrained"), {})
        assert reason == ""
        assert sum(d.count for d in ta.domains) == 2

    def test_best_fit_prefers_smallest_fitting_domain(self):
        snap = make_snapshot(
            nodes=[node("b1", "r1", "h1", cpu=16), node("b1", "r2", "h2", cpu=4)]
        )
        ta, reason = snap.find_topology_assignment(req(3, mode="Required"), {})
        assert reason == ""
        # r2 fits exactly-ish (4 >= 3) and is smaller than r1 (16)
        assert ta.domains[0].values == ("h2",)

    def test_least_free_profile(self):
        with features.override("TASProfileLeastFreeCapacity", True):
            snap = make_snapshot(
                nodes=[node("b1", "r1", "h1", cpu=16), node("b1", "r2", "h2", cpu=4)]
            )
            ta, reason = snap.find_topology_assignment(req(3, mode="Required"), {})
            assert reason == ""
            assert ta.domains[0].values == ("h2",)
            # least-free also changes multi-domain packing order
            ta2, _ = snap.find_topology_assignment(
                req(18, mode="Required", level=BLOCK), {}
            )
            counts = {d.values[0]: d.count for d in ta2.domains}
            assert counts["h2"] == 4  # least-free host exhausted first

    def test_most_free_profile_takes_biggest(self):
        with features.override("TASProfileMostFreeCapacity", True):
            snap = make_snapshot(
                nodes=[node("b1", "r1", "h1", cpu=16), node("b1", "r2", "h2", cpu=4)]
            )
            ta, reason = snap.find_topology_assignment(req(3, mode="Required"), {})
            assert reason == ""
            assert ta.domains[0].values == ("h1",)

    def test_taint_excludes_node(self):
        taint = Taint(key="gpu", value="true", effect="NoSchedule")
        nodes = [
            node("b1", "r1", "h1") + ((taint,),),
            node("b1", "r1", "h2"),
        ]
        snap = make_snapshot(nodes=nodes)
        ta, reason = snap.find_topology_assignment(req(8, mode="Required"), {})
        assert ta is None  # only h2 usable -> 4 pods max
        r = req(8, mode="Required")
        r.tolerations = (Toleration(key="gpu", operator="Exists"),)
        ta, reason = snap.find_topology_assignment(r, {})
        assert reason == ""

    def test_hostname_lowest_level_emits_host_only_values(self):
        snap = make_snapshot(nodes=DEFAULT_NODES)
        ta, _ = snap.find_topology_assignment(req(1, mode="Required"), {})
        assert ta.levels == (HOST,)
        assert all(len(d.values) == 1 for d in ta.domains)

    def test_non_hostname_lowest_emits_full_values(self):
        snap = make_snapshot(
            levels=(BLOCK, RACK),
            nodes=[node("b1", "r1", "hX"), node("b1", "r2", "hY")],
        )
        ta, reason = snap.find_topology_assignment(
            req(4, mode="Required", level=RACK), {}
        )
        assert reason == ""
        assert ta.levels == (BLOCK, RACK)
        assert ta.domains[0].values == ("b1", "r1") or ta.domains[0].values == ("b1", "r2")

    def test_pods_capacity_limits(self):
        snap = make_snapshot(nodes=[node("b1", "r1", "h1", cpu=1000, pods=3)])
        ta, reason = snap.find_topology_assignment(req(4, mode="Required"), {})
        assert ta is None
        ta, reason = snap.find_topology_assignment(req(3, mode="Required"), {})
        assert reason == ""

    def test_multi_podset_assumed_usage(self):
        snap = make_snapshot(nodes=[node("b1", "r1", "h1", cpu=8)])
        res = snap.find_topology_assignments(
            [req(4, name="a"), req(4, name="b")]
        )
        assert res.failure_reason == ""
        assert set(res.assignments) == {"a", "b"}
        # a third podset cannot fit: 8 cpus consumed
        res = snap.find_topology_assignments(
            [req(4, name="a"), req(4, name="b"), req(1, name="c")]
        )
        assert res.failed_podset == "c"

    def test_simulate_empty_ignores_tas_usage(self):
        snap = make_snapshot(nodes=[node("b1", "r1", "h1", cpu=4)])
        snap.add_tas_usage("h1", {"cpu": 4000}, 4)
        ta, reason = snap.find_topology_assignment(req(4, mode="Required"), {})
        assert ta is None
        ta, reason = snap.find_topology_assignment(
            req(4, mode="Required"), {}, simulate_empty=True
        )
        assert reason == ""

    def test_missing_level_reported(self):
        snap = make_snapshot(nodes=DEFAULT_NODES)
        r = req(1, mode="Required", level="no/such-level")
        ta, reason = snap.find_topology_assignment(r, {})
        assert "no requested topology level" in reason

    def test_non_tas_usage_reduces_capacity(self):
        snap = TASFlavorSnapshot("default", (BLOCK, RACK, HOST))
        did = snap.add_node(*node("b1", "r1", "h1", cpu=4))
        snap.add_non_tas_usage(did, {"cpu": 2000})
        snap.freeze()
        ta, reason = snap.find_topology_assignment(req(3, mode="Required"), {})
        assert ta is None
        ta, reason = snap.find_topology_assignment(req(2, mode="Required"), {})
        assert reason == ""


class TestKernelParity:
    @pytest.mark.parametrize("seed", range(4))
    def test_leaf_counts_match_host(self, seed):
        from kueue_tpu._jax import jnp
        from kueue_tpu.ops.tas_kernel import fill_in_counts, topology_from_snapshot

        rng = np.random.default_rng(seed)
        nodes = []
        for b in range(2):
            for r in range(3):
                for h in range(rng.integers(1, 4)):
                    nodes.append(
                        node(f"b{b}", f"r{b}-{r}", f"h{b}{r}{h}", cpu=int(rng.integers(1, 9)))
                    )
        snap = make_snapshot(nodes=nodes)
        topo = topology_from_snapshot(snap)

        reqs, assumed, taint_ok, sim = [], [], [], []
        host_counts = []
        n_l = len(snap._leaf_order)
        for _ in range(3):
            cpu = int(rng.integers(500, 3000))
            request = {"cpu": cpu, "pods": 1}
            host_counts.append(
                snap._leaf_counts(request, {}, False, ())
            )
            vec = np.zeros(len(snap._resources), dtype=np.int64)
            for rname, v in request.items():
                vec[snap._resources.index(rname)] = v
            reqs.append(vec)
            assumed.append(np.zeros((n_l, len(snap._resources)), dtype=np.int64))
            taint_ok.append(np.ones(n_l, dtype=bool))
            sim.append(False)

        counts, levels = fill_in_counts(
            topo,
            jnp.asarray(np.stack(reqs)),
            jnp.asarray(np.stack(assumed)),
            jnp.asarray(np.stack(taint_ok)),
            jnp.asarray(np.array(sim)),
        )
        np.testing.assert_array_equal(np.asarray(counts), np.stack(host_counts))
        # per-domain level vectors must equal the host bubble-up states
        # (kernel domain order at level d = sorted level-value prefixes)
        for b, host_leaf in enumerate(host_counts):
            # replay the host bubble-up for this request
            snap.fill_in_counts(
                {"cpu": int(reqs[b][snap._resources.index("cpu")]),
                 "pods": 1},
                {}, False, (),
            )
            for d, lc in enumerate(levels):
                doms = sorted(
                    snap.domains_per_level[d].values(),
                    key=lambda dm: dm.level_values[: d + 1],
                )
                host_states = np.array([dm.state for dm in doms], dtype=np.int64)
                np.testing.assert_array_equal(
                    np.asarray(lc)[b], host_states,
                    err_msg=f"request {b} level {d}",
                )


def build_tas_env(nodes, quota_cpu="24"):
    cache = Cache()
    qm = QueueManager(Clock())
    topo = Topology(
        name="default",
        levels=(TopologyLevel(BLOCK), TopologyLevel(RACK), TopologyLevel(HOST)),
    )
    flavor = ResourceFlavor(name="tas-flavor", topology_name="default")
    tas = TASCache()
    tas.add_or_update_topology(topo)
    cache.add_or_update_topology(topo)
    cache.add_or_update_flavor(flavor)
    tas.add_or_update_flavor(flavor)
    for i, (labels, alloc, *rest) in enumerate(nodes):
        tas.add_or_update_node(
            Node(name=f"n{i}", labels=labels, allocatable=alloc, taints=rest[0] if rest else ())
        )
    cache.tas_cache = tas
    cq = ClusterQueue(
        name="cq",
        namespace_selector={},
        resource_groups=(
            ResourceGroup(
                ("cpu",), (FlavorQuotas.build("tas-flavor", {"cpu": quota_cpu}),)
            ),
        ),
    )
    cache.add_or_update_cluster_queue(cq)
    qm.add_cluster_queue(cq)
    cache.add_or_update_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    qm.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    manager = TASManager(tas, cache.flavors)
    sched = Scheduler(
        queues=qm, cache=cache, clock=Clock(),
        tas_check=manager.check, tas_assign=manager.assign,
    )
    return sched, qm, cache, tas, manager


def tas_workload(name, count, cpu="1", mode="Required", level=RACK, t=0.0):
    tr = PodSetTopologyRequest(mode=mode, level=None if mode == "Unconstrained" else level)
    return Workload(
        namespace="ns", name=name, queue_name="lq", creation_time=t,
        pod_sets=(PodSet.build("main", count, {"cpu": cpu}, topology_request=tr),),
    )


class TestSchedulerIntegration:
    def test_admission_carries_topology_assignment(self):
        sched, qm, cache, tas, _ = build_tas_env(DEFAULT_NODES)
        qm.add_or_update_workload(tas_workload("w1", 8))
        res = sched.schedule()
        assert len(res.admitted) == 1
        adm = res.admitted[0].workload.admission
        ta = adm.pod_set_assignments[0].topology_assignment
        assert ta is not None
        assert sum(d.count for d in ta.domains) == 8

    def test_second_workload_sees_first_usage(self):
        sched, qm, cache, tas, _ = build_tas_env(DEFAULT_NODES)
        qm.add_or_update_workload(tas_workload("w1", 12, t=0.0))  # fills r3
        res = sched.schedule()
        assert [e.workload.name for e in res.admitted] == ["w1"]
        # w2 requires a rack with 8 free: only r1 remains (r3 full)
        qm.add_or_update_workload(tas_workload("w2", 8, t=1.0))
        res = sched.schedule()
        assert [e.workload.name for e in res.admitted] == ["w2"]
        hosts = {
            d.values[0]
            for e in res.admitted
            for d in e.workload.admission.pod_set_assignments[0].topology_assignment.domains
        }
        assert hosts == {"h1", "h2"}

    def test_tas_capacity_exhausted_requeues(self):
        sched, qm, cache, tas, _ = build_tas_env(DEFAULT_NODES, quota_cpu="100")
        qm.add_or_update_workload(tas_workload("w1", 12, t=0.0))
        sched.schedule()
        qm.add_or_update_workload(tas_workload("w2", 12, t=1.0))
        res = sched.schedule()
        assert res.admitted == []
        assert any("fit" in (e.inadmissible_msg or "") for e in res.requeued)

    def test_workload_removal_frees_tas_capacity(self):
        sched, qm, cache, tas, _ = build_tas_env(DEFAULT_NODES, quota_cpu="100")
        wl = tas_workload("w1", 12, t=0.0)
        qm.add_or_update_workload(wl)
        res = sched.schedule()
        admitted_wl = res.admitted[0].workload
        qm.add_or_update_workload(tas_workload("w2", 12, t=1.0))
        assert sched.schedule().admitted == []
        cache.delete_workload(admitted_wl)
        qm.queue_associated_inadmissible_workloads_after("cq")
        res = sched.schedule()
        assert [e.workload.name for e in res.admitted] == ["w2"]

    def test_non_tas_podset_rejected_on_tas_flavor(self):
        sched, qm, cache, tas, manager = build_tas_env(DEFAULT_NODES)
        wl = Workload(
            namespace="ns", name="plain", queue_name="lq", creation_time=0.0,
            pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
        )
        # CQ is TAS-only -> TAS is implied, so this is admitted with an
        # implied assignment at the lowest level
        qm.add_or_update_workload(wl)
        res = sched.schedule()
        assert len(res.admitted) == 1
        ta = res.admitted[0].workload.admission.pod_set_assignments[0].topology_assignment
        assert ta is not None

    def test_check_rejects_topology_request_on_plain_flavor(self):
        _, _, cache, tas, manager = build_tas_env(DEFAULT_NODES)
        plain = ResourceFlavor(name="plain")
        cq = ClusterQueue(
            name="cq2", namespace_selector={},
            resource_groups=(ResourceGroup(("cpu",), (FlavorQuotas.build("plain", {"cpu": "8"}),)),),
        )
        ps = PodSet.build(
            "main", 1, {"cpu": "1"},
            topology_request=PodSetTopologyRequest(mode="Required", level=RACK),
        )
        msg = manager.check(cq, ps, plain)
        assert "does not support TopologyAwareScheduling" in msg


class TestInCycleTASRecheck:
    """Two heads from different CQs sharing a TAS flavor must not be
    admitted in one cycle with overlapping domain assignments
    (reference: ClusterQueueSnapshot.Fits validates TAS usage,
    clusterqueue_snapshot.go:135-149)."""

    def _env_two_cqs(self):
        cache = Cache()
        qm = QueueManager(Clock())
        topo = Topology(
            name="default",
            levels=(TopologyLevel(BLOCK), TopologyLevel(RACK), TopologyLevel(HOST)),
        )
        flavor = ResourceFlavor(name="tas-flavor", topology_name="default")
        tas = TASCache()
        tas.add_or_update_topology(topo)
        cache.add_or_update_topology(topo)
        cache.add_or_update_flavor(flavor)
        tas.add_or_update_flavor(flavor)
        for i, (labels, alloc) in enumerate(DEFAULT_NODES):
            tas.add_or_update_node(Node(name=f"n{i}", labels=labels, allocatable=alloc))
        cache.tas_cache = tas
        for cq_name, lq_name in (("cq-a", "lq-a"), ("cq-b", "lq-b")):
            cq = ClusterQueue(
                name=cq_name,
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",), (FlavorQuotas.build("tas-flavor", {"cpu": "24"}),)
                    ),
                ),
            )
            cache.add_or_update_cluster_queue(cq)
            qm.add_cluster_queue(cq)
            lq = LocalQueue(namespace="ns", name=lq_name, cluster_queue=cq_name)
            cache.add_or_update_local_queue(lq)
            qm.add_local_queue(lq)
        manager = TASManager(tas, cache.flavors)
        sched = Scheduler(
            queues=qm, cache=cache, clock=Clock(),
            tas_check=manager.check, tas_assign=manager.assign,
            tas_fits=manager.fits,
        )
        return sched, qm, cache, tas

    @staticmethod
    def _rack_workload(name, lq_name, t):
        # 12 cpu in one rack: only r3 (h4+h5+h6, 12 cpu) can hold it
        tr = PodSetTopologyRequest(mode="Required", level=RACK)
        return Workload(
            namespace="ns", name=name, queue_name=lq_name, creation_time=t,
            pod_sets=(PodSet.build("main", 12, {"cpu": "1"}, topology_request=tr),),
        )

    def test_overlapping_heads_not_both_admitted(self):
        sched, qm, cache, tas = self._env_two_cqs()
        qm.add_or_update_workload(self._rack_workload("wa", "lq-a", 0.0))
        qm.add_or_update_workload(self._rack_workload("wb", "lq-b", 1.0))
        res = sched.schedule()
        # only one fits in rack r3; the other is skipped this cycle
        assert len(res.admitted) == 1
        assert res.admitted[0].workload.name == "wa"
        skipped = [e for e in res.requeued if e.workload.name == "wb"]
        assert skipped and "no longer fits" in skipped[0].inadmissible_msg.lower()
        # domains are NOT over-subscribed: total charged in r3 <= 12 cpu
        fc = tas.flavors["tas-flavor"]
        total = sum(
            acc.get("cpu", 0) for acc in fc._usage.values()
        )
        assert total == 12000

    def test_non_overlapping_heads_both_admitted(self):
        sched, qm, cache, tas = self._env_two_cqs()
        tr = PodSetTopologyRequest(mode="Required", level=RACK)
        # 8-cpu rack workload -> r1 (h1+h2); 12-cpu rack workload -> r3
        wa = Workload(
            namespace="ns", name="wa", queue_name="lq-a", creation_time=0.0,
            pod_sets=(PodSet.build("main", 12, {"cpu": "1"}, topology_request=tr),),
        )
        wb = Workload(
            namespace="ns", name="wb", queue_name="lq-b", creation_time=1.0,
            pod_sets=(PodSet.build("main", 8, {"cpu": "1"}, topology_request=tr),),
        )
        qm.add_or_update_workload(wa)
        qm.add_or_update_workload(wb)
        res = sched.schedule()
        assert sorted(e.workload.name for e in res.admitted) == ["wa", "wb"]

    def test_skipped_head_admits_next_cycle(self):
        sched, qm, cache, tas = self._env_two_cqs()
        qm.add_or_update_workload(self._rack_workload("wa", "lq-a", 0.0))
        qm.add_or_update_workload(self._rack_workload("wb", "lq-b", 1.0))
        sched.schedule()
        # wa finishes; its TAS usage is released
        wa = next(iter(cache.cluster_queues["cq-a"].workloads.values()))
        cache.delete_workload(wa)
        qm.queue_associated_inadmissible_workloads_after("cq-a")
        qm.queue_associated_inadmissible_workloads_after("cq-b")
        res = sched.schedule()
        assert [e.workload.name for e in res.admitted] == ["wb"]
