"""The device drain as the SERVICE's bulk path.

``ClusterRuntime.run_until_idle`` routes backlogs at/above
``bulk_drain_threshold`` through one ``core/drain`` device dispatch
(``ClusterRuntime.bulk_drain``) and applies the outcome through the
same admission/eviction machinery the cycle loop uses — the reference
runs its scheduler as the leader-elected service
(``pkg/scheduler/scheduler.go:143-154``); here the drain is the bulk
form of that service. Decisions must be IDENTICAL to the pure
cycle-loop runtime on the same inputs.
"""

import numpy as np
import pytest

from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.core.workload_info import make_admission
from kueue_tpu.models import (
    ClusterQueue,
    LocalQueue,
    Preemption,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.constants import (
    PreemptionPolicy,
    ReclaimWithinCohortPolicy,
    WorkloadConditionType,
)
from kueue_tpu.models.workload import PodSet
from kueue_tpu.utils.clock import FakeClock

N_CQ = 8


def build_rt(bulk: bool, preempt: bool = False, threshold: int = 64):
    clock = FakeClock(start=1000.0)
    rt = ClusterRuntime(
        clock=clock, bulk_drain_threshold=threshold if bulk else None
    )
    rt.add_flavor(ResourceFlavor(name="default"))
    for i in range(N_CQ):
        kw = {}
        if preempt:
            # even CQs: pure reclaim targets (never preempt); odd CQs:
            # full classic ladder
            kw["preemption"] = (
                Preemption()
                if i % 2 == 0
                else Preemption(
                    within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
                    reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
                )
            )
        rt.add_cluster_queue(
            ClusterQueue(
                name=f"cq-{i}",
                cohort=f"co-{i // 4}",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (FlavorQuotas.build("default", {"cpu": "16"}),),
                    ),
                ),
                **kw,
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{i}", cluster_queue=f"cq-{i}")
        )
    return rt, clock


def seed_backlog(rt, wl_per_cq=40, seed=0, priority_base=0):
    rng = np.random.default_rng(seed)
    for i in range(N_CQ):
        for w in range(wl_per_cq):
            rt.add_workload(
                Workload(
                    namespace="ns", name=f"w-{i}-{w}", queue_name=f"lq-{i}",
                    priority=priority_base + int(rng.integers(0, 4)) * 10,
                    creation_time=float(i * wl_per_cq + w),
                    pod_sets=(
                        PodSet.build(
                            "main", 1, {"cpu": str(int(rng.integers(1, 6)))}
                        ),
                    ),
                )
            )


def seed_victims(rt, seed=1):
    """Even (never-preempting) CQs saturated ABOVE nominal: 8 x 3 = 24
    cpu against nominal 16 — borrowing from the cohort, reclaim bait."""
    rng = np.random.default_rng(seed)
    for i in range(0, N_CQ, 2):
        for v in range(8):
            wl = Workload(
                namespace="ns", name=f"victim-{i}-{v}",
                queue_name=f"lq-{i}", priority=int(rng.integers(0, 3)) * 5,
                creation_time=float(v),
                pod_sets=(PodSet.build("main", 1, {"cpu": "3"}),),
            )
            wl.admission = make_admission(
                f"cq-{i}", {"main": {"cpu": "default"}}, wl
            )
            wl.set_condition(
                WorkloadConditionType.QUOTA_RESERVED, True,
                reason="QuotaReserved", now=float(v),
            )
            rt.add_workload(wl)


def final_state(rt):
    admitted = {
        k for k, wl in rt.workloads.items() if wl.has_quota_reservation
    }
    evicted = {
        k
        for k, wl in rt.workloads.items()
        if wl.condition_true(WorkloadConditionType.EVICTED)
    }
    parked = {
        key
        for pq in rt.queues.cluster_queues.values()
        for key in pq.inadmissible
    }
    return admitted, evicted, parked


def drain_traces(rt):
    return [t for t in rt.scheduler.last_traces if t.resolution == "drain"]


class TestBulkDrainService:
    def test_plain_backlog_one_dispatch_parity(self):
        rt_b, _ = build_rt(bulk=True)
        seed_backlog(rt_b)
        rt_b.run_until_idle(max_iterations=300)
        traces = drain_traces(rt_b)
        assert traces, "bulk path never dispatched a drain"
        # the whole backlog decided by the drain: the first dispatch
        # saw every representable head
        assert traces[0].heads == N_CQ * 40
        adm_b, ev_b, park_b = final_state(rt_b)
        assert adm_b and park_b and not ev_b

        rt_c, _ = build_rt(bulk=False)
        seed_backlog(rt_c)
        rt_c.run_until_idle(max_iterations=300)
        assert not drain_traces(rt_c)
        assert final_state(rt_c) == (adm_b, ev_b, park_b)

    def test_preempting_backlog_invariants(self):
        """Cross-CQ cohort reclamation through the service bulk path.

        Exact end-state equality with the pure cycle loop is NOT a
        sound assertion under preemption churn: evicted victims requeue
        and may re-admit into capacity freed later, so the final
        admitted set depends on eviction/requeue interleaving — true
        between any two host drivers too (the reference's evictions are
        async SSA writes, preemption.go:232-257). Kernel decision
        parity is asserted against the compressed-eviction oracle in
        tests/test_drain.py; here the service run must satisfy the
        state invariants on BOTH paths."""
        for bulk in (True, False):
            rt, _ = build_rt(bulk=bulk, preempt=True)
            seed_victims(rt)
            seed_backlog(rt, wl_per_cq=20, priority_base=50)
            rt.run_until_idle(max_iterations=300)
            if bulk:
                assert drain_traces(rt), "bulk path never dispatched"
                assert any(t.preempting for t in drain_traces(rt))
            admitted, _evicted, parked = final_state(rt)
            reasons = {
                k: wl.conditions[WorkloadConditionType.PREEMPTED].reason
                for k, wl in rt.workloads.items()
                if wl.conditions.get(WorkloadConditionType.PREEMPTED)
                is not None
                and wl.conditions[WorkloadConditionType.PREEMPTED].status
            }
            assert reasons and set(reasons.values()) <= {
                "InClusterQueue",
                "InCohortReclamation",
                "InCohortReclaimWhileBorrowing",
            }
            # cross-CQ reclaim fired: even CQs never preempt, so any
            # preemption of their victims came from another CQ
            assert any(k.startswith("ns/victim-") for k in reasons)
            # cache consistency: usage == sum of admitted requests
            from kueue_tpu.resources import FlavorResource, requests_from_spec

            fr = FlavorResource("default", "cpu")
            one_cpu = requests_from_spec({"cpu": "1"})["cpu"]
            for i in range(N_CQ):
                cached = rt.cache.cluster_queues[f"cq-{i}"]
                want = sum(
                    psa.resource_usage.get("cpu", 0)
                    for wl in cached.workloads.values()
                    for psa in wl.admission.pod_set_assignments
                )
                got = rt.cache.usage_for(f"cq-{i}").get(fr, 0)
                assert got == want, f"cq-{i}: usage {got} != admitted {want}"
            # no cohort overcommit: each 4-CQ cohort holds <= 64 cpu
            for co in range(2):
                total = sum(
                    rt.cache.usage_for(f"cq-{i}").get(fr, 0)
                    for i in range(co * 4, co * 4 + 4)
                )
                assert total <= 64 * one_cpu, (
                    f"cohort co-{co} overcommitted: {total}"
                )
            # nothing lost: every workload is admitted, evicted-pending,
            # parked, or in a heap
            in_heap = {
                wl.key
                for pq in rt.queues.cluster_queues.values()
                for wl in pq.snapshot_active_sorted()
            }
            for k in rt.workloads:
                assert (
                    k in admitted or k in parked or k in in_heap
                ), f"workload {k} vanished from every surface"

    def test_fair_sharing_backlog_parity(self):
        results = []
        for bulk in (True, False):
            clock = FakeClock(start=1000.0)
            rt = ClusterRuntime(
                clock=clock, fair_sharing=True,
                bulk_drain_threshold=64 if bulk else None,
            )
            rt.add_flavor(ResourceFlavor(name="default"))
            from kueue_tpu.models.cluster_queue import FairSharing

            weights = [500, 1000, 2000]
            for i in range(N_CQ):
                rt.add_cluster_queue(
                    ClusterQueue(
                        name=f"cq-{i}", cohort=f"co-{i // 4}",
                        namespace_selector={},
                        resource_groups=(
                            ResourceGroup(
                                ("cpu",),
                                (FlavorQuotas.build("default", {"cpu": "8"}),),
                            ),
                        ),
                        fair_sharing=FairSharing(
                            weight_milli=weights[i % len(weights)]
                        ),
                    )
                )
                rt.add_local_queue(
                    LocalQueue(
                        namespace="ns", name=f"lq-{i}", cluster_queue=f"cq-{i}"
                    )
                )
            seed_backlog(rt, wl_per_cq=20)
            rt.run_until_idle(max_iterations=300)
            if bulk:
                assert drain_traces(rt), "fair bulk path never dispatched"
            results.append(final_state(rt))
        assert results[0] == results[1]

    def test_fair_preempting_backlog_through_bulk_path(self):
        """Fair cohorts WITH preemption (the production fair config) go
        through run_drain_fair_preempt in ONE dispatch: preempt-capable
        CQs stay in the drain (no wholesale fallback), victims carry
        fair-sharing reasons, and the usual state invariants hold."""
        from kueue_tpu.models.cluster_queue import FairSharing
        from kueue_tpu.resources import FlavorResource

        clock = FakeClock(start=1000.0)
        rt = ClusterRuntime(
            clock=clock, fair_sharing=True, bulk_drain_threshold=64
        )
        rt.add_flavor(ResourceFlavor(name="default"))
        weights = [500, 1000, 2000]
        prem = Preemption(
            within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
            reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
        )
        for i in range(N_CQ):
            rt.add_cluster_queue(
                ClusterQueue(
                    name=f"cq-{i}", cohort=f"co-{i // 4}",
                    namespace_selector={},
                    resource_groups=(
                        ResourceGroup(
                            ("cpu",),
                            (FlavorQuotas.build("default", {"cpu": "16"}),),
                        ),
                    ),
                    fair_sharing=FairSharing(
                        weight_milli=weights[i % len(weights)]
                    ),
                    preemption=prem,
                )
            )
            rt.add_local_queue(
                LocalQueue(
                    namespace="ns", name=f"lq-{i}", cluster_queue=f"cq-{i}"
                )
            )
        seed_victims(rt)
        seed_backlog(rt, wl_per_cq=20, priority_base=50)
        rt.run_until_idle(max_iterations=300)
        traces = drain_traces(rt)
        assert traces, "fair-preempt bulk path never dispatched"
        # preempt-capable fair CQs stayed in the drain: the dispatch saw
        # the whole representable backlog, and preemptions came from it
        assert traces[0].heads == N_CQ * 20
        assert any(t.preempting for t in traces)
        reasons = {
            k: wl.conditions[WorkloadConditionType.PREEMPTED].reason
            for k, wl in rt.workloads.items()
            if wl.conditions.get(WorkloadConditionType.PREEMPTED) is not None
            and wl.conditions[WorkloadConditionType.PREEMPTED].status
        }
        assert reasons and set(reasons.values()) <= {
            "InClusterQueue",
            "InCohortFairSharing",
        }
        # cache consistency: usage == sum of admitted requests
        fr = FlavorResource("default", "cpu")
        for i in range(N_CQ):
            cached = rt.cache.cluster_queues[f"cq-{i}"]
            want = sum(
                psa.resource_usage.get("cpu", 0)
                for wl in cached.workloads.values()
                for psa in wl.admission.pod_set_assignments
            )
            got = rt.cache.usage_for(f"cq-{i}").get(fr, 0)
            assert got == want, f"cq-{i}: usage {got} != admitted {want}"
        admitted, _evicted, parked = final_state(rt)
        in_heap = {
            wl.key
            for pq in rt.queues.cluster_queues.values()
            for wl in pq.snapshot_active_sorted()
        }
        for k in rt.workloads:
            assert (
                k in admitted or k in parked or k in in_heap
            ), f"workload {k} vanished from every surface"

    def test_no_progress_drain_falls_through_to_cycle(self):
        """A drain that decides NOTHING (all heads fell back) must not
        satisfy run_until_idle's iteration — the cycle loop runs and the
        backlog still gets scheduled (regression: an all-fallback drain
        used to break the loop with everything pending)."""
        rt, _ = build_rt(bulk=True, threshold=64)
        seed_backlog(rt, wl_per_cq=20)

        import kueue_tpu.core.drain as drain_mod
        from kueue_tpu.core.drain import DrainOutcome

        orig = drain_mod.run_drain

        def all_fallback_drain(snapshot, pending, flavors, **kw):
            return DrainOutcome(
                admitted=[], parked=[], fallback=list(pending), cycles=0
            )

        # bulk_drain imports run_drain from the module at call time
        drain_mod.run_drain = all_fallback_drain
        try:
            rt.run_until_idle(max_iterations=300)
        finally:
            drain_mod.run_drain = orig
        admitted, _, parked = final_state(rt)
        assert admitted, "cycle loop never ran after a no-progress drain"
        # every workload reached a decision surface
        in_heap = {
            wl.key
            for pq in rt.queues.cluster_queues.values()
            for wl in pq.snapshot_active_sorted()
        }
        for k in rt.workloads:
            assert k in admitted or k in parked or k in in_heap

    def test_gates(self):
        # below threshold: no drain
        rt, _ = build_rt(bulk=True, threshold=10_000)
        seed_backlog(rt)
        rt.run_until_idle(max_iterations=300)
        assert not drain_traces(rt)
        # solver off: no drain
        rt2, _ = build_rt(bulk=True)
        rt2.scheduler.use_solver = False
        seed_backlog(rt2)
        rt2.run_until_idle(max_iterations=300)
        assert not drain_traces(rt2)

    def test_observer_sees_drain_preemptions(self):
        """The first-class cycle hook delivers the bulk drain's
        preemptions (the solve_assign reporting surface)."""
        rt, _ = build_rt(bulk=True, preempt=True)
        seed_victims(rt)
        seed_backlog(rt, wl_per_cq=20, priority_base=50)
        seen = []

        def observe(result):
            for entry in result.preempting:
                for tgt in entry.preemption_targets:
                    seen.append(
                        (entry.workload.key, tgt.workload.workload.key,
                         tgt.reason)
                    )

        rt.scheduler.cycle_observers.append(observe)
        rt.run_until_idle(max_iterations=300)
        assert seen, "observer saw no preemptions from the drain path"
        victims = {v for _, v, _ in seen}
        assert any(v.startswith("ns/victim-") for v in victims)


class TestServerBulkApply:
    # tier-1 runtime headroom (ISSUE 14): 1.5k workloads tier-1 (still
    # well above bulk_drain_threshold, still multi-round pipelined);
    # the original 5k VERDICT-scale run rides @slow below
    N_SRV_CQ = 10
    WL_PER_CQ = 150

    def _objects(self):
        from kueue_tpu import serialization as ser

        rng = np.random.default_rng(7)
        flavors = [ser.flavor_to_dict(ResourceFlavor(name="default"))]
        cqs, lqs, wls = [], [], []
        for i in range(self.N_SRV_CQ):
            cqs.append(
                ser.cq_to_dict(
                    ClusterQueue(
                        name=f"bcq-{i}", cohort=f"bco-{i // 5}",
                        namespace_selector={},
                        resource_groups=(
                            ResourceGroup(
                                ("cpu",),
                                (FlavorQuotas.build("default", {"cpu": "64"}),),
                            ),
                        ),
                    )
                )
            )
            lqs.append(
                ser.lq_to_dict(
                    LocalQueue(
                        namespace="ns", name=f"blq-{i}",
                        cluster_queue=f"bcq-{i}",
                    )
                )
            )
            for w in range(self.WL_PER_CQ):
                wls.append(
                    ser.workload_to_dict(
                        Workload(
                            namespace="ns", name=f"bw-{i}-{w}",
                            queue_name=f"blq-{i}",
                            priority=int(rng.integers(0, 4)) * 10,
                            creation_time=float(i * self.WL_PER_CQ + w),
                            pod_sets=(
                                PodSet.build(
                                    "main", 1,
                                    {"cpu": str(int(rng.integers(1, 6)))},
                                ),
                            ),
                        )
                    )
                )
        return flavors, cqs, lqs, wls

    def test_bulk_apply_drains_in_one_dispatch(self):
        """VERDICT r4 #2's done-criterion, updated for the PR-7
        pipelined loop: a bulk apply (N_SRV_CQ x WL_PER_CQ workloads)
        is decided entirely through DRAIN rounds (asserted through
        /debug/cycles — round 1 sees the whole backlog, every round
        carries the pipeline's solve/apply/prefetch/commit spans),
        with decisions identical to the pure cycle loop on the same
        inputs."""
        import json
        import urllib.request

        from kueue_tpu import serialization as ser
        from kueue_tpu.server import KueueServer

        flavors, cqs, lqs, wls = self._objects()
        srv = KueueServer()
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"

            def post(path, body):
                req = urllib.request.Request(
                    base + path, data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(req) as resp:
                    return json.loads(resp.read())

            post(
                "/apis/kueue/v1beta1/batch",
                {
                    "resourceflavors": flavors,
                    "clusterqueues": cqs,
                    "localqueues": lqs,
                },
            )
            post("/apis/kueue/v1beta1/batch", {"workloads": wls})
            with urllib.request.urlopen(base + "/debug/cycles") as resp:
                cycles = json.loads(resp.read())["cycles"]
            drains = [c for c in cycles if c["resolution"] == "drain"]
            assert drains, "no drain rounds ran"
            # round 1 of the pipelined loop considers the WHOLE backlog;
            # later rounds shrink to the undecided suffix
            assert drains[0]["heads"] == self.N_SRV_CQ * self.WL_PER_CQ
            for d in drains:
                assert "solve" in d["spansMs"] and "apply" in d["spansMs"]
                assert "prefetch" in d["spansMs"] and "commit" in d["spansMs"]
            pipe = srv.runtime.pipeline
            assert pipe.rounds == len(drains)
            # with the default --pipeline on, every multi-round drain
            # overlaps: each non-final round prefetched the next
            if len(drains) > 1:
                assert pipe.prefetches >= len(drains) - 1
                assert pipe.commits + pipe.discards == pipe.prefetches
                assert pipe.commits >= 1 and pipe.overlap_ratio > 0.0
            admitted_srv = {
                k
                for k, wl in srv.runtime.workloads.items()
                if wl.has_quota_reservation
            }
            parked_srv = {
                key
                for pq in srv.runtime.queues.cluster_queues.values()
                for key in pq.inadmissible
            }
        finally:
            srv.stop()

        # pure cycle-loop baseline on identical inputs
        rt = ClusterRuntime(bulk_drain_threshold=None)
        for f in flavors:
            rt.add_flavor(ser.flavor_from_dict(f))
        for c in cqs:
            rt.add_cluster_queue(ser.cq_from_dict(c))
        for l in lqs:
            rt.add_local_queue(ser.lq_from_dict(l))
        for w in wls:
            rt.add_workload(ser.workload_from_dict(w))
        rt.run_until_idle(max_iterations=600)
        admitted_cyc = {
            k for k, wl in rt.workloads.items() if wl.has_quota_reservation
        }
        parked_cyc = {
            key
            for pq in rt.queues.cluster_queues.values()
            for key in pq.inadmissible
        }
        assert admitted_srv == admitted_cyc
        assert parked_srv == parked_cyc


@pytest.mark.slow
class TestServerBulkApplyFullScale(TestServerBulkApply):
    """The original 5k-workload VERDICT r4 #2 scale (same assertions,
    inherited test)."""

    WL_PER_CQ = 500


class TestDrainEvictionAttribution:
    def test_evictor_and_reason(self):
        """run_drain_preempt reports WHO evicted each victim: the
        reclaiming CQ (exact) and the reference condition reason."""
        from kueue_tpu.core.cache import Cache
        from kueue_tpu.core.drain import run_drain_preempt
        from kueue_tpu.core.snapshot import take_snapshot

        cache = Cache()
        cache.add_or_update_flavor(ResourceFlavor(name="default"))
        for name, prem in (
            ("hoard", Preemption()),
            (
                "self",
                Preemption(
                    within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY
                ),
            ),
            (
                "reclaim",
                Preemption(
                    reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY
                ),
            ),
        ):
            cache.add_or_update_cluster_queue(
                ClusterQueue(
                    name=name, cohort="co", namespace_selector={},
                    resource_groups=(
                        ResourceGroup(
                            ("cpu",),
                            (FlavorQuotas.build("default", {"cpu": "4"}),),
                        ),
                    ),
                    preemption=prem,
                )
            )
        # hoard borrows above nominal (reclaim bait for "reclaim")
        for v in range(3):
            wl = Workload(
                namespace="ns", name=f"hv-{v}", queue_name="lq-hoard",
                priority=0, creation_time=float(v),
                pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
            )
            wl.admission = make_admission("hoard", {"main": {"cpu": "default"}}, wl)
            wl.set_condition(
                WorkloadConditionType.QUOTA_RESERVED, True,
                reason="QuotaReserved", now=float(v),
            )
            cache.add_or_update_workload(wl)
        # "self" holds a low-priority workload of its own (within-CQ bait)
        sv = Workload(
            namespace="ns", name="sv", queue_name="lq-self", priority=0,
            pod_sets=(PodSet.build("main", 1, {"cpu": "3"}),),
        )
        sv.admission = make_admission("self", {"main": {"cpu": "default"}}, sv)
        sv.set_condition(
            WorkloadConditionType.QUOTA_RESERVED, True,
            reason="QuotaReserved", now=0.0,
        )
        cache.add_or_update_workload(sv)

        pending = [
            (
                Workload(
                    namespace="ns", name="self-head", queue_name="lq-self",
                    priority=100, creation_time=10.0,
                    pod_sets=(PodSet.build("main", 1, {"cpu": "3"}),),
                ),
                "self",
            ),
            (
                Workload(
                    namespace="ns", name="reclaim-head",
                    queue_name="lq-reclaim", priority=100,
                    creation_time=11.0,
                    pod_sets=(PodSet.build("main", 1, {"cpu": "4"}),),
                ),
                "reclaim",
            ),
        ]
        outcome = run_drain_preempt(
            take_snapshot(cache), pending, cache.flavors
        )
        assert not outcome.fallback and not outcome.truncated
        assert len(outcome.evictions) == len(outcome.preempted)
        by_victim = {ev.victim.name: ev for ev in outcome.evictions}
        assert "sv" in by_victim
        self_ev = by_victim["sv"]
        assert self_ev.by_cq == "self"
        assert self_ev.reason == "InClusterQueue"
        assert self_ev.by_workload is not None
        assert self_ev.by_workload.name == "self-head"
        hoard_evs = [
            ev for name, ev in by_victim.items() if name.startswith("hv-")
        ]
        assert hoard_evs, "no cohort reclaim happened"
        for ev in hoard_evs:
            assert ev.by_cq == "reclaim"
            assert ev.reason == "InCohortReclamation"
            assert ev.by_workload is not None
            assert ev.by_workload.name == "reclaim-head"


class TestTASBulkDrain:
    """Topology-requesting backlogs through the service bulk path: one
    run_drain_tas dispatch, decisions + TAS leaf charges identical to
    the pure cycle loop (tas_flavor_snapshot.go placement semantics at
    drain granularity)."""

    N_TAS_CQ = 4
    WL_PER_CQ = 20

    def _build_rt(self, bulk: bool, threshold: int = 64, fair: bool = False):
        from kueue_tpu.models import Topology
        from kueue_tpu.models.topology import TopologyLevel
        from kueue_tpu.tas import TASCache
        from kueue_tpu.tas.cache import Node

        BLOCK = "cloud.google.com/gce-topology-block"
        RACK = "cloud.google.com/gce-topology-rack"
        HOST = "kubernetes.io/hostname"
        topo = Topology(
            name="default",
            levels=(
                TopologyLevel(BLOCK), TopologyLevel(RACK), TopologyLevel(HOST)
            ),
        )
        tas = TASCache()
        tas.add_or_update_topology(topo)
        flavor = ResourceFlavor(name="tas-flavor", topology_name="default")
        tas.add_or_update_flavor(flavor)
        for b in range(2):
            for r in range(3):
                for h in range(4):
                    tas.add_or_update_node(
                        Node(
                            name=f"n-{b}-{r}-{h}",
                            labels={
                                BLOCK: f"b{b}",
                                RACK: f"b{b}-r{r}",
                                HOST: f"h-{b}-{r}-{h}",
                            },
                            allocatable={"cpu": 8000, "pods": 64},
                        )
                    )
        clock = FakeClock(start=1000.0)
        rt = ClusterRuntime(
            clock=clock,
            tas_cache=tas,
            fair_sharing=fair,
            bulk_drain_threshold=threshold if bulk else None,
        )
        rt.cache.add_or_update_topology(topo)
        rt.add_flavor(flavor)
        for i in range(self.N_TAS_CQ):
            rt.add_cluster_queue(
                ClusterQueue(
                    name=f"tcq-{i}",
                    namespace_selector={},
                    resource_groups=(
                        ResourceGroup(
                            ("cpu",),
                            (FlavorQuotas.build("tas-flavor", {"cpu": "999"}),),
                        ),
                    ),
                )
            )
            rt.add_local_queue(
                LocalQueue(
                    namespace="ns", name=f"tlq-{i}", cluster_queue=f"tcq-{i}"
                )
            )
        return rt, (BLOCK, RACK, HOST)

    def _seed(self, rt, levels, seed=7):
        from kueue_tpu.models.workload import PodSetTopologyRequest

        BLOCK, RACK, HOST = levels
        rng = np.random.default_rng(seed)
        modes = ("Required", "Preferred", "Unconstrained")
        lvls = (BLOCK, RACK, RACK, HOST)
        t = 0.0
        for i in range(self.N_TAS_CQ):
            for w in range(self.WL_PER_CQ):
                t += 1.0
                mode = modes[int(rng.integers(0, 3))]
                tr = PodSetTopologyRequest(
                    mode=mode,
                    level=(
                        None
                        if mode == "Unconstrained"
                        else lvls[int(rng.integers(0, 4))]
                    ),
                )
                rt.add_workload(
                    Workload(
                        namespace="ns", name=f"tw-{i}-{w}",
                        queue_name=f"tlq-{i}",
                        creation_time=t,
                        pod_sets=(
                            PodSet.build(
                                "main",
                                int(rng.integers(1, 9)),
                                {"cpu": str(int(rng.integers(1, 4)))},
                                topology_request=tr,
                            ),
                        ),
                    )
                )

    def _tas_leaf_usage(self, rt):
        snap = rt.cache.tas_cache.flavors["tas-flavor"].snapshot()
        return {
            did: dict(u) for did, u in snap._tas_usage_map.items() if u
        }

    def test_tas_backlog_one_dispatch_parity(self):
        rt_b, levels = self._build_rt(bulk=True)
        self._seed(rt_b, levels)
        rt_b.run_until_idle(max_iterations=300)
        traces = drain_traces(rt_b)
        assert traces, "TAS bulk path never dispatched a drain"
        assert traces[0].heads == self.N_TAS_CQ * self.WL_PER_CQ
        adm_b, ev_b, park_b = final_state(rt_b)
        assert adm_b and not ev_b

        rt_c, levels_c = self._build_rt(bulk=False)
        self._seed(rt_c, levels_c)
        rt_c.run_until_idle(max_iterations=300)
        assert not drain_traces(rt_c)
        assert final_state(rt_c) == (adm_b, ev_b, park_b)
        # every admitted workload carries a real TopologyAssignment and
        # the TAS leaf charges match the cycle loop's exactly
        for key in adm_b:
            psa = rt_b.workloads[key].admission.pod_set_assignments[0]
            assert psa.topology_assignment is not None
            assert sum(d.count for d in psa.topology_assignment.domains) > 0
        assert self._tas_leaf_usage(rt_b) == self._tas_leaf_usage(rt_c)

    def test_mixed_tas_and_plain_backlog(self):
        """Plain quota CQs drain in the SAME run_drain_tas dispatch as
        the TAS queues (non-TAS queues stay in the TAS drain)."""
        rt_b, levels = self._build_rt(bulk=True)
        rt_b.add_flavor(ResourceFlavor(name="plain"))
        rt_b.add_cluster_queue(
            ClusterQueue(
                name="pcq",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",), (FlavorQuotas.build("plain", {"cpu": "40"}),)
                    ),
                ),
            )
        )
        rt_b.add_local_queue(
            LocalQueue(namespace="ns", name="plq", cluster_queue="pcq")
        )
        self._seed(rt_b, levels)
        for w in range(30):
            rt_b.add_workload(
                Workload(
                    namespace="ns", name=f"pw-{w}", queue_name="plq",
                    creation_time=2000.0 + w,
                    pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
                )
            )
        rt_b.run_until_idle(max_iterations=300)
        traces = drain_traces(rt_b)
        assert traces
        assert traces[0].heads == self.N_TAS_CQ * self.WL_PER_CQ + 30

        rt_c, levels_c = self._build_rt(bulk=False)
        rt_c.add_flavor(ResourceFlavor(name="plain"))
        rt_c.add_cluster_queue(
            ClusterQueue(
                name="pcq",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",), (FlavorQuotas.build("plain", {"cpu": "40"}),)
                    ),
                ),
            )
        )
        rt_c.add_local_queue(
            LocalQueue(namespace="ns", name="plq", cluster_queue="pcq")
        )
        self._seed(rt_c, levels_c)
        for w in range(30):
            rt_c.add_workload(
                Workload(
                    namespace="ns", name=f"pw-{w}", queue_name="plq",
                    creation_time=2000.0 + w,
                    pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
                )
            )
        rt_c.run_until_idle(max_iterations=300)
        assert final_state(rt_c) == final_state(rt_b)

    def test_preempting_plain_cq_sends_tas_to_cycle_loop(self):
        """A preempt-capable PLAIN CQ in the backlog forces the preempt
        drain, which cannot carry placement state: TAS heads must fall
        to the cycle loop (not drain unplaced, not block the drain)."""
        rt, levels = self._build_rt(bulk=True, threshold=16)
        rt.add_flavor(ResourceFlavor(name="plain"))
        rt.add_cluster_queue(
            ClusterQueue(
                name="pcq",
                cohort="co",
                namespace_selector={},
                preemption=Preemption(
                    within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
                    reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
                ),
                resource_groups=(
                    ResourceGroup(
                        ("cpu",), (FlavorQuotas.build("plain", {"cpu": "99"}),)
                    ),
                ),
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name="plq", cluster_queue="pcq")
        )
        self._seed(rt, levels)
        for w in range(20):
            rt.add_workload(
                Workload(
                    namespace="ns", name=f"pw-{w}", queue_name="plq",
                    creation_time=2000.0 + w,
                    pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
                )
            )
        rt.run_until_idle(max_iterations=400)
        traces = drain_traces(rt)
        # the drain ran for the plain backlog only
        assert traces and traces[0].heads == 20
        # and the TAS heads still got decided — by the cycle loop
        adm, _, _ = final_state(rt)
        assert any(k.startswith("ns/tw-") for k in adm)
        for key in adm:
            if key.startswith("ns/tw-"):
                psa = rt.workloads[key].admission.pod_set_assignments[0]
                assert psa.topology_assignment is not None

    def test_fair_sharing_sends_tas_to_cycle_loop(self):
        """Fair sharing has no TAS drain scope either: with a
        fair-sharing runtime the TAS heads fall to the cycle loop while
        the plain backlog still drains (fair ordering) — and BOTH
        halves fully admit (capacities are sized to make full admission
        deterministic, so a half that silently decides nothing fails)."""
        from kueue_tpu.models.workload import PodSetTopologyRequest

        rt, levels = self._build_rt(bulk=True, threshold=16, fair=True)
        _, _, HOST = levels
        rt.add_flavor(ResourceFlavor(name="plain"))
        for i in range(2):
            rt.add_cluster_queue(
                ClusterQueue(
                    name=f"fcq-{i}",
                    cohort="fair-co",
                    namespace_selector={},
                    resource_groups=(
                        ResourceGroup(
                            ("cpu",),
                            (FlavorQuotas.build("plain", {"cpu": "30"}),),
                        ),
                    ),
                )
            )
            rt.add_local_queue(
                LocalQueue(
                    namespace="ns", name=f"flq-{i}", cluster_queue=f"fcq-{i}"
                )
            )
        # 10 TAS gangs of 2x1cpu on a 192-cpu topology, quota 999: all
        # must admit; 2x15 plain 2cpu workloads against quota 2x30: all
        # must admit
        for w in range(10):
            rt.add_workload(
                Workload(
                    namespace="ns", name=f"tw-{w}", queue_name="tlq-0",
                    creation_time=float(w),
                    pod_sets=(
                        PodSet.build(
                            "main", 2, {"cpu": "1"},
                            topology_request=PodSetTopologyRequest(
                                mode="Required", level=HOST
                            ),
                        ),
                    ),
                )
            )
        for i in range(2):
            for w in range(15):
                rt.add_workload(
                    Workload(
                        namespace="ns", name=f"fw-{i}-{w}",
                        queue_name=f"flq-{i}",
                        creation_time=100.0 + i * 15 + w,
                        pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
                    )
                )
        rt.run_until_idle(max_iterations=300)
        traces = drain_traces(rt)
        # the fair drain ran over the PLAIN backlog only
        assert traces and traces[0].heads == 30
        adm, _, _ = final_state(rt)
        # the plain fair backlog fully admitted through the drain
        assert all(f"ns/fw-{i}-{w}" in adm for i in range(2) for w in range(15))
        # and every TAS head was still decided — by the cycle loop,
        # with real placements
        tas_admitted = [k for k in adm if k.startswith("ns/tw-")]
        assert len(tas_admitted) == 10
        for key in tas_admitted:
            psa = rt.workloads[key].admission.pod_set_assignments[0]
            assert psa.topology_assignment is not None
            assert sum(d.count for d in psa.topology_assignment.domains) == 2


class TestServerTASBulkApply:
    """The north-star story over the wire: node inventory, topology,
    TAS flavor, queues, and a bulk batch of topology-requesting gangs
    all arrive through the HTTP API, and the backlog is decided by ONE
    TAS drain dispatch (asserted via /debug/cycles) with real
    TopologyAssignments served back."""

    BLOCK = "cloud.google.com/gce-topology-block"
    HOST = "kubernetes.io/hostname"
    N_TCQ = 4
    WL_PER_CQ = 80  # 320 >= the default bulk_drain_threshold of 256

    def test_bulk_tas_apply_one_drain_dispatch(self):
        import json
        import urllib.request

        from kueue_tpu.server import KueueServer

        srv = KueueServer()
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"

            def post(path, body):
                req = urllib.request.Request(
                    base + path, data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(req) as resp:
                    return json.loads(resp.read())

            nodes = [
                {
                    "name": f"n-{b}-{h}",
                    "labels": {
                        self.BLOCK: f"b{b}",
                        self.HOST: f"n-{b}-{h}",
                    },
                    "allocatable": {"cpu": "16", "pods": "64"},
                }
                for b in range(4)
                for h in range(8)
            ]
            cqs, lqs, wls = [], [], []
            rng = np.random.default_rng(11)
            modes = ("Required", "Preferred", "Unconstrained")
            for i in range(self.N_TCQ):
                cqs.append(
                    {
                        "name": f"stcq-{i}",
                        "namespaceSelector": {},
                        "resourceGroups": [
                            {
                                "coveredResources": ["cpu"],
                                "flavors": [
                                    {
                                        "name": "tas-flavor",
                                        "resources": [
                                            {
                                                "name": "cpu",
                                                "nominalQuota": "999",
                                            }
                                        ],
                                    }
                                ],
                            }
                        ],
                    }
                )
                lqs.append(
                    {
                        "namespace": "ns",
                        "name": f"stlq-{i}",
                        "clusterQueue": f"stcq-{i}",
                    }
                )
                for w in range(self.WL_PER_CQ):
                    mode = modes[int(rng.integers(0, 3))]
                    wls.append(
                        {
                            "namespace": "ns",
                            "name": f"stw-{i}-{w}",
                            "queueName": f"stlq-{i}",
                            "creationTime": float(i * self.WL_PER_CQ + w),
                            "podSets": [
                                {
                                    "name": "main",
                                    "count": int(rng.integers(1, 5)),
                                    "requests": {"cpu": "1"},
                                    "topologyRequest": {
                                        "mode": mode,
                                        "level": (
                                            None
                                            if mode == "Unconstrained"
                                            else self.HOST
                                        ),
                                    },
                                }
                            ],
                        }
                    )
            post(
                "/apis/kueue/v1beta1/batch",
                {
                    "topologies": [
                        {"name": "default", "levels": [self.BLOCK, self.HOST]}
                    ],
                    "resourceflavors": [
                        {"name": "tas-flavor", "topologyName": "default"}
                    ],
                    "nodes": nodes,
                    "clusterqueues": cqs,
                    "localqueues": lqs,
                },
            )
            post("/apis/kueue/v1beta1/batch", {"workloads": wls})
            with urllib.request.urlopen(base + "/debug/cycles") as resp:
                cycles = json.loads(resp.read())["cycles"]
            drains = [c for c in cycles if c["resolution"] == "drain"]
            assert len(drains) == 1, (
                f"expected exactly one drain dispatch, got {len(drains)}"
            )
            assert drains[0]["heads"] == self.N_TCQ * self.WL_PER_CQ
            admitted = [
                wl
                for wl in srv.runtime.workloads.values()
                if wl.has_quota_reservation
            ]
            assert admitted
            # every admitted gang carries a real placement, and the
            # modes that REQUIRE a single domain actually got one
            for wl in admitted:
                psa = wl.admission.pod_set_assignments[0]
                ta = psa.topology_assignment
                assert ta is not None
                total = sum(d.count for d in ta.domains)
                assert total == wl.pod_sets[0].count
                if wl.pod_sets[0].topology_request.mode == "Required":
                    assert len(ta.domains) == 1
        finally:
            srv.stop()
