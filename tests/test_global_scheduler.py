"""Global scheduler (kueue_tpu/federation/global_scheduler.py +
federation/aggregate.py + ops/global_kernel.py): batched cross-cluster
rescoring bit-for-bit against its numpy mirror, federation-wide
aggregation through in-process runtimes and the replica feed,
planner-driven rebalancing under hysteresis + fencing, and the chaos
property — exactly-one admission across the ``global.*`` fault points
(crash mid-retraction, stale fence, partitioned worker)."""

import numpy as np
import pytest

from kueue_tpu.admissionchecks.multikueue import MultiKueueCluster
from kueue_tpu.admissionchecks.multikueue_transport import TransportError
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.federation import (
    FederationDispatcher,
    GlobalScheduler,
    collect_global_snapshot,
)
from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import ResourceGroup
from kueue_tpu.models.constants import WorkloadConditionType
from kueue_tpu.models.workload import PodSet
from kueue_tpu.ops.global_kernel import (
    INVALID_KEY,
    MAX_CLUSTERS,
    rescore_pairs,
)
from kueue_tpu.ops.global_np import rescore_np
from kueue_tpu.storage.journal import Journal
from kueue_tpu.storage.recovery import recover
from kueue_tpu.testing import faults
from kueue_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---- kernel <-> mirror parity ----
class TestRescoreKernelParity:
    """Acceptance: the global rescore kernel is bit-for-bit its numpy
    mirror over seeded heterogeneous fleets."""

    def _random_fleet(self, rng):
        w = int(rng.integers(0, 12))
        c = int(rng.integers(1, 9))
        # heterogeneous forecasts: a mix of instant fits, deep queues,
        # horizon-overflow values, plus deliberate TTA ties so the
        # score and rotation tie-breaks engage
        tta = rng.choice(
            [0, 1, 999, 60_000, 600_000, 10**9, 2**40],
            size=(w, c),
        ).astype(np.int64)
        score = rng.integers(-(2**22), 2**22, size=(w, c))
        valid = rng.random((w, c)) < 0.75
        current = rng.integers(-1, c, size=max(w, 1))[:w].astype(np.int32)
        rotation = (
            rng.integers(0, 2**31, size=max(w, 1))[:w] % c
        ).astype(np.int32)
        hysteresis = int(rng.choice([0, 1, 30_000, 600_000]))
        return tta, score, valid, current, rotation, hysteresis

    @pytest.mark.parametrize("seed", range(8))
    def test_kernel_matches_mirror(self, seed):
        rng = np.random.default_rng(seed)
        for _ in range(12):
            args = self._random_fleet(rng)
            dev = rescore_pairs(*args)
            host = rescore_np(*args)
            for d, h, name in zip(dev, host, dev._fields):
                assert np.array_equal(np.asarray(d), np.asarray(h)), (
                    f"seed {seed}: field {name} diverged\n{args}"
                )

    def test_tta_wins_then_score_then_rotation(self):
        tta = np.array([[100, 100, 50]], dtype=np.int64)
        score = np.array([[5, 9, 0]], dtype=np.int64)
        valid = np.ones((1, 3), dtype=bool)
        cur = np.array([0], dtype=np.int32)
        rot = np.array([0], dtype=np.int32)
        res = rescore_np(tta, score, valid, cur, rot, 0)
        assert res.best[0] == 2  # lowest tta wins outright
        # tie on tta: higher score wins
        tta = np.array([[100, 100, 100]], dtype=np.int64)
        res = rescore_np(tta, score, valid, cur, rot, 0)
        assert res.best[0] == 1
        # full tie: the rotated index decides (rotation 2 makes
        # column 2 position 0)
        score = np.zeros((1, 3), dtype=np.int64)
        res = rescore_np(
            tta, score, valid, cur, np.array([2], dtype=np.int32), 0
        )
        assert res.best[0] == 2
        dev = rescore_pairs(
            tta, score, valid, cur, np.array([2], dtype=np.int32), 0
        )
        assert dev.best[0] == 2

    def test_hysteresis_boundary(self):
        # current cluster forecasts 100s, the other 0s: gain 100_000ms
        tta = np.array([[100_000, 0]], dtype=np.int64)
        score = np.zeros((1, 2), dtype=np.int64)
        valid = np.ones((1, 2), dtype=bool)
        cur = np.array([0], dtype=np.int32)
        rot = np.array([0], dtype=np.int32)
        at = rescore_np(tta, score, valid, cur, rot, 100_000)
        assert not at.rebalance[0]  # gain == T: stay
        above = rescore_np(tta, score, valid, cur, rot, 99_999)
        assert above.rebalance[0] and above.gain_ms[0] == 100_000
        dev = rescore_pairs(tta, score, valid, cur, rot, 99_999)
        assert bool(dev.rebalance[0])

    def test_invalid_and_degenerate_shapes(self):
        for fn in (rescore_pairs, rescore_np):
            res = fn(
                np.zeros((0, 3), dtype=np.int64),
                np.zeros((0, 3), dtype=np.int64),
                np.zeros((0, 3), dtype=bool),
                np.zeros(0, dtype=np.int32),
                np.zeros(0, dtype=np.int32),
                0,
            )
            assert res.best.shape == (0,)
            # all-invalid row: best -1, INVALID_KEY, no rebalance
            res = fn(
                np.zeros((1, 2), dtype=np.int64),
                np.zeros((1, 2), dtype=np.int64),
                np.zeros((1, 2), dtype=bool),
                np.array([0], dtype=np.int32),
                np.array([0], dtype=np.int32),
                0,
            )
            assert res.best[0] == -1
            assert res.best_key[0] == INVALID_KEY
            assert not res.rebalance[0]

    def test_unscorable_current_never_rebalances(self):
        # the current placement cannot be forecast (partitioned
        # worker): conservative — no move on one-sided information
        tta = np.array([[0, 0]], dtype=np.int64)
        valid = np.array([[False, True]])
        res = rescore_np(
            tta, np.zeros((1, 2), dtype=np.int64), valid,
            np.array([0], dtype=np.int32),
            np.array([0], dtype=np.int32), 0,
        )
        assert res.best[0] == 1 and not res.rebalance[0]

    def test_cluster_budget_is_enforced(self):
        shape = (1, MAX_CLUSTERS + 1)
        with pytest.raises(ValueError):
            rescore_np(
                np.zeros(shape, dtype=np.int64),
                np.zeros(shape, dtype=np.int64),
                np.ones(shape, dtype=bool),
                np.array([0], dtype=np.int32),
                np.array([0], dtype=np.int32),
                0,
            )


# ---- federation builders ----
def build_worker(clock, cpu="10", journal_path=None):
    rt = ClusterRuntime(clock=clock)
    journal = None
    if journal_path is not None:
        journal = Journal(str(journal_path), fsync_policy="never").open()
        rt.attach_journal(journal)
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",), (FlavorQuotas.build("default", {"cpu": cpu}),)
                ),
            ),
        )
    )
    rt.add_local_queue(
        LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
    )
    return rt, journal


def wl(name, cpu="1", **kw):
    return Workload(
        namespace="ns", name=name, queue_name="lq",
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),), **kw,
    )


def finish(rt, key, clock):
    w = rt.workloads[key]
    w.set_condition(
        WorkloadConditionType.FINISHED, True, "JobFinished", "done",
        now=clock.now(),
    )
    rt.on_workload_finished(w)


def congested_federation(
    tmp_path=None,
    n_workers=2,
    fanout=1,
    hysteresis_s=10.0,
    n_wl=1,
    **gs_kw,
):
    """Every worker saturated by a local hog, ``n_wl`` federated
    workloads parked on their single-target placements — finishing one
    hog is what makes a rescore move them."""
    clock = FakeClock(0.0)
    workers = {}
    clusters = {}
    for i in range(n_workers):
        name = f"w{i + 1}"
        rt, _ = build_worker(clock)
        hog = wl(f"hog-{name}", cpu="10")
        rt.add_workload(hog)
        rt.run_until_idle()
        assert hog.is_admitted
        workers[name] = rt
        clusters[name] = MultiKueueCluster(name=name, runtime=rt)
    mgr = ClusterRuntime(clock=clock)
    journal = None
    if tmp_path is not None:
        journal = Journal(
            str(tmp_path / "mgr-journal"), fsync_policy="never"
        ).open()
        mgr.attach_journal(journal)
    disp = FederationDispatcher(
        mgr, clusters=clusters, drive_inprocess=True, fanout=fanout,
        worker_lost_timeout=1e9, heartbeat_interval_s=1e9,
    )
    gs = GlobalScheduler(
        disp, hysteresis_s=hysteresis_s, rescore_interval_s=0.0, **gs_kw
    )
    fed = []
    for i in range(n_wl):
        w = wl(f"fed-{i}", cpu="4")
        mgr.add_workload(w)
        fed.append(w)
    mgr.run_until_idle()
    for w in fed:
        assert not w.is_admitted  # parked: every worker is full
    return mgr, disp, gs, workers, clock, journal, fed


def drive(mgr, clock, passes=6, advance=10.0):
    for _ in range(passes):
        mgr.run_until_idle()
        clock.advance(advance)
    mgr.run_until_idle()


def assert_converged_once(mgr, workers, keys):
    admitted = {k for k, w in mgr.workloads.items() if w.is_admitted}
    assert admitted == set(keys)
    for key in keys:
        holders = sorted(
            n for n, rt in workers.items() if key in rt.workloads
        )
        assert len(holders) == 1, f"{key}: copies on {holders}"
        assert workers[holders[0]].workloads[key].has_quota_reservation
    assert mgr.check_invariants() == []
    for name, rt in workers.items():
        assert rt.check_invariants() == [], f"worker {name}"


# ---- aggregation ----
class TestAggregation:
    def test_snapshot_standings_capacities_and_forecasts(self):
        mgr, disp, gs, workers, clock, _, fed = congested_federation()
        key = fed[0].key
        cur = disp.states[key].clusters[0]
        other = next(n for n in workers if n != cur)
        finish(workers[other], f"ns/hog-{other}", clock)
        snap = collect_global_snapshot(disp)
        assert snap.clusters == sorted(workers)
        assert snap.keys == [key]
        assert snap.fences[key] == 1
        assert snap.current[key] == cur
        j_cur = snap.clusters.index(cur)
        j_other = snap.clusters.index(other)
        assert snap.valid[0, j_cur] and snap.valid[0, j_other]
        assert snap.tta_ms[0, j_other] == 0  # freed worker fits now
        assert snap.tta_ms[0, j_cur] == 600_000  # runtime-hint release
        view = snap.workers[cur]
        assert view.reachable and view.source == "inprocess"
        (q,) = view.queues
        assert q["clusterQueue"] == "cq" and q["pending"] >= 1
        assert q["dominantShareMilli"] >= 0 and q["weightMilli"] == 1000
        (cap,) = [
            c for c in view.capacities
            if c["flavor"] == "default" and c["resource"] == "cpu"
        ]
        assert cap["nominal"] == 10_000 and cap["usage"] == 10_000
        assert cap["available"] == 0

    def test_admitted_workloads_are_not_rows(self):
        clock = FakeClock(0.0)
        w1, _ = build_worker(clock)
        mgr = ClusterRuntime(clock=clock)
        disp = FederationDispatcher(
            mgr,
            clusters={"w1": MultiKueueCluster(name="w1", runtime=w1)},
            drive_inprocess=True,
        )
        GlobalScheduler(disp, rescore_interval_s=0.0)
        w = wl("runs")
        mgr.add_workload(w)
        drive(mgr, clock, passes=2, advance=0.0)
        assert w.is_admitted
        assert collect_global_snapshot(disp).keys == []

    def test_wire_only_worker_without_reader_is_unscorable(self):
        from kueue_tpu.admissionchecks.multikueue_transport import (
            HTTPTransport,
        )

        clock = FakeClock(0.0)
        w1, _ = build_worker(clock)
        mgr = ClusterRuntime(clock=clock)
        disp = FederationDispatcher(
            mgr,
            clusters={
                "w1": MultiKueueCluster(name="w1", runtime=w1),
                "dark": MultiKueueCluster(
                    name="dark",
                    transport=HTTPTransport("http://127.0.0.1:1"),
                ),
            },
            drive_inprocess=True,
        )
        GlobalScheduler(disp, rescore_interval_s=0.0)
        mgr.add_workload(wl("probe", cpu="20"))  # unadmittable: stays
        mgr.run_until_idle()
        snap = collect_global_snapshot(disp)
        dark = snap.workers["dark"]
        assert not dark.reachable and dark.source == "none"
        j = snap.clusters.index("dark")
        assert not snap.valid[:, j].any()

    def test_partitioned_worker_degrades_not_fails(self):
        mgr, disp, gs, workers, clock, _, fed = congested_federation()

        def _raise():
            raise TransportError("aggregation partitioned")

        faults.arm("global.partition", action=_raise)
        snap = collect_global_snapshot(disp)
        assert all(not v.reachable for v in snap.workers.values())
        assert not snap.valid.any()
        res = gs.rescore()
        assert res["rebalanced"] == []


# ---- rebalancing ----
class TestRebalancing:
    def _free_other(self, disp, workers, clock, key):
        cur = disp.states[key].winner or disp.states[key].clusters[0]
        other = next(n for n in workers if n != cur)
        finish(workers[other], f"ns/hog-{other}", clock)
        return cur, other

    def test_rebalance_moves_parked_workload_and_converges(self):
        mgr, disp, gs, workers, clock, _, fed = congested_federation()
        key = fed[0].key
        cur, other = self._free_other(disp, workers, clock, key)
        report = gs.rescore()
        assert report["rebalanced"] == [
            {
                "workload": key,
                "from": cur,
                "to": other,
                "gainS": 600.0,
            }
        ]
        st = disp.states[key]
        assert st.fence == 2 and st.clusters == [other]
        drive(mgr, clock, passes=4)
        assert_converged_once(mgr, workers, [key])
        assert fed[0].is_admitted
        # the move is journaled + evented + counted
        events = [
            e for e in mgr.events if e.kind == "MultiKueueRebalanced"
        ]
        assert events and other in events[-1].message
        assert gs.rebalances == 1
        text = mgr.metrics.registry.expose()
        assert (
            'kueue_global_rebalances_total{outcome="applied"} 1' in text
        )

    def test_rebalance_span_joins_lifecycle_trace(self):
        mgr, disp, gs, workers, clock, _, fed = congested_federation()
        key = fed[0].key
        self._free_other(disp, workers, clock, key)
        gs.rescore()
        tracer = getattr(mgr, "tracer", None)
        if tracer is None:
            pytest.skip("runtime has no tracer")
        tid = tracer.workload_trace_id(key)
        assert tid is not None
        names = {s.name for s in tracer.trace(tid)}
        assert "global.rescore" in names
        assert "federation.dispatch" in names  # same joined trace

    def test_hysteresis_blocks_small_gains(self):
        mgr, disp, gs, workers, clock, _, fed = congested_federation(
            hysteresis_s=10_000.0,  # > the 600s runtime-hint gain
        )
        key = fed[0].key
        self._free_other(disp, workers, clock, key)
        report = gs.rescore()
        assert report["rebalanced"] == []
        assert disp.states[key].fence == 1

    def test_covered_target_is_skipped(self):
        # fanout=2: both clusters are already targets of the race —
        # a better forecast inside the target set is NOT a move
        mgr, disp, gs, workers, clock, _, fed = congested_federation(
            fanout=2,
        )
        key = fed[0].key
        self._free_other(disp, workers, clock, key)
        report = gs.rescore()
        assert report["rebalanced"] == []
        text = mgr.metrics.registry.expose()
        assert (
            'kueue_global_rebalances_total{outcome="skipped_covered"} 1'
            in text
        )

    def test_stale_fence_cas_drops_the_move(self):
        mgr, disp, gs, workers, clock, _, fed = congested_federation()
        key = fed[0].key
        self._free_other(disp, workers, clock, key)
        faults.arm("global.stale_fence", action=lambda t: t + 1)
        report = gs.rescore()
        assert report["rebalanced"] == []
        st = disp.states[key]
        assert st.fence == 1  # untouched: no retraction, no re-dispatch
        text = mgr.metrics.registry.expose()
        assert (
            'kueue_global_rebalances_total{outcome="skipped_stale"} 1'
            in text
        )
        faults.reset()
        gs.rescore()
        drive(mgr, clock, passes=4)
        assert_converged_once(mgr, workers, [key])

    def test_max_rebalances_per_pass_caps_churn(self):
        mgr, disp, gs, workers, clock, _, fed = congested_federation(
            n_workers=3, n_wl=3, max_rebalances_per_pass=1,
        )
        # free every non-current worker: all three workloads see gains
        for w in fed:
            st = disp.states[w.key]
            cur = st.winner or st.clusters[0]
        for name in workers:
            hog_key = f"ns/hog-{name}"
            targets = {
                (disp.states[w.key].winner or disp.states[w.key].clusters[0])
                for w in fed
            }
            if name not in targets:
                finish(workers[name], hog_key, clock)
        report = gs.rescore()
        assert len(report["rebalanced"]) <= 1

    def test_interval_gating(self):
        mgr, disp, gs, workers, clock, _, fed = congested_federation()
        gs.rescore_interval_s = 30.0
        gs.rescore()  # primes last_rescore_at
        n = gs.rescores
        mgr.run_until_idle()
        assert gs.rescores == n  # within the interval: gated
        clock.advance(31.0)
        mgr.run_until_idle()
        assert gs.rescores == n + 1

    def test_standings_is_read_only(self):
        from kueue_tpu import serialization as ser

        mgr, disp, gs, workers, clock, _, fed = congested_federation()
        key = fed[0].key
        self._free_other(disp, workers, clock, key)
        before = ser.runtime_to_state(mgr)
        fence_before = disp.states[key].fence
        body = gs.standings()
        assert ser.runtime_to_state(mgr) == before
        assert disp.states[key].fence == fence_before
        (row,) = body["workloads"]
        assert row["rebalance"] is True and row["best"] is not None

    def test_host_mirror_path_decides_identically(self):
        a = congested_federation(use_device=True)
        b = congested_federation(use_device=False)
        for mgr, disp, gs, workers, clock, _, fed in (a, b):
            key = fed[0].key
            cur = disp.states[key].clusters[0]
            other = next(n for n in workers if n != cur)
            finish(workers[other], f"ns/hog-{other}", clock)
        ra = a[2].rescore()
        rb = b[2].rescore()
        assert ra["path"] == "device" and rb["path"] == "host"
        strip = lambda r: [
            {k: v for k, v in row.items()} for row in r["workloads"]
        ]
        assert strip(ra) == strip(rb)
        assert [x["to"] for x in ra["rebalanced"]] == [
            x["to"] for x in rb["rebalanced"]
        ]


# ---- chaos: exactly-one admission across the global.* fault points ----
def recover_manager(journal, tmp_path, clusters, clock, **gs_kw):
    journal.close()
    mgr2 = ClusterRuntime(clock=clock)
    res = recover(
        None, str(tmp_path / "mgr-journal"), runtime=mgr2, strict=True
    )
    mgr2.attach_journal(res.journal)
    disp2 = FederationDispatcher(
        mgr2, clusters=clusters, drive_inprocess=True, fanout=1,
        worker_lost_timeout=1e9, heartbeat_interval_s=1e9,
    )
    gs2 = GlobalScheduler(
        disp2, hysteresis_s=10.0, rescore_interval_s=0.0, **gs_kw
    )
    return mgr2, disp2, gs2, res.journal


class TestChaosProperty:
    """Acceptance: crash/corrupt at every ``global.*`` point during
    active rebalancing; after recovery the federation converges to
    exactly one admission per workload with invariants clean."""

    def _arm_and_run(self, tmp_path, point, action, occurrence=0):
        mgr, disp, gs, workers, clock, journal, fed = (
            congested_federation(tmp_path, n_workers=3, n_wl=3)
        )
        keys = [w.key for w in fed]
        # free capacity the current placements don't hold: rebalances
        # are genuinely in flight when the fault fires
        targets = {
            disp.states[k].winner or disp.states[k].clusters[0]
            for k in keys
        }
        for name in workers:
            if name not in targets:
                finish(workers[name], f"ns/hog-{name}", clock)
        faults.arm(point, action=action, skip=occurrence)
        crashed = False
        try:
            drive(mgr, clock, passes=3)
        except faults.InjectedCrash:
            crashed = True
        faults.reset()
        if crashed:
            mgr, disp, gs, journal = recover_manager(
                journal, tmp_path, disp.clusters, clock
            )
        # release the remaining hogs so every workload can admit
        for name in workers:
            hog_key = f"ns/hog-{name}"
            if (
                hog_key in workers[name].workloads
                and not workers[name].workloads[hog_key].is_finished
            ):
                finish(workers[name], hog_key, clock)
        drive(mgr, clock, passes=8)
        assert_converged_once(mgr, workers, keys)
        journal.close()
        return crashed

    @pytest.mark.parametrize("occurrence", [0, 1, 2])
    def test_crash_mid_retraction(self, tmp_path, occurrence):
        crashed = self._arm_and_run(
            tmp_path, "global.rebalance_retract", "crash", occurrence
        )
        assert crashed or occurrence > 0

    @pytest.mark.parametrize("occurrence", [0, 2])
    def test_crash_mid_aggregation_partition_point(
        self, tmp_path, occurrence
    ):
        self._arm_and_run(
            tmp_path, "global.partition", "crash", occurrence
        )

    def test_partitioned_worker_during_rebalancing(self, tmp_path):
        def _raise():
            raise TransportError("injected aggregation partition")

        self._arm_and_run(tmp_path, "global.partition", _raise)

    def test_stale_fence_everywhere(self, tmp_path):
        self._arm_and_run(
            tmp_path, "global.stale_fence", lambda t: t + 99
        )

    def test_crash_at_stale_fence_window(self, tmp_path):
        self._arm_and_run(tmp_path, "global.stale_fence", "crash")

    def test_recovered_rebalance_state_is_consistent(self, tmp_path):
        """Crash exactly inside the rebalance window, then inspect the
        replayed state: the old epoch's retraction survived the crash,
        the fence did NOT advance (the new dispatch intent never hit
        the journal), and the pump deletes the stale copy before any
        re-mirror (the retraction barrier)."""
        mgr, disp, gs, workers, clock, journal, fed = (
            congested_federation(tmp_path)
        )
        key = fed[0].key
        cur = disp.states[key].clusters[0]
        other = next(n for n in workers if n != cur)
        finish(workers[other], f"ns/hog-{other}", clock)
        faults.arm("global.rebalance_retract", action="crash")
        with pytest.raises(faults.InjectedCrash):
            mgr.run_until_idle()
        faults.reset()
        mgr2, disp2, gs2, j2 = recover_manager(
            journal, tmp_path, disp.clusters, clock
        )
        st = disp2.states[key]
        assert st.fence == 1 and st.winner is None
        pending = [
            r for r in disp2.retractions.values() if not r.acked
        ]
        assert [(r.cluster, r.fence) for r in pending] == [(cur, 1)]
        drive(mgr2, clock, passes=6)
        assert_converged_once(mgr2, workers, [key])
        j2.close()


# ---- riding the replica feed (wire-only workers) ----
class TestFeedReaders:
    def test_http_worker_scored_through_replica_feed(self, tmp_path):
        from kueue_tpu.admissionchecks.multikueue_transport import (
            HTTPTransport,
        )
        from kueue_tpu.server import KueueServer

        clock = FakeClock(0.0)
        wrt, wjournal = build_worker(
            clock, journal_path=tmp_path / "w-journal"
        )
        wsrv = KueueServer(runtime=wrt)
        port = wsrv.start()
        mgr = ClusterRuntime(clock=clock)
        disp = FederationDispatcher(
            mgr,
            clusters={
                "east": MultiKueueCluster(
                    name="east",
                    transport=HTTPTransport(f"http://127.0.0.1:{port}"),
                ),
            },
            heartbeat_interval_s=0.0,
        )
        gs = GlobalScheduler(disp, rescore_interval_s=0.0)
        gs.attach_feed_reader("east", f"http://127.0.0.1:{port}")
        try:
            # park a workload: the worker is saturated by a local hog
            hog = wl("hog", cpu="10")
            wrt.add_workload(hog)
            wrt.run_until_idle()
            w = wl("wire-fed", cpu="4")
            mgr.add_workload(w)
            mgr.run_until_idle()
            wrt.run_until_idle()
            snap = collect_global_snapshot(disp, readers=gs.readers)
            east = snap.workers["east"]
            assert east.reachable and east.source == "feed"
            assert snap.keys == [w.key]
            assert snap.valid[0, 0]
            # the feed twin sees the hog: forecast = its release time
            assert snap.tta_ms[0, 0] == 600_000
        finally:
            wsrv.stop()
            wjournal.close()


# ---- surfaces: route, client, CLI, metrics ----
class TestSurfaces:
    def test_route_404_without_global_scheduler(self):
        from kueue_tpu.server import KueueClient, KueueServer
        from kueue_tpu.server.client import ClientError

        clock = FakeClock(0.0)
        mgr = ClusterRuntime(clock=clock)
        srv = KueueServer(runtime=mgr)
        port = srv.start()
        try:
            with pytest.raises(ClientError) as e:
                KueueClient(f"http://127.0.0.1:{port}").global_standings()
            assert e.value.status == 404
        finally:
            srv.stop()

    def test_standings_route_client_and_cli(self, capsys):
        from kueue_tpu.cli.__main__ import main as cli_main
        from kueue_tpu.server import KueueClient, KueueServer

        mgr, disp, gs, workers, clock, _, fed = congested_federation()
        key = fed[0].key
        cur = disp.states[key].clusters[0]
        other = next(n for n in workers if n != cur)
        finish(workers[other], f"ns/hog-{other}", clock)
        srv = KueueServer(runtime=mgr)
        port = srv.start()
        try:
            body = KueueClient(
                f"http://127.0.0.1:{port}"
            ).global_standings()
            assert body["clusters"] == sorted(workers)
            (row,) = body["workloads"]
            assert row["workload"] == key
            assert row["best"] == other and row["rebalance"] is True
            assert body["workers"][cur]["reachable"] is True
            assert body["hysteresisS"] == gs.hysteresis_s
            rc = cli_main(
                ["pending-workloads", "--global", "--server",
                 f"http://127.0.0.1:{port}"]
            )
            assert rc == 0
            out = capsys.readouterr().out
            assert "WORKLOAD" in out and "REBALANCE" in out
            assert key in out and "yes" in out
            assert "CLUSTER" in out  # worker standings table
        finally:
            srv.stop()

    def test_cli_global_requires_server(self):
        from kueue_tpu.cli.__main__ import main as cli_main

        with pytest.raises(SystemExit):
            cli_main(["pending-workloads", "--global"])

    def test_cli_plain_still_needs_clusterqueue(self):
        from kueue_tpu.cli.__main__ import main as cli_main

        with pytest.raises(SystemExit):
            cli_main(["pending-workloads"])

    def test_metrics_exposed_at_zero(self):
        clock = FakeClock(0.0)
        mgr = ClusterRuntime(clock=clock)
        text = mgr.metrics.registry.expose()
        for family in (
            "kueue_global_rescore_total",
            "kueue_global_rescore_seconds",
            "kueue_global_rebalances_total",
            "kueue_global_pending_workloads",
            "kueue_global_workers_reachable",
        ):
            assert family in text, family
        for outcome in (
            "applied", "skipped_stale", "skipped_gone",
            "skipped_covered", "skipped_cooldown",
        ):
            assert f'outcome="{outcome}"' in text
