"""Mesh-sharded drain family: parity + composition on the 8-device
virtual CPU mesh (conftest forces
``--xla_force_host_platform_device_count=8``, the same mechanism the
driver's dryrun uses — so the mesh path is exercised on every tier-1
run).

The property under test everywhere: a ``(wl[, fr])`` mesh NEVER changes
a decision. Admitted sets (with flavors and cycle indices), victim
sets, parked sets and cycle counts must be bit-for-bit the
single-device kernels' — sharding is a placement concern, not a policy
one.
"""

import importlib

import numpy as np
import pytest

from kueue_tpu.core.drain import (
    launch_drain,
    run_drain,
    run_drain_fair_preempt,
    run_drain_for_scope,
    run_drain_preempt,
)
from kueue_tpu.core.pipeline import outcome_signature
from kueue_tpu.core.queue_manager import queue_order_timestamp
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.parallel import make_mesh
from kueue_tpu.parallel import harness

from tests.test_solver_path import build_env, random_spec
from tests.test_drain import (
    build_preempt_env,
    cohort_reclaim_spec,
    fair_drain_spec,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def _pending_of(mgr):
    pending = []
    for cq_name, pq in mgr.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            pending.append((wl, cq_name))
    return pending


def _ts(mgr):
    return lambda wl: queue_order_timestamp(wl, mgr._ts_policy)


def _preempt_sig(out):
    return (
        frozenset((wl.name, cq, cyc) for wl, cq, _, cyc in out.admitted),
        frozenset((wl.name, cq, cyc) for wl, cq, cyc in out.preempted),
        frozenset(
            (
                ev.victim.name,
                ev.victim_cq,
                ev.cycle,
                ev.by_cq,
                ev.by_workload.name if ev.by_workload else None,
                ev.reason,
            )
            for ev in out.evictions
        ),
        frozenset(wl.name for wl, _ in out.parked),
        out.cycles,
    )


class TestShardedDrainFamilyParity:
    """Every drain-family kernel under the mesh == single-device,
    across seeded environments (the PR-8 acceptance sweep)."""

    @pytest.mark.parametrize("seed", [0])
    def test_plain_drain_parity(self, mesh, seed):
        spec = random_spec(seed, workloads_per_cq=6)
        sigs = {}
        for label, m in (("plain", None), ("mesh", mesh)):
            sched, mgr, cache, _ = build_env(spec, use_solver=False)
            out = run_drain(
                take_snapshot(cache), _pending_of(mgr), cache.flavors,
                timestamp_fn=_ts(mgr), mesh=m,
            )
            sigs[label] = outcome_signature(out)
        assert sigs["plain"] == sigs["mesh"]

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [1])
    def test_preempt_drain_parity(self, mesh, seed):
        # tier-1 runtime headroom: the preempt kernel's mesh coverage
        # stays tier-1 via TestNarrowPanelMeshFence (same kernel, same
        # mesh, parity-asserted); this seed joins the wide sweep budget
        spec = cohort_reclaim_spec(seed)
        sigs = {}
        for label, m in (("plain", None), ("mesh", mesh)):
            sched, mgr, cache, _ = build_preempt_env(spec)
            out = run_drain_preempt(
                take_snapshot(cache), _pending_of(mgr), cache.flavors,
                timestamp_fn=_ts(mgr), mesh=m,
            )
            sigs[label] = _preempt_sig(out)
        assert sigs["plain"] == sigs["mesh"]

    def test_fair_drain_parity(self, mesh):
        spec = fair_drain_spec(9, n_cohorts=2, cqs_per_cohort=3)
        sigs = {}
        for label, m in (("plain", None), ("mesh", mesh)):
            sched, mgr, cache, _ = build_env(spec, use_solver=False)
            out = run_drain(
                take_snapshot(cache), _pending_of(mgr), cache.flavors,
                timestamp_fn=_ts(mgr), fair_sharing=True, mesh=m,
            )
            sigs[label] = outcome_signature(out)
        assert sigs["plain"] == sigs["mesh"]

    @pytest.mark.slow
    def test_fair_preempt_drain_parity(self, mesh):
        # tier-1 runtime headroom: rides the @slow budget with the
        # wide sweep (TestShardedParityWideSweep covers 4 more seeds);
        # single-device fair-preempt parity stays tier-1 elsewhere
        spec = cohort_reclaim_spec(3)
        sigs = {}
        for label, m in (("plain", None), ("mesh", mesh)):
            sched, mgr, cache, _ = build_preempt_env(spec)
            out = run_drain_fair_preempt(
                take_snapshot(cache), _pending_of(mgr), cache.flavors,
                timestamp_fn=_ts(mgr), mesh=m,
            )
            sigs[label] = _preempt_sig(out)
        assert sigs["plain"] == sigs["mesh"]

    def test_tas_drain_parity(self, mesh):
        import tests.test_tas_drain as ttd
        from kueue_tpu.core.drain import run_drain_tas

        wls = ttd.tas_spec(
            7, n_cq=3, wl_per_cq=4,
            modes=("Required", "Preferred", "Unconstrained"),
        )
        sigs = {}
        for label, m in (("plain", None), ("mesh", mesh)):
            sched, qm, cache, tas = ttd.build_env()
            for w in wls:
                qm.add_or_update_workload(ttd.tas_wl(**w))
            out = run_drain_tas(
                take_snapshot(cache), _pending_of(qm), cache.flavors, tas,
                timestamp_fn=_ts(qm), mesh=m,
            )
            adm = {}
            for (wl, _, _, cyc), ta in zip(out.admitted, out.assignments):
                adm[wl.name] = (
                    cyc,
                    tuple(sorted((d.values, d.count) for d in ta.domains))
                    if ta is not None
                    else None,
                )
            sigs[label] = (
                adm, frozenset(wl.name for wl, _ in out.parked), out.cycles
            )
        assert sigs["plain"] == sigs["mesh"]

    def test_scope_dispatch_carries_mesh(self, mesh):
        """run_drain_for_scope(mesh=...) must route the mesh into every
        kind — the production bulk path's one entry point."""
        spec = cohort_reclaim_spec(1)
        sigs = {}
        for label, m in (("plain", None), ("mesh", mesh)):
            sched, mgr, cache, _ = build_preempt_env(spec)
            out = run_drain_for_scope(
                "preempt", take_snapshot(cache), _pending_of(mgr),
                cache.flavors, timestamp_fn=_ts(mgr), mesh=m,
            )
            sigs[label] = _preempt_sig(out)
        assert sigs["plain"] == sigs["mesh"]


@pytest.mark.slow
class TestShardedParityWideSweep:
    """The wide seeded sweep (tier-1 keeps one seed per kind; this is
    the full acceptance sweep, @slow like the other wide parities)."""

    @pytest.mark.parametrize("seed", range(6))
    def test_plain_drain_parity(self, mesh, seed):
        spec = random_spec(seed + 10, workloads_per_cq=7)
        sigs = {}
        for label, m in (("plain", None), ("mesh", mesh)):
            sched, mgr, cache, _ = build_env(spec, use_solver=False)
            out = run_drain(
                take_snapshot(cache), _pending_of(mgr), cache.flavors,
                timestamp_fn=_ts(mgr), mesh=m,
            )
            sigs[label] = outcome_signature(out)
        assert sigs["plain"] == sigs["mesh"]

    @pytest.mark.parametrize("seed", range(6))
    def test_preempt_drain_parity(self, mesh, seed):
        spec = cohort_reclaim_spec(seed + 10)
        sigs = {}
        for label, m in (("plain", None), ("mesh", mesh)):
            sched, mgr, cache, _ = build_preempt_env(spec)
            out = run_drain_preempt(
                take_snapshot(cache), _pending_of(mgr), cache.flavors,
                timestamp_fn=_ts(mgr), mesh=m,
            )
            sigs[label] = _preempt_sig(out)
        assert sigs["plain"] == sigs["mesh"]

    @pytest.mark.parametrize("seed", range(4))
    def test_fair_preempt_drain_parity(self, mesh, seed):
        spec = cohort_reclaim_spec(seed + 20)
        sigs = {}
        for label, m in (("plain", None), ("mesh", mesh)):
            sched, mgr, cache, _ = build_preempt_env(spec)
            out = run_drain_fair_preempt(
                take_snapshot(cache), _pending_of(mgr), cache.flavors,
                timestamp_fn=_ts(mgr), mesh=m,
            )
            sigs[label] = _preempt_sig(out)
        assert sigs["plain"] == sigs["mesh"]


class TestLaunchDrainMesh:
    """The async (pipelined) launch path rides the same sharded specs
    as the blocking solve."""

    def test_launch_fetch_equals_run_drain(self, mesh):
        spec = random_spec(2, workloads_per_cq=6)
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        pending = _pending_of(mgr)
        snap = take_snapshot(cache)
        ref = run_drain(
            snap, pending, cache.flavors, timestamp_fn=_ts(mgr), mesh=mesh
        )
        got = launch_drain(
            snap, pending, cache.flavors, timestamp_fn=_ts(mgr), mesh=mesh
        ).fetch()
        assert outcome_signature(ref) == outcome_signature(got)
        # the speculation surface (final usage) survives the mesh too
        assert ref.final_usage is not None and got.final_usage is not None
        assert np.array_equal(ref.final_usage, got.final_usage)

    def test_chunked_launch_undecided_parity(self, mesh):
        """A truncated (chunked) mesh launch reports the same undecided
        tail as single-device — the pipelined loop's routing input."""
        spec = random_spec(6, workloads_per_cq=8)
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        pending = _pending_of(mgr)
        snap = take_snapshot(cache)
        outs = {}
        for label, m in (("plain", None), ("mesh", mesh)):
            out = launch_drain(
                snap, pending, cache.flavors, timestamp_fn=_ts(mgr),
                max_cycles=2, mesh=m,
            ).fetch()
            outs[label] = (
                outcome_signature(out),
                frozenset(wl.name for wl, _ in out.undecided),
            )
        assert outs["plain"] == outs["mesh"]


class TestPipelinedMeshRuntime:
    """--pipeline and --mesh compose: the double-buffered production
    loop under the mesh makes the serial single-device decisions, and
    the chaos fault points still converge after crash+recovery."""

    @pytest.mark.slow
    def test_pipelined_mesh_equals_serial_single_device(self, mesh):
        # tier-1 runtime headroom: the mesh+pipeline composition stays
        # tier-1 via the chaos tests below (same loop, same mesh, same
        # admitted-set-equals-serial assertion, plus recovery)
        from tests.test_pipeline import admitted, build_rt, parked

        rt_s, _ = build_rt(11, "serial")
        rt_s.run_until_idle(max_iterations=60)
        rt_m, _ = build_rt(11, "on")
        rt_m.set_mesh(mesh)
        rt_m.run_until_idle(max_iterations=60)
        assert admitted(rt_s) == admitted(rt_m)
        assert parked(rt_s) == parked(rt_m)
        assert admitted(rt_m), "vacuous trace"
        assert rt_m.pipeline.rounds >= 1
        assert not rt_m.check_invariants()
        # every drain trace carries the mesh annotation
        drains = [
            t for t in rt_m.scheduler.last_traces if t.resolution == "drain"
        ]
        assert drains and all(t.mesh == "wl=8" for t in drains)
        assert all(t.mesh == "off" for t in rt_s.scheduler.last_traces)

    @pytest.mark.parametrize(
        "point", ["cycle.prefetch_launched", "cycle.commit_pre_apply"]
    )
    def test_chaos_crash_recover_converge_with_mesh(
        self, tmp_path, mesh, point
    ):
        from kueue_tpu.storage import recover
        from kueue_tpu.testing import faults
        from tests.test_pipeline import _bare_rt, admitted, build_rt, parked

        ref, j_ref = build_rt(0, "serial", tmp_path / "ref")
        ref.run_until_idle(max_iterations=60)
        ref_admitted = admitted(ref)
        j_ref.close()

        rt, j = build_rt(0, "on", tmp_path / "j")
        rt.set_mesh(mesh)
        faults.arm(point, "crash", skip=1)
        crashed = False
        try:
            rt.run_until_idle(max_iterations=60)
        except faults.InjectedCrash:
            crashed = True
        finally:
            faults.reset()
        j.close()
        assert crashed, f"{point} never fired with the mesh active"

        rt2 = _bare_rt("on")
        rt2.set_mesh(mesh)
        res = recover(None, str(tmp_path / "j"), runtime=rt2, strict=True)
        rt2.attach_journal(res.journal)
        rt2.run_until_idle(max_iterations=60)
        assert admitted(rt2) == ref_admitted
        assert parked(rt2) == parked(ref)
        assert not rt2.check_invariants()


class TestResidentEncoder:
    """The PR-7 follow-up: device-resident drain encode between
    pipelined rounds, byte-identical to a fresh encode."""

    def _env(self, seed=0):
        spec = random_spec(seed, workloads_per_cq=5)
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        return sched, mgr, cache

    def test_resident_arrays_byte_equal_fresh_encode(self):
        from kueue_tpu.core.encode import ResidentEncoder, encode_snapshot

        sched, mgr, cache = self._env()
        snap = take_snapshot(cache)
        res = ResidentEncoder()
        tree, paths, usage = res.refresh(snap)
        enc = encode_snapshot(snap)
        assert np.array_equal(np.asarray(tree.nominal), enc.nominal)
        assert np.array_equal(np.asarray(tree.lending_limit), enc.lending_limit)
        assert np.array_equal(
            np.asarray(tree.borrowing_limit), enc.borrowing_limit
        )
        assert np.array_equal(np.asarray(tree.parent), enc.parent)
        assert np.array_equal(np.asarray(usage), enc.local_usage)
        assert res.full_encodes == 1 and res.delta_rounds == 0

    def test_delta_rounds_ship_only_touched_rows(self):
        from kueue_tpu.core.encode import ResidentEncoder

        sched, mgr, cache = self._env(1)
        snap = take_snapshot(cache)
        res = ResidentEncoder()
        res.refresh(snap)
        # touch ONE ClusterQueue's usage (what one commit does)
        snap2 = take_snapshot(cache)
        snap2.local_usage = snap2.local_usage.copy()
        snap2.local_usage[0, 0] += 3
        _, _, usage2 = res.refresh(snap2)
        assert np.array_equal(np.asarray(usage2), snap2.local_usage)
        assert res.full_encodes == 1  # no re-encode
        assert res.delta_rounds == 1 and res.delta_rows == 1

    def test_config_mutation_forces_full_encode(self):
        from kueue_tpu.core.encode import ResidentEncoder

        sched, mgr, cache = self._env(2)
        snap = take_snapshot(cache)
        res = ResidentEncoder()
        res.refresh(snap)
        snap2 = take_snapshot(cache)
        snap2.nominal = snap2.nominal.copy()
        snap2.nominal[0, 0] += 100  # a quota edit
        tree2, _, _ = res.refresh(snap2)
        assert res.full_encodes == 2
        assert np.asarray(tree2.nominal)[0, 0] == snap2.nominal[0, 0]

    def test_launch_drain_resident_equals_fresh(self):
        from kueue_tpu.core.encode import ResidentEncoder

        sched, mgr, cache = self._env(3)
        pending = _pending_of(mgr)
        snap = take_snapshot(cache)
        ref = run_drain(
            snap, pending, cache.flavors, timestamp_fn=_ts(mgr)
        )
        res = ResidentEncoder()
        for _ in range(2):  # second round rides the delta path
            got = launch_drain(
                snap, pending, cache.flavors, timestamp_fn=_ts(mgr),
                resident=res,
            ).fetch()
            assert outcome_signature(ref) == outcome_signature(got)
        assert res.full_encodes == 1 and res.delta_rounds == 1

    def test_pipelined_runtime_uses_resident_encode(self):
        from tests.test_pipeline import admitted, build_rt

        rt, _ = build_rt(13, "on")
        rt.run_until_idle(max_iterations=60)
        assert admitted(rt)
        res = rt._drain_resident
        assert res is not None and res.full_encodes >= 1
        assert res.delta_rounds >= 1  # later rounds delta-updated
        assert rt.mesh_status()["residentEncode"] == res.stats()


class TestNarrowPanelMeshFence:
    """The GSPMD narrow-panel probe: supported rungs run the ladder
    under the mesh; unsupported rungs are clamped; a fully-unsupported
    mesh pins the exact width — regression either way."""

    def test_probe_verdicts_are_memoized_per_width(self, mesh):
        v8 = harness.narrow_panels_supported(mesh, 8)
        assert harness.narrow_panels_supported(mesh, 8) is v8
        assert isinstance(v8, bool)

    def test_mesh_safe_widths_clamps_unsupported_rungs(
        self, mesh, monkeypatch
    ):
        monkeypatch.setattr(
            harness, "narrow_panels_supported",
            lambda m, w=8: w >= 16,
        )
        assert harness.mesh_safe_widths(mesh, (8, 64)) == (16, 64)
        assert harness.mesh_safe_widths(mesh, (16, 64)) == (16, 64)

    def test_fully_fenced_mesh_pins_exact_width(self, mesh, monkeypatch):
        """With every narrow rung refused, the schedule degenerates to
        the pinned exact search_width (the PR-7 behavior) and decisions
        still match single-device."""
        monkeypatch.setattr(
            harness, "narrow_panels_supported", lambda m, w=8: False
        )
        snap, pending, flavors = harness._canary_preempt_case()
        ref = run_drain_preempt(snap, pending, flavors, search_width=32)
        snap2, pending2, flavors2 = harness._canary_preempt_case()
        got = run_drain_preempt(
            snap2, pending2, flavors2, search_width=32, mesh=mesh
        )
        assert harness._preempt_sig(ref) == harness._preempt_sig(got)
        sched = harness.last_panel_schedule()
        assert sched["widths"] == (32,) and sched["fenced"] is True

    def test_supported_ladder_runs_under_mesh(self, mesh, monkeypatch):
        """With rungs >= 16 certified, the tuner ladder survives the
        mesh (clamped, not pinned) and decisions match."""
        monkeypatch.setattr(
            harness, "narrow_panels_supported", lambda m, w=8: w >= 16
        )
        spec = cohort_reclaim_spec(4)
        sigs = {}
        for label, m in (("plain", None), ("mesh", mesh)):
            sched, mgr, cache, _ = build_preempt_env(spec)
            out = run_drain_preempt(
                take_snapshot(cache), _pending_of(mgr), cache.flavors,
                timestamp_fn=_ts(mgr), search_width=64, mesh=m,
            )
            sigs[label] = _preempt_sig(out)
        assert sigs["plain"] == sigs["mesh"]
        sched_rec = harness.last_panel_schedule()
        assert len(sched_rec["widths"]) >= 2  # a real ladder, not a pin
        assert sched_rec["widths"][-1] == 64
        assert all(w >= 16 for w in sched_rec["widths"][:-1])

    def test_demoted_width_is_clamped_from_future_schedules(self, mesh):
        m2 = make_mesh(8, fr_parallel=True)
        # width 32 doubles straight to the final 64, so no other width
        # needs a (probe-triggering) verdict in this unit test
        key = (harness.mesh_fingerprint(m2), 32)
        old = harness._NARROW_VERDICTS.get(key)
        try:
            harness._NARROW_VERDICTS[key] = True
            assert harness.mesh_safe_widths(m2, (32, 64)) == (32, 64)
            harness.demote_panel_width(m2, 32)
            assert harness.mesh_safe_widths(m2, (32, 64)) == (64,)
        finally:
            if old is None:
                harness._NARROW_VERDICTS.pop(key, None)
            else:
                harness._NARROW_VERDICTS[key] = old

    def test_2d_mesh_preempt_parity_with_self_healing_ladder(self):
        """The dryrun regression: on the 2-D (wl, fr) mesh the
        miscompile is problem-shape-dependent — a narrow tier the
        canary certified can still be rejected at a bigger shape. The
        containment demotes it and escalates; decisions must equal
        single-device either way."""
        mesh2 = make_mesh(8, fr_parallel=True)
        spec = cohort_reclaim_spec(6)
        sigs = {}
        for label, m in (("plain", None), ("mesh", mesh2)):
            sched, mgr, cache, _ = build_preempt_env(spec)
            out = run_drain_preempt(
                take_snapshot(cache), _pending_of(mgr), cache.flavors,
                timestamp_fn=_ts(mgr), mesh=m,
            )
            sigs[label] = _preempt_sig(out)
        assert sigs["plain"] == sigs["mesh"]

    def test_real_probe_catches_the_documented_miscompile(self, mesh):
        """On the 8-device CPU mesh the width-8 compaction is rejected
        by the hlo verifier after spmd-partitioning (the documented
        mixed s64/s32 compare) — the probe must report it unsupported,
        and wider rungs must still be usable or the fence pins exact.
        If a future jaxlib fixes the partitioner this test still
        passes: the probe then certifies width 8 honestly."""
        v8 = harness.narrow_panels_supported(mesh, 8)
        safe = harness.mesh_safe_widths(mesh, (8, 64))
        if v8:
            assert safe == (8, 64)
        else:
            assert safe[-1] == 64 and 8 not in safe[:-1]


class TestShardedKernelRegistry:
    """SHARDED_KERNELS is the KERNEL_MIRRORS twin: every sharded entry
    point resolves, and its kernel answers to the SAME host mirror as
    the single-device twin."""

    def test_every_sharded_kernel_has_a_registered_mirror(self):
        from kueue_tpu.ops import KERNEL_MIRRORS
        from kueue_tpu.parallel import SHARDED_KERNELS

        missing = set(SHARDED_KERNELS) - set(KERNEL_MIRRORS)
        assert not missing, (
            f"sharded kernels without a registered host mirror: {missing}"
        )

    def test_sharded_entry_points_resolve(self):
        from kueue_tpu.parallel import SHARDED_KERNELS

        for kernel, entry in SHARDED_KERNELS.items():
            mod_name, attr = entry.split(":")
            mod = importlib.import_module(mod_name)
            assert hasattr(mod, attr), (
                f"{kernel}: sharded entry {entry} does not resolve"
            )

    def test_mirrors_of_sharded_kernels_resolve(self):
        from kueue_tpu.ops import KERNEL_MIRRORS
        from kueue_tpu.parallel import SHARDED_KERNELS

        for kernel in SHARDED_KERNELS:
            mirror, _test = KERNEL_MIRRORS[kernel]
            mod_name, attr = mirror.split(":")
            mod = importlib.import_module(mod_name)
            assert hasattr(mod, attr)


class TestMeshObservability:
    def test_metrics_materialized_at_zero(self):
        from kueue_tpu.metrics import Metrics

        text = Metrics().registry.expose()
        assert "kueue_mesh_devices 0" in text
        assert "kueue_mesh_shard_width 0" in text
        assert "kueue_mesh_allgather_seconds 0" in text

    def test_runtime_mesh_gauges_and_status(self, mesh):
        from kueue_tpu.controllers import ClusterRuntime

        rt = ClusterRuntime(mesh=mesh)
        text = rt.metrics.registry.expose()
        assert "kueue_mesh_devices 8" in text
        assert "kueue_mesh_shard_width 8" in text
        st = rt.mesh_status()
        assert st["shape"] == "wl=8" and st["devices"] == 8
        assert "buckets" in st and "placeSeconds" in st
        rt.set_mesh(None)
        assert rt.mesh_status()["shape"] == "off"
        assert "kueue_mesh_devices 0" in rt.metrics.registry.expose()

    def test_runtime_accepts_operator_spec(self):
        from kueue_tpu.controllers import ClusterRuntime

        rt = ClusterRuntime(mesh="auto")
        assert rt.mesh is not None and rt.mesh.size == 8
        rt2 = ClusterRuntime(mesh="off")
        assert rt2.mesh is None
        rt3 = ClusterRuntime(mesh=4)
        assert rt3.mesh is not None and rt3.mesh.size == 4

    def test_resolve_mesh_specs(self):
        from kueue_tpu.parallel import resolve_mesh

        assert resolve_mesh("off") is None
        assert resolve_mesh(None) is None
        assert resolve_mesh(1) is None  # <2 devices: no mesh
        m = resolve_mesh("auto")
        assert m is not None and m.size == 8
        assert resolve_mesh("4").size == 4

    def test_cycle_trace_mesh_annotation(self):
        from kueue_tpu.core.scheduler import CycleTrace

        d = CycleTrace(cycle=1, mesh="wl=8").to_dict()
        assert d["mesh"] == "wl=8"
        assert CycleTrace().to_dict()["mesh"] == "off"

    def test_dump_and_dashboard_sections(self, mesh):
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.debugger import dump
        from kueue_tpu.server.dashboard import dashboard_payload

        rt = ClusterRuntime(mesh=mesh)
        text = dump(rt)
        assert "-- mesh (multi-chip admission) --" in text
        assert "shape=wl=8" in text
        payload = dashboard_payload(rt)
        assert payload["mesh"]["shape"] == "wl=8"
        assert payload["mesh"]["devices"] == 8

    def test_bucket_accounting_counts_hits(self):
        harness.reset_stats()
        m = make_mesh(8)
        assert harness.note_bucket("drain_kernel", (1, 2, 3), m) is False
        assert harness.note_bucket("drain_kernel", (1, 2, 3), m) is True
        assert harness.note_bucket("drain_kernel", (9, 9, 9), m) is False
        st = harness.bucket_stats()
        assert st["buckets"] == 2 and st["hits"] == 1 and st["misses"] == 2
        assert st["perKernel"]["drain_kernel"]["hits"] == 1
        harness.reset_stats()
