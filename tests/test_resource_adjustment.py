"""Resource adjustment pipeline: LimitRange defaulting/validation,
RuntimeClass overhead, limits-as-requests, excludeResourcePrefixes and
transformations — mirroring pkg/workload/resources.go and
pkg/util/limitrange behaviors."""

import pytest

from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import ResourceGroup
from kueue_tpu.models.workload import PodSet
from kueue_tpu.core.limit_range import (
    LimitRange,
    LimitRangeItem,
    RuntimeClass,
    adjust_workload_resources,
    summarize,
    validate_limit_range,
    validate_resources,
)
from kueue_tpu.core.workload_info import (
    REPLACE,
    RETAIN,
    ResourceTransform,
    ResourceTransformConfig,
    quota_per_pod,
)
from kueue_tpu.controllers import ClusterRuntime


def wl_with(ps: PodSet) -> Workload:
    return Workload(namespace="ns", name="w", queue_name="lq", pod_sets=(ps,))


class TestSummarize:
    def test_merge_rules(self):
        a = LimitRange(
            namespace="ns", name="a",
            items=[
                LimitRangeItem.build(
                    max={"cpu": "8"}, min={"cpu": "1"},
                    default={"cpu": "4"}, default_request={"cpu": "2"},
                )
            ],
        )
        b = LimitRange(
            namespace="ns", name="b",
            items=[
                LimitRangeItem.build(
                    max={"cpu": "6"}, min={"cpu": "2"},
                    default={"cpu": "3"}, default_request={"cpu": "1"},
                )
            ],
        )
        s = summarize([a, b])["Container"]
        assert s.max == {"cpu": 6000}  # keep-min
        assert s.min == {"cpu": 2000}  # keep-max
        assert s.default == {"cpu": 4000}  # keep-first
        assert s.default_request == {"cpu": 2000}


class TestAdjust:
    def test_limit_range_defaults_applied(self):
        lr = LimitRange(
            namespace="ns", name="lr",
            items=[
                LimitRangeItem.build(
                    default={"cpu": "4"}, default_request={"cpu": "2"}
                )
            ],
        )
        wl = wl_with(PodSet.build("main", 1, {}))
        adjust_workload_resources(wl, [lr])
        assert wl.pod_sets[0].requests == {"cpu": 2000}
        assert wl.pod_sets[0].limits == {"cpu": 4000}

    def test_limits_used_as_missing_requests(self):
        wl = wl_with(
            PodSet.build("main", 1, {"cpu": "1"}, limits={"cpu": "2", "memory": "1Gi"})
        )
        adjust_workload_resources(wl, [])
        # cpu request explicit; memory request defaulted from its limit
        assert wl.pod_sets[0].requests == {"cpu": 1000, "memory": 1 << 30}

    def test_runtime_class_overhead_filled(self):
        wl = wl_with(
            PodSet.build("main", 1, {"cpu": "1"}, runtime_class_name="gvisor")
        )
        adjust_workload_resources(
            wl, [], {"gvisor": RuntimeClass.build("gvisor", {"cpu": "250m"})}
        )
        assert wl.pod_sets[0].overhead == {"cpu": 250}
        # explicit overhead is never overwritten
        wl2 = wl_with(
            PodSet.build(
                "main", 1, {"cpu": "1"}, runtime_class_name="gvisor",
                overhead={"cpu": "100m"},
            )
        )
        adjust_workload_resources(
            wl2, [], {"gvisor": RuntimeClass.build("gvisor", {"cpu": "250m"})}
        )
        assert wl2.pod_sets[0].overhead == {"cpu": 100}

    def test_other_namespace_limit_range_ignored(self):
        lr = LimitRange(
            namespace="other", name="lr",
            items=[LimitRangeItem.build(default_request={"cpu": "2"})],
        )
        wl = wl_with(PodSet.build("main", 1, {}))
        adjust_workload_resources(wl, [lr])
        assert wl.pod_sets[0].requests == {}


class TestValidate:
    def test_requests_exceed_limits(self):
        wl = wl_with(PodSet.build("main", 1, {"cpu": "4"}, limits={"cpu": "2"}))
        errs = validate_resources(wl)
        assert errs and "must not exceed" in errs[0]
        assert validate_resources(
            wl_with(PodSet.build("main", 1, {"cpu": "1"}, limits={"cpu": "2"}))
        ) == []

    def test_limit_range_bounds(self):
        lr = LimitRange(
            namespace="ns", name="lr",
            items=[LimitRangeItem.build(max={"cpu": "4"}, min={"cpu": "1"})],
        )
        over = wl_with(PodSet.build("main", 1, {"cpu": "8"}))
        under = wl_with(PodSet.build("main", 1, {"cpu": "500m"}))
        ok = wl_with(PodSet.build("main", 1, {"cpu": "2"}))
        assert any("above" in e for e in validate_limit_range(over, [lr]))
        assert any("below" in e for e in validate_limit_range(under, [lr]))
        assert validate_limit_range(ok, [lr]) == []

    def test_pod_type_includes_overhead(self):
        lr = LimitRange(
            namespace="ns", name="lr",
            items=[LimitRangeItem.build(type="Pod", max={"cpu": "4"})],
        )
        wl = wl_with(
            PodSet.build("main", 1, {"cpu": "3800m"}, overhead={"cpu": "500m"})
        )
        assert any("above" in e for e in validate_limit_range(wl, [lr]))


class TestTransform:
    def test_retain_and_replace(self):
        cfg = ResourceTransformConfig(
            transformations={
                "nvidia.com/mig-1g.5gb": ResourceTransform(
                    outputs={"example.com/gpu-units": 1, "example.com/gpu-mem": 5},
                    strategy=REPLACE,
                ),
                "cpu": ResourceTransform(
                    outputs={"example.com/credits": 2}, strategy=RETAIN
                ),
            }
        )
        ps = PodSet(
            name="main", count=1,
            requests={"nvidia.com/mig-1g.5gb": 2, "cpu": 3},
        )
        out = quota_per_pod(ps, cfg)
        assert out == {
            "example.com/gpu-units": 2,
            "example.com/gpu-mem": 10,
            "cpu": 3,
            "example.com/credits": 6,
        }

    def test_exclude_prefixes(self):
        cfg = ResourceTransformConfig(exclude_prefixes=("networking.example.com/",))
        ps = PodSet(
            name="main", count=1,
            requests={"cpu": 1, "networking.example.com/vpc": 1},
        )
        assert quota_per_pod(ps, cfg) == {"cpu": 1}

    def test_overhead_added_to_quota_view(self):
        ps = PodSet(name="main", count=1, requests={"cpu": 1000}, overhead={"cpu": 250})
        assert quota_per_pod(ps) == {"cpu": 1250}

    def test_fast_path_returns_spec_requests(self):
        ps = PodSet(name="main", count=1, requests={"cpu": 1000})
        assert quota_per_pod(ps) is ps.requests


def _runtime(**kw):
    rt = ClusterRuntime(**kw)
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",), (FlavorQuotas.build("default", {"cpu": "10"}),)
                ),
            ),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    return rt


class TestJobEquivalence:
    def test_limit_range_defaults_do_not_churn_job_workloads(self):
        """A LimitRange default applied at workload ingress must not
        make the job reconciler see its workload as stale (delete/
        recreate loop): equivalence compares adjusted-vs-adjusted."""
        from kueue_tpu.controllers.jobs.batch_job import BatchJob

        rt = _runtime()
        rt.add_limit_range(
            LimitRange(
                namespace="ns", name="lr",
                items=[
                    LimitRangeItem.build(default_request={"memory": "1Gi"})
                ],
            )
        )
        job = BatchJob.build(
            "ns", "train", "lq", parallelism=1, requests={"cpu": "1"}
        )
        rt.add_job(job)
        rt.run_until_idle()
        created = sum(1 for e in rt.job_reconciler.events if e.kind == "CreatedWorkload")
        deleted = sum(1 for e in rt.job_reconciler.events if e.kind == "DeletedWorkload")
        assert created == 1 and deleted == 0
        wl = rt.workloads[
            f"ns/{rt.job_reconciler.workload_name_for(job)}"
        ]
        # memory quota only admits if within CQ... cq has no memory
        # quota, so just assert the workload is stable and unsuspended
        # decisions aside, and the adjusted requests stuck
        assert wl.pod_sets[0].requests.get("memory") == 1 << 30


class TestRuntimeIntegration:
    def test_adjustment_at_ingress_then_admission(self):
        rt = _runtime()
        rt.add_limit_range(
            LimitRange(
                namespace="ns", name="lr",
                items=[LimitRangeItem.build(default_request={"cpu": "2"})],
            )
        )
        rt.add_runtime_class(RuntimeClass.build("rtc", {"cpu": "1"}))
        wl = wl_with(PodSet.build("main", 1, {}, runtime_class_name="rtc"))
        rt.add_workload(wl)
        rt.run_until_idle()
        # defaulted to 2 cpu + 1 cpu overhead => 3 cpu charged
        assert wl.is_admitted
        assert wl.admission.pod_set_assignments[0].resource_usage == {"cpu": 3000}

    def test_limit_range_violation_is_inadmissible(self):
        rt = _runtime()
        rt.add_limit_range(
            LimitRange(
                namespace="ns", name="lr",
                items=[LimitRangeItem.build(max={"cpu": "2"})],
            )
        )
        wl = wl_with(PodSet.build("main", 1, {"cpu": "4"}))
        rt.add_workload(wl)
        rt.run_until_idle()
        assert not wl.is_admitted
        pq = rt.queues.cluster_queues["cq"]
        assert wl.key in pq.inadmissible

    def test_requests_above_limits_inadmissible(self):
        rt = _runtime()
        wl = wl_with(PodSet.build("main", 1, {"cpu": "4"}, limits={"cpu": "2"}))
        rt.add_workload(wl)
        rt.run_until_idle()
        assert not wl.is_admitted

    def test_transform_affects_quota_not_spec(self):
        from kueue_tpu.config import ResourceSettings

        rt = _runtime(
            resources=ResourceSettings(
                transformations={
                    "example.com/accel": {
                        "strategy": "Replace",
                        "outputs": {"cpu": 2.0},
                    }
                }
            )
        )
        wl = wl_with(PodSet(name="main", count=1, requests={"example.com/accel": 3}))
        rt.add_workload(wl)
        rt.run_until_idle()
        assert wl.is_admitted
        # quota charged on the transformed resource (3 accel -> 6 cpu
        # canonical units)
        assert wl.admission.pod_set_assignments[0].resource_usage == {"cpu": 6}
        # the spec keeps the original resource
        assert wl.pod_sets[0].requests == {"example.com/accel": 3}

    def test_transform_solver_parity(self):
        """Device solver and host assigner agree under transformations."""
        from kueue_tpu.config import ResourceSettings

        decisions = {}
        for use_solver in (False, True):
            rt = _runtime(
                resources=ResourceSettings(
                    exclude_resource_prefixes=("ignored.example.com/",),
                    transformations={
                        "example.com/accel": {
                            "strategy": "Replace",
                            "outputs": {"cpu": 2000.0},
                        }
                    },
                ),
                use_solver=use_solver,
                solver_threshold=1,
            )
            for i in range(6):
                rt.add_workload(
                    Workload(
                        namespace="ns", name=f"w{i}", queue_name="lq",
                        priority=i, creation_time=float(i),
                        pod_sets=(
                            PodSet(
                                name="main", count=1,
                                requests={
                                    "example.com/accel": 2,
                                    "ignored.example.com/x": 5,
                                },
                            ),
                        ),
                    )
                )
            rt.run_until_idle()
            decisions[use_solver] = sorted(
                name for name, wl in (
                    (w.name, w) for w in rt.workloads.values()
                ) if wl.is_admitted
            )
        assert decisions[False] == decisions[True]
        # 10 cpu quota / 4 cpu per wl -> 2 admitted (highest priority)
        assert decisions[True] == ["w4", "w5"]
