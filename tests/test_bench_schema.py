"""Bench compact-line schema lint (over the stage registry): every
``bench.py`` invocation's final compact JSON line must carry
``headline_ms`` + ``backend`` — the ``BENCH_*.json`` contract the
growth driver tail-parses — so ``--serve`` and future stages cannot
silently drift from it. Pure-function lint: the stage registry, the
single-stage CLI modes, the headline-promotion fallback and the
compact-line builder are exercised on synthetic records, no benchmark
runs.
"""

import importlib.util
import os

import pytest

_BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_module", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestCompactLineContract:
    def test_every_single_stage_mode_names_a_registered_stage(self, bench):
        for flag, stages in bench.SINGLE_STAGE_MODES.items():
            assert stages, f"{flag} runs no stages"
            for name in stages:
                assert name in bench.STAGES, (
                    f"{flag} names unregistered stage {name!r}"
                )
            # a one-stage mode must be able to promote its metric to
            # the headline slot, or its compact line ships headline_ms
            # null on a SUCCESSFUL run
            assert stages[0] in bench.HEADLINE_FALLBACK_STAGES, (
                f"{flag}'s stage {stages[0]!r} has no headline fallback"
            )

    def test_fallback_stages_are_registered(self, bench):
        for name in bench.HEADLINE_FALLBACK_STAGES:
            assert name in bench.STAGES

    def test_compact_line_always_has_headline_and_backend(self, bench):
        # full run: the headline stage supplies value directly
        record = bench.finalize_headline({"value": 47.1, "backend": "tpu"})
        compact = bench.compact_line(record)
        assert compact["headline_ms"] == 47.1
        assert compact["backend"] == "tpu"
        # each single-stage mode: the stage's *_value triple promotes
        for flag, stages in bench.SINGLE_STAGE_MODES.items():
            name = stages[0]
            record = bench.finalize_headline(
                {
                    f"{name}_value": 12.5,
                    f"{name}_metric": f"{name} metric",
                    f"{name}_unit": "ms",
                    "backend": "cpu-fallback",
                }
            )
            compact = bench.compact_line(record)
            assert set(compact) >= {"headline_ms", "backend"}, flag
            assert compact["headline_ms"] == 12.5, (
                f"{flag}: stage value did not promote to headline_ms"
            )
            assert compact["backend"] == "cpu-fallback"
        # total failure still yields the contract keys (value None)
        record = bench.finalize_headline({"backend": "error"})
        compact = bench.compact_line(record)
        assert set(compact) >= {"headline_ms", "backend"}

    def test_compact_extras_reference_known_keys(self, bench):
        # every extra source key is produced by some stage's record —
        # approximated by requiring the stage-name prefix convention
        prefixes = tuple(bench.STAGES) + ("serve",)
        for src, dst in bench.COMPACT_EXTRAS:
            assert any(src.startswith(p) for p in prefixes), src
            assert dst
        # the --serve contract keys specifically
        record = bench.finalize_headline(
            {
                "serve_value": 9.9,
                "serve_unit": "ms",
                "serve_admissions_per_s": 50.0,
                "serve_read_qps": 1000.0,
                "serve_max_lag_s": 0.1,
                "backend": "cpu-fallback",
            }
        )
        compact = bench.compact_line(record)
        assert set(compact) >= {
            "headline_ms", "backend", "admissions_per_s", "read_qps",
            "max_lag_s",
        }
