"""Shared snapshot <-> device-array codec (core/encode.py).

The ISSUE 3 round-trip satellite: the encoding the live cycle, the bulk
drain and the capacity planner consume is ONE definition — encode a
snapshot, decode it back, and the result must be an equal, independent,
fully functional Snapshot. Divergence here would let the planner
forecast a cluster the scheduler isn't actually running.
"""

import numpy as np
import pytest

from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.core.encode import (
    decode_snapshot,
    device_arrays,
    encode_snapshot,
)
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.models import (
    ClusterQueue,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.cohort import Cohort
from kueue_tpu.models.workload import PodSet
from kueue_tpu.utils.clock import FakeClock


def _runtime():
    """Two-level cohort forest with borrowing limits, two flavors,
    admitted usage — enough structure that every encoded field is
    non-trivial."""
    rt = ClusterRuntime(clock=FakeClock(1000.0))
    rt.add_flavor(ResourceFlavor(name="on-demand"))
    rt.add_flavor(ResourceFlavor(name="spot"))
    rt.add_cohort(Cohort(name="root"))
    rt.add_cohort(Cohort(name="team", parent="root"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq-a",
            cohort="team",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu", "memory"),
                    (
                        FlavorQuotas.build(
                            "on-demand",
                            {"cpu": ("4", "2", "1"), "memory": "8Gi"},
                        ),
                        FlavorQuotas.build("spot", {"cpu": "2", "memory": "4Gi"}),
                    ),
                ),
            ),
        )
    )
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq-b",
            cohort="root",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",), (FlavorQuotas.build("on-demand", {"cpu": "8"}),)
                ),
            ),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq-a", cluster_queue="cq-a"))
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq-b", cluster_queue="cq-b"))
    rt.add_workload(
        Workload(
            namespace="ns", name="running", queue_name="lq-a", priority=1,
            creation_time=0.0,
            pod_sets=(PodSet.build("main", 1, {"cpu": "2", "memory": "2Gi"}),),
        )
    )
    rt.run_until_idle()
    assert rt.workloads["ns/running"].is_admitted
    return rt


class TestRoundTrip:
    def test_encode_decode_equal_snapshot(self):
        snap = take_snapshot(_runtime().cache)
        enc = encode_snapshot(snap)
        back = decode_snapshot(enc)

        # identity / forest structure
        assert back.flat.cq_names == snap.flat.cq_names
        assert back.flat.cohort_names == snap.flat.cohort_names
        assert back.flat.index == snap.flat.index
        assert np.array_equal(back.flat.parent, snap.flat.parent)
        assert np.array_equal(back.flat.depth, snap.flat.depth)
        assert back.flat.max_depth == snap.flat.max_depth
        assert np.array_equal(back._lm(), snap._lm())

        # quota cells and derived trees
        assert back.fr_list == snap.fr_list
        assert back.fr_index == snap.fr_index
        assert back.resource_names == snap.resource_names
        assert np.array_equal(back.resource_index, snap.resource_index)
        for field in (
            "nominal", "lending_limit", "borrowing_limit",
            "subtree", "guaranteed", "local_usage", "weight_milli",
        ):
            assert np.array_equal(getattr(back, field), getattr(snap, field)), field

        # host-object carry-over
        assert set(back.cq_models) == set(snap.cq_models)
        assert back.generations == snap.generations
        assert back.inactive_cqs == snap.inactive_cqs
        assert set(back.workloads) == set(snap.workloads)
        for key, ws in snap.workloads.items():
            assert np.array_equal(back.workloads[key].usage_vec, ws.usage_vec)

    def test_encode_is_view_decode_is_copy(self):
        snap = take_snapshot(_runtime().cache)
        enc = encode_snapshot(snap)
        # encode is zero-copy: the hot path pays nothing
        assert enc.nominal is snap.nominal
        assert enc.local_usage is snap.local_usage
        # decode is independent: mutating the decoded snapshot (the
        # planner's per-scenario simulations) never touches the source
        back = decode_snapshot(enc)
        vec = back.vector_of({})
        back.nominal[0, 0] += 1000
        back.add_usage(back.flat.cq_names[0], vec)
        assert np.array_equal(enc.nominal, snap.nominal)
        assert np.array_equal(enc.local_usage, snap.local_usage)

    def test_decoded_snapshot_is_functional(self):
        """The decoded snapshot must answer the same admission
        questions as the original — fits/available/borrowing drive the
        planner's forecast simulation."""
        snap = take_snapshot(_runtime().cache)
        back = decode_snapshot(encode_snapshot(snap))
        for cq in snap.flat.cq_names:
            assert np.array_equal(
                back.available_for(cq), snap.available_for(cq)
            ), cq
            probe = np.zeros(len(snap.fr_list), dtype=np.int64)
            probe[0] = 1000
            assert back.fits(cq, probe) == snap.fits(cq, probe), cq
            assert back.is_borrowing(cq) == snap.is_borrowing(cq), cq
        # usage bubbles identically through the cohort tree
        cq = snap.flat.cq_names[0]
        vec = np.zeros(len(snap.fr_list), dtype=np.int64)
        vec[0] = 2000
        snap.add_usage(cq, vec)
        back.add_usage(cq, vec)
        assert np.array_equal(back.usage(), snap.usage())
        snap.remove_usage(cq, vec)
        back.remove_usage(cq, vec)
        assert np.array_equal(back.usage(), snap.usage())

    def test_with_quota_variant_shares_structure(self):
        snap = take_snapshot(_runtime().cache)
        enc = encode_snapshot(snap)
        bumped = enc.nominal.copy()
        bumped[0, 0] += 4000
        variant = enc.with_quota(nominal=bumped)
        assert variant.parent is enc.parent  # structure is shared
        assert variant.lending_limit is enc.lending_limit
        back = decode_snapshot(variant)
        assert back.nominal[0, 0] == snap.nominal[0, 0] + 4000
        # untouched cells identical
        assert np.array_equal(back.nominal[1:], snap.nominal[1:])

    def test_device_arrays_match_solver_tree(self):
        """tree_arrays (the scheduler's device inputs) now routes
        through encode — the two consumers read the same bytes."""
        from kueue_tpu.core.solver import tree_arrays

        snap = take_snapshot(_runtime().cache)
        tree, paths, roots = tree_arrays(snap)
        tree2, paths2, roots2 = device_arrays(encode_snapshot(snap))
        assert np.array_equal(np.asarray(tree.nominal), np.asarray(tree2.nominal))
        assert np.array_equal(np.asarray(tree.parent), np.asarray(tree2.parent))
        assert np.array_equal(
            np.asarray(tree.level_mask), np.asarray(tree2.level_mask)
        )
        assert np.array_equal(np.asarray(paths), np.asarray(paths2))
        assert np.array_equal(roots, roots2)
