"""Self-healing admission hot path (core/guard.py).

The chaos suite for the resilient solver executor: with faults injected
at every new named point — device raise, hang-past-deadline,
wrong-answer — across seeded admission/preemption traces, the loop must
keep admitting, the final admitted set must equal the fault-free
host-only run, ``check_invariants()`` must hold throughout, and no
cycle may abort. Plus units for the circuit breaker, poison bisection,
quarantine lifecycle + durability, the transactional apply (satellite
bugfix), /healthz degradation, and the fault-point registry lint.
"""

import json
import urllib.request

import numpy as np
import pytest

from kueue_tpu import serialization as ser
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.core.guard import (
    CircuitBreaker,
    GuardConfig,
    QuarantineList,
    bisect_poison,
    solve_lowered_host,
)
from kueue_tpu.models import (
    ClusterQueue,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.constants import InadmissibleReason
from kueue_tpu.models.workload import PodSet
from kueue_tpu.storage import Journal, recover
from kueue_tpu.testing import faults
from kueue_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---- scenario: seeded admission/preemption traces ----
def build_rt(seed=0, mode="auto", k_div=16, use_solver=True,
             bulk_drain_threshold=None, ttl_s=300.0, threshold=3):
    rt = ClusterRuntime(
        clock=FakeClock(0.0),
        use_solver=use_solver,
        bulk_drain_threshold=bulk_drain_threshold,
        guard_config=GuardConfig(
            mode=mode,
            divergence_check_every=k_div,
            base_backoff_s=1.0,
            poison_threshold=threshold,
            quarantine_ttl_s=ttl_s,
        ),
    )
    # CREATION queue-order timestamps: clock-advancing faults (hang,
    # phase-deadline) must not reorder eviction requeues, or the
    # decisions-equal-host-run comparison would measure the clock, not
    # the guard (set before any CQ captures the policy)
    from kueue_tpu.core.queue_manager import RequeueTimestamp

    rt.queues._ts_policy = RequeueTimestamp.CREATION
    rt.add_flavor(ResourceFlavor(name="default"))
    rng = np.random.default_rng(seed)
    for i in range(4):
        quota = str(int(rng.integers(4, 10)))
        rt.add_cluster_queue(
            ser.cq_from_dict(
                {
                    "name": f"cq-{i}",
                    "cohort": "co",
                    "namespaceSelector": {},
                    "preemption": {
                        "withinClusterQueue": (
                            "LowerPriority" if i % 2 == 0 else "Never"
                        ),
                        "reclaimWithinCohort": "Never",
                        "borrowWithinCohort": {"policy": "Never"},
                    },
                    "resourceGroups": [
                        {
                            "coveredResources": ["cpu"],
                            "flavors": [
                                {
                                    "name": "default",
                                    "resources": [
                                        {"name": "cpu", "nominalQuota": quota}
                                    ],
                                }
                            ],
                        }
                    ],
                }
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{i}", cluster_queue=f"cq-{i}")
        )
    return rt


def make_wl(name, cq_index=0, prio=0, cpu="1", t=0.0):
    return Workload(
        namespace="ns", name=name, queue_name=f"lq-{cq_index}",
        priority=prio, creation_time=t,
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
    )


def admitted_set(rt):
    return frozenset(k for k, wl in rt.workloads.items() if wl.is_admitted)


def run_trace(rt, seed=0, waves=3, wl_per_wave=12):
    """Seeded admission + preemption waves: each wave's priorities rise,
    so preempt-capable CQs evict earlier admissions. Invariants checked
    after every settle. Returns the invariant violations seen."""
    rng = np.random.default_rng(1000 + seed)
    violations = []
    k = 0
    for wave in range(waves):
        for _ in range(wl_per_wave):
            # priorities are UNIQUE: victim selection tiebreaks on
            # quota_reserved_time, which clock-advancing faults (hang)
            # legitimately shift — distinct priorities keep the
            # decisions a pure function of the inputs
            rt.add_workload(
                make_wl(
                    f"w{k}",
                    cq_index=int(rng.integers(0, 4)),
                    prio=wave * 100 + k,
                    cpu=str(int(rng.integers(1, 4))),
                    t=float(k),
                )
            )
            k += 1
        for _ in range(20):
            if rt.run_until_idle(max_iterations=30) < 30:
                break
        violations += rt.check_invariants()
    return violations


# ---- the chaos suite (acceptance criterion) ----
def _hang_action(rt, seconds):
    def advance():
        rt.clock.advance(seconds)

    return advance


def _corrupt_result(res):
    adm = np.asarray(res.admitted).copy()
    adm[:] = ~adm  # every decision wrong
    return res._replace(admitted=adm)


class TestChaosSuite:
    @pytest.mark.parametrize("seed", range(3))
    def test_fault_free_device_equals_host_only(self, seed):
        dev = build_rt(seed, mode="auto")
        run_trace(dev, seed)
        host = build_rt(seed, mode="host")
        run_trace(host, seed)
        assert admitted_set(dev) == admitted_set(host)
        assert dev.guard.contained_cycles == 0

    @pytest.mark.parametrize("skip", [0, 1, 3])
    @pytest.mark.parametrize("seed", range(2))
    def test_device_raise_fails_over(self, seed, skip):
        rt = build_rt(seed, mode="auto")

        def boom():
            raise RuntimeError("injected device fault")

        faults.arm("solver.device_raise", action=boom, skip=skip)
        violations = run_trace(rt, seed)
        faults.reset()
        assert not violations
        assert rt.guard.contained_cycles == 0  # no cycle aborted
        assert rt.guard.failovers > 0
        host = build_rt(seed, mode="host")
        run_trace(host, seed)
        assert admitted_set(rt) == admitted_set(host)
        # the breaker opened and the operator can see it
        assert rt.guard.breaker.state in ("open", "half_open")
        assert any(e.kind == "SolverFailover" for e in rt.events)

    @pytest.mark.parametrize("seed", range(2))
    def test_device_hang_past_deadline_fails_over(self, seed):
        rt = build_rt(seed, mode="auto")
        faults.arm(
            "solver.device_hang",
            action=_hang_action(rt, rt.guard.config.device_deadline_s + 1),
        )
        violations = run_trace(rt, seed)
        faults.reset()
        assert not violations
        assert rt.guard.contained_cycles == 0
        assert rt.guard.failovers > 0
        assert rt.guard.breaker.last_failure.startswith("cycle solve exceeded")
        host = build_rt(seed, mode="host")
        run_trace(host, seed)
        assert admitted_set(rt) == admitted_set(host)

    @pytest.mark.parametrize("seed", range(2))
    def test_device_wrong_answer_caught_and_quarantined(self, seed):
        # K=1: every device solve is differentially verified, so the
        # corrupted kernel is caught before any wrong decision applies
        rt = build_rt(seed, mode="auto", k_div=1)
        faults.arm("solver.device_wrong_answer", action=_corrupt_result)
        violations = run_trace(rt, seed)
        faults.reset()
        assert not violations
        assert rt.guard.divergences >= 1
        assert rt.guard.breaker.state == "quarantined"
        assert any(e.kind == "SolverDiverged" for e in rt.events)
        assert rt.metrics.solver_divergences_total.value() >= 1
        host = build_rt(seed, mode="host")
        run_trace(host, seed)
        assert admitted_set(rt) == admitted_set(host)

    def test_phase_deadline_breach_with_device_opens_breaker(self):
        rt = build_rt(0, mode="auto")
        faults.arm(
            "cycle.phase_deadline",
            action=_hang_action(rt, rt.guard.config.cycle_deadline_s + 1),
        )
        violations = run_trace(rt, 0)
        faults.reset()
        assert not violations
        assert rt.guard.deadline_breaches > 0
        assert rt.guard.contained_cycles == 0
        host = build_rt(0, mode="host")
        run_trace(host, 0)
        assert admitted_set(rt) == admitted_set(host)

    def test_recovery_after_outage_reprobes_device(self):
        rt = build_rt(0, mode="auto")

        def boom():
            raise RuntimeError("transient outage")

        faults.arm("solver.device_raise", action=boom)
        run_trace(rt, 0, waves=1)
        assert rt.guard.breaker.state in ("open", "half_open")
        faults.reset()
        # b * 2^(n-1) backoff elapses -> the next solve is the half-open
        # probe; it succeeds and the device path closes again
        rt.clock.advance(3600.0)
        run_trace(rt, 1, waves=1)
        assert rt.guard.breaker.state == "closed"
        assert any(e.kind == "SolverRecovered" for e in rt.events)
        assert rt.metrics.solver_path.value(path="device") == 1

    def test_bulk_drain_outage_falls_back_to_cycle_loop(self):
        rt = build_rt(0, mode="auto", bulk_drain_threshold=16)

        def boom():
            raise RuntimeError("drain launch died")

        faults.arm("solver.device_raise", action=boom)
        violations = run_trace(rt, 0, waves=2, wl_per_wave=24)
        faults.reset()
        assert not violations
        assert rt.guard.failovers > 0
        host = build_rt(0, mode="host", bulk_drain_threshold=16)
        run_trace(host, 0, waves=2, wl_per_wave=24)
        assert admitted_set(rt) == admitted_set(host)


# ---- host mirror parity (the failover authority) ----
class TestHostMirror:
    @pytest.mark.parametrize("seed", range(4))
    def test_mirror_matches_device_decisions(self, seed):
        from kueue_tpu.core.queue_manager import queue_order_timestamp
        from kueue_tpu.core.snapshot import take_snapshot
        from kueue_tpu.core.solver import dispatch_lowered, lower_heads

        rt = build_rt(seed, mode="host", use_solver=False)
        rng = np.random.default_rng(seed)
        for k in range(24):
            rt.add_workload(
                make_wl(
                    f"m{k}", cq_index=int(rng.integers(0, 4)),
                    prio=int(rng.integers(0, 3)),
                    cpu=str(int(rng.integers(1, 4))), t=float(k),
                )
            )
        snapshot = take_snapshot(rt.cache)
        heads = [
            (wl, rt.queues.cluster_queue_for_workload(wl) or "")
            for wl in sorted(rt.workloads.values(), key=lambda w: w.name)
        ]
        lowered = lower_heads(
            snapshot, heads, rt.cache.flavors,
            timestamp_fn=lambda wl: queue_order_timestamp(
                wl, rt.queues._ts_policy
            ),
        )
        dev = dispatch_lowered(snapshot, lowered)
        host = solve_lowered_host(snapshot, lowered)
        for field in ("chosen", "admitted", "borrows", "reserved"):
            assert np.array_equal(
                np.asarray(getattr(dev, field)),
                np.asarray(getattr(host, field)),
            ), field

    def test_host_mode_runs_no_device_solves(self):
        rt = build_rt(0, mode="host")
        run_trace(rt, 0, waves=1)
        assert rt.guard.device_solves == 0
        assert admitted_set(rt)  # still admitting
        assert rt.metrics.solver_path.value(path="host") == 1


# ---- circuit breaker units ----
class TestCircuitBreaker:
    def test_threshold_opens_and_backoff_doubles(self):
        clock = FakeClock(0.0)
        b = CircuitBreaker(clock, failure_threshold=3, base_backoff_s=2.0)
        assert b.state == "closed"
        b.record_failure("x")
        b.record_failure("x")
        assert b.state == "closed" and b.allow_device()
        assert b.record_failure("x")  # third opens
        assert b.state == "open" and not b.allow_device()
        assert b.next_probe_at == 2.0  # b * 2^0
        clock.advance(2.0)
        assert b.state == "half_open" and b.allow_device()
        # failed probe: re-opens with doubled backoff (b * 2^1)
        assert not b.record_failure("probe failed")  # already open
        assert b.next_probe_at == clock.now() + 4.0
        clock.advance(4.0)
        assert b.allow_device()
        assert b.record_success()  # closes
        assert b.state == "closed" and b.consecutive_failures == 0

    def test_backoff_capped(self):
        clock = FakeClock(0.0)
        b = CircuitBreaker(
            clock, failure_threshold=1, base_backoff_s=1.0, max_backoff_s=8.0
        )
        for _ in range(10):
            b.record_failure("x")
        assert b.next_probe_at - clock.now() == 8.0

    def test_quarantine_is_sticky(self):
        clock = FakeClock(0.0)
        b = CircuitBreaker(clock)
        b.quarantine("divergence")
        clock.advance(1e9)
        assert b.state == "quarantined" and not b.allow_device()
        b.reset()
        assert b.state == "closed" and b.allow_device()


# ---- poison bisection units ----
class TestBisectPoison:
    def _probe(self, poison):
        def probe(subset):
            if any(x in poison for x in subset):
                raise RuntimeError("boom")

        return probe

    def test_single_poison(self):
        assert bisect_poison(list(range(16)), self._probe({11})) == [11]

    def test_multiple_poison(self):
        out = bisect_poison(list(range(16)), self._probe({2, 13}))
        assert sorted(out) == [2, 13]

    def test_no_poison(self):
        assert bisect_poison(list(range(8)), self._probe(set())) == []

    def test_interaction_returns_group(self):
        def probe(subset):
            if 1 in subset and 2 in subset:
                raise RuntimeError("only together")

        out = bisect_poison([0, 1, 2, 3], probe)
        assert 1 in out and 2 in out

    def test_empty(self):
        assert bisect_poison([], self._probe({0})) == []


# ---- poison workloads: quarantine lifecycle ----
class _PoisonWorkload(Workload):
    """Raises during prevalidation — a malformed object the API layer
    let through. Serialization never calls is_active(), so the journal
    can still persist it."""

    poisoned = True

    def is_active(self):
        if self.poisoned:
            raise RuntimeError("poison workload")
        return super().is_active()


class TestPoisonQuarantine:
    def test_poison_head_is_bisected_struck_and_quarantined(self):
        rt = build_rt(0, mode="host", threshold=3, ttl_s=300.0)
        bad = _PoisonWorkload(
            namespace="ns", name="bad", queue_name="lq-0", priority=0,
            creation_time=0.0,
            pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
        )
        rt.add_workload(bad)
        for k in range(6):
            rt.add_workload(make_wl(f"good{k}", cq_index=k % 4, t=1.0 + k))
        rt.run_until_idle()
        # the cluster is NOT wedged: good workloads admitted
        assert all(f"ns/good{k}" in admitted_set(rt) for k in range(6))
        assert rt.quarantine.active("ns/bad", rt.clock.now())
        assert rt.guard.contained_cycles >= rt.quarantine.threshold
        assert any(e.kind == "WorkloadQuarantined" for e in rt.events)
        assert rt.metrics.solver_quarantined_workloads.value() == 1
        qr = bad.conditions[
            __import__(
                "kueue_tpu.models.constants", fromlist=["x"]
            ).WorkloadConditionType.QUOTA_RESERVED
        ]
        assert qr.reason == InadmissibleReason.QUARANTINED.value
        # quarantined head is sidelined, not nominated, and check_invariants holds
        assert not rt.check_invariants()
        before = rt.scheduler.scheduling_cycle
        rt.run_until_idle()
        assert rt.guard.contained_cycles >= 3  # no NEW containment churn
        assert rt.scheduler.scheduling_cycle >= before

    def test_ttl_expiry_readmits_to_nomination(self):
        rt = build_rt(0, mode="host", threshold=2, ttl_s=60.0)
        bad = _PoisonWorkload(
            namespace="ns", name="bad", queue_name="lq-0", priority=0,
            creation_time=0.0,
            pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
        )
        rt.add_workload(bad)
        rt.run_until_idle()
        assert rt.quarantine.active("ns/bad", rt.clock.now())
        # the workload gets fixed while sidelined; TTL lapses -> requeue
        bad.poisoned = False
        rt.clock.advance(61.0)
        rt.run_until_idle()
        assert not rt.quarantine.active("ns/bad", rt.clock.now())
        assert any(e.kind == "WorkloadUnquarantined" for e in rt.events)
        assert "ns/bad" in admitted_set(rt)

    def test_operator_clear_requeues_immediately(self):
        rt = build_rt(0, mode="host", threshold=2, ttl_s=1e6)
        bad = _PoisonWorkload(
            namespace="ns", name="bad", queue_name="lq-0", priority=0,
            creation_time=0.0,
            pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
        )
        rt.add_workload(bad)
        rt.run_until_idle()
        assert rt.quarantine.active("ns/bad", rt.clock.now())
        bad.poisoned = False
        assert rt.clear_quarantine("ns/bad") == ["ns/bad"]
        rt.run_until_idle()
        assert "ns/bad" in admitted_set(rt)
        assert rt.metrics.solver_quarantined_workloads.value() == 0

    def test_quarantine_journaled_and_recovered(self, tmp_path):
        rt = build_rt(0, mode="host", threshold=2, ttl_s=1e6)
        journal = Journal(str(tmp_path / "j")).open()
        rt.attach_journal(journal)
        bad = _PoisonWorkload(
            namespace="ns", name="bad", queue_name="lq-0", priority=0,
            creation_time=0.0,
            pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
        )
        rt.add_workload(bad)
        rt.add_workload(make_wl("good", cq_index=1, t=1.0))
        rt.run_until_idle()
        assert rt.quarantine.active("ns/bad", rt.clock.now())
        journal.close()
        # crash + recover: the quarantine survives via the journal
        res = recover(None, str(tmp_path / "j"),
                      runtime=build_rt(0, mode="host"), strict=True)
        rt2 = res.runtime
        assert rt2.quarantine.active("ns/bad", 0.0)
        entry = rt2.quarantine.get("ns/bad")
        assert entry.strikes >= 2 and "quarantined" in entry.message
        res.journal.close()
        # and via the checkpoint (compaction must not release poison)
        state = ser.runtime_to_state(rt)
        rt3 = ser.runtime_from_state(json.loads(json.dumps(state)))
        assert rt3.quarantine.active("ns/bad", 0.0)

    def test_quarantine_state_follows_deletion(self):
        rt = build_rt(0, mode="host", threshold=1, ttl_s=1e6)
        bad = _PoisonWorkload(
            namespace="ns", name="bad", queue_name="lq-0", priority=0,
            creation_time=0.0,
            pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
        )
        rt.add_workload(bad)
        rt.run_until_idle()
        assert len(rt.quarantine) == 1
        rt.delete_workload(bad)
        assert len(rt.quarantine) == 0


# ---- transactional apply (satellite bugfix) ----
class TestTransactionalApply:
    def test_raising_apply_mid_cycle_leaves_usage_consistent(self):
        """A durable-write hook that RAISES on one head mid-apply (two
        heads already committed, one still to go) must cost that head a
        requeue, not the cycle — and cached usage must equal the sum
        over admitted workloads at every point."""
        rt = build_rt(0, mode="host")
        broken = {"t2"}

        def apply_admission(wl):
            if wl.name in broken:
                raise RuntimeError("API server went away")
            return True

        rt.scheduler.apply_admission = apply_admission
        # one head per CQ: t2's raise lands MID-apply, between t0/t1's
        # commits and t3's
        for k in range(4):
            rt.add_workload(make_wl(f"t{k}", cq_index=k, t=float(k)))
        res = rt.schedule_once()
        rt.run_until_idle()  # settle (clears inflight markers); t2
        # keeps failing its durable write and keeps being retried
        violations = rt.check_invariants()
        assert not violations, violations
        adm = admitted_set(rt)
        assert adm == {"ns/t0", "ns/t1", "ns/t3"}
        assert {e.workload.name for e in res.admitted} == {"t0", "t1", "t3"}
        # the failed head carries the canonical reason and is requeued
        rec = rt.audit.latest("ns/t2")
        assert rec is not None
        assert rec.reason == InadmissibleReason.DURABLE_WRITE_FAILED
        assert rt.guard.contained_cycles == 0  # contained per head
        # the API heals: the requeued head admits on the next cycle
        broken.clear()
        rt.run_until_idle()
        assert "ns/t2" in admitted_set(rt)
        assert not rt.check_invariants()

    def test_raising_apply_every_time_never_corrupts(self):
        rt = build_rt(0, mode="host")

        def apply_admission(wl):
            raise RuntimeError("always down")

        rt.scheduler.apply_admission = apply_admission
        for k in range(4):
            rt.add_workload(make_wl(f"t{k}", cq_index=k % 4, t=float(k)))
        rt.run_until_idle()
        assert not rt.check_invariants()
        assert not admitted_set(rt)
        # nothing charged: every CQ's usage is zero
        for cached in rt.cache.cluster_queues.values():
            assert all(q == 0 for q in cached.usage.values())


# ---- /healthz degradation (satellite bugfix) ----
class TestHealthz:
    def _get(self, port):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz") as r:
            return json.loads(r.read())

    def test_degraded_while_circuit_open_and_while_quarantined(self):
        from kueue_tpu.server import KueueServer

        rt = build_rt(0, mode="auto")
        srv = KueueServer(runtime=rt, auto_reconcile=False)
        port = srv.start()
        try:
            body = self._get(port)
            assert body["status"] == "ok"
            assert body["solver"]["path"] == "device"
            # circuit opens -> degraded
            for _ in range(rt.guard.config.failure_threshold):
                rt.guard._note_failure("test outage", "raise")
            body = self._get(port)
            assert body["status"] == "degraded"
            assert body["solver"]["breaker"] == "open"
            assert body["solver"]["path"] == "host"
            # recovery -> ok again
            rt.guard._note_success()
            body = self._get(port)
            assert body["status"] == "ok"
            # quarantined workload -> degraded, cleared -> ok
            rt.add_workload(make_wl("q0"))
            rt.scheduler._do_quarantine(rt.workloads["ns/q0"], "test")
            rt.scheduler.on_quarantine(rt.workloads["ns/q0"], "test")
            body = self._get(port)
            assert body["status"] == "degraded"
            assert body["solver"]["quarantinedWorkloads"] == 1
            rt.clear_quarantine()
            body = self._get(port)
            assert body["status"] == "ok"
        finally:
            srv.stop()

    def test_quarantine_routes_and_dashboard_badge(self):
        from kueue_tpu.server import KueueClient, KueueServer

        rt = build_rt(0, mode="host", threshold=1, ttl_s=1e6)
        bad = _PoisonWorkload(
            namespace="ns", name="bad", queue_name="lq-0", priority=0,
            creation_time=0.0,
            pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
        )
        rt.add_workload(bad)
        rt.run_until_idle()
        srv = KueueServer(runtime=rt, auto_reconcile=False)
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            out = client.quarantine_list()
            assert [q["key"] for q in out["items"]] == ["ns/bad"]
            assert out["solver"]["mode"] == "host"
            from kueue_tpu.server.dashboard import dashboard_payload

            payload = dashboard_payload(rt)
            assert payload["solver"]["quarantined"][0]["key"] == "ns/bad"
            bad.poisoned = False
            cleared = client.quarantine_clear("ns/bad")
            assert cleared["cleared"] == ["ns/bad"]
            assert client.quarantine_list()["items"] == []
        finally:
            srv.stop()


# ---- kueuectl quarantine ----
class TestKueuectlQuarantine:
    def test_offline_list_and_clear(self, tmp_path, capsys):
        from kueue_tpu.cli.__main__ import main as kueuectl

        rt = build_rt(0, mode="host", threshold=1, ttl_s=1e6)
        rt.add_workload(make_wl("w0"))
        rt.scheduler._do_quarantine(rt.workloads["ns/w0"], "bad object")
        state_path = tmp_path / "state.json"
        state_path.write_text(json.dumps(ser.runtime_to_state(rt)))

        assert kueuectl(["--state", str(state_path), "quarantine", "list"]) == 0
        out = capsys.readouterr().out
        assert "ns/w0" in out and "bad object" in out

        assert kueuectl(
            ["--state", str(state_path), "quarantine", "clear", "ns/w0"]
        ) == 0
        assert "cleared 1" in capsys.readouterr().out
        data = json.loads(state_path.read_text())
        assert data.get("quarantine", []) == []

    def test_server_mode(self, tmp_path, capsys):
        from kueue_tpu.cli.__main__ import main as kueuectl
        from kueue_tpu.server import KueueServer

        rt = build_rt(0, mode="host", threshold=1, ttl_s=1e6)
        rt.add_workload(make_wl("w0"))
        rt.scheduler._do_quarantine(rt.workloads["ns/w0"], "bad object")
        rt.scheduler.on_quarantine(rt.workloads["ns/w0"], "bad object")
        srv = KueueServer(runtime=rt, auto_reconcile=False)
        port = srv.start()
        try:
            url = f"http://127.0.0.1:{port}"
            assert kueuectl(
                ["--state", str(tmp_path / "s.json"),
                 "quarantine", "list", "--server", url]
            ) == 0
            out = capsys.readouterr().out
            assert "ns/w0" in out and "solver path: host" in out
            assert kueuectl(
                ["--state", str(tmp_path / "s.json"),
                 "quarantine", "clear", "--server", url]
            ) == 0
            assert "ns/w0" in capsys.readouterr().out
            assert len(rt.quarantine) == 0
        finally:
            srv.stop()


# ---- divergence verdict durability ----
class TestDivergenceDurability:
    def test_verdict_journaled_and_requarantines_on_recovery(self, tmp_path):
        rt = build_rt(0, mode="auto", k_div=1)
        journal = Journal(str(tmp_path / "j")).open()
        rt.attach_journal(journal)
        faults.arm("solver.device_wrong_answer", action=_corrupt_result)
        run_trace(rt, 0, waves=1)
        faults.reset()
        assert rt.guard.breaker.state == "quarantined"
        assert rt.last_solver_verdict is not None
        assert rt.last_solver_verdict["authority"] == "host"
        journal.close()
        res = recover(None, str(tmp_path / "j"),
                      runtime=build_rt(0, mode="auto"), strict=True)
        rt2 = res.runtime
        assert rt2.last_solver_verdict is not None
        # a kernel that answered wrong is not trusted again on restart
        assert rt2.guard.breaker.state == "quarantined"
        assert rt2.guard.path == "host"
        res.journal.close()


# ---- fault-point registry lint (satellite) ----
class TestFaultPointRegistry:
    def test_every_call_site_is_registered(self):
        """Static lint over the tree: every literal fault-point name at
        a ``faults.fire("...")`` / ``faults.transform("...")`` /
        ``fault_point="..."`` call site must be registered in
        FAULT_POINTS (mirroring the PR-2 reason-enum lint), and every
        registered point must have at least one production call site.
        Thin wrapper over the kueuelint ``fault-point`` rule."""
        from kueue_tpu.analysis import lint

        offenders = lint(rules=["fault-point"])
        assert not offenders, (
            "fault-point registry violations:\n"
            + "\n".join(str(f) for f in offenders)
        )

    def test_list_fault_points_sorted_and_documented(self):
        pts = faults.list_fault_points()
        assert pts == sorted(pts)
        assert all(faults.FAULT_POINTS[p] for p in pts)

    def test_transform_hook(self):
        assert faults.transform("solver.device_wrong_answer", 41) == 41
        faults.arm("solver.device_wrong_answer", action=lambda v: v + 1)
        assert faults.transform("solver.device_wrong_answer", 41) == 42
        assert faults.fired("solver.device_wrong_answer") == 1
        faults.arm("solver.device_wrong_answer")  # "crash"
        with pytest.raises(faults.InjectedCrash):
            faults.transform("solver.device_wrong_answer", 41)


# ---- quarantine list units ----
class TestQuarantineList:
    def test_strike_threshold_and_ttl(self):
        q = QuarantineList(threshold=3, ttl_s=100.0)
        assert q.strike("a") == 1
        assert q.strike("a") == 2
        assert q.strike("a") == 3
        q.add("a", "bad", now=10.0)
        assert q.active("a", 50.0)
        assert not q.active("a", 110.0)  # TTL lapsed (read-side)
        assert [e.key for e in q.expired(110.0)] == ["a"]
        entry = q.release("a")
        assert entry is not None and q.strikes("a") == 0

    def test_restore_roundtrip(self):
        q = QuarantineList()
        q.add("a", "bad", now=5.0)
        d = q.get("a").to_dict()
        q2 = QuarantineList()
        q2.restore(**d)
        assert q2.get("a").until == q.get("a").until
