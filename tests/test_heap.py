"""Keyed heap semantics (pkg/util/heap parity)."""

from dataclasses import dataclass

from kueue_tpu.utils.heap import Heap


@dataclass
class Item:
    name: str
    prio: int


def make_heap():
    return Heap(key_fn=lambda it: it.name, less=lambda a, b: a.prio > b.prio)


def test_push_pop_order():
    h = make_heap()
    for name, p in [("a", 1), ("b", 5), ("c", 3)]:
        assert h.push_if_not_present(Item(name, p))
    assert h.pop().name == "b"
    assert h.pop().name == "c"
    assert h.pop().name == "a"
    assert h.pop() is None


def test_push_if_not_present_rejects_dup():
    h = make_heap()
    assert h.push_if_not_present(Item("a", 1))
    assert not h.push_if_not_present(Item("a", 99))
    assert h.peek().prio == 1


def test_push_or_update_reorders():
    h = make_heap()
    h.push_or_update(Item("a", 1))
    h.push_or_update(Item("b", 2))
    h.push_or_update(Item("a", 10))
    assert len(h) == 2
    assert h.pop().name == "a"


def test_delete_and_get():
    h = make_heap()
    h.push_or_update(Item("a", 1))
    h.push_or_update(Item("b", 2))
    assert h.get_by_key("a").prio == 1
    assert h.delete("b")
    assert not h.delete("b")
    assert h.pop().name == "a"
    assert len(h) == 0


def test_fifo_tiebreak():
    h = make_heap()
    h.push_or_update(Item("first", 5))
    h.push_or_update(Item("second", 5))
    assert h.pop().name == "first"
    assert h.pop().name == "second"
