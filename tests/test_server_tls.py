"""TLS serving + internal cert management (pkg/util/cert behaviors:
self-signed CA signing a rotated serving cert; cmd/kueue/main.go:154-179
secure serving with hot cert reload)."""

import datetime as dt
import ssl

import pytest

# the whole module exercises cert generation/rotation; without the
# cryptography package every test would fail at the first CA issue —
# skip them as missing-dependency instead
pytest.importorskip("cryptography")

from kueue_tpu.models import (  # noqa: E402
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import ResourceGroup
from kueue_tpu.models.workload import PodSet
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.server import KueueClient, KueueServer
from kueue_tpu.server.client import ClientError
from kueue_tpu.utils.cert import (
    CertRotator,
    cert_not_after,
    generate_ca,
    issue_serving_cert,
)


def simple_runtime(cpu="10"):
    rt = ClusterRuntime()
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",), (FlavorQuotas.build("default", {"cpu": cpu}),)
                ),
            ),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    return rt


class TestCertGeneration:
    def test_ca_signs_serving_cert_with_sans(self, tmp_path):
        ca_cert, ca_key = generate_ca(valid_days=100)
        cert, key = issue_serving_cert(
            ca_cert, ca_key, ["localhost", "127.0.0.1", "kueue.kueue-system.svc"]
        )
        from cryptography import x509

        loaded = x509.load_pem_x509_certificate(cert)
        sans = loaded.extensions.get_extension_for_class(
            x509.SubjectAlternativeName
        ).value
        names = {str(v) for v in sans.get_values_for_type(x509.DNSName)}
        assert names == {"localhost", "kueue.kueue-system.svc"}
        ips = {str(v) for v in sans.get_values_for_type(x509.IPAddress)}
        assert ips == {"127.0.0.1"}
        # actually chains to the CA
        ctx = ssl.create_default_context()
        ctx.load_verify_locations(cadata=ca_cert.decode())

    def test_rotator_first_boot_generates_everything(self, tmp_path):
        rot = CertRotator(str(tmp_path / "certs"))
        rot.ensure()
        for p in (rot.ca_path, rot.cert_path, rot.key_path):
            assert open(p, "rb").read().startswith(b"-----BEGIN")
        assert rot.rotations == 1
        # idempotent: a second ensure must not reissue
        rot.ensure()
        assert rot.rotations == 1

    def test_rotation_inside_refresh_window(self, tmp_path):
        now = [dt.datetime.now(dt.timezone.utc)]
        rot = CertRotator(
            str(tmp_path),
            cert_valid_days=90,
            refresh_before_days=30,
            now_fn=lambda: now[0],
        )
        rot.ensure()
        fired = []
        rot.reload_hooks.append(lambda: fired.append(True))
        old_cert = open(rot.cert_path, "rb").read()
        old_ca = open(rot.ca_path, "rb").read()
        assert rot.maybe_rotate() is False  # fresh: nothing to do
        # jump to 61 days out: 29 days of validity left < 30-day window
        now[0] += dt.timedelta(days=61)
        assert rot.maybe_rotate() is True
        new_cert = open(rot.cert_path, "rb").read()
        assert new_cert != old_cert
        assert open(rot.ca_path, "rb").read() == old_ca  # same root
        assert cert_not_after(new_cert) > cert_not_after(old_cert)
        assert fired == [True]

    def test_ca_reroot_two_phase_overlap(self, tmp_path):
        """Re-root is two-phase: the new root ships in a bundle with
        the old one while the old-root-signed serving cert KEEPS
        serving (clients holding the stale ca.crt must not hard-fail at
        the instant of rotation); the serving cert re-signs under the
        new root one refresh window later, before its signer dies."""
        from cryptography import x509

        now = [dt.datetime.now(dt.timezone.utc)]
        rot = CertRotator(
            str(tmp_path),
            ca_valid_days=100,
            cert_valid_days=90,
            refresh_before_days=30,
            now_fn=lambda: now[0],
        )
        rot.ensure()
        old_ca = open(rot.ca_path, "rb").read()
        old_serving = open(rot.cert_path, "rb").read()

        # phase 1: CA has 55 days left (<= 2 windows) -> re-root early,
        # bundle = new + old, serving cert untouched
        now[0] += dt.timedelta(days=45)
        assert rot.maybe_rotate() is True
        bundle = open(rot.ca_path, "rb").read()
        assert bundle != old_ca
        assert old_ca.strip() in bundle  # overlap: old root still trusted
        assert open(rot.cert_path, "rb").read() == old_serving

        # phase 2: the old root (the serving cert's signer) is now one
        # window from expiry -> re-sign under the bundle's new root
        now[0] += dt.timedelta(days=26)  # old root: 29 days left
        assert rot.maybe_rotate() is True
        new_root = x509.load_pem_x509_certificate(bundle)
        serving = x509.load_pem_x509_certificate(
            open(rot.cert_path, "rb").read()
        )
        aki = serving.extensions.get_extension_for_class(
            x509.AuthorityKeyIdentifier
        ).value.key_identifier
        ski = new_root.extensions.get_extension_for_class(
            x509.SubjectKeyIdentifier
        ).value.digest
        assert aki == ski  # chained to the NEW root now

        # next re-root keeps only {newest, previous} — no unbounded tail
        now[0] += dt.timedelta(days=3650)
        rot.maybe_rotate()
        assert open(rot.ca_path, "rb").read().count(b"-----BEGIN CERT") == 2


class TestTLSServing:
    def test_client_verifies_against_rotator_ca(self, tmp_path):
        rot = CertRotator(str(tmp_path))
        srv = KueueServer(runtime=simple_runtime(), tls=rot)
        port = srv.start()
        try:
            client = KueueClient(
                f"https://127.0.0.1:{port}", ca_cert=rot.ca_path
            )
            assert client.healthz()["status"] == "ok"
            # a full write round trip over the wire
            from kueue_tpu import serialization as ser

            wl = Workload(
                namespace="ns", name="tls-wl", queue_name="lq",
                pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
            )
            client.apply("workloads", ser.workload_to_dict(wl))
            assert client.get_workload("ns", "tls-wl")["name"] == "tls-wl"
        finally:
            srv.stop()

    def test_untrusted_client_rejected(self, tmp_path):
        rot = CertRotator(str(tmp_path))
        srv = KueueServer(runtime=simple_runtime(), tls=rot)
        port = srv.start()
        try:
            # default trust store does not contain our self-signed CA
            with pytest.raises((ssl.SSLError, OSError)):
                KueueClient(f"https://127.0.0.1:{port}").healthz()
            # insecure mode (tests-only escape hatch) connects anyway
            insecure = KueueClient(
                f"https://127.0.0.1:{port}", insecure=True
            )
            assert insecure.healthz()["status"] == "ok"
        finally:
            srv.stop()

    def test_rotation_hot_reloads_live_server(self, tmp_path):
        rot = CertRotator(
            str(tmp_path), cert_valid_days=90, refresh_before_days=30
        )
        srv = KueueServer(runtime=simple_runtime(), tls=rot)
        port = srv.start()
        try:
            client = KueueClient(
                f"https://127.0.0.1:{port}", ca_cert=rot.ca_path
            )
            assert client.healthz()["status"] == "ok"
            before = rot.rotations
            # pull the cert into the refresh window under the REAL
            # clock (a fake-future clock would stamp a not-yet-valid
            # cert and break the live handshake this test is about)
            rot.refresh_before = dt.timedelta(days=91)
            assert rot.maybe_rotate() is True
            assert rot.rotations == before + 1
            # new handshakes get the rotated cert (same CA) with no
            # restart: the reload hook refreshed the live SSLContext
            assert client.healthz()["status"] == "ok"
            peer = ssl.get_server_certificate(("127.0.0.1", port))
            from cryptography import x509

            assert x509.load_pem_x509_certificate(
                peer.encode()
            ).serial_number == x509.load_pem_x509_certificate(
                open(rot.cert_path, "rb").read()
            ).serial_number
        finally:
            srv.stop()

    def test_provided_cert_pair_mode(self, tmp_path):
        # cmd/kueue/main.go:161-168 — certs provided, no rotator
        ca_cert, ca_key = generate_ca()
        cert, key = issue_serving_cert(ca_cert, ca_key, ["127.0.0.1"])
        cert_p, key_p, ca_p = (
            tmp_path / "tls.crt", tmp_path / "tls.key", tmp_path / "ca.crt"
        )
        cert_p.write_bytes(cert)
        key_p.write_bytes(key)
        ca_p.write_bytes(ca_cert)
        srv = KueueServer(
            runtime=simple_runtime(), tls=(str(cert_p), str(key_p))
        )
        port = srv.start()
        try:
            client = KueueClient(
                f"https://127.0.0.1:{port}", ca_cert=str(ca_p)
            )
            assert client.healthz()["status"] == "ok"
        finally:
            srv.stop()

    def test_auth_token_composes_with_tls(self, tmp_path):
        rot = CertRotator(str(tmp_path))
        srv = KueueServer(
            runtime=simple_runtime(), tls=rot, auth_token="s3cret"
        )
        port = srv.start()
        try:
            anon = KueueClient(
                f"https://127.0.0.1:{port}", ca_cert=rot.ca_path
            )
            with pytest.raises(ClientError) as ei:
                anon.metrics_text()
            assert ei.value.status == 401
            authed = KueueClient(
                f"https://127.0.0.1:{port}",
                ca_cert=rot.ca_path,
                token="s3cret",
            )
            assert "kueue" in authed.metrics_text()
        finally:
            srv.stop()


class TestMultiKueueOverTLS:
    def test_dispatch_to_https_worker(self, tmp_path):
        """MultiKueue over a TLS wire: the worker control plane serves
        https, the manager's transport verifies its CA (the multikueue
        kubeconfig's certificate-authority)."""
        from kueue_tpu.admissionchecks.multikueue import (
            MultiKueueCluster,
            MultiKueueConfig,
            MultiKueueController,
        )
        from kueue_tpu.admissionchecks.multikueue_transport import (
            ORIGIN_LABEL,
            HTTPTransport,
        )
        from kueue_tpu.models import AdmissionCheck
        from kueue_tpu.models.constants import (
            MULTIKUEUE_CONTROLLER_NAME,
            AdmissionCheckStateType,
        )

        rot = CertRotator(str(tmp_path))
        worker_rt = simple_runtime()
        srv = KueueServer(runtime=worker_rt, tls=rot)
        port = srv.start()
        try:
            rt = simple_runtime()
            rt.add_admission_check(
                AdmissionCheck(
                    name="mk",
                    controller_name=MULTIKUEUE_CONTROLLER_NAME,
                    parameters="cfg",
                )
            )
            cq = rt.cache.cluster_queues["cq"].model
            rt.add_cluster_queue(
                ClusterQueue(
                    name="cq", namespace_selector={},
                    resource_groups=cq.resource_groups,
                    admission_checks=("mk",),
                )
            )
            cluster = MultiKueueCluster(
                name="tls-worker",
                transport=HTTPTransport(
                    f"https://127.0.0.1:{port}", ca_cert=rot.ca_path
                ),
            )
            ctrl = MultiKueueController(
                rt,
                clusters={"tls-worker": cluster},
                configs={
                    "cfg": MultiKueueConfig(
                        name="cfg", clusters=("tls-worker",)
                    )
                },
            )
            rt.admission_check_controllers.append(ctrl)
            wl = Workload(
                namespace="ns", name="tls-job", queue_name="lq",
                pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
            )
            rt.add_workload(wl)
            for _ in range(6):
                rt.run_until_idle()
            assert wl.key in worker_rt.workloads
            assert worker_rt.workloads[wl.key].labels[ORIGIN_LABEL] == "local"
            assert (
                wl.admission_check_states["mk"].state
                == AdmissionCheckStateType.READY
            )
            assert wl.is_admitted
        finally:
            srv.stop()


class TestTLSAcceptLoopResilience:
    def test_stalled_client_does_not_block_server(self, tmp_path):
        """A client that connects and never speaks must not wedge the
        accept loop: the handshake runs lazily in the per-request
        worker thread with a bounded timeout, so probes keep serving."""
        import socket
        import time

        rot = CertRotator(str(tmp_path))
        srv = KueueServer(runtime=simple_runtime(), tls=rot)
        port = srv.start()
        stalled = []
        try:
            # several silent TCP connections held open
            for _ in range(3):
                s = socket.create_connection(("127.0.0.1", port), timeout=5)
                stalled.append(s)
            time.sleep(0.2)
            client = KueueClient(
                f"https://127.0.0.1:{port}", ca_cert=rot.ca_path, timeout=10
            )
            t0 = time.monotonic()
            assert client.healthz()["status"] == "ok"
            assert time.monotonic() - t0 < 5.0
        finally:
            for s in stalled:
                s.close()
            srv.stop()
