"""Gray-failure immunity (PR 20): the latency-aware health plane
(federation/health.py), the network chaos layer (testing/chaos.py),
adaptive per-call deadlines, hedged dispatch, health-aware scheduling,
and the acceptance properties — a limping worker answering just under
the old fixed deadline cannot drag the federation down, and hedging x
asymmetric loss x crash recovery still converge to exactly one
admission per workload."""

import math
import random
import threading

import numpy as np
import pytest

from kueue_tpu.admissionchecks.multikueue import MultiKueueCluster
from kueue_tpu.admissionchecks.multikueue_transport import (
    ClusterUnreachable,
    InProcessTransport,
    RemoteClient,
    TransportError,
)
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.federation import FederationDispatcher
from kueue_tpu.federation.health import (
    DEGRADED,
    HEALTHY,
    LOST,
    HealthPlane,
)
from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import ResourceGroup
from kueue_tpu.models.workload import PodSet
from kueue_tpu.ops.global_kernel import rescore_pairs
from kueue_tpu.ops.global_np import rescore_np
from kueue_tpu.storage.journal import Journal
from kueue_tpu.storage.recovery import recover
from kueue_tpu.testing import faults
from kueue_tpu.testing.chaos import (
    AsymmetricLossTransport,
    LatencyTransport,
    RecordingTransport,
    SlowDripTransport,
    flapping_schedule,
)
from kueue_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---- shared harness (mirrors tests/test_federation.py) ----
def build_worker(clock, cpu="10"):
    rt = ClusterRuntime(clock=clock)
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",), (FlavorQuotas.build("default", {"cpu": cpu}),)
                ),
            ),
        )
    )
    rt.add_local_queue(
        LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
    )
    return rt


def wl(name, cpu="1", **kw):
    return Workload(
        namespace="ns", name=name, queue_name="lq",
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),), **kw,
    )


def federation(
    tmp_path=None,
    n_workers=2,
    clock=None,
    worker_cpu="10",
    chaos=None,  # {worker_name: transport_wrapper(inner, clock)}
    **disp_kw,
):
    """Federation harness with a chaos hook: ``chaos`` wraps the named
    workers' in-process transports in the given chaos transports."""
    clock = clock or FakeClock(0.0)
    chaos = chaos or {}
    workers = {}
    clusters = {}
    for i in range(n_workers):
        name = f"w{i + 1}"
        rt = build_worker(clock, cpu=worker_cpu)
        workers[name] = rt
        transport = InProcessTransport(rt)
        if name in chaos:
            transport = chaos[name](transport, clock)
        clusters[name] = MultiKueueCluster(name=name, transport=transport)
    mgr = ClusterRuntime(clock=clock)
    journal = None
    if tmp_path is not None:
        journal = Journal(
            str(tmp_path / "mgr-journal"), fsync_policy="never"
        ).open()
        mgr.attach_journal(journal)
    disp_kw.setdefault("worker_lost_timeout", 20.0)
    disp_kw.setdefault("max_backoff_s", 8.0)
    disp_kw.setdefault("drive_inprocess", True)
    disp = FederationDispatcher(mgr, clusters=clusters, **disp_kw)
    return mgr, disp, workers, clock, journal


def drive(mgr, clock, passes=6, advance=10.0):
    for _ in range(passes):
        mgr.run_until_idle()
        clock.advance(advance)
    mgr.run_until_idle()


def holders(workers, key):
    return sorted(n for n, rt in workers.items() if key in rt.workloads)


def assert_converged(mgr, workers, keys):
    """Exactly one admission per workload, every plane sound."""
    admitted = {k for k, w in mgr.workloads.items() if w.is_admitted}
    assert admitted == set(keys), (
        f"federated admitted set {sorted(admitted)} != {sorted(keys)}"
    )
    for key in keys:
        hold = holders(workers, key)
        assert len(hold) == 1, f"{key}: copies on {hold} (expected one)"
        rwl = workers[hold[0]].workloads[key]
        assert rwl.has_quota_reservation, f"{key}: copy not reserving"
    assert mgr.check_invariants() == []
    for name, rt in workers.items():
        assert rt.check_invariants() == [], f"worker {name}"


# ---- health plane state machine ----
class TestHealthPlane:
    def plane(self, clock=None, **kw):
        return HealthPlane(clock or FakeClock(0.0), **kw)

    def test_healthy_until_min_samples(self):
        hp = self.plane()
        hp.observe_rtt("w1", 9.0)
        hp.observe_rtt("w1", 9.0)
        assert hp.state("w1") == HEALTHY  # 2 < degrade_min_samples
        hp.observe_rtt("w1", 9.0)
        assert hp.state("w1") == DEGRADED

    def test_degrade_on_error_rate(self):
        hp = self.plane()
        hp.observe_rtt("w1", 0.01)
        hp.observe_error("w1")
        hp.observe_error("w1")
        # 2/3 failures >= 0.5 threshold
        assert hp.state("w1") == DEGRADED

    def test_probation_clears_after_hold_with_clean_window(self):
        clock = FakeClock(0.0)
        hp = self.plane(clock, window=4, probation_hold_s=30.0)
        for _ in range(4):
            hp.observe_rtt("w1", 9.0)
        assert hp.state("w1") == DEGRADED
        # clean samples flush the window, but the hold still gates
        for _ in range(4):
            hp.observe_rtt("w1", 0.01)
        assert hp.state("w1") == DEGRADED
        clock.advance(31.0)
        hp.observe_rtt("w1", 0.01)
        assert hp.state("w1") == HEALTHY

    def test_lost_on_error_streak_recovers_via_probation(self):
        clock = FakeClock(0.0)
        hp = self.plane(clock, lost_error_streak=4)
        for _ in range(4):
            hp.observe_error("w1")
        assert hp.state("w1") == LOST
        # first success re-enters DEGRADED (probation), never HEALTHY
        hp.observe_rtt("w1", 0.01)
        assert hp.state("w1") == DEGRADED

    def test_heartbeat_slack_breach_degrades_idle_worker(self):
        clock = FakeClock(0.0)
        hp = self.plane(
            clock, heartbeat_interval_s=10.0, slack_factor=3.0
        )
        hp.observe_rtt("w1", 0.01)
        assert hp.state("w1") == HEALTHY
        clock.advance(31.0)  # > 3 * 10s without contact
        assert hp.state("w1") == DEGRADED

    def test_flapping_extends_probation_hold(self):
        def flap_once(hp, clock):
            # breach -> degraded, then clean window + hold -> healthy
            for _ in range(4):
                hp.observe_rtt("w1", 9.0)
            assert hp.state("w1") == DEGRADED
            for _ in range(4):
                hp.observe_rtt("w1", 0.01)
            clock.advance(11.0)
            hp.observe_rtt("w1", 0.01)
            assert hp.state("w1") == HEALTHY

        # each flap cycle costs two transitions (enter + leave
        # probation); threshold 5 lets two full cycles recover at the
        # base hold, and the THIRD degradation trips flap detection
        kw = dict(
            window=4, probation_hold_s=10.0, flap_window_s=10_000.0,
            flap_threshold=5, flap_extend_factor=4.0,
        )
        # worker A: one flap cycle, recovery at the base hold
        clock_a = FakeClock(0.0)
        a = self.plane(clock_a, **kw)
        flap_once(a, clock_a)

        # worker B: two flap cycles, then the third degradation holds
        # past the base hold (flap detection extended it 4x)
        clock_b = FakeClock(0.0)
        b = self.plane(clock_b, **kw)
        for _ in range(2):
            flap_once(b, clock_b)
        for _ in range(4):
            b.observe_rtt("w1", 9.0)
        assert b.state("w1") == DEGRADED
        for _ in range(4):
            b.observe_rtt("w1", 0.01)
        clock_b.advance(11.0)  # base hold elapsed — NOT enough now
        b.observe_rtt("w1", 0.01)
        assert b.state("w1") == DEGRADED
        clock_b.advance(40.0)  # the extended (4x) hold elapses
        b.observe_rtt("w1", 0.01)
        assert b.state("w1") == HEALTHY

    def test_adaptive_deadline_clamp(self):
        hp = self.plane(
            deadline_k=3.0, deadline_floor_s=1.0, deadline_cap_s=10.0
        )
        # no samples: the conservative full cap
        assert hp.deadline_s("w1") == 10.0
        for _ in range(8):
            hp.observe_rtt("w1", 0.05)
        # 3 * 0.05 < floor -> floor
        assert hp.deadline_s("w1") == 1.0
        for _ in range(64):
            hp.observe_rtt("w2", 1.0)
        # 3 * 1.0 in band -> k * p99
        assert hp.deadline_s("w2") == pytest.approx(3.0)
        for _ in range(8):
            hp.observe_rtt("w3", 9.0)
        assert hp.deadline_s("w3") == 10.0  # capped
        # per-call cap override (heartbeat probes)
        assert hp.deadline_s("w3", cap_s=2.0) == 2.0

    def test_hedge_delay_gated_on_samples_and_budget(self):
        hp = self.plane(hedge_min_samples=4, hedge_budget=0.05)
        assert hp.hedge_delay_s("w1") is None
        for _ in range(4):
            hp.observe_rtt("w1", 0.5)
        assert hp.hedge_delay_s("w1") == pytest.approx(0.5)
        # exhaust the fleet-wide budget: 5 hedges over 100 calls
        for _ in range(100):
            hp.record_call()
        for _ in range(5):
            hp.record_hedge()
        assert hp.hedge_delay_s("w1") is None
        assert hp.hedge_rate() == pytest.approx(0.05)

    def test_snapshot_zero_materialized(self):
        hp = self.plane()
        snap = hp.snapshot("never-seen")
        assert snap == {
            "state": HEALTHY, "ewmaRtt": 0.0, "rttP50": 0.0,
            "rttP95": 0.0, "rttP99": 0.0, "errorRate": 0.0,
            "samples": 0,
        }


# ---- chaos transports ----
class _StubInner:
    """Innermost transport stub: counts calls, returns a sentinel."""

    runtime = None
    deadline_s = None

    def __init__(self):
        self.calls = []

    def get_workload(self, key):
        self.calls.append(("get_workload", key))
        return "remote-copy"

    def delete_workload(self, key):
        self.calls.append(("delete_workload", key))


class TestChaosTransports:
    def test_latency_under_deadline_advances_clock_and_forwards(self):
        clock = FakeClock(0.0)
        inner = _StubInner()
        t = LatencyTransport(inner, clock, delay_s=3.0)
        assert t.get_workload("k") == "remote-copy"
        assert clock.now() == pytest.approx(3.0)
        assert inner.calls == [("get_workload", "k")]
        assert faults.fired("chaos.latency") == 0  # unarmed: free

    def test_latency_request_timeout_never_reaches_worker(self):
        clock = FakeClock(0.0)
        inner = _StubInner()
        t = LatencyTransport(inner, clock, delay_s=12.0)  # default 10s
        with pytest.raises(TransportError):
            t.get_workload("k")
        assert inner.calls == []  # dropped before the worker
        assert clock.now() == pytest.approx(10.0)  # full deadline burned
        assert t.timeouts == 1

    def test_latency_response_timeout_lands_then_raises(self):
        clock = FakeClock(0.0)
        inner = _StubInner()
        t = LatencyTransport(
            inner, clock, delay_s=12.0, direction="response"
        )
        with pytest.raises(TransportError):
            t.delete_workload("k")
        # the mutation LANDED; only the ack was lost
        assert inner.calls == [("delete_workload", "k")]

    def test_latency_tracks_threaded_deadline_fraction(self):
        clock = FakeClock(0.0)
        t = LatencyTransport(
            _StubInner(), clock, deadline_fraction=0.99
        )
        t.deadline_s = 4.0  # what RemoteClient._invoke does per-call
        t.get_workload("k")
        assert clock.now() == pytest.approx(3.96)
        t.deadline_s = None  # back to the constructor default
        t.get_workload("k")
        assert clock.now() == pytest.approx(3.96 + 9.9)

    def test_slow_drip_progresses_to_timeout(self):
        clock = FakeClock(0.0)
        inner = _StubInner()
        t = SlowDripTransport(
            inner, clock, step_s=4.0, default_deadline_s=10.0
        )
        t.get_workload("a")  # 0s
        t.get_workload("b")  # 4s
        t.get_workload("c")  # 8s
        assert clock.now() == pytest.approx(12.0)
        with pytest.raises(TransportError):
            t.get_workload("d")  # 12s >= 10s deadline
        assert len(inner.calls) == 3

    def test_slow_drip_max_caps_the_drip(self):
        clock = FakeClock(0.0)
        t = SlowDripTransport(_StubInner(), clock, step_s=4.0, max_s=6.0)
        for key in "abcdef":
            t.get_workload(key)
        assert t.timeouts == 0  # capped under the deadline forever

    def test_asymmetric_loss_response_lands_then_drops(self):
        clock = FakeClock(0.0)
        inner = _StubInner()
        t = AsymmetricLossTransport(inner, clock, direction="response")
        with pytest.raises(TransportError):
            t.delete_workload("k")
        assert inner.calls == [("delete_workload", "k")]
        assert t.dropped == 1
        assert clock.now() == pytest.approx(10.0)

    def test_asymmetric_loss_request_never_lands(self):
        clock = FakeClock(0.0)
        inner = _StubInner()
        t = AsymmetricLossTransport(inner, clock, direction="request")
        with pytest.raises(TransportError):
            t.get_workload("k")
        assert inner.calls == []

    def test_asymmetric_loss_probabilistic(self):
        clock = FakeClock(0.0)
        inner = _StubInner()
        t = AsymmetricLossTransport(
            inner, clock, p=0.5, rng=random.Random(7)
        )
        outcomes = []
        for i in range(20):
            try:
                t.get_workload(str(i))
                outcomes.append(True)
            except TransportError:
                outcomes.append(False)
        assert any(outcomes) and not all(outcomes)
        assert t.dropped == outcomes.count(False)

    def test_flapping_schedule_duty_cycle(self):
        sched = flapping_schedule(5.0, period_s=10.0, duty=0.3)
        assert sched(0.0) == 5.0
        assert sched(2.9) == 5.0
        assert sched(3.1) == 0.0
        assert sched(12.0) == 5.0  # next period's bad window

    def test_recording_transport_sees_injected_delay(self):
        clock = FakeClock(0.0)
        sink = []
        t = RecordingTransport(
            LatencyTransport(_StubInner(), clock, delay_s=2.5),
            clock,
            sink=sink,
        )
        t.get_workload("k")
        with pytest.raises(TransportError):
            # shrink the threaded deadline below the delay
            t.deadline_s = 1.0
            t.get_workload("k")
        # both the success (2.5s) and the timeout (1.0s) are recorded
        assert sink == [pytest.approx(2.5), pytest.approx(1.0)]

    def test_chaos_fault_points_armable(self):
        clock = FakeClock(0.0)
        t = AsymmetricLossTransport(
            _StubInner(), clock, direction="response"
        )
        faults.arm("chaos.drop_response", action="crash")
        with pytest.raises(faults.InjectedCrash):
            t.delete_workload("k")


# ---- adaptive deadline threading ----
class _DeadlineProbe:
    """Transport wrapper recording the threaded per-call deadline."""

    def __init__(self, inner, clock=None):
        self.inner = inner
        self.seen = []

    @property
    def runtime(self):
        return self.inner.runtime

    @property
    def deadline_s(self):
        return getattr(self.inner, "deadline_s", None)

    @deadline_s.setter
    def deadline_s(self, value):
        self.inner.deadline_s = value

    def __getattr__(self, name):
        fn = getattr(self.inner, name)

        def wrapped(*args):
            self.seen.append((name, self.deadline_s))
            return fn(*args)

        return wrapped


class TestAdaptiveDeadlines:
    def test_fixed_mode_threads_no_deadline(self):
        probe = {}

        def wrap(inner, clock):
            probe["t"] = _DeadlineProbe(inner)
            return probe["t"]

        mgr, disp, workers, clock, _ = federation(
            n_workers=1, chaos={"w1": wrap}, adaptive_deadlines=False,
            hedging=False,
        )
        mgr.add_workload(wl("fixed"))
        drive(mgr, clock, passes=2)
        assert probe["t"].seen, "no wire exchanges happened"
        assert all(d is None for _op, d in probe["t"].seen), (
            "fixed-timeout baseline must ride the transport default"
        )

    def test_adaptive_mode_threads_clamped_deadline(self):
        probe = {}

        def wrap(inner, clock):
            probe["t"] = _DeadlineProbe(inner)
            return probe["t"]

        mgr, disp, workers, clock, _ = federation(
            n_workers=1, chaos={"w1": wrap}, hedging=False,
        )
        # seed the health plane below the floor: deadline clamps there
        for _ in range(8):
            disp.worker_health.observe_rtt("w1", 0.01)
        mgr.add_workload(wl("adaptive"))
        drive(mgr, clock, passes=2)
        deadlines = [d for _op, d in probe["t"].seen if d is not None]
        assert deadlines, "adaptive deadlines never threaded"
        assert all(d <= 2.0 for d in deadlines), (
            f"expected floor/probe-cap deadlines, saw {deadlines}"
        )

    def test_heartbeat_probe_uses_probe_cap(self):
        probe = {}

        def wrap(inner, clock):
            probe["t"] = _DeadlineProbe(inner)
            return probe["t"]

        mgr, disp, workers, clock, _ = federation(
            n_workers=1, chaos={"w1": wrap}, hedging=False,
            probe_deadline_s=2.0,
        )
        # plenty of slow-but-healthy samples: full deadline would be 10
        for _ in range(8):
            disp.worker_health.observe_rtt("w1", 4.0)
        mgr.run_until_idle()
        clock.advance(31.0)  # past the heartbeat interval
        probe["t"].seen.clear()
        mgr.run_until_idle()
        beats = [
            d for op, d in probe["t"].seen if op == "list_workload_keys"
        ]
        assert beats and all(d == 2.0 for d in beats), (
            f"heartbeat probes must be capped at probe_deadline_s: {beats}"
        )


# ---- non-blocking heartbeats (satellite: step never stalls) ----
class TestHeartbeatBudget:
    def test_black_holed_worker_costs_at_most_probe_deadline(self):
        """Regression: a black-holed worker used to burn the full 10 s
        transport timeout inside EVERY step's heartbeat sweep. Probes
        are now capped at probe_deadline_s and budgeted per step."""
        mgr, disp, workers, clock, _ = federation(
            n_workers=3,
            chaos={
                "w3": lambda inner, clock: LatencyTransport(
                    inner, clock, delay_s=1e9
                )
            },
            probe_deadline_s=2.0,
            heartbeat_probe_budget=1,
        )
        w = wl("job-a")
        mgr.add_workload(w)
        drive(mgr, clock, passes=4)  # dispatch + detect the black hole
        assert not disp.clusters["w3"].client.active
        # steady state: one heartbeat sweep with the black hole in
        # backoff-elapsed state costs at most ONE probe deadline
        clock.advance(31.0)
        t0 = clock.now()
        mgr.run_until_idle()
        cost = clock.now() - t0
        assert cost <= 2.0 + 1e-9, (
            f"heartbeat sweep burned {cost:.1f}s of step time"
        )
        # the healthy workers still converged the dispatch
        assert w.is_admitted

    def test_probe_budget_zero_skips_lost_worker_probes(self):
        mgr, disp, workers, clock, _ = federation(
            n_workers=2,
            chaos={
                "w2": lambda inner, clock: LatencyTransport(
                    inner, clock, delay_s=1e9
                )
            },
            probe_deadline_s=2.0,
            heartbeat_probe_budget=0,
        )
        # probation keeps every dispatch (and so every retraction) off
        # w2; mark it lost so the only possible w2 wire exchange left
        # is a heartbeat reconnect probe
        for _ in range(8):
            disp.worker_health.observe_rtt("w2", 9.0)
        disp.clusters["w2"].mark_lost(clock.now())
        w = wl("job-a")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        assert w.is_admitted
        assert not disp.clusters["w2"].client.active
        clock.advance(31.0)
        t0 = clock.now()
        mgr.run_until_idle()
        assert clock.now() - t0 == pytest.approx(0.0), (
            "budget=0 must skip reconnect probes entirely"
        )


# ---- hedged dispatch ----
class _ScriptedTransport:
    """Succeeds iff the threaded deadline is >= ``needs_s``."""

    runtime = None
    deadline_s = None

    def __init__(self, needs_s):
        self.needs_s = needs_s
        self.attempts = []

    def get_workload(self, key):
        self.attempts.append(self.deadline_s)
        d = 10.0 if self.deadline_s is None else self.deadline_s
        if d < self.needs_s:
            raise TransportError(f"deadline {d} < needs {self.needs_s}")
        return "remote-copy"


class TestHedging:
    def client(self, transport):
        return RemoteClient(transport, FakeClock(0.0))

    def test_backup_wins_after_primary_misses_hedge_delay(self):
        t = _ScriptedTransport(needs_s=3.0)
        c = self.client(t)
        out = c.call("get_workload", "k", deadline_s=5.0, hedge_delay_s=1.0)
        assert out == "remote-copy"
        assert t.attempts == [1.0, 5.0]  # primary bounded, backup full
        assert c.last_hedge == "won"
        # the missed hedge delay is NOT charged to connectivity
        assert c.active and c.failed_attempts == 0
        assert faults.fired("multikueue.hedge") == 0  # unarmed: free

    def test_backup_failure_is_the_calls_verdict(self):
        t = _ScriptedTransport(needs_s=30.0)  # hopeless
        c = self.client(t)
        with pytest.raises(ClusterUnreachable):
            c.call("get_workload", "k", deadline_s=5.0, hedge_delay_s=1.0)
        assert c.last_hedge == "lost"
        assert c.failed_attempts == 1  # charged exactly once

    def test_no_hedge_delay_no_backup(self):
        t = _ScriptedTransport(needs_s=30.0)
        c = self.client(t)
        with pytest.raises(ClusterUnreachable):
            c.call("get_workload", "k", deadline_s=5.0)
        assert t.attempts == [5.0]
        assert c.last_hedge is None

    def test_dispatcher_hedges_and_stays_in_budget(self):
        """End-to-end: a worker whose exchanges run just past the p95
        hedge delay (but inside the adaptive deadline) triggers hedges
        through the dispatcher; the accounting lands in the health
        plane and stays within the budget assertion's reach."""
        mgr, disp, workers, clock, _ = federation(
            n_workers=1,
            chaos={
                "w1": lambda inner, clock: LatencyTransport(
                    inner, clock, delay_s=1.0
                )
            },
        )
        # seed: p95=0.5 -> hedge delay 0.5 (missed by the 1.0s limp),
        # p99=0.5 -> deadline clamp(1.5, 1, 10)=1.5 (backup succeeds)
        for _ in range(8):
            disp.worker_health.observe_rtt("w1", 0.5)
        w = wl("hedged")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3, advance=0.0)
        assert w.is_admitted
        assert disp.worker_health.hedges_total > 0
        assert disp.worker_health.hedge_rate() <= 0.5  # sane accounting


# ---- health-aware scheduling ----
class TestHealthAwareScheduling:
    def test_probation_excludes_worker_from_new_dispatches(self):
        mgr, disp, workers, clock, _ = federation(n_workers=3)
        for _ in range(8):
            disp.worker_health.observe_rtt("w2", 9.0)
        assert disp.worker_health.state("w2") == DEGRADED
        keys = []
        for i in range(4):
            w = wl(f"job-{i}")
            keys.append(w.key)
            mgr.add_workload(w)
        drive(mgr, clock, passes=4)
        assert_converged(mgr, workers, keys)
        assert not workers["w2"].workloads, (
            "probation worker received new dispatches"
        )

    def test_all_degraded_falls_back_to_dispatching(self):
        """A slow federation beats a stalled one: when probation would
        empty the fleet, degraded workers stay in rotation."""
        mgr, disp, workers, clock, _ = federation(n_workers=2)
        for name in workers:
            for _ in range(8):
                disp.worker_health.observe_rtt(name, 9.0)
        w = wl("still-runs")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        assert w.is_admitted

    def test_probation_keeps_syncing_existing_placements(self):
        mgr, disp, workers, clock, _ = federation(n_workers=2)
        w = wl("placed-then-gray")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        winner = disp.states[w.key].winner
        # the winner limps AFTER placement: probation, not retraction
        for _ in range(8):
            disp.worker_health.observe_rtt(winner, 9.0)
        drive(mgr, clock, passes=2)
        assert_converged(mgr, workers, [w.key])
        assert holders(workers, w.key) == [winner], (
            "probation must keep existing placements, not retract them"
        )

    def test_rescore_degraded_penalty_device_matches_numpy(self):
        rng = np.random.default_rng(20)
        for _ in range(10):
            w = int(rng.integers(1, 12))
            c = int(rng.integers(2, 9))
            tta = rng.integers(0, 10_000, size=(w, c)).astype(np.int64)
            score = rng.integers(0, 100, size=(w, c)).astype(np.int64)
            valid = rng.random((w, c)) > 0.2
            current = rng.integers(-1, c, size=w).astype(np.int32)
            rotation = rng.integers(0, c, size=w).astype(np.int32)
            degraded = rng.random(c) > 0.5
            dev = rescore_pairs(
                tta, score, valid, current, rotation, 500,
                degraded=degraded, degraded_penalty_ms=120_000,
            )
            ref = rescore_np(
                tta, score, valid, current, rotation, 500,
                degraded=degraded, degraded_penalty_ms=120_000,
            )
            for field in ("best", "best_key", "gain_ms", "rebalance"):
                assert np.array_equal(
                    getattr(dev, field), getattr(ref, field)
                ), field

    def test_rescore_penalty_moves_wins_off_degraded_clusters(self):
        # two clusters, equal forecasts: without the penalty cluster 0
        # wins on rotation; with cluster 0 degraded, cluster 1 wins
        tta = np.array([[100, 100]], dtype=np.int64)
        score = np.zeros((1, 2), dtype=np.int64)
        valid = np.ones((1, 2), dtype=bool)
        current = np.array([-1], dtype=np.int32)
        rotation = np.zeros(1, dtype=np.int32)
        base = rescore_np(tta, score, valid, current, rotation, 0)
        assert base.best[0] == 0
        shifted = rescore_np(
            tta, score, valid, current, rotation, 0,
            degraded=np.array([True, False]),
            degraded_penalty_ms=120_000,
        )
        assert shifted.best[0] == 1

    def test_rescore_penalty_omitted_is_all_healthy(self):
        rng = np.random.default_rng(7)
        tta = rng.integers(0, 1000, size=(4, 3)).astype(np.int64)
        score = rng.integers(0, 10, size=(4, 3)).astype(np.int64)
        valid = np.ones((4, 3), dtype=bool)
        current = np.array([-1, 0, 1, 2], dtype=np.int32)
        rotation = np.zeros(4, dtype=np.int32)
        a = rescore_np(tta, score, valid, current, rotation, 100)
        b = rescore_np(
            tta, score, valid, current, rotation, 100,
            degraded=np.zeros(3, dtype=bool), degraded_penalty_ms=120_000,
        )
        for field in ("best", "best_key", "gain_ms", "rebalance"):
            assert np.array_equal(getattr(a, field), getattr(b, field))


# ---- backoff jitter + probe cap (satellite: property tests) ----
class TestBackoffProperties:
    def test_backoff_windows_respect_jitter_bounds(self):
        """Property: after the n-th consecutive failure the wait is in
        [min(cap, b*2^(n-1)), min(cap, b*2^(n-1)) * (1 + jitter))."""
        for seed in range(40):
            clock = FakeClock(1000.0)
            c = RemoteClient(
                _ScriptedTransport(needs_s=0.0), clock,
                base_backoff_s=1.0, max_backoff_s=300.0, jitter=0.1,
                rng=random.Random(seed),
            )
            for n in range(1, 13):
                c._record_failure()
                delay = c.next_retry_at - clock.now()
                lo = min(300.0, 1.0 * 2 ** (n - 1))
                hi = lo * 1.1
                assert lo <= delay < hi, (
                    f"seed={seed} n={n}: {delay} not in [{lo}, {hi})"
                )

    def test_zero_jitter_is_exact_exponential(self):
        clock = FakeClock(0.0)
        c = RemoteClient(
            _ScriptedTransport(needs_s=0.0), clock,
            base_backoff_s=2.0, max_backoff_s=100.0, jitter=0.0,
        )
        expected = [2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 100.0, 100.0]
        for want in expected:
            c._record_failure()
            assert c.next_retry_at - clock.now() == pytest.approx(want)

    def test_single_reconnect_probe_under_concurrent_callers(self):
        clock = FakeClock(0.0)
        entered = threading.Event()
        release = threading.Event()

        class _Blocking:
            runtime = None
            deadline_s = None

            def get_workload(self, key):
                entered.set()
                assert release.wait(timeout=10.0)
                return "ok"

        c = RemoteClient(_Blocking(), clock, max_inflight_probes=1)
        c._record_failure()  # lost; backoff from t=0
        clock.advance(100.0)  # backoff elapsed: next call is the probe
        results = []
        t = threading.Thread(
            target=lambda: results.append(c.call("get_workload", "k"))
        )
        t.start()
        assert entered.wait(timeout=10.0)
        # the probe slot is held: every concurrent caller is refused
        for _ in range(3):
            with pytest.raises(ClusterUnreachable) as ei:
                c.call("get_workload", "k")
            assert "probe already in flight" in str(ei.value)
        release.set()
        t.join(timeout=10.0)
        assert results == ["ok"]
        assert c.active  # probe success restored the cluster
        # slot released: a fresh loss allows a fresh probe
        c._record_failure()
        clock.advance(100.0)
        release.set()
        assert c.call("get_workload", "k") == "ok"

    def test_probe_cap_scales_with_max_inflight(self):
        clock = FakeClock(0.0)
        gate = threading.Event()
        entered = threading.Semaphore(0)

        class _Blocking:
            runtime = None
            deadline_s = None

            def get_workload(self, key):
                entered.release()
                assert gate.wait(timeout=10.0)
                return "ok"

        c = RemoteClient(_Blocking(), clock, max_inflight_probes=2)
        c._record_failure()
        clock.advance(100.0)
        results = []
        threads = [
            threading.Thread(
                target=lambda: results.append(c.call("get_workload", "k"))
            )
            for _ in range(2)
        ]
        for t in threads:
            t.start()
        assert entered.acquire(timeout=10.0)
        assert entered.acquire(timeout=10.0)
        with pytest.raises(ClusterUnreachable):
            c.call("get_workload", "k")  # third concurrent probe refused
        gate.set()
        for t in threads:
            t.join(timeout=10.0)
        assert results == ["ok", "ok"]


# ---- acceptance: the limping worker ----
class TestLimpingWorkerAcceptance:
    def _run(self, limping, adaptive, n_workers=4, n_wl=12):
        chaos = {}
        if limping:
            chaos["w1"] = lambda inner, clock: LatencyTransport(
                inner, clock, deadline_fraction=0.99
            )
        mgr, disp, workers, clock, _ = federation(
            n_workers=n_workers,
            worker_cpu="20",
            chaos=chaos,
            adaptive_deadlines=adaptive,
            hedging=adaptive,
            health_plane_kw=(
                None if adaptive else {"degrade_min_samples": 10**9}
            ),
        )
        keys = []
        for i in range(n_wl):
            w = wl(f"limp-{i:02d}")
            keys.append(w.key)
            mgr.add_workload(w)
        passes = 0
        admitted = set()
        t0 = clock.now()
        for _ in range(30):
            mgr.run_until_idle()
            passes += 1
            admitted = {
                k for k, w in mgr.workloads.items() if w.is_admitted
            }
            if admitted == set(keys):
                break
            clock.advance(5.0)
        assert admitted == set(keys)
        assert_converged(mgr, workers, keys)
        return passes, clock.now() - t0, admitted

    def test_limping_worker_sustains_70pct_of_healthy_rate(self):
        """Acceptance: one worker limping at 0.99x the old fixed
        deadline; with the health plane + adaptive deadlines + hedging
        the federation still admits at >= 70% of the healthy fleet's
        per-pass rate, on the identical admitted set."""
        h_passes, _h_sim, h_admitted = self._run(
            limping=False, adaptive=True
        )
        l_passes, _l_sim, l_admitted = self._run(
            limping=True, adaptive=True
        )
        assert l_admitted == h_admitted
        healthy_rate = len(h_admitted) / h_passes
        limping_rate = len(l_admitted) / l_passes
        assert limping_rate >= 0.7 * healthy_rate, (
            f"limping fleet admitted at {limping_rate:.2f}/pass vs "
            f"healthy {healthy_rate:.2f}/pass"
        )

    def test_immunity_beats_fixed_timeouts_on_wall_cost(self):
        """The A/B the bench publishes, at test scale: the fixed
        10 s-timeout configuration burns far more simulated time on
        the limping wire than the adaptive+probation configuration."""
        _passes_f, sim_fixed, a_fixed = self._run(
            limping=True, adaptive=False
        )
        _passes_a, sim_adaptive, a_adaptive = self._run(
            limping=True, adaptive=True
        )
        assert a_fixed == a_adaptive  # immunity never costs correctness
        assert sim_adaptive < sim_fixed, (
            f"adaptive {sim_adaptive:.1f}s vs fixed {sim_fixed:.1f}s"
        )


# ---- acceptance: exactly-once under hedging x loss x crash ----
def crash_recover_manager(journal, tmp_path, clusters, clock, **disp_kw):
    journal.close()
    mgr2 = ClusterRuntime(clock=clock)
    res = recover(
        None, str(tmp_path / "mgr-journal"), runtime=mgr2, strict=True
    )
    mgr2.attach_journal(res.journal)
    disp_kw.setdefault("worker_lost_timeout", 20.0)
    disp_kw.setdefault("max_backoff_s", 8.0)
    disp_kw.setdefault("drive_inprocess", True)
    disp2 = FederationDispatcher(mgr2, clusters=clusters, **disp_kw)
    return mgr2, disp2, res.journal


class TestExactlyOnceUnderChaos:
    @pytest.mark.parametrize("seed", range(4))
    def test_asymmetric_response_loss_converges_exactly_once(self, seed):
        """Responses from w1 drop 40% of the time: every landed-but-
        unacked mutation must be deduplicated by name+fence (and
        404==ack for retractions) on the retry path."""
        mgr, disp, workers, clock, _ = federation(
            n_workers=2,
            chaos={
                "w1": lambda inner, clock: AsymmetricLossTransport(
                    inner, clock, p=0.4, rng=random.Random(seed)
                )
            },
        )
        keys = []
        for i in range(4):
            w = wl(f"lossy-{i}")
            keys.append(w.key)
            mgr.add_workload(w)
        drive(mgr, clock, passes=12)
        assert_converged(mgr, workers, keys)

    def test_crash_at_hedge_point_recovers_exactly_once(self, tmp_path):
        """The dispatcher dies at the instant a hedge fires (primary
        timed out, backup about to go): recovery must re-dispatch and
        converge to exactly one admission."""
        mgr, disp, workers, clock, journal = federation(
            tmp_path=tmp_path,
            n_workers=2,
            chaos={
                "w1": lambda inner, clock: LatencyTransport(
                    inner, clock, delay_s=1.0
                )
            },
        )
        for _ in range(8):
            disp.worker_health.observe_rtt("w1", 0.5)
        w = wl("hedge-crash")
        mgr.add_workload(w)
        faults.arm("multikueue.hedge", action="crash")
        with pytest.raises(faults.InjectedCrash):
            drive(mgr, clock, passes=3, advance=0.0)
        faults.reset()
        mgr2, disp2, j2 = crash_recover_manager(
            journal, tmp_path, disp.clusters, clock
        )
        drive(mgr2, clock, passes=6)
        assert_converged(mgr2, workers, [w.key])
        j2.close()

    def test_crash_at_drop_response_recovers_exactly_once(self, tmp_path):
        """The hardest window: the mutation LANDED on w1, the response
        was dropped, and the dispatcher crashed before journaling any
        of it. Recovery + (healed network) must converge to exactly
        one admission with no duplicate copy left anywhere."""
        mgr, disp, workers, clock, journal = federation(
            tmp_path=tmp_path,
            n_workers=2,
            chaos={
                "w1": lambda inner, clock: AsymmetricLossTransport(
                    inner, clock, p=1.0
                )
            },
        )
        w = wl("landed-unacked")
        mgr.add_workload(w)
        faults.arm("chaos.drop_response", action="crash")
        with pytest.raises(faults.InjectedCrash):
            drive(mgr, clock, passes=3)
        faults.reset()
        # the network heals across the restart
        chaos_t = disp.clusters["w1"].client.transport
        chaos_t.p = 0.0
        mgr2, disp2, j2 = crash_recover_manager(
            journal, tmp_path, disp.clusters, clock
        )
        drive(mgr2, clock, passes=8)
        assert_converged(mgr2, workers, [w.key])
        j2.close()

    @pytest.mark.parametrize("seed", range(3))
    def test_hedging_under_flap_converges_exactly_once(self, seed):
        """Hedged dispatch against a flapping limper (bad half of every
        window) across seeds: convergence, exactly-once, and the
        fleet-wide hedge accounting stays coherent."""
        def flappy(inner, clock):
            return LatencyTransport(
                inner, clock,
                schedule=flapping_schedule(3.0, period_s=40.0, duty=0.5),
            )

        mgr, disp, workers, clock, _ = federation(
            n_workers=3, chaos={"w1": flappy},
        )
        rng = random.Random(seed)
        keys = []
        for i in range(5):
            w = wl(f"flap-{seed}-{i}", priority=rng.randrange(5))
            keys.append(w.key)
            mgr.add_workload(w)
        drive(mgr, clock, passes=10, advance=7.0)
        assert_converged(mgr, workers, keys)
        hp = disp.worker_health
        assert 0.0 <= hp.hedge_rate() <= 1.0
        assert hp.hedges_total <= hp.calls_total
