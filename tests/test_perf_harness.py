"""Perf harness tests (scaled-down default scenario)."""

from kueue_tpu.perf import DEFAULT_GENERATOR_CONFIG, RangeSpec, check, run
from kueue_tpu.perf.generator import generate


class TestGenerator:
    def test_default_config_shape(self):
        scenario = generate(DEFAULT_GENERATOR_CONFIG)
        assert len(scenario.cluster_queues) == 30  # 5 cohorts x 6 CQs
        assert len(scenario.local_queues) == 30
        assert len(scenario.workloads) == 2500  # 5 x (350+100+50)
        classes = {}
        for gw in scenario.workloads:
            classes[gw.class_name] = classes.get(gw.class_name, 0) + 1
        assert classes == {"small": 1750, "medium": 500, "large": 250}
        # borrowing limits present
        cq = scenario.cluster_queues[0]
        rq = cq.resource_groups[0].flavors[0].resources["cpu"]
        assert rq.nominal == 20_000 and rq.borrowing_limit == 100_000

    def test_scaled(self):
        cfg = DEFAULT_GENERATOR_CONFIG.scaled(0.1)
        scenario = generate(cfg)
        assert len(scenario.workloads) == 5 * (35 + 10 + 5)


class TestArrivalProcess:
    """The open-loop arrival-stream config (bench.py --serve)."""

    def test_uniform_spacing(self):
        import numpy as np

        from kueue_tpu.perf.generator import ArrivalProcess

        proc = ArrivalProcess(
            rate_per_s=10.0, duration_s=2.0, process="uniform"
        )
        times = proc.arrival_times(np.random.default_rng(0))
        assert len(times) == 20
        gaps = {round(b - a, 6) for a, b in zip(times, times[1:])}
        assert gaps == {0.1}

    def test_poisson_is_seeded_and_rate_correct(self):
        import numpy as np

        from kueue_tpu.perf.generator import ArrivalProcess

        proc = ArrivalProcess(rate_per_s=100.0, duration_s=20.0)
        a = proc.arrival_times(np.random.default_rng(7))
        b = proc.arrival_times(np.random.default_rng(7))
        assert a == b, "same seed must reproduce the same stream"
        # law of large numbers: ~2000 arrivals within 10%
        assert 1800 <= len(a) <= 2200
        assert all(0.0 <= t < 20.0 for t in a)
        assert a == sorted(a)

    def test_arrival_stream_round_robins_queues_and_classes(self):
        import numpy as np
        import pytest

        from kueue_tpu.perf.generator import (
            ArrivalProcess,
            arrival_stream,
        )

        proc = ArrivalProcess(
            rate_per_s=5.0, duration_s=2.0, process="uniform"
        )
        stream = arrival_stream(
            proc, ["lq-0", "lq-1"], np.random.default_rng(0)
        )
        assert len(stream) == 10
        assert {gw.workload.queue_name for gw in stream} == {"lq-0", "lq-1"}
        assert {gw.class_name for gw in stream} == {"small", "medium"}
        for gw in stream:
            assert gw.workload.creation_time == gw.creation_s
            assert gw.runtime_s > 0
        with pytest.raises(ValueError):
            ArrivalProcess(process="bursty").arrival_times(
                np.random.default_rng(0)
            )


class TestRunner:
    def test_scaled_run_admits_everything(self):
        result = run(DEFAULT_GENERATOR_CONFIG.scaled(0.04))
        assert result.admitted == result.total == 100
        assert result.virtual_s > 0
        assert set(result.time_to_admission) == {"small", "medium", "large"}
        violations = check(
            result,
            RangeSpec(
                wl_classes_max_avg_tta_s={"large": 11.0, "medium": 90.0, "small": 233.0},
            ),
        )
        assert violations == []

    def test_contention_produces_queueing(self):
        # 10x the load on the same quota: small workloads must wait
        cfg = DEFAULT_GENERATOR_CONFIG.scaled(0.2)
        result = run(cfg)
        assert result.admitted == result.total
        # higher-priority large workloads admit faster than small ones
        assert result.avg_tta("large") <= result.avg_tta("small") + 1e-9

    def test_checker_flags_violations(self):
        result = run(DEFAULT_GENERATOR_CONFIG.scaled(0.04))
        errs = check(
            result, RangeSpec(wl_classes_max_avg_tta_s={"small": -1.0})
        )
        assert errs and "small" in errs[0]


class TestSolverRunnerParity:
    def test_scaled_run_solver_matches_host(self):
        cfg = DEFAULT_GENERATOR_CONFIG.scaled(0.08)
        host = run(cfg, use_solver=False)
        dev = run(cfg, use_solver=True)
        assert dev.admitted == host.admitted == dev.total
        # identical admission decisions: per-class TTA lists match exactly
        assert dev.time_to_admission == host.time_to_admission
        assert dev.cq_avg_utilization == host.cq_avg_utilization
        assert dev.backlog_fraction == host.backlog_fraction
        assert dev.cq_backlogged_utilization == host.cq_backlogged_utilization


class TestContendedScenario:
    def test_floors_hold_under_sustained_backlog(self):
        # the contended variant (runtimes x100) sustains a backlog so
        # the no-idle-capacity-under-backlog floor and nonzero TTA
        # ceilings are REAL assertions (round-3 verdict weak #2);
        # scaled down for CI, the structural floors still hold
        from kueue_tpu.perf import (
            CONTENDED_GENERATOR_CONFIG,
            RangeSpec,
            check,
            run,
        )

        result = run(CONTENDED_GENERATOR_CONFIG.scaled(0.2), use_solver=False)
        assert result.admitted == result.total
        assert result.backlog_fraction > 0.5
        assert min(result.cq_backlogged_utilization.values()) >= 0.55
        # queueing is real: every class waited
        for cls in ("small", "medium", "large"):
            assert result.avg_tta(cls) > 1.0
        # the priority ladder: prio-200 gangs wait least
        assert result.avg_tta("large") < result.avg_tta("small")
        errs = check(
            result,
            RangeSpec(
                wl_classes_min_avg_tta_s={"small": 1.0, "large": 1.0},
                cq_min_avg_utilization=0.55,
                cq_min_backlogged_utilization=0.55,
                min_backlog_fraction=0.5,
            ),
        )
        assert errs == []

    def test_checker_flags_vacuous_scenario(self):
        # the DEFAULT scenario admits instantly: the contended floors
        # must FLAG it (that is the point of the floors)
        from kueue_tpu.perf import (
            CONTENDED_RANGE_SPEC,
            DEFAULT_GENERATOR_CONFIG,
            check,
            run,
        )

        result = run(DEFAULT_GENERATOR_CONFIG.scaled(0.04), use_solver=False)
        errs = check(result, CONTENDED_RANGE_SPEC)
        assert errs  # no backlog, zero TTAs -> floors flag it


class TestMultiKueueAtScale:
    """BASELINE config #5 at test scale: worker clusters x workloads
    through batched dispatch, full lifecycle to completion
    (workload.go:298-425 behaviors at fleet granularity)."""

    def test_dispatch_lifecycle_floors(self):
        from kueue_tpu.perf.multikueue import (
            MULTIKUEUE_RANGE_SPEC,
            check_mk,
            run_multikueue,
        )

        # 320 workloads over 4 workers; capacity forces ~2 dispatch
        # waves; backlog (320) clears the 256 bulk-drain threshold so
        # the device drain and the batched dispatch compose
        r = run_multikueue(
            n_workers=4,
            n_workloads=320,
            worker_cpu_each=40,
            n_queues=8,
        )
        assert check_mk(r, MULTIKUEUE_RANGE_SPEC) == []
        assert r.finished == r.total == 320
        # wire efficiency: every create rode a batched exchange, and
        # batches were real (≥ tens of creates per exchange on average)
        assert r.unbatched_creates == 0
        assert r.total_batched_creates >= 4 * 320  # a copy per cluster
        assert r.avg_batch >= 10.0
        # the first-reserving race path genuinely ran and resolved
        assert r.first_reserving_races > 0
        # the load spread across ALL workers (scan-order rotation)
        assert set(r.winner_counts) == {f"worker{i}" for i in range(4)}
        assert min(r.winner_counts.values()) >= 0.05 * r.total
        assert sum(r.winner_counts.values()) == r.total
        # hygiene: no origin-labeled remote survives the final GC
        assert r.remote_leftovers == 0

    def test_checker_flags_unbatched_and_orphans(self):
        from kueue_tpu.perf.multikueue import (
            MKRangeSpec,
            MKRunResult,
            check_mk,
        )

        bad = MKRunResult(
            wall_s=1.0, virtual_s=1.0, n_workers=4, total=10, dispatched=10,
            finished=9, driver_iterations=1, unbatched_creates=3,
            batched_exchanges=2, total_batched_creates=4, max_batch=2,
            avg_batch=1.5, first_reserving_races=0,
            winner_counts={"worker0": 10},
            orphans_gced=0, remote_leftovers=2,
        )
        errs = check_mk(bad, MKRangeSpec())
        joined = "\n".join(errs)
        assert "finished 9/10" in joined
        assert "bypassed the batched exchange" in joined
        assert "races" in joined
        assert "survived GC" in joined
        # a worker that never won is a spread violation even though the
        # per-worker share loop can only see workers that DID win
        assert "only 1/4 workers" in joined
