"""Device-resident admission megaloop — ops/megaloop_kernel +
core/drain.launch_drain_megaloop + ClusterRuntime._megaloop_bulk_drain.

Three layers of the serial==megaloop property, mirroring
tests/test_pipeline.py:

1. KERNEL: one fused K-round launch decides bit-for-bit what K chained
   serial ``launch_drain(max_cycles=chunk)`` rounds decide, per-round
   stamps, cursors, stuck sets and final usage included — checked
   against both the serial chain and the numpy mirror
   ops/megaloop_np.solve_megaloop_np (which literally IS the serial
   loop over suffix-trimmed queues; KERNEL_MIRRORS entry).
2. RUNTIME: the megaloop drain loop produces the BIT-FOR-BIT same
   admitted set, journal record sequence and audit records as the
   serial chunked loop on the same seeded traces, and the per-round
   conflict check truncates the batch under interference instead of
   shipping stale decisions.
3. CHAOS: a crash at either new fault point
   (``cycle.megaloop_launched``, ``cycle.megaloop_commit_round``),
   followed by journal recovery and a rerun, converges to the serial
   loop's admitted set.
"""

import numpy as np
import pytest

from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.core.drain import (
    launch_drain,
    launch_drain_megaloop,
    run_drain_megaloop_host,
)
from kueue_tpu.core.guard import RoundsTuner, SolverGuard
from kueue_tpu.core.pipeline import outcome_signature, speculative_snapshot
from kueue_tpu.core.queue_manager import queue_order_timestamp
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.storage import Journal, recover
from kueue_tpu.testing import faults
from kueue_tpu.utils.clock import FakeClock

from tests.test_pipeline import (
    CHUNK,
    THRESHOLD,
    _OpenGate,
    admitted,
    audit_dump,
    build_rt,
    journal_sequence,
    parked,
)
from tests.test_solver_path import build_env, random_spec


def build_ml_rt(seed, megaloop, journal_dir=None, pipeline="on",
                chunk=CHUNK):
    """The tests/test_pipeline seeded environment with the megaloop
    knob exposed (same CQs/workloads per seed by construction)."""
    rt, journal = build_rt(seed, pipeline, journal_dir, chunk)
    rt.set_megaloop(megaloop)
    return rt, journal


# ---- layer 1: kernel vs serial chain vs numpy mirror ----


def _kernel_env(spec):
    sched, mgr, cache, _ = build_env(spec, use_solver=False)
    pending = []
    for cq_name, pq in mgr.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            pending.append((wl, cq_name))
    snapshot = take_snapshot(cache)
    ts_fn = lambda wl: queue_order_timestamp(wl, mgr._ts_policy)  # noqa: E731
    return snapshot, pending, cache.flavors, ts_fn


def _round_view(outcome):
    sig = outcome_signature(outcome)
    sig["undecided"] = [(wl.key, cq) for wl, cq in outcome.undecided]
    return sig


class TestKernelSerialEquivalence:
    """One fused launch == the chained serial rounds, bit-for-bit."""

    @pytest.mark.parametrize("seed", range(3))
    def test_fused_equals_serial_chain(self, seed):
        snapshot, pending, flavors, ts_fn = _kernel_env(
            random_spec(seed, workloads_per_cq=8)
        )
        log = launch_drain_megaloop(
            snapshot, pending, flavors, timestamp_fn=ts_fn,
            chunk_cycles=2, max_rounds=16,
        ).fetch()
        assert log.n_rounds >= 2, "trace too shallow to exercise fusion"
        s, p = snapshot, pending
        for r, round_out in enumerate(log.rounds):
            serial = launch_drain(
                s, p, flavors, timestamp_fn=ts_fn, max_cycles=2
            ).fetch()
            assert _round_view(serial) == _round_view(round_out), r
            assert np.array_equal(
                serial.final_usage, round_out.final_usage
            ), r
            if not serial.undecided:
                break
            s = speculative_snapshot(s, serial.final_usage)
            p = serial.undecided

    @pytest.mark.parametrize("seed", range(3))
    def test_fused_equals_numpy_mirror(self, seed):
        """KERNEL_MIRRORS parity: the device log equals the numpy
        mirror's — and the mirror IS the serial loop over trimmed
        tensors, so this is the serial==megaloop proof at the tensor
        level (multi-flavor specs exercise the per-round g_start /
        retry-budget resets)."""
        snapshot, pending, flavors, ts_fn = _kernel_env(
            random_spec(seed, workloads_per_cq=8)
        )
        dev = launch_drain_megaloop(
            snapshot, pending, flavors, timestamp_fn=ts_fn,
            chunk_cycles=3, max_rounds=8,
        ).fetch()
        host = run_drain_megaloop_host(
            snapshot, pending, flavors, timestamp_fn=ts_fn,
            chunk_cycles=3, max_rounds=8,
        )
        assert dev.n_rounds == host.n_rounds
        assert dev.cycles == host.cycles
        assert dev.truncated == host.truncated
        for r, (a, b) in enumerate(zip(dev.rounds, host.rounds)):
            assert _round_view(a) == _round_view(b), r
            assert np.array_equal(a.final_usage, b.final_usage), r

    def test_round_budget_truncates_log(self):
        """max_rounds caps the batch: the final round reports the
        remaining backlog undecided and the log says truncated."""
        snapshot, pending, flavors, ts_fn = _kernel_env(
            random_spec(0, workloads_per_cq=8)
        )
        log = launch_drain_megaloop(
            snapshot, pending, flavors, timestamp_fn=ts_fn,
            chunk_cycles=1, max_rounds=2,
        ).fetch()
        assert log.n_rounds == 2
        assert log.truncated
        assert log.rounds[-1].undecided

    def test_policy_scores_flow_through(self):
        """Policy-complete: a gavel-scored megaloop decides exactly
        what gavel-scored serial rounds decide (score tensors ride
        plan_drain into the fused kernel unchanged)."""
        from kueue_tpu.policy import resolve_policy

        policy = resolve_policy("gavel")
        snapshot, pending, flavors, ts_fn = _kernel_env(
            random_spec(2, workloads_per_cq=8)
        )
        log = launch_drain_megaloop(
            snapshot, pending, flavors, timestamp_fn=ts_fn,
            chunk_cycles=2, max_rounds=16, policy=policy, now=5.0,
        ).fetch()
        s, p = snapshot, pending
        for r, round_out in enumerate(log.rounds):
            serial = launch_drain(
                s, p, flavors, timestamp_fn=ts_fn, max_cycles=2,
                policy=policy, now=5.0,
            ).fetch()
            assert _round_view(serial) == _round_view(round_out), r
            if not serial.undecided:
                break
            s = speculative_snapshot(s, serial.final_usage)
            p = serial.undecided

    def test_resident_mesh_rejected_loudly(self):
        """launch_drain / launch_drain_megaloop are documented
        single-device-only with a resident: a mesh + resident call must
        raise, not silently ignore the resident buffers."""
        import types

        from kueue_tpu.core.encode import ResidentEncoder

        snapshot, pending, flavors, ts_fn = _kernel_env(
            random_spec(0, workloads_per_cq=4)
        )
        fake_mesh = types.SimpleNamespace(shape={"wl": 2})
        with pytest.raises(ValueError, match="single-device"):
            launch_drain(
                snapshot, pending, flavors, timestamp_fn=ts_fn,
                mesh=fake_mesh, resident=ResidentEncoder(),
            )
        with pytest.raises(ValueError, match="single-device"):
            launch_drain_megaloop(
                snapshot, pending, flavors, timestamp_fn=ts_fn,
                mesh=fake_mesh, resident=ResidentEncoder(),
            )


class TestMeshComposition:
    """--megaloop composes with --mesh: the fused launch shards its
    queue tensors (and suffix budgets) along wl and decides bit-for-bit
    the single-device log (8 virtual CPU devices via conftest)."""

    @pytest.fixture(scope="class")
    def mesh(self):
        from kueue_tpu.parallel import make_mesh

        return make_mesh(8)

    @pytest.mark.parametrize("seed", [0, 1])
    def test_sharded_log_parity(self, mesh, seed):
        snapshot, pending, flavors, ts_fn = _kernel_env(
            random_spec(seed, workloads_per_cq=6)
        )
        single = launch_drain_megaloop(
            snapshot, pending, flavors, timestamp_fn=ts_fn,
            chunk_cycles=2, max_rounds=8,
        ).fetch()
        sharded = launch_drain_megaloop(
            snapshot, pending, flavors, timestamp_fn=ts_fn,
            chunk_cycles=2, max_rounds=8, mesh=mesh,
        ).fetch()
        assert single.n_rounds == sharded.n_rounds
        for r, (a, b) in enumerate(zip(single.rounds, sharded.rounds)):
            assert _round_view(a) == _round_view(b), r
            assert np.array_equal(a.final_usage, b.final_usage), r


# ---- layer 2: runtime equivalence + truncation ----


class TestMegaloopEqualsSerial:
    """The bit-for-bit property over seeded traces: decisions, journal
    record sequence and audit trail identical with the megaloop on."""

    @pytest.mark.parametrize("seed", range(3))
    def test_decisions_journal_audit_identical(self, tmp_path, seed):
        rt_s, j_s = build_rt(seed, "serial", tmp_path / "s")
        rt_m, j_m = build_ml_rt(seed, "on", tmp_path / "m")
        rt_s.run_until_idle(max_iterations=60)
        rt_m.run_until_idle(max_iterations=60)
        assert admitted(rt_s) == admitted(rt_m)
        assert parked(rt_s) == parked(rt_m)
        assert admitted(rt_m), "vacuous trace: nothing admitted"
        # the fusion actually engaged and amortized dispatches
        ml = rt_m.megaloop
        assert ml.launches >= 1
        assert ml.rounds > ml.launches, ml.to_dict()
        assert rt_s.megaloop.launches == 0
        assert not rt_s.check_invariants() and not rt_m.check_invariants()
        j_s.close()
        j_m.close()
        assert journal_sequence(tmp_path / "s") == journal_sequence(
            tmp_path / "m"
        )
        assert audit_dump(rt_s) == audit_dump(rt_m)

    def test_pinned_k_forces_multiple_launches(self):
        """--megaloop K pins the rounds-per-launch: a deep backlog
        takes ceil(rounds / K) launches, decisions unchanged."""
        rt_auto, _ = build_ml_rt(3, "on")
        rt_auto.run_until_idle(max_iterations=60)
        rt_k, _ = build_ml_rt(3, "2")
        rt_k.run_until_idle(max_iterations=60)
        assert admitted(rt_auto) == admitted(rt_k)
        assert rt_k.megaloop_rounds == 2
        assert rt_k.megaloop.launches > rt_auto.megaloop.launches

    def test_megaloop_off_by_default(self):
        rt = ClusterRuntime(clock=FakeClock(0.0))
        assert rt.drain_megaloop == "off"
        assert rt.megaloop_rounds == 0

    def test_knob_parsing(self):
        rt = ClusterRuntime(clock=FakeClock(0.0))
        for spec, want in [
            ("on", ("on", 0)), ("off", ("off", 0)), (4, ("on", 4)),
            ("8", ("on", 8)), (0, ("off", 0)), (None, ("off", 0)),
        ]:
            rt.set_megaloop(spec)
            assert (rt.drain_megaloop, rt.megaloop_rounds) == want, spec
        with pytest.raises(ValueError):
            rt.set_megaloop("sideways")

    def test_observability_surfaces(self):
        rt, _ = build_ml_rt(3, "on")
        rt.run_until_idle(max_iterations=60)
        # per-launch cycle.megaloop span on the drain cycle trees
        tracer = rt.scheduler.tracer
        names = {
            s.name
            for t in tracer.traces_summary(limit=256)
            for s in tracer.trace(t["traceId"])
        }
        assert "cycle.megaloop" in names
        # metrics exposed (materialized-at-zero contract checked by the
        # metrics lint; here: live values flow)
        text = rt.metrics.registry.expose()
        assert "kueue_megaloop_rounds_per_launch" in text
        assert "kueue_megaloop_launches_total" in text
        assert "kueue_megaloop_truncations_total" in text
        # SIGUSR2 dump section
        from kueue_tpu.debugger import dump

        out = dump(rt)
        assert "-- megaloop --" in out
        assert "roundsPerLaunch" in out
        # dashboard payload
        from kueue_tpu.server.dashboard import dashboard_payload

        state = dashboard_payload(rt)
        assert state["megaloop"]["mode"] == "on"
        assert state["megaloop"]["launches"] >= 1

    def test_resident_usage_carry(self):
        """After a fully-committed launch the ResidentEncoder adopts
        the kernel's final usage device slice: the next launch ships
        zero delta rows for everything the batch itself changed."""
        rt, _ = build_ml_rt(0, "on")
        rt.run_until_idle(max_iterations=60)
        res = rt._drain_resident
        assert res is not None
        assert res.adopts >= 1, res.stats()


class TestConflictTruncation:
    def test_interference_truncates_batch_not_decisions(self):
        """Mutating queue state during a round's apply invalidates the
        rest of the fused batch: the megaloop truncates there,
        re-solves from the real state, and the final decisions match
        the serial loop run against the same interference."""

        def run(megaloop):
            rt, _ = build_ml_rt(5, megaloop)
            if megaloop == "off":
                rt.drain_pipeline = "serial"
            orig = rt._apply_drain_outcome
            state = {"fired": False}

            def interfering_apply(outcome, snapshot):
                res = orig(outcome, snapshot)
                if not state["fired"] and outcome.undecided:
                    state["fired"] = True
                    wl, _cq = outcome.undecided[0]
                    rt.delete_workload(wl)
                return res

            rt._apply_drain_outcome = interfering_apply
            rt.run_until_idle(max_iterations=60)
            assert state["fired"], "interference never triggered"
            return rt

        rt_m = run("on")
        rt_s = run("off")
        assert rt_m.megaloop.truncations >= 1, rt_m.megaloop.to_dict()
        assert admitted(rt_m) == admitted(rt_s)
        assert not rt_m.check_invariants()


# ---- layer 3: chaos at the new fault points ----


class TestMegaloopChaos:
    """Crash-at-every-new-fault-point x occurrence sweep: recovery from
    the journal plus a rerun converges to the fault-free serial
    admitted set (the tests/test_pipeline chaos pattern)."""

    POINTS = ("cycle.megaloop_launched", "cycle.megaloop_commit_round")

    @pytest.mark.parametrize("point", POINTS)
    @pytest.mark.parametrize("occurrence", [0, 1, 2])
    def test_crash_recover_converge(self, tmp_path, point, occurrence):
        ref, j_ref = build_rt(0, "serial", tmp_path / "ref")
        ref.run_until_idle(max_iterations=60)
        ref_admitted = admitted(ref)
        j_ref.close()

        # pin K=2 so a deep trace takes several fused launches and
        # every (point, occurrence) pair genuinely fires
        rt, j = build_ml_rt(0, "2", tmp_path / "j")
        faults.arm(point, "crash", skip=occurrence)
        crashed = False
        try:
            rt.run_until_idle(max_iterations=60)
        except faults.InjectedCrash:
            crashed = True
        finally:
            faults.reset()
        j.close()
        if not crashed:
            pytest.fail(f"{point} occurrence {occurrence} never fired")

        rt2, _ = build_ml_rt(0, "2")
        res = recover(None, str(tmp_path / "j"), runtime=rt2, strict=True)
        rt2.attach_journal(res.journal)
        rt2.run_until_idle(max_iterations=60)
        assert admitted(rt2) == ref_admitted
        assert parked(rt2) == parked(ref)
        assert not rt2.check_invariants()

    def test_points_registered(self):
        for p in self.POINTS:
            assert p in faults.FAULT_POINTS


# ---- guard coverage: tuner, deadline, sampled replay ----


class TestGuardMegaloop:
    def test_rounds_tuner_shrinks_on_truncation(self):
        t = RoundsTuner(default_k=8)
        assert t.k_for(1000) == 8
        t.observe(1000, committed=1, truncated=True)
        assert t.k_for(1000) == 4
        t.observe(1000, committed=1, truncated=True)
        t.observe(1000, committed=1, truncated=True)
        assert t.k_for(1000) == 2  # floor of the ladder
        assert t.truncations == 3

    def test_rounds_tuner_grows_on_clean_exhaustion(self):
        t = RoundsTuner(default_k=8, grow_after=2)
        t.observe(1000, committed=8, truncated=False)
        assert t.k_for(1000) == 8  # one clean launch is not enough
        t.observe(1000, committed=8, truncated=False)
        assert t.k_for(1000) == 16
        # a quiesced (non-exhausted) launch resets the streak
        t.observe(1000, committed=3, truncated=False)
        t.observe(1000, committed=16, truncated=False)
        assert t.k_for(1000) == 16

    def test_rounds_tuner_is_per_backlog_bucket(self):
        t = RoundsTuner(default_k=8)
        t.observe(100, committed=1, truncated=True)
        assert t.k_for(100) == 4
        assert t.k_for(100000) == 8  # other mixes untouched

    def test_pick_replay_round_deterministic_and_in_range(self):
        g = SolverGuard(clock=FakeClock(0.0))
        picks = set()
        for n in range(1, 40):
            g.divergence_checks = n
            r = g.pick_replay_round(7)
            assert 0 <= r < 7
            picks.add(r)
        assert len(picks) > 1, "degenerate replay schedule"
        g.divergence_checks = 5
        assert g.pick_replay_round(7) == g.pick_replay_round(7)

    def test_launch_deadline_override(self):
        """The megaloop's K-scaled deadline: a launch that would breach
        the per-round budget passes under its scaled override, and
        still breaches past it."""
        clock = FakeClock(0.0)
        guard = SolverGuard(clock=clock)
        guard.config.device_deadline_s = 5.0
        launch = guard.device_launch(
            lambda: "h", label="megaloop", deadline_s=40.0
        )
        clock.advance(30.0)  # past per-round budget, inside the batch's
        out = guard.device_join(launch, lambda h: h)
        assert out.result == "h"
        launch = guard.device_launch(
            lambda: "h", label="megaloop", deadline_s=40.0
        )
        clock.advance(41.0)
        out = guard.device_join(launch, lambda h: h)
        assert out.result is None
        assert guard.breaker.consecutive_failures == 1

    def test_sampled_round_replay_in_loop(self):
        """divergence_check_every=1: every fused launch replays one of
        its rounds on the numpy mirror BEFORE applying it; agreement
        keeps the device path trusted and decisions match serial."""
        rt, _ = build_ml_rt(2, "on")
        rt.guard.config.divergence_check_every = 1
        rt.run_until_idle(max_iterations=60)
        assert rt.megaloop.launches >= 1
        assert rt.guard.divergence_checks >= 1
        assert rt.guard.divergences == 0
        assert not rt.guard.breaker.quarantined
        ref, _ = build_rt(2, "serial")
        ref.run_until_idle(max_iterations=60)
        assert admitted(rt) == admitted(ref)

    def test_divergence_surface_label(self):
        guard = SolverGuard(clock=FakeClock(0.0))
        host = guard.check_drain_divergence(
            {"admitted": ["a"]},
            lambda: ("HOST", {"admitted": ["b"]}),
            heads=3,
            surface="drain-megaloop",
        )
        assert host == "HOST"
        assert guard.last_divergence["surface"] == "drain-megaloop"
        assert guard.breaker.quarantined
