"""Field-index layer tests (pkg/controller/core/indexer/indexer.go).

Covers the generic FieldIndexer (multi-value postings, incremental
update/delete, registration ordering) and the runtime wiring: the
standard workload indexes stay consistent through admission, eviction
and deletion, and index-backed listings match brute-force scans.
"""

import pytest

from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.controllers.indexer import (
    WORKLOAD_ADMISSION_CHECK_KEY,
    WORKLOAD_CLUSTER_QUEUE_KEY,
    WORKLOAD_QUEUE_KEY,
    FieldIndexer,
    workload_indexer,
)
from kueue_tpu.controllers.jobs import BatchJob
from kueue_tpu.models import (
    AdmissionCheck,
    ClusterQueue,
    LocalQueue,
    ResourceFlavor,
)
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.utils.clock import FakeClock


class TestFieldIndexer:
    def test_multi_value_postings(self):
        ix = FieldIndexer()
        ix.register("tags", lambda o: list(o))
        ix.update("a", ["x", "y"])
        ix.update("b", ["y"])
        assert ix.lookup("tags", "x") == ["a"]
        assert ix.lookup("tags", "y") == ["a", "b"]
        assert ix.values("tags") == ["x", "y"]

    def test_update_replaces_old_postings(self):
        ix = FieldIndexer()
        ix.register("tags", lambda o: list(o))
        ix.update("a", ["x"])
        ix.update("a", ["z"])
        assert ix.lookup("tags", "x") == []
        assert ix.lookup("tags", "z") == ["a"]

    def test_delete_clears_empty_posting(self):
        ix = FieldIndexer()
        ix.register("tags", lambda o: list(o))
        ix.update("a", ["x"])
        ix.delete("a")
        assert ix.lookup("tags", "x") == []
        assert ix.values("tags") == []
        assert len(ix) == 0

    def test_empty_values_not_indexed(self):
        ix = FieldIndexer()
        ix.register("tags", lambda o: list(o))
        ix.update("a", [""])
        assert ix.values("tags") == []

    def test_duplicate_registration_rejected(self):
        ix = FieldIndexer()
        ix.register("f", lambda o: [])
        with pytest.raises(ValueError):
            ix.register("f", lambda o: [])

    def test_late_registration_rejected(self):
        ix = FieldIndexer()
        ix.register("f", lambda o: ["v"])
        ix.update("a", object())
        with pytest.raises(RuntimeError):
            ix.register("g", lambda o: [])

    def test_unknown_field_raises(self):
        ix = FieldIndexer()
        with pytest.raises(KeyError):
            ix.lookup("nope", "v")


def make_runtime(**kw):
    checks = kw.pop("checks", None)
    clock = FakeClock(start=1000.0)
    rt = ClusterRuntime(clock=clock, **kw)
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",), (FlavorQuotas.build("default", {"cpu": "4"}),)
                ),
            ),
            **({"admission_checks": checks} if checks else {}),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    return rt, clock


class TestRuntimeWiring:
    def test_queue_index_tracks_lifecycle(self):
        rt, _ = make_runtime()
        job = BatchJob.build("ns", "j1", "lq", parallelism=1, requests={"cpu": "1"})
        rt.add_job(job)
        rt.reconcile_once()
        keys = rt.indexer.lookup(WORKLOAD_QUEUE_KEY, "ns/lq")
        assert len(keys) == 1
        wls = rt.list_workloads(WORKLOAD_QUEUE_KEY, "ns/lq")
        assert [w.queue_name for w in wls] == ["lq"]
        rt.delete_job(job.key)
        rt.reconcile_once()
        assert rt.indexer.lookup(WORKLOAD_QUEUE_KEY, "ns/lq") == []

    def test_cluster_queue_index_follows_admission(self):
        rt, _ = make_runtime()
        job = BatchJob.build("ns", "j1", "lq", parallelism=1, requests={"cpu": "1"})
        rt.add_job(job)
        rt.reconcile_once()  # creates the workload
        assert rt.indexer.lookup(WORKLOAD_CLUSTER_QUEUE_KEY, "cq") == []
        rt.schedule_once()  # admits -> admission set, event emitted
        rt.reconcile_once()
        admitted = rt.list_workloads(WORKLOAD_CLUSTER_QUEUE_KEY, "cq")
        assert len(admitted) == 1
        assert admitted[0].admission.cluster_queue == "cq"

    def test_admission_check_index(self):
        rt, _ = make_runtime(checks=("prov",))
        rt.add_admission_check(AdmissionCheck(name="prov", controller_name="c"))
        job = BatchJob.build("ns", "j1", "lq", parallelism=1, requests={"cpu": "1"})
        rt.add_job(job)
        rt.reconcile_once()
        rt.schedule_once()
        rt.reconcile_once()  # workload controller syncs check states
        assert len(rt.indexer.lookup(WORKLOAD_ADMISSION_CHECK_KEY, "prov")) == 1

    def test_index_matches_brute_force_scan(self):
        rt, _ = make_runtime()
        for i in range(6):
            rt.add_job(
                BatchJob.build(
                    "ns", f"j{i}", "lq", parallelism=1, requests={"cpu": "1"}
                )
            )
        rt.reconcile_once()
        for _ in range(6):
            rt.schedule_once()
        rt.reconcile_once()
        want = sorted(
            w.key
            for w in rt.workloads.values()
            if w.admission is not None and w.admission.cluster_queue == "cq"
        )
        assert rt.indexer.lookup(WORKLOAD_CLUSTER_QUEUE_KEY, "cq") == want

    def test_local_queue_status_counts_from_index(self):
        rt, _ = make_runtime()
        # quota 4 cpus; 6 one-cpu jobs -> 4 admitted, 2 pending
        for i in range(6):
            rt.add_job(
                BatchJob.build(
                    "ns", f"j{i}", "lq", parallelism=1, requests={"cpu": "1"}
                )
            )
        rt.reconcile_once()
        for _ in range(6):  # heads() pops one head per CQ per cycle
            rt.schedule_once()
        rt.reconcile_once()
        st = rt.local_queue_status("ns", "lq")
        assert st["reservingWorkloads"] == 4
        assert st["admittedWorkloads"] == 4
        assert st["pendingWorkloads"] == 2


def test_queue_change_refreshes_index():
    # queue_name is mutated in place (jobframework queue-move) with no
    # event; on_workload_queue_changed must refresh the index or the
    # LQ status mirror counts the workload under the old queue forever
    rt, _ = make_runtime()
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq2", cluster_queue="cq"))
    job = BatchJob.build("ns", "j1", "lq", parallelism=1, requests={"cpu": "99"})
    rt.add_job(job)
    rt.reconcile_once()  # pending (doesn't fit), indexed under ns/lq
    (wl,) = rt.list_workloads(WORKLOAD_QUEUE_KEY, "ns/lq")
    wl.queue_name = "lq2"
    rt.on_workload_queue_changed(wl)
    assert rt.list_workloads(WORKLOAD_QUEUE_KEY, "ns/lq") == []
    assert [w.key for w in rt.list_workloads(WORKLOAD_QUEUE_KEY, "ns/lq2")] == [wl.key]


def test_standard_indexer_fields():
    ix = workload_indexer()
    assert sorted(ix._extractors) == sorted(
        [
            WORKLOAD_QUEUE_KEY,
            WORKLOAD_CLUSTER_QUEUE_KEY,
            WORKLOAD_ADMISSION_CHECK_KEY,
        ]
    )
