"""Journal-tailing read replicas: incremental tailing over local and
HTTP sources, segment-index skip + offset-cursor feed reads, torn-tail
/ rotation / compaction / fencing-handover handling, replica serving
surfaces (visibility, watch resourceVersion contract, explain, plan,
307 write redirects, healthz/metrics/dashboard/SIGUSR2), and the
byte-identical quiescent-convergence property the ISSUE-9 acceptance
names — with chaos via the ``replica.tail_gap`` / ``replica.resync``
fault points.
"""

import json
import os
import threading

import pytest

from kueue_tpu import serialization as ser
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.models import LocalQueue, ResourceFlavor, Workload
from kueue_tpu.models.workload import PodSet
from kueue_tpu.storage import (
    HTTPTailSource,
    Journal,
    JournalTailer,
    LocalTailSource,
    TailSourceError,
)
from kueue_tpu.storage.journal import select_segments
from kueue_tpu.testing import faults
from kueue_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---- scenario helpers (test_storage idiom) ----
def cq_dict(name, quota="4"):
    return {
        "name": name,
        "namespaceSelector": {},
        "resourceGroups": [
            {
                "coveredResources": ["cpu"],
                "flavors": [
                    {
                        "name": "default",
                        "resources": [{"name": "cpu", "nominalQuota": quota}],
                    }
                ],
            }
        ],
    }


def fresh_rt(clock_start=0.0):
    return ClusterRuntime(
        clock=FakeClock(clock_start), use_solver=False,
        bulk_drain_threshold=None,
    )


def leader_with_journal(tmp_path, name="journal", **journal_kw):
    rt = fresh_rt()
    journal = Journal(str(tmp_path / name), **journal_kw).open()
    rt.attach_journal(journal)
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(ser.cq_from_dict(cq_dict("cq-0")))
    rt.add_local_queue(
        LocalQueue(namespace="ns", name="lq-0", cluster_queue="cq-0")
    )
    return rt, journal


def submit(rt, name, cpu="1", prio=0):
    rt.add_workload(
        Workload(
            namespace="ns", name=name, queue_name="lq-0", priority=prio,
            pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
        )
    )
    rt.run_until_idle()


def state_of(rt) -> str:
    out = ser.runtime_to_state(rt)
    out.pop("persistence")
    return json.dumps(out, sort_keys=True)


def local_tailer(tmp_path, state_path=None, name="journal"):
    return JournalTailer(
        LocalTailSource(
            str(tmp_path / name),
            state_path=str(state_path) if state_path else None,
        ),
        build_runtime=fresh_rt,
    )


def checkpoint_to(path, rt, token=None):
    state = ser.runtime_to_state(rt)
    if token is not None:
        state["persistence"]["token"] = token
    path.write_text(json.dumps(state))


# ---- satellite: segment-index skip in Journal.records(min_seq) ----
class TestSegmentIndex:
    def _journal(self, tmp_path, n=30, segment_max_bytes=400):
        j = Journal(
            str(tmp_path / "j"), segment_max_bytes=segment_max_bytes
        ).open()
        for i in range(n):
            j.append("object_upsert", {"section": "x", "object": {"i": i}})
        return j

    def test_select_segments_skips_covered(self, tmp_path):
        j = self._journal(tmp_path)
        names = sorted(
            n for n in os.listdir(j.path) if n.endswith(".wal")
        )
        assert len(names) > 3, "scenario must rotate several segments"
        # min_seq far into the chain: every fully-covered segment drops
        kept = select_segments(names, 25)
        assert kept == names[-len(kept):], "kept set must be a suffix"
        assert len(kept) < len(names)
        # the segments holding seq 26..30 must all be kept
        first_kept = int(kept[0][len("journal-"):-len(".wal")])
        assert first_kept <= 26
        # min_seq 0 keeps everything; huge min_seq keeps only the tail
        assert select_segments(names, 0) == names
        assert len(select_segments(names, 10 ** 9)) >= 1

    def test_records_equal_full_scan(self, tmp_path):
        j = self._journal(tmp_path)
        for min_seq in (0, 1, 7, 15, 29, 30, 99):
            via_index = [r.seq for r in j.records(min_seq)]
            expected = [s for s in range(1, 31) if s > min_seq]
            assert via_index == expected

    def test_tail_records_cursor_matches_cold_scan(self, tmp_path):
        j = self._journal(tmp_path, n=10)
        first = j.tail_records(0)
        assert [r.seq for r in first] == list(range(1, 11))
        # warm repeat at the head: nothing new, cursor holds
        assert j.tail_records(10) == []
        # appends (with rotation) land incrementally via the cursor
        for i in range(10, 16):
            j.append("object_upsert", {"section": "x", "object": {"i": i}})
        warm = [r.seq for r in j.tail_records(10)]
        assert warm == list(range(11, 17))
        # a cold cursor (different seq) still answers correctly
        assert [r.seq for r in j.tail_records(3)][:3] == [4, 5, 6]

    def test_tail_records_survives_compaction(self, tmp_path):
        j = self._journal(tmp_path, n=20)
        assert [r.seq for r in j.tail_records(18)] == [19, 20]
        j.compact(15)
        # cursor segment may be gone; the indexed cold path answers
        assert [r.seq for r in j.tail_records(18)] == [19, 20]
        assert j.first_available_seq() > 1

    def test_first_available_seq(self, tmp_path):
        j = Journal(str(tmp_path / "j")).open()
        assert j.first_available_seq() == 1
        for i in range(5):
            j.append("object_upsert", {"section": "x", "object": {"i": i}})
        j.compact(5)
        assert j.first_available_seq() == 6


# ---- local tailing ----
class TestLocalTailer:
    def test_incremental_apply_converges(self, tmp_path):
        rt, _ = leader_with_journal(tmp_path)
        tailer = local_tailer(tmp_path)
        res = tailer.poll_once()
        assert res.applied > 0 and res.caught_up and not res.error
        submit(rt, "wl-0")
        submit(rt, "wl-1", cpu="8")  # does not fit: stays pending
        res = tailer.poll_once()
        assert res.applied > 0
        assert state_of(tailer.runtime) == state_of(rt)
        assert tailer.runtime.workloads["ns/wl-0"].is_admitted
        assert not tailer.runtime.workloads["ns/wl-1"].is_admitted
        assert tailer.runtime.check_invariants() == []
        # replica rv mirrors the leader's mutation counter
        assert tailer.runtime.resource_version == rt.resource_version

    def test_segment_rotation_is_invisible(self, tmp_path):
        rt, journal = leader_with_journal(
            tmp_path, segment_max_bytes=500
        )
        tailer = local_tailer(tmp_path)
        tailer.poll_once()
        for i in range(12):
            submit(rt, f"wl-{i}")
        assert journal.stats().segments > 1
        tailer.poll_once()
        assert state_of(tailer.runtime) == state_of(rt)

    def test_torn_tail_not_applied_then_retried(self, tmp_path):
        rt, journal = leader_with_journal(tmp_path)
        tailer = local_tailer(tmp_path)
        tailer.poll_once()
        before = tailer.applied_seq
        submit(rt, "wl-0")
        # tear the newest frame: the tailer must stop cleanly before it
        seg = journal.segment_paths()[-1]
        full = open(seg, "rb").read()
        faults.corrupt_tail(seg, 5)
        applied_torn = tailer.poll_once().applied
        torn_seq = tailer.applied_seq
        assert torn_seq < journal.last_seq
        # the write completes (leader finishes the frame): applied now
        with open(seg, "wb") as f:
            f.write(full)
        tailer.poll_once()
        assert tailer.applied_seq == journal.last_seq
        assert state_of(tailer.runtime) == state_of(rt)
        assert applied_torn + tailer.records_applied >= before

    def test_compaction_jump_resyncs_from_checkpoint(self, tmp_path):
        rt, journal = leader_with_journal(
            tmp_path, segment_max_bytes=400
        )
        ckpt = tmp_path / "state.json"
        tailer = local_tailer(tmp_path, state_path=ckpt)
        tailer.poll_once()
        for i in range(10):
            submit(rt, f"wl-{i}")
        # leader checkpoints + compacts: the tailer's resume segment is
        # deleted out from under it
        checkpoint_to(ckpt, rt)
        deleted = journal.compact(journal.last_seq)
        assert deleted > 0
        res = tailer.poll_once()
        assert res.resynced and tailer.resyncs == 1
        assert tailer.applied_seq == journal.last_seq
        assert state_of(tailer.runtime) == state_of(rt)
        # post-resync tailing continues incrementally
        submit(rt, "wl-after")
        res = tailer.poll_once()
        assert res.applied > 0 and not res.resynced
        assert state_of(tailer.runtime) == state_of(rt)

    def test_compaction_jump_without_checkpoint_reports_error(self, tmp_path):
        rt, journal = leader_with_journal(
            tmp_path, segment_max_bytes=400
        )
        tailer = local_tailer(tmp_path)  # no state_path
        tailer.poll_once()
        for i in range(10):
            submit(rt, f"wl-{i}")
        journal.compact(journal.last_seq)
        res = tailer.poll_once()
        assert res.error and "resync" in res.error
        assert tailer.last_error
        # the previous consistent state keeps serving
        assert tailer.runtime.check_invariants() == []

    def test_stale_fence_records_refused(self, tmp_path):
        rt, journal = leader_with_journal(tmp_path)
        journal.token_provider = lambda: 5
        submit(rt, "wl-0")
        tailer = local_tailer(tmp_path)
        tailer.poll_once()
        assert tailer.max_token == 5
        reference = state_of(tailer.runtime)
        # a deposed leader's stray append lands with an older token
        journal.append(
            "workload_upsert",
            ser.workload_to_dict(
                Workload(
                    namespace="ns", name="stray", queue_name="lq-0",
                    pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
                )
            ),
            token=1,
        )
        res = tailer.poll_once()
        assert res.skipped_stale == 1
        assert "ns/stray" not in tailer.runtime.workloads
        assert state_of(tailer.runtime) == reference
        # but the cursor advanced past it: newer records still apply
        journal.token_provider = lambda: 5
        submit(rt, "wl-1")
        tailer.poll_once()
        assert "ns/wl-1" in tailer.runtime.workloads

    def test_fence_handover_reanchors_on_checkpoint(self, tmp_path):
        rt, journal = leader_with_journal(tmp_path)
        journal.token_provider = lambda: 1
        submit(rt, "wl-0")
        ckpt = tmp_path / "state.json"
        tailer = local_tailer(tmp_path, state_path=ckpt)
        tailer.poll_once()
        assert tailer.max_token == 1
        # leader handover: the new leader's records carry a HIGHER
        # token; the replica must re-anchor on the new checkpoint
        # rather than trust its own pre-handover prefix
        journal.token_provider = lambda: 7
        submit(rt, "wl-1")
        checkpoint_to(ckpt, rt, token=7)
        res = tailer.poll_once()
        assert res.resynced and tailer.resyncs == 1
        assert tailer.max_token == 7
        assert state_of(tailer.runtime) == state_of(rt)
        assert tailer.runtime.check_invariants() == []

    def test_chaos_crash_at_fault_points_recovers(self, tmp_path):
        rt, journal = leader_with_journal(
            tmp_path, segment_max_bytes=400
        )
        ckpt = tmp_path / "state.json"
        tailer = local_tailer(tmp_path, state_path=ckpt)
        tailer.poll_once()
        for i in range(8):
            submit(rt, f"wl-{i}")
        checkpoint_to(ckpt, rt)
        journal.compact(journal.last_seq)
        # crash the replica INSIDE the gap-detection window
        faults.arm("replica.tail_gap", action="crash")
        with pytest.raises(faults.InjectedCrash):
            tailer.poll_once()
        faults.reset()
        # crash it INSIDE the resync rebuild
        faults.arm("replica.resync", action="crash")
        with pytest.raises(faults.InjectedCrash):
            tailer.poll_once()
        faults.reset()
        # next poll completes the resync and converges byte-identical
        res = tailer.poll_once()
        assert res.resynced
        assert state_of(tailer.runtime) == state_of(rt)
        assert tailer.runtime.check_invariants() == []

    def test_inconsistent_feed_reanchors_after_grace(self, tmp_path):
        """A feed claiming a head PAST the cursor while shipping zero
        records and no compaction marker (journal dir deleted under a
        live leader) must re-anchor on a checkpoint after a short
        grace (one empty poll can be a torn in-flight frame)."""
        rt, _ = leader_with_journal(tmp_path)
        submit(rt, "wl-0")
        ckpt = tmp_path / "state.json"
        checkpoint_to(ckpt, rt)

        from kueue_tpu.storage.tailer import TailBatch

        class LyingSource:
            def __init__(self):
                self.local = LocalTailSource(
                    str(tmp_path / "journal"), state_path=str(ckpt)
                )
                self.lying = False

            def fetch(self, since_seq, since_event_rv=0,
                      since_audit_seq=0, status=None):
                if self.lying:
                    return TailBatch(last_seq=since_seq + 50)
                return self.local.fetch(since_seq)

            def checkpoint(self):
                return self.local.checkpoint()

        src = LyingSource()
        tailer = JournalTailer(src, build_runtime=fresh_rt)
        assert tailer.poll_once().caught_up
        src.lying = True
        # two empty-behind polls are tolerated (torn-frame grace)...
        assert not tailer.poll_once().resynced
        assert not tailer.poll_once().resynced
        # ...the third re-anchors on the checkpoint
        res = tailer.poll_once()
        assert res.resynced and tailer.resyncs == 1
        assert state_of(tailer.runtime) == state_of(rt)

    def test_resync_failure_keeps_previous_runtime(self, tmp_path):
        rt, journal = leader_with_journal(
            tmp_path, segment_max_bytes=400
        )
        ckpt = tmp_path / "state.json"
        tailer = local_tailer(tmp_path, state_path=ckpt)
        tailer.poll_once()
        reference = state_of(tailer.runtime)
        for i in range(8):
            submit(rt, f"wl-{i}")
        journal.compact(journal.last_seq)  # no checkpoint written yet
        ckpt.write_text("{ definitely not json")
        res = tailer.poll_once()
        assert res.error
        assert state_of(tailer.runtime) == reference  # still serving
        checkpoint_to(ckpt, rt)  # checkpoint lands: next poll heals
        res = tailer.poll_once()
        assert res.resynced
        assert state_of(tailer.runtime) == state_of(rt)


# ---- recorder / audit replication primitives ----
class TestIngestPrimitives:
    def test_event_ingest_preserves_resource_version(self):
        from kueue_tpu.core.events import EventRecorder

        leader = EventRecorder()
        replica = EventRecorder()
        leader.record("Admitted", "ns/a", "fits")
        leader.record("Pending", "ns/b", "no quota")
        items, _ = leader.since(0)
        for item in items:
            replica.ingest(item)
        assert replica.resource_version == leader.resource_version
        mirrored, too_old = replica.since(0)
        assert not too_old
        assert [e["resourceVersion"] for e in mirrored] == [
            e["resourceVersion"] for e in items
        ]
        # count-dedup restamp mirrors as an update, not a duplicate
        leader.record("Pending", "ns/b", "no quota")
        items2, _ = leader.since(replica.resource_version)
        for item in items2:
            replica.ingest(item)
        final, _ = replica.since(0)
        assert len(final) == 2
        assert final[-1]["count"] == 2
        assert replica.resource_version == leader.resource_version

    def test_event_note_gap_forces_relist(self):
        from kueue_tpu.core.events import EventRecorder

        replica = EventRecorder()
        replica.ingest(
            {"reason": "Admitted", "object": "ns/a", "message": "",
             "regarding": {"kind": "Workload"}, "resourceVersion": 50}
        )
        replica.note_gap(49)
        _, too_old = replica.since(10)
        assert too_old  # a watcher resumed below the gap must relist
        _, ok = replica.since(50)
        assert not ok

    def test_audit_since_and_ingest_round_trip(self):
        from kueue_tpu.core.audit import DecisionAuditLog, DecisionRecord
        from kueue_tpu.models.constants import InadmissibleReason

        leader = DecisionAuditLog()
        replica = DecisionAuditLog()
        for i in range(3):
            leader.record(
                DecisionRecord(
                    workload=f"ns/w-{i}", cluster_queue="cq", cycle=i,
                    outcome="Pending",
                    reason=InadmissibleReason.INSUFFICIENT_QUOTA,
                )
            )
        # dedup merge restamps: the merged record re-ships
        leader.record(
            DecisionRecord(
                workload="ns/w-0", cluster_queue="cq", cycle=9,
                outcome="Pending",
                reason=InadmissibleReason.INSUFFICIENT_QUOTA,
            )
        )
        delta = leader.since(0)
        assert [d["seq"] for d in delta] == sorted(d["seq"] for d in delta)
        for item in delta:
            replica.ingest(item)
        assert replica.seq == leader.seq
        assert len(replica.for_workload("ns/w-0")) == 1
        assert replica.for_workload("ns/w-0")[0].count == 2
        # incremental: nothing new -> empty delta; fast path == cold
        assert leader.since(leader.seq) == []
        cold = sorted(
            (r.seq for ring in leader._records.values() for r in ring)
        )
        fast = [d["seq"] for d in leader.since(0)]
        assert fast == cold


# ---- HTTP replica serving (the --replica-of surface) ----
def _wl_wire(name, cpu="1000m"):
    return {
        "namespace": "ns", "name": name, "queueName": "lq-0",
        "podSets": [{"name": "main", "count": 1,
                     "requests": {"cpu": cpu}}],
    }


@pytest.fixture()
def http_pair(tmp_path):
    """A live journaled leader server + an attached HTTP read replica
    server (tail driven MANUALLY via pair.sync() — no background
    thread, so tests are deterministic)."""
    from kueue_tpu.replica import ReadReplica
    from kueue_tpu.server import KueueServer
    from kueue_tpu.server.client import KueueClient

    class Pair:
        def __init__(self):
            self.rt = fresh_rt()
            self.journal = Journal(
                str(tmp_path / "journal"), segment_max_bytes=100 << 10
            ).open()
            self.rt.attach_journal(self.journal)
            self.srv = KueueServer(runtime=self.rt)
            port = self.srv.start()
            self.leader_url = f"http://127.0.0.1:{port}"
            self.leader = KueueClient(self.leader_url)
            self.rep = ReadReplica(
                self.leader_url, replica_id="t-rep",
                build_runtime=fresh_rt,
            )
            self.rsrv = KueueServer(replica=self.rep)
            rport = self.rsrv.start()
            self.replica_url = f"http://127.0.0.1:{rport}"
            self.replica = KueueClient(self.replica_url)
            self.leader.apply("resourceflavors", {"name": "default"})
            self.leader.apply("clusterqueues", cq_dict("cq-0"))
            self.leader.apply(
                "localqueues",
                {"namespace": "ns", "name": "lq-0", "clusterQueue": "cq-0"},
            )
            self.rep.sync(resync=True)

        def sync(self):
            return self.rep.sync()

        def close(self):
            self.rsrv.stop()
            self.srv.stop()
            self.journal.close()

    pair = Pair()
    yield pair
    pair.close()


class TestHTTPReplica:
    def test_reads_follow_leader_and_converge_byte_identical(self, http_pair):
        p = http_pair
        for i in range(5):
            p.leader.apply("workloads", _wl_wire(f"wl-{i}"))
        p.sync()
        # visibility + state served from replayed state
        pending = p.replica.pending_workloads_cq("cq-0")["items"]
        assert [i["name"] for i in pending] == ["wl-4"]
        assert p.replica.served_by_replica
        assert p.replica.last_replica_lag_s is not None
        # the quiescence acceptance check: BYTE-identical state dumps
        assert json.dumps(p.leader.state(), sort_keys=True) == json.dumps(
            p.replica.state(), sort_keys=True
        )

    def test_watch_resource_version_contract_across_the_wire(self, http_pair):
        p = http_pair
        p.leader.apply("workloads", _wl_wire("wl-0"))
        p.sync()
        leader_events = p.leader.events()
        replica_events = p.replica.events()
        assert (
            replica_events["resourceVersion"]
            == leader_events["resourceVersion"]
        )
        assert [
            (e["resourceVersion"], e["reason"], e["object"])
            for e in replica_events["items"]
        ] == [
            (e["resourceVersion"], e["reason"], e["object"])
            for e in leader_events["items"]
        ]
        # a resume cursor taken on the LEADER works on the REPLICA:
        # long-poll returns exactly the events past the cursor
        cursor = leader_events["items"][0]["resourceVersion"]
        out = p.replica._request(
            "GET",
            "/apis/kueue/v1beta1/events?watch=1"
            f"&resourceVersion={cursor}&timeoutSeconds=2",
        )
        assert out["items"]
        assert all(
            e["resourceVersion"] > cursor for e in out["items"]
        )

    def test_explain_and_plan_served_from_replica(self, http_pair):
        p = http_pair
        p.leader.apply("workloads", _wl_wire("wl-big", cpu="8000m"))
        p.sync()
        rows = p.replica.workload_decisions("ns", "wl-big")["items"]
        assert rows and rows[-1]["reason"] == "RequestExceedsMaxCapacity"
        assert rows == p.leader.workload_decisions("ns", "wl-big")["items"]
        # plan is best-effort-stale but SERVED (leader-only pre-replica)
        report = p.replica.plan(workload="ns/wl-big")
        assert report["scenarios"]
        assert p.replica.served_by_replica

    def test_writes_redirect_and_client_follows(self, http_pair):
        p = http_pair
        out = p.replica.apply("workloads", _wl_wire("wl-via-replica"))
        assert out["applied"]["name"] == "wl-via-replica"
        assert p.replica.last_redirected_to.startswith(p.leader_url)
        p.sync()
        assert "ns/wl-via-replica" in [
            f"{w['namespace']}/{w['name']}"
            for w in p.replica.list("workloads")
        ]
        # delete + reconcile redirect too
        p.replica.delete_workload("ns", "wl-via-replica")
        p.replica.reconcile()
        p.sync()
        assert "wl-via-replica" not in [
            w["name"] for w in p.replica.list("workloads")
        ]

    def test_redirect_without_follow_is_307_with_location(self, http_pair):
        import urllib.request

        p = http_pair
        req = urllib.request.Request(
            f"{p.replica_url}/reconcile", data=b"{}", method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("expected a 307")
        except urllib.error.HTTPError as e:
            assert e.code == 307
            assert e.headers["Location"] == f"{p.leader_url}/reconcile"

    def test_roster_health_metrics_and_dump(self, http_pair):
        from kueue_tpu.debugger import dump

        p = http_pair
        p.leader.apply("workloads", _wl_wire("wl-0"))
        p.sync()
        # the roster holds the appliedSeq AS OF each poll request (the
        # replica reports its pre-poll position); after a caught-up
        # second poll the leader sees it fully current
        p.sync()
        roster = p.leader.replicas()
        assert roster["role"] == "leader"
        assert [r["id"] for r in roster["items"]] == ["t-rep"]
        assert roster["items"][0]["behind"] == 0
        mine = p.replica.replicas()
        assert mine["role"] == "replica"
        assert mine["items"][0]["appliedSeq"] == p.journal.last_seq
        health = p.replica.healthz()
        assert health["replication"]["role"] == "replica"
        assert health["replication"]["appliedSeq"] == p.journal.last_seq
        assert health["status"] == "ok"
        metrics = p.replica.metrics_text()
        assert (
            f"kueue_replica_applied_seq {p.journal.last_seq}" in metrics
        )
        assert "kueue_replica_lag_seconds" in metrics
        assert "kueue_replica_resyncs_total" in metrics
        # leader metrics materialize the same series at zero
        assert "kueue_replica_applied_seq 0" in p.leader.metrics_text()
        # dashboard + SIGUSR2 replication sections
        board = p.replica.dashboard()
        assert board["replication"]["role"] == "replica"
        text = dump(p.rep.runtime)
        assert "-- replication (journal-tailing read replicas) --" in text
        assert "role=replica" in text

    def test_tail_during_compaction_over_http(self, http_pair):
        p = http_pair
        for i in range(6):
            p.leader.apply("workloads", _wl_wire(f"wl-{i}"))
        # leader compacts everything (the checkpoint IS /state here):
        # the replica's resume prefix is gone mid-tail
        p.journal.sync()
        p.journal.compact(p.journal.last_seq)
        res = p.sync()
        assert res.resynced
        assert p.rep.tailer.resyncs >= 1  # initial anchor + this one
        assert json.dumps(p.leader.state(), sort_keys=True) == json.dumps(
            p.replica.state(), sort_keys=True
        )
        # and incremental tailing resumes afterwards
        p.leader.apply("workloads", _wl_wire("wl-post"))
        res = p.sync()
        assert res.applied > 0 and not res.resynced

    def test_sse_stream_serves_mirrored_events(self, http_pair):
        p = http_pair
        p.leader.apply("workloads", _wl_wire("wl-0"))
        p.sync()
        got = []
        gen = p.replica.stream_events(resource_version=0)

        def pull():
            for ev in gen:
                got.append(ev)
                if len(got) >= 2:
                    return

        t = threading.Thread(target=pull, daemon=True)
        t.start()
        t.join(timeout=10)
        assert len(got) >= 2
        assert all(ev["resourceVersion"] > 0 for ev in got)


# ---- serve-bench plumbing (unit level; the full A/B runs in bench) ----
class TestServeBenchPlumbing:
    def test_http_source_against_live_leader(self, http_pair):
        p = http_pair
        tailer = JournalTailer(
            HTTPTailSource(p.leader_url, replica_id="unit-src"),
            build_runtime=fresh_rt,
        )
        p.leader.apply("workloads", _wl_wire("wl-0"))
        res = tailer.poll_once()
        assert res.applied > 0
        assert state_of(tailer.runtime) == state_of(p.rt)

    def test_http_source_unreachable_is_contained(self):
        tailer = JournalTailer(
            HTTPTailSource("http://127.0.0.1:1", timeout=0.5),
            build_runtime=fresh_rt,
        )
        res = tailer.poll_once()
        assert res.error and tailer.last_error
        with pytest.raises(TailSourceError):
            tailer.source.checkpoint()


class TestTailerClockAndLocking:
    """kueuelint satellites: LocalTailSource stamps leader_time through
    its injected ``now_fn``, and the tailer's cursors/accounting are
    written under ``lock`` so a status() racing a poll never tears."""

    def test_local_source_leader_time_is_injected(self, tmp_path):
        rt, journal = leader_with_journal(tmp_path)
        submit(rt, "wl-0")
        clock = FakeClock(500.0)
        src = LocalTailSource(
            str(tmp_path / "journal"), now_fn=clock.now
        )
        batch = src.fetch(0)
        assert batch.leader_time == 500.0
        clock.advance(7.0)
        assert src.fetch(batch.last_seq).leader_time == 507.0
        journal.close()

    def test_status_is_consistent_under_concurrent_polls(self, tmp_path):
        """Hammer poll_once from one thread while reading status from
        another: every snapshot must be internally consistent (cursor
        never behind recordsApplied progress seen earlier)."""
        rt, journal = leader_with_journal(tmp_path)
        tailer = local_tailer(tmp_path)
        errors = []
        stop = threading.Event()

        def reader():
            last_applied = -1
            while not stop.is_set():
                st = tailer.status()
                if st["appliedSeq"] < last_applied:
                    errors.append(
                        f"appliedSeq regressed: {st['appliedSeq']} < "
                        f"{last_applied}"
                    )
                last_applied = st["appliedSeq"]

        t = threading.Thread(target=reader)
        t.start()
        try:
            for i in range(20):
                submit(rt, f"wl-{i}")
                tailer.poll_once()
        finally:
            stop.set()
            t.join()
        assert not errors, errors
        assert tailer.status()["appliedSeq"] == journal.last_seq
        journal.close()


class TestServerClockInjection:
    """kueuelint clock-discipline satellite: the serving surface's
    timestamps (feed leaderTime, roster staleness) come from the
    runtime's injected clock, so a FakeClock pins them."""

    def test_feed_leader_time_and_roster_staleness_use_runtime_clock(
        self, tmp_path
    ):
        from kueue_tpu.server import KueueServer
        from kueue_tpu.server.client import KueueClient

        rt, journal = leader_with_journal(tmp_path)
        rt.clock.set(1000.0)
        srv = KueueServer(runtime=rt)
        assert srv.clock is rt.clock
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            out = client.journal_tail(
                since_seq=0, replica="rep-a", applied_seq=0, lag_s=0.0
            )
            assert out["leaderTime"] == 1000.0
            rt.clock.advance(12.0)
            roster = client.replicas()
            item = [i for i in roster["items"] if i["id"] == "rep-a"][0]
            assert item["lastSeenAgoS"] == 12.0
        finally:
            srv.stop()
            journal.close()
