"""Batched fair-sharing victim search vs the host tournament.

Randomized decision parity: ops/fair_preempt_kernel (vmapped device
tournament) must produce the same victim sets as
core/preemption._fair_preemptions for every head, across cohort
shapes, weights, borrowing patterns, and both strategy stacks."""

import numpy as np
import pytest

from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import FairSharing, Preemption, ResourceGroup
from kueue_tpu.models.cohort import Cohort
from kueue_tpu.models.constants import (
    PreemptionPolicy,
    ReclaimWithinCohortPolicy,
)
from kueue_tpu.models.workload import PodSet
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.preemption import (
    LESS_THAN_INITIAL_SHARE,
    LESS_THAN_OR_EQUAL_TO_FINAL_SHARE,
    Preemptor,
)
from kueue_tpu.core.preempt_batch import batched_fair_get_targets
from kueue_tpu.core.flavor_assigner import FlavorAssigner
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.core.workload_info import make_admission
from kueue_tpu.utils.clock import FakeClock


def build_fair_cluster(seed, n_cohorts=2, cqs_per_cohort=3, victims_per_cq=3,
                       deep=False, n_res=1):
    """Cohort forest with admitted (partly borrowing) workloads."""
    rng = np.random.default_rng(seed)
    cache = Cache()
    resources = ["cpu", "memory"][:n_res]
    for f in ("fl-a", "fl-b"):
        cache.add_or_update_flavor(ResourceFlavor(name=f))
    prem = Preemption(
        within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
        reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
    )
    cq_names = []
    for ci in range(n_cohorts):
        parent = None
        if deep:
            cache.add_or_update_cohort(Cohort(name=f"root-{ci}"))
            cache.add_or_update_cohort(
                Cohort(name=f"mid-{ci}", parent=f"root-{ci}")
            )
            parent = f"mid-{ci}"
        for qi in range(cqs_per_cohort):
            name = f"cq-{ci}-{qi}"
            cq_names.append(name)
            nfl = int(rng.integers(1, 3))
            quotas = tuple(
                FlavorQuotas.build(
                    f, {res: str(int(rng.integers(4, 12))) for res in resources}
                )
                for f in ("fl-a", "fl-b")[:nfl]
            )
            cache.add_or_update_cluster_queue(
                ClusterQueue(
                    name=name,
                    cohort=(
                        parent
                        if parent is not None and qi % 2 == 0
                        else f"root-{ci}" if deep else f"cohort-{ci}"
                    ),
                    namespace_selector={},
                    resource_groups=(ResourceGroup(tuple(resources), quotas),),
                    preemption=prem,
                    fair_sharing=FairSharing(
                        weight_milli=int(rng.choice([500, 1000, 1000, 2000]))
                    ),
                )
            )
            flavor_names = [q.name for q in quotas]
            for vi in range(int(rng.integers(1, victims_per_cq + 1))):
                wl = Workload(
                    namespace="ns", name=f"v-{ci}-{qi}-{vi}",
                    queue_name=f"lq-{name}",
                    priority=int(rng.integers(0, 3)) * 10,
                    creation_time=float(rng.integers(0, 100)),
                    pod_sets=(
                        PodSet.build(
                            "main", int(rng.integers(1, 4)),
                            {
                                res: str(int(rng.integers(1, 5)))
                                for res in resources
                            },
                        ),
                    ),
                )
                flavor = flavor_names[int(rng.integers(0, len(flavor_names)))]
                wl.admission = make_admission(
                    name, {"main": {res: flavor for res in resources}}, wl
                )
                from kueue_tpu.models import WorkloadConditionType

                wl.set_condition(
                    WorkloadConditionType.QUOTA_RESERVED, True,
                    reason="QuotaReserved", now=float(vi),
                )
                cache.add_or_update_workload(wl)
    return cache, cq_names


def fair_items(cache, cq_names, seed, n_heads=6):
    """Preempt-mode heads with their assignments (host authority)."""
    rng = np.random.default_rng(seed + 1000)
    snapshot = take_snapshot(cache)
    assigner = FlavorAssigner(
        snapshot, cache.flavors, enable_fair_sharing=True
    )
    items = []
    for i in range(n_heads):
        cq_name = cq_names[int(rng.integers(0, len(cq_names)))]
        wl = Workload(
            namespace="ns", name=f"head-{i}", queue_name=f"lq-{cq_name}",
            priority=100, creation_time=1000.0 + i,
            pod_sets=(
                PodSet.build(
                    "main", int(rng.integers(1, 3)),
                    {"cpu": str(int(rng.integers(2, 8)))},
                ),
            ),
        )
        assignment = assigner.assign(wl, cq_name)
        from kueue_tpu.core.flavor_assigner import Mode

        if assignment.representative_mode() == Mode.PREEMPT:
            items.append((wl, cq_name, assignment))
    return snapshot, items


def assert_fair_parity(seed, strategies, **cluster_kw):
    cache, cq_names = build_fair_cluster(seed, **cluster_kw)
    snapshot, items = fair_items(cache, cq_names, seed)
    if not items:
        pytest.skip("no preempt-mode heads generated")
    preemptor = Preemptor(
        FakeClock(0.0), enable_fair_sharing=True, fs_strategies=strategies
    )
    batched = batched_fair_get_targets(snapshot, items, preemptor)
    for i, (wl, cq_name, assignment) in enumerate(items):
        host = preemptor.get_targets(wl, cq_name, assignment, snapshot)
        host_set = {
            (t.workload.workload.name, t.reason) for t in host
        }
        dev_set = {
            (t.workload.workload.name, t.reason) for t in batched[i]
        }
        assert dev_set == host_set, (
            f"seed={seed} head={wl.name} cq={cq_name}: "
            f"device={sorted(dev_set)} host={sorted(host_set)}"
        )
    return items


BOTH = (LESS_THAN_OR_EQUAL_TO_FINAL_SHARE, LESS_THAN_INITIAL_SHARE)


class TestFairPreemptParity:
    @pytest.mark.parametrize("seed", range(12))
    def test_randomized_default_strategies(self, seed):
        assert_fair_parity(seed, BOTH)

    @pytest.mark.parametrize("seed", range(12, 18))
    def test_randomized_single_strategy(self, seed):
        assert_fair_parity(seed, (LESS_THAN_OR_EQUAL_TO_FINAL_SHARE,))

    @pytest.mark.parametrize("seed", range(18, 24))
    def test_randomized_initial_share_first(self, seed):
        assert_fair_parity(seed, (LESS_THAN_INITIAL_SHARE,))

    @pytest.mark.parametrize("seed", range(24, 32))
    def test_randomized_deep_trees(self, seed):
        assert_fair_parity(seed, BOTH, deep=True, n_cohorts=2)

    @pytest.mark.parametrize("seed", range(32, 38))
    def test_randomized_two_resources(self, seed):
        assert_fair_parity(seed, BOTH, n_res=2)

    def test_some_scenario_produces_targets(self):
        """Sanity: across the seeds at least one head actually preempts
        (guards against vacuous parity)."""
        found = False
        for seed in range(12):
            cache, cq_names = build_fair_cluster(seed)
            snapshot, items = fair_items(cache, cq_names, seed)
            if not items:
                continue
            preemptor = Preemptor(
                FakeClock(0.0), enable_fair_sharing=True, fs_strategies=BOTH
            )
            out = batched_fair_get_targets(snapshot, items, preemptor)
            if any(out):
                found = True
                break
        assert found
