"""Flavor assigner semantics (pkg/scheduler/flavorassigner parity)."""

import numpy as np
import pytest

from kueue_tpu.models import (
    ClusterQueue,
    FlavorFungibility,
    FlavorQuotas,
    Preemption,
    ResourceFlavor,
    ResourceGroup,
    Taint,
    Toleration,
    Workload,
)
from kueue_tpu.models.constants import (
    BorrowWithinCohortPolicy,
    FlavorFungibilityPolicy,
    PreemptionPolicy,
)
from kueue_tpu.models.cluster_queue import BorrowWithinCohort
from kueue_tpu.models.workload import PodSet
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.flavor_assigner import (
    FlavorAssigner,
    GranularMode,
    Mode,
    find_max_counts,
)
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.core.workload_info import make_admission
from kueue_tpu.resources import FlavorResource


def build(cq_specs, flavors=None, admitted=None):
    """cq_specs: list of ClusterQueue; admitted: [(name, cq, flavor, cpu_total)]"""
    cache = Cache()
    for f in flavors or [ResourceFlavor(name="on-demand"), ResourceFlavor(name="spot")]:
        cache.add_or_update_flavor(f)
    for cq in cq_specs:
        cache.add_or_update_cluster_queue(cq)
    for name, cq_name, flavor, cpu in admitted or []:
        wl = Workload(
            namespace="ns", name=name, queue_name="lq",
            pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
        )
        wl.admission = make_admission(cq_name, {"main": {"cpu": flavor}}, wl)
        cache.add_or_update_workload(wl)
    snap = take_snapshot(cache)
    return cache, snap


def two_flavor_cq(name="cq", cohort=None, fungibility=None, preemption=None):
    return ClusterQueue(
        name=name,
        cohort=cohort,
        resource_groups=(
            ResourceGroup(
                ("cpu",),
                (
                    FlavorQuotas.build("on-demand", {"cpu": "4"}),
                    FlavorQuotas.build("spot", {"cpu": "10"}),
                ),
            ),
        ),
        flavor_fungibility=fungibility or FlavorFungibility(),
        preemption=preemption or Preemption(),
    )


def wl_cpu(name, cpu, count=1, **kw):
    return Workload(
        namespace="ns", name=name, queue_name="lq",
        pod_sets=(PodSet.build("main", count, {"cpu": cpu}, **kw),),
    )


def flavors_dict(cache):
    return cache.flavors


def test_fit_first_flavor():
    cache, snap = build([two_flavor_cq()])
    a = FlavorAssigner(snap, flavors_dict(cache))
    res = a.assign(wl_cpu("w", "3"), "cq")
    assert res.representative_mode() == Mode.FIT
    assert res.pod_sets[0].flavors["cpu"].name == "on-demand"
    assert res.usage[FlavorResource("on-demand", "cpu")] == 3000


def test_falls_to_second_flavor_when_first_full():
    cache, snap = build(
        [two_flavor_cq()], admitted=[("used", "cq", "on-demand", "3")]
    )
    a = FlavorAssigner(snap, flavors_dict(cache))
    res = a.assign(wl_cpu("w", "2"), "cq")
    assert res.representative_mode() == Mode.FIT
    assert res.pod_sets[0].flavors["cpu"].name == "spot"


def test_no_fit_exceeds_all():
    cache, snap = build([two_flavor_cq()])
    a = FlavorAssigner(snap, flavors_dict(cache))
    res = a.assign(wl_cpu("w", "11"), "cq")
    assert res.representative_mode() == Mode.NO_FIT
    assert "insufficient quota" in res.message()


def test_preempt_mode_within_nominal():
    # first flavor fully used by another workload; request fits nominal
    cache, snap = build(
        [two_flavor_cq()], admitted=[("used", "cq", "on-demand", "4")]
    )
    # make spot full too so no Fit anywhere
    wl2 = wl_cpu("used2", "10")
    wl2.admission = make_admission("cq", {"main": {"cpu": "spot"}}, wl2)
    cache.add_or_update_workload(wl2)
    snap = take_snapshot(cache)
    a = FlavorAssigner(snap, flavors_dict(cache))
    res = a.assign(wl_cpu("w", "2"), "cq")
    assert res.representative_mode() == Mode.PREEMPT
    # whenCanPreempt=TryNextFlavor (default): both flavors attempted,
    # best (first Preempt) kept
    assert res.pod_sets[0].flavors["cpu"].name == "on-demand"


def test_untolerated_taint_skips_flavor():
    flavors = [
        ResourceFlavor(name="on-demand", node_taints=(Taint(key="reserved"),)),
        ResourceFlavor(name="spot"),
    ]
    cache, snap = build([two_flavor_cq()], flavors=flavors)
    a = FlavorAssigner(snap, flavors_dict(cache))
    res = a.assign(wl_cpu("w", "2"), "cq")
    assert res.pod_sets[0].flavors["cpu"].name == "spot"
    # with a toleration the first flavor is usable again
    res2 = a.assign(
        wl_cpu("w2", "2", tolerations=(Toleration(key="reserved", operator="Exists"),)),
        "cq",
    )
    assert res2.pod_sets[0].flavors["cpu"].name == "on-demand"


def test_node_selector_filters_flavor():
    flavors = [
        ResourceFlavor(name="on-demand", node_labels={"type": "on-demand"}),
        ResourceFlavor(name="spot", node_labels={"type": "spot"}),
    ]
    cache, snap = build([two_flavor_cq()], flavors=flavors)
    a = FlavorAssigner(snap, flavors_dict(cache))
    res = a.assign(wl_cpu("w", "2", node_selector={"type": "spot"}), "cq")
    assert res.pod_sets[0].flavors["cpu"].name == "spot"
    # selector key not among flavor label keys is ignored
    res2 = a.assign(wl_cpu("w2", "2", node_selector={"zone": "z1"}), "cq")
    assert res2.pod_sets[0].flavors["cpu"].name == "on-demand"


def test_borrowing_within_cohort():
    cq_a = two_flavor_cq("cq-a", cohort="team")
    cq_b = two_flavor_cq("cq-b", cohort="team")
    cache, snap = build([cq_a, cq_b])
    a = FlavorAssigner(snap, flavors_dict(cache))
    # 6 cpu > cq-a nominal 4 on-demand, but cohort has 8 on-demand total
    res = a.assign(wl_cpu("w", "6"), "cq-a")
    assert res.representative_mode() == Mode.FIT
    assert res.borrowing
    assert res.pod_sets[0].flavors["cpu"].name == "on-demand"


def test_fungibility_borrow_vs_next_flavor():
    # whenCanBorrow=TryNextFlavor: prefer spot (no borrowing) over
    # borrowing on-demand from the cohort
    fung = FlavorFungibility(
        when_can_borrow=FlavorFungibilityPolicy.TRY_NEXT_FLAVOR
    )
    cq_a = two_flavor_cq("cq-a", cohort="team", fungibility=fung)
    cq_b = two_flavor_cq("cq-b", cohort="team")
    cache, snap = build([cq_a, cq_b])
    a = FlavorAssigner(snap, flavors_dict(cache))
    res = a.assign(wl_cpu("w", "6"), "cq-a")
    assert res.representative_mode() == Mode.FIT
    assert not res.borrowing
    assert res.pod_sets[0].flavors["cpu"].name == "spot"


def test_fungibility_preempt_stops_search():
    # whenCanPreempt=Preempt: stop at first preemptable flavor
    fung = FlavorFungibility(when_can_preempt=FlavorFungibilityPolicy.PREEMPT)
    cache, snap = build(
        [two_flavor_cq(fungibility=fung)],
        admitted=[("used", "cq", "on-demand", "4")],
    )
    a = FlavorAssigner(snap, flavors_dict(cache))
    res = a.assign(wl_cpu("w", "3"), "cq")
    # on-demand is preemptable (3 <= nominal 4); search stops there even
    # though spot would Fit
    assert res.representative_mode() == Mode.PREEMPT
    assert res.pod_sets[0].flavors["cpu"].name == "on-demand"


def test_resume_cursor_last_assignment():
    cache, snap = build(
        [two_flavor_cq()], admitted=[("used", "cq", "on-demand", "4")]
    )
    wl2 = wl_cpu("used2", "10")
    wl2.admission = make_admission("cq", {"main": {"cpu": "spot"}}, wl2)
    cache.add_or_update_workload(wl2)
    snap = take_snapshot(cache)
    a = FlavorAssigner(snap, flavors_dict(cache))
    w = wl_cpu("w", "2")
    res = a.assign(w, "cq")
    assert res.representative_mode() == Mode.PREEMPT
    w.last_assignment = res.last_state
    # cursor recorded: on-demand (idx 0) tried, spot (idx 1) is last =>
    # stored as -1 (wrap to start next time)
    assert res.last_state.last_tried_flavor_idx[0]["cpu"] == -1


def test_reclaim_oracle_upgrades_mode():
    cache, snap = build(
        [two_flavor_cq()], admitted=[("used", "cq", "on-demand", "4")]
    )
    wl2 = wl_cpu("used2", "10")
    wl2.admission = make_admission("cq", {"main": {"cpu": "spot"}}, wl2)
    cache.add_or_update_workload(wl2)
    snap = take_snapshot(cache)
    a = FlavorAssigner(
        snap, flavors_dict(cache), reclaim_oracle=lambda cq, wl, fr, q: True
    )
    res = a.assign(wl_cpu("w", "2"), "cq")
    assert res.pod_sets[0].flavors["cpu"].mode == GranularMode.RECLAIM
    assert res.representative_mode() == Mode.PREEMPT  # public mode


def one_flavor_cq(name, cohort=None, preemption=None):
    return ClusterQueue(
        name=name,
        cohort=cohort,
        resource_groups=(
            ResourceGroup(
                ("cpu",), (FlavorQuotas.build("on-demand", {"cpu": "4"}),)
            ),
        ),
        preemption=preemption or Preemption(),
    )


def test_preempt_while_borrowing_policy():
    # request above nominal: mode NoFit unless borrowWithinCohort allows
    # preempting while borrowing (flavorassigner.go:713-731)
    cache, snap = build(
        [one_flavor_cq("cq-a", cohort="team"), one_flavor_cq("cq-b", cohort="team")],
        admitted=[("used-a", "cq-a", "on-demand", "4"),
                  ("used-b", "cq-b", "on-demand", "4")],
    )
    a = FlavorAssigner(snap, flavors_dict(cache))
    res = a.assign(wl_cpu("w", "6"), "cq-a")
    assert res.representative_mode() == Mode.NO_FIT

    borrow_preempt = Preemption(
        within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
        borrow_within_cohort=BorrowWithinCohort(
            policy=BorrowWithinCohortPolicy.LOWER_PRIORITY
        ),
    )
    cache.add_or_update_cluster_queue(
        one_flavor_cq("cq-a", cohort="team", preemption=borrow_preempt)
    )
    snap2 = take_snapshot(cache)
    a2 = FlavorAssigner(snap2, flavors_dict(cache))
    res2 = a2.assign(wl_cpu("w", "6"), "cq-a")
    assert res2.representative_mode() == Mode.PREEMPT


def test_multiple_podsets_share_usage():
    cache, snap = build([two_flavor_cq()])
    a = FlavorAssigner(snap, flavors_dict(cache))
    wl = Workload(
        namespace="ns", name="w", queue_name="lq",
        pod_sets=(
            PodSet.build("driver", 1, {"cpu": "3"}),
            PodSet.build("workers", 1, {"cpu": "3"}),
        ),
    )
    res = a.assign(wl, "cq")
    assert res.representative_mode() == Mode.FIT
    # driver takes on-demand (4), workers must spill to spot (3+3 > 4)
    assert res.pod_sets[0].flavors["cpu"].name == "on-demand"
    assert res.pod_sets[1].flavors["cpu"].name == "spot"


def test_partial_admission_reducer():
    cache, snap = build([two_flavor_cq()])
    a = FlavorAssigner(snap, flavors_dict(cache))
    # 14 pods x 1cpu > 14 total quota; minCount 2
    wl = Workload(
        namespace="ns", name="w", queue_name="lq",
        pod_sets=(PodSet.build("main", 20, {"cpu": "1"}, min_count=2),),
    )
    counts = find_max_counts(lambda c: a.assign(wl, "cq", counts=c), wl)
    assert counts is not None
    # one flavor per (podset, resource): best single flavor is spot (10)
    assert counts[0] == 10
    res = a.assign(wl, "cq", counts=counts)
    assert res.representative_mode() == Mode.FIT


def test_pods_resource_implicit():
    cq = ClusterQueue(
        name="cq",
        resource_groups=(
            ResourceGroup(
                ("cpu", "pods"),
                (FlavorQuotas.build("on-demand", {"cpu": "100", "pods": "3"}),),
            ),
        ),
    )
    cache, snap = build([cq])
    a = FlavorAssigner(snap, flavors_dict(cache))
    res = a.assign(wl_cpu("w", "1", count=5), "cq")
    # 5 pods > pods quota 3
    assert res.representative_mode() != Mode.FIT
