"""Cache + snapshot behavior (pkg/cache parity) and np/JAX kernel parity."""

import numpy as np

from kueue_tpu.models import (
    AdmissionCheck,
    ClusterQueue,
    Cohort,
    FlavorQuotas,
    ResourceFlavor,
    ResourceGroup,
    Topology,
    TopologyLevel,
    Workload,
)
from kueue_tpu.models.constants import StopPolicy
from kueue_tpu.models.workload import PodSet
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.core.workload_info import admission_usage, make_admission
from kueue_tpu.resources import FlavorResource


def build_cache():
    cache = Cache()
    cache.add_or_update_flavor(ResourceFlavor(name="on-demand"))
    cache.add_or_update_flavor(ResourceFlavor(name="spot"))
    cq_a = ClusterQueue(
        name="cq-a",
        cohort="team",
        resource_groups=(
            ResourceGroup(
                ("cpu", "memory"),
                (
                    FlavorQuotas.build("on-demand", {"cpu": "10", "memory": "10Gi"}),
                    FlavorQuotas.build("spot", {"cpu": "20", "memory": "20Gi"}),
                ),
            ),
        ),
    )
    cq_b = ClusterQueue(
        name="cq-b",
        cohort="team",
        resource_groups=(
            ResourceGroup(
                ("cpu", "memory"),
                (FlavorQuotas.build("on-demand", {"cpu": "5", "memory": "5Gi"}),),
            ),
        ),
    )
    cache.add_or_update_cluster_queue(cq_a)
    cache.add_or_update_cluster_queue(cq_b)
    return cache


def admitted_wl(name, cq, cpu_per_pod="1", count=2):
    wl = Workload(
        namespace="ns",
        name=name,
        queue_name="lq",
        pod_sets=(PodSet.build("main", count, {"cpu": cpu_per_pod, "memory": "1Gi"}),),
    )
    wl.admission = make_admission(
        cq, {"main": {"cpu": "on-demand", "memory": "on-demand"}}, wl
    )
    return wl


def test_admission_usage_vector():
    wl = admitted_wl("w", "cq-a")
    usage = admission_usage(wl)
    assert usage[FlavorResource("on-demand", "cpu")] == 2000
    assert usage[FlavorResource("on-demand", "memory")] == 2 * 2**30


def test_reclaimable_pods_discount_usage():
    wl = admitted_wl("w", "cq-a", count=4)
    wl.reclaimable_pods["main"] = 1
    usage = admission_usage(wl)
    assert usage[FlavorResource("on-demand", "cpu")] == 3000


def test_cache_usage_tracking():
    cache = build_cache()
    wl = admitted_wl("w1", "cq-a")
    assert cache.add_or_update_workload(wl)
    assert cache.usage_for("cq-a")[FlavorResource("on-demand", "cpu")] == 2000
    assert cache.delete_workload(wl)
    assert cache.usage_for("cq-a")[FlavorResource("on-demand", "cpu")] == 0


def test_assume_and_forget():
    cache = build_cache()
    wl = admitted_wl("w1", "cq-a")
    assert cache.assume_workload(wl)
    assert not cache.assume_workload(wl)  # double assume rejected
    assert cache.usage_for("cq-a")[FlavorResource("on-demand", "cpu")] == 2000
    assert cache.forget_workload(wl)
    assert cache.usage_for("cq-a")[FlavorResource("on-demand", "cpu")] == 0
    assert not cache.forget_workload(wl)


def test_cq_status_reasons():
    cache = Cache()
    cq = ClusterQueue(
        name="cq",
        resource_groups=(
            ResourceGroup(("cpu",), (FlavorQuotas.build("missing", {"cpu": "1"}),)),
        ),
        admission_checks=("nonexistent",),
    )
    cache.add_or_update_cluster_queue(cq)
    st = cache.cluster_queue_status("cq")
    assert not st.active
    assert "FlavorNotFound" in st.reasons
    assert "AdmissionCheckNotFound" in st.reasons
    cache.add_or_update_flavor(ResourceFlavor(name="missing"))
    cache.add_or_update_admission_check(
        AdmissionCheck(name="nonexistent", controller_name="ctrl")
    )
    assert cache.cluster_queue_status("cq").active


def test_cq_status_tas_misconfig():
    cache = Cache()
    cache.add_or_update_flavor(ResourceFlavor(name="tpu", topology_name="default"))
    cq = ClusterQueue(
        name="cq",
        resource_groups=(
            ResourceGroup(("cpu",), (FlavorQuotas.build("tpu", {"cpu": "1"}),)),
        ),
    )
    cache.add_or_update_cluster_queue(cq)
    assert "TopologyNotFound" in cache.cluster_queue_status("cq").reasons
    cache.add_or_update_topology(
        Topology(name="default", levels=(TopologyLevel("rack"), TopologyLevel("host")))
    )
    assert cache.cluster_queue_status("cq").active


def test_stopped_cq_inactive():
    cache = build_cache()
    model = cache.cluster_queues["cq-a"].model
    import dataclasses

    stopped = dataclasses.replace(model, stop_policy=StopPolicy.HOLD)
    cache.add_or_update_cluster_queue(stopped)
    assert "Stopped" in cache.cluster_queue_status("cq-a").reasons
    snap = take_snapshot(cache)
    assert "cq-a" in snap.inactive_cqs
    assert "cq-b" in snap.flat.cq_names


def test_snapshot_quota_and_fits():
    cache = build_cache()
    cache.add_or_update_workload(admitted_wl("w1", "cq-a", count=8))  # 8 cpu
    snap = take_snapshot(cache)
    od_cpu = snap.fr_index[FlavorResource("on-demand", "cpu")]
    # cohort subtree: 10+20 (cq-a) + 5 (cq-b) = 35 cpu across flavors;
    # on-demand cpu cell: 10 + 5 = 15
    team_row = snap.flat.index["team"]
    assert snap.subtree[team_row, od_cpu] == 15_000
    # cq-b can use on-demand cpu: 15 - 8 used = 7
    assert snap.available_for("cq-b")[od_cpu] == 7_000
    vec = np.zeros(len(snap.fr_list), dtype=np.int64)
    vec[od_cpu] = 7_000
    assert snap.fits("cq-b", vec)
    vec[od_cpu] = 7_001
    assert not snap.fits("cq-b", vec)


def test_snapshot_simulate_remove_workload():
    cache = build_cache()
    cache.add_or_update_workload(admitted_wl("w1", "cq-a", count=8))
    snap = take_snapshot(cache)
    od_cpu = snap.fr_index[FlavorResource("on-demand", "cpu")]
    ws = snap.remove_workload("ns/w1")
    assert ws is not None
    assert snap.available_for("cq-b")[od_cpu] == 15_000
    snap.add_workload(ws)  # undo
    assert snap.available_for("cq-b")[od_cpu] == 7_000


def test_snapshot_cohort_members():
    cache = build_cache()
    lone = ClusterQueue(
        name="lone",
        resource_groups=(
            ResourceGroup(("cpu",), (FlavorQuotas.build("on-demand", {"cpu": "1"}),)),
        ),
    )
    cache.add_or_update_cluster_queue(lone)
    snap = take_snapshot(cache)
    assert snap.cohort_members("cq-a") == {"cq-a", "cq-b"}
    assert snap.cohort_members("lone") == {"lone"}
    assert not snap.has_cohort("lone")


def test_np_jax_kernel_parity():
    """The host-side numpy mirrors must agree with the jit kernels."""
    from kueue_tpu._jax import jnp
    from kueue_tpu.ops import quota as qj
    from kueue_tpu.ops import quota_np as qn

    rng = np.random.default_rng(7)
    cache = build_cache()
    cache.add_or_update_cohort(Cohort(name="team", parent="org"))
    cache.add_or_update_cohort(
        Cohort(
            name="org",
            resource_groups=(
                ResourceGroup(
                    ("cpu",), (FlavorQuotas.build("on-demand", {"cpu": "100"}),)
                ),
            ),
        )
    )
    snap = take_snapshot(cache)
    n, fr = snap.local_usage.shape
    local = rng.integers(0, 30_000, size=(n, fr)).astype(np.int64)
    local[snap.flat.n_cq :] = 0
    lm = snap.flat.level_masks()

    st_np, g_np = qn.subtree_quota_np(snap.flat.parent, lm, snap.nominal, snap.lending_limit)
    u_np = qn.usage_tree_np(snap.flat.parent, lm, g_np, local)
    a_np = qn.available_all_np(snap.flat.parent, lm, st_np, g_np, snap.borrowing_limit, u_np)

    tree = qj.QuotaTree(
        parent=jnp.asarray(snap.flat.parent),
        level_mask=jnp.asarray(lm),
        nominal=jnp.asarray(snap.nominal),
        lending_limit=jnp.asarray(snap.lending_limit),
        borrowing_limit=jnp.asarray(snap.borrowing_limit),
    )
    st_j, g_j = qj.subtree_quota(tree)
    u_j = qj.usage_tree(tree, g_j, jnp.asarray(local))
    a_j = qj.available_all(tree, st_j, g_j, u_j)

    np.testing.assert_array_equal(st_np, np.asarray(st_j))
    np.testing.assert_array_equal(g_np, np.asarray(g_j))
    np.testing.assert_array_equal(u_np, np.asarray(u_j))
    np.testing.assert_array_equal(a_np, np.asarray(a_j))

    wl_req = rng.integers(0, 10_000, size=(n, fr)).astype(np.int64)
    weight = np.where(rng.random(n) < 0.2, 0, 1000).astype(np.int64)
    d_np, dom_np = qn.dominant_resource_share_np(
        snap.flat.parent, lm, st_np, g_np, snap.borrowing_limit, u_np,
        wl_req, weight, snap.resource_index, len(snap.resource_names),
    )
    d_j, dom_j = qj.dominant_resource_share(
        tree, st_j, g_j, u_j, jnp.asarray(wl_req), jnp.asarray(weight),
        jnp.asarray(snap.resource_index), len(snap.resource_names),
    )
    np.testing.assert_array_equal(d_np, np.asarray(d_j))
    np.testing.assert_array_equal(dom_np, np.asarray(dom_j))


def test_incremental_available_row_parity():
    """available_row (path-walk over incrementally-maintained tree
    usage) must match the full available_all_np reduction cell-for-cell
    across random interleaved add/remove mutations."""
    import numpy as np

    from kueue_tpu.models import ClusterQueue, ResourceFlavor, LocalQueue
    from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
    from kueue_tpu.models.cohort import Cohort
    from kueue_tpu.core.cache import Cache
    from kueue_tpu.core.snapshot import take_snapshot

    rng = np.random.default_rng(7)
    cache = Cache()
    cache.add_or_update_flavor(ResourceFlavor(name="f"))
    # depth-3 forest: root <- mid-a/mid-b <- cqs, with lending/borrowing
    cache.add_or_update_cohort(Cohort(name="root"))
    cache.add_or_update_cohort(Cohort(name="mid-a", parent="root"))
    cache.add_or_update_cohort(Cohort(name="mid-b", parent="root"))
    names = []
    for i in range(8):
        name = f"cq{i}"
        names.append(name)
        cache.add_or_update_cluster_queue(
            ClusterQueue(
                name=name,
                cohort="mid-a" if i % 2 else "mid-b",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (
                            FlavorQuotas.build(
                                "f",
                                {
                                    "cpu": (
                                        str(4 + i),
                                        str(3),  # borrowingLimit
                                        str(2),  # lendingLimit
                                    )
                                },
                            ),
                        ),
                    ),
                ),
            )
        )
    snap = take_snapshot(cache)
    # force the incremental structures alive before mutations
    for name in names:
        snap.available_row(snap.row(name))
    for step in range(200):
        name = names[int(rng.integers(0, len(names)))]
        vec = np.zeros(len(snap.fr_list), dtype=np.int64)
        vec[int(rng.integers(0, len(snap.fr_list)))] = int(rng.integers(1, 5000))
        if rng.random() < 0.5:
            snap.add_usage(name, vec)
        else:
            snap.remove_usage(name, vec)
        full = snap.available()
        for q in names:
            r = snap.row(q)
            np.testing.assert_array_equal(
                snap.available_row(r), full[r], err_msg=f"step {step} row {q}"
            )
