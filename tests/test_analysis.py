"""kueuelint (kueue_tpu/analysis) — tier-1 suite.

Three layers:

- **fixture snippets per rule**: each rule must flag its known-bad
  snippet, pass the clean twin, and honor ``# kueuelint: disable=``
  pragmas. The kernel-dtype bad fixture reproduces the TAS s64/s32
  dynamic-update-slice mix (the PR-8 GSPMD miscompile) and the
  journal-symmetry bad fixture deletes a recovery handler (the PR-9
  convergence-bug shape) — both acceptance criteria of ISSUE 11.
- **engine units**: pragmas, Finding ordering, baseline parse/match/
  shrink-only ratchet, CLI exit codes.
- **the package gate**: the full rule suite over the real tree must
  be clean modulo the checked-in baseline, and every baseline entry
  must still resolve to a real file:line AND a current finding
  (stale-baseline check).
"""

import os

import pytest

from kueue_tpu.analysis import (
    Baseline,
    BaselineEntry,
    Finding,
    lint,
    repo_root,
    rule_names,
    run_analysis,
)
from kueue_tpu.analysis.baseline import DEFAULT_BASELINE_PATH
from kueue_tpu.analysis.core import SourceFile


def write_tree(root, files):
    for rel, text in files.items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(text)


def run_fixture(tmp_path, files, rules, config=None):
    # each call gets a fresh tree so one test's bad fixture cannot
    # leak into its clean twin's run
    n = len(os.listdir(str(tmp_path)))
    root = os.path.join(str(tmp_path), f"case{n}")
    write_tree(root, files)
    cfg = {"require_call_sites": False}
    cfg.update(config or {})
    return run_analysis(root, rules=rules, subdir="", config=cfg)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---- kernel-dtype ----
TAS_DUS_BAD = '''\
import jax.numpy as jnp
from jax import lax


def tas_step(free):
    cur = jnp.zeros((4,), dtype=jnp.int32)
    adm = jnp.zeros((4, 8), dtype=jnp.int64)
    row = jnp.zeros((1, 8), dtype=jnp.int32)
    adm = lax.dynamic_update_slice(adm, row, (cur[0], 0))
    hit = cur[0] == adm[0, 0]
    mix = cur[0] + adm[0, 0]
    return adm, hit, mix
'''

TAS_DUS_GOOD = '''\
import jax.numpy as jnp
from jax import lax


def tas_step(free):
    cur = jnp.zeros((4,), dtype=jnp.int32)
    adm = jnp.zeros((4, 8), dtype=jnp.int64)
    row = jnp.zeros((1, 8), dtype=jnp.int32)
    adm = lax.dynamic_update_slice(adm, row.astype(jnp.int64), (cur[0], 0))
    cur64 = cur.astype(jnp.int64)
    hit = cur64[0] == adm[0, 0]
    mix = cur64[0] + adm[0, 0]
    return adm, hit, mix
'''


class TestKernelDtypeRule:
    def test_flags_the_tas_s64_s32_dus_mix(self, tmp_path):
        """ISSUE-11 acceptance: the exact historical miscompile shape
        is caught at lint time."""
        findings = run_fixture(
            tmp_path, {"ops/tas_fixture_kernel.py": TAS_DUS_BAD},
            rules=["kernel-dtype"],
        )
        messages = "\n".join(f.message for f in findings)
        assert any("dynamic_update_slice" in f.message for f in findings)
        assert any("comparison" in f.message for f in findings)
        assert any("promotion" in f.message for f in findings)
        assert all(f.rule == "kernel-dtype" for f in findings), messages

    def test_passes_the_astype_aligned_twin(self, tmp_path):
        assert run_fixture(
            tmp_path, {"ops/tas_fixture_kernel.py": TAS_DUS_GOOD},
            rules=["kernel-dtype"],
        ) == []

    def test_at_update_sugar_is_covered(self, tmp_path):
        src = (
            "import jax.numpy as jnp\n\n\n"
            "def k():\n"
            "    a = jnp.zeros((4,), dtype=jnp.int64)\n"
            "    v = jnp.ones((4,), dtype=jnp.int32)\n"
            "    return a.at[0].set(v[0])\n"
        )
        findings = run_fixture(
            tmp_path, {"ops/at_kernel.py": src}, rules=["kernel-dtype"]
        )
        assert len(findings) == 1 and ".at[...]" in findings[0].message

    def test_scoped_to_kernel_files(self, tmp_path):
        # the same bad source OUTSIDE ops/*_kernel.py is host code
        assert run_fixture(
            tmp_path, {"core/host.py": TAS_DUS_BAD}, rules=["kernel-dtype"]
        ) == []

    def test_pragma_suppresses(self, tmp_path):
        src = TAS_DUS_BAD.replace(
            "    adm = lax.dynamic_update_slice(adm, row, (cur[0], 0))",
            "    # kueuelint: disable=kernel-dtype — fixture-justified\n"
            "    adm = lax.dynamic_update_slice(adm, row, (cur[0], 0))",
        )
        findings = run_fixture(
            tmp_path, {"ops/tas_fixture_kernel.py": src},
            rules=["kernel-dtype"],
        )
        assert not any(
            "dynamic_update_slice" in f.message for f in findings
        )


# ---- trace-safety ----
TRACE_BAD = '''\
import random
import time

import jax
import jax.numpy as jnp


@jax.jit
def solve(x):
    t0 = time.time()
    jitter = random.random()
    if jnp.any(x > 0):
        x = x + 1
    n = int(jnp.sum(x))
    y = x.item()
    return x, t0, jitter, n, y


def body(c):
    time.monotonic()
    return c


stepper = jax.vmap(body)
'''

TRACE_GOOD = '''\
import time

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def solve(x):
    x = jnp.where(jnp.any(x > 0), x + 1, x)
    return lax.cond(x.sum() > 0, lambda v: v, lambda v: v * 0, x)


def host_loop(x):
    # host code may read clocks freely — it is not traced
    t0 = time.monotonic()
    return solve(x), t0
'''


class TestTraceSafetyRule:
    def test_flags_host_calls_in_jitted_fn(self, tmp_path):
        findings = run_fixture(
            tmp_path, {"ops/jitted.py": TRACE_BAD}, rules=["trace-safety"]
        )
        msgs = [f.message for f in findings]
        assert any("time.time()" in m for m in msgs)
        assert any("random.random()" in m for m in msgs)
        assert any("`if` on a traced value" in m for m in msgs)
        assert any("int() over a traced value" in m for m in msgs)
        assert any(".item()" in m for m in msgs)
        # the vmapped-by-name body is traced too
        assert any("time.monotonic()" in m and "body" in m for m in msgs)

    def test_passes_clean_kernel_and_host_code(self, tmp_path):
        assert run_fixture(
            tmp_path, {"ops/clean.py": TRACE_GOOD}, rules=["trace-safety"]
        ) == []

    def test_pragma_suppresses(self, tmp_path):
        src = TRACE_BAD.replace(
            "    t0 = time.time()",
            "    t0 = time.time()  # kueuelint: disable=trace-safety",
        )
        findings = run_fixture(
            tmp_path, {"ops/jitted.py": src}, rules=["trace-safety"]
        )
        assert not any("time.time()" in f.message for f in findings)


# host-side effects reachable inside a lax.while_loop body in the
# kernel package — the megaloop's io_callback-free contract: nothing
# inside a fused device loop may journal, record or fire fault points
TRACE_EFFECT_BAD = '''\
import jax.numpy as jnp
from jax import lax

from kueue_tpu.testing import faults


def solve_fused(tree, state):
    def body(s):
        faults.fire("cycle.inside_loop")
        return s + jnp.int32(1)

    def cond(s):
        return s < 8

    return lax.while_loop(cond, body, state)


def solve_logged(journal, state):
    def logging_body(s):
        journal.record("round", {"s": 0})
        return s + 1

    return lax.while_loop(lambda s: s < 4, logging_body, state)
'''

TRACE_EFFECT_GOOD = '''\
import jax.numpy as jnp
from jax import lax

from kueue_tpu.testing import faults


def solve_fused(tree, state):
    def body(s):
        return s + jnp.int32(1)

    return lax.while_loop(lambda s: s < 8, body, state)


def launch_and_apply(journal, state):
    # host glue OUTSIDE the trace journals freely: the effect sits on
    # the host side of the launch/fetch split
    out = solve_fused(None, state)
    journal.record("round", {"s": 1})
    faults.fire("cycle.post_solve_pre_apply")
    return out
'''


class TestTraceSafetyHostEffects:
    def test_flags_effects_in_while_loop_bodies(self, tmp_path):
        findings = run_fixture(
            tmp_path,
            {"ops/fused.py": TRACE_EFFECT_BAD},
            rules=["trace-safety"],
        )
        msgs = [f.message for f in findings]
        assert any(
            "faults.fire()" in m and "io_callback-free" in m for m in msgs
        ), msgs
        assert any("journal.record()" in m for m in msgs), msgs

    def test_host_side_effects_outside_trace_pass(self, tmp_path):
        assert run_fixture(
            tmp_path,
            {"ops/fused.py": TRACE_EFFECT_GOOD},
            rules=["trace-safety"],
        ) == []

    def test_scope_is_kernel_package_and_drain_glue(self, tmp_path):
        # the same body outside ops/ + core/drain.py is not in scope
        # for the effect contract (server/event code fires freely)
        assert run_fixture(
            tmp_path,
            {"controllers/loopy.py": TRACE_EFFECT_BAD},
            rules=["trace-safety"],
        ) == []
        findings = run_fixture(
            tmp_path,
            {"core/drain.py": TRACE_EFFECT_BAD},
            rules=["trace-safety"],
        )
        assert findings, "core/drain.py must be in the effect scope"

    def test_real_tree_fused_loops_clean(self):
        """The production contract: the real ops/ kernels (incl. the
        megaloop while_loop) and core/drain.py carry no host effects
        inside traced scope."""
        from kueue_tpu.analysis import lint

        findings = [
            f
            for f in lint(rules=["trace-safety"])
            if "io_callback-free" in f.message
        ]
        assert findings == [], findings


# ---- journal-symmetry ----
SYM_PRODUCER = '''\
UPSERT = "workload_upsert"


class Runtime:
    def _journal_append(self, rtype, data):
        pass

    def add_workload(self, wl):
        self._journal_append(UPSERT, {"wl": wl})

    def quarantine(self, key):
        self._journal_append("quarantine_set", {"key": key})
'''

SYM_RECOVERY = '''\
WORKLOAD_UPSERT = "workload_upsert"
QUARANTINE_SET = "quarantine_set"


def apply_record(rt, rec):
    if rec.type == WORKLOAD_UPSERT:
        rt.add(rec.data)
    elif rec.type in (QUARANTINE_SET,):
        rt.q(rec.data)
'''

SYM_TAILER = '''\
from storage.recovery import apply_record


def poll(rt, recs):
    for rec in recs:
        apply_record(rt, rec)
'''

# The elastic capacity plane journals its grants/revokes from its own
# module (not the runtime funnel file) — the rule must still pair them
# with the recovery handler's membership-tuple dispatch.
SYM_ELASTIC_PRODUCER = '''\
ELASTIC_GRANT = "elastic_grant"
ELASTIC_REVOKE = "elastic_revoke"


class Plane:
    def _grant(self, data):
        self.runtime._journal_append(ELASTIC_GRANT, data)

    def _revoke(self, data):
        self.runtime._journal_append(ELASTIC_REVOKE, data)
'''

SYM_ELASTIC_RECOVERY = '''\
WORKLOAD_UPSERT = "workload_upsert"
QUARANTINE_SET = "quarantine_set"
ELASTIC_GRANT = "elastic_grant"
ELASTIC_REVOKE = "elastic_revoke"
_ELASTIC_TYPES = (ELASTIC_GRANT, ELASTIC_REVOKE)


def apply_record(rt, rec):
    if rec.type == WORKLOAD_UPSERT:
        rt.add(rec.data)
    elif rec.type in (QUARANTINE_SET,):
        rt.q(rec.data)
    elif rec.type in _ELASTIC_TYPES:
        rt.capacity(rec.type, rec.data)
'''


# The delta checkpointer appends its chain marks with kinds IMPORTED
# from the recovery module rather than defined locally — the rule must
# resolve them through the cross-module constants map.
SYM_CKPT_PRODUCER = '''\
from storage.recovery import CHECKPOINT_ANCHOR, CHECKPOINT_DELTA


class DeltaCheckpointer:
    def prepare(self, runtime, full):
        if full:
            runtime._journal_append(CHECKPOINT_ANCHOR, {"name": "a"})
        else:
            runtime._journal_append(CHECKPOINT_DELTA, {"name": "d"})
'''

SYM_CKPT_RECOVERY = '''\
WORKLOAD_UPSERT = "workload_upsert"
QUARANTINE_SET = "quarantine_set"
CHECKPOINT_ANCHOR = "checkpoint_anchor"
CHECKPOINT_DELTA = "checkpoint_delta"
_CHECKPOINT_TYPES = (CHECKPOINT_ANCHOR, CHECKPOINT_DELTA)


def apply_record(rt, rec):
    if rec.type == WORKLOAD_UPSERT:
        rt.add(rec.data)
    elif rec.type in (QUARANTINE_SET,):
        rt.q(rec.data)
    elif rec.type in _CHECKPOINT_TYPES:
        rt.mark(rec.type, rec.data)
'''


class TestJournalSymmetryRule:
    def _tree(self, recovery=SYM_RECOVERY, tailer=SYM_TAILER, extra=None):
        files = {
            "controllers/cluster.py": SYM_PRODUCER,
            "storage/recovery.py": recovery,
        }
        if tailer is not None:
            files["storage/tailer.py"] = tailer
        if extra:
            files.update(extra)
        return files

    def test_symmetric_tree_is_clean(self, tmp_path):
        assert run_fixture(
            tmp_path, self._tree(), rules=["journal-symmetry"]
        ) == []

    def test_deleting_a_handler_fails(self, tmp_path):
        """ISSUE-11 acceptance: remove the quarantine_set handler and
        the appended kind no longer replays — a finding at the append
        site."""
        broken = SYM_RECOVERY.replace(
            "    elif rec.type in (QUARANTINE_SET,):\n        rt.q(rec.data)\n",
            "",
        )
        findings = run_fixture(
            tmp_path, self._tree(recovery=broken),
            rules=["journal-symmetry"],
        )
        assert len(findings) == 1
        f = findings[0]
        assert "quarantine_set" in f.message
        assert f.file == "controllers/cluster.py"

    def test_handler_without_producer_fails(self, tmp_path):
        orphan = SYM_RECOVERY.replace(
            'QUARANTINE_SET = "quarantine_set"',
            'QUARANTINE_SET = "quarantine_set"\nGHOST = "ghost_kind"',
        ).replace(
            "    elif rec.type in (QUARANTINE_SET,):",
            "    elif rec.type in (QUARANTINE_SET, GHOST):",
        )
        findings = run_fixture(
            tmp_path, self._tree(recovery=orphan),
            rules=["journal-symmetry"],
        )
        assert len(findings) == 1
        assert "ghost_kind" in findings[0].message
        assert "dead vocabulary" in findings[0].message

    def test_missing_tailer_path_fails(self, tmp_path):
        findings = run_fixture(
            tmp_path, self._tree(tailer=None), rules=["journal-symmetry"]
        )
        assert len(findings) == 1
        assert "tailer" in findings[0].message

    def test_elastic_kinds_symmetric_tree_is_clean(self, tmp_path):
        """ISSUE-18: elastic_grant/elastic_revoke journaled from the
        capacity plane's own module, replayed via the recovery
        membership tuple — symmetric, no findings."""
        assert run_fixture(
            tmp_path,
            self._tree(
                recovery=SYM_ELASTIC_RECOVERY,
                extra={"elastic/plane.py": SYM_ELASTIC_PRODUCER},
            ),
            rules=["journal-symmetry"],
        ) == []

    def test_elastic_handler_missing_fails_both_kinds(self, tmp_path):
        """Producer present, recovery never taught the elastic kinds:
        one finding per kind, each anchored at the plane's append
        site (crash-recovery would silently drop granted capacity)."""
        findings = run_fixture(
            tmp_path,
            self._tree(extra={"elastic/plane.py": SYM_ELASTIC_PRODUCER}),
            rules=["journal-symmetry"],
        )
        assert len(findings) == 2
        kinds = {("elastic_grant" in f.message, "elastic_revoke" in f.message)
                 for f in findings}
        assert kinds == {(True, False), (False, True)}
        assert all(f.file == "elastic/plane.py" for f in findings)

    def test_elastic_producer_deleted_is_dead_vocabulary(self, tmp_path):
        """Recovery still dispatches the elastic kinds but nothing
        journals them — dead vocabulary findings on the handler."""
        findings = run_fixture(
            tmp_path,
            self._tree(recovery=SYM_ELASTIC_RECOVERY),
            rules=["journal-symmetry"],
        )
        assert len(findings) == 2
        assert all("dead vocabulary" in f.message for f in findings)
        assert all(f.file == "storage/recovery.py" for f in findings)

    def test_checkpoint_kinds_imported_constants_clean(self, tmp_path):
        """ISSUE-19: the checkpointer appends chain marks with kinds
        imported from the recovery module (no local literal) — the
        cross-module constants map pairs them with the recovery
        membership tuple; symmetric, no findings."""
        assert run_fixture(
            tmp_path,
            self._tree(
                recovery=SYM_CKPT_RECOVERY,
                extra={"storage/checkpoint.py": SYM_CKPT_PRODUCER},
            ),
            rules=["journal-symmetry"],
        ) == []

    def test_checkpoint_handler_deleted_fails_both_kinds(self, tmp_path):
        """Delete the _CHECKPOINT_TYPES dispatch arm (constants stay):
        one finding per mark kind, anchored at the checkpointer's
        append sites — replay would drop the chain marks."""
        broken = SYM_CKPT_RECOVERY.replace(
            "    elif rec.type in _CHECKPOINT_TYPES:\n"
            "        rt.mark(rec.type, rec.data)\n",
            "",
        )
        findings = run_fixture(
            tmp_path,
            self._tree(
                recovery=broken,
                extra={"storage/checkpoint.py": SYM_CKPT_PRODUCER},
            ),
            rules=["journal-symmetry"],
        )
        assert len(findings) == 2
        kinds = {("checkpoint_anchor" in f.message,
                  "checkpoint_delta" in f.message)
                 for f in findings}
        assert kinds == {(True, False), (False, True)}
        assert all(f.file == "storage/checkpoint.py" for f in findings)

    def test_checkpoint_producer_deleted_is_dead_vocabulary(self, tmp_path):
        """Recovery still dispatches the checkpoint mark kinds but the
        checkpointer module is gone — dead vocabulary on the handler."""
        findings = run_fixture(
            tmp_path,
            self._tree(recovery=SYM_CKPT_RECOVERY),
            rules=["journal-symmetry"],
        )
        assert len(findings) == 2
        assert all("dead vocabulary" in f.message for f in findings)
        assert all(f.file == "storage/recovery.py" for f in findings)

    def test_real_tree_checkpoint_kinds_paired(self):
        """The production contract: the real storage/checkpoint.py
        appends checkpoint_anchor/checkpoint_delta marks via imported
        constants, and the real recovery module replays them — the
        rule resolves the pairing across modules with no findings."""
        from kueue_tpu.analysis import lint

        assert [f for f in lint(rules=["journal-symmetry"])] == []


# ---- clock-discipline ----
class TestClockDisciplineRule:
    def test_flags_naked_clocks_and_aliases(self, tmp_path):
        src = (
            "import time as _time\n"
            "from datetime import datetime\n\n\n"
            "def stamp():\n"
            "    return _time.time(), datetime.now()\n"
        )
        findings = run_fixture(
            tmp_path, {"core/x.py": src}, rules=["clock-discipline"],
            config={"clock_allowlist": {}},
        )
        assert len(findings) == 2
        assert all("naked" in f.message for f in findings)

    def test_injected_clock_is_clean(self, tmp_path):
        src = (
            "class Thing:\n"
            "    def __init__(self, clock):\n"
            "        self.clock = clock\n\n"
            "    def stamp(self):\n"
            "        return self.clock.now()\n"
        )
        assert run_fixture(
            tmp_path, {"core/x.py": src}, rules=["clock-discipline"],
            config={"clock_allowlist": {}},
        ) == []

    def test_allowlist_scopes_and_stale_entries(self, tmp_path):
        src = (
            "import time\n\n\n"
            "def fallback():\n"
            "    return time.time()\n"
        )
        allow = {"core/x.py::fallback": "documented fallback"}
        assert run_fixture(
            tmp_path, {"core/x.py": src}, rules=["clock-discipline"],
            config={"clock_allowlist": dict(allow)},
        ) == []
        # a stale entry (nothing naked left in scope) is itself flagged
        allow["core/x.py::gone"] = "rotted justification"
        findings = run_fixture(
            tmp_path, {"core/x.py": src}, rules=["clock-discipline"],
            config={"clock_allowlist": allow},
        )
        assert len(findings) == 1 and "stale" in findings[0].message

    def test_every_real_allowlist_entry_is_justified(self):
        from kueue_tpu.analysis.rules_clock import CLOCK_ALLOWLIST

        for scope, why in CLOCK_ALLOWLIST.items():
            assert isinstance(why, str) and len(why) > 20, (
                f"{scope}: allowlist entries carry real justifications"
            )


class TestClockStrictFederationScope:
    """The federation strict sub-scope (ISSUE 15 satellite): under
    kueue_tpu/federation/, duration measurement and sleeps are ALSO
    findings — the FakeClock chaos suites drive that code end to end,
    so even telemetry timing must be allowlisted deliberately."""

    BAD = (
        "import time\n\n\n"
        "def pump():\n"
        "    t0 = time.perf_counter()\n"
        "    time.sleep(0.1)\n"
        "    return time.perf_counter() - t0\n"
    )

    def test_strict_scope_flags_perf_counter_and_sleep(self, tmp_path):
        findings = run_fixture(
            tmp_path,
            {"kueue_tpu/federation/x.py": self.BAD},
            rules=["clock-discipline"],
            config={"clock_allowlist": {}},
        )
        assert len(findings) == 3
        assert all("strict scope" in f.message for f in findings)

    def test_outside_federation_perf_counter_stays_allowed(self, tmp_path):
        assert run_fixture(
            tmp_path,
            {"kueue_tpu/core/x.py": self.BAD},
            rules=["clock-discipline"],
            config={"clock_allowlist": {}},
        ) == []

    def test_strict_scope_honors_allowlist(self, tmp_path):
        allow = {
            "kueue_tpu/federation/x.py::pump": (
                "RTT measurement, reported never scheduled on"
            )
        }
        assert run_fixture(
            tmp_path,
            {"kueue_tpu/federation/x.py": self.BAD},
            rules=["clock-discipline"],
            config={"clock_allowlist": allow},
        ) == []

    def test_strict_prefixes_configurable(self, tmp_path):
        findings = run_fixture(
            tmp_path,
            {"kueue_tpu/core/x.py": self.BAD},
            rules=["clock-discipline"],
            config={
                "clock_allowlist": {},
                "clock_strict_prefixes": ("kueue_tpu/core/",),
            },
        )
        assert len(findings) == 3

    def test_real_federation_tree_is_strict_clean(self):
        """The shipped federation package passes its own strict rule
        (dispatcher RTT + rescore timing ride allowlist entries)."""
        findings = [
            f
            for f in run_analysis(
                repo_root(), rules=["clock-discipline"]
            )
            if f.file.startswith("kueue_tpu/federation/")
        ]
        assert findings == []


# ---- lock-discipline ----
LOCK_BAD = '''\
import threading


class Cursor:
    def __init__(self):
        self._lock = threading.Lock()
        self.pos = 0  # guarded by: _lock

    def advance(self):
        self.pos += 1

    def push(self, item):
        self.items.append(item)
'''

LOCK_GOOD = '''\
import threading


class Cursor:
    def __init__(self):
        self._lock = threading.Lock()
        self.pos = 0  # guarded by: _lock

    def advance(self):
        with self._lock:
            self.pos += 1

    def _bump_locked(self):
        self.pos += 1

    def reset(self):  # kueuelint: holds=_lock
        self.pos = 0
'''


class TestLockDisciplineRule:
    def test_flags_unlocked_writes(self, tmp_path):
        findings = run_fixture(
            tmp_path, {"core/c.py": LOCK_BAD}, rules=["lock-discipline"]
        )
        assert len(findings) == 1
        assert "self.pos" in findings[0].message

    def test_locked_suffix_and_holds_marker_exempt(self, tmp_path):
        assert run_fixture(
            tmp_path, {"core/c.py": LOCK_GOOD}, rules=["lock-discipline"]
        ) == []

    def test_cross_class_write_is_flagged(self, tmp_path):
        other = (
            "from core.c import Cursor\n\n\n"
            "def hammer(cur):\n"
            "    cur.pos += 1\n"
        )
        findings = run_fixture(
            tmp_path,
            {"core/c.py": LOCK_GOOD, "core/other.py": other},
            rules=["lock-discipline"],
        )
        assert len(findings) == 1
        assert findings[0].file == "core/other.py"
        assert "outside class Cursor" in findings[0].message

    def test_ambiguous_attr_names_skip_cross_class_check(self, tmp_path):
        ambiguous = (
            "class Result:\n"
            "    def __init__(self):\n"
            "        self.pos = 0\n\n\n"
            "def fill(res):\n"
            "    res.pos = 5\n"
        )
        assert run_fixture(
            tmp_path,
            {"core/c.py": LOCK_GOOD, "core/res.py": ambiguous},
            rules=["lock-discipline"],
        ) == []

    def test_mutating_container_calls_count_as_writes(self, tmp_path):
        src = LOCK_GOOD.replace(
            "        self.pos = 0  # guarded by: _lock",
            "        self.pos = 0  # guarded by: _lock\n"
            "        self.items = []  # guarded by: _lock",
        ) + (
            "\n    def push(self, item):\n"
            "        self.items.append(item)\n"
        )
        findings = run_fixture(
            tmp_path, {"core/c.py": src}, rules=["lock-discipline"]
        )
        assert len(findings) == 1 and ".append()" in findings[0].message


# ---- registry rules ----
class TestRegistryRules:
    def test_reason_enum(self, tmp_path):
        bad = 'def f(r):\n    r.record("BadReason", "x", "msg")\n'
        good = 'def f(r):\n    r.record("GoodReason", "x", "msg")\n'
        cfg = {"event_reasons": {"GoodReason"}}
        assert run_fixture(
            tmp_path, {"a.py": bad}, rules=["reason-enum"], config=dict(cfg)
        )[0].message.startswith("ad-hoc event reason 'BadReason'")
        assert run_fixture(
            tmp_path, {"b.py": good}, rules=["reason-enum"], config=dict(cfg)
        ) == []
        pragma = bad.replace(
            '    r.record(', '    # kueuelint: disable=reason-enum\n'
            '    r.record(',
        )
        assert run_fixture(
            tmp_path, {"c.py": pragma}, rules=["reason-enum"],
            config=dict(cfg),
        ) == []

    def test_span_name(self, tmp_path):
        cfg = {"span_names": {"cycle.solve"}}
        bad = 'def f(tr):\n    tr.add_cycle_span("cycle.bogus")\n'
        good = 'def f(tr):\n    tr.add_cycle_span("cycle.solve")\n'
        assert "cycle.bogus" in run_fixture(
            tmp_path, {"a.py": bad}, rules=["span-name"], config=dict(cfg)
        )[0].message
        assert run_fixture(
            tmp_path, {"b.py": good}, rules=["span-name"], config=dict(cfg)
        ) == []

    def test_span_name_pattern_rot_guard(self, tmp_path):
        cfg = {"span_names": {"cycle.solve"}, "require_call_sites": True}
        findings = run_fixture(
            tmp_path, {"a.py": "x = 1\n"}, rules=["span-name"],
            config=cfg,
        )
        assert len(findings) == 1 and "rotted" in findings[0].message

    def test_fault_point(self, tmp_path):
        cfg = {"fault_points": {"a.b": "doc"}}
        bad = 'def f(faults):\n    faults.fire("z.q")\n'
        good = (
            "def f(faults, run):\n"
            '    faults.fire("a.b")\n'
            '    run(fault_point="a.b")\n'
        )
        assert "z.q" in run_fixture(
            tmp_path, {"a.py": bad}, rules=["fault-point"], config=dict(cfg)
        )[0].message
        assert run_fixture(
            tmp_path, {"b.py": good}, rules=["fault-point"],
            config=dict(cfg),
        ) == []

    def test_fault_point_unfired_registry_entry(self, tmp_path):
        cfg = {
            "fault_points": {"a.b": "doc", "never.fired": "doc"},
            "require_call_sites": True,
        }
        findings = run_fixture(
            tmp_path, {"a.py": 'def f(faults):\n    faults.fire("a.b")\n'},
            rules=["fault-point"], config=cfg,
        )
        assert len(findings) == 1 and "never.fired" in findings[0].message

    def test_metrics_families(self, tmp_path):
        src = (
            'NS = "kueue"\n\n\n'
            "def build(r):\n"
            '    a = r.counter(f"{NS}_good_total", "help text")\n'
            '    b = r.gauge("unprefixed_thing", "help")\n'
            '    c = r.histogram("kueue_dup_seconds", "help")\n'
            '    d = r.counter("kueue_dup_seconds", "help")\n'
            '    e = r.counter("kueue_empty_total", "")\n'
            "    return a, b, c, d, e\n"
        )
        findings = run_fixture(
            tmp_path, {"metrics/metrics.py": src},
            rules=["metrics-families"],
        )
        msgs = [f.message for f in findings]
        assert any("unprefixed_thing" in m and "prefix" in m for m in msgs)
        assert any("duplicate" in m for m in msgs)
        assert any("empty HELP" in m for m in msgs)
        assert not any("kueue_good_total" in m for m in msgs)

    def test_kernel_mirrors_good_and_bad(self, tmp_path):
        anchor = SourceFile(
            "<mem>", "ops/__init__.py", "KERNEL_MIRRORS = {}\n"
        )
        good = run_analysis(
            repo_root(), rules=["kernel-mirrors"], sources=[anchor],
            config={
                "kernel_stems": {"foo_kernel"},
                "kernel_mirrors": {
                    "foo_kernel": (
                        "kueue_tpu.ops.drain_np:solve_drain_np",
                        "tests/test_drain_parity.py",
                    )
                },
                "sharded_kernels": {},
            },
        )
        assert good == []
        bad = run_analysis(
            repo_root(), rules=["kernel-mirrors"], sources=[anchor],
            config={
                "kernel_stems": {"foo_kernel", "bar_kernel"},
                "kernel_mirrors": {
                    "foo_kernel": (
                        "kueue_tpu.no_such_module:missing",
                        "tests/no_such_test.py",
                    )
                },
                "sharded_kernels": {"baz_kernel": "kueue_tpu.x:y"},
            },
        )
        msgs = [f.message for f in bad]
        assert any("bar_kernel" in m and "no registered" in m for m in msgs)
        assert any("does not import" in m for m in msgs)
        assert any("no_such_test.py" in m for m in msgs)
        assert any("baz_kernel" in m and "sharded" in m for m in msgs)


# ---- deadline-discipline ----
DEADLINE_BAD = '''\
from kueue_tpu.server.client import KueueClient


class Pump:
    def __init__(self, url):
        self.client = KueueClient(url)

    def sync(self, cluster, key):
        return cluster.call("get_workload", key)

    def poll(self):
        return self.client.journal_tail(since_seq=0)
'''

DEADLINE_GOOD = '''\
from kueue_tpu.server.client import KueueClient


class Pump:
    def __init__(self, url):
        self.client = KueueClient(url, timeout=10.0)

    def sync(self, cluster, key, deadline):
        return cluster.call("get_workload", key, deadline_s=deadline)

    def forward(self, cluster, key, **kw):
        return cluster.call("get_workload", key, **kw)

    def poll(self, deadline):
        return self.client.journal_tail(since_seq=0, timeout_s=deadline)
'''


class TestDeadlineDisciplineRule:
    """The gray-failure habit fix (ISSUE 20 satellite): control-loop
    call sites under federation/, replica/ and admissionchecks/ must
    name their per-call deadline instead of riding whatever timeout
    the transport constructor baked in."""

    def test_flags_default_timeout_call_sites(self, tmp_path):
        findings = run_fixture(
            tmp_path,
            {"kueue_tpu/federation/x.py": DEADLINE_BAD},
            rules=["deadline-discipline"],
        )
        msgs = [f.message for f in findings]
        assert len(findings) == 3
        assert any("KueueClient" in m and "timeout=" in m for m in msgs)
        assert any(".call(" in m and "deadline_s=" in m for m in msgs)
        assert any(".journal_tail(" in m and "timeout_s=" in m for m in msgs)

    def test_explicit_deadlines_and_splats_are_clean(self, tmp_path):
        assert run_fixture(
            tmp_path,
            {"kueue_tpu/federation/x.py": DEADLINE_GOOD},
            rules=["deadline-discipline"],
        ) == []

    def test_out_of_scope_files_are_ignored(self, tmp_path):
        # the discipline binds control loops; CLI one-shots, bench
        # scripts and the server glue stay out of scope
        assert run_fixture(
            tmp_path,
            {"kueue_tpu/cli/x.py": DEADLINE_BAD},
            rules=["deadline-discipline"],
        ) == []

    def test_allowlist_scopes_and_stale_entries(self, tmp_path):
        allow = {
            "kueue_tpu/federation/x.py::Pump.sync": "caller-bounded",
            "kueue_tpu/federation/x.py::Pump.__init__": "script glue",
            "kueue_tpu/federation/x.py::Pump.poll": "long-poll wire",
        }
        assert run_fixture(
            tmp_path,
            {"kueue_tpu/federation/x.py": DEADLINE_BAD},
            rules=["deadline-discipline"],
            config={"deadline_allowlist": dict(allow)},
        ) == []
        # a stale entry (scope now clean) is itself a finding
        allow["kueue_tpu/federation/x.py::Pump.gone"] = "rotted"
        findings = run_fixture(
            tmp_path,
            {"kueue_tpu/federation/x.py": DEADLINE_BAD},
            rules=["deadline-discipline"],
            config={"deadline_allowlist": allow},
        )
        assert len(findings) == 1 and "stale" in findings[0].message

    def test_real_tree_is_deadline_clean(self):
        """The production contract: every .call/journal_tail/transport
        construction in the scoped control loops already names its
        bound — no allowlist debt at introduction time."""
        assert lint(rules=["deadline-discipline"]) == []


# ---- engine units ----
class TestEngine:
    def test_parse_error_is_a_finding_not_a_crash(self, tmp_path):
        findings = run_fixture(
            tmp_path, {"bad.py": "def broken(:\n"}, rules=["reason-enum"],
            config={"event_reasons": set()},
        )
        assert len(findings) == 1 and findings[0].rule == "parse-error"

    def test_disable_file_pragma(self, tmp_path):
        src = (
            "# kueuelint: disable-file=clock-discipline\n"
            "import time\n\n\n"
            "def a():\n    return time.time()\n\n\n"
            "def b():\n    return time.time()\n"
        )
        assert run_fixture(
            tmp_path, {"x.py": src}, rules=["clock-discipline"],
            config={"clock_allowlist": {}},
        ) == []

    def test_unknown_rule_rejected(self):
        with pytest.raises(ValueError, match="unknown rule"):
            run_analysis(repo_root(), rules=["no-such-rule"], sources=[])

    def test_finding_str_is_clickable(self):
        f = Finding("kernel-dtype", "kueue_tpu/ops/x.py", 12, "boom")
        assert str(f) == "kueue_tpu/ops/x.py:12: [kernel-dtype] boom"

    def test_rule_registry_is_closed_and_complete(self):
        assert rule_names() == sorted(
            [
                "kernel-dtype", "trace-safety", "journal-symmetry",
                "clock-discipline", "lock-discipline", "reason-enum",
                "span-name", "fault-point", "metrics-families",
                "kernel-mirrors", "policy-name", "deadline-discipline",
            ]
        )


class TestBaseline:
    def _finding(self, msg="m", line=3):
        return Finding("clock-discipline", "kueue_tpu/a.py", line, msg)

    def test_entry_round_trip(self):
        e = BaselineEntry.from_finding(self._finding())
        assert BaselineEntry.parse(e.format()) == e

    def test_split_and_line_drift_tolerance(self):
        base = Baseline([BaselineEntry.from_finding(self._finding())])
        drifted = self._finding(line=99)  # same rule/file/message
        new, suppressed, stale = base.split([drifted])
        assert new == [] and suppressed == [drifted] and stale == []
        other = self._finding(msg="different")
        new, suppressed, stale = base.split([other])
        assert new == [other] and len(stale) == 1

    def test_shrink_never_grows(self):
        base = Baseline([BaselineEntry.from_finding(self._finding())])
        grown_input = [self._finding(), self._finding(msg="new debt")]
        shrunk = base.shrink(grown_input)
        assert len(shrunk) == 1  # the new finding did NOT enter
        assert base.shrink([]).entries == []  # fixed findings drop out
        assert len(base.grown(grown_input)) == 2  # explicit intake only

    def test_stale_locations(self, tmp_path):
        ok = BaselineEntry("r", "real.py", 1, "m")
        gone = BaselineEntry("r", "missing.py", 1, "m")
        far = BaselineEntry("r", "real.py", 99, "m")
        (tmp_path / "real.py").write_text("x = 1\n")
        problems = Baseline([ok, gone, far]).stale_locations(str(tmp_path))
        assert len(problems) == 2
        assert any("does not exist" in p for p in problems)
        assert any("out of range" in p for p in problems)


class TestCLI:
    def _fixture_root(self, tmp_path):
        write_tree(
            str(tmp_path),
            {
                "kueue_tpu/core/x.py": (
                    "import time\n\n\ndef f():\n    return time.time()\n"
                )
            },
        )
        return str(tmp_path)

    def test_exit_2_on_findings_and_0_when_baselined(self, tmp_path, capsys):
        from kueue_tpu.analysis.__main__ import main

        root = self._fixture_root(tmp_path)
        bl = str(tmp_path / "bl.txt")
        rc = main(
            ["--root", root, "--rule", "clock-discipline",
             "--baseline", bl]
        )
        assert rc == 2
        out = capsys.readouterr().out
        assert "[clock-discipline]" in out and "1 new" in out
        # reviewed debt intake -> clean run
        rc = main(
            ["--root", root, "--rule", "clock-discipline",
             "--baseline", bl, "--update-baseline", "--allow-grow"]
        )
        assert rc == 0
        rc = main(
            ["--root", root, "--rule", "clock-discipline",
             "--baseline", bl]
        )
        assert rc == 0  # the intaken entry now suppresses the finding

    def test_update_baseline_is_shrink_only(self, tmp_path, capsys):
        from kueue_tpu.analysis.__main__ import main

        root = self._fixture_root(tmp_path)
        bl = str(tmp_path / "bl.txt")
        main(
            ["--root", root, "--rule", "clock-discipline", "--baseline",
             bl, "--update-baseline", "--allow-grow", "-q"]
        )
        assert len(Baseline.load(bl)) == 1
        # fix the code: the entry must shrink away, plain update only
        write_tree(
            str(tmp_path), {"kueue_tpu/core/x.py": "def f():\n    pass\n"}
        )
        rc = main(
            ["--root", root, "--rule", "clock-discipline", "--baseline",
             bl]
        )
        assert rc == 2  # stale entry: the ratchet demands a shrink
        rc = main(
            ["--root", root, "--rule", "clock-discipline", "--baseline",
             bl, "--update-baseline", "-q"]
        )
        assert rc == 0
        assert len(Baseline.load(bl)) == 0

    def test_list_rules(self, capsys):
        from kueue_tpu.analysis.__main__ import main

        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for name in rule_names():
            assert name in out


# ---- the package gate (tier-1 acceptance) ----
class TestPackageGate:
    def test_full_suite_clean_modulo_baseline(self):
        """`python -m kueue_tpu.analysis` exits 0 over the tree: every
        finding is either fixed or a justified baseline entry."""
        offenders = lint()
        assert offenders == [], "\n".join(str(f) for f in offenders)

    def test_baseline_entries_resolve_and_match(self):
        """Stale-baseline check: every checked-in entry points at a
        real file:line AND matches a current finding (shrink-only —
        fixed findings must leave the baseline)."""
        baseline = Baseline.load(DEFAULT_BASELINE_PATH)
        problems = baseline.stale_locations(repo_root())
        assert problems == [], "\n".join(problems)
        findings = run_analysis(repo_root())
        _new, _suppressed, stale = baseline.split(findings)
        assert stale == [], (
            "baseline entries with no matching finding (run "
            "--update-baseline):\n"
            + "\n".join(e.format() for e in stale)
        )

    def test_cli_exit_zero_over_the_tree(self, capsys):
        from kueue_tpu.analysis.__main__ import main

        assert main([]) == 0
        assert "kueuelint:" in capsys.readouterr().out
