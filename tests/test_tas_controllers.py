"""TAS node lifecycle + topology ungater + device phase-1 threshold.

References mirrored: pkg/controller/tas/resource_flavor.go:71-110 (node
watch), topology_ungater.go:60-136 (per-domain ungating with the
expectations barrier), pkg/util/expectations/store.go:30.
"""

import numpy as np
import pytest

from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
)
from kueue_tpu.models.cluster_queue import ResourceGroup
from kueue_tpu.models.topology import Topology, TopologyLevel
from kueue_tpu.models.workload import PodSetTopologyRequest
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.controllers.jobs.pod import (
    POD_PENDING,
    POD_RUNNING,
    PodGroup,
    SimPod,
)
from kueue_tpu.tas.cache import Node, TASCache
from kueue_tpu.utils.expectations import ExpectationsStore

LEVELS = ("cloud.google.com/block", "cloud.google.com/rack", "kubernetes.io/hostname")


def make_node(name, block, rack, cpu="8", extra_labels=None):
    from kueue_tpu.resources import requests_from_spec

    labels = {
        LEVELS[0]: block,
        LEVELS[1]: rack,
        LEVELS[2]: name,
        "type": "tpu",
    }
    labels.update(extra_labels or {})
    return Node(
        name=name, labels=labels,
        allocatable=requests_from_spec({"cpu": cpu, "pods": "110"}),
    )


def tas_runtime(n_blocks=2, racks_per_block=2, hosts_per_rack=2):
    cache = TASCache()
    rt = ClusterRuntime(tas_cache=cache)
    rt.add_topology(
        Topology(name="default", levels=tuple(TopologyLevel(k) for k in LEVELS))
    )
    rt.add_flavor(
        ResourceFlavor(
            name="tas", node_labels={"type": "tpu"}, topology_name="default"
        )
    )
    for b in range(n_blocks):
        for r in range(racks_per_block):
            for h in range(hosts_per_rack):
                rt.add_node(
                    make_node(f"n-{b}-{r}-{h}", f"block-{b}", f"rack-{b}-{r}")
                )
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("tas", {"cpu": "64"}),)),
            ),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    return rt


class TestExpectationsStore:
    def test_barrier(self):
        store = ExpectationsStore("t")
        assert store.satisfied("k")
        store.expect_uids("k", ["a", "b"])
        assert not store.satisfied("k")
        store.observed_uid("k", "a")
        assert not store.satisfied("k")
        store.observed_uid("k", "b")
        assert store.satisfied("k")
        # observing unknown uids is a no-op
        store.observed_uid("k", "z")
        store.observed_uid("other", "a")
        assert store.satisfied("other")


class TestNodeController:
    def test_node_ingest_updates_capacity(self):
        rt = tas_runtime(n_blocks=1, racks_per_block=1, hosts_per_rack=1)
        snap = rt.cache.tas_cache.flavors["tas"].snapshot()
        assert len(snap.leaves) == 1
        gen = rt.cache.tas_cache.generation
        rt.add_node(make_node("n-x", "block-0", "rack-0-0"))
        assert rt.cache.tas_cache.generation > gen
        assert len(rt.cache.tas_cache.flavors["tas"].snapshot().leaves) == 2
        rt.delete_node("n-x")
        assert len(rt.cache.tas_cache.flavors["tas"].snapshot().leaves) == 1

    def test_non_matching_node_excluded(self):
        rt = tas_runtime(n_blocks=1, racks_per_block=1, hosts_per_rack=1)
        node = make_node("cpu-node", "block-0", "rack-0-0")
        node.labels["type"] = "cpu"
        rt.add_node(node)
        assert len(rt.cache.tas_cache.flavors["tas"].snapshot().leaves) == 1


class TestTopologyUngater:
    def _group(self, rt, n_pods=4, level=LEVELS[1]):
        pods = [
            SimPod.build(f"p{i}", {"cpu": "2"}, rank=i) for i in range(n_pods)
        ]
        job = PodGroup(
            namespace="ns", name="grp", queue="lq",
            total_count=n_pods, pods=pods,
        )
        # pod-group podsets need the topology request on the workload:
        # PodGroup.pod_sets has no topology plumbed; patch via workload
        # after creation (the pod webhook annotation analog)
        rt.add_job(job)
        rt.reconcile_once()
        wl = rt.workloads[f"ns/{rt.job_reconciler.workload_name_for(job)}"]
        pods_sets = list(wl.pod_sets)
        for i, ps in enumerate(pods_sets):
            ps.topology_request = PodSetTopologyRequest(
                mode="Required", level=level
            )
        return job, wl

    def test_gang_placed_and_ungated_per_domain(self):
        rt = tas_runtime()
        job, wl = self._group(rt, n_pods=4)
        rt.run_until_idle()
        assert wl.is_admitted
        psa = wl.admission.pod_set_assignments[0]
        ta = psa.topology_assignment
        assert ta is not None
        assert sum(d.count for d in ta.domains) == 4
        # after the loop, all pods ungated with domain node selectors
        assert all(not p.topology_gate for p in job.pods)
        assert all(p.phase == POD_RUNNING for p in job.pods)
        placed_racks = {p.node_selector.get(LEVELS[1]) for p in job.pods}
        # Required rack level: all pods within ONE rack
        assert len(placed_racks) == 1

    def test_barrier_delays_second_batch(self):
        """Manual reconcile: ungating expects the pod UIDs; a second
        reconcile before the echo is a no-op (errPendingUngateOps)."""
        rt = tas_runtime()
        job, wl = self._group(rt, n_pods=2)
        rt.run_until_idle()
        ung = rt.topology_ungater
        assert ung.ungated_total == 2
        # simulate a fresh gated pod appearing (replacement) while the
        # previous expectations are outstanding
        ung.expectations.expect_uids(wl.key, ["ghost-uid"])
        p_new = SimPod.build("p-late", {"cpu": "2"}, rank=9)
        p_new.topology_gate = True
        p_new.gated = False
        job.pods.append(p_new)
        before = ung.ungated_total
        n = ung.reconcile(wl, job)
        assert n == 0 and ung.pending_reconciles >= 1  # barred
        ung.expectations.observed_uid(wl.key, "ghost-uid")
        # placed pods already fill the domain counts; the late pod only
        # ungates if its domain has room — with count==2 and 2 placed,
        # there is none: still zero
        assert ung.reconcile(wl, job) == 0
        assert ung.ungated_total == before

    def test_rank_order_assignment(self):
        rt = tas_runtime()
        job, wl = self._group(rt, n_pods=4, level=LEVELS[2])  # hostname
        rt.run_until_idle()
        assert wl.is_admitted
        # hostname-level: lowest-rank pods land in domain order
        hosts = [p.node_selector.get(LEVELS[2]) for p in sorted(job.pods, key=lambda p: p.rank)]
        assert all(h is not None for h in hosts)


class TestDeviceLeafCounts:
    @pytest.mark.parametrize("simulate_empty", [False, True])
    def test_device_host_parity(self, simulate_empty, monkeypatch):
        from kueue_tpu.tas.snapshot import TASFlavorSnapshot, TASPodSetRequest

        rt = tas_runtime(n_blocks=3, racks_per_block=2, hosts_per_rack=3)
        fc = rt.cache.tas_cache.flavors["tas"]
        # charge some TAS usage so free != allocatable
        snap_h = fc.snapshot()
        req = TASPodSetRequest(
            podset_name="main", count=5,
            single_pod_requests={"cpu": 2000},
            topology_request=PodSetTopologyRequest(mode="Required", level=LEVELS[1]),
        )
        assumed = {
            next(iter(snap_h.leaves)): {"cpu": 4000, "pods": 2},
        }
        host_counts = snap_h.podset_fit_counts(req, assumed, simulate_empty)

        snap_d = fc.snapshot()
        monkeypatch.setattr(TASFlavorSnapshot, "DEVICE_LEAF_THRESHOLD", 1)
        dev_counts = snap_d.podset_fit_counts(req, assumed, simulate_empty)
        np.testing.assert_array_equal(host_counts, dev_counts)

        # full placement decisions identical through the device path
        host_out = fc.snapshot().find_topology_assignments([req], simulate_empty)
        monkeypatch.setattr(TASFlavorSnapshot, "DEVICE_LEAF_THRESHOLD", 10**9)
        host_out2 = fc.snapshot().find_topology_assignments([req], simulate_empty)
        assert host_out.assignments == host_out2.assignments

    def test_unknown_resource_zero(self, monkeypatch):
        from kueue_tpu.tas.snapshot import TASFlavorSnapshot, TASPodSetRequest

        rt = tas_runtime(n_blocks=1, racks_per_block=1, hosts_per_rack=2)
        monkeypatch.setattr(TASFlavorSnapshot, "DEVICE_LEAF_THRESHOLD", 1)
        snap = rt.cache.tas_cache.flavors["tas"].snapshot()
        req = TASPodSetRequest(
            podset_name="main", count=1,
            single_pod_requests={"nvidia.com/gpu": 1},
            topology_request=PodSetTopologyRequest(mode="Required", level=LEVELS[2]),
        )
        counts = snap.podset_fit_counts(req, {})
        assert (counts == 0).all()
