"""Sharded-solver tests on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

import jax

from kueue_tpu.parallel import ShardedSolver, make_mesh


def build_problem(seed=0, n_cq=24, n_cohort=4, fr=8, w=20, k=3, c=3):
    import __graft_entry__

    return __graft_entry__._synthetic_problem(
        n_cq=n_cq, n_cohort=n_cohort, fr=fr, w=w, k=k, c=c
    )


@pytest.mark.parametrize("fr_parallel", [False, True])
def test_sharded_matches_single_device(fr_parallel):
    from kueue_tpu.ops.assign_kernel import solve_cycle_jit

    tree, usage, heads, paths = build_problem(w=24)
    expected = solve_cycle_jit(tree, usage, heads, paths)

    mesh = make_mesh(8, fr_parallel=fr_parallel)
    solver = ShardedSolver(mesh)
    got = solver(tree, usage, heads, paths)

    np.testing.assert_array_equal(np.asarray(got.chosen), np.asarray(expected.chosen))
    np.testing.assert_array_equal(np.asarray(got.admitted), np.asarray(expected.admitted))
    np.testing.assert_array_equal(np.asarray(got.usage), np.asarray(expected.usage))


def test_padding_to_axis_multiple():
    tree, usage, heads, paths = build_problem(w=13)  # not divisible by 8
    from kueue_tpu.ops.assign_kernel import solve_cycle_jit

    expected = solve_cycle_jit(tree, usage, heads, paths)
    solver = ShardedSolver(make_mesh(8))
    got = solver(tree, usage, heads, paths)
    assert got.admitted.shape[0] == 16  # padded
    np.testing.assert_array_equal(
        np.asarray(got.admitted)[:13], np.asarray(expected.admitted)
    )
    assert not np.asarray(got.admitted)[13:].any()


def test_mesh_shapes():
    assert make_mesh(8).axis_names == ("wl",)
    assert make_mesh(8, fr_parallel=True).axis_names == ("wl", "fr")
    assert make_mesh(3, fr_parallel=True).axis_names == ("wl",)  # odd: 1-D


def test_sharded_drain_matches_unsharded():
    """run_drain with a mesh (Q axis sharded over 8 devices) must make
    identical decisions to the unsharded dispatch."""
    from kueue_tpu.core.drain import run_drain
    from kueue_tpu.core.queue_manager import queue_order_timestamp
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.parallel import make_mesh

    from tests.test_solver_path import build_env, random_spec

    spec = random_spec(3, workloads_per_cq=6)
    outcomes = {}
    for label, mesh in (("plain", None), ("mesh", make_mesh(8))):
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        pending = []
        for cq_name, pq in mgr.cluster_queues.items():
            for wl in pq.snapshot_sorted():
                pending.append((wl, cq_name))
        out = run_drain(
            take_snapshot(cache), pending, cache.flavors,
            timestamp_fn=lambda wl: queue_order_timestamp(wl, mgr._ts_policy),
            mesh=mesh,
        )
        outcomes[label] = (
            {(wl.name, tuple(sorted(fl.items())), cyc) for wl, _, fl, cyc in out.admitted},
            {wl.name for wl, _ in out.parked},
        )
    assert outcomes["plain"] == outcomes["mesh"]


def test_sharded_dispatch_lowered_matches_unsharded():
    """dispatch_lowered with a mesh shards heads along wl; decisions
    must match the unsharded path."""
    import numpy as np

    from kueue_tpu.core.solver import dispatch_lowered, lower_heads
    from kueue_tpu.core.queue_manager import queue_order_timestamp
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.parallel import make_mesh

    from tests.test_solver_path import build_env, random_spec

    spec = random_spec(5, workloads_per_cq=4)
    sched, mgr, cache, _ = build_env(spec, use_solver=False)
    heads = []
    for cq_name, pq in mgr.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            heads.append((wl, cq_name))
    snapshot = take_snapshot(cache)
    ts = lambda wl: queue_order_timestamp(wl, mgr._ts_policy)  # noqa: E731
    lowered = lower_heads(snapshot, heads, cache.flavors, timestamp_fn=ts)
    plain = dispatch_lowered(snapshot, lowered)
    sharded = dispatch_lowered(snapshot, lowered, mesh=make_mesh(8))
    np.testing.assert_array_equal(plain.chosen, sharded.chosen)
    np.testing.assert_array_equal(plain.admitted, sharded.admitted)
    np.testing.assert_array_equal(plain.reserved, sharded.reserved)


def test_sharded_preempt_drain_matches_unsharded():
    """run_drain_preempt with a mesh (queues + per-queue victim config
    sharded along wl, segment pools replicated) must decide identically
    to the unsharded dispatch — cohort reclaim included."""
    from kueue_tpu.core.queue_manager import queue_order_timestamp
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.parallel import make_mesh

    from tests.test_drain import build_preempt_env, cohort_reclaim_spec

    spec = cohort_reclaim_spec(2)
    outcomes = {}
    for label, mesh in (("plain", None), ("mesh", make_mesh(8))):
        from kueue_tpu.core.drain import run_drain_preempt

        sched, mgr, cache, _ = build_preempt_env(spec)
        pending = []
        for cq_name, pq in mgr.cluster_queues.items():
            for wl in pq.snapshot_sorted():
                pending.append((wl, cq_name))
        out = run_drain_preempt(
            take_snapshot(cache), pending, cache.flavors,
            timestamp_fn=lambda wl: queue_order_timestamp(wl, mgr._ts_policy),
            mesh=mesh,
        )
        outcomes[label] = (
            {(wl.name, cyc) for wl, _, _, cyc in out.admitted},
            {wl.name for wl, _, _ in out.preempted},
            {wl.name for wl, _ in out.parked},
        )
    assert outcomes["plain"] == outcomes["mesh"]


def test_sharded_fair_drain_matches_unsharded():
    """run_drain(fair_sharing=True) with a mesh (per-queue tensors +
    DRS chain work sharded along wl, node space replicated) must make
    identical decisions — separate root cohorts are independent
    subproblems the tournament shards over."""
    from kueue_tpu.core.drain import run_drain
    from kueue_tpu.core.queue_manager import queue_order_timestamp
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.parallel import make_mesh

    from tests.test_drain import fair_drain_spec
    from tests.test_solver_path import build_env

    spec = fair_drain_spec(7, n_cohorts=3, cqs_per_cohort=3)
    outcomes = {}
    for label, mesh in (("plain", None), ("mesh", make_mesh(8))):
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        pending = []
        for cq_name, pq in mgr.cluster_queues.items():
            for wl in pq.snapshot_sorted():
                pending.append((wl, cq_name))
        out = run_drain(
            take_snapshot(cache), pending, cache.flavors,
            timestamp_fn=lambda wl: queue_order_timestamp(wl, mgr._ts_policy),
            fair_sharing=True,
            mesh=mesh,
        )
        assert not out.fallback
        outcomes[label] = (
            {
                (wl.name, tuple(sorted(fl.items())), cyc)
                for wl, _, fl, cyc in out.admitted
            },
            {wl.name for wl, _ in out.parked},
        )
    assert outcomes["plain"] == outcomes["mesh"]


def test_sharded_fair_search_matches_unsharded():
    """batched_fair_get_targets with a mesh (FairProblem rows sharded
    along wl) must return the same victim sets."""
    import pytest

    from kueue_tpu.core.preempt_batch import batched_fair_get_targets
    from kueue_tpu.core.preemption import Preemptor
    from kueue_tpu.parallel import make_mesh
    from kueue_tpu.utils.clock import FakeClock

    from tests.test_fair_preempt import build_fair_cluster, fair_items

    cache, cq_names = build_fair_cluster(3)
    snapshot, items = fair_items(cache, cq_names, 3)
    if not items:
        pytest.skip("no preempt-mode heads generated")
    preemptor = Preemptor(FakeClock(0.0), enable_fair_sharing=True)
    plain = batched_fair_get_targets(snapshot, items, preemptor)
    sharded = batched_fair_get_targets(
        snapshot, items, preemptor, mesh=make_mesh(8)
    )
    names = lambda rs: [  # noqa: E731
        sorted(t.workload.workload.name for t in r) for r in rs
    ]
    assert names(plain) == names(sharded)
