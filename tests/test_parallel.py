"""Sharded-solver tests on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

import jax

from kueue_tpu.parallel import ShardedSolver, make_mesh


def build_problem(seed=0, n_cq=24, n_cohort=4, fr=8, w=20, k=3, c=3):
    import __graft_entry__

    return __graft_entry__._synthetic_problem(
        n_cq=n_cq, n_cohort=n_cohort, fr=fr, w=w, k=k, c=c
    )


@pytest.mark.parametrize("fr_parallel", [False, True])
def test_sharded_matches_single_device(fr_parallel):
    from kueue_tpu.ops.assign_kernel import solve_cycle_jit

    tree, usage, heads, paths = build_problem(w=24)
    expected = solve_cycle_jit(tree, usage, heads, paths)

    mesh = make_mesh(8, fr_parallel=fr_parallel)
    solver = ShardedSolver(mesh)
    got = solver(tree, usage, heads, paths)

    np.testing.assert_array_equal(np.asarray(got.chosen), np.asarray(expected.chosen))
    np.testing.assert_array_equal(np.asarray(got.admitted), np.asarray(expected.admitted))
    np.testing.assert_array_equal(np.asarray(got.usage), np.asarray(expected.usage))


def test_padding_to_axis_multiple():
    tree, usage, heads, paths = build_problem(w=13)  # not divisible by 8
    from kueue_tpu.ops.assign_kernel import solve_cycle_jit

    expected = solve_cycle_jit(tree, usage, heads, paths)
    solver = ShardedSolver(make_mesh(8))
    got = solver(tree, usage, heads, paths)
    assert got.admitted.shape[0] == 16  # padded
    np.testing.assert_array_equal(
        np.asarray(got.admitted)[:13], np.asarray(expected.admitted)
    )
    assert not np.asarray(got.admitted)[13:].any()


def test_mesh_shapes():
    assert make_mesh(8).axis_names == ("wl",)
    assert make_mesh(8, fr_parallel=True).axis_names == ("wl", "fr")
    assert make_mesh(3, fr_parallel=True).axis_names == ("wl",)  # odd: 1-D
