"""Device TAS drain (ops/drain_kernel.solve_drain_tas) vs the host
scheduler cycle loop with TAS hooks — decision parity for bulk
topology-aware backlogs (VERDICT r3 item 4: TAS heads no longer fall
back from the batched drain)."""

import numpy as np
import pytest

from kueue_tpu.core.cache import Cache
from kueue_tpu.core.drain import run_drain_tas
from kueue_tpu.core.queue_manager import QueueManager, queue_order_timestamp
from kueue_tpu.core.scheduler import Scheduler
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.models import (
    ClusterQueue,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.topology import Topology, TopologyLevel
from kueue_tpu.models.workload import PodSet, PodSetTopologyRequest
from kueue_tpu.tas import Node, TASCache, TASManager
from kueue_tpu.utils.clock import Clock

BLOCK = "cloud.google.com/topology-block"
RACK = "cloud.google.com/topology-rack"
HOST = "kubernetes.io/hostname"


def build_env(n_cq=3, blocks=2, racks=3, hosts=4, host_cpu=8, quota="999"):
    cache = Cache()
    qm = QueueManager(Clock())
    topo = Topology(
        name="default",
        levels=(TopologyLevel(BLOCK), TopologyLevel(RACK), TopologyLevel(HOST)),
    )
    flavor = ResourceFlavor(name="tas-flavor", topology_name="default")
    tas = TASCache()
    tas.add_or_update_topology(topo)
    cache.add_or_update_topology(topo)
    cache.add_or_update_flavor(flavor)
    tas.add_or_update_flavor(flavor)
    for b in range(blocks):
        for r in range(racks):
            for h in range(hosts):
                tas.add_or_update_node(
                    Node(
                        name=f"n-{b}-{r}-{h}",
                        labels={
                            BLOCK: f"b{b}",
                            RACK: f"b{b}-r{r}",
                            HOST: f"h-{b}-{r}-{h}",
                        },
                        allocatable={"cpu": host_cpu * 1000, "pods": 32},
                    )
                )
    cache.tas_cache = tas
    for i in range(n_cq):
        cq = ClusterQueue(
            name=f"cq-{i}",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",),
                    (FlavorQuotas.build("tas-flavor", {"cpu": quota}),),
                ),
            ),
        )
        cache.add_or_update_cluster_queue(cq)
        qm.add_cluster_queue(cq)
        lq = LocalQueue(namespace="ns", name=f"lq-{i}", cluster_queue=f"cq-{i}")
        cache.add_or_update_local_queue(lq)
        qm.add_local_queue(lq)
    manager = TASManager(tas, cache.flavors)
    sched = Scheduler(
        queues=qm, cache=cache, clock=Clock(),
        tas_check=manager.check, tas_assign=manager.assign,
        tas_fits=manager.fits,
        use_solver=False,
    )
    return sched, qm, cache, tas


def tas_wl(name, lq, count, cpu, level, prio=0, t=0.0, mode="Required"):
    tr = PodSetTopologyRequest(
        mode=mode, level=None if mode == "Unconstrained" else level
    )
    return Workload(
        namespace="ns", name=name, queue_name=lq, priority=prio,
        creation_time=t,
        pod_sets=(
            PodSet.build("main", count, {"cpu": cpu}, topology_request=tr),
        ),
    )


def tas_spec(seed, n_cq=3, wl_per_cq=5, modes=("Required",)):
    rng = np.random.default_rng(seed + 61000)
    wls = []
    t = 0.0
    levels = [BLOCK, RACK, RACK, HOST]
    for i in range(n_cq):
        for w in range(wl_per_cq):
            t += 1.0
            wls.append(
                dict(
                    name=f"wl-{i}-{w}",
                    lq=f"lq-{i}",
                    count=int(rng.integers(1, 9)),
                    cpu=str(int(rng.integers(1, 4))),
                    level=levels[int(rng.integers(0, len(levels)))],
                    prio=int(rng.integers(0, 3)) * 10,
                    t=t,
                    mode=modes[int(rng.integers(0, len(modes)))],
                )
            )
    return wls


def host_trace(wls, **env_kw):
    sched, qm, cache, _ = build_env(**env_kw)
    for w in wls:
        qm.add_or_update_workload(tas_wl(**w))
    admitted, cycle = {}, 0
    for _ in range(100):
        if not any(
            pq.pending_active() > 0 for pq in qm.cluster_queues.values()
        ):
            break
        res = sched.schedule()
        for e in res.admitted:
            psa = e.workload.admission.pod_set_assignments[0]
            ta = psa.topology_assignment
            admitted[e.workload.name] = (
                cycle,
                tuple(sorted((d.values, d.count) for d in ta.domains)),
            )
        cycle += 1
    parked = {
        wl.name
        for pq in qm.cluster_queues.values()
        for wl in list(pq.inadmissible.values()) + list(pq.heap.items())
    }
    return admitted, parked


def device_trace(wls, **env_kw):
    sched, qm, cache, tas = build_env(**env_kw)
    for w in wls:
        qm.add_or_update_workload(tas_wl(**w))
    pending = []
    for cq_name, pq in qm.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            pending.append((wl, cq_name))
    snapshot = take_snapshot(cache)
    outcome = run_drain_tas(
        snapshot, pending, cache.flavors, tas,
        timestamp_fn=lambda wl: queue_order_timestamp(wl, qm._ts_policy),
    )
    admitted = {}
    for (wl, _, _, cycle), ta in zip(outcome.admitted, outcome.assignments):
        admitted[wl.name] = (
            cycle,
            tuple(sorted((d.values, d.count) for d in ta.domains)),
        )
    parked = {wl.name for wl, _ in outcome.parked}
    return admitted, parked, outcome


class TestTASDrain:
    def test_basic_rack_placement(self):
        wls = [
            dict(name="w1", lq="lq-0", count=8, cpu="2", level=RACK, t=1.0),
            dict(name="w2", lq="lq-1", count=4, cpu="2", level=RACK, t=2.0),
        ]
        h_adm, h_park = host_trace(wls)
        d_adm, d_park, outcome = device_trace(wls)
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park

    def test_contended_rack_defers_to_next_cycle(self):
        # both want a whole rack's capacity; the second loses the
        # in-cycle re-check and must re-place (or park) next cycle
        wls = [
            dict(name="w1", lq="lq-0", count=16, cpu="2", level=RACK, t=1.0),
            dict(name="w2", lq="lq-1", count=16, cpu="2", level=RACK, t=2.0),
            dict(name="w3", lq="lq-2", count=16, cpu="2", level=RACK, t=3.0),
        ]
        h_adm, h_park = host_trace(wls)
        d_adm, d_park, outcome = device_trace(wls)
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park

    def test_block_level_gang(self):
        wls = [
            dict(name="big", lq="lq-0", count=40, cpu="2", level=BLOCK, t=1.0),
            dict(name="small", lq="lq-1", count=6, cpu="1", level=HOST, t=2.0),
        ]
        h_adm, h_park = host_trace(wls)
        d_adm, d_park, outcome = device_trace(wls)
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park

    def test_quota_and_topology_interact(self):
        # tight quota: one CQ's backlog exceeds its quota even though
        # the topology could hold it
        wls = [
            dict(name="a1", lq="lq-0", count=8, cpu="2", level=RACK, t=1.0),
            dict(name="a2", lq="lq-0", count=8, cpu="2", level=RACK, t=2.0),
        ]
        h_adm, h_park = host_trace(wls, quota="20")
        d_adm, d_park, outcome = device_trace(wls, quota="20")
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park

    def test_topology_request_on_non_tas_flavor_parks_in_kernel(self):
        # a Required-topology workload on a CQ whose flavor has no
        # topology must NOT be silently admitted as plain quota: the
        # host rejects the flavor and parks — the drain PARKS the entry
        # in kernel (t_bad) at the same cycle instead of dropping the
        # whole queue to fallback (regression r1: it admitted with no
        # placement; r4: it punted the entire queue)
        sched, qm, cache, tas = build_env()
        plain_flavor = ResourceFlavor(name="plain")
        cache.add_or_update_flavor(plain_flavor)
        cq = ClusterQueue(
            name="cq-plain",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",), (FlavorQuotas.build("plain", {"cpu": "99"}),)
                ),
            ),
        )
        cache.add_or_update_cluster_queue(cq)
        qm.add_cluster_queue(cq)
        lq = LocalQueue(namespace="ns", name="lq-plain", cluster_queue="cq-plain")
        cache.add_or_update_local_queue(lq)
        qm.add_local_queue(lq)
        qm.add_or_update_workload(tas_wl("w", "lq-plain", 2, "1", RACK, t=1.0))
        pending = []
        for cq_name, pq in qm.cluster_queues.items():
            for wl in pq.snapshot_sorted():
                pending.append((wl, cq_name))
        snapshot = take_snapshot(cache)
        outcome = run_drain_tas(
            snapshot, pending, cache.flavors, tas,
            timestamp_fn=lambda wl: queue_order_timestamp(wl, qm._ts_policy),
        )
        assert not outcome.fallback
        assert [wl.name for wl, _ in outcome.parked] == ["w"]
        assert not outcome.admitted

    @pytest.mark.parametrize("seed", range(16))
    def test_randomized(self, seed):
        wls = tas_spec(seed)
        h_adm, h_park = host_trace(wls)
        d_adm, d_park, outcome = device_trace(wls)
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park


def build_env_two_flavors(
    n_cq=4, blocks=2, racks=3, hosts=4, host_cpu=8, quota="999",
    flat_racks=4, flat_hosts=3,
):
    """Two TAS flavors with DIFFERENT topologies: tas-a (3 levels,
    block/rack/host) and tas-b (2 levels, rack/host). Even CQs use
    tas-a, odd CQs tas-b — the drain segments queues by flavor over one
    merged forest."""
    cache = Cache()
    qm = QueueManager(Clock())
    tas = TASCache()
    topo_a = Topology(
        name="deep",
        levels=(TopologyLevel(BLOCK), TopologyLevel(RACK), TopologyLevel(HOST)),
    )
    topo_b = Topology(
        name="flat", levels=(TopologyLevel(RACK), TopologyLevel(HOST))
    )
    # nodeLabels partition the fleet between the flavors (a flavor with
    # no selector would ingest every node)
    fl_a = ResourceFlavor(
        name="tas-a", topology_name="deep", node_labels={"pool": "a"}
    )
    fl_b = ResourceFlavor(
        name="tas-b", topology_name="flat", node_labels={"pool": "b"}
    )
    for topo in (topo_a, topo_b):
        tas.add_or_update_topology(topo)
        cache.add_or_update_topology(topo)
    for fl in (fl_a, fl_b):
        cache.add_or_update_flavor(fl)
        tas.add_or_update_flavor(fl)
    for b in range(blocks):
        for r in range(racks):
            for h in range(hosts):
                tas.add_or_update_node(
                    Node(
                        name=f"a-{b}-{r}-{h}",
                        labels={
                            "pool": "a",
                            BLOCK: f"b{b}",
                            RACK: f"b{b}-r{r}",
                            HOST: f"ha-{b}-{r}-{h}",
                        },
                        allocatable={"cpu": host_cpu * 1000, "pods": 32},
                    )
                )
    for r in range(flat_racks):
        for h in range(flat_hosts):
            tas.add_or_update_node(
                Node(
                    name=f"b-{r}-{h}",
                    labels={"pool": "b", RACK: f"fr{r}", HOST: f"hb-{r}-{h}"},
                    allocatable={"cpu": host_cpu * 1000, "pods": 32},
                )
            )
    cache.tas_cache = tas
    for i in range(n_cq):
        fname = "tas-a" if i % 2 == 0 else "tas-b"
        cq = ClusterQueue(
            name=f"cq-{i}",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",), (FlavorQuotas.build(fname, {"cpu": quota}),)
                ),
            ),
        )
        cache.add_or_update_cluster_queue(cq)
        qm.add_cluster_queue(cq)
        lq = LocalQueue(namespace="ns", name=f"lq-{i}", cluster_queue=f"cq-{i}")
        cache.add_or_update_local_queue(lq)
        qm.add_local_queue(lq)
    manager = TASManager(tas, cache.flavors)
    sched = Scheduler(
        queues=qm, cache=cache, clock=Clock(),
        tas_check=manager.check, tas_assign=manager.assign,
        tas_fits=manager.fits,
        use_solver=False,
    )
    return sched, qm, cache, tas


def two_flavor_spec(seed, n_cq=4, wl_per_cq=4, modes=("Required",)):
    """Workloads across both flavors' queues; odd (tas-b) queues only
    request rack/host levels (the flat topology has no block)."""
    rng = np.random.default_rng(seed + 71000)
    wls = []
    t = 0.0
    for i in range(n_cq):
        levels = [BLOCK, RACK, HOST] if i % 2 == 0 else [RACK, HOST]
        for w in range(wl_per_cq):
            t += 1.0
            wls.append(
                dict(
                    name=f"wl-{i}-{w}",
                    lq=f"lq-{i}",
                    count=int(rng.integers(1, 9)),
                    cpu=str(int(rng.integers(1, 4))),
                    level=levels[int(rng.integers(0, len(levels)))],
                    prio=int(rng.integers(0, 3)) * 10,
                    t=t,
                    mode=modes[int(rng.integers(0, len(modes)))],
                )
            )
    return wls


ALL_MODES = ("Required", "Preferred", "Unconstrained")


class TestTASDrainWidenedScope:
    """VERDICT r4 item 4: preferred-mode level relaxation, unconstrained
    mode, and multiple TAS flavors per drain — all in kernel, zero
    fallback."""

    def test_preferred_relaxes_to_block(self):
        # one rack holds 4 hosts x 8 cpu = 16 pods at 2 cpu; 20 pods
        # can't fit one rack, so Preferred relaxes to the block level
        # and splits across its racks (Required at RACK would park)
        wls = [
            dict(name="pref", lq="lq-0", count=20, cpu="2", level=RACK,
                 t=1.0, mode="Preferred"),
            dict(name="reqd", lq="lq-1", count=20, cpu="2", level=RACK,
                 t=2.0, mode="Required"),
        ]
        h_adm, h_park = host_trace(wls)
        d_adm, d_park, outcome = device_trace(wls)
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park
        assert "pref" in d_adm and "reqd" in d_park
        # the placement genuinely spans more than one rack
        racks_used = {v[:2] for v, _ in d_adm["pref"][1]}
        assert len(racks_used) > 1

    def test_preferred_multi_domain_at_top(self):
        # no single BLOCK holds 50 pods at 2 cpu (a block = 3 racks x
        # 16 pods = 48): the preferred search falls through to the
        # multi-domain take across blocks (:450-465)
        wls = [
            dict(name="huge", lq="lq-0", count=50, cpu="2", level=RACK,
                 t=1.0, mode="Preferred"),
        ]
        h_adm, h_park = host_trace(wls)
        d_adm, d_park, outcome = device_trace(wls)
        assert not outcome.fallback
        assert d_adm == h_adm and d_park == h_park
        assert "huge" in d_adm
        blocks_used = {v[:1] for v, _ in d_adm["huge"][1]}
        assert len(blocks_used) > 1

    def test_unconstrained_splits_at_leaf(self):
        # unconstrained: single host if possible, else greedy across
        # hosts with no upward relaxation
        wls = [
            dict(name="u-small", lq="lq-0", count=3, cpu="2", level=HOST,
                 t=1.0, mode="Unconstrained"),
            dict(name="u-big", lq="lq-1", count=10, cpu="2", level=HOST,
                 t=2.0, mode="Unconstrained"),
        ]
        h_adm, h_park = host_trace(wls)
        d_adm, d_park, outcome = device_trace(wls)
        assert not outcome.fallback
        assert d_adm == h_adm and d_park == h_park
        assert len(d_adm["u-small"][1]) == 1  # one host suffices
        assert len(d_adm["u-big"][1]) > 1  # 10 pods x 2 cpu > one host

    def test_two_flavors_segment_by_queue(self):
        wls = [
            dict(name="a1", lq="lq-0", count=6, cpu="2", level=RACK, t=1.0),
            dict(name="b1", lq="lq-1", count=6, cpu="2", level=RACK, t=2.0),
            dict(name="a2", lq="lq-2", count=4, cpu="1", level=HOST, t=3.0),
            dict(name="b2", lq="lq-3", count=4, cpu="1", level=HOST, t=4.0),
        ]
        sched, qm, cache, tas = build_env_two_flavors()
        for w in wls:
            qm.add_or_update_workload(tas_wl(**w))
        pending = []
        for cq_name, pq in qm.cluster_queues.items():
            for wl in pq.snapshot_sorted():
                pending.append((wl, cq_name))
        outcome = run_drain_tas(
            take_snapshot(cache), pending, cache.flavors, tas,
            timestamp_fn=lambda wl: queue_order_timestamp(wl, qm._ts_policy),
        )
        assert not outcome.fallback
        assigned = {
            wl.name: ta for (wl, _, _, _), ta in
            zip(outcome.admitted, outcome.assignments)
        }
        assert set(assigned) == {"a1", "b1", "a2", "b2"}
        # flavor isolation: deep-topology hosts are ha-*, flat hb-*
        for name, prefix in (("a1", "ha-"), ("a2", "ha-"),
                             ("b1", "hb-"), ("b2", "hb-")):
            hosts = {v[-1] for v in
                     (d.values for d in assigned[name].domains)}
            assert all(h.startswith(prefix) for h in hosts), (name, hosts)

    @pytest.mark.parametrize("seed", range(12))
    def test_randomized_all_modes(self, seed):
        wls = tas_spec(seed + 100, modes=ALL_MODES)
        h_adm, h_park = host_trace(wls)
        d_adm, d_park, outcome = device_trace(wls)
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park

    @pytest.mark.parametrize("seed", range(8))
    def test_randomized_two_flavors(self, seed):
        wls = two_flavor_spec(seed, modes=ALL_MODES)

        def host():
            sched, qm, cache, _ = build_env_two_flavors()
            for w in wls:
                qm.add_or_update_workload(tas_wl(**w))
            admitted, cycle = {}, 0
            for _ in range(100):
                if not any(
                    pq.pending_active() > 0
                    for pq in qm.cluster_queues.values()
                ):
                    break
                res = sched.schedule()
                for e in res.admitted:
                    psa = e.workload.admission.pod_set_assignments[0]
                    ta = psa.topology_assignment
                    admitted[e.workload.name] = (
                        cycle,
                        tuple(sorted((d.values, d.count) for d in ta.domains)),
                    )
                cycle += 1
            parked = {
                wl.name
                for pq in qm.cluster_queues.values()
                for wl in list(pq.inadmissible.values()) + list(pq.heap.items())
            }
            return admitted, parked

        def device():
            sched, qm, cache, tas = build_env_two_flavors()
            for w in wls:
                qm.add_or_update_workload(tas_wl(**w))
            pending = []
            for cq_name, pq in qm.cluster_queues.items():
                for wl in pq.snapshot_sorted():
                    pending.append((wl, cq_name))
            outcome = run_drain_tas(
                take_snapshot(cache), pending, cache.flavors, tas,
                timestamp_fn=lambda wl: queue_order_timestamp(
                    wl, qm._ts_policy
                ),
            )
            admitted = {}
            for (wl, _, _, cycle), ta in zip(
                outcome.admitted, outcome.assignments
            ):
                admitted[wl.name] = (
                    cycle,
                    tuple(sorted((d.values, d.count) for d in ta.domains)),
                )
            return admitted, {wl.name for wl, _ in outcome.parked}, outcome

        h_adm, h_park = host()
        d_adm, d_park, outcome = device()
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park

    @pytest.mark.parametrize("seed", range(8))
    def test_randomized_tight_quota(self, seed):
        wls = tas_spec(seed, n_cq=4, wl_per_cq=4)
        h_adm, h_park = host_trace(wls, n_cq=4, quota="30")
        d_adm, d_park, outcome = device_trace(wls, n_cq=4, quota="30")
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park
