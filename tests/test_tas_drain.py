"""Device TAS drain (ops/drain_kernel.solve_drain_tas) vs the host
scheduler cycle loop with TAS hooks — decision parity for bulk
topology-aware backlogs (VERDICT r3 item 4: TAS heads no longer fall
back from the batched drain)."""

import numpy as np
import pytest

from kueue_tpu.core.cache import Cache
from kueue_tpu.core.drain import run_drain_tas
from kueue_tpu.core.queue_manager import QueueManager, queue_order_timestamp
from kueue_tpu.core.scheduler import Scheduler
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.models import (
    ClusterQueue,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.topology import Topology, TopologyLevel
from kueue_tpu.models.workload import PodSet, PodSetTopologyRequest
from kueue_tpu.tas import Node, TASCache, TASManager
from kueue_tpu.utils.clock import Clock

BLOCK = "cloud.google.com/topology-block"
RACK = "cloud.google.com/topology-rack"
HOST = "kubernetes.io/hostname"


def build_env(n_cq=3, blocks=2, racks=3, hosts=4, host_cpu=8, quota="999"):
    cache = Cache()
    qm = QueueManager(Clock())
    topo = Topology(
        name="default",
        levels=(TopologyLevel(BLOCK), TopologyLevel(RACK), TopologyLevel(HOST)),
    )
    flavor = ResourceFlavor(name="tas-flavor", topology_name="default")
    tas = TASCache()
    tas.add_or_update_topology(topo)
    cache.add_or_update_topology(topo)
    cache.add_or_update_flavor(flavor)
    tas.add_or_update_flavor(flavor)
    for b in range(blocks):
        for r in range(racks):
            for h in range(hosts):
                tas.add_or_update_node(
                    Node(
                        name=f"n-{b}-{r}-{h}",
                        labels={
                            BLOCK: f"b{b}",
                            RACK: f"b{b}-r{r}",
                            HOST: f"h-{b}-{r}-{h}",
                        },
                        allocatable={"cpu": host_cpu * 1000, "pods": 32},
                    )
                )
    cache.tas_cache = tas
    for i in range(n_cq):
        cq = ClusterQueue(
            name=f"cq-{i}",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",),
                    (FlavorQuotas.build("tas-flavor", {"cpu": quota}),),
                ),
            ),
        )
        cache.add_or_update_cluster_queue(cq)
        qm.add_cluster_queue(cq)
        lq = LocalQueue(namespace="ns", name=f"lq-{i}", cluster_queue=f"cq-{i}")
        cache.add_or_update_local_queue(lq)
        qm.add_local_queue(lq)
    manager = TASManager(tas, cache.flavors)
    sched = Scheduler(
        queues=qm, cache=cache, clock=Clock(),
        tas_check=manager.check, tas_assign=manager.assign,
        tas_fits=manager.fits,
        use_solver=False,
    )
    return sched, qm, cache, tas


def tas_wl(name, lq, count, cpu, level, prio=0, t=0.0):
    tr = PodSetTopologyRequest(mode="Required", level=level)
    return Workload(
        namespace="ns", name=name, queue_name=lq, priority=prio,
        creation_time=t,
        pod_sets=(
            PodSet.build("main", count, {"cpu": cpu}, topology_request=tr),
        ),
    )


def tas_spec(seed, n_cq=3, wl_per_cq=5):
    rng = np.random.default_rng(seed + 61000)
    wls = []
    t = 0.0
    levels = [BLOCK, RACK, RACK, HOST]
    for i in range(n_cq):
        for w in range(wl_per_cq):
            t += 1.0
            wls.append(
                dict(
                    name=f"wl-{i}-{w}",
                    lq=f"lq-{i}",
                    count=int(rng.integers(1, 9)),
                    cpu=str(int(rng.integers(1, 4))),
                    level=levels[int(rng.integers(0, len(levels)))],
                    prio=int(rng.integers(0, 3)) * 10,
                    t=t,
                )
            )
    return wls


def host_trace(wls, **env_kw):
    sched, qm, cache, _ = build_env(**env_kw)
    for w in wls:
        qm.add_or_update_workload(tas_wl(**w))
    admitted, cycle = {}, 0
    for _ in range(100):
        if not any(
            pq.pending_active() > 0 for pq in qm.cluster_queues.values()
        ):
            break
        res = sched.schedule()
        for e in res.admitted:
            psa = e.workload.admission.pod_set_assignments[0]
            ta = psa.topology_assignment
            admitted[e.workload.name] = (
                cycle,
                tuple(sorted((d.values, d.count) for d in ta.domains)),
            )
        cycle += 1
    parked = {
        wl.name
        for pq in qm.cluster_queues.values()
        for wl in list(pq.inadmissible.values()) + list(pq.heap.items())
    }
    return admitted, parked


def device_trace(wls, **env_kw):
    sched, qm, cache, tas = build_env(**env_kw)
    for w in wls:
        qm.add_or_update_workload(tas_wl(**w))
    pending = []
    for cq_name, pq in qm.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            pending.append((wl, cq_name))
    snapshot = take_snapshot(cache)
    outcome = run_drain_tas(
        snapshot, pending, cache.flavors, tas,
        timestamp_fn=lambda wl: queue_order_timestamp(wl, qm._ts_policy),
    )
    admitted = {}
    for (wl, _, _, cycle), ta in zip(outcome.admitted, outcome.assignments):
        admitted[wl.name] = (
            cycle,
            tuple(sorted((d.values, d.count) for d in ta.domains)),
        )
    parked = {wl.name for wl, _ in outcome.parked}
    return admitted, parked, outcome


class TestTASDrain:
    def test_basic_rack_placement(self):
        wls = [
            dict(name="w1", lq="lq-0", count=8, cpu="2", level=RACK, t=1.0),
            dict(name="w2", lq="lq-1", count=4, cpu="2", level=RACK, t=2.0),
        ]
        h_adm, h_park = host_trace(wls)
        d_adm, d_park, outcome = device_trace(wls)
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park

    def test_contended_rack_defers_to_next_cycle(self):
        # both want a whole rack's capacity; the second loses the
        # in-cycle re-check and must re-place (or park) next cycle
        wls = [
            dict(name="w1", lq="lq-0", count=16, cpu="2", level=RACK, t=1.0),
            dict(name="w2", lq="lq-1", count=16, cpu="2", level=RACK, t=2.0),
            dict(name="w3", lq="lq-2", count=16, cpu="2", level=RACK, t=3.0),
        ]
        h_adm, h_park = host_trace(wls)
        d_adm, d_park, outcome = device_trace(wls)
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park

    def test_block_level_gang(self):
        wls = [
            dict(name="big", lq="lq-0", count=40, cpu="2", level=BLOCK, t=1.0),
            dict(name="small", lq="lq-1", count=6, cpu="1", level=HOST, t=2.0),
        ]
        h_adm, h_park = host_trace(wls)
        d_adm, d_park, outcome = device_trace(wls)
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park

    def test_quota_and_topology_interact(self):
        # tight quota: one CQ's backlog exceeds its quota even though
        # the topology could hold it
        wls = [
            dict(name="a1", lq="lq-0", count=8, cpu="2", level=RACK, t=1.0),
            dict(name="a2", lq="lq-0", count=8, cpu="2", level=RACK, t=2.0),
        ]
        h_adm, h_park = host_trace(wls, quota="20")
        d_adm, d_park, outcome = device_trace(wls, quota="20")
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park

    def test_topology_request_on_non_tas_flavor_falls_back(self):
        # a Required-topology workload on a CQ whose flavor has no
        # topology must NOT be silently admitted as plain quota: the
        # host rejects the flavor and parks, so the drain routes the
        # queue to fallback (regression: it admitted with no placement)
        sched, qm, cache, tas = build_env()
        plain_flavor = ResourceFlavor(name="plain")
        cache.add_or_update_flavor(plain_flavor)
        cq = ClusterQueue(
            name="cq-plain",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",), (FlavorQuotas.build("plain", {"cpu": "99"}),)
                ),
            ),
        )
        cache.add_or_update_cluster_queue(cq)
        qm.add_cluster_queue(cq)
        lq = LocalQueue(namespace="ns", name="lq-plain", cluster_queue="cq-plain")
        cache.add_or_update_local_queue(lq)
        qm.add_local_queue(lq)
        qm.add_or_update_workload(tas_wl("w", "lq-plain", 2, "1", RACK, t=1.0))
        pending = []
        for cq_name, pq in qm.cluster_queues.items():
            for wl in pq.snapshot_sorted():
                pending.append((wl, cq_name))
        snapshot = take_snapshot(cache)
        outcome = run_drain_tas(
            snapshot, pending, cache.flavors, tas,
            timestamp_fn=lambda wl: queue_order_timestamp(wl, qm._ts_policy),
        )
        assert [wl.name for wl, _ in outcome.fallback] == ["w"]
        assert not outcome.admitted

    @pytest.mark.parametrize("seed", range(16))
    def test_randomized(self, seed):
        wls = tas_spec(seed)
        h_adm, h_park = host_trace(wls)
        d_adm, d_park, outcome = device_trace(wls)
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park

    @pytest.mark.parametrize("seed", range(8))
    def test_randomized_tight_quota(self, seed):
        wls = tas_spec(seed, n_cq=4, wl_per_cq=4)
        h_adm, h_park = host_trace(wls, n_cq=4, quota="30")
        d_adm, d_park, outcome = device_trace(wls, n_cq=4, quota="30")
        assert not outcome.fallback
        assert d_adm == h_adm
        assert d_park == h_park
