"""Native C++ runtime core tests: heap ordering parity + quota math
parity against the JAX kernels, plus a micro-benchmark sanity check."""

import numpy as np
import pytest

from kueue_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


class TestNativeHeap:
    def test_ordering(self):
        h = native.NativeHeap()
        h.push(1, 10, 100)
        h.push(2, 20, 50)
        h.push(3, 10, 50)
        assert [h.pop(), h.pop(), h.pop()] == [2, 3, 1]
        assert h.pop() is None

    def test_fifo_tiebreak(self):
        h = native.NativeHeap()
        for key in (7, 3, 9):
            h.push(key, 5, 100)
        assert [h.pop(), h.pop(), h.pop()] == [7, 3, 9]

    def test_update_reorders(self):
        h = native.NativeHeap()
        h.push(1, 1, 0)
        h.push(2, 2, 0)
        h.push(1, 3, 0)  # update: 1 now highest priority
        assert h.pop() == 1

    def test_delete_and_contains(self):
        h = native.NativeHeap()
        h.push(1, 1, 0)
        h.push(2, 2, 0)
        assert 1 in h and len(h) == 2
        assert h.delete(1)
        assert not h.delete(1)
        assert 1 not in h
        assert h.pop() == 2

    def test_push_if_not_present(self):
        h = native.NativeHeap()
        assert h.push_if_not_present(1, 1, 0)
        assert not h.push_if_not_present(1, 99, 0)
        h2_prio_unchanged = h.pop()
        assert h2_prio_unchanged == 1

    @pytest.mark.parametrize("seed", range(3))
    def test_randomized_against_python_heap(self, seed):
        from kueue_tpu.utils.heap import Heap

        rng = np.random.default_rng(seed)
        nh = native.NativeHeap()

        def less(a, b):
            if a[1] != b[1]:
                return a[1] > b[1]
            return a[2] < b[2]

        ph = Heap(key_fn=lambda x: str(x[0]), less=less)
        for _ in range(500):
            op = rng.random()
            key = int(rng.integers(0, 60))
            if op < 0.5:
                # timestamp = key makes every rank unique, so ordering
                # is fully determined (tie-break PROTOCOLS differ:
                # updates keep the native seq but re-sequence in the
                # Python heap — both valid FIFO-ish, just not equal)
                prio, ts = int(rng.integers(0, 5)), key
                nh.push(key, prio, ts)
                ph.push_or_update((key, prio, ts))
            elif op < 0.7:
                assert nh.delete(key) == ph.delete(str(key))
            else:
                got = nh.pop()
                want = ph.pop()
                assert (got is None) == (want is None)
                if want is not None:
                    assert got == want[0]
        assert len(nh) == len(ph)


class TestBackendEquivalence:
    @pytest.mark.parametrize("seed", range(3))
    def test_py_and_native_workload_heaps_identical(self, seed):
        """The two make_workload_heap backends must order IDENTICALLY,
        including exact rank ties (frozen ranks + fresh seq on update)."""
        from kueue_tpu.utils.native_heap import NativeWorkloadHeap, PyWorkloadHeap

        rng = np.random.default_rng(seed)
        mk = lambda cls: cls(lambda x: x[0], lambda x: x[1], lambda x: x[2])
        nh, ph = mk(NativeWorkloadHeap), mk(PyWorkloadHeap)
        for step in range(1000):
            op = rng.random()
            key = f"k{int(rng.integers(0, 40))}"
            if op < 0.5:
                item = (key, int(rng.integers(0, 4)), float(rng.integers(0, 4)))
                nh.push_or_update(item)
                ph.push_or_update(item)
            elif op < 0.65:
                item = (key, int(rng.integers(0, 4)), float(rng.integers(0, 4)))
                assert nh.push_if_not_present(item) == ph.push_if_not_present(item)
            elif op < 0.8:
                assert nh.delete(key) == ph.delete(key)
            else:
                a, b = nh.pop(), ph.pop()
                assert (a is None) == (b is None)
                if a is not None:
                    assert a[0] == b[0], (step, a, b)
        assert len(nh) == len(ph)
        assert sorted(nh.keys()) == sorted(ph.keys())


class TestNativeQuota:
    def build(self, seed=0, n_cq=20, n_cohort=5, fr=6):
        rng = np.random.default_rng(seed)
        n = n_cq + n_cohort
        parent = np.full(n, -1, dtype=np.int32)
        parent[:n_cq] = n_cq + rng.integers(0, n_cohort, size=n_cq)
        # chain a couple of cohorts for depth
        parent[n_cq] = n_cq + 1 if n_cohort > 1 else -1
        NO_LIMIT = 1 << 60
        nominal = np.zeros((n, fr), dtype=np.int64)
        nominal[:n_cq] = rng.integers(0, 50, size=(n_cq, fr))
        lending = np.where(
            rng.random((n, fr)) < 0.3, rng.integers(0, 20, size=(n, fr)), NO_LIMIT
        ).astype(np.int64)
        borrowing = np.where(
            rng.random((n, fr)) < 0.3, rng.integers(0, 30, size=(n, fr)), NO_LIMIT
        ).astype(np.int64)
        local_usage = np.zeros((n, fr), dtype=np.int64)
        local_usage[:n_cq] = rng.integers(0, 40, size=(n_cq, fr))
        return parent, nominal, lending, borrowing, local_usage

    @staticmethod
    def order_deepest_first(parent):
        n = len(parent)
        depth = np.zeros(n, dtype=np.int32)
        for i in range(n):
            d, cur = 0, i
            while parent[cur] >= 0:
                cur = parent[cur]
                d += 1
            depth[i] = d
        return np.argsort(-depth, kind="stable").astype(np.int32)

    @staticmethod
    def jax_reference(parent, nominal, lending, borrowing, local_usage):
        from kueue_tpu._jax import jnp
        from kueue_tpu.ops.quota import QuotaTree, subtree_quota, usage_tree, available_all

        n = len(parent)
        depth = np.zeros(n, dtype=np.int32)
        for i in range(n):
            d, cur = 0, i
            while parent[cur] >= 0:
                cur = parent[cur]
                d += 1
            depth[i] = d
        max_depth = depth.max()
        level_mask = np.zeros((max_depth + 1, n), dtype=bool)
        for i in range(n):
            level_mask[depth[i], i] = True
        tree = QuotaTree(
            parent=jnp.asarray(parent),
            level_mask=jnp.asarray(level_mask),
            nominal=jnp.asarray(nominal),
            lending_limit=jnp.asarray(lending),
            borrowing_limit=jnp.asarray(borrowing),
        )
        subtree, guaranteed = subtree_quota(tree)
        usage = usage_tree(tree, guaranteed, jnp.asarray(local_usage))
        avail = available_all(tree, subtree, guaranteed, usage)
        return (
            np.asarray(subtree), np.asarray(guaranteed),
            np.asarray(usage), np.asarray(avail),
        )

    @pytest.mark.parametrize("seed", range(3))
    def test_matches_jax_kernels(self, seed):
        parent, nominal, lending, borrowing, local_usage = self.build(seed)
        order = self.order_deepest_first(parent)
        nq = native.NativeQuota()
        subtree, guaranteed = nq.subtree(parent, order, nominal, lending)
        usage = nq.usage_tree(parent, order, guaranteed, local_usage)
        want_sub, want_g, want_u, want_avail = self.jax_reference(
            parent, nominal, lending, borrowing, local_usage
        )
        np.testing.assert_array_equal(subtree, want_sub)
        np.testing.assert_array_equal(guaranteed, want_g)
        np.testing.assert_array_equal(usage, want_u)

        # available() per node along its path
        n = len(parent)
        for i in range(n):
            path = [i]
            while parent[path[-1]] >= 0:
                path.append(parent[path[-1]])
            path = np.array(path + [-1], dtype=np.int32)
            got = nq.available_node(path, subtree, guaranteed, borrowing, usage)
            np.testing.assert_array_equal(got, want_avail[i], err_msg=f"node {i}")

    def test_add_usage_bubble(self):
        parent, nominal, lending, borrowing, local_usage = self.build(1)
        order = self.order_deepest_first(parent)
        nq = native.NativeQuota()
        _, guaranteed = nq.subtree(parent, order, nominal, lending)
        usage = nq.usage_tree(parent, order, guaranteed, local_usage)

        # add delta at node 0, then verify equal to recomputed tree
        delta = np.zeros(nominal.shape[1], dtype=np.int64)
        delta[0] = 7
        path = [0]
        while parent[path[-1]] >= 0:
            path.append(parent[path[-1]])
        path = np.array(path + [-1], dtype=np.int32)
        updated = nq.add_usage(path, guaranteed, delta, usage.copy(), sign=1)

        local2 = local_usage.copy()
        local2[0, 0] += 7
        want = nq.usage_tree(parent, order, guaranteed, local2)
        np.testing.assert_array_equal(updated, want)
        # removal restores
        restored = nq.add_usage(path, guaranteed, delta, updated, sign=-1)
        np.testing.assert_array_equal(
            restored, nq.usage_tree(parent, order, guaranteed, local_usage)
        )


class TestQueueManagerNativeBacked:
    def test_pending_queue_uses_native(self):
        from kueue_tpu.core.queue_manager import PendingClusterQueue
        from kueue_tpu.models.constants import QueueingStrategy
        from kueue_tpu.utils.clock import FakeClock
        from kueue_tpu.utils.native_heap import NativeWorkloadHeap

        pq = PendingClusterQueue(
            "cq", QueueingStrategy.BEST_EFFORT_FIFO, FakeClock(), lambda w: w.priority
        )
        assert isinstance(pq.heap, NativeWorkloadHeap)
