"""Decision audit trail tests (core/audit.py + surfaces).

Covers the ISSUE 2 acceptance scenario — a workload rejected by quota,
then by taints, then admitted via preemption across successive cycles,
with identical canonical reasons on the host and device (solver)
resolution paths — plus the audit log's dedup/bounds, the reason-enum
lint (no ad-hoc reason strings in events or decision records), the
server decisions endpoint, `kueuectl explain` rendering, the
inadmissible-reason metric, the dashboard "why pending" feed, and the
SIGUSR2 dump.
"""

import contextlib
import io
import json
import re
from pathlib import Path

import pytest

from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.core.audit import DecisionAuditLog, DecisionRecord
from kueue_tpu.models import (
    ClusterQueue,
    LocalQueue,
    PreemptionPolicy,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import (
    FlavorQuotas,
    Preemption,
    ResourceGroup,
)
from kueue_tpu.models.constants import (
    EVENT_REASONS,
    InadmissibleReason,
    classify_inadmissible_message,
)
from kueue_tpu.models.resource_flavor import Taint
from kueue_tpu.models.workload import PodSet
from kueue_tpu.utils.clock import FakeClock


def _cq(preemption_policy=PreemptionPolicy.NEVER):
    return ClusterQueue(
        name="cq",
        namespace_selector={},
        preemption=Preemption(within_cluster_queue=preemption_policy),
        resource_groups=(
            ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": "2"}),)),
        ),
    )


def _wl(name, cpu="2", priority=0, created=0.0):
    return Workload(
        namespace="ns", name=name, queue_name="lq", priority=priority,
        creation_time=created,
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
    )


def run_acceptance_scenario(use_solver):
    """Quota rejection -> taint rejection -> admission via preemption,
    driven by object updates between reconcile passes."""
    rt = ClusterRuntime(clock=FakeClock(1000.0), use_solver=use_solver)
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(_cq())
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))

    # phase 0: a low-priority victim takes the whole quota
    rt.add_workload(_wl("victim", priority=0, created=0.0))
    rt.run_until_idle()
    assert rt.workloads["ns/victim"].is_admitted

    # phase 1: the subject can't fit and nobody is preemptible
    rt.add_workload(_wl("subject", priority=10, created=1.0))
    rt.run_until_idle()

    # phase 2: the flavor grows a taint the subject doesn't tolerate
    # (the update reactivates the parked head)
    rt.add_flavor(
        ResourceFlavor(
            name="default",
            node_taints=(Taint(key="maintenance", value="true"),),
        )
    )
    rt.run_until_idle()

    # phase 3: taint lifted AND the CQ allows in-queue preemption
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(_cq(PreemptionPolicy.LOWER_PRIORITY))
    rt.run_until_idle()
    assert rt.workloads["ns/subject"].is_admitted
    return rt


class TestAcceptanceScenario:
    """ISSUE 2 acceptance criterion."""

    @pytest.mark.parametrize("use_solver", [False, True])
    def test_three_phase_history_with_cycle_ids(self, use_solver):
        rt = run_acceptance_scenario(use_solver)
        recs = rt.audit.for_workload("ns/subject")
        seq = [(r.outcome, r.reason) for r in recs]
        assert seq == [
            ("Pending", InadmissibleReason.INSUFFICIENT_QUOTA),
            ("Pending", InadmissibleReason.UNTOLERATED_TAINT),
            ("Preempting", InadmissibleReason.PENDING_PREEMPTION),
            ("Admitted", InadmissibleReason.ADMITTED),
        ]
        cycles = [r.cycle for r in recs]
        assert cycles == sorted(cycles) and len(set(cycles)) == len(cycles)
        # the preemption record names the victim and its reason
        pre = recs[2].preemption
        assert pre["victims"] == [
            {"workload": "ns/victim", "reason": "InClusterQueue"}
        ]
        # flavor-by-flavor rejection details survive
        assert any(
            "untolerated taint" in r
            for r in recs[1].flavor_reasons.get("main", [])
        )
        assert recs[0].message and "insufficient unused quota" in recs[0].message

    def test_host_and_device_paths_attribute_identically(self):
        host = run_acceptance_scenario(use_solver=False)
        device = run_acceptance_scenario(use_solver=True)
        h = [(r.outcome, r.reason, r.message)
             for r in host.audit.for_workload("ns/subject")]
        d = [(r.outcome, r.reason, r.message)
             for r in device.audit.for_workload("ns/subject")]
        assert h == d

    def test_decisions_endpoint(self):
        from kueue_tpu.server import KueueClient, KueueServer
        from kueue_tpu.server.client import ClientError

        rt = run_acceptance_scenario(use_solver=False)
        srv = KueueServer(runtime=rt)
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            out = client.workload_decisions("ns", "subject")
            assert out["workload"] == "ns/subject"
            reasons = [i["reason"] for i in out["items"]]
            assert reasons == [
                "InsufficientQuota", "UntoleratedTaint",
                "PendingPreemption", "Admitted",
            ]
            assert all("cycle" in i for i in out["items"])
            with pytest.raises(ClientError) as ei:
                client.workload_decisions("ns", "ghost")
            assert ei.value.status == 404
        finally:
            srv.stop()

    def test_explain_server_mode_renders_timeline(self, tmp_path):
        from kueue_tpu.cli.__main__ import main
        from kueue_tpu.server import KueueServer

        rt = run_acceptance_scenario(use_solver=False)
        srv = KueueServer(runtime=rt)
        port = srv.start()
        try:
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                rc = main([
                    "--state", str(tmp_path / "state.json"),
                    "explain", "subject", "-n", "ns",
                    "--server", f"http://127.0.0.1:{port}",
                ])
            text = buf.getvalue()
            assert rc == 0
            assert "Workload:      ns/subject" in text
            assert "Status:        ADMITTED" in text
            for needle in (
                "InsufficientQuota", "UntoleratedTaint",
                "PendingPreemption", "Admitted",
                "victim: ns/victim (InClusterQueue)",
                "untolerated taint",
            ):
                assert needle in text, f"explain output missing {needle!r}"
        finally:
            srv.stop()

    def test_explain_state_mode_reproduces_decisions(self, tmp_path):
        from kueue_tpu import serialization as ser
        from kueue_tpu.cli.__main__ import main

        state = {
            "resourceFlavors": [{"name": "default"}],
            "clusterQueues": [
                {
                    "name": "cq", "namespaceSelector": {},
                    "resourceGroups": [{
                        "coveredResources": ["cpu"],
                        "flavors": [{
                            "name": "default",
                            "resources": [{"name": "cpu", "nominalQuota": "1"}],
                        }],
                    }],
                }
            ],
            "localQueues": [
                {"name": "lq", "namespace": "ns", "clusterQueue": "cq"}
            ],
            "workloads": [
                ser.workload_to_dict(_wl("starved", cpu="2", created=0.0))
            ],
        }
        path = tmp_path / "state.json"
        path.write_text(json.dumps(state))
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = main(["--state", str(path), "explain", "starved", "-n", "ns"])
        text = buf.getvalue()
        assert rc == 0
        assert "Status:        PENDING" in text
        assert "RequestExceedsMaxCapacity" in text
        # offline explain is a read-only what-if: the state file is intact
        assert json.loads(path.read_text()) == state


class TestDecisionAuditLog:
    def _rec(self, cycle=1, reason=InadmissibleReason.INSUFFICIENT_QUOTA,
             message="no quota", workload="ns/w"):
        return DecisionRecord(
            workload=workload, cluster_queue="cq", cycle=cycle,
            outcome="Pending", reason=reason, message=message,
        )

    def test_consecutive_identical_decisions_dedup(self):
        log = DecisionAuditLog(clock=FakeClock(5.0))
        log.record(self._rec(cycle=1))
        stored = log.record(self._rec(cycle=7))
        recs = log.for_workload("ns/w")
        assert len(recs) == 1
        assert stored.count == 2
        assert (stored.cycle, stored.last_cycle) == (1, 7)
        # a different reason breaks the series
        log.record(self._rec(cycle=9, reason=InadmissibleReason.UNTOLERATED_TAINT,
                             message="taint"))
        assert len(log.for_workload("ns/w")) == 2

    def test_per_workload_ring_bound(self):
        log = DecisionAuditLog(per_workload=4)
        for i in range(8):
            # alternate messages so nothing dedups
            log.record(self._rec(cycle=i, message=f"m{i}"))
        recs = log.for_workload("ns/w")
        assert len(recs) == 4
        assert [r.cycle for r in recs] == [4, 5, 6, 7]

    def test_max_workloads_lru_eviction(self):
        log = DecisionAuditLog(max_workloads=3)
        for i in range(5):
            log.record(self._rec(workload=f"ns/w{i}"))
        assert len(log.keys()) == 3
        assert log.for_workload("ns/w0") == []
        assert log.latest("ns/w4") is not None

    def test_tail_orders_by_cycle(self):
        log = DecisionAuditLog()
        log.record(self._rec(workload="ns/b", cycle=2))
        log.record(self._rec(workload="ns/a", cycle=1))
        log.record(self._rec(workload="ns/c", cycle=3))
        assert [r.workload for r in log.tail(2)] == ["ns/b", "ns/c"]

    def test_forget_drops_history(self):
        log = DecisionAuditLog()
        log.record(self._rec())
        log.forget("ns/w")
        assert log.for_workload("ns/w") == [] and len(log) == 0


class TestReasonLint:
    """Satellite: no ad-hoc reason strings — every event reason emitted
    through the runtime recorder and every DecisionRecord reason must
    belong to the canonical enums."""

    def test_audit_log_rejects_ad_hoc_reason_strings(self):
        log = DecisionAuditLog()
        with pytest.raises(ValueError, match="canonical"):
            log.record(
                DecisionRecord(
                    workload="ns/w", cluster_queue="cq", cycle=1,
                    outcome="Pending", reason="SomeAdHocString",  # type: ignore[arg-type]
                )
            )

    def test_source_event_reasons_are_canonical(self):
        """Static lint over the package: every literal first argument
        of runtime.event(...) / self.events(...) / events.record(...)
        must be a member of EVENT_REASONS. Thin wrapper over the
        kueuelint ``reason-enum`` rule (kueue_tpu/analysis) — the one
        scanning implementation since PR 11."""
        from kueue_tpu.analysis import lint

        offenders = lint(rules=["reason-enum"])
        assert not offenders, (
            "ad-hoc event reasons (add to EVENT_REASONS or fix the "
            "call site):\n" + "\n".join(str(f) for f in offenders)
        )

    def test_scenario_records_classify_without_unknown(self):
        rt = run_acceptance_scenario(use_solver=False)
        for key in rt.audit.keys():
            for rec in rt.audit.for_workload(key):
                assert isinstance(rec.reason, InadmissibleReason)
                assert rec.reason != InadmissibleReason.UNKNOWN, (
                    f"{key}: message {rec.message!r} classified UNKNOWN"
                )

    def test_classifier_known_messages(self):
        cases = {
            "couldn't assign flavors to pod set main: insufficient unused "
            "quota for cpu in flavor default, 1 more needed":
                InadmissibleReason.INSUFFICIENT_QUOTA,
            "insufficient quota for cpu in flavor default, request > "
            "maximum capacity (3 > 2)":
                InadmissibleReason.REQUEST_EXCEEDS_CAPACITY,
            "untolerated taint in flavor default":
                InadmissibleReason.UNTOLERATED_TAINT,
            "flavor gone not found": InadmissibleReason.FLAVOR_NOT_FOUND,
            "ClusterQueue cq not found":
                InadmissibleReason.CLUSTER_QUEUE_NOT_FOUND,
            "ClusterQueue cq is inactive":
                InadmissibleReason.CLUSTER_QUEUE_INACTIVE,
            "Workload namespace doesn't match ClusterQueue selector":
                InadmissibleReason.NAMESPACE_MISMATCH,
            "The workload is deactivated": InadmissibleReason.DEACTIVATED,
            "The workload has failed admission checks":
                InadmissibleReason.FAILED_ADMISSION_CHECKS,
            "Workload no longer fits after processing another workload":
                InadmissibleReason.LOST_QUOTA_RACE,
            "Workload has overlapping preemption targets with another "
            "workload": InadmissibleReason.OVERLAPPING_PREEMPTION,
            "waiting for all admitted workloads to be in PodsReady "
            "condition": InadmissibleReason.WAITING_FOR_PODS_READY,
            'topology "t" doesn\'t allow to fit any of 3 pod(s)':
                InadmissibleReason.TOPOLOGY_NO_FIT,
            'Flavor "f" supports only TopologyAwareScheduling':
                InadmissibleReason.TOPOLOGY_INCOMPATIBLE,
            "Workload didn't fit": InadmissibleReason.INSUFFICIENT_QUOTA,
            "": InadmissibleReason.UNKNOWN,
            "gibberish nobody emits": InadmissibleReason.UNKNOWN,
        }
        for message, expected in cases.items():
            assert classify_inadmissible_message(message) == expected, message


class TestMetricAndDashboard:
    def test_inadmissible_reason_metric_series(self):
        rt = run_acceptance_scenario(use_solver=False)
        m = rt.metrics
        assert m.inadmissible_reason_total.value(
            cluster_queue="cq", reason="InsufficientQuota"
        ) >= 1
        assert m.inadmissible_reason_total.value(
            cluster_queue="cq", reason="UntoleratedTaint"
        ) >= 1
        text = m.registry.expose()
        assert "kueue_inadmissible_reason_total" in text

    def test_dashboard_why_pending_panel_feed(self):
        from kueue_tpu.server.dashboard import DASHBOARD_HTML, dashboard_payload

        rt = ClusterRuntime()
        rt.add_flavor(ResourceFlavor(name="default"))
        rt.add_cluster_queue(_cq())
        rt.add_local_queue(
            LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
        )
        rt.add_workload(_wl("fits", created=0.0))
        rt.add_workload(_wl("starved", created=1.0))
        rt.run_until_idle()
        payload = dashboard_payload(rt)
        why = payload["whyPending"]
        assert [w["workload"] for w in why] == ["ns/starved"]
        assert why[0]["reason"] == "InsufficientQuota"
        assert payload["pendingReasons"] == {"InsufficientQuota": 1}
        assert 'id="why"' in DASHBOARD_HTML and "whyPending" in DASHBOARD_HTML

    def test_visibility_items_reason_over_http(self):
        from kueue_tpu.server import KueueClient, KueueServer

        rt = run_acceptance_scenario(use_solver=False)
        srv = KueueServer(runtime=rt)
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            out = client.pending_workloads_cq("cq")
            # the preempted victim is pending again, with its reason
            items = {i["name"]: i for i in out["items"]}
            assert "victim" in items
            assert items["victim"]["inadmissibleReason"] == "InsufficientQuota"
        finally:
            srv.stop()


class TestDebuggerDump:
    def test_dump_includes_decisions_and_traces(self):
        from kueue_tpu.debugger import dump

        rt = run_acceptance_scenario(use_solver=False)
        text = dump(rt)
        assert "recent decisions (audit trail)" in text
        assert "ns/subject @ cq: Admitted/Admitted" in text
        assert "recent cycles (phase attribution)" in text


class TestDrainPathDecisions:
    def test_bulk_drain_records_with_drain_resolution(self):
        rt = ClusterRuntime(bulk_drain_threshold=4)
        rt.add_flavor(ResourceFlavor(name="default"))
        rt.add_cluster_queue(
            ClusterQueue(
                name="cq", namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",), (FlavorQuotas.build("default", {"cpu": "4"}),)
                    ),
                ),
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
        )
        for i in range(8):
            rt.add_workload(_wl(f"w{i}", cpu="1", created=float(i)))
        rt.run_until_idle()
        drains = [
            t for t in rt.scheduler.last_traces if t.resolution == "drain"
        ]
        assert drains, "bulk drain never ran"
        admitted = [
            rt.audit.latest(f"ns/w{i}")
            for i in range(8)
            if rt.workloads[f"ns/w{i}"].is_admitted
        ]
        assert admitted and all(
            r is not None and r.resolution == "drain" for r in admitted
        )
        parked = [
            rt.audit.latest(f"ns/w{i}")
            for i in range(8)
            if not rt.workloads[f"ns/w{i}"].is_admitted
        ]
        assert parked and all(
            r.reason == InadmissibleReason.INSUFFICIENT_QUOTA for r in parked
        )


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))
