"""Host/device parity of the bulk drain (ops/drain_np vs drain_kernel).

The test_encode.py-style round-trip extended to DECISIONS: the same
``DrainPlan`` solved by the device kernel (``use_device=True``) and by
the numpy host mirror (``use_device=False``) must agree bit-for-bit —
who admits, with which flavors, in which cycle, who parks, who gets no
decision — across seeded random snapshots. This is the property the
solver guard's failover authority rests on.

Tier-1 runs a deterministic seed subset; the wide 50-snapshot sweep is
``@slow``.
"""

import numpy as np
import pytest

from kueue_tpu.core.drain import run_drain
from kueue_tpu.core.queue_manager import queue_order_timestamp
from kueue_tpu.core.snapshot import take_snapshot

from tests.test_solver_path import build_env, random_spec

WIDE_SWEEP = 50
TIER1_SEEDS = range(12)


def _both_traces(spec):
    """(device outcome view, host-mirror outcome view) for one spec —
    fresh snapshots per run so neither can leak state into the other."""

    def run(use_device):
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        pending = []
        for cq_name, pq in mgr.cluster_queues.items():
            for wl in pq.snapshot_sorted():
                pending.append((wl, cq_name))
        snapshot = take_snapshot(cache)
        outcome = run_drain(
            snapshot,
            pending,
            cache.flavors,
            timestamp_fn=lambda wl: queue_order_timestamp(
                wl, mgr._ts_policy
            ),
            use_device=use_device,
        )
        admitted = {
            wl.name: (tuple(sorted(flavors.items())), cycle)
            for wl, _, flavors, cycle in outcome.admitted
        }
        parked = {wl.name for wl, _ in outcome.parked}
        fallback = {wl.name for wl, _ in outcome.fallback}
        return admitted, parked, fallback, outcome

    return run(True), run(False)


def _assert_parity(spec, seed):
    (da, dp, df, dev), (ha, hp, hf, host) = _both_traces(spec)
    assert da == ha, f"seed {seed}: admitted sets/flavors/cycles diverge"
    assert dp == hp, f"seed {seed}: parked sets diverge"
    assert df == hf, f"seed {seed}: fallback sets diverge"
    assert dev.cycles == host.cycles, f"seed {seed}: cycle counts diverge"
    assert dev.truncated == host.truncated


class TestDrainHostDeviceParity:
    @pytest.mark.parametrize("seed", TIER1_SEEDS)
    def test_seeded_parity(self, seed):
        _assert_parity(random_spec(seed, workloads_per_cq=8), seed)

    @pytest.mark.parametrize("seed", [0, 3])
    def test_parity_under_contention(self, seed):
        # heavier per-CQ depth: more in-cycle conflicts, cursor resumes
        # and PendingFlavors retries to disagree on
        _assert_parity(random_spec(seed, workloads_per_cq=16), seed)

    def test_host_mirror_admits_nontrivially(self):
        # guard against a vacuous sweep: the mirror must actually admit
        spec = random_spec(1, workloads_per_cq=8)
        _, (ha, hp, _, host) = _both_traces(spec)
        assert ha and host.cycles > 0

    def test_use_device_false_rejects_fair_and_mesh(self):
        spec = random_spec(0, workloads_per_cq=4)
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        pending = [
            (wl, cq_name)
            for cq_name, pq in mgr.cluster_queues.items()
            for wl in pq.snapshot_sorted()
        ]
        snapshot = take_snapshot(cache)
        with pytest.raises(ValueError, match="plain drain"):
            run_drain(
                snapshot, pending, cache.flavors,
                fair_sharing=True, use_device=False,
            )


@pytest.mark.slow
class TestDrainParityWideSweep:
    @pytest.mark.parametrize("seed", range(WIDE_SWEEP))
    def test_seeded_parity_wide(self, seed):
        rng = np.random.default_rng(10_000 + seed)
        depth = int(rng.integers(4, 12))
        _assert_parity(random_spec(seed, workloads_per_cq=depth), seed)
