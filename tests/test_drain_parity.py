"""Host/device parity of the bulk drain (ops/drain_np vs drain_kernel).

The test_encode.py-style round-trip extended to DECISIONS: the same
``DrainPlan`` solved by the device kernel (``use_device=True``) and by
the numpy host mirror (``use_device=False``) must agree bit-for-bit —
who admits, with which flavors, in which cycle, who parks, who gets no
decision — across seeded random snapshots. This is the property the
solver guard's failover authority rests on.

Tier-1 runs a deterministic seed subset; the wide 50-snapshot sweep is
``@slow``.
"""

import numpy as np
import pytest

from kueue_tpu.core.drain import run_drain
from kueue_tpu.core.queue_manager import queue_order_timestamp
from kueue_tpu.core.snapshot import take_snapshot

from tests.test_solver_path import build_env, random_spec

WIDE_SWEEP = 50
TIER1_SEEDS = range(12)


def _both_traces(spec):
    """(device outcome view, host-mirror outcome view) for one spec —
    fresh snapshots per run so neither can leak state into the other."""

    def run(use_device):
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        pending = []
        for cq_name, pq in mgr.cluster_queues.items():
            for wl in pq.snapshot_sorted():
                pending.append((wl, cq_name))
        snapshot = take_snapshot(cache)
        outcome = run_drain(
            snapshot,
            pending,
            cache.flavors,
            timestamp_fn=lambda wl: queue_order_timestamp(
                wl, mgr._ts_policy
            ),
            use_device=use_device,
        )
        admitted = {
            wl.name: (tuple(sorted(flavors.items())), cycle)
            for wl, _, flavors, cycle in outcome.admitted
        }
        parked = {wl.name for wl, _ in outcome.parked}
        fallback = {wl.name for wl, _ in outcome.fallback}
        return admitted, parked, fallback, outcome

    return run(True), run(False)


def _assert_parity(spec, seed):
    (da, dp, df, dev), (ha, hp, hf, host) = _both_traces(spec)
    assert da == ha, f"seed {seed}: admitted sets/flavors/cycles diverge"
    assert dp == hp, f"seed {seed}: parked sets diverge"
    assert df == hf, f"seed {seed}: fallback sets diverge"
    assert dev.cycles == host.cycles, f"seed {seed}: cycle counts diverge"
    assert dev.truncated == host.truncated


class TestDrainHostDeviceParity:
    @pytest.mark.parametrize("seed", TIER1_SEEDS)
    def test_seeded_parity(self, seed):
        _assert_parity(random_spec(seed, workloads_per_cq=8), seed)

    @pytest.mark.parametrize("seed", [0, 3])
    def test_parity_under_contention(self, seed):
        # heavier per-CQ depth: more in-cycle conflicts, cursor resumes
        # and PendingFlavors retries to disagree on
        _assert_parity(random_spec(seed, workloads_per_cq=16), seed)

    def test_host_mirror_admits_nontrivially(self):
        # guard against a vacuous sweep: the mirror must actually admit
        spec = random_spec(1, workloads_per_cq=8)
        _, (ha, hp, _, host) = _both_traces(spec)
        assert ha and host.cycles > 0

    def test_use_device_false_rejects_fair_and_mesh(self):
        spec = random_spec(0, workloads_per_cq=4)
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        pending = [
            (wl, cq_name)
            for cq_name, pq in mgr.cluster_queues.items()
            for wl in pq.snapshot_sorted()
        ]
        snapshot = take_snapshot(cache)
        with pytest.raises(ValueError, match="plain drain"):
            run_drain(
                snapshot, pending, cache.flavors,
                fair_sharing=True, use_device=False,
            )


@pytest.mark.slow
class TestDrainParityWideSweep:
    @pytest.mark.parametrize("seed", range(WIDE_SWEEP))
    def test_seeded_parity_wide(self, seed):
        rng = np.random.default_rng(10_000 + seed)
        depth = int(rng.integers(4, 12))
        _assert_parity(random_spec(seed, workloads_per_cq=depth), seed)


class TestPanelLadderExactness:
    """The two-tier victim-search panel (run_drain_preempt
    ``panel_widths``): decisions bit-for-bit identical to the fixed
    wide panel under EVERY narrow schedule — a clean narrow solve is
    provably exact, and an inconclusive truncated search escalates to
    the wide width instead of shipping the freeze."""

    # tier-1 runtime headroom (ISSUE 14): 3 deterministic seeds per
    # schedule stay tier-1, the rest of the sweep rides @slow
    @pytest.mark.parametrize("seed", range(3))
    @pytest.mark.parametrize("widths", [(1, 32), (2, 32), (8, 32)])
    def test_narrow_schedule_matches_wide(self, seed, widths):
        from tests.test_drain import device_preempt_drain_trace, preempt_spec

        spec = preempt_spec(seed)
        wide = device_preempt_drain_trace(
            spec, search_width=32, panel_widths=(32,)
        )
        narrow = device_preempt_drain_trace(
            spec, search_width=32, panel_widths=widths
        )
        assert wide[:3] == narrow[:3], (
            f"seed {seed} widths {widths}: decisions diverged"
        )
        assert {w.name for w, _ in wide[3].fallback} == {
            w.name for w, _ in narrow[3].fallback
        }
        assert [c for *_, c in wide[3].admitted] == [
            c for *_, c in narrow[3].admitted
        ], "admission cycle indices diverged"


    @pytest.mark.slow
    @pytest.mark.parametrize("seed", range(3, 6))
    @pytest.mark.parametrize("widths", [(1, 32), (2, 32), (8, 32)])
    def test_narrow_schedule_matches_wide_sweep(self, seed, widths):
        self.test_narrow_schedule_matches_wide(seed, widths)

    def test_escalation_fires_and_stays_exact(self):
        """A width-1 panel on a head that needs several victims MUST
        trip the kernel's inconclusive-truncation flag; the tuner
        observes the escalation and the decisions equal the wide run."""
        from kueue_tpu.core.drain import PanelTuner
        from kueue_tpu.models.cluster_queue import Preemption
        from kueue_tpu.models.constants import PreemptionPolicy

        from tests.test_drain import device_preempt_drain_trace

        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": "cq",
                    "cohort": None,
                    "groups": [
                        {
                            "resources": ["cpu"],
                            "flavors": [("f", {"cpu": "10"}, None, None)],
                        }
                    ],
                    "preemption": Preemption(
                        within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY
                    ),
                }
            ],
            "workloads": [
                {
                    "name": "attacker", "queue": "lq-cq", "prio": 100,
                    "t": 50.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "8"}}
                    ],
                }
            ],
            # four 2-cpu victims: the search must remove several, so a
            # width-1 window overflows and misses (inconclusive)
            "victims": [
                ("v0", "cq", "f", "2", 0, 1.0),
                ("v1", "cq", "f", "2", 0, 2.0),
                ("v2", "cq", "f", "2", 10, 3.0),
                ("v3", "cq", "f", "2", 10, 4.0),
            ],
        }
        tuner = PanelTuner()
        tuner._narrow[32] = 1  # force the overflowing narrow tier
        narrow = device_preempt_drain_trace(
            spec, search_width=32, panel_tuner=tuner
        )
        wide = device_preempt_drain_trace(
            spec, search_width=32, panel_widths=(32,)
        )
        assert tuner.escalations == 1, "escape hatch never fired"
        assert tuner._narrow[32] > 1, "tuner did not widen after escalation"
        assert narrow[:3] == wide[:3]
        assert narrow[1], "no eviction happened — vacuous scenario"

    def test_tuner_walks_the_ladder(self):
        from kueue_tpu.core.drain import PanelTuner

        t = PanelTuner(shrink_after=2)
        assert t.widths_for(64) == (16, 64)
        assert t.widths_for(8) == (8,)  # narrow == final collapses
        t.observe(64, escalated=True)
        assert t.widths_for(64) == (32, 64)
        t.observe(64, escalated=False)
        t.observe(64, escalated=False)  # shrink_after clean solves
        assert t.widths_for(64) == (16, 64)
        t2 = PanelTuner()
        t2._narrow[64] = 64
        assert t2.widths_for(64) == (64,)


class TestKernelMirrorRegistry:
    """The kernel<->host-mirror parity lint (ops/__init__.py
    KERNEL_MIRRORS): every device kernel module must register a mirror
    that resolves and a parity test file that exists — so a new kernel
    (or a reworked panel shape) cannot silently drop mirror coverage.
    Thin wrappers over the kueuelint ``kernel-mirrors`` rule
    (kueue_tpu/analysis) — one scanning implementation since PR 11,
    historical test names preserved."""

    def _findings(self):
        from kueue_tpu.analysis import lint

        return lint(rules=["kernel-mirrors"])

    def test_every_kernel_has_a_registered_mirror(self):
        offenders = [
            f for f in self._findings()
            if "host mirror" in f.message or "stale" in f.message
        ]
        assert not offenders, "\n".join(str(f) for f in offenders)

    def test_sharded_entry_points_share_the_single_device_mirror(self):
        """PR-8 extension: every kernel with a mesh path
        (parallel.SHARDED_KERNELS) must be registered too — a sharded
        launch answers to the SAME host mirror as its single-device
        twin (mirrors are mesh-agnostic), so the guard's failover and
        the pipelined drain's divergence sampling never change with
        the mesh. A sharded entry without a mirror, or one that does
        not resolve, fails CI."""
        offenders = [
            f for f in self._findings() if "sharded" in f.message
        ]
        assert not offenders, "\n".join(str(f) for f in offenders)

    def test_mirrors_resolve_and_tests_exist(self):
        offenders = self._findings()
        assert not offenders, "\n".join(str(f) for f in offenders)

    def test_drain_mirror_is_wired_to_the_kernel_shapes(self):
        """The registered drain mirror must accept the live DrainPlan
        shapes end-to-end — the property the whole registry exists to
        protect (a shape rework that breaks the mirror fails HERE even
        if no parity seed happens to cover the new field)."""
        spec = random_spec(0, workloads_per_cq=6)
        (_, _, _, dev), (_, _, _, host) = _both_traces(spec)
        assert host.final_usage is not None
        assert dev.final_usage is not None
        # the two paths agree on the speculation surface too: the
        # final leaf usage the pipelined loop launches round t+1 from
        assert np.array_equal(dev.final_usage, host.final_usage)
