"""Bearer-token authentication on the secured service surface
(ref: cmd/kueue/main.go:154-179 — metrics behind authn/z, writes via
the authenticated apiserver). Probes, visibility and the dashboard
stay open."""

import pytest

from kueue_tpu import serialization as ser
from kueue_tpu.models import ResourceFlavor
from kueue_tpu.server import KueueClient, KueueServer
from kueue_tpu.server.client import ClientError

TOKEN = "s3cret-token"


@pytest.fixture()
def server():
    srv = KueueServer(auth_token=TOKEN)
    srv.start()
    yield srv
    srv.stop()


class TestBearerAuth:
    def test_unauthenticated_writes_rejected(self, server):
        anon = KueueClient(f"http://127.0.0.1:{server.port}")
        with pytest.raises(ClientError) as e:
            anon.apply(
                "resourceflavors",
                ser.flavor_to_dict(ResourceFlavor(name="default")),
            )
        assert e.value.status == 401

    def test_wrong_token_rejected(self, server):
        bad = KueueClient(f"http://127.0.0.1:{server.port}", token="nope")
        with pytest.raises(ClientError) as e:
            bad.reconcile()
        assert e.value.status == 401

    def test_metrics_and_state_secured(self, server):
        anon = KueueClient(f"http://127.0.0.1:{server.port}")
        for call in (anon.metrics_text, anon.state):
            with pytest.raises(ClientError) as e:
                call()
            assert e.value.status == 401

    def test_probes_and_reads_stay_open(self, server):
        anon = KueueClient(f"http://127.0.0.1:{server.port}")
        assert anon.healthz()["status"] == "ok"
        assert anon.list("workloads") == []
        assert "clusterQueues" in anon.dashboard()

    def test_token_grants_full_surface(self, server):
        c = KueueClient(f"http://127.0.0.1:{server.port}", token=TOKEN)
        c.apply(
            "resourceflavors",
            ser.flavor_to_dict(ResourceFlavor(name="default")),
        )
        assert "kueue_admission_attempts_total" in c.metrics_text()
        c.reconcile()
        assert isinstance(c.state(), dict)

    def test_no_token_server_stays_open(self):
        srv = KueueServer()
        srv.start()
        try:
            anon = KueueClient(f"http://127.0.0.1:{srv.port}")
            anon.apply(
                "resourceflavors",
                ser.flavor_to_dict(ResourceFlavor(name="default")),
            )
            anon.metrics_text()
        finally:
            srv.stop()
