"""Golden tests: tensorized quota math vs a recursive oracle.

The oracle is a direct transcription of the reference's recursive
definitions (pkg/cache/resource_node.go, fair_sharing.go) over a toy
node graph; the kernels in kueue_tpu.ops.quota must agree cell-for-cell
on randomized forests.
"""

import numpy as np
import pytest

from kueue_tpu._jax import jnp
from kueue_tpu.ops.quota import (
    DRS_MAX,
    NO_LIMIT,
    QuotaTree,
    available_all,
    dominant_resource_share,
    potential_available_all,
    subtree_quota,
    usage_tree,
)

ROOT = -1


# ---------------------------------------------------------------- oracle
class Node:
    def __init__(self, nominal, lending=None, borrowing=None):
        self.nominal = dict(nominal)  # fr -> int
        self.lending = dict(lending or {})  # fr -> int or absent
        self.borrowing = dict(borrowing or {})
        self.parent = None
        self.children = []
        self.subtree = {}
        self.usage = {}

    def guaranteed(self, fr):
        if fr in self.lending:
            return max(0, self.subtree.get(fr, 0) - self.lending[fr])
        return 0


def update_tree(root, frs):
    """updateCohortResourceNode semantics."""
    for child in root.children:
        update_tree(child, frs)
    root.subtree = {fr: root.nominal.get(fr, 0) for fr in frs}
    root.usage = {fr: root.usage.get(fr, 0) if not root.children else 0 for fr in frs}
    for child in root.children:
        for fr in frs:
            root.subtree[fr] += child.subtree.get(fr, 0) - child.guaranteed(fr)
            root.usage[fr] = root.usage.get(fr, 0) + max(
                0, child.usage.get(fr, 0) - child.guaranteed(fr)
            )


def oracle_available(node, fr):
    if node.parent is None:
        return node.subtree.get(fr, 0) - node.usage.get(fr, 0)
    local = max(0, node.guaranteed(fr) - node.usage.get(fr, 0))
    parent_avail = oracle_available(node.parent, fr)
    if fr in node.borrowing:
        stored = node.subtree.get(fr, 0) - node.guaranteed(fr)
        used = max(0, node.usage.get(fr, 0) - node.guaranteed(fr))
        parent_avail = min(stored - used + node.borrowing[fr], parent_avail)
    return local + parent_avail


def oracle_potential(node, fr):
    if node.parent is None:
        return node.subtree.get(fr, 0)
    avail = node.guaranteed(fr) + oracle_potential(node.parent, fr)
    if fr in node.borrowing:
        avail = min(node.subtree.get(fr, 0) + node.borrowing[fr], avail)
    return avail


# ------------------------------------------------------------- flattening
def build_tree_arrays(nodes, parents, frs):
    """nodes: list of Node; parents: list of parent indices (-1 root)."""
    n = len(nodes)
    for i, p in enumerate(parents):
        if p != ROOT:
            nodes[i].parent = nodes[p]
            nodes[p].children.append(nodes[i])
    depth = np.zeros(n, dtype=np.int32)
    for i in range(n):
        d, cur = 0, parents[i]
        while cur != ROOT:
            d += 1
            cur = parents[cur]
        depth[i] = d
    max_depth = int(depth.max()) if n else 0
    level_mask = np.stack([depth == d for d in range(max_depth + 1)])

    fr_list = sorted(frs)
    nominal = np.zeros((n, len(fr_list)), dtype=np.int64)
    lend = np.full((n, len(fr_list)), NO_LIMIT, dtype=np.int64)
    borrow = np.full((n, len(fr_list)), NO_LIMIT, dtype=np.int64)
    for i, node in enumerate(nodes):
        for j, fr in enumerate(fr_list):
            nominal[i, j] = node.nominal.get(fr, 0)
            if fr in node.lending:
                lend[i, j] = node.lending[fr]
            if fr in node.borrowing:
                borrow[i, j] = node.borrowing[fr]
    tree = QuotaTree(
        parent=jnp.asarray(parents, dtype=jnp.int32),
        level_mask=jnp.asarray(level_mask),
        nominal=jnp.asarray(nominal),
        lending_limit=jnp.asarray(lend),
        borrowing_limit=jnp.asarray(borrow),
    )
    return tree, fr_list


def run_kernels(nodes, parents, frs, usages):
    tree, fr_list = build_tree_arrays(nodes, parents, frs)
    local_usage = np.zeros((len(nodes), len(fr_list)), dtype=np.int64)
    for i, u in usages.items():
        for fr, v in u.items():
            local_usage[i, fr_list.index(fr)] = v
            nodes[i].usage[fr] = v
    subtree, guaranteed = subtree_quota(tree)
    usage = usage_tree(tree, guaranteed, jnp.asarray(local_usage))
    avail = available_all(tree, subtree, guaranteed, usage)
    pot = potential_available_all(tree, subtree, guaranteed)

    roots = [nodes[i] for i, p in enumerate(parents) if p == ROOT]
    for r in roots:
        update_tree(r, frs)
    return tree, fr_list, subtree, guaranteed, usage, avail, pot


# ------------------------------------------------------------------ tests
def test_flat_cq_no_cohort():
    nodes = [Node({"f/cpu": 1000})]
    _, fr_list, subtree, _, usage, avail, pot = run_kernels(
        nodes, [ROOT], {"f/cpu"}, {0: {"f/cpu": 300}}
    )
    assert subtree[0, 0] == 1000
    assert avail[0, 0] == 700
    assert pot[0, 0] == 1000


def test_two_cqs_borrowing():
    # cq0, cq1 under cohort2; cq0 may borrow everything cq1 lends
    nodes = [Node({"f/cpu": 10}), Node({"f/cpu": 20}), Node({})]
    _, fr, subtree, g, usage, avail, pot = run_kernels(
        nodes, [2, 2, ROOT], {"f/cpu"}, {0: {"f/cpu": 5}}
    )
    # cohort subtree = 10+20 = 30 (no lending limits -> all lendable)
    assert subtree[2, 0] == 30
    # cq0 guaranteed 0 (no lending limit set -> fully lendable)
    assert g[0, 0] == 0
    # cq0 available = 0 local + parent (30 - 5 usage bubbled) = 25
    assert avail[0, 0] == 25
    assert avail[1, 0] == 25
    assert pot[0, 0] == 30


def test_lending_limit_guarantees_local():
    # cq1 lends at most 5 of its 20
    nodes = [Node({"f/cpu": 10}), Node({"f/cpu": 20}, lending={"f/cpu": 5}), Node({})]
    _, fr, subtree, g, usage, avail, pot = run_kernels(
        nodes, [2, 2, ROOT], {"f/cpu"}, {}
    )
    assert g[1, 0] == 15
    # cohort sees 10 + 5 = 15
    assert subtree[2, 0] == 15
    assert avail[0, 0] == 15
    # cq1 keeps guaranteed 15 + full cohort availability 15 = 30
    assert avail[1, 0] == 30


def test_borrowing_limit_clamps():
    nodes = [
        Node({"f/cpu": 10}, borrowing={"f/cpu": 3}),
        Node({"f/cpu": 20}),
        Node({}),
    ]
    _, fr, subtree, g, usage, avail, pot = run_kernels(
        nodes, [2, 2, ROOT], {"f/cpu"}, {}
    )
    # cq0 can use its 10 (stored in parent) + borrow at most 3
    assert avail[0, 0] == 13
    assert pot[0, 0] == 13


def test_overadmission_negative_available():
    nodes = [Node({"f/cpu": 10})]
    _, _, _, _, _, avail, _ = run_kernels(nodes, [ROOT], {"f/cpu"}, {0: {"f/cpu": 15}})
    assert avail[0, 0] == -5


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_against_oracle(seed):
    rng = np.random.default_rng(seed)
    n_cohorts = rng.integers(1, 5)
    n_cqs = rng.integers(1, 8)
    frs = {f"f{k}/cpu" for k in range(rng.integers(1, 4))}

    nodes = []
    parents = []
    # cohorts first as a chain/tree among themselves
    for c in range(n_cohorts):
        nominal = {fr: int(rng.integers(0, 50)) for fr in frs if rng.random() < 0.5}
        lending = {fr: int(rng.integers(0, 30)) for fr in nominal if rng.random() < 0.4}
        node = Node(nominal, lending=lending)
        nodes.append(node)
        parents.append(ROOT if c == 0 else int(rng.integers(0, c)))
    for q in range(n_cqs):
        nominal = {fr: int(rng.integers(0, 50)) for fr in frs}
        lending = {fr: int(rng.integers(0, 30)) for fr in nominal if rng.random() < 0.4}
        borrowing = {fr: int(rng.integers(0, 40)) for fr in nominal if rng.random() < 0.4}
        nodes.append(Node(nominal, lending=lending, borrowing=borrowing))
        parents.append(int(rng.integers(0, n_cohorts)))

    usages = {
        n_cohorts + q: {fr: int(rng.integers(0, 60)) for fr in frs}
        for q in range(n_cqs)
    }
    _, fr_list, subtree, g, usage, avail, pot = run_kernels(
        nodes, parents, frs, usages
    )

    for i, node in enumerate(nodes):
        for j, fr in enumerate(fr_list):
            assert subtree[i, j] == node.subtree.get(fr, 0), (i, fr, "subtree")
            assert usage[i, j] == node.usage.get(fr, 0), (i, fr, "usage")
            assert avail[i, j] == oracle_available(node, fr), (i, fr, "avail")
            assert pot[i, j] == oracle_potential(node, fr), (i, fr, "potential")


def test_drs_basic():
    # cq0 borrows 5 cpu above its subtree quota; cohort lends 30 total
    nodes = [Node({"f/cpu": 10}), Node({"f/cpu": 20}), Node({})]
    tree, fr_list = build_tree_arrays(nodes, [2, 2, ROOT], {"f/cpu"})
    subtree, guaranteed = subtree_quota(tree)
    local_usage = jnp.asarray(np.array([[15], [0], [0]], dtype=np.int64))
    usage = usage_tree(tree, guaranteed, local_usage)
    resource_index = jnp.zeros(1, dtype=jnp.int32)
    weight = jnp.asarray([1000, 1000, 1000], dtype=jnp.int64)
    wl_req = jnp.zeros((3, 1), dtype=jnp.int64)
    dws, dom = dominant_resource_share(
        tree, subtree, guaranteed, usage, wl_req, weight, resource_index, 1
    )
    # borrowed = 15-10 = 5; lendable(parent) = potentialAvailable(cohort)=30
    # drs = 5*1000/30 = 166; weight 1 -> 166
    assert dws[0] == 166
    assert dom[0] == 0
    assert dws[1] == 0 and dom[1] == -1


def test_drs_zero_weight_borrowing_is_max():
    nodes = [Node({"f/cpu": 10}), Node({"f/cpu": 20}), Node({})]
    tree, _ = build_tree_arrays(nodes, [2, 2, ROOT], {"f/cpu"})
    subtree, guaranteed = subtree_quota(tree)
    usage = usage_tree(
        tree, guaranteed, jnp.asarray(np.array([[15], [0], [0]], dtype=np.int64))
    )
    dws, _ = dominant_resource_share(
        tree,
        subtree,
        guaranteed,
        usage,
        jnp.zeros((3, 1), dtype=jnp.int64),
        jnp.asarray([0, 1000, 1000], dtype=jnp.int64),
        jnp.zeros(1, dtype=jnp.int32),
        1,
    )
    assert dws[0] == DRS_MAX


def test_drs_with_workload_request():
    # not borrowing now, but would borrow if wl admitted
    nodes = [Node({"f/cpu": 10}), Node({"f/cpu": 20}), Node({})]
    tree, _ = build_tree_arrays(nodes, [2, 2, ROOT], {"f/cpu"})
    subtree, guaranteed = subtree_quota(tree)
    usage = usage_tree(
        tree, guaranteed, jnp.asarray(np.array([[8], [0], [0]], dtype=np.int64))
    )
    wl_req = jnp.asarray(np.array([[8], [0], [0]], dtype=np.int64))
    dws, _ = dominant_resource_share(
        tree,
        subtree,
        guaranteed,
        usage,
        wl_req,
        jnp.asarray([1000, 1000, 1000], dtype=jnp.int64),
        jnp.zeros(1, dtype=jnp.int32),
        1,
    )
    # borrowed = 8+8-10 = 6 -> 6*1000/30 = 200
    assert dws[0] == 200
