"""Resource quantity parsing and arithmetic (pkg/resources parity)."""

import pytest

from kueue_tpu.resources import (
    COUNT_IN_UNBOUNDED,
    FlavorResource,
    add_requests,
    count_in,
    flavor_resources,
    quantity_to_int,
    requests_from_spec,
    scale_requests,
)


def test_cpu_milli():
    assert quantity_to_int("cpu", "1") == 1000
    assert quantity_to_int("cpu", "300m") == 300
    assert quantity_to_int("cpu", "2.5") == 2500
    assert quantity_to_int("cpu", 4) == 4000


def test_memory_bytes():
    assert quantity_to_int("memory", "1Ki") == 1024
    assert quantity_to_int("memory", "1Gi") == 2**30
    assert quantity_to_int("memory", "1G") == 10**9
    assert quantity_to_int("memory", "512") == 512
    assert quantity_to_int("memory", "100m") == 1  # rounds up sub-unit


def test_extended_resources_plain():
    assert quantity_to_int("google.com/tpu", "8") == 8
    assert quantity_to_int("pods", 3) == 3


def test_invalid_quantity():
    with pytest.raises(ValueError):
        quantity_to_int("cpu", "abc")


def test_requests_arithmetic():
    a = requests_from_spec({"cpu": "1", "memory": "1Gi"})
    b = requests_from_spec({"cpu": "500m"})
    add_requests(a, b)
    assert a["cpu"] == 1500
    assert scale_requests(b, 3)["cpu"] == 1500


def test_count_in():
    per_unit = requests_from_spec({"cpu": "1", "memory": "1Gi"})
    capacity = requests_from_spec({"cpu": "10", "memory": "4Gi"})
    assert count_in(per_unit, capacity) == 4
    # zero-valued requests fit unboundedly (reference CountIn -> MaxInt32)
    assert count_in({}, capacity) == COUNT_IN_UNBOUNDED
    assert count_in({"cpu": 0}, capacity) == COUNT_IN_UNBOUNDED


def test_int64_precision_preserved():
    big = 2**53 + 1  # first integer float64 cannot represent
    assert quantity_to_int("memory", big) == big
    assert quantity_to_int("memory", str(big)) == big


def test_flavor_resource_keys():
    frs = flavor_resources(["on-demand", "spot"], ["cpu", "memory"])
    assert len(frs) == 4
    assert FlavorResource("spot", "cpu") in frs
    assert sorted(frs)[0] == FlavorResource("on-demand", "cpu")
