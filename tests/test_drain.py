"""On-device bulk drain vs the sequential host scheduler.

The drain kernel runs the whole multi-cycle backlog on device; for
preemption-free, fully-representable backlogs its decisions — who is
admitted, with which flavors, in which cycle — must match running the
host Scheduler cycle-by-cycle to quiescence.
"""

import numpy as np
import pytest

from kueue_tpu.core.drain import run_drain
from kueue_tpu.core.queue_manager import queue_order_timestamp
from kueue_tpu.core.snapshot import take_snapshot

from tests.test_solver_path import build_env, random_spec


def host_drain_trace(spec):
    """Drain via the host scheduler; returns {wl name: (flavors, cycle)}
    plus the parked set."""
    sched, mgr, cache, _ = build_env(spec, use_solver=False)
    admitted = {}
    cycle = 0
    for _ in range(200):
        # quiescent only when every active heap is empty — a cycle that
        # parks its head uncovers the next workload behind it
        if not any(
            pq.pending_active() > 0 for pq in mgr.cluster_queues.values()
        ):
            break
        res = sched.schedule()
        for e in res.admitted:
            psa = e.workload.admission.pod_set_assignments[0]
            admitted[e.workload.name] = (dict(psa.flavors), cycle)
        cycle += 1
    parked = {
        wl.name
        for pq in mgr.cluster_queues.values()
        for wl in list(pq.inadmissible.values()) + list(pq.heap.items())
    }
    return admitted, parked


def device_drain_trace(spec):
    sched, mgr, cache, _ = build_env(spec, use_solver=False)
    # collect the backlog in per-CQ heap order
    pending = []
    for cq_name, pq in mgr.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            pending.append((wl, cq_name))
    snapshot = take_snapshot(cache)
    outcome = run_drain(
        snapshot,
        pending,
        cache.flavors,
        timestamp_fn=lambda wl: queue_order_timestamp(wl, mgr._ts_policy),
    )
    admitted = {
        wl.name: (flavors, cycle) for wl, _, flavors, cycle in outcome.admitted
    }
    parked = {wl.name for wl, _ in outcome.parked}
    return admitted, parked, outcome


class TestDrainParity:
    @pytest.mark.parametrize("seed", range(10))
    def test_randomized(self, seed):
        spec = random_spec(seed, workloads_per_cq=8)
        host_admitted, host_parked = host_drain_trace(spec)
        dev_admitted, dev_parked, outcome = device_drain_trace(spec)
        assert not outcome.fallback
        assert dev_admitted == host_admitted
        assert dev_parked == host_parked

    def test_multi_flavor_spillover(self):
        # second flavor absorbs what the first can't; drain must walk
        # candidates exactly like the host
        spec = {
            "flavors": ["fast", "slow"],
            "cqs": [
                {
                    "name": "cq",
                    "cohort": "co",
                    "groups": [
                        {
                            "resources": ["cpu"],
                            "flavors": [
                                ("fast", {"cpu": "4"}, None, None),
                                ("slow", {"cpu": "100"}, None, None),
                            ],
                        }
                    ],
                    "preemption": None,
                }
            ],
            "workloads": [
                {
                    "name": f"w{i}",
                    "queue": "lq-cq",
                    "prio": 0,
                    "t": float(i),
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "3"}}
                    ],
                }
                for i in range(6)
            ],
        }
        host_admitted, _ = host_drain_trace(spec)
        dev_admitted, _, _ = device_drain_trace(spec)
        assert dev_admitted == host_admitted
        # first workload on "fast", rest spill to "slow"
        assert dev_admitted["w0"][0] == {"cpu": "fast"}
        assert dev_admitted["w1"][0] == {"cpu": "slow"}

    def test_cohort_borrowing_contention(self):
        # shared cohort capacity: cross-CQ conflicts resolved per cycle
        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": f"cq-{i}",
                    "cohort": "co",
                    "groups": [
                        {
                            "resources": ["cpu"],
                            "flavors": [("f", {"cpu": "4"}, None, None)],
                        }
                    ],
                    "preemption": None,
                }
                for i in range(4)
            ],
            "workloads": [
                {
                    "name": f"w{i}",
                    "queue": f"lq-cq-{i % 4}",
                    "prio": (i * 7) % 3,
                    "t": float(i),
                    "pod_sets": [
                        {
                            "name": "main",
                            "count": 1,
                            "requests": {"cpu": str(2 + (i % 5))},
                        }
                    ],
                }
                for i in range(20)
            ],
        }
        host_admitted, host_parked = host_drain_trace(spec)
        dev_admitted, dev_parked, outcome = device_drain_trace(spec)
        assert dev_admitted == host_admitted
        assert dev_parked == host_parked
        assert outcome.cycles >= 2


def deep_tree_spec(seed, depth=3, fanout=2, workloads_per_cq=5):
    """Cohort tree of the given depth: root holds the quota, interior
    cohorts are pass-through, CQs at the leaves borrow all the way up."""
    rng = np.random.default_rng(seed)
    cohorts = [
        {
            "name": "root",
            "groups": [
                {"resources": ["cpu"], "flavors": [("f", {"cpu": "40"}, None, None)]}
            ],
        }
    ]
    parents = ["root"]
    for d in range(1, depth):
        nxt = []
        for p in parents:
            for i in range(fanout):
                name = f"{p}-{i}"
                cohorts.append({"name": name, "parent": p})
                nxt.append(name)
        parents = nxt
    cqs = []
    workloads = []
    t = 0.0
    for p in parents:
        name = f"cq-{p}"
        cqs.append(
            {
                "name": name,
                "cohort": p,
                "groups": [
                    {
                        "resources": ["cpu"],
                        "flavors": [("f", {"cpu": "2"}, None, None)],
                    }
                ],
                "preemption": None,
            }
        )
        for wi in range(workloads_per_cq):
            t += 1.0
            workloads.append(
                {
                    "name": f"w-{name}-{wi}",
                    "queue": f"lq-{name}",
                    "prio": int(rng.integers(0, 3)),
                    "t": t,
                    "pod_sets": [
                        {
                            "name": "main",
                            "count": 1,
                            "requests": {"cpu": str(int(rng.integers(1, 6)))},
                        }
                    ],
                }
            )
    return {"flavors": ["f"], "cohorts": cohorts, "cqs": cqs, "workloads": workloads}


class TestDrainDeepTree:
    @pytest.mark.parametrize("seed", range(4))
    def test_depth3_parity(self, seed):
        spec = deep_tree_spec(seed)
        host_admitted, host_parked = host_drain_trace(spec)
        dev_admitted, dev_parked, outcome = device_drain_trace(spec)
        assert not outcome.fallback
        assert not outcome.truncated
        assert dev_admitted == host_admitted
        assert dev_parked == host_parked


class TestDrainTruncation:
    def test_max_cycles_routes_unprocessed_to_fallback(self):
        spec = random_spec(3, workloads_per_cq=8)
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        pending = []
        for cq_name, pq in mgr.cluster_queues.items():
            for wl in pq.snapshot_sorted():
                pending.append((wl, cq_name))
        snapshot = take_snapshot(cache)
        kwargs = dict(
            flavors=cache.flavors,
            timestamp_fn=lambda wl: queue_order_timestamp(wl, mgr._ts_policy),
        )
        cut = run_drain(snapshot, pending, max_cycles=1, **kwargs)
        assert cut.truncated
        assert cut.cycles == 1
        assert cut.fallback  # unprocessed entries are NOT silently parked
        snapshot2 = take_snapshot(cache)
        full = run_drain(snapshot2, pending, **kwargs)
        assert not full.truncated
        # decided prefixes agree; everything else was surfaced as fallback
        decided = {wl.name for wl, *_ in cut.admitted} | {
            wl.name for wl, _ in cut.parked
        }
        full_admitted = {wl.name for wl, *_ in full.admitted}
        for wl, *_ in cut.admitted:
            assert wl.name in full_admitted
        assert (
            decided | {wl.name for wl, _ in cut.fallback}
            == {wl.name for wl, _ in pending}
        )


# ---------------------------------------------------------------- preemption
def _admit_victim(cache, mgr_clock_t, name, cq_name, flavor, cpu, prio, uid_t):
    """flavor/cpu: either a flavor name + cpu quantity (the single-RG
    shorthand) or {resource: flavor} + {resource: quantity} dicts."""
    from kueue_tpu.core.workload_info import make_admission
    from kueue_tpu.models import Workload, WorkloadConditionType
    from kueue_tpu.models.workload import PodSet

    requests = cpu if isinstance(cpu, dict) else {"cpu": cpu}
    flavors = flavor if isinstance(flavor, dict) else {"cpu": flavor}
    wl = Workload(
        namespace="ns", name=name, queue_name=f"lq-{cq_name}", priority=prio,
        creation_time=uid_t,
        pod_sets=(PodSet.build("main", 1, requests),),
    )
    wl.admission = make_admission(cq_name, {"main": flavors}, wl)
    wl.set_condition(
        WorkloadConditionType.QUOTA_RESERVED, True, reason="QuotaReserved",
        now=uid_t,
    )
    cache.add_or_update_workload(wl)
    return wl


def build_preempt_env(spec):
    """build_env + pre-admitted victims from spec['victims']:
    (name, cq, flavor, cpu, prio, t) tuples."""
    sched, mgr, cache, workloads = build_env(spec, use_solver=False)
    for name, cq_name, flavor, cpu, prio, t in spec.get("victims", []):
        _admit_victim(cache, None, name, cq_name, flavor, cpu, prio, t)
    return sched, mgr, cache, workloads


def host_preempt_drain_trace(spec):
    """Host truth: scheduler cycles with evictions applied between
    cycles (the reconciler's stop/delete round-trip compressed to the
    cycle boundary), to quiescence."""
    sched, mgr, cache, _ = build_preempt_env(spec)
    admitted, evicted = {}, set()
    for _ in range(300):
        progressed = False
        if any(pq.pending_active() > 0 for pq in mgr.cluster_queues.values()):
            progressed = True  # active heads: the cycle itself is progress
        res = sched.schedule()
        for e in res.admitted:
            psas = e.workload.admission.pod_set_assignments
            if len(psas) == 1:
                admitted[e.workload.name] = dict(psas[0].flavors)
            else:
                admitted[e.workload.name] = {
                    psa.name: dict(psa.flavors) for psa in psas
                }
        victims = []
        for e in res.preempting:
            for t in e.preemption_targets:
                victims.append(t.workload.workload)
        for wl in victims:
            if wl.name in evicted:
                continue
            evicted.add(wl.name)
            cq_name = wl.admission.cluster_queue
            cache.delete_workload(wl)
            mgr.queue_associated_inadmissible_workloads_after(cq_name)
            progressed = True
        if not progressed:
            break
    parked = {
        wl.name
        for pq in mgr.cluster_queues.values()
        for wl in list(pq.inadmissible.values()) + list(pq.heap.items())
    }
    return admitted, evicted, parked


def device_preempt_drain_trace(spec, **kw):
    from kueue_tpu.core.drain import run_drain_preempt

    sched, mgr, cache, _ = build_preempt_env(spec)
    pending = []
    for cq_name, pq in mgr.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            pending.append((wl, cq_name))
    snapshot = take_snapshot(cache)
    outcome = run_drain_preempt(
        snapshot,
        pending,
        cache.flavors,
        timestamp_fn=lambda wl: queue_order_timestamp(wl, mgr._ts_policy),
        **kw,
    )
    admitted = {wl.name: flavors for wl, _, flavors, _ in outcome.admitted}
    evicted = {wl.name for wl, _, _ in outcome.preempted}
    parked = {wl.name for wl, _ in outcome.parked}
    return admitted, evicted, parked, outcome


def preempt_spec(seed, n_cohorts=2, cqs_per_cohort=3, victims_per_cq=4,
                 workloads_per_cq=4):
    """Random scenario inside the device preemption-drain scope:
    within-CQ preemption, reclaimWithinCohort=Never, single RG."""
    from kueue_tpu.models.cluster_queue import Preemption
    from kueue_tpu.models.constants import PreemptionPolicy

    rng = np.random.default_rng(seed)
    flavors = ["fl-0", "fl-1"]
    cqs, workloads, victims = [], [], []
    t = 0.0
    for ci in range(n_cohorts):
        for qi in range(cqs_per_cohort):
            name = f"cq-{ci}-{qi}"
            cohort = f"cohort-{ci}" if rng.random() < 0.7 else None
            k = int(rng.integers(1, 3))
            fls = []
            for f in flavors[:k]:
                bl = (
                    str(int(rng.integers(0, 8)))
                    if cohort is not None and rng.random() < 0.4
                    else None
                )
                fls.append((f, {"cpu": str(int(rng.integers(6, 16)))}, bl, None))
            # index the list (rng.choice would coerce enums to numpy
            # strings and corrupt the policies)
            policy_opts = [
                PreemptionPolicy.NEVER,
                PreemptionPolicy.LOWER_PRIORITY,
                PreemptionPolicy.LOWER_PRIORITY,
                PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY,
            ]
            policy = policy_opts[int(rng.integers(0, len(policy_opts)))]
            cqs.append(
                {
                    "name": name,
                    "cohort": cohort,
                    "groups": [{"resources": ["cpu"], "flavors": fls}],
                    "preemption": Preemption(within_cluster_queue=policy),
                }
            )
            for vi in range(int(rng.integers(0, victims_per_cq + 1))):
                t += 1.0
                victims.append(
                    (
                        f"victim-{ci}-{qi}-{vi}", name,
                        fls[int(rng.integers(0, len(fls)))][0],
                        str(int(rng.integers(1, 5))),
                        int(rng.integers(0, 3)) * 10, t,
                    )
                )
            for wi in range(workloads_per_cq):
                t += 1.0
                workloads.append(
                    {
                        "name": f"wl-{ci}-{qi}-{wi}",
                        "queue": f"lq-{name}",
                        "prio": int(rng.integers(0, 4)) * 10,
                        "t": t,
                        "pod_sets": [
                            {
                                "name": "main",
                                "count": int(rng.integers(1, 3)),
                                "requests": {"cpu": str(int(rng.integers(1, 7)))},
                            }
                        ],
                    }
                )
    return {
        "flavors": flavors, "cqs": cqs, "workloads": workloads,
        "victims": victims,
    }


class TestPreemptDrainParity:
    def test_basic_preempt_then_admit(self):
        from kueue_tpu.models.cluster_queue import Preemption
        from kueue_tpu.models.constants import PreemptionPolicy

        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": "cq",
                    "cohort": None,
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "10"}, None, None)]}
                    ],
                    "preemption": Preemption(
                        within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY
                    ),
                }
            ],
            "workloads": [
                {
                    "name": "attacker", "queue": "lq-cq", "prio": 100, "t": 50.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "8"}}
                    ],
                }
            ],
            "victims": [
                ("v0", "cq", "f", "4", 0, 1.0),
                ("v1", "cq", "f", "4", 10, 2.0),
            ],
        }
        admitted, evicted, parked, outcome = device_preempt_drain_trace(spec)
        h_admitted, h_evicted, h_parked = host_preempt_drain_trace(spec)
        assert admitted == h_admitted == {"attacker": {"cpu": "f"}}
        assert evicted == h_evicted
        assert parked == h_parked == set()
        assert not outcome.fallback and not outcome.truncated

    def test_minimal_victim_set(self):
        """Fill-back keeps unnecessary victims admitted: only enough
        victims to fit the head are evicted."""
        from kueue_tpu.models.cluster_queue import Preemption
        from kueue_tpu.models.constants import PreemptionPolicy

        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": "cq",
                    "cohort": None,
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "12"}, None, None)]}
                    ],
                    "preemption": Preemption(
                        within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY
                    ),
                }
            ],
            "workloads": [
                {
                    "name": "attacker", "queue": "lq-cq", "prio": 100, "t": 50.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "4"}}
                    ],
                }
            ],
            "victims": [
                ("v-low", "cq", "f", "4", 0, 1.0),
                ("v-mid", "cq", "f", "4", 10, 2.0),
                ("v-high", "cq", "f", "4", 20, 3.0),
            ],
        }
        admitted, evicted, parked, _ = device_preempt_drain_trace(spec)
        h_admitted, h_evicted, h_parked = host_preempt_drain_trace(spec)
        assert admitted == h_admitted
        assert evicted == h_evicted == {"v-low"}
        assert parked == h_parked

    def test_never_policy_parks(self):
        from kueue_tpu.models.cluster_queue import Preemption
        from kueue_tpu.models.constants import PreemptionPolicy

        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": "cq",
                    "cohort": None,
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "10"}, None, None)]}
                    ],
                    "preemption": Preemption(
                        within_cluster_queue=PreemptionPolicy.NEVER
                    ),
                }
            ],
            "workloads": [
                {
                    "name": "blocked", "queue": "lq-cq", "prio": 100, "t": 50.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "8"}}
                    ],
                }
            ],
            "victims": [("v0", "cq", "f", "8", 0, 1.0)],
        }
        admitted, evicted, parked, _ = device_preempt_drain_trace(spec)
        h_admitted, h_evicted, h_parked = host_preempt_drain_trace(spec)
        assert admitted == h_admitted == {}
        assert evicted == h_evicted == set()
        assert parked == h_parked == {"blocked"}

    def test_cohort_reclaim_stays_in_kernel(self):
        # A reclaimWithinCohort CQ is IN the device scope (round 4):
        # the head preempts the lower-priority same-CQ victim in-kernel
        # instead of falling back to the cycle loop.
        from kueue_tpu.models.cluster_queue import Preemption
        from kueue_tpu.models.constants import (
            PreemptionPolicy,
            ReclaimWithinCohortPolicy,
        )

        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": "cq",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "10"}, None, None)]}
                    ],
                    "preemption": Preemption(
                        within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
                        reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
                    ),
                }
            ],
            "workloads": [
                {
                    "name": "w", "queue": "lq-cq", "prio": 100, "t": 50.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "8"}}
                    ],
                }
            ],
            "victims": [("v0", "cq", "f", "8", 0, 1.0)],
        }
        admitted, evicted, parked, outcome = device_preempt_drain_trace(spec)
        assert not outcome.fallback
        h_admitted, h_evicted, h_parked = host_preempt_drain_trace(spec)
        assert admitted == h_admitted
        assert evicted == h_evicted == {"v0"}
        assert parked == h_parked

    # tier-1 runtime headroom (ISSUE 14): 4 deterministic seeds stay
    # tier-1, the remainder of the historical sweep rides @slow
    @pytest.mark.parametrize("seed", range(4))
    def test_randomized(self, seed):
        spec = preempt_spec(seed)
        h_admitted, h_evicted, h_parked = host_preempt_drain_trace(spec)
        admitted, evicted, parked, outcome = device_preempt_drain_trace(spec)
        assert not outcome.fallback
        assert admitted == h_admitted
        assert evicted == h_evicted
        assert parked == h_parked

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", range(4, 16))
    def test_randomized_wide(self, seed):
        self.test_randomized(seed)

    def test_reactivated_head_preempts_drain_admitted_same_cq(self):
        # Within-CQ-only cohort (no reclaim anywhere): w-hi parks (its
        # only candidate outranks it), the lower-priority w-lo admits
        # behind it, and an eviction elsewhere in the cohort reactivates
        # w-hi — which must then preempt the DRAIN-ADMITTED w-lo. The
        # part-B candidate pool must exist even without cohort reclaim
        # (regression: slots were gated on reclaim being enabled).
        from kueue_tpu.models.cluster_queue import Preemption
        from kueue_tpu.models.constants import PreemptionPolicy

        prem = Preemption(within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY)
        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": "cq-a",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "8"}, None, None)]}
                    ],
                    "preemption": prem,
                },
                {
                    "name": "cq-b",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "12"}, None, None)]}
                    ],
                    "preemption": prem,
                },
            ],
            "workloads": [
                {
                    "name": "w-blk", "queue": "lq-cq-b", "prio": 60, "t": 1.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "21"}}
                    ],
                },
                {
                    "name": "w-hi", "queue": "lq-cq-a", "prio": 50, "t": 2.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "8"}}
                    ],
                },
                {
                    "name": "w-lo", "queue": "lq-cq-a", "prio": 0, "t": 3.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "4"}}
                    ],
                },
                {
                    "name": "w-e", "queue": "lq-cq-b", "prio": 50, "t": 6.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "6"}}
                    ],
                },
            ],
            "victims": [
                ("v0", "cq-a", "f", "8", 60, 1.0),
                ("v2", "cq-b", "f", "5", 0, 2.0),
                ("v2b", "cq-b", "f", "2", 70, 3.0),
            ],
        }
        h_admitted, h_evicted, h_parked = host_preempt_drain_trace(spec)
        admitted, evicted, parked, outcome = device_preempt_drain_trace(spec)
        assert not outcome.fallback
        assert admitted == h_admitted
        assert evicted == h_evicted
        assert parked == h_parked
        assert "w-lo" in evicted and "w-hi" in admitted


def cohort_reclaim_spec(seed, n_cohorts=2, cqs_per_cohort=3,
                        victims_per_cq=3, workloads_per_cq=3):
    """Random cross-CQ contention: cohorts whose members borrow (some
    victims admitted above nominal), mixed withinClusterQueue /
    reclaimWithinCohort / borrowWithinCohort policies with priority
    thresholds — the preemption scope the round-3 drain routed to host
    fallback (preemption.go:480-524, :194-204)."""
    from kueue_tpu.models.cluster_queue import BorrowWithinCohort, Preemption
    from kueue_tpu.models.constants import (
        BorrowWithinCohortPolicy,
        PreemptionPolicy,
        ReclaimWithinCohortPolicy,
    )

    rng = np.random.default_rng(seed + 31000)
    flavors = ["fl-0", "fl-1"]
    cqs, workloads, victims = [], [], []
    t = 0.0
    for ci in range(n_cohorts):
        for qi in range(cqs_per_cohort):
            name = f"cq-{ci}-{qi}"
            k = int(rng.integers(1, 3))
            fls = []
            for f in flavors[:k]:
                bl = (
                    str(int(rng.integers(0, 10)))
                    if rng.random() < 0.5
                    else None
                )
                fls.append((f, {"cpu": str(int(rng.integers(4, 12)))}, bl, None))
            # index the lists (rng.choice would coerce enums to numpy
            # strings and corrupt the policies)
            wcq_opts = [
                PreemptionPolicy.NEVER,
                PreemptionPolicy.LOWER_PRIORITY,
                PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY,
            ]
            wcq = wcq_opts[int(rng.integers(0, len(wcq_opts)))]
            reclaim_opts = [
                ReclaimWithinCohortPolicy.NEVER,
                ReclaimWithinCohortPolicy.LOWER_PRIORITY,
                ReclaimWithinCohortPolicy.ANY,
                ReclaimWithinCohortPolicy.ANY,
            ]
            reclaim = reclaim_opts[int(rng.integers(0, len(reclaim_opts)))]
            if rng.random() < 0.4 and reclaim != ReclaimWithinCohortPolicy.NEVER:
                bwc = BorrowWithinCohort(
                    policy=BorrowWithinCohortPolicy.LOWER_PRIORITY,
                    max_priority_threshold=(
                        int(rng.integers(0, 4)) * 10
                        if rng.random() < 0.7
                        else None
                    ),
                )
            else:
                bwc = BorrowWithinCohort()
            cqs.append(
                {
                    "name": name,
                    "cohort": f"cohort-{ci}",
                    "groups": [{"resources": ["cpu"], "flavors": fls}],
                    "preemption": Preemption(
                        within_cluster_queue=wcq,
                        reclaim_within_cohort=reclaim,
                        borrow_within_cohort=bwc,
                    ),
                }
            )
            # victims sized to overshoot nominal sometimes: the CQ then
            # borrows from the cohort, making its workloads reclaimable
            for vi in range(int(rng.integers(0, victims_per_cq + 1))):
                t += 1.0
                victims.append(
                    (
                        f"victim-{ci}-{qi}-{vi}", name,
                        fls[int(rng.integers(0, len(fls)))][0],
                        str(int(rng.integers(1, 9))),
                        int(rng.integers(0, 3)) * 10, t,
                    )
                )
            for wi in range(workloads_per_cq):
                t += 1.0
                workloads.append(
                    {
                        "name": f"wl-{ci}-{qi}-{wi}",
                        "queue": f"lq-{name}",
                        "prio": int(rng.integers(0, 5)) * 10,
                        "t": t,
                        "pod_sets": [
                            {
                                "name": "main",
                                "count": int(rng.integers(1, 3)),
                                "requests": {"cpu": str(int(rng.integers(1, 6)))},
                            }
                        ],
                    }
                )
    return {
        "flavors": flavors, "cqs": cqs, "workloads": workloads,
        "victims": victims,
    }


class TestPreemptDrainCohortReclaim:
    def test_cross_cq_reclaim_releases_borrowed(self):
        # cq-a borrows above nominal; cq-b's head reclaims from it
        # (reclaimWithinCohort=Any) without touching cq-b's own victims
        from kueue_tpu.models.cluster_queue import Preemption
        from kueue_tpu.models.constants import (
            PreemptionPolicy,
            ReclaimWithinCohortPolicy,
        )

        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": "cq-a",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "4"}, None, None)]}
                    ],
                    "preemption": Preemption(),
                },
                {
                    "name": "cq-b",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "6"}, None, None)]}
                    ],
                    "preemption": Preemption(
                        reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
                    ),
                },
            ],
            "workloads": [
                {
                    "name": "wb", "queue": "lq-cq-b", "prio": 0, "t": 50.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "6"}}
                    ],
                }
            ],
            # cq-a holds 8 > nominal 4: borrowing 4 from the cohort
            "victims": [
                ("va-0", "cq-a", "f", "4", 50, 1.0),
                ("va-1", "cq-a", "f", "4", 50, 2.0),
            ],
        }
        admitted, evicted, parked, outcome = device_preempt_drain_trace(spec)
        assert not outcome.fallback
        h_admitted, h_evicted, h_parked = host_preempt_drain_trace(spec)
        assert admitted == h_admitted
        assert evicted == h_evicted
        # reclaim succeeds even though the victims have HIGHER priority
        # (reclaimWithinCohort=Any has no priority constraint)
        assert "wb" in admitted and len(evicted) == 1
        assert parked == h_parked

    def test_lower_priority_reclaim_respects_priority(self):
        from kueue_tpu.models.cluster_queue import Preemption
        from kueue_tpu.models.constants import ReclaimWithinCohortPolicy

        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": "cq-a",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "4"}, None, None)]}
                    ],
                    "preemption": Preemption(),
                },
                {
                    "name": "cq-b",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "6"}, None, None)]}
                    ],
                    "preemption": Preemption(
                        reclaim_within_cohort=ReclaimWithinCohortPolicy.LOWER_PRIORITY,
                    ),
                },
            ],
            "workloads": [
                {
                    "name": "wb", "queue": "lq-cq-b", "prio": 10, "t": 50.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "6"}}
                    ],
                }
            ],
            # borrowing victims at prio 50 >= 10: NOT reclaimable
            "victims": [
                ("va-0", "cq-a", "f", "4", 50, 1.0),
                ("va-1", "cq-a", "f", "4", 50, 2.0),
            ],
        }
        admitted, evicted, parked, outcome = device_preempt_drain_trace(spec)
        assert not outcome.fallback
        h_admitted, h_evicted, h_parked = host_preempt_drain_trace(spec)
        assert admitted == h_admitted == {}
        assert evicted == h_evicted == set()
        assert parked == h_parked == {"wb"}

    def test_admitted_entry_becomes_reclaim_candidate(self):
        # cq-a's entry admits first (borrowing into the cohort), then
        # cq-b's later head reclaims it — the part-B dynamic-victim
        # flow: the workload ends BOTH admitted and evicted, exactly as
        # the host cycle loop decides it
        from kueue_tpu.models.cluster_queue import Preemption
        from kueue_tpu.models.constants import (
            PreemptionPolicy,
            ReclaimWithinCohortPolicy,
        )

        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": "cq-a",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "2"}, None, None)]}
                    ],
                    "preemption": Preemption(),
                },
                {
                    "name": "cq-b",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "6"}, None, None)]}
                    ],
                    "preemption": Preemption(
                        reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
                    ),
                },
            ],
            "workloads": [
                # admitted in cycle 1, borrowing 4 above cq-a's nominal
                {
                    "name": "wa", "queue": "lq-cq-a", "prio": 50, "t": 10.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "6"}}
                    ],
                },
                # keeps wb off cycle 1: NoFit (100 > total), parks
                {
                    "name": "w-big", "queue": "lq-cq-b", "prio": 90, "t": 5.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "100"}}
                    ],
                },
                # cq-b's cycle-2 head needs its nominal back -> reclaims
                # the DRAIN-ADMITTED wa (borrowing by then)
                {
                    "name": "wb", "queue": "lq-cq-b", "prio": 0, "t": 20.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "6"}}
                    ],
                },
            ],
            "victims": [],
        }
        admitted, evicted, parked, outcome = device_preempt_drain_trace(spec)
        assert not outcome.fallback
        h_admitted, h_evicted, h_parked = host_preempt_drain_trace(spec)
        assert admitted == h_admitted
        assert evicted == h_evicted
        assert parked == h_parked
        assert "wa" in admitted and "wa" in evicted and "wb" in admitted

    @pytest.mark.parametrize("seed", range(24))
    def test_randomized(self, seed):
        spec = cohort_reclaim_spec(seed)
        h_admitted, h_evicted, h_parked = host_preempt_drain_trace(spec)
        admitted, evicted, parked, outcome = device_preempt_drain_trace(spec)
        assert not outcome.fallback
        assert admitted == h_admitted
        assert evicted == h_evicted
        assert parked == h_parked


def deep_lending_spec(seed, depth=3, workloads_per_cq=6):
    """Nested cohort forest (depth>2) with lending AND borrowing limits
    at every level — the drain must reproduce the host's quota walk
    through interior nodes exactly."""
    rng = np.random.default_rng(seed + 5000)
    flavors = ["fl-0", "fl-1"]
    cohorts = [{"name": "root", "groups": []}]
    parents = ["root"]
    for d in range(1, depth - 1):
        new_parents = []
        for pi, parent in enumerate(parents):
            for k in range(2):
                name = f"co-{d}-{pi}-{k}"
                groups = []
                if rng.random() < 0.5:
                    # quota at interior nodes (hierarchical cohorts)
                    groups = [
                        {
                            "resources": ["cpu"],
                            "flavors": [
                                ("fl-0", {"cpu": str(int(rng.integers(4, 10)))}, None, None)
                            ],
                        }
                    ]
                cohorts.append({"name": name, "parent": parent, "groups": groups})
                new_parents.append(name)
        parents = new_parents
    cqs, workloads = [], []
    t = 0.0
    for pi, parent in enumerate(parents):
        for qi in range(2):
            name = f"cq-{pi}-{qi}"
            fls = []
            for f in flavors[: int(rng.integers(1, 3))]:
                bl = str(int(rng.integers(0, 8))) if rng.random() < 0.6 else None
                ll = str(int(rng.integers(0, 5))) if rng.random() < 0.6 else None
                fls.append((f, {"cpu": str(int(rng.integers(4, 12)))}, bl, ll))
            cqs.append(
                {
                    "name": name,
                    "cohort": parent,
                    "groups": [{"resources": ["cpu"], "flavors": fls}],
                    "preemption": None,
                }
            )
            for wi in range(workloads_per_cq):
                t += 1.0
                workloads.append(
                    {
                        "name": f"wl-{pi}-{qi}-{wi}",
                        "queue": f"lq-{name}",
                        "prio": int(rng.integers(0, 4)) * 10,
                        "t": t,
                        "pod_sets": [
                            {
                                "name": "main",
                                "count": int(rng.integers(1, 4)),
                                "requests": {"cpu": str(int(rng.integers(1, 6)))},
                            }
                        ],
                    }
                )
    return {
        "flavors": flavors, "cohorts": cohorts, "cqs": cqs,
        "workloads": workloads,
    }


class TestDrainParityDeepTrees:
    """VERDICT weak #6: lending-limit and depth>2 drain parity."""

    @pytest.mark.parametrize("seed", range(8))
    def test_deep_tree_with_lending_limits(self, seed):
        spec = deep_lending_spec(seed)
        host_admitted, host_parked = host_drain_trace(spec)
        dev_admitted, dev_parked, outcome = device_drain_trace(spec)
        assert not outcome.fallback
        assert dev_admitted == host_admitted
        assert dev_parked == host_parked

    @pytest.mark.parametrize("seed", range(8, 12))
    def test_depth_four(self, seed):
        spec = deep_lending_spec(seed, depth=4, workloads_per_cq=4)
        host_admitted, host_parked = host_drain_trace(spec)
        dev_admitted, dev_parked, outcome = device_drain_trace(spec)
        assert not outcome.fallback
        assert dev_admitted == host_admitted
        assert dev_parked == host_parked


def multi_rg_spec(seed, n_cohorts=2, cqs_per_cohort=3, workloads_per_cq=6):
    """Backlogs whose CQs cover TWO resource groups ((cpu,memory) and
    gpu): candidates are cartesian products of per-group flavor walks,
    exercising the drain's per-group cursor vectors."""
    import numpy as np

    rng = np.random.default_rng(seed)
    flavors = ["fa", "fb", "ga", "gb"]
    cqs, workloads = [], []
    t = 0.0
    for ci in range(n_cohorts):
        for qi in range(cqs_per_cohort):
            name = f"cq-{ci}-{qi}"
            kf = int(rng.integers(1, 3))  # 1-2 cpu/mem flavors
            kg = int(rng.integers(1, 3))  # 1-2 gpu flavors
            cpu_flavors = [
                (f, {"cpu": str(int(rng.integers(6, 16))),
                     "memory": f"{int(rng.integers(8, 32))}Gi"},
                 str(int(rng.integers(0, 8))) if rng.random() < 0.4 else None,
                 None)
                for f in ["fa", "fb"][:kf]
            ]
            gpu_flavors = [
                (f, {"gpu": str(int(rng.integers(2, 8)))},
                 str(int(rng.integers(0, 4))) if rng.random() < 0.3 else None,
                 None)
                for f in ["ga", "gb"][:kg]
            ]
            cqs.append({
                "name": name,
                "cohort": f"cohort-{ci}",
                "groups": [
                    {"resources": ["cpu", "memory"], "flavors": cpu_flavors},
                    {"resources": ["gpu"], "flavors": gpu_flavors},
                ],
                "preemption": None,
            })
            for wi in range(workloads_per_cq):
                t += 1.0
                requests = {"cpu": str(int(rng.integers(1, 5))),
                            "memory": f"{int(rng.integers(1, 8))}Gi"}
                if rng.random() < 0.7:  # most workloads touch both RGs
                    requests["gpu"] = str(int(rng.integers(1, 3)))
                workloads.append({
                    "name": f"wl-{ci}-{qi}-{wi}",
                    "queue": f"lq-{name}",
                    "prio": int(rng.integers(0, 4)) * 10,
                    "t": t,
                    "pod_sets": [{
                        "name": "main",
                        "count": int(rng.integers(1, 3)),
                        "requests": requests,
                    }],
                })
    return {"flavors": flavors, "cqs": cqs, "workloads": workloads}


class TestDrainMultiResourceGroup:
    """Multi-RG backlogs run ON DEVICE: the per-group cursor vectors
    must reproduce the sequential scheduler's per-group LastAssignment
    resume exactly (previously these heads were routed to fallback)."""

    @pytest.mark.parametrize("seed", range(16))
    def test_randomized_parity(self, seed):
        spec = multi_rg_spec(seed)
        host_admitted, host_parked = host_drain_trace(spec)
        dev_admitted, dev_parked, outcome = device_drain_trace(spec)
        # multi-RG heads must actually run on the device now
        assert not outcome.fallback
        assert dev_admitted == host_admitted
        assert dev_parked == host_parked
        assert host_admitted  # non-trivial scenario

    def test_cartesian_cursor_resume_after_conflict(self):
        # Two CQs in one cohort contend for borrowed gpu quota: the
        # loser's retry must resume its (cpu x gpu) cartesian walk at
        # the per-group cursors, not at combo k+1.
        spec = {
            "flavors": ["fa", "fb", "ga", "gb"],
            "cqs": [
                {
                    "name": f"cq-{x}",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"], "flavors": [
                            ("fa", {"cpu": "4"}, "4", None),
                            ("fb", {"cpu": "4"}, "4", None),
                        ]},
                        {"resources": ["gpu"], "flavors": [
                            ("ga", {"gpu": "1"}, "1", None),
                            ("gb", {"gpu": "2"}, "2", None),
                        ]},
                    ],
                    "preemption": None,
                }
                for x in ("a", "b")
            ],
            "workloads": [
                {
                    "name": f"w-{x}-{i}",
                    "queue": f"lq-cq-{x}",
                    "prio": 0,
                    "t": float(i + (0 if x == "a" else 10)),
                    "pod_sets": [{
                        "name": "main", "count": 1,
                        "requests": {"cpu": "3", "gpu": "2"},
                    }],
                }
                for x in ("a", "b")
                for i in range(3)
            ],
        }
        host_admitted, host_parked = host_drain_trace(spec)
        dev_admitted, dev_parked, outcome = device_drain_trace(spec)
        assert not outcome.fallback
        assert dev_admitted == host_admitted
        assert dev_parked == host_parked


def multi_rg_preempt_spec(seed, n_cqs=4, victims_per_cq=3, workloads_per_cq=4):
    """Multi-resource-group scenarios INSIDE the preempt-drain scope
    (withinClusterQueue=LowerPriority, no cohort): saturated CQs whose
    victims and pending workloads both span two resource groups, so the
    device's per-group cursors, reclaim-oracle emulation, and victim
    search run together."""
    from kueue_tpu.models.cluster_queue import Preemption
    from kueue_tpu.models.constants import PreemptionPolicy

    rng = np.random.default_rng(seed)
    cqs, workloads, victims = [], [], []
    t = 0.0
    for qi in range(n_cqs):
        name = f"cq-{qi}"
        kf = int(rng.integers(1, 3))
        kg = int(rng.integers(1, 3))
        cpu_flavors = [
            (f, {"cpu": str(int(rng.integers(8, 16)))}, None, None)
            for f in ["fa", "fb"][:kf]
        ]
        gpu_flavors = [
            (f, {"gpu": str(int(rng.integers(4, 8)))}, None, None)
            for f in ["ga", "gb"][:kg]
        ]
        cqs.append({
            "name": name,
            "cohort": None,
            "groups": [
                {"resources": ["cpu"], "flavors": cpu_flavors},
                {"resources": ["gpu"], "flavors": gpu_flavors},
            ],
            "preemption": Preemption(
                within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY
            ),
        })
        for vi in range(victims_per_cq):
            t += 1.0
            requests = {"cpu": str(int(rng.integers(2, 6)))}
            flavors = {"cpu": rng.choice(["fa", "fb"][:kf])}
            if rng.random() < 0.8:
                requests["gpu"] = str(int(rng.integers(1, 3)))
                flavors["gpu"] = rng.choice(["ga", "gb"][:kg])
            victims.append((f"v-{qi}-{vi}", name, flavors, requests, 0, t))
        for wi in range(workloads_per_cq):
            t += 1.0
            requests = {"cpu": str(int(rng.integers(2, 6)))}
            if rng.random() < 0.8:
                requests["gpu"] = str(int(rng.integers(1, 3)))
            workloads.append({
                "name": f"wl-{qi}-{wi}",
                "queue": f"lq-{name}",
                "prio": int(rng.integers(1, 4)) * 10,
                "t": t,
                "pod_sets": [{
                    "name": "main", "count": 1, "requests": requests,
                }],
            })
    return {
        "flavors": ["fa", "fb", "ga", "gb"],
        "cqs": cqs,
        "workloads": workloads,
        "victims": victims,
    }


class TestPreemptDrainMultiResourceGroup:
    """Multi-RG preemption drains on device: per-group cursor vectors +
    reclaim-oracle emulation + in-kernel victim search must match the
    sequential host scheduler with evictions applied at cycle
    boundaries."""

    @pytest.mark.parametrize("seed", range(12))
    def test_randomized_parity(self, seed):
        spec = multi_rg_preempt_spec(seed)
        ha, he, hp = host_preempt_drain_trace(spec)
        da, de, dp, outcome = device_preempt_drain_trace(spec)
        assert not outcome.fallback
        assert da == ha
        assert de == he
        assert dp == hp
        assert ha and he  # scenario admits and evicts


def fungibility_spec(seed, n_cohorts=2, cqs_per_cohort=3, workloads_per_cq=7):
    """Backlogs over CQs with randomized flavorFungibility policies
    (whenCanBorrow Borrow|TryNextFlavor x whenCanPreempt
    TryNextFlavor|Preempt): the drain's policy-aware group walk must
    stop/continue exactly like the host's _should_try_next_flavor."""
    from kueue_tpu.models import FlavorFungibility
    from kueue_tpu.models.constants import FlavorFungibilityPolicy as FFP

    rng = np.random.default_rng(seed)
    flavors = [f"fl-{i}" for i in range(3)]
    cqs, workloads = [], []
    t = 0.0
    for ci in range(n_cohorts):
        for qi in range(cqs_per_cohort):
            name = f"cq-{ci}-{qi}"
            k = int(rng.integers(2, 4))
            fls = [
                (f, {"cpu": str(int(rng.integers(4, 14)))},
                 str(int(rng.integers(0, 10))) if rng.random() < 0.5 else None,
                 None)
                for f in flavors[:k]
            ]
            # index, not rng.choice: numpy truncates str-enum members
            # to fixed-width unicode scalars that equal neither member
            fung = FlavorFungibility(
                when_can_borrow=[FFP.BORROW, FFP.TRY_NEXT_FLAVOR][
                    int(rng.integers(0, 2))
                ],
                when_can_preempt=[FFP.TRY_NEXT_FLAVOR, FFP.PREEMPT][
                    int(rng.integers(0, 2))
                ],
            )
            cqs.append({
                "name": name,
                "cohort": f"cohort-{ci}",
                "groups": [{"resources": ["cpu"], "flavors": fls}],
                "preemption": None,
                "fungibility": fung,
            })
            for wi in range(workloads_per_cq):
                t += 1.0
                workloads.append({
                    "name": f"wl-{ci}-{qi}-{wi}",
                    "queue": f"lq-{name}",
                    "prio": int(rng.integers(0, 4)) * 10,
                    "t": t,
                    "pod_sets": [{
                        "name": "main",
                        "count": int(rng.integers(1, 4)),
                        "requests": {"cpu": str(int(rng.integers(1, 6)))},
                    }],
                })
    return {"flavors": flavors, "cqs": cqs, "workloads": workloads}


class TestDrainFungibilityPolicies:
    """Non-default flavorFungibility on device (previously host-only):
    TryNextFlavor borrowing (prefer a later non-borrowing flavor, fall
    back to the first borrowing fit) and Preempt stopping."""

    @pytest.mark.parametrize("seed", range(16))
    def test_randomized_parity(self, seed):
        spec = fungibility_spec(seed)
        host_admitted, host_parked = host_drain_trace(spec)
        dev_admitted, dev_parked, outcome = device_drain_trace(spec)
        assert not outcome.fallback
        assert dev_admitted == host_admitted
        assert dev_parked == host_parked
        assert host_admitted

    def test_try_next_flavor_prefers_non_borrowing(self):
        # first flavor only fits by borrowing; whenCanBorrow=
        # TryNextFlavor must walk on and take the non-borrowing second
        from kueue_tpu.models import FlavorFungibility
        from kueue_tpu.models.constants import FlavorFungibilityPolicy as FFP

        spec = {
            "flavors": ["small", "big"],
            "cqs": [
                {
                    "name": "cq-a",
                    "cohort": "co",
                    "groups": [{"resources": ["cpu"], "flavors": [
                        ("small", {"cpu": "2"}, "10", None),
                        ("big", {"cpu": "10"}, None, None),
                    ]}],
                    "preemption": None,
                    "fungibility": FlavorFungibility(
                        when_can_borrow=FFP.TRY_NEXT_FLAVOR,
                        when_can_preempt=FFP.TRY_NEXT_FLAVOR,
                    ),
                },
                {
                    "name": "cq-b",
                    "cohort": "co",
                    "groups": [{"resources": ["cpu"], "flavors": [
                        ("small", {"cpu": "10"}, None, None),
                    ]}],
                    "preemption": None,
                },
            ],
            "workloads": [
                {
                    "name": "w0", "queue": "lq-cq-a", "prio": 0, "t": 0.0,
                    "pod_sets": [{"name": "main", "count": 1,
                                  "requests": {"cpu": "4"}}],
                }
            ],
        }
        host_admitted, _ = host_drain_trace(spec)
        dev_admitted, _, outcome = device_drain_trace(spec)
        assert not outcome.fallback
        assert dev_admitted == host_admitted
        assert dev_admitted["w0"][0] == {"cpu": "big"}


class TestPreemptDrainFungibility:
    """Non-default fungibility through solve_drain_preempt: the policy
    bits must reach the preempt kernel's group walk alongside the
    victim-aware reclaim upgrade."""

    @pytest.mark.parametrize("seed", range(8))
    def test_randomized_parity(self, seed):
        from kueue_tpu.models import FlavorFungibility
        from kueue_tpu.models.constants import FlavorFungibilityPolicy as FFP

        rng = np.random.default_rng(1000 + seed)
        spec = multi_rg_preempt_spec(seed)
        for cq_spec in spec["cqs"]:
            cq_spec["fungibility"] = FlavorFungibility(
                when_can_borrow=[FFP.BORROW, FFP.TRY_NEXT_FLAVOR][
                    int(rng.integers(0, 2))
                ],
                when_can_preempt=[FFP.TRY_NEXT_FLAVOR, FFP.PREEMPT][
                    int(rng.integers(0, 2))
                ],
            )
        ha, he, hp = host_preempt_drain_trace(spec)
        da, de, dp, outcome = device_preempt_drain_trace(spec)
        assert not outcome.fallback
        assert da == ha
        assert de == he
        assert dp == hp


def host_drain_trace_multi(spec):
    """Host truth with per-podset flavor maps: single-podset workloads
    keep the flat {resource: flavor}; multi-podset ones nest by podset
    name — the same shapes the device outcome mapping produces.

    Returns (admitted, parked, undecided): at quiescence heaps are
    empty, so heap leftovers exist only when the cycle cap was hit —
    a PendingFlavors retry loop that never converges (the reference's
    immediate-requeue machinery spins identically,
    cluster_queue.go:231); those entries are no-decision, which the
    device drain reports as fallback after ITS cycle cap."""
    sched, mgr, cache, _ = build_env(spec, use_solver=False)
    admitted = {}
    cycle = 0
    for _ in range(300):
        if not any(
            pq.pending_active() > 0 for pq in mgr.cluster_queues.values()
        ):
            break
        res = sched.schedule()
        for e in res.admitted:
            psas = e.workload.admission.pod_set_assignments
            if len(psas) == 1:
                fl = dict(psas[0].flavors)
            else:
                fl = {psa.name: dict(psa.flavors) for psa in psas}
            admitted[e.workload.name] = (fl, cycle)
        cycle += 1
    parked = {
        wl.name
        for pq in mgr.cluster_queues.values()
        for wl in pq.inadmissible.values()
    }
    undecided = {
        wl.name
        for pq in mgr.cluster_queues.values()
        for wl in pq.heap.items()
    }
    return admitted, parked, undecided


def multi_podset_spec(seed, n_cohorts=2, cqs_per_cohort=3, workloads_per_cq=5):
    """Driver+worker style workloads: 2-3 podsets per workload sharing
    (flavor, resource) cells, so podset nominations couple through
    assignment_usage exactly like the host's sequential walk."""
    rng = np.random.default_rng(seed)
    flavors = ["fa", "fb"]
    cqs, workloads = [], []
    t = 0.0
    for ci in range(n_cohorts):
        for qi in range(cqs_per_cohort):
            name = f"cq-{ci}-{qi}"
            k = int(rng.integers(1, 3))
            fls = [
                (f, {"cpu": str(int(rng.integers(8, 20)))},
                 str(int(rng.integers(0, 10))) if rng.random() < 0.4 else None,
                 None)
                for f in flavors[:k]
            ]
            cqs.append({
                "name": name,
                "cohort": f"cohort-{ci}",
                "groups": [{"resources": ["cpu"], "flavors": fls}],
                "preemption": None,
            })
            for wi in range(workloads_per_cq):
                t += 1.0
                npods = int(rng.integers(1, 4))
                pod_sets = [
                    {
                        "name": ["driver", "worker", "aux"][pp],
                        "count": int(rng.integers(1, 3)),
                        "requests": {"cpu": str(int(rng.integers(1, 5)))},
                    }
                    for pp in range(npods)
                ]
                workloads.append({
                    "name": f"wl-{ci}-{qi}-{wi}",
                    "queue": f"lq-{name}",
                    "prio": int(rng.integers(0, 4)) * 10,
                    "t": t,
                    "pod_sets": pod_sets,
                })
    return {"flavors": flavors, "cqs": cqs, "workloads": workloads}


class TestDrainMultiPodset:
    """Multi-podset workloads on the device drain: podsets nominate
    sequentially with assignment_usage coupling at shared cells
    (previously every multi-podset head routed to the host fallback)."""

    @pytest.mark.parametrize("seed", range(16))
    def test_randomized_parity(self, seed):
        spec = multi_podset_spec(seed)
        host_admitted, host_parked, undecided = host_drain_trace_multi(spec)
        dev_admitted, dev_parked, outcome = device_drain_trace(spec)
        assert dev_admitted == host_admitted
        assert dev_parked == host_parked
        # non-converging PendingFlavors retry loops spin forever on the
        # host (the reference's immediate-requeue does the same until
        # external events change state); the drain freezes the stuck
        # queue — the head keeps nominating so its reservations still
        # shape other queues — and reports its entries as no-decision
        assert {wl.name for wl, _ in outcome.fallback} == undecided
        assert host_admitted

    def test_podsets_share_cells(self):
        # driver 3cpu + workers 2x2cpu = 7 > fa's 8? fits; the SECOND
        # workload's driver alone would fit fa but the sum must spill:
        # assignment_usage coupling decides flavors per podset
        spec = {
            "flavors": ["fa", "fb"],
            "cqs": [{
                "name": "cq",
                "cohort": "co",
                "groups": [{"resources": ["cpu"], "flavors": [
                    ("fa", {"cpu": "8"}, None, None),
                    ("fb", {"cpu": "100"}, None, None),
                ]}],
                "preemption": None,
            }],
            "workloads": [
                {
                    "name": f"w{i}",
                    "queue": "lq-cq",
                    "prio": 0,
                    "t": float(i),
                    "pod_sets": [
                        {"name": "driver", "count": 1,
                         "requests": {"cpu": "3"}},
                        {"name": "worker", "count": 2,
                         "requests": {"cpu": "2"}},
                    ],
                }
                for i in range(3)
            ],
        }
        host_admitted, host_parked, undecided = host_drain_trace_multi(spec)
        dev_admitted, dev_parked, outcome = device_drain_trace(spec)
        assert not outcome.fallback and not undecided
        assert dev_admitted == host_admitted
        assert dev_parked == host_parked


class TestPreemptDrainMultiPodset:
    @pytest.mark.parametrize("seed", range(8))
    def test_randomized_parity(self, seed):
        # multi-podset pending workloads over saturated single-podset
        # victims (within-CQ preemption)
        rng = np.random.default_rng(seed)
        spec = multi_rg_preempt_spec(seed, n_cqs=3)
        for w in spec["workloads"]:
            if rng.random() < 0.6:
                w["pod_sets"].append({
                    "name": "worker",
                    "count": int(rng.integers(1, 3)),
                    "requests": {"cpu": str(int(rng.integers(1, 4)))},
                })
        ha, he, hp = host_preempt_drain_trace(spec)
        da, de, dp, outcome = device_preempt_drain_trace(spec)
        assert not outcome.fallback
        assert da == ha
        assert de == he
        assert dp == hp


def host_fair_drain_trace(spec):
    """Host truth under fair-sharing admission ordering: scheduler
    cycles with fair_sharing enabled, to quiescence."""
    sched, mgr, cache, _ = build_env(spec, use_solver=False)
    sched.fair_sharing = True
    admitted = {}
    cycle = 0
    for _ in range(200):
        if not any(
            pq.pending_active() > 0 for pq in mgr.cluster_queues.values()
        ):
            break
        res = sched.schedule()
        for e in res.admitted:
            psa = e.workload.admission.pod_set_assignments[0]
            admitted[e.workload.name] = (dict(psa.flavors), cycle)
        cycle += 1
    parked = {
        wl.name
        for pq in mgr.cluster_queues.values()
        for wl in list(pq.inadmissible.values()) + list(pq.heap.items())
    }
    return admitted, parked


def device_fair_drain_trace(spec):
    sched, mgr, cache, _ = build_env(spec, use_solver=False)
    pending = []
    for cq_name, pq in mgr.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            pending.append((wl, cq_name))
    snapshot = take_snapshot(cache)
    outcome = run_drain(
        snapshot,
        pending,
        cache.flavors,
        timestamp_fn=lambda wl: queue_order_timestamp(wl, mgr._ts_policy),
        fair_sharing=True,
    )
    admitted = {
        wl.name: (flavors, cycle) for wl, _, flavors, cycle in outcome.admitted
    }
    parked = {wl.name for wl, _ in outcome.parked}
    return admitted, parked, outcome


def fair_drain_spec(seed, n_cohorts=2, cqs_per_cohort=4, workloads_per_cq=5):
    """Cohorts with shared borrowable capacity, unequal fairSharing
    weights and contending backlogs — admission ORDER is decided by the
    DRS tournament, not (priority, FIFO)."""
    rng = np.random.default_rng(seed + 47000)
    flavors = ["fl-0", "fl-1"]
    cqs, workloads = [], []
    t = 0.0
    weights = [500, 1000, 1000, 2000]
    for ci in range(n_cohorts):
        for qi in range(cqs_per_cohort):
            name = f"cq-{ci}-{qi}"
            k = int(rng.integers(1, 3))
            fls = []
            for f in flavors[:k]:
                fls.append((f, {"cpu": str(int(rng.integers(2, 8)))}, None, None))
            cqs.append(
                {
                    "name": name,
                    "cohort": f"cohort-{ci}",
                    "groups": [{"resources": ["cpu"], "flavors": fls}],
                    "fair_weight": weights[int(rng.integers(0, len(weights)))],
                }
            )
            for wi in range(workloads_per_cq):
                t += 1.0
                workloads.append(
                    {
                        "name": f"wl-{ci}-{qi}-{wi}",
                        "queue": f"lq-{name}",
                        "prio": int(rng.integers(0, 3)) * 10,
                        "t": t,
                        "pod_sets": [
                            {
                                "name": "main",
                                "count": int(rng.integers(1, 3)),
                                "requests": {"cpu": str(int(rng.integers(1, 5)))},
                            }
                        ],
                    }
                )
    return {"flavors": flavors, "cqs": cqs, "workloads": workloads}


class TestDrainFairSharing:
    def test_tournament_orders_by_drs(self):
        # cq-a (weight 500) already borrows heavily; cq-b (weight 2000)
        # borrows little. Fair order admits cq-b's head first when only
        # one can fit — the opposite of the FIFO order.
        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": "cq-a",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "2"}, None, None)]}
                    ],
                    "fair_weight": 500,
                },
                {
                    "name": "cq-b",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "2"}, None, None)]}
                    ],
                    "fair_weight": 2000,
                },
            ],
            "workloads": [
                # FIFO would admit wa first (earlier timestamp)
                {
                    "name": "wa", "queue": "lq-cq-a", "prio": 0, "t": 1.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "3"}}
                    ],
                },
                {
                    "name": "wb", "queue": "lq-cq-b", "prio": 0, "t": 2.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "3"}}
                    ],
                },
            ],
        }
        h_admitted, h_parked = host_fair_drain_trace(spec)
        d_admitted, d_parked, outcome = device_fair_drain_trace(spec)
        assert not outcome.fallback
        assert d_admitted == h_admitted
        assert d_parked == h_parked
        # both would borrow 1 above nominal 2; b's weight (2000) makes
        # its simulated share lower, so b wins the tournament, admits
        # in cycle 0, and a (no capacity left) parks
        assert d_admitted == {"wb": ({"cpu": "f"}, 0)}
        assert d_parked == {"wa"}
        # the NON-fair order decides the opposite way (wa is older), so
        # the tournament — not FIFO — made this call
        ff_admitted, ff_parked, _ = device_drain_trace(spec)
        assert "wa" in ff_admitted and ff_parked == {"wb"}

    def test_preempt_capable_cqs_fall_back_in_fair_mode(self):
        from kueue_tpu.models.cluster_queue import Preemption
        from kueue_tpu.models.constants import PreemptionPolicy

        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": "cq",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "4"}, None, None)]}
                    ],
                    "preemption": Preemption(
                        within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY
                    ),
                }
            ],
            "workloads": [
                {
                    "name": "w", "queue": "lq-cq", "prio": 0, "t": 1.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "2"}}
                    ],
                }
            ],
        }
        _, _, outcome = device_fair_drain_trace(spec)
        assert [wl.name for wl, _ in outcome.fallback] == ["w"]

    # tier-1 runtime headroom (ISSUE 14): 4 deterministic seeds stay
    # tier-1, the remainder of the historical sweep rides @slow
    @pytest.mark.parametrize("seed", range(4))
    def test_randomized(self, seed):
        spec = fair_drain_spec(seed)
        h_admitted, h_parked = host_fair_drain_trace(spec)
        d_admitted, d_parked, outcome = device_fair_drain_trace(spec)
        assert not outcome.fallback
        assert d_admitted == h_admitted
        assert d_parked == h_parked

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", range(4, 16))
    def test_randomized_wide(self, seed):
        self.test_randomized(seed)


def host_fair_preempt_drain_trace(spec, fs_strategies=None):
    """Host truth under fair-sharing ordering AND fair-sharing
    preemption: scheduler cycles with evictions applied between cycles
    (the reconciler round-trip compressed to the cycle boundary)."""
    sched, mgr, cache, _ = build_preempt_env(spec)
    sched.fair_sharing = True
    sched.preemptor.enable_fair_sharing = True
    if fs_strategies is not None:
        sched.preemptor.fs_strategies = list(fs_strategies)
    admitted, evicted = {}, set()
    for _ in range(300):
        progressed = False
        if any(pq.pending_active() > 0 for pq in mgr.cluster_queues.values()):
            progressed = True
        res = sched.schedule()
        for e in res.admitted:
            psa = e.workload.admission.pod_set_assignments[0]
            admitted[e.workload.name] = dict(psa.flavors)
        victims = []
        for e in res.preempting:
            for t in e.preemption_targets:
                victims.append(t.workload.workload)
        for wl in victims:
            if wl.name in evicted:
                continue
            evicted.add(wl.name)
            cq_name = wl.admission.cluster_queue
            cache.delete_workload(wl)
            mgr.queue_associated_inadmissible_workloads_after(cq_name)
            progressed = True
        if not progressed:
            break
    parked = {
        wl.name
        for pq in mgr.cluster_queues.values()
        for wl in list(pq.inadmissible.values()) + list(pq.heap.items())
    }
    return admitted, evicted, parked


def device_fair_preempt_drain_trace(spec, fs_strategies=None, **kw):
    from kueue_tpu.core.drain import run_drain_fair_preempt

    sched, mgr, cache, _ = build_preempt_env(spec)
    pending = []
    for cq_name, pq in mgr.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            pending.append((wl, cq_name))
    snapshot = take_snapshot(cache)
    outcome = run_drain_fair_preempt(
        snapshot,
        pending,
        cache.flavors,
        timestamp_fn=lambda wl: queue_order_timestamp(wl, mgr._ts_policy),
        fs_strategies=fs_strategies,
        **kw,
    )
    admitted = {wl.name: flavors for wl, _, flavors, _ in outcome.admitted}
    evicted = {wl.name for wl, _, _ in outcome.preempted}
    parked = {wl.name for wl, _ in outcome.parked}
    return admitted, evicted, parked, outcome


def fair_preempt_spec(
    seed, n_cohorts=2, cqs_per_cohort=3, victims_per_cq=3, workloads_per_cq=3
):
    """Random fair cohorts WITH preemption enabled — borrowing victims
    saturate shared capacity, pending backlogs need the fair victim
    tournament to start."""
    from kueue_tpu.models.cluster_queue import Preemption
    from kueue_tpu.models.constants import (
        PreemptionPolicy,
        ReclaimWithinCohortPolicy,
    )

    rng = np.random.default_rng(seed + 91000)
    flavors = ["fl-0"]
    cqs, workloads, victims = [], [], []
    weights = [500, 1000, 1000, 2000]
    wcq_opts = [
        PreemptionPolicy.NEVER,
        PreemptionPolicy.LOWER_PRIORITY,
        PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY,
    ]
    rwc_opts = [
        ReclaimWithinCohortPolicy.NEVER,
        ReclaimWithinCohortPolicy.LOWER_PRIORITY,
        ReclaimWithinCohortPolicy.ANY,
    ]
    t = 0.0
    for ci in range(n_cohorts):
        for qi in range(cqs_per_cohort):
            name = f"cq-{ci}-{qi}"
            quota = int(rng.integers(4, 10))
            cqs.append(
                {
                    "name": name,
                    "cohort": f"cohort-{ci}",
                    "groups": [
                        {
                            "resources": ["cpu"],
                            "flavors": [("fl-0", {"cpu": str(quota)}, None, None)],
                        }
                    ],
                    "fair_weight": weights[int(rng.integers(0, len(weights)))],
                    "preemption": Preemption(
                        within_cluster_queue=wcq_opts[
                            int(rng.integers(0, len(wcq_opts)))
                        ],
                        reclaim_within_cohort=rwc_opts[
                            int(rng.integers(0, len(rwc_opts)))
                        ],
                    ),
                }
            )
            # admitted victims, some borrowing above nominal (DRS > 0)
            for vi in range(int(rng.integers(1, victims_per_cq + 1))):
                t += 1.0
                victims.append(
                    (
                        f"victim-{ci}-{qi}-{vi}", name, "fl-0",
                        str(int(rng.integers(2, 7))),
                        int(rng.integers(0, 3)) * 10, t,
                    )
                )
            for wi in range(workloads_per_cq):
                t += 1.0
                workloads.append(
                    {
                        "name": f"wl-{ci}-{qi}-{wi}",
                        "queue": f"lq-{name}",
                        "prio": int(rng.integers(0, 3)) * 10,
                        "t": t,
                        "pod_sets": [
                            {
                                "name": "main",
                                "count": 1,
                                "requests": {
                                    "cpu": str(int(rng.integers(1, 5)))
                                },
                            }
                        ],
                    }
                )
    return {
        "flavors": flavors, "cqs": cqs, "workloads": workloads,
        "victims": victims,
    }


class TestFairPreemptDrain:
    def test_fair_preemption_in_kernel(self):
        # cohort capacity saturated by a borrowing low-weight CQ; the
        # high-weight CQ's head can only start via the fair victim
        # tournament — no fallback, eviction decided in the drain
        from kueue_tpu.models.cluster_queue import Preemption
        from kueue_tpu.models.constants import (
            PreemptionPolicy,
            ReclaimWithinCohortPolicy,
        )
        from kueue_tpu.core.preemption import IN_COHORT_FAIR_SHARING

        prem = Preemption(
            within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
            reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
        )
        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": "cq-a",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"],
                         "flavors": [("f", {"cpu": "4"}, None, None)]}
                    ],
                    "fair_weight": 1000,
                    "preemption": prem,
                },
                {
                    "name": "cq-b",
                    "cohort": "co",
                    "groups": [
                        {"resources": ["cpu"],
                         "flavors": [("f", {"cpu": "4"}, None, None)]}
                    ],
                    "fair_weight": 1000,
                    "preemption": prem,
                },
            ],
            # cq-a borrows the whole cohort (8 cpu over nominal 4)
            "victims": [
                ("va-0", "cq-a", "f", "4", 0, 1.0),
                ("va-1", "cq-a", "f", "4", 0, 2.0),
            ],
            "workloads": [
                {
                    "name": "wb", "queue": "lq-cq-b", "prio": 0, "t": 3.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "3"}}
                    ],
                }
            ],
        }
        ha, he, hp = host_fair_preempt_drain_trace(spec)
        da, de, dp, outcome = device_fair_preempt_drain_trace(spec)
        assert not outcome.fallback
        assert da == ha
        assert de == he
        assert dp == hp
        assert "wb" in da and de  # preemption actually happened
        other_cq = [
            ev for ev in outcome.evictions if ev.victim_cq != ev.by_cq
        ]
        assert all(
            ev.reason == IN_COHORT_FAIR_SHARING for ev in other_cq
        ) and other_cq

    # tier-1 runtime headroom (ISSUE 14): 4 deterministic seeds stay
    # tier-1, the remainder of the historical sweep rides @slow
    @pytest.mark.parametrize("seed", range(4))
    def test_randomized_parity(self, seed):
        spec = fair_preempt_spec(seed)
        ha, he, hp = host_fair_preempt_drain_trace(spec)
        da, de, dp, outcome = device_fair_preempt_drain_trace(spec)
        assert not outcome.fallback
        assert da == ha
        assert de == he
        assert dp == hp

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", range(4, 16))
    def test_randomized_parity_wide(self, seed):
        self.test_randomized_parity(seed)

    @pytest.mark.parametrize("seed", range(3))
    def test_randomized_parity_single_strategy(self, seed):
        # LessThanInitialShare alone (the other configurable strategy
        # list, config fairSharing.preemptionStrategies)
        from kueue_tpu.core.preemption import LESS_THAN_INITIAL_SHARE

        strategies = [LESS_THAN_INITIAL_SHARE]
        spec = fair_preempt_spec(seed + 300)
        ha, he, hp = host_fair_preempt_drain_trace(spec, strategies)
        da, de, dp, outcome = device_fair_preempt_drain_trace(
            spec, fs_strategies=strategies
        )
        assert not outcome.fallback
        assert da == ha
        assert de == he
        assert dp == hp

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", range(3, 8))
    def test_randomized_parity_single_strategy_wide(self, seed):
        self.test_randomized_parity_single_strategy(seed)


def test_retry_cap_scales_with_walk_odometer():
    """The stuck-detection budget must cover any CONVERGENT
    PendingFlavors sequence: prod over podsets and resource groups of
    (walk length + 1), not a flat multiple of K."""
    from kueue_tpu.core.drain import plan_drain
    from tests.test_solver_path import build_env

    spec = {
        "flavors": ["f0", "f1", "f2", "f3", "g0", "g1"],
        "cqs": [{
            "name": "cq",
            "cohort": "co",
            "groups": [
                {"resources": ["cpu"], "flavors": [
                    (f, {"cpu": "4"}, None, None) for f in ["f0", "f1", "f2", "f3"]
                ]},
                {"resources": ["gpu"], "flavors": [
                    (g, {"gpu": "2"}, None, None) for g in ["g0", "g1"]
                ]},
            ],
            "preemption": None,
        }],
        "workloads": [
            {
                "name": "w-multi", "queue": "lq-cq", "prio": 0, "t": 0.0,
                "pod_sets": [
                    {"name": "driver", "count": 1,
                     "requests": {"cpu": "1", "gpu": "1"}},
                    {"name": "worker", "count": 1,
                     "requests": {"cpu": "1"}},
                ],
            },
        ],
    }
    sched, mgr, cache, _ = build_env(spec, use_solver=False)
    pending = [
        (wl, cqn.replace("lq-", ""))
        for cqn, pq in mgr.cluster_queues.items()
        for wl in pq.snapshot_sorted()
    ]
    pending = [(wl, "cq") for wl, _ in pending]
    snap = take_snapshot(cache)
    plan = plan_drain(snap, pending, cache.flavors)
    # driver: (4+1)*(2+1)=15; worker: (4+1)=5 -> joint odometer 75 (+1)
    assert int(plan.queues_np["retry_cap"][0]) == 76
