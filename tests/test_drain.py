"""On-device bulk drain vs the sequential host scheduler.

The drain kernel runs the whole multi-cycle backlog on device; for
preemption-free, fully-representable backlogs its decisions — who is
admitted, with which flavors, in which cycle — must match running the
host Scheduler cycle-by-cycle to quiescence.
"""

import numpy as np
import pytest

from kueue_tpu.core.drain import run_drain
from kueue_tpu.core.queue_manager import queue_order_timestamp
from kueue_tpu.core.snapshot import take_snapshot

from tests.test_solver_path import build_env, random_spec


def host_drain_trace(spec):
    """Drain via the host scheduler; returns {wl name: (flavors, cycle)}
    plus the parked set."""
    sched, mgr, cache, _ = build_env(spec, use_solver=False)
    admitted = {}
    cycle = 0
    for _ in range(200):
        # quiescent only when every active heap is empty — a cycle that
        # parks its head uncovers the next workload behind it
        if not any(
            pq.pending_active() > 0 for pq in mgr.cluster_queues.values()
        ):
            break
        res = sched.schedule()
        for e in res.admitted:
            psa = e.workload.admission.pod_set_assignments[0]
            admitted[e.workload.name] = (dict(psa.flavors), cycle)
        cycle += 1
    parked = {
        wl.name
        for pq in mgr.cluster_queues.values()
        for wl in list(pq.inadmissible.values()) + list(pq.heap.items())
    }
    return admitted, parked


def device_drain_trace(spec):
    sched, mgr, cache, _ = build_env(spec, use_solver=False)
    # collect the backlog in per-CQ heap order
    pending = []
    for cq_name, pq in mgr.cluster_queues.items():
        for wl in pq.snapshot_sorted():
            pending.append((wl, cq_name))
    snapshot = take_snapshot(cache)
    outcome = run_drain(
        snapshot,
        pending,
        cache.flavors,
        timestamp_fn=lambda wl: queue_order_timestamp(wl, mgr._ts_policy),
    )
    admitted = {
        wl.name: (flavors, cycle) for wl, _, flavors, cycle in outcome.admitted
    }
    parked = {wl.name for wl, _ in outcome.parked}
    return admitted, parked, outcome


class TestDrainParity:
    @pytest.mark.parametrize("seed", range(10))
    def test_randomized(self, seed):
        spec = random_spec(seed, workloads_per_cq=8)
        host_admitted, host_parked = host_drain_trace(spec)
        dev_admitted, dev_parked, outcome = device_drain_trace(spec)
        assert not outcome.fallback
        assert dev_admitted == host_admitted
        assert dev_parked == host_parked

    def test_multi_flavor_spillover(self):
        # second flavor absorbs what the first can't; drain must walk
        # candidates exactly like the host
        spec = {
            "flavors": ["fast", "slow"],
            "cqs": [
                {
                    "name": "cq",
                    "cohort": "co",
                    "groups": [
                        {
                            "resources": ["cpu"],
                            "flavors": [
                                ("fast", {"cpu": "4"}, None, None),
                                ("slow", {"cpu": "100"}, None, None),
                            ],
                        }
                    ],
                    "preemption": None,
                }
            ],
            "workloads": [
                {
                    "name": f"w{i}",
                    "queue": "lq-cq",
                    "prio": 0,
                    "t": float(i),
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "3"}}
                    ],
                }
                for i in range(6)
            ],
        }
        host_admitted, _ = host_drain_trace(spec)
        dev_admitted, _, _ = device_drain_trace(spec)
        assert dev_admitted == host_admitted
        # first workload on "fast", rest spill to "slow"
        assert dev_admitted["w0"][0] == {"cpu": "fast"}
        assert dev_admitted["w1"][0] == {"cpu": "slow"}

    def test_cohort_borrowing_contention(self):
        # shared cohort capacity: cross-CQ conflicts resolved per cycle
        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": f"cq-{i}",
                    "cohort": "co",
                    "groups": [
                        {
                            "resources": ["cpu"],
                            "flavors": [("f", {"cpu": "4"}, None, None)],
                        }
                    ],
                    "preemption": None,
                }
                for i in range(4)
            ],
            "workloads": [
                {
                    "name": f"w{i}",
                    "queue": f"lq-cq-{i % 4}",
                    "prio": (i * 7) % 3,
                    "t": float(i),
                    "pod_sets": [
                        {
                            "name": "main",
                            "count": 1,
                            "requests": {"cpu": str(2 + (i % 5))},
                        }
                    ],
                }
                for i in range(20)
            ],
        }
        host_admitted, host_parked = host_drain_trace(spec)
        dev_admitted, dev_parked, outcome = device_drain_trace(spec)
        assert dev_admitted == host_admitted
        assert dev_parked == host_parked
        assert outcome.cycles >= 2


def deep_tree_spec(seed, depth=3, fanout=2, workloads_per_cq=5):
    """Cohort tree of the given depth: root holds the quota, interior
    cohorts are pass-through, CQs at the leaves borrow all the way up."""
    rng = np.random.default_rng(seed)
    cohorts = [
        {
            "name": "root",
            "groups": [
                {"resources": ["cpu"], "flavors": [("f", {"cpu": "40"}, None, None)]}
            ],
        }
    ]
    parents = ["root"]
    for d in range(1, depth):
        nxt = []
        for p in parents:
            for i in range(fanout):
                name = f"{p}-{i}"
                cohorts.append({"name": name, "parent": p})
                nxt.append(name)
        parents = nxt
    cqs = []
    workloads = []
    t = 0.0
    for p in parents:
        name = f"cq-{p}"
        cqs.append(
            {
                "name": name,
                "cohort": p,
                "groups": [
                    {
                        "resources": ["cpu"],
                        "flavors": [("f", {"cpu": "2"}, None, None)],
                    }
                ],
                "preemption": None,
            }
        )
        for wi in range(workloads_per_cq):
            t += 1.0
            workloads.append(
                {
                    "name": f"w-{name}-{wi}",
                    "queue": f"lq-{name}",
                    "prio": int(rng.integers(0, 3)),
                    "t": t,
                    "pod_sets": [
                        {
                            "name": "main",
                            "count": 1,
                            "requests": {"cpu": str(int(rng.integers(1, 6)))},
                        }
                    ],
                }
            )
    return {"flavors": ["f"], "cohorts": cohorts, "cqs": cqs, "workloads": workloads}


class TestDrainDeepTree:
    @pytest.mark.parametrize("seed", range(4))
    def test_depth3_parity(self, seed):
        spec = deep_tree_spec(seed)
        host_admitted, host_parked = host_drain_trace(spec)
        dev_admitted, dev_parked, outcome = device_drain_trace(spec)
        assert not outcome.fallback
        assert not outcome.truncated
        assert dev_admitted == host_admitted
        assert dev_parked == host_parked


class TestDrainTruncation:
    def test_max_cycles_routes_unprocessed_to_fallback(self):
        spec = random_spec(3, workloads_per_cq=8)
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        pending = []
        for cq_name, pq in mgr.cluster_queues.items():
            for wl in pq.snapshot_sorted():
                pending.append((wl, cq_name))
        snapshot = take_snapshot(cache)
        kwargs = dict(
            flavors=cache.flavors,
            timestamp_fn=lambda wl: queue_order_timestamp(wl, mgr._ts_policy),
        )
        cut = run_drain(snapshot, pending, max_cycles=1, **kwargs)
        assert cut.truncated
        assert cut.cycles == 1
        assert cut.fallback  # unprocessed entries are NOT silently parked
        snapshot2 = take_snapshot(cache)
        full = run_drain(snapshot2, pending, **kwargs)
        assert not full.truncated
        # decided prefixes agree; everything else was surfaced as fallback
        decided = {wl.name for wl, *_ in cut.admitted} | {
            wl.name for wl, _ in cut.parked
        }
        full_admitted = {wl.name for wl, *_ in full.admitted}
        for wl, *_ in cut.admitted:
            assert wl.name in full_admitted
        assert (
            decided | {wl.name for wl, _ in cut.fallback}
            == {wl.name for wl, _ in pending}
        )
