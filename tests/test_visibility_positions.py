"""Visibility pending-position math (pkg/visibility analog).

Direct coverage for pending_workloads_in_cq / _in_lq ordering: priority
descending, FIFO within ties, per-LocalQueue position recomputation,
StrictFIFO head-blocking vs BestEffortFIFO parking, and stable absolute
positions under offset/limit pagination.
"""

import pytest

from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.models import (
    ClusterQueue,
    LocalQueue,
    QueueingStrategy,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.workload import PodSet
from kueue_tpu.visibility import (
    pending_position,
    pending_workloads_in_cq,
    pending_workloads_in_lq,
)


def _runtime(cpu="2", strategy=QueueingStrategy.BEST_EFFORT_FIFO, lqs=("lq",)):
    rt = ClusterRuntime()
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq",
            namespace_selector={},
            queueing_strategy=strategy,
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": cpu}),)),
            ),
        )
    )
    for lq in lqs:
        rt.add_local_queue(LocalQueue(namespace="ns", name=lq, cluster_queue="cq"))
    return rt


def _wl(name, cpu="2", priority=0, created=0.0, lq="lq"):
    return Workload(
        namespace="ns", name=name, queue_name=lq, priority=priority,
        creation_time=created,
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
    )


class TestClusterQueuePositions:
    def test_priority_orders_positions(self):
        rt = _runtime(cpu="0")  # nothing fits: everything stays pending
        for i, prio in enumerate([1, 5, 3]):
            rt.add_workload(_wl(f"w{i}", priority=prio, created=float(i)))
        rt.run_until_idle()
        summary = pending_workloads_in_cq(rt.queues, "cq")
        names = [pw.name for pw in summary.items]
        assert names == ["w1", "w2", "w0"]  # priority desc
        assert [pw.position_in_cluster_queue for pw in summary.items] == [0, 1, 2]

    def test_priority_ties_fall_back_to_fifo(self):
        rt = _runtime(cpu="0")
        # same priority, deliberately added out of creation order
        rt.add_workload(_wl("late", priority=7, created=50.0))
        rt.add_workload(_wl("early", priority=7, created=10.0))
        rt.add_workload(_wl("mid", priority=7, created=30.0))
        rt.run_until_idle()
        names = [pw.name for pw in pending_workloads_in_cq(rt.queues, "cq").items]
        assert names == ["early", "mid", "late"]

    def test_offset_limit_keeps_absolute_positions(self):
        rt = _runtime(cpu="0")
        for i in range(5):
            rt.add_workload(_wl(f"w{i}", created=float(i)))
        rt.run_until_idle()
        page = pending_workloads_in_cq(rt.queues, "cq", offset=2, limit=2)
        assert [pw.name for pw in page.items] == ["w2", "w3"]
        # positions are absolute (computed before slicing), not page-relative
        assert [pw.position_in_cluster_queue for pw in page.items] == [2, 3]

    def test_unknown_cq_is_empty(self):
        rt = _runtime()
        assert pending_workloads_in_cq(rt.queues, "nope").items == []


class TestLocalQueuePositions:
    def test_per_lq_positions_recomputed_from_interleaved_cq_order(self):
        rt = _runtime(cpu="0", lqs=("lq-a", "lq-b"))
        # CQ order interleaves the two LQs: a0, b0, a1, b1 by priority
        rt.add_workload(_wl("a0", priority=9, created=0.0, lq="lq-a"))
        rt.add_workload(_wl("b0", priority=8, created=1.0, lq="lq-b"))
        rt.add_workload(_wl("a1", priority=7, created=2.0, lq="lq-a"))
        rt.add_workload(_wl("b1", priority=6, created=3.0, lq="lq-b"))
        rt.run_until_idle()
        cq_items = pending_workloads_in_cq(rt.queues, "cq").items
        assert [pw.name for pw in cq_items] == ["a0", "b0", "a1", "b1"]
        # each LQ numbers its own members 0..n over the CQ ordering
        assert [(pw.name, pw.position_in_local_queue) for pw in cq_items] == [
            ("a0", 0), ("b0", 0), ("a1", 1), ("b1", 1)
        ]
        lq_b = pending_workloads_in_lq(rt.queues, "ns", "lq-b")
        assert [pw.name for pw in lq_b.items] == ["b0", "b1"]
        # CQ positions survive the LQ filter (the reference keeps both)
        assert [pw.position_in_cluster_queue for pw in lq_b.items] == [1, 3]

    def test_lq_offset_limit(self):
        rt = _runtime(cpu="0")
        for i in range(4):
            rt.add_workload(_wl(f"w{i}", created=float(i)))
        rt.run_until_idle()
        page = pending_workloads_in_lq(rt.queues, "ns", "lq", offset=1, limit=2)
        assert [pw.name for pw in page.items] == ["w1", "w2"]

    def test_unknown_lq_is_empty(self):
        rt = _runtime()
        assert pending_workloads_in_lq(rt.queues, "ns", "nope").items == []


class TestQueueingStrategyVisibility:
    """StrictFIFO blocks behind an unadmittable head; BestEffortFIFO
    parks it and admits the rest — the pending listing must show both
    truthfully."""

    def _load(self, strategy):
        rt = _runtime(cpu="2", strategy=strategy)
        # head needs more than total quota -> can never admit
        rt.add_workload(_wl("blocker", cpu="3", priority=5, created=0.0))
        rt.add_workload(_wl("small", cpu="1", priority=0, created=1.0))
        rt.run_until_idle()
        return rt

    def test_strict_fifo_blocks_and_lists_both(self):
        rt = self._load(QueueingStrategy.STRICT_FIFO)
        assert not rt.workloads["ns/small"].is_admitted
        items = pending_workloads_in_cq(rt.queues, "cq", audit=rt.audit).items
        assert [pw.name for pw in items] == ["blocker", "small"]
        assert [pw.position_in_cluster_queue for pw in items] == [0, 1]

    def test_best_effort_fifo_parks_blocker_and_admits_small(self):
        rt = self._load(QueueingStrategy.BEST_EFFORT_FIFO)
        assert rt.workloads["ns/small"].is_admitted
        items = pending_workloads_in_cq(rt.queues, "cq", audit=rt.audit).items
        assert [pw.name for pw in items] == ["blocker"]
        # the parked head carries its structured reason
        assert items[0].inadmissible_reason == "RequestExceedsMaxCapacity"

    def test_pending_position_lookup(self):
        rt = self._load(QueueingStrategy.STRICT_FIFO)
        pw = pending_position(rt.queues, "cq", "ns/small", audit=rt.audit)
        assert pw is not None and pw.position_in_cluster_queue == 1
        assert pending_position(rt.queues, "cq", "ns/gone") is None


class TestReasonEnrichment:
    def test_items_carry_latest_structured_reason(self):
        rt = _runtime(cpu="2")
        rt.add_workload(_wl("fits", cpu="2", created=0.0))
        rt.add_workload(_wl("starved", cpu="2", created=1.0))
        rt.run_until_idle()
        items = pending_workloads_in_cq(rt.queues, "cq", audit=rt.audit).items
        assert [pw.name for pw in items] == ["starved"]
        assert items[0].inadmissible_reason == "InsufficientQuota"
        assert "insufficient unused quota" in items[0].message
        assert items[0].last_cycle >= 1

    def test_no_audit_keeps_reason_empty(self):
        rt = _runtime(cpu="0")
        rt.add_workload(_wl("w", created=0.0))
        rt.run_until_idle()
        items = pending_workloads_in_cq(rt.queues, "cq").items
        assert items and items[0].inadmissible_reason == ""


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))
