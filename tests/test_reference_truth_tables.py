"""Reference truth-table parity: flavor assignment.

The Go reference cannot be executed in this image (no Go toolchain), so
decision parity with `pkg/scheduler/flavorassigner` is asserted against
its table-driven unit suite instead: each case below re-states a named
scenario from `flavorassigner_test.go` (reference file:line cited per
case) in this repo's models and asserts the same representative mode,
per-resource flavor choice + mode, borrowing flag, and quota usage.

Scenario-encoding notes:
- the reference charges an implicit `pods` resource per podset
  (workload.Info); cases whose ClusterQueue covers `pods` encode it as
  an explicit per-pod request of 1, which exercises the same quota math;
- the reference's oracle-driven Preempt/Reclaim split is internal; the
  public mode (Fit/Preempt/NoFit) plus flavor choice is what the
  admission decision consumes and what these tables assert;
- node-affinity terms are expressed via node_selector (the repo's
  flavor selector input), matching the reference cases that use
  NodeSelector.
"""

import pytest

from kueue_tpu.core.cache import Cache
from kueue_tpu.core.flavor_assigner import FlavorAssigner, Mode
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.core.workload_info import make_admission
from kueue_tpu.models import (
    ClusterQueue,
    FlavorFungibility,
    FlavorQuotas,
    ResourceFlavor,
    ResourceGroup,
    Taint,
    Toleration,
    Workload,
)
from kueue_tpu.models.constants import FlavorFungibilityPolicy
from kueue_tpu.models.workload import PodSet
from kueue_tpu.resources import FlavorResource, parse_quantity, quantity_to_int

Mi = 2**20
Gi = 2**30

# the reference's shared flavor fixtures (flavorassigner_test.go:44-69)
FLAVORS = [
    ResourceFlavor(name="default"),
    ResourceFlavor(name="one", node_labels={"type": "one"}),
    ResourceFlavor(name="two", node_labels={"type": "two"}),
    ResourceFlavor(name="b_one", node_labels={"b_type": "one"}),
    ResourceFlavor(name="b_two", node_labels={"b_type": "two"}),
    ResourceFlavor(
        name="tainted",
        node_taints=(Taint(key="instance", value="spot", effect="NoSchedule"),),
    ),
    ResourceFlavor(
        name="taint_and_toleration",
        node_taints=(Taint(key="instance", value="spot", effect="NoSchedule"),),
        tolerations=(
            Toleration(
                key="instance", operator="Equal", value="spot",
                effect="NoSchedule",
            ),
        ),
    ),
]

SPOT_TOLERATION = Toleration(
    key="instance", operator="Equal", value="spot", effect="NoSchedule"
)


def rg(*flavor_quotas, resources=None):
    resources = resources or sorted(
        {r for fq in flavor_quotas for r in fq.resources}
    )
    return ResourceGroup(tuple(resources), tuple(flavor_quotas))


def setup(cq, secondary=None, usage=None, sec_usage=None):
    """usage / sec_usage: {(flavor, resource): quantity-str} charged via
    admitted single-podset workloads (the analog of the reference's
    clusterQueueUsage / secondaryClusterQueueUsage fields)."""
    cache = Cache()
    for f in FLAVORS:
        cache.add_or_update_flavor(f)
    cache.add_or_update_cluster_queue(cq)
    if secondary is not None:
        cache.add_or_update_cluster_queue(secondary)
    n = 0
    for cq_name, charge in ((cq.name, usage), (secondary.name if secondary else "", sec_usage)):
        for (flavor, resource), qty in (charge or {}).items():
            n += 1
            wl = Workload(
                namespace="ns", name=f"used-{n}", queue_name="lq",
                pod_sets=(PodSet.build("main", 1, {resource: qty}),),
            )
            wl.admission = make_admission(cq_name, {"main": {resource: flavor}}, wl)
            cache.add_or_update_workload(wl)
    return FlavorAssigner(take_snapshot(cache), cache.flavors)


def case_workload(pod_sets, reclaimable=None):
    wl = Workload(
        namespace="ns", name="wl", queue_name="lq", pod_sets=tuple(pod_sets)
    )
    if reclaimable:
        wl.reclaimable_pods = dict(reclaimable)
    return wl


def assert_case(
    res,
    rep_mode,
    flavors=None,  # {podset: {resource: (flavor_name, Mode)}}
    usage=None,  # {(flavor, resource): canonical int}
    borrowing=False,
    reasons=None,  # substrings expected among the podset reasons
):
    assert res.representative_mode() == rep_mode
    assert res.borrowing == borrowing
    for ps_name, per_res in (flavors or {}).items():
        (psr,) = [p for p in res.pod_sets if p.name == ps_name]
        for resource, (fname, mode) in per_res.items():
            choice = psr.flavors[resource]
            assert choice.name == fname, (ps_name, resource, choice)
            assert choice.mode.public() == mode, (ps_name, resource, choice)
    if usage is not None:
        got = {
            (fr.flavor, fr.resource): qty
            for fr, qty in res.usage.items()
            if qty
        }
        assert got == usage
    for sub in reasons or []:
        assert any(
            sub in r for ps in res.pod_sets for r in ps.reasons
        ), (sub, [ps.reasons for ps in res.pod_sets])


class TestAssignFlavorsParity:
    """flavorassigner_test.go TestAssignFlavors, case names preserved."""

    def test_single_flavor_fits(self):  # :83
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("default", {"cpu": "1", "memory": "2Mi"})),)))
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"cpu": "1", "memory": "1Mi"})]), "cq")
        assert_case(res, Mode.FIT,
                    flavors={"main": {"cpu": ("default", Mode.FIT),
                                      "memory": ("default", Mode.FIT)}},
                    usage={("default", "cpu"): 1000, ("default", "memory"): Mi})

    def test_single_flavor_fits_tainted_flavor(self):  # :119
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("tainted", {"cpu": "4"})),)))
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"cpu": "1"},
                         tolerations=(SPOT_TOLERATION,))]), "cq")
        assert_case(res, Mode.FIT,
                    flavors={"main": {"cpu": ("tainted", Mode.FIT)}},
                    usage={("tainted", "cpu"): 1000})

    def test_single_flavor_fits_tainted_flavor_with_toleration(self):  # :155
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("taint_and_toleration", {"cpu": "4"})),)))
        res = a.assign(case_workload([PodSet.build("main", 1, {"cpu": "1"})]), "cq")
        assert_case(res, Mode.FIT,
                    flavors={"main": {"cpu": ("taint_and_toleration", Mode.FIT)}},
                    usage={("taint_and_toleration", "cpu"): 1000})

    def test_single_flavor_used_resources_doesnt_fit(self):  # :183
        a = setup(
            ClusterQueue(name="cq", resource_groups=(
                rg(FlavorQuotas.build("default", {"cpu": "4"})),)),
            usage={("default", "cpu"): "3"})
        res = a.assign(case_workload([PodSet.build("main", 1, {"cpu": "2"})]), "cq")
        assert_case(res, Mode.PREEMPT,
                    flavors={"main": {"cpu": ("default", Mode.PREEMPT)}},
                    usage={("default", "cpu"): 2000},
                    reasons=["insufficient unused quota for cpu in flavor default, 1000 more needed"])

    def test_multiple_resource_groups_fits(self):  # :218
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("one", {"cpu": "2"}),
               FlavorQuotas.build("two", {"cpu": "4"})),
            rg(FlavorQuotas.build("b_one", {"memory": "1Gi"}),
               FlavorQuotas.build("b_two", {"memory": "5Gi"})),)))
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"cpu": "3", "memory": "10Mi"})]), "cq")
        assert_case(res, Mode.FIT,
                    flavors={"main": {"cpu": ("two", Mode.FIT),
                                      "memory": ("b_one", Mode.FIT)}},
                    usage={("two", "cpu"): 3000, ("b_one", "memory"): 10 * Mi})

    def test_multiple_resource_groups_one_preempt_other_nofit(self):  # :263
        a = setup(
            ClusterQueue(name="cq", resource_groups=(
                rg(FlavorQuotas.build("one", {"cpu": "3"})),
                rg(FlavorQuotas.build("b_one", {"memory": "1Mi"})),)),
            usage={("one", "cpu"): "1"})
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"cpu": "3", "memory": "10Mi"})]), "cq")
        assert_case(res, Mode.NO_FIT, usage={},
                    reasons=["insufficient quota for memory in flavor b_one, request > maximum capacity (10485760 > 1048576)"])

    def test_multiple_resource_groups_multiple_resources_fits(self):  # :302
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("one", {"cpu": "2", "memory": "1Gi"}),
               FlavorQuotas.build("two", {"cpu": "4", "memory": "15Mi"})),
            rg(FlavorQuotas.build("b_one", {"example.com/gpu": "4"}),
               FlavorQuotas.build("b_two", {"example.com/gpu": "2"})),)))
        res = a.assign(case_workload([
            PodSet.build("main", 1,
                         {"cpu": "3", "memory": "10Mi", "example.com/gpu": "3"})]),
            "cq")
        assert_case(res, Mode.FIT,
                    flavors={"main": {"cpu": ("two", Mode.FIT),
                                      "memory": ("two", Mode.FIT),
                                      "example.com/gpu": ("b_one", Mode.FIT)}},
                    usage={("two", "cpu"): 3000, ("two", "memory"): 10 * Mi,
                           ("b_one", "example.com/gpu"): 3})

    def test_multiple_resource_groups_fits_with_different_modes(self):  # :352
        a = setup(
            ClusterQueue(name="cq", cohort="test-cohort", resource_groups=(
                rg(FlavorQuotas.build("one", {"cpu": "2", "memory": "1Gi"}),
                   FlavorQuotas.build("two", {"cpu": "4", "memory": "15Mi"})),
                rg(FlavorQuotas.build("b_one", {"example.com/gpu": "4"})),)),
            secondary=ClusterQueue(
                name="cq2", cohort="test-cohort", resource_groups=(
                    rg(FlavorQuotas.build("b_one", {"example.com/gpu": "0"})),)),
            usage={("two", "memory"): "10Mi"},
            sec_usage={("b_one", "example.com/gpu"): "2"})
        res = a.assign(case_workload([
            PodSet.build("main", 1,
                         {"cpu": "3", "memory": "10Mi", "example.com/gpu": "3"})]),
            "cq")
        assert_case(res, Mode.PREEMPT, borrowing=True,
                    flavors={"main": {"cpu": ("two", Mode.FIT),
                                      "memory": ("two", Mode.PREEMPT),
                                      "example.com/gpu": ("b_one", Mode.PREEMPT)}},
                    usage={("two", "cpu"): 3000, ("two", "memory"): 10 * Mi,
                           ("b_one", "example.com/gpu"): 3},
                    reasons=["insufficient quota for cpu in flavor one",
                             "insufficient unused quota for memory in flavor two",
                             "insufficient unused quota for example.com/gpu in flavor b_one, 1 more needed"])

    def test_multiple_resources_in_group_doesnt_fit(self):  # :421
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("one", {"cpu": "2", "memory": "1Gi"}),
               FlavorQuotas.build("two", {"cpu": "4", "memory": "5Mi"})),)))
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"cpu": "3", "memory": "10Mi"})]), "cq")
        assert_case(res, Mode.NO_FIT, usage={},
                    reasons=["insufficient quota for cpu in flavor one",
                             "insufficient quota for memory in flavor two"])

    def test_multiple_flavors_fits_while_skipping_tainted(self):  # :457
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("tainted", {"cpu": "4"}),
               FlavorQuotas.build("two", {"cpu": "4"})),)))
        res = a.assign(case_workload([PodSet.build("main", 1, {"cpu": "3"})]), "cq")
        assert_case(res, Mode.FIT,
                    flavors={"main": {"cpu": ("two", Mode.FIT)}},
                    usage={("two", "cpu"): 3000})

    def test_multiple_flavors_fits_a_node_selector(self):  # :489
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("one", {"cpu": "4"}),
               FlavorQuotas.build("two", {"cpu": "4"})),)))
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"cpu": "1"},
                         node_selector={"type": "two"})]), "cq")
        assert_case(res, Mode.FIT,
                    flavors={"main": {"cpu": ("two", Mode.FIT)}},
                    usage={("two", "cpu"): 1000})

    def test_multiple_flavors_doesnt_fit_node_affinity(self):  # :655
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("one", {"cpu": "4"}),
               FlavorQuotas.build("two", {"cpu": "4"})),)))
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"cpu": "1"},
                         node_selector={"type": "three"})]), "cq")
        assert_case(res, Mode.NO_FIT, usage={},
                    reasons=["flavor one doesn't match node affinity",
                             "flavor two doesn't match node affinity"])

    def test_multiple_specs_fit_different_flavors(self):  # :703
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("one", {"cpu": "4"}),
               FlavorQuotas.build("two", {"cpu": "10"})),)))
        res = a.assign(case_workload([
            PodSet.build("driver", 1, {"cpu": "5"}),
            PodSet.build("worker", 1, {"cpu": "3"})]), "cq")
        assert_case(res, Mode.FIT,
                    flavors={"driver": {"cpu": ("two", Mode.FIT)},
                             "worker": {"cpu": ("one", Mode.FIT)}},
                    usage={("one", "cpu"): 3000, ("two", "cpu"): 5000})

    def test_multiple_specs_fits_borrowing(self):  # :752
        a = setup(
            ClusterQueue(name="cq", cohort="test-cohort", resource_groups=(
                rg(FlavorQuotas.build("default", {
                    "cpu": ("2", "98", None), "memory": "2Gi"})),)),
            secondary=ClusterQueue(
                name="cq2", cohort="test-cohort", resource_groups=(
                    rg(FlavorQuotas.build("default", {
                        "cpu": "198", "memory": "198Gi"})),)))
        res = a.assign(case_workload([
            PodSet.build("driver", 1, {"cpu": "4", "memory": "1Gi"}),
            PodSet.build("worker", 1, {"cpu": "6", "memory": "4Gi"})]), "cq")
        assert_case(res, Mode.FIT, borrowing=True,
                    flavors={"driver": {"cpu": ("default", Mode.FIT),
                                        "memory": ("default", Mode.FIT)},
                             "worker": {"cpu": ("default", Mode.FIT),
                                        "memory": ("default", Mode.FIT)}},
                    usage={("default", "cpu"): 10_000,
                           ("default", "memory"): 5 * Gi})

    def test_not_enough_space_to_borrow(self):  # :815
        a = setup(
            ClusterQueue(name="cq", cohort="test-cohort", resource_groups=(
                rg(FlavorQuotas.build("one", {"cpu": "1"})),)),
            secondary=ClusterQueue(
                name="cq2", cohort="test-cohort", resource_groups=(
                    rg(FlavorQuotas.build("one", {"cpu": ("10", None, "0")})),)),
            sec_usage={("one", "cpu"): "9"})
        res = a.assign(case_workload([PodSet.build("main", 1, {"cpu": "2"})]), "cq")
        assert_case(res, Mode.NO_FIT, usage={},
                    reasons=["insufficient quota for cpu in flavor one, request > maximum capacity"])

    def test_past_max_but_can_preempt_in_cq(self):  # :852
        a = setup(
            ClusterQueue(name="cq", cohort="test-cohort", resource_groups=(
                rg(FlavorQuotas.build("one", {"cpu": ("2", "8", None)})),)),
            secondary=ClusterQueue(
                name="cq2", cohort="test-cohort", resource_groups=(
                    rg(FlavorQuotas.build("one", {"cpu": "98"})),)),
            usage={("one", "cpu"): "9"},
            sec_usage={("one", "cpu"): "9"})
        res = a.assign(case_workload([PodSet.build("main", 1, {"cpu": "2"})]), "cq")
        assert_case(res, Mode.PREEMPT, borrowing=True,
                    flavors={"main": {"cpu": ("one", Mode.PREEMPT)}},
                    usage={("one", "cpu"): 2000},
                    reasons=["insufficient unused quota for cpu in flavor one, 1000 more needed"])

    def test_past_min_but_can_preempt_in_cq(self):  # :901
        a = setup(
            ClusterQueue(name="cq", resource_groups=(
                rg(FlavorQuotas.build("one", {"cpu": "2"})),)),
            usage={("one", "cpu"): "1"})
        res = a.assign(case_workload([PodSet.build("main", 1, {"cpu": "2"})]), "cq")
        assert_case(res, Mode.PREEMPT,
                    flavors={"main": {"cpu": ("one", Mode.PREEMPT)}},
                    usage={("one", "cpu"): 2000},
                    reasons=["insufficient unused quota for cpu in flavor one, 1000 more needed"])

    def test_past_min_but_can_preempt_in_cohort_and_cq(self):  # :936
        a = setup(
            ClusterQueue(name="cq", cohort="test-cohort", resource_groups=(
                rg(FlavorQuotas.build("one", {"cpu": "3"})),)),
            secondary=ClusterQueue(
                name="cq2", cohort="test-cohort", resource_groups=(
                    rg(FlavorQuotas.build("one", {"cpu": "7"})),)),
            usage={("one", "cpu"): "2"},
            sec_usage={("one", "cpu"): "8"})
        res = a.assign(case_workload([PodSet.build("main", 1, {"cpu": "2"})]), "cq")
        assert_case(res, Mode.PREEMPT, borrowing=True,
                    flavors={"main": {"cpu": ("one", Mode.PREEMPT)}},
                    usage={("one", "cpu"): 2000},
                    reasons=["insufficient unused quota for cpu in flavor one, 2000 more needed"])

    def test_can_only_preempt_flavors_that_match_affinity(self):  # :983
        a = setup(
            ClusterQueue(name="cq", resource_groups=(
                rg(FlavorQuotas.build("one", {"cpu": "4"}),
                   FlavorQuotas.build("two", {"cpu": "4"})),)),
            usage={("one", "cpu"): "3", ("two", "cpu"): "3"})
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"cpu": "2"},
                         node_selector={"type": "two"})]), "cq")
        assert_case(res, Mode.PREEMPT,
                    flavors={"main": {"cpu": ("two", Mode.PREEMPT)}},
                    usage={("two", "cpu"): 2000},
                    reasons=["flavor one doesn't match node affinity",
                             "insufficient unused quota for cpu in flavor two, 1000 more needed"])

    def test_num_pods_fit(self):  # :1123
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("default", {"pods": "3", "cpu": "10"})),)))
        res = a.assign(case_workload([
            PodSet.build("main", 3, {"cpu": "1", "pods": "1"})]), "cq")
        assert_case(res, Mode.FIT,
                    flavors={"main": {"cpu": ("default", Mode.FIT),
                                      "pods": ("default", Mode.FIT)}},
                    usage={("default", "cpu"): 3000, ("default", "pods"): 3})

    def test_num_pods_dont_fit(self):  # :1158
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("default", {"pods": "2", "cpu": "10"})),)))
        res = a.assign(case_workload([
            PodSet.build("main", 3, {"cpu": "1", "pods": "1"})]), "cq")
        assert_case(res, Mode.NO_FIT, usage={},
                    reasons=["insufficient quota for pods in flavor default, request > maximum capacity (3 > 2)"])

    def test_with_reclaimable_pods(self):  # :1187
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("default", {"pods": "3", "cpu": "10"})),)))
        res = a.assign(case_workload(
            [PodSet.build("main", 5, {"cpu": "1", "pods": "1"})],
            reclaimable={"main": 2}), "cq")
        assert_case(res, Mode.FIT,
                    flavors={"main": {"cpu": ("default", Mode.FIT),
                                      "pods": ("default", Mode.FIT)}},
                    usage={("default", "cpu"): 3000, ("default", "pods"): 3})

    def test_preempt_before_try_next_flavor(self):  # :1227
        a = setup(
            ClusterQueue(
                name="cq",
                flavor_fungibility=FlavorFungibility(
                    when_can_borrow=FlavorFungibilityPolicy.BORROW,
                    when_can_preempt=FlavorFungibilityPolicy.PREEMPT),
                resource_groups=(
                    rg(FlavorQuotas.build("one", {"pods": "10", "cpu": "10"}),
                       FlavorQuotas.build("two", {"pods": "10", "cpu": "10"})),)),
            usage={("one", "cpu"): "2"})
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"cpu": "9", "pods": "1"})]), "cq")
        assert_case(res, Mode.PREEMPT,
                    flavors={"main": {"cpu": ("one", Mode.PREEMPT),
                                      "pods": ("one", Mode.FIT)}},
                    usage={("one", "cpu"): 9000, ("one", "pods"): 1},
                    reasons=["insufficient unused quota for cpu in flavor one, 1000 more needed"])

    def test_preempt_try_next_flavor(self):  # :1271 (default fungibility)
        a = setup(
            ClusterQueue(name="cq", resource_groups=(
                rg(FlavorQuotas.build("one", {"pods": "10", "cpu": "10"}),
                   FlavorQuotas.build("two", {"pods": "10", "cpu": "10"})),)),
            usage={("one", "cpu"): "2"})
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"cpu": "9", "pods": "1"})]), "cq")
        assert_case(res, Mode.FIT,
                    flavors={"main": {"cpu": ("two", Mode.FIT),
                                      "pods": ("two", Mode.FIT)}},
                    usage={("two", "cpu"): 9000, ("two", "pods"): 1})

    def test_borrow_try_next_flavor_found_the_first_flavor(self):  # :1311
        a = setup(
            ClusterQueue(
                name="cq", cohort="test-cohort",
                flavor_fungibility=FlavorFungibility(
                    when_can_borrow=FlavorFungibilityPolicy.TRY_NEXT_FLAVOR,
                    when_can_preempt=FlavorFungibilityPolicy.TRY_NEXT_FLAVOR),
                resource_groups=(
                    rg(FlavorQuotas.build("one", {"pods": "10",
                                                  "cpu": ("10", "1", None)}),
                       FlavorQuotas.build("two", {"pods": "10", "cpu": "1"})),)),
            secondary=ClusterQueue(
                name="cq2", cohort="test-cohort", resource_groups=(
                    rg(FlavorQuotas.build("one", {"cpu": "1"})),)),
            usage={("one", "cpu"): "2"})
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"cpu": "9", "pods": "1"})]), "cq")
        assert_case(res, Mode.FIT, borrowing=True,
                    flavors={"main": {"cpu": ("one", Mode.FIT),
                                      "pods": ("one", Mode.FIT)}},
                    usage={("one", "cpu"): 9000, ("one", "pods"): 1})

    def test_borrow_try_next_flavor_found_the_second_flavor(self):  # :1362
        a = setup(
            ClusterQueue(
                name="cq", cohort="test-cohort",
                flavor_fungibility=FlavorFungibility(
                    when_can_borrow=FlavorFungibilityPolicy.TRY_NEXT_FLAVOR,
                    when_can_preempt=FlavorFungibilityPolicy.TRY_NEXT_FLAVOR),
                resource_groups=(
                    rg(FlavorQuotas.build("one", {"pods": "10",
                                                  "cpu": ("10", "1", None)}),
                       FlavorQuotas.build("two", {"pods": "10", "cpu": "10"})),)),
            secondary=ClusterQueue(
                name="cq2", cohort="test-cohort", resource_groups=(
                    rg(FlavorQuotas.build("one", {"cpu": "1"})),)),
            usage={("one", "cpu"): "2"})
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"cpu": "9", "pods": "1"})]), "cq")
        assert_case(res, Mode.FIT, borrowing=False,
                    flavors={"main": {"cpu": ("two", Mode.FIT),
                                      "pods": ("two", Mode.FIT)}},
                    usage={("two", "cpu"): 9000, ("two", "pods"): 1})

    def test_borrow_before_try_next_flavor(self):  # :1413 (default WhenCanBorrow=Borrow)
        a = setup(
            ClusterQueue(
                name="cq", cohort="test-cohort", resource_groups=(
                    rg(FlavorQuotas.build("one", {"pods": "10",
                                                  "cpu": ("10", "1", None)}),
                       FlavorQuotas.build("two", {"pods": "10", "cpu": "10"})),)),
            secondary=ClusterQueue(
                name="cq2", cohort="test-cohort", resource_groups=(
                    rg(FlavorQuotas.build("one", {"cpu": "1"})),)),
            usage={("one", "cpu"): "2"})
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"cpu": "9", "pods": "1"})]), "cq")
        assert_case(res, Mode.FIT, borrowing=True,
                    flavors={"main": {"cpu": ("one", Mode.FIT),
                                      "pods": ("one", Mode.FIT)}},
                    usage={("one", "cpu"): 9000, ("one", "pods"): 1})

    def test_resource_not_listed_in_cluster_queue(self):  # :1097
        a = setup(ClusterQueue(name="cq", resource_groups=(
            rg(FlavorQuotas.build("default", {"cpu": "4"})),)))
        res = a.assign(case_workload([
            PodSet.build("main", 1, {"example.com/gpu": "1"})]), "cq")
        assert res.representative_mode() == Mode.NO_FIT


# ---------------------------------------------------------------------------
# Preemption truth tables (preemption_test.go TestPreemption).
# Each case re-states a named reference scenario: same CQ fixtures
# (preemption_test.go:72-249), same admitted set, same forced Preempt
# assignment, asserting the same victim set and preemption reasons.
# ---------------------------------------------------------------------------

from kueue_tpu.core.flavor_assigner import (
    AssignmentResult,
    FlavorChoice,
    GranularMode,
    PodSetResult,
)
from kueue_tpu.core.preemption import (
    IN_CLUSTER_QUEUE,
    IN_COHORT_RECLAIM_WHILE_BORROWING,
    IN_COHORT_RECLAMATION,
    Preemptor,
)
from kueue_tpu.models import Preemption
from kueue_tpu.models.cluster_queue import BorrowWithinCohort
from kueue_tpu.models.constants import (
    BorrowWithinCohortPolicy,
    PreemptionPolicy,
    ReclaimWithinCohortPolicy,
    WorkloadConditionType,
)
from kueue_tpu.utils.clock import FakeClock

NOW = 1000.0


def _cq(name, quotas, cohort=None, preemption=None):
    """quotas: {resource: (nominal[, borrowing[, lending]]) | str} on
    flavor 'default' (the preemption fixtures are single-flavor)."""
    return ClusterQueue(
        name=name, cohort=cohort, namespace_selector={},
        resource_groups=(
            rg(FlavorQuotas.build("default", quotas)),
        ),
        preemption=preemption or Preemption(),
    )


# preemption_test.go:73-249 fixture CQs (subset exercised below)
def fixture_cqs():
    lower = PreemptionPolicy.LOWER_PRIORITY
    return [
        _cq("standalone", {"cpu": "6"},
            preemption=Preemption(within_cluster_queue=lower)),
        _cq("c1", {"cpu": ("6", "6", None), "memory": ("3Gi", "3Gi", None)},
            cohort="cohort",
            preemption=Preemption(
                within_cluster_queue=lower,
                reclaim_within_cohort=ReclaimWithinCohortPolicy.LOWER_PRIORITY)),
        _cq("c2", {"cpu": ("6", "6", None), "memory": ("3Gi", "3Gi", None)},
            cohort="cohort",
            preemption=Preemption(
                within_cluster_queue=PreemptionPolicy.NEVER,
                reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY)),
        _cq("preventStarvation", {"cpu": "6"},
            preemption=Preemption(
                within_cluster_queue=PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY)),
        _cq("a_standard", {"cpu": ("1", "12", None)}, cohort="with_shared_cq",
            preemption=Preemption(
                within_cluster_queue=PreemptionPolicy.NEVER,
                reclaim_within_cohort=ReclaimWithinCohortPolicy.LOWER_PRIORITY,
                borrow_within_cohort=BorrowWithinCohort(
                    policy=BorrowWithinCohortPolicy.LOWER_PRIORITY,
                    max_priority_threshold=0))),
        _cq("b_standard", {"cpu": ("1", "12", None)}, cohort="with_shared_cq",
            preemption=Preemption(
                within_cluster_queue=lower,
                reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
                borrow_within_cohort=BorrowWithinCohort(
                    policy=BorrowWithinCohortPolicy.LOWER_PRIORITY,
                    max_priority_threshold=0))),
        _cq("a_best_effort", {"cpu": ("1", "12", None)}, cohort="with_shared_cq",
            preemption=Preemption(
                within_cluster_queue=PreemptionPolicy.NEVER,
                reclaim_within_cohort=ReclaimWithinCohortPolicy.LOWER_PRIORITY,
                borrow_within_cohort=BorrowWithinCohort(
                    policy=BorrowWithinCohortPolicy.LOWER_PRIORITY,
                    max_priority_threshold=0))),
        _cq("b_best_effort", {"cpu": ("0", "13", None)}, cohort="with_shared_cq",
            preemption=Preemption(
                within_cluster_queue=PreemptionPolicy.NEVER,
                reclaim_within_cohort=ReclaimWithinCohortPolicy.LOWER_PRIORITY,
                borrow_within_cohort=BorrowWithinCohort(
                    policy=BorrowWithinCohortPolicy.LOWER_PRIORITY,
                    max_priority_threshold=0))),
        _cq("shared", {"cpu": "10"}, cohort="with_shared_cq"),
        _cq("lend1", {"cpu": ("6", None, "4")}, cohort="cohort-lend",
            preemption=Preemption(
                within_cluster_queue=lower,
                reclaim_within_cohort=ReclaimWithinCohortPolicy.LOWER_PRIORITY)),
        _cq("lend2", {"cpu": ("6", None, "2")}, cohort="cohort-lend",
            preemption=Preemption(
                within_cluster_queue=lower,
                reclaim_within_cohort=ReclaimWithinCohortPolicy.LOWER_PRIORITY)),
    ]


def preempt_env(admitted):
    """admitted: [(name, cq, {res: qty}, {res: flavor}, prio, reserved_at)]"""
    cache = Cache()
    for f in FLAVORS:
        cache.add_or_update_flavor(f)
    for cq in fixture_cqs():
        cache.add_or_update_cluster_queue(cq)
    for name, cq, reqs, flavs, prio, at in admitted:
        wl = Workload(
            namespace="ns", name=name, queue_name=f"lq-{cq}", priority=prio,
            creation_time=NOW,
            pod_sets=(PodSet.build("main", 1, reqs),),
        )
        wl.admission = make_admission(cq, {"main": flavs}, wl)
        wl.set_condition(
            WorkloadConditionType.QUOTA_RESERVED, True,
            reason="QuotaReserved", now=at,
        )
        cache.add_or_update_workload(wl)
    return cache


def forced_preempt_assignment(wl, flavors, fit=()):
    """The reference's singlePodSetAssignment with Mode=Preempt; ``fit``
    lists resources forced to Fit instead (preemption_test.go:596)."""
    pod_sets, usage = [], {}
    for ps in wl.pod_sets:
        choices = {}
        for res, fname in flavors.items():
            mode = GranularMode.FIT if res in fit else GranularMode.PREEMPT
            choices[res] = FlavorChoice(fname, mode)
            key = FlavorResource(fname, res)
            usage[key] = usage.get(key, 0) + ps.requests.get(res, 0) * ps.count
        pod_sets.append(PodSetResult(name=ps.name, count=ps.count, flavors=choices))
    return AssignmentResult(pod_sets=pod_sets, usage=usage)


def run_preemption(admitted, incoming_reqs, target_cq, prio=0, creation=NOW,
                   flavors=None, fit=()):
    cache = preempt_env(admitted)
    wl = Workload(
        namespace="ns", name="in", queue_name=f"lq-{target_cq}",
        priority=prio, creation_time=creation,
        pod_sets=(PodSet.build("main", 1, incoming_reqs),),
    )
    snap = take_snapshot(cache)
    assignment = forced_preempt_assignment(
        wl, flavors or {r: "default" for r in incoming_reqs}, fit=fit
    )
    p = Preemptor(FakeClock(start=NOW + 100))
    targets = p.get_targets(wl, target_cq, assignment, snap)
    return {(t.workload.workload.name, t.reason) for t in targets}


class TestPreemptionParity:
    """preemption_test.go TestPreemption, case names preserved."""

    def test_preempt_lowest_priority(self):  # :289
        got = run_preemption(
            [("low", "standalone", {"cpu": "2"}, {"cpu": "default"}, -1, NOW),
             ("mid", "standalone", {"cpu": "2"}, {"cpu": "default"}, 0, NOW),
             ("high", "standalone", {"cpu": "2"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "2"}, "standalone", prio=1)
        assert got == {("low", IN_CLUSTER_QUEUE)}

    def test_preempt_multiple(self):  # :329
        got = run_preemption(
            [("low", "standalone", {"cpu": "2"}, {"cpu": "default"}, -1, NOW),
             ("mid", "standalone", {"cpu": "2"}, {"cpu": "default"}, 0, NOW),
             ("high", "standalone", {"cpu": "2"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "3"}, "standalone", prio=1)
        assert got == {("low", IN_CLUSTER_QUEUE), ("mid", IN_CLUSTER_QUEUE)}

    def test_no_preemption_for_low_priority(self):  # :370
        got = run_preemption(
            [("low", "standalone", {"cpu": "3"}, {"cpu": "default"}, -1, NOW),
             ("mid", "standalone", {"cpu": "3"}, {"cpu": "default"}, 0, NOW)],
            {"cpu": "1"}, "standalone", prio=-1)
        assert got == set()

    def test_not_enough_low_priority_workloads(self):  # :401
        got = run_preemption(
            [("low", "standalone", {"cpu": "3"}, {"cpu": "default"}, -1, NOW),
             ("mid", "standalone", {"cpu": "3"}, {"cpu": "default"}, 0, NOW)],
            {"cpu": "4"}, "standalone", prio=0)
        assert got == set()

    def test_some_free_quota_preempt_low_priority(self):  # :431
        got = run_preemption(
            [("low", "standalone", {"cpu": "1"}, {"cpu": "default"}, -1, NOW),
             ("mid", "standalone", {"cpu": "1"}, {"cpu": "default"}, 0, NOW),
             ("high", "standalone", {"cpu": "3"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "2"}, "standalone", prio=1)
        assert got == {("low", IN_CLUSTER_QUEUE)}

    def test_minimal_set_excludes_low_priority(self):  # :471
        got = run_preemption(
            [("low", "standalone", {"cpu": "1"}, {"cpu": "default"}, -1, NOW),
             ("mid", "standalone", {"cpu": "2"}, {"cpu": "default"}, 0, NOW),
             ("high", "standalone", {"cpu": "3"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "2"}, "standalone", prio=1)
        assert got == {("mid", IN_CLUSTER_QUEUE)}

    def test_only_preempt_workloads_using_the_chosen_flavor(self):  # :511
        got = run_preemption(
            [("low", "standalone", {"memory": "2Gi"}, {"memory": "alpha"}, -1, NOW),
             ("mid", "standalone", {"memory": "1Gi"}, {"memory": "beta"}, 0, NOW),
             ("high", "standalone", {"memory": "1Gi"}, {"memory": "beta"}, 1, NOW)],
            {"memory": "1Gi"}, "standalone", prio=1,
            flavors={"memory": "beta"})
        assert got == {("mid", IN_CLUSTER_QUEUE)}

    def test_reclaim_quota_from_borrower(self):  # :556
        got = run_preemption(
            [("c1-low", "c1", {"cpu": "3"}, {"cpu": "default"}, -1, NOW),
             ("c2-mid", "c2", {"cpu": "3"}, {"cpu": "default"}, 0, NOW),
             ("c2-high", "c2", {"cpu": "6"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "3"}, "c1", prio=1)
        assert got == {("c2-mid", IN_COHORT_RECLAMATION)}

    def test_reclaim_quota_with_zero_request_at_nominal(self):  # :596
        got = run_preemption(
            [("c1-low", "c1", {"cpu": "3", "memory": "3Gi"},
              {"cpu": "default", "memory": "default"}, -1, NOW),
             ("c2-mid", "c2", {"cpu": "3"}, {"cpu": "default"}, 0, NOW),
             ("c2-high", "c2", {"cpu": "6"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "3", "memory": "0"}, "c1", prio=1,
            flavors={"cpu": "default", "memory": "default"},
            fit=("memory",))
        assert got == {("c2-mid", IN_COHORT_RECLAMATION)}

    def test_no_workloads_borrowing(self):  # :633
        got = run_preemption(
            [("c1-high", "c1", {"cpu": "4"}, {"cpu": "default"}, 1, NOW),
             ("c2-low-1", "c2", {"cpu": "4"}, {"cpu": "default"}, -1, NOW)],
            {"cpu": "4"}, "c1", prio=1)
        assert got == set()

    def test_not_enough_workloads_borrowing(self):  # :665
        got = run_preemption(
            [("c1-high", "c1", {"cpu": "4"}, {"cpu": "default"}, 1, NOW),
             ("c2-low-1", "c2", {"cpu": "4"}, {"cpu": "default"}, -1, NOW),
             ("c2-low-2", "c2", {"cpu": "4"}, {"cpu": "default"}, -1, NOW)],
            {"cpu": "4"}, "c1", prio=1)
        assert got == set()

    def test_no_reclaim_same_priority_for_lower_priority_policy(self):  # :920
        got = run_preemption(
            [("c1", "c1", {"cpu": "2"}, {"cpu": "default"}, 0, NOW),
             ("c2-1", "c2", {"cpu": "4"}, {"cpu": "default"}, 0, NOW),
             ("c2-2", "c2", {"cpu": "4"}, {"cpu": "default"}, 0, NOW)],
            {"cpu": "4"}, "c1", prio=0)
        assert got == set()

    def test_reclaim_same_priority_for_any_policy(self):  # :956
        got = run_preemption(
            [("c1-1", "c1", {"cpu": "4"}, {"cpu": "default"}, 0, NOW),
             ("c1-2", "c1", {"cpu": "4"}, {"cpu": "default"}, 1, NOW),
             ("c2", "c2", {"cpu": "2"}, {"cpu": "default"}, 0, NOW)],
            {"cpu": "4"}, "c2", prio=0)
        assert got == {("c1-1", IN_COHORT_RECLAMATION)}

    def test_preempt_from_all_cluster_queues_in_cohort(self):  # :994
        got = run_preemption(
            [("c1-low", "c1", {"cpu": "3"}, {"cpu": "default"}, -1, NOW),
             ("c1-mid", "c1", {"cpu": "2"}, {"cpu": "default"}, 0, NOW),
             ("c2-low", "c2", {"cpu": "3"}, {"cpu": "default"}, -1, NOW),
             ("c2-mid", "c2", {"cpu": "4"}, {"cpu": "default"}, 0, NOW)],
            {"cpu": "4"}, "c1", prio=0)
        assert got == {("c1-low", IN_CLUSTER_QUEUE),
                       ("c2-low", IN_COHORT_RECLAMATION)}

    def test_cannot_preempt_within_cq_never(self):  # :1040
        got = run_preemption(
            [("c2-low", "c2", {"cpu": "3"}, {"cpu": "default"}, -1, NOW)],
            {"cpu": "4"}, "c2", prio=1)
        assert got == set()

    def test_preempt_newer_workloads_with_same_priority(self):  # :1119
        got = run_preemption(
            [("wl1", "preventStarvation", {"cpu": "2"}, {"cpu": "default"}, 2, NOW),
             ("wl2", "preventStarvation", {"cpu": "2"}, {"cpu": "default"}, 1, NOW + 1),
             ("wl3", "preventStarvation", {"cpu": "2"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "2"}, "preventStarvation", prio=1, creation=NOW - 15)
        assert got == {("wl2", IN_CLUSTER_QUEUE)}

    def test_borrow_within_cohort_preempt_other_cq_while_borrowing(self):  # :1173
        got = run_preemption(
            [("a_best_effort_low", "a_best_effort", {"cpu": "10"},
              {"cpu": "default"}, -1, NOW),
             ("b_best_effort_low", "b_best_effort", {"cpu": "1"},
              {"cpu": "default"}, -1, NOW)],
            {"cpu": "10"}, "a_standard", prio=0)
        assert got == {("a_best_effort_low", IN_COHORT_RECLAIM_WHILE_BORROWING)}

    def test_borrow_within_cohort_threshold_blocks_when_still_borrowing(self):  # :1205
        got = run_preemption(
            [("b_standard", "b_standard", {"cpu": "10"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "10"}, "a_standard", prio=2)
        assert got == set()

    def test_borrow_within_cohort_threshold_allows_when_not_borrowing_after(self):  # :1229
        got = run_preemption(
            [("b_standard", "b_standard", {"cpu": "13"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "1"}, "a_standard", prio=2)
        assert got == {("b_standard", IN_COHORT_RECLAMATION)}

    def test_borrow_within_cohort_not_same_cq(self):  # :1256
        got = run_preemption(
            [("a_standard", "a_standard", {"cpu": "13"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "1"}, "a_standard", prio=2)
        assert got == set()

    def test_borrow_within_cohort_cq_first_when_above_nominal(self):  # :1280
        got = run_preemption(
            [("a_standard_1", "a_standard", {"cpu": "10"}, {"cpu": "default"}, 1, NOW),
             ("a_standard_2", "a_standard", {"cpu": "1"}, {"cpu": "default"}, 1, NOW),
             ("b_standard_1", "b_standard", {"cpu": "1"}, {"cpu": "default"}, 1, NOW),
             ("b_standard_2", "b_standard", {"cpu": "1"}, {"cpu": "default"}, 2, NOW)],
            {"cpu": "1"}, "b_standard", prio=3)
        assert got == {("b_standard_1", IN_CLUSTER_QUEUE)}

    def test_reclaim_quota_from_lender(self):  # :1378
        got = run_preemption(
            [("lend1-low", "lend1", {"cpu": "3"}, {"cpu": "default"}, -1, NOW),
             ("lend2-mid", "lend2", {"cpu": "3"}, {"cpu": "default"}, 0, NOW),
             ("lend2-high", "lend2", {"cpu": "4"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "3"}, "lend1", prio=1)
        assert got == {("lend2-mid", IN_COHORT_RECLAMATION)}

    def test_preempt_from_all_cluster_queues_in_cohort_lend(self):  # :1418
        got = run_preemption(
            [("lend1-low", "lend1", {"cpu": "3"}, {"cpu": "default"}, -1, NOW),
             ("lend1-mid", "lend1", {"cpu": "2"}, {"cpu": "default"}, 0, NOW),
             ("lend2-low", "lend2", {"cpu": "3"}, {"cpu": "default"}, -1, NOW),
             ("lend2-mid", "lend2", {"cpu": "4"}, {"cpu": "default"}, 0, NOW)],
            {"cpu": "4"}, "lend1", prio=0)
        assert got == {("lend1-low", IN_CLUSTER_QUEUE),
                       ("lend2-low", IN_COHORT_RECLAMATION)}

    def test_cannot_preempt_beyond_lending_limit(self):  # :1464
        got = run_preemption(
            [("lend2-low", "lend2", {"cpu": "10"}, {"cpu": "default"}, -1, NOW)],
            {"cpu": "9"}, "lend1", prio=0)
        assert got == set()


# ---------------------------------------------------------------------------
# Fair-sharing preemption truth tables (preemption_test.go
# TestFairPreemptions). Same baseCQs fixture (:1884-1929): a/b/c nominal
# 3 in cohort "all" with reclaimWithinCohort=Any and borrowWithinCohort
# (LowerPriority, threshold -3); "preemptible" nominal 0.
# ---------------------------------------------------------------------------

from kueue_tpu.core.preemption import IN_COHORT_FAIR_SHARING
from kueue_tpu.models import Cohort
from kueue_tpu.models.cluster_queue import FairSharing


def fair_cq(name, cpu, cohort="all", weight=1000, preemption=None):
    return ClusterQueue(
        name=name, cohort=cohort, namespace_selector={},
        resource_groups=(rg(FlavorQuotas.build("default", {"cpu": cpu})),),
        fair_sharing=FairSharing(weight_milli=weight),
        preemption=preemption or Preemption(
            within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
            reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
            borrow_within_cohort=BorrowWithinCohort(
                policy=BorrowWithinCohortPolicy.LOWER_PRIORITY,
                max_priority_threshold=-3)),
    )


def fair_base_cqs():
    return [fair_cq("a", "3"), fair_cq("b", "3"), fair_cq("c", "3"),
            fair_cq("preemptible", "0", preemption=Preemption())]


def run_fair(admitted, incoming_cpu, target_cq, prio=0, cqs=None, cohorts=None):
    """admitted: [(name, cq, cpu, prio)] all reserved at NOW."""
    cache = Cache()
    cache.add_or_update_flavor(ResourceFlavor(name="default"))
    for c in cohorts or []:
        cache.add_or_update_cohort(c)
    for cq in cqs if cqs is not None else fair_base_cqs():
        cache.add_or_update_cluster_queue(cq)
    for name, cq, cpu, p in admitted:
        wl = Workload(
            namespace="ns", name=name, queue_name=f"lq-{cq}", priority=p,
            creation_time=NOW,
            pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
        )
        wl.admission = make_admission(cq, {"main": {"cpu": "default"}}, wl)
        wl.set_condition(
            WorkloadConditionType.QUOTA_RESERVED, True,
            reason="QuotaReserved", now=NOW,
        )
        cache.add_or_update_workload(wl)
    incoming = Workload(
        namespace="ns", name="in", queue_name=f"lq-{target_cq}",
        priority=prio, creation_time=NOW,
        pod_sets=(PodSet.build("main", 1, {"cpu": incoming_cpu}),),
    )
    snap = take_snapshot(cache)
    assignment = forced_preempt_assignment(incoming, {"cpu": "default"})
    p = Preemptor(FakeClock(start=NOW + 100), enable_fair_sharing=True)
    targets = p.get_targets(incoming, target_cq, assignment, snap)
    return {(t.workload.workload.name, t.reason) for t in targets}


def units(prefix_counts, prio=0):
    """[('a', 3), ('b', 5)] -> unit-cpu workloads a1..a3, b1..b5."""
    out = []
    for cq, n in prefix_counts:
        out.extend((f"{cq}{i + 1}", cq, "1", prio) for i in range(n))
    return out


class TestFairPreemptionsParity:
    """preemption_test.go TestFairPreemptions, case names preserved."""

    def test_reclaim_nominal_from_user_using_the_most(self):  # :1940
        got = run_fair(units([("a", 3), ("b", 5), ("c", 1)]), "1", "c")
        assert got == {("b1", IN_COHORT_FAIR_SHARING)}

    def test_reclaim_from_queue_using_less_if_latest_not_enough(self):  # :1957
        got = run_fair(
            [("a1", "a", "3", 0), ("a2", "a", "1", 0),
             ("b1", "b", "2", 0), ("b2", "b", "3", 0)],
            "3", "c")
        assert got == {("a1", IN_COHORT_FAIR_SHARING)}

    def test_reclaim_borrowable_quota_from_user_using_the_most(self):  # :1969
        got = run_fair(units([("a", 3), ("b", 5), ("c", 1)]), "1", "a")
        assert got == {("b1", IN_COHORT_FAIR_SHARING)}

    def test_preempt_one_from_each_cq_borrowing(self):  # :1986
        got = run_fair(
            [("a1", "a", "0.5", 0), ("a2", "a", "0.5", 0), ("a3", "a", "3", 0),
             ("b1", "b", "0.5", 0), ("b2", "b", "0.5", 0), ("b3", "b", "3", 0)],
            "2", "c")
        assert got == {("a1", IN_COHORT_FAIR_SHARING),
                       ("b1", IN_COHORT_FAIR_SHARING)}

    def test_cant_preempt_when_everyone_under_nominal(self):  # :2003
        got = run_fair(units([("a", 3), ("b", 3), ("c", 3)]), "1", "c")
        assert got == set()

    def test_cant_preempt_when_it_would_switch_the_imbalance(self):  # :2019
        got = run_fair(units([("a", 3), ("b", 3), ("c", 3)]), "2", "c")
        assert got == set()

    def test_can_preempt_lower_priority_from_same_cq(self):  # :2034
        got = run_fair(
            [("a1_low", "a", "1", -1), ("a2_low", "a", "1", -1),
             ("a3", "a", "1", 0), ("a4", "a", "1", 0)]
            + units([("b", 5)]),
            "2", "a")
        assert got == {("a1_low", IN_CLUSTER_QUEUE),
                       ("a2_low", IN_CLUSTER_QUEUE)}

    def test_can_preempt_combination_of_same_cq_and_highest_user(self):  # :2054
        got = run_fair(
            [("a_low", "a", "1", -1), ("a2", "a", "1", 0), ("a3", "a", "1", 0)]
            + units([("b", 6)]),
            "2", "a")
        assert got == {("a_low", IN_CLUSTER_QUEUE),
                       ("b1", IN_COHORT_FAIR_SHARING)}

    def test_hierarchical_preemption(self):  # :2413
        cohorts = [
            Cohort(name="ROOT", resource_groups=(
                rg(FlavorQuotas.build("default", {"cpu": "5"})),)),
            Cohort(name="LEFT", parent="ROOT",
                   fair_sharing=FairSharing(weight_milli=2000),
                   resource_groups=(
                       rg(FlavorQuotas.build("default", {"cpu": "5"})),)),
            Cohort(name="RIGHT", parent="ROOT", resource_groups=(
                rg(FlavorQuotas.build("default", {"cpu": "5"})),)),
        ]
        reclaim_any = Preemption(
            reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY)
        cqs = [
            fair_cq("a", "1", cohort="LEFT", weight=2000,
                    preemption=reclaim_any),
            fair_cq("b", "1", cohort="LEFT", preemption=Preemption()),
            fair_cq("c", "1", cohort="ROOT", preemption=Preemption()),
            fair_cq("d", "1", cohort="RIGHT", preemption=Preemption()),
            fair_cq("e", "1", cohort="RIGHT", weight=990,
                    preemption=Preemption()),
        ]
        admitted = [
            (f"{cq}{i}", cq, "1", i)
            for cq in ("b", "c", "d", "e")
            for i in range(1, 6)
        ]
        got = run_fair(admitted, "5", "a", cqs=cqs, cohorts=cohorts)
        assert got == {("b1", IN_COHORT_FAIR_SHARING),
                       ("b2", IN_COHORT_FAIR_SHARING),
                       ("c1", IN_COHORT_FAIR_SHARING),
                       ("c2", IN_COHORT_FAIR_SHARING),
                       ("e1", IN_COHORT_FAIR_SHARING)}


# ---------------------------------------------------------------------------
# Scheduler cycle truth tables (scheduler_test.go TestSchedule).
# Shared fixture CQs (scheduler_test.go:84-167): sales (default 50, no
# borrowing), eng-alpha / eng-beta in cohort "eng" (on-demand 50 with
# borrowingLimit 50/10, spot 100/0 and 0/100, beta adds model-a gpu 20
# and preemption), lend-a / lend-b in cohort "lend" with lendingLimits
# 2/2. One scheduler cycle, asserting the same scheduled set, flavor
# picks, usage, and queue leftovers.
# ---------------------------------------------------------------------------

from kueue_tpu.core.queue_manager import QueueManager
from kueue_tpu.core.scheduler import Scheduler
from kueue_tpu.models import LocalQueue, QueueingStrategy


def _strict(name, cohort, groups, preemption=None):
    return ClusterQueue(
        name=name, cohort=cohort, namespace_selector={},
        queueing_strategy=QueueingStrategy.STRICT_FIFO,
        resource_groups=tuple(groups),
        preemption=preemption or Preemption(),
    )


def sched_fixture_cqs():
    return [
        # the reference's fixture writes borrowingLimit 0 on cohort-less
        # "sales"; our model enforces the CEL rule (borrowingLimit
        # requires cohort), and without a cohort the limit is inert
        _strict("sales", None,
                [rg(FlavorQuotas.build("default", {"cpu": "50"}))]),
        _strict("eng-alpha", "eng",
                [rg(FlavorQuotas.build("on-demand", {"cpu": ("50", "50", None)}),
                    FlavorQuotas.build("spot", {"cpu": ("100", "0", None)}))]),
        _strict("eng-beta", "eng",
                [rg(FlavorQuotas.build("on-demand", {"cpu": ("50", "10", None)}),
                    FlavorQuotas.build("spot", {"cpu": ("0", "100", None)})),
                 rg(FlavorQuotas.build("model-a", {"example.com/gpu": ("20", "0", None)}))],
                preemption=Preemption(
                    within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
                    reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY)),
        # lend-a/lend-b keep the default BestEffortFIFO strategy (the
        # reference fixture sets StrictFIFO only on sales/eng queues);
        # BestEffortFIFO is what parks NoFit heads as inadmissible
        ClusterQueue(
            name="lend-a", cohort="lend", namespace_selector={},
            resource_groups=(
                rg(FlavorQuotas.build("default", {"cpu": ("3", None, "2")})),)),
        ClusterQueue(
            name="lend-b", cohort="lend", namespace_selector={},
            resource_groups=(
                rg(FlavorQuotas.build("default", {"cpu": ("2", None, "2")})),)),
    ]


SCHED_FLAVORS = [ResourceFlavor(name=n)
                 for n in ("default", "on-demand", "spot", "model-a")]


def sched_env(extra_cqs=(), cohorts=(), fair=False):
    from kueue_tpu.core.preemption import Preemptor

    clock = FakeClock(NOW)
    cache = Cache()
    for f in SCHED_FLAVORS:
        cache.add_or_update_flavor(f)
    mgr = QueueManager(clock=clock)
    for c in cohorts:
        cache.add_or_update_cohort(c)
    for cq in list(sched_fixture_cqs()) + list(extra_cqs):
        cache.add_or_update_cluster_queue(cq)
        mgr.add_cluster_queue(cq)
        mgr.add_local_queue(LocalQueue(
            namespace="ns", name=f"lq-{cq.name}", cluster_queue=cq.name))
    sched = Scheduler(
        queues=mgr, cache=cache, clock=clock, fair_sharing=fair,
        preemptor=Preemptor(clock, enable_fair_sharing=fair),
    )
    return sched, mgr, cache, clock


def sched_pending(mgr, name, cq, pod_sets, prio=0, t=None):
    wl = Workload(
        namespace="ns", name=name, queue_name=f"lq-{cq}", priority=prio,
        creation_time=NOW if t is None else t,
        pod_sets=tuple(pod_sets),
    )
    mgr.add_or_update_workload(wl)
    return wl


def sched_admitted(cache, name, cq, pod_sets, flavors, prio=0):
    wl = Workload(
        namespace="ns", name=name, queue_name=f"lq-{cq}", priority=prio,
        creation_time=NOW, pod_sets=tuple(pod_sets),
    )
    wl.admission = make_admission(cq, flavors, wl)
    wl.set_condition(
        WorkloadConditionType.QUOTA_RESERVED, True,
        reason="QuotaReserved", now=NOW,
    )
    cache.add_or_update_workload(wl)
    return wl


def admitted_names(res):
    return sorted(e.workload.name for e in res.admitted)


def psa(wl, ps_name):
    (m,) = [p for p in wl.admission.pod_set_assignments if p.name == ps_name]
    return m


class TestSchedulerCycleParity:
    """scheduler_test.go TestSchedule, case names preserved."""

    def test_admit_in_different_cohorts(self):  # :469
        sched, mgr, cache, _ = sched_env()
        sched_pending(mgr, "new-sales", "sales",
                      [PodSet.build("one", 1, {"cpu": "1"})])
        sched_pending(mgr, "new-alpha", "eng-alpha",
                      [PodSet.build("one", 51, {"cpu": "1"})])  # borrows
        res = sched.schedule()
        assert admitted_names(res) == ["new-alpha", "new-sales"]
        wl = cache.cluster_queues["eng-alpha"].workloads["ns/new-alpha"]
        assert psa(wl, "one").flavors["cpu"] == "on-demand"
        assert psa(wl, "one").resource_usage["cpu"] == 51_000

    def test_admit_in_same_cohort_with_no_borrowing(self):  # :518
        sched, mgr, cache, _ = sched_env()
        sched_pending(mgr, "new-alpha", "eng-alpha",
                      [PodSet.build("one", 40, {"cpu": "1"})])
        sched_pending(mgr, "new-beta", "eng-beta",
                      [PodSet.build("one", 40, {"cpu": "1"})])
        res = sched.schedule()
        assert admitted_names(res) == ["new-alpha", "new-beta"]
        for cq, name in (("eng-alpha", "new-alpha"), ("eng-beta", "new-beta")):
            wl = cache.cluster_queues[cq].workloads[f"ns/{name}"]
            assert psa(wl, "one").flavors["cpu"] == "on-demand"

    def test_assign_multiple_resources_and_flavors(self):  # :567
        sched, mgr, cache, _ = sched_env()
        sched_pending(mgr, "new", "eng-beta", [
            PodSet.build("one", 10, {"cpu": "6", "example.com/gpu": "1"}),
            PodSet.build("two", 40, {"cpu": "1"}),
        ])
        res = sched.schedule()
        assert admitted_names(res) == ["new"]
        wl = cache.cluster_queues["eng-beta"].workloads["ns/new"]
        one, two = psa(wl, "one"), psa(wl, "two")
        assert one.flavors == {"cpu": "on-demand", "example.com/gpu": "model-a"}
        assert one.resource_usage["cpu"] == 60_000
        assert two.flavors == {"cpu": "spot"}
        assert two.resource_usage["cpu"] == 40_000

    def test_cannot_borrow_when_cohort_assigned_would_overadmit(self):  # :613
        sched, mgr, cache, _ = sched_env()
        sched_pending(mgr, "new-alpha", "eng-alpha",
                      [PodSet.build("one", 45, {"cpu": "1"})])
        sched_pending(mgr, "new-beta", "eng-beta",
                      [PodSet.build("one", 56, {"cpu": "1"})])
        res = sched.schedule()
        assert admitted_names(res) == ["new-alpha"]
        # beta stays in the active queue (requeued), not inadmissible
        assert "ns/new-beta" in mgr.cluster_queues["eng-beta"].heap.keys()

    def test_can_borrow_when_cohort_assigned_without_overadmission(self):  # :650
        sched, mgr, cache, _ = sched_env()
        sched_pending(mgr, "new-alpha", "eng-alpha",
                      [PodSet.build("one", 45, {"cpu": "1"})])
        sched_pending(mgr, "new-beta", "eng-beta",
                      [PodSet.build("one", 55, {"cpu": "1"})])
        res = sched.schedule()
        assert admitted_names(res) == ["new-alpha", "new-beta"]

    def test_can_borrow_when_reclaim_possible_in_other_flavor(self):  # :699
        sched, mgr, cache, _ = sched_env()
        sched_admitted(cache, "user-on-demand", "eng-beta",
                       [PodSet.build("main", 1, {"cpu": "50"})],
                       {"main": {"cpu": "on-demand"}})
        sched_admitted(cache, "user-spot", "eng-beta",
                       [PodSet.build("main", 1, {"cpu": "1"})],
                       {"main": {"cpu": "spot"}})
        sched_pending(mgr, "can-reclaim", "eng-alpha",
                      [PodSet.build("main", 1, {"cpu": "100"})])
        sched_pending(mgr, "needs-to-borrow", "eng-beta",
                      [PodSet.build("main", 1, {"cpu": "1"})])
        res = sched.schedule()
        assert admitted_names(res) == ["needs-to-borrow"]
        wl = cache.cluster_queues["eng-beta"].workloads["ns/needs-to-borrow"]
        assert psa(wl, "main").flavors["cpu"] == "on-demand"

    def test_workload_exceeds_lending_limit_when_borrowing(self):  # :730
        sched, mgr, cache, _ = sched_env()
        sched_admitted(cache, "a", "lend-b",
                       [PodSet.build("main", 1, {"cpu": "2"})],
                       {"main": {"cpu": "default"}})
        sched_pending(mgr, "b", "lend-b",
                      [PodSet.build("main", 1, {"cpu": "3"})])
        res = sched.schedule()
        assert admitted_names(res) == []
        assert "ns/b" in mgr.cluster_queues["lend-b"].inadmissible

    def test_fair_sharing_lowest_share_first(self):  # :1487
        shared = _strict("eng-shared", "eng", [
            rg(FlavorQuotas.build("on-demand", {"cpu": ("10", "0", None)}))])
        sched, mgr, cache, _ = sched_env(extra_cqs=[shared], fair=True)
        sched_admitted(cache, "all_nominal", "eng-alpha",
                       [PodSet.build("one", 50, {"cpu": "1"})],
                       {"one": {"cpu": "on-demand"}})
        sched_admitted(cache, "borrowing", "eng-beta",
                       [PodSet.build("one", 55, {"cpu": "1"})],
                       {"one": {"cpu": "on-demand"}})
        sched_pending(mgr, "older_new", "eng-beta",
                      [PodSet.build("one", 1, {"cpu": "1"})], t=NOW - 60)
        sched_pending(mgr, "new", "eng-alpha",
                      [PodSet.build("one", 5, {"cpu": "1"})], t=NOW)
        res = sched.schedule()
        # eng-alpha has the lower share (all nominal) so its head wins
        # the cycle despite the older eng-beta head
        assert admitted_names(res) == ["new"]
        assert "ns/older_new" in mgr.cluster_queues["eng-beta"].heap.keys()

    def test_hierarchical_fair_sharing_tournament(self):  # :1569
        cohorts = [
            Cohort(name="A", resource_groups=(
                rg(FlavorQuotas.build("on-demand", {"cpu": "200"})),)),
            Cohort(name="B", parent="A"),
            Cohort(name="C", parent="A"),
        ]
        zero = {"cpu": ("0", None, None)}
        extra = [
            _strict("d", "B", [rg(FlavorQuotas.build("on-demand", zero))]),
            _strict("e", "B", [rg(FlavorQuotas.build("on-demand", zero))]),
            _strict("f", "C", [rg(FlavorQuotas.build("on-demand", zero))]),
            _strict("g", "C", [rg(FlavorQuotas.build("on-demand", zero))]),
        ]
        sched, mgr, cache, _ = sched_env(
            extra_cqs=extra, cohorts=cohorts, fair=True)
        sched_admitted(cache, "d0", "d", [PodSet.build("one", 1, {"cpu": "10"})],
                       {"one": {"cpu": "on-demand"}})
        sched_admitted(cache, "e0", "e", [PodSet.build("one", 1, {"cpu": "20"})],
                       {"one": {"cpu": "on-demand"}})
        sched_admitted(cache, "g0", "g", [PodSet.build("one", 1, {"cpu": "100"})],
                       {"one": {"cpu": "on-demand"}})
        sched_pending(mgr, "d1", "d", [PodSet.build("one", 1, {"cpu": "70"})])
        sched_pending(mgr, "e1", "e", [PodSet.build("one", 1, {"cpu": "61"})])
        sched_pending(mgr, "f1", "f", [PodSet.build("one", 1, {"cpu": "1"})])
        sched_pending(mgr, "g1", "g", [PodSet.build("one", 1, {"cpu": "1"})])
        res = sched.schedule()
        # d1 wins: B's post-admission share (100) < C's (101), and d
        # beats e at the lower tournament level (80 < 81)
        assert admitted_names(res) == ["d1"]

    def test_fair_sharing_highest_priority_first(self):  # :1816
        cohorts = [
            Cohort(name="A", resource_groups=(
                rg(FlavorQuotas.build("on-demand", {"cpu": "10"})),)),
        ]
        zero = {"cpu": ("0", None, None)}
        extra = [
            _strict("b", "A", [rg(FlavorQuotas.build("on-demand", zero))]),
            _strict("c", "A", [rg(FlavorQuotas.build("on-demand", zero))]),
        ]
        sched, mgr, cache, _ = sched_env(
            extra_cqs=extra, cohorts=cohorts, fair=True)
        sched_pending(mgr, "b1", "b", [PodSet.build("one", 1, {"cpu": "10"})],
                      prio=99)
        sched_pending(mgr, "c1", "c", [PodSet.build("one", 1, {"cpu": "10"})],
                      prio=101)
        res = sched.schedule()
        assert admitted_names(res) == ["c1"]
        assert "ns/b1" in mgr.cluster_queues["b"].heap.keys()

    def test_fair_sharing_earliest_timestamp_first(self):  # :1870
        cohorts = [
            Cohort(name="A", resource_groups=(
                rg(FlavorQuotas.build("on-demand", {"cpu": "10"})),)),
        ]
        zero = {"cpu": ("0", None, None)}
        extra = [
            _strict("b", "A", [rg(FlavorQuotas.build("on-demand", zero))]),
            _strict("c", "A", [rg(FlavorQuotas.build("on-demand", zero))]),
        ]
        sched, mgr, cache, _ = sched_env(
            extra_cqs=extra, cohorts=cohorts, fair=True)
        sched_pending(mgr, "b1", "b", [PodSet.build("one", 1, {"cpu": "10"})],
                      prio=101, t=NOW + 1)
        sched_pending(mgr, "c1", "c", [PodSet.build("one", 1, {"cpu": "10"})],
                      prio=101, t=NOW)
        res = sched.schedule()
        assert admitted_names(res) == ["c1"]


# ---------------------------------------------------------------------------
# TAS placement truth tables (pkg/cache/tas_cache_test.go
# TestFindTopologyAssignment). Reference node fixtures re-stated
# verbatim (defaultNodes :51-118, binaryTreesNodes :200-289, and the
# per-case trees), asserting the same TopologyAssignment (levels +
# domain values + per-domain counts) under the same placement-profile
# feature gates.
# ---------------------------------------------------------------------------

from kueue_tpu import features
from kueue_tpu.tas import TASFlavorSnapshot, TASPodSetRequest
from kueue_tpu.models.workload import PodSetTopologyRequest

BLOCK, RACK, HOST = (
    "cloud.com/topology-block",
    "cloud.com/topology-rack",
    "kubernetes.io/hostname",
)
THREE_LEVELS = (BLOCK, RACK, HOST)
TWO_LEVELS = (BLOCK, RACK)


def tas_node(b, r, x, cpu=1, mem=1 << 30, pods=10):
    return ({BLOCK: b, RACK: r, HOST: x},
            {"cpu": cpu * 1000, "memory": mem, "pods": pods})


# defaultNodes (tas_cache_test.go:51-118): x6 is the big host
TAS_DEFAULT_NODES = [
    tas_node("b1", "r1", "x1"),
    tas_node("b1", "r2", "x2"),
    tas_node("b1", "r2", "x3"),
    tas_node("b1", "r2", "x4"),
    tas_node("b2", "r1", "x5"),
    tas_node("b2", "r2", "x6", cpu=2, mem=4 << 30, pods=40),
]

# binaryTreesNodes (:200-289): 2 blocks x 2 racks x 2 hosts, uniform
TAS_BINARY_NODES = [
    tas_node(f"b{bi}", f"r{ri}", f"x{(bi - 1) * 4 + (ri - 1) * 2 + hi}")
    for bi in (1, 2) for ri in (1, 2) for hi in (1, 2)
]


def tas_snapshot(nodes, levels=THREE_LEVELS):
    snap = TASFlavorSnapshot("default", tuple(levels))
    for labels, alloc in nodes:
        snap.add_node(labels, alloc, ())
    snap.freeze()
    return snap


def tas_request(count, level, mode="Required", cpu=1000):
    return TASPodSetRequest(
        podset_name="main", count=count,
        single_pod_requests={"cpu": cpu},
        topology_request=PodSetTopologyRequest(mode=mode, level=level),
    )


def domains_of(ta):
    return sorted((tuple(d.values), d.count) for d in ta.domains)


class TestTASPlacementParity:
    """tas_cache_test.go TestFindTopologyAssignment, names preserved."""

    def test_minimize_racks_before_nodes_most_free(self):  # :306
        nodes = [
            tas_node("b1", "r1", "x1", cpu=2),
            tas_node("b1", "r2", "x2", cpu=2, pods=20),
            tas_node("b1", "r3", "x3"),
            tas_node("b1", "r3", "x4"),
            tas_node("b1", "r3", "x5"),
            tas_node("b1", "r3", "x6"),
        ]
        with features.override("TASProfileMostFreeCapacity", True):
            snap = tas_snapshot(nodes)
            ta, reason = snap.find_topology_assignment(
                tas_request(4, BLOCK), {})
        assert reason == ""
        assert ta.levels == (HOST,)
        assert domains_of(ta) == [(("x3",), 1), (("x4",), 1),
                                  (("x5",), 1), (("x6",), 1)]

    def test_minimize_fragmentation_least_free(self):  # :417
        nodes = [
            tas_node("b1", "r1", "x1", cpu=2),
            tas_node("b1", "r1", "x2"),
            tas_node("b1", "r1", "x3"),
        ]
        with features.override("TASProfileLeastFreeCapacity", True):
            snap = tas_snapshot(nodes)
            ta, reason = snap.find_topology_assignment(
                tas_request(2, BLOCK), {})
        assert reason == ""
        assert domains_of(ta) == [(("x2",), 1), (("x3",), 1)]

    def test_choose_node_that_accommodates_all_pods(self):  # :483
        nodes = [
            tas_node("b1", "r1", "x1", cpu=2),
            tas_node("b1", "r1", "x2"),
            tas_node("b1", "r1", "x3"),
        ]
        snap = tas_snapshot(nodes)
        ta, reason = snap.find_topology_assignment(tas_request(2, BLOCK), {})
        assert reason == ""
        assert domains_of(ta) == [(("x1",), 2)]

    def test_block_required_binary_tree_best_fit(self):  # :784
        snap = tas_snapshot(TAS_BINARY_NODES)
        ta, reason = snap.find_topology_assignment(tas_request(4, BLOCK), {})
        assert reason == ""
        assert domains_of(ta) == [(("x1",), 1), (("x2",), 1),
                                  (("x3",), 1), (("x4",), 1)]

    def test_block_required_binary_tree_most_free(self):  # :743
        with features.override("TASProfileMostFreeCapacity", True):
            snap = tas_snapshot(TAS_BINARY_NODES)
            ta, reason = snap.find_topology_assignment(
                tas_request(4, BLOCK), {})
        assert reason == ""
        assert domains_of(ta) == [(("x1",), 1), (("x2",), 1),
                                  (("x3",), 1), (("x4",), 1)]

    def test_host_required_best_fit(self):  # :871
        snap = tas_snapshot(TAS_DEFAULT_NODES)
        ta, reason = snap.find_topology_assignment(tas_request(1, HOST), {})
        assert reason == ""
        assert domains_of(ta) == [(("x1",), 1)]

    def test_host_required_most_free(self):  # :824
        with features.override("TASProfileMostFreeCapacity", True):
            snap = tas_snapshot(TAS_DEFAULT_NODES)
            ta, reason = snap.find_topology_assignment(
                tas_request(1, HOST), {})
        assert reason == ""
        assert domains_of(ta) == [(("x6",), 1)]

    def test_rack_required_two_levels_most_free(self):  # :939
        with features.override("TASProfileMostFreeCapacity", True):
            snap = tas_snapshot(TAS_DEFAULT_NODES, levels=TWO_LEVELS)
            ta, reason = snap.find_topology_assignment(
                tas_request(1, RACK), {})
        assert reason == ""
        assert ta.levels == TWO_LEVELS
        assert domains_of(ta) == [(("b1", "r2"), 1)]

    def test_rack_preferred_multiple_racks_least_free(self):  # :987
        with features.override("TASProfileLeastFreeCapacity", True):
            snap = tas_snapshot(TAS_DEFAULT_NODES, levels=TWO_LEVELS)
            ta, reason = snap.find_topology_assignment(
                tas_request(2, RACK, mode="Preferred"), {})
        assert reason == ""
        assert domains_of(ta) == [(("b2", "r1"), 1), (("b2", "r2"), 1)]


# ---------------------------------------------------------------------------
# Partial-admission reducer truth tables (podset_reducer_test.go
# TestSearch): the binary search over scaled-down podset counts must
# find the reference's exact totals, including the 150k-pod
# granularity cases.
# ---------------------------------------------------------------------------

from kueue_tpu.core.flavor_assigner import (
    AssignmentResult as _AR,
    PodSetResult as _PSR,
    find_max_counts,
)
from kueue_tpu.core.flavor_assigner import GranularMode as _GM
from kueue_tpu.core.flavor_assigner import FlavorChoice as _FC


def _reduce(pod_sets, count_limit):
    """Drive find_max_counts with the reference's fits predicate:
    total scaled count <= countLimit. pod_sets: [(count, min|None)]."""
    wl = Workload(
        namespace="ns", name="w", queue_name="lq",
        pod_sets=tuple(
            PodSet.build(f"ps{i}", cnt, {"cpu": "1"},
                         min_count=mn)
            for i, (cnt, mn) in enumerate(pod_sets)
        ),
    )

    def assign_fn(counts):
        fit = sum(counts) <= count_limit
        mode = _GM.FIT if fit else _GM.NO_FIT
        psrs = [
            _PSR(name=f"ps{i}", count=c,
                 flavors={"cpu": _FC("f", mode)} if fit else {},
                 reasons=[] if fit else ["over limit"])
            for i, c in enumerate(counts)
        ]
        return _AR(pod_sets=psrs)

    res = find_max_counts(assign_fn, wl)
    if res is None:
        return False, 0
    return True, sum(res)


class TestPodSetReducerParity:
    """podset_reducer_test.go TestSearch, case names preserved (the
    'empty' case is unrepresentable: the Workload model requires >= 1
    podSet, matching the CRD's minItems)."""

    def test_partial_not_available(self):
        found, _ = _reduce([(1, None), (2, 2)], 2)
        assert not found

    def test_partial_available(self):
        found, total = _reduce([(5, 3), (5, 4), (5, 1), (5, 2)], 15)
        assert found and total == 15

    def test_one_partial_available(self):
        found, total = _reduce([(5, 3), (5, None), (5, None), (5, None)], 19)
        assert found and total == 19

    def test_to_min(self):
        found, total = _reduce([(5, 3), (5, 4), (5, 1), (5, 2)], 10)
        assert found and total == 10

    def test_to_max(self):
        found, total = _reduce([(5, 3), (5, 4), (5, 1), (5, 2)], 20)
        assert found and total == 20

    def test_no_overflow(self):
        found, total = _reduce([(150_000, 1)] * 8, 150_000)
        assert found and total == 150_000

    def test_max_pods_on_127(self):
        found, total = _reduce(
            [(150_000, 1)] + [(1, None)] * 7, 150_000
        )
        assert found and total == 150_000


# ---------------------------------------------------------------------------
# DominantResourceShare truth tables (pkg/cache/fair_sharing_test.go
# TestDominantResourceShare): exact weighted-share values and dominant
# resources per node, including hierarchical cohorts, weights
# (integer/decimal/zero), lending and borrowing limits.
# ---------------------------------------------------------------------------

from kueue_tpu.ops.quota import DRS_MAX
from kueue_tpu.ops.quota_np import dominant_resource_share_np


def _drs_env(cqs, cohorts=(), usage=None, wl_req=None):
    """usage: {cq_name: {(flavor, resource): qty}} charged via admitted
    workloads; wl_req: {(flavor, resource): qty} added for the first
    CQ (the reference's flvResQ incoming-workload usage). Returns
    {node name: (weighted share, dominant resource or None)}."""
    cache = Cache()
    for f in ("default", "on-demand", "spot"):
        cache.add_or_update_flavor(ResourceFlavor(name=f))
    for c in cohorts:
        cache.add_or_update_cohort(c)
    for cq in cqs:
        cache.add_or_update_cluster_queue(cq)
    n = 0
    for cq_name, charge in (usage or {}).items():
        for (flavor, resource), qty in charge.items():
            n += 1
            wl = Workload(
                namespace="ns", name=f"u{n}", queue_name="lq",
                pod_sets=(PodSet.build("main", 1, {resource: qty}),),
            )
            wl.admission = make_admission(
                cq_name, {"main": {resource: flavor}}, wl
            )
            cache.add_or_update_workload(wl)
    snap = take_snapshot(cache)
    nrows, nfr = snap.local_usage.shape
    wl_mat = np.zeros((nrows, nfr), dtype=np.int64)
    if wl_req:
        r0 = snap.row(cqs[0].name)
        for (flavor, resource), qty in wl_req.items():
            j = snap.fr_index[FlavorResource(flavor, resource)]
            wl_mat[r0, j] = quantity_to_int(resource, qty)
    lm = snap.flat.level_masks()
    dws, dom = dominant_resource_share_np(
        snap.flat.parent, lm, snap.subtree, snap.guaranteed,
        snap.borrowing_limit, snap.usage(), wl_mat, snap.weight_milli,
        snap.resource_index, len(snap.resource_names),
    )
    out = {}
    for name in [c.name for c in cqs] + [c.name for c in cohorts]:
        r = snap.row(name)
        d = int(dom[r])
        out[name] = (
            int(dws[r]),
            snap.resource_names[d] if d >= 0 else None,
        )
    return out


def _drs_cq(name, quotas, cohort="test-cohort", weight=1000):
    return ClusterQueue(
        name=name, cohort=cohort, namespace_selector={},
        resource_groups=(rg(FlavorQuotas.build("default", quotas)),),
        fair_sharing=FairSharing(weight_milli=weight),
    )


import numpy as np

from kueue_tpu.models.cluster_queue import FairSharing


class TestDominantResourceShareParity:
    """fair_sharing_test.go TestDominantResourceShare, names preserved."""

    def test_no_cohort(self):
        cq = _drs_cq("cq", {"cpu": "2000", "example.com/gpu": "5"},
                     cohort=None)
        got = _drs_env([cq], usage={"cq": {
            ("default", "cpu"): "1", ("default", "example.com/gpu"): "2"}})
        assert got["cq"] == (0, None)

    def _pair(self, cq_quotas, lending_quotas, usage, wl_req=None,
              weight=1000, lending_weight=1000):
        cq = _drs_cq("cq", cq_quotas, weight=weight)
        lend = _drs_cq("lending-cq", lending_quotas, weight=lending_weight)
        cohorts = [Cohort(name="test-cohort")]
        return _drs_env([cq, lend], cohorts, usage=usage, wl_req=wl_req)

    def test_usage_below_nominal(self):
        got = self._pair(
            {"cpu": "2", "example.com/gpu": "5"},
            {"cpu": "8", "example.com/gpu": "5"},
            {"cq": {("default", "cpu"): "1",
                    ("default", "example.com/gpu"): "2"}},
        )
        assert got["cq"] == (0, None)
        assert got["lending-cq"] == (0, None)
        assert got["test-cohort"] == (0, None)

    def test_usage_above_nominal(self):
        got = self._pair(
            {"cpu": "2", "example.com/gpu": "5"},
            {"cpu": "8", "example.com/gpu": "5"},
            {"cq": {("default", "cpu"): "3",
                    ("default", "example.com/gpu"): "7"}},
        )
        assert got["cq"] == (200, "example.com/gpu")  # (7-5)*1000/10
        assert got["lending-cq"] == (0, None)
        assert got["test-cohort"] == (0, None)

    def test_one_resource_above_nominal(self):
        got = self._pair(
            {"cpu": "2", "example.com/gpu": "5"},
            {"cpu": "8", "example.com/gpu": "5"},
            {"cq": {("default", "cpu"): "3",
                    ("default", "example.com/gpu"): "3"}},
        )
        assert got["cq"] == (100, "cpu")  # (3-2)*1000/10

    def test_usage_with_workload_above_nominal(self):
        got = self._pair(
            {"cpu": "2", "example.com/gpu": "5"},
            {"cpu": "8", "example.com/gpu": "5"},
            {"cq": {("default", "cpu"): "1",
                    ("default", "example.com/gpu"): "2"}},
            wl_req={("default", "cpu"): "4",
                    ("default", "example.com/gpu"): "4"},
        )
        assert got["cq"] == (300, "cpu")  # (1+4-2)*1000/10

    def test_resource_with_zero_lendable(self):
        got = self._pair(
            {"cpu": "2", "example.com/gpu": ("2", None, "0")},
            {"cpu": "8", "example.com/gpu": ("64", None, "0")},
            {"cq": {("default", "cpu"): "1",
                    ("default", "example.com/gpu"): "1"}},
            wl_req={("default", "cpu"): "4",
                    ("default", "example.com/gpu"): "4"},
        )
        assert got["cq"] == (300, "cpu")  # gpu lendable is zero

    def test_multiple_flavors(self):
        cq = ClusterQueue(
            name="cq", cohort="test-cohort", namespace_selector={},
            resource_groups=(rg(
                FlavorQuotas.build("on-demand", {"cpu": "20"}),
                FlavorQuotas.build("spot", {"cpu": "80"}),
            ),),
        )
        lend = ClusterQueue(
            name="lending-cq", cohort="test-cohort", namespace_selector={},
            resource_groups=(rg(
                FlavorQuotas.build("on-demand", {"cpu": "100"}),
            ),),
        )
        got = _drs_env(
            [cq, lend], [Cohort(name="test-cohort")],
            usage={"cq": {("on-demand", "cpu"): "15", ("spot", "cpu"): "5"}},
            wl_req={("on-demand", "cpu"): "10"},
        )
        assert got["cq"] == (25, "cpu")  # ((15+10-20)+0)*1000/200

    def test_above_nominal_with_integer_weight(self):
        got = self._pair(
            {"example.com/gpu": "5"},
            {"example.com/gpu": "5"},
            {"cq": {("default", "example.com/gpu"): "7"}},
            weight=2000,
        )
        assert got["cq"] == (100, "example.com/gpu")  # ((7-5)*1000/10)/2

    def test_above_nominal_with_decimal_weight(self):
        got = self._pair(
            {"example.com/gpu": "5"},
            {"example.com/gpu": "5"},
            {"cq": {("default", "example.com/gpu"): "7"}},
            weight=500,
        )
        assert got["cq"] == (400, "example.com/gpu")  # ((7-5)*1000/10)/0.5

    def test_above_nominal_with_zero_weight(self):
        got = self._pair(
            {"example.com/gpu": "5"},
            {"example.com/gpu": "10"},
            {"cq": {("default", "example.com/gpu"): "7"}},
            weight=0,
        )
        assert got["cq"] == (DRS_MAX, "example.com/gpu")

    def test_cohort_has_resource_share(self):
        cq = _drs_cq("cq", {"example.com/gpu": "5"}, cohort="child-cohort")
        cohorts = [
            Cohort(name="child-cohort", parent="root",
                   fair_sharing=FairSharing(weight_milli=2000)),
            Cohort(name="root", resource_groups=(
                rg(FlavorQuotas.build("default", {"example.com/gpu": "45"})),)),
        ]
        got = _drs_env([cq], cohorts,
                       usage={"cq": {("default", "example.com/gpu"): "10"}})
        assert got["cq"] == (100, "example.com/gpu")  # (5/50)*1000
        assert got["child-cohort"] == (50, "example.com/gpu")  # /2
        assert got["root"] == (0, None)

    def test_resource_share_only_at_root(self):
        cq = _drs_cq("cq", {"example.com/gpu": "0"}, cohort="child-cohort")
        cohorts = [
            Cohort(name="child-cohort", parent="root",
                   fair_sharing=FairSharing(weight_milli=2000)),
            Cohort(name="root", resource_groups=(
                rg(FlavorQuotas.build("default", {"example.com/gpu": "50"})),)),
        ]
        got = _drs_env([cq], cohorts,
                       usage={"cq": {("default", "example.com/gpu"): "10"}})
        assert got["cq"] == (200, "example.com/gpu")  # (10/50)*1000
        assert got["child-cohort"] == (100, "example.com/gpu")

    def test_resource_share_affected_by_borrowing_limit(self):
        cq = _drs_cq("cq", {"example.com/gpu": "0"}, cohort="child-cohort")
        cohorts = [
            Cohort(name="child-cohort", parent="root", resource_groups=(
                rg(FlavorQuotas.build(
                    "default", {"example.com/gpu": ("0", "10", None)})),)),
            Cohort(name="root", resource_groups=(
                rg(FlavorQuotas.build("default", {"example.com/gpu": "50"})),)),
        ]
        got = _drs_env([cq], cohorts,
                       usage={"cq": {("default", "example.com/gpu"): "10"}})
        assert got["cq"] == (1000, "example.com/gpu")  # (10/10)*1000
        assert got["child-cohort"] == (200, "example.com/gpu")  # (10/50)*1000
        assert got["root"] == (0, None)


class TestSchedulerSameCycleBorrowing:
    """scheduler_test.go TestSchedule same-cycle borrowing trio: one
    admission per borrowing cohort per cycle is NOT the rule — multiple
    borrowers admit together when the cohort quota still fits all of
    them after in-cycle re-checks."""

    def _borrow_env(self):
        preemption = Preemption(
            within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
            reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
        )
        extra = [
            ClusterQueue(
                name=f"cq{i}", cohort="co", namespace_selector={},
                queueing_strategy=QueueingStrategy.STRICT_FIFO,
                resource_groups=(rg(FlavorQuotas.build("default", {
                    "r1": ("10", "10", None), "r2": ("10", "10", None)})),),
                preemption=preemption,
            )
            for i in (1, 2, 3)
        ]
        return sched_env(extra_cqs=extra)

    def test_two_borrow_different_resources_same_cycle(self):  # :1251
        sched, mgr, cache, _ = self._borrow_env()
        sched_pending(mgr, "wl1", "cq1", [PodSet.build("main", 1, {"r1": "16"})],
                      prio=-1)
        sched_pending(mgr, "wl2", "cq2", [PodSet.build("main", 1, {"r2": "16"})],
                      prio=-2)
        res = sched.schedule()
        assert admitted_names(res) == ["wl1", "wl2"]

    def test_two_borrow_same_resource_fits_cohort(self):  # :1286
        sched, mgr, cache, _ = self._borrow_env()
        sched_pending(mgr, "wl1", "cq1", [PodSet.build("main", 1, {"r1": "16"})],
                      prio=-1)
        sched_pending(mgr, "wl2", "cq2", [PodSet.build("main", 1, {"r1": "14"})],
                      prio=-2)
        res = sched.schedule()
        assert admitted_names(res) == ["wl1", "wl2"]

    def test_only_one_borrows_when_cohort_cannot_fit_both(self):  # :1321
        sched, mgr, cache, _ = self._borrow_env()
        sched_pending(mgr, "wl1", "cq1", [PodSet.build("main", 1, {"r1": "16"})],
                      prio=-1)
        sched_pending(mgr, "wl2", "cq2", [PodSet.build("main", 1, {"r1": "16"})],
                      prio=-2)
        res = sched.schedule()
        assert admitted_names(res) == ["wl1"]
        assert "ns/wl2" in mgr.cluster_queues["cq2"].heap.keys()


def test_preemption_wait_does_not_block_other_borrower():  # :1356
    """A head blocked on (impossible) preemption reserves capacity but
    must not keep a DIFFERENT ClusterQueue's borrowing head from
    admitting when the reservation still leaves room."""
    from kueue_tpu.models.cluster_queue import BorrowWithinCohort

    prem = Preemption(
        reclaim_within_cohort=ReclaimWithinCohortPolicy.LOWER_PRIORITY,
        borrow_within_cohort=BorrowWithinCohort(
            policy=BorrowWithinCohortPolicy.LOWER_PRIORITY),
    )
    extra = [
        ClusterQueue(
            name="cq_shared", cohort="pwb", namespace_selector={},
            resource_groups=(rg(FlavorQuotas.build(
                "default", {"cpu": ("4", "0", None)})),)),
        ClusterQueue(
            name="cq_a", cohort="pwb", namespace_selector={},
            resource_groups=(rg(FlavorQuotas.build(
                "default", {"cpu": ("0", "3", None)})),),
            preemption=prem),
        ClusterQueue(
            name="cq_b", cohort="pwb", namespace_selector={},
            resource_groups=(rg(FlavorQuotas.build(
                "default", {"cpu": ("0", None, None)})),),
            preemption=prem),
    ]
    sched, mgr, cache, _ = sched_env(extra_cqs=extra)
    sched_admitted(cache, "admitted_a", "cq_a",
                   [PodSet.build("main", 1, {"cpu": "2"})],
                   {"main": {"cpu": "default"}})
    sched_pending(mgr, "a", "cq_a", [PodSet.build("main", 1, {"cpu": "3"})],
                  t=NOW + 1)
    sched_pending(mgr, "b", "cq_b", [PodSet.build("main", 1, {"cpu": "1"})],
                  t=NOW + 2)
    res = sched.schedule()
    assert admitted_names(res) == ["b"]
    assert "ns/a" in mgr.cluster_queues["cq_a"].inadmissible


class TestSchedulerPreemptionFlavorPreference:
    """scheduler_test.go: which flavor a preemptor targets when several
    need preemption — reclaim-only flavors beat within-CQ preemption,
    and a later flavor that doesn't improve the assignment loses to the
    first (flavorassigner whenCanPreempt + oracle interplay driving the
    real cycle, with victims recorded via the preemptor)."""

    def _env(self, beta_preemption=True):
        prem = Preemption(
            within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
            reclaim_within_cohort=ReclaimWithinCohortPolicy.LOWER_PRIORITY,
        )
        extra = [
            ClusterQueue(
                name="other-alpha", cohort="other", namespace_selector={},
                resource_groups=(rg(
                    FlavorQuotas.build("on-demand", {"gpu": "10"}),
                    FlavorQuotas.build("spot", {"gpu": "10"}),
                ),),
                preemption=prem,
            ),
            ClusterQueue(
                name="other-beta", cohort="other", namespace_selector={},
                resource_groups=(rg(
                    FlavorQuotas.build("on-demand", {"gpu": ("0", None, None)}),
                    FlavorQuotas.build("spot", {"gpu": ("0", None, None)}),
                ),),
                preemption=prem if beta_preemption else Preemption(),
            ),
        ]
        return sched_env(extra_cqs=extra)

    def test_prefer_reclamation_over_cq_priority_preemption(self):  # :2655
        sched, mgr, cache, _ = self._env()
        sched_admitted(cache, "a1", "other-alpha",
                       [PodSet.build("main", 1, {"gpu": "5"})],
                       {"main": {"gpu": "on-demand"}}, prio=50)
        sched_admitted(cache, "b1", "other-beta",
                       [PodSet.build("main", 1, {"gpu": "5"})],
                       {"main": {"gpu": "spot"}}, prio=50)
        sched_pending(mgr, "preemptor", "other-alpha",
                      [PodSet.build("main", 1, {"gpu": "6"})], prio=100)
        res = sched.schedule()
        # spot only needs reclaiming the borrower b1; on-demand would
        # preempt a1 in the own CQ — reclaim wins
        victims = {
            t.workload.workload.name
            for e in res.preempting
            for t in e.preemption_targets
        }
        assert victims == {"b1"}
        assert admitted_names(res) == []

    def test_prefer_first_flavor_when_second_needs_reclaim_and_cq(self):  # :2716
        sched, mgr, cache, _ = self._env()
        sched_admitted(cache, "a1", "other-alpha",
                       [PodSet.build("main", 1, {"gpu": "5"})],
                       {"main": {"gpu": "on-demand"}}, prio=50)
        sched_admitted(cache, "a2", "other-alpha",
                       [PodSet.build("main", 1, {"gpu": "5"})],
                       {"main": {"gpu": "spot"}}, prio=50)
        sched_admitted(cache, "b1", "other-beta",
                       [PodSet.build("main", 1, {"gpu": "5"})],
                       {"main": {"gpu": "spot"}}, prio=50)
        sched_pending(mgr, "preemptor", "other-alpha",
                      [PodSet.build("main", 1, {"gpu": "6"})], prio=100)
        res = sched.schedule()
        # spot would need reclaim AND a within-CQ preemption — no
        # improvement over on-demand's single within-CQ victim
        victims = {
            t.workload.workload.name
            for e in res.preempting
            for t in e.preemption_targets
        }
        assert victims == {"a1"}


class TestSchedulerMinimalPreemptions:
    """scheduler_test.go: victim-set minimality and preemption
    eligibility driven through the real cycle."""

    def test_minimal_preemptions_when_target_queue_exhausted(self):  # :1926
        prem = Preemption(
            within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
            reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
        )
        extra = [
            ClusterQueue(
                name="other-alpha", cohort="other", namespace_selector={},
                resource_groups=(rg(FlavorQuotas.build(
                    "on-demand", {"cpu": "2"})),),
                preemption=prem),
            ClusterQueue(
                name="other-beta", cohort="other", namespace_selector={},
                resource_groups=(rg(FlavorQuotas.build(
                    "on-demand", {"cpu": "2"})),)),
            ClusterQueue(
                name="other-gamma", cohort="other", namespace_selector={},
                resource_groups=(rg(FlavorQuotas.build(
                    "on-demand", {"cpu": "2"})),)),
        ]
        sched, mgr, cache, _ = sched_env(extra_cqs=extra)
        for name, prio in (("a1", -2), ("a2", -2), ("a3", -1)):
            sched_admitted(cache, name, "other-alpha",
                           [PodSet.build("main", 1, {"cpu": "1"})],
                           {"main": {"cpu": "on-demand"}}, prio=prio)
        for name in ("b1", "b2", "b3"):
            sched_admitted(cache, name, "other-beta",
                           [PodSet.build("main", 1, {"cpu": "1"})],
                           {"main": {"cpu": "on-demand"}}, prio=0)
        sched_pending(mgr, "incoming", "other-alpha",
                      [PodSet.build("main", 1, {"cpu": "2"})], prio=0)
        res = sched.schedule()
        victims = {
            t.workload.workload.name
            for e in res.preempting
            for t in e.preemption_targets
        }
        # minimal set: exactly the two lowest-priority own-CQ victims,
        # not the newer a3 and none of beta's same-priority workloads
        assert victims == {"a1", "a2"}

    def test_preemptor_must_fit_within_nominal(self):  # :2015
        prem = Preemption(
            within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
            reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
        )
        extra = [
            ClusterQueue(
                name="other-alpha", cohort="other", namespace_selector={},
                resource_groups=(rg(FlavorQuotas.build(
                    "on-demand", {"cpu": "2"})),),
                preemption=prem),
            ClusterQueue(
                name="other-beta", cohort="other", namespace_selector={},
                resource_groups=(rg(FlavorQuotas.build(
                    "on-demand", {"cpu": "2"})),)),
        ]
        sched, mgr, cache, _ = sched_env(extra_cqs=extra)
        sched_admitted(cache, "a1", "other-alpha",
                       [PodSet.build("main", 1, {"cpu": "1"})],
                       {"main": {"cpu": "on-demand"}}, prio=-1)
        sched_admitted(cache, "b1", "other-beta",
                       [PodSet.build("main", 1, {"cpu": "1"})],
                       {"main": {"cpu": "on-demand"}}, prio=-1)
        sched_pending(mgr, "incoming", "other-alpha",
                      [PodSet.build("main", 1, {"cpu": "3"})], prio=1)
        res = sched.schedule()
        # 3 cpu exceeds other-alpha's 2-cpu nominal: no preemption at
        # all (borrowing preemptors are ineligible), workload parks
        assert admitted_names(res) == []
        assert not res.preempting
        assert "ns/incoming" in mgr.cluster_queues["other-alpha"].inadmissible


def test_multiple_preemptions_skip_overlapping_targets():  # :2453
    """Two preemptors targeting the same fair-sharing victim in one
    cycle: the first (higher priority) issues its preemptions, the
    second is SKIPPED with the per-CQ skip counter incremented
    (scheduler.go overlapping-targets rule).

    The reference case leaves ReclaimWithinCohort UNSET, which its
    undefaulted test fixtures treat as non-Never (fair-sharing
    preemption proceeds); this model defaults the field like the
    webhook does, so the port sets it explicitly."""
    prem = Preemption(
        within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
        reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
    )
    extra = [
        ClusterQueue(
            name="other-alpha", cohort="other", namespace_selector={},
            resource_groups=(rg(FlavorQuotas.build("default", {
                "cpu": ("0", None, None), "alpha-resource": "1"})),),
            preemption=prem),
        ClusterQueue(
            name="other-beta", cohort="other", namespace_selector={},
            resource_groups=(rg(FlavorQuotas.build("default", {
                "cpu": ("0", None, None), "beta-resource": "1"})),),
            preemption=prem),
        ClusterQueue(
            name="other-gamma", cohort="other", namespace_selector={},
            resource_groups=(rg(FlavorQuotas.build("default", {
                "cpu": ("0", None, None), "gamma-resource": "1"})),),
            preemption=prem),
        ClusterQueue(
            name="resource-bank", cohort="other", namespace_selector={},
            resource_groups=(rg(FlavorQuotas.build("default", {"cpu": "9"})),)),
    ]
    sched, mgr, cache, _ = sched_env(extra_cqs=extra, fair=True)
    sched_admitted(cache, "a1", "other-alpha",
                   [PodSet.build("main", 1, {"alpha-resource": "1"})],
                   {"main": {"alpha-resource": "default"}}, prio=0)
    sched_admitted(cache, "b1", "other-beta",
                   [PodSet.build("main", 1, {"beta-resource": "1"})],
                   {"main": {"beta-resource": "default"}}, prio=0)
    sched_admitted(cache, "c1", "other-gamma",
                   [PodSet.build("main", 1, {"cpu": "9"})],
                   {"main": {"cpu": "default"}}, prio=0)
    sched_pending(mgr, "preemptor", "other-alpha",
                  [PodSet.build("main", 1,
                                {"cpu": "3", "alpha-resource": "1"})],
                  prio=100)
    sched_pending(mgr, "pretending-preemptor", "other-beta",
                  [PodSet.build("main", 1,
                                {"cpu": "3", "beta-resource": "1"})],
                  prio=99)
    res = sched.schedule()
    victims = {
        t.workload.workload.name
        for e in res.preempting
        for t in e.preemption_targets
    }
    assert victims == {"a1", "c1"}
    assert res.skipped_preemptions.get("other-beta") == 1
    assert not res.skipped_preemptions.get("other-alpha")


class TestFairSharingCycleMore:
    """Two more fair-sharing cycle scenarios from the reference."""

    def test_lowest_drf_after_admission(self):  # :1681
        cohorts = [Cohort(name="A", resource_groups=(
            rg(FlavorQuotas.build("on-demand", {"cpu": "100"})),))]
        zero = {"cpu": ("0", None, None)}
        extra = [
            _strict("b", "A", [rg(FlavorQuotas.build("on-demand", zero))]),
            _strict("c", "A", [rg(FlavorQuotas.build("on-demand", zero))]),
        ]
        sched, mgr, cache, _ = sched_env(
            extra_cqs=extra, cohorts=cohorts, fair=True)
        sched_admitted(cache, "b0", "b", [PodSet.build("one", 1, {"cpu": "10"})],
                       {"one": {"cpu": "on-demand"}})
        sched_pending(mgr, "b1", "b", [PodSet.build("one", 1, {"cpu": "50"})])
        sched_pending(mgr, "c1", "c", [PodSet.build("one", 1, {"cpu": "75"})])
        res = sched.schedule()
        # b0+b1 = 60 < c1's 75: b ends with the lower share, so b1 wins
        assert admitted_names(res) == ["b1"]
        assert "ns/c1" in mgr.cluster_queues["c"].heap.keys()

    def test_singleton_cqs_and_no_cohort(self):  # :1751
        cohorts = [
            Cohort(name="A", resource_groups=(
                rg(FlavorQuotas.build("on-demand", {"cpu": "10"})),)),
            Cohort(name="B"),
        ]
        extra = [
            _strict("a", "A", [rg(FlavorQuotas.build(
                "on-demand", {"cpu": ("0", None, None)}))]),
            _strict("b", "B", [rg(FlavorQuotas.build("on-demand", {"cpu": "10"}))]),
            _strict("c", None, [rg(FlavorQuotas.build("on-demand", {"cpu": "10"}))]),
        ]
        sched, mgr, cache, _ = sched_env(
            extra_cqs=extra, cohorts=cohorts, fair=True)
        for cq in ("a", "b", "c"):
            sched_pending(mgr, f"{cq}1", cq,
                          [PodSet.build("one", 1, {"cpu": "10"})])
        res = sched.schedule()
        assert admitted_names(res) == ["a1", "b1", "c1"]


def test_no_overadmission_while_borrowing():  # :939
    """An existing gamma borrower holds 51 on-demand (1 over nominal
    via borrowing): beta's 50-pod head and alpha's 1-pod head admit on
    the cohort's remaining capacity while gamma's 50-pod head must NOT
    overadmit and parks."""
    prem = Preemption(
        within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
        reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
    )
    gamma = ClusterQueue(
        name="eng-gamma", cohort="eng", namespace_selector={},
        resource_groups=(rg(
            FlavorQuotas.build("on-demand", {"cpu": ("50", "10", None)}),
            FlavorQuotas.build("spot", {"cpu": ("0", "100", None)}),
        ),),
        preemption=prem,
    )
    sched, mgr, cache, _ = sched_env(extra_cqs=[gamma])
    sched_admitted(
        cache, "existing", "eng-gamma",
        [PodSet.build("borrow-on-demand", 51, {"cpu": "1"}),
         PodSet.build("use-all-spot", 100, {"cpu": "1"})],
        {"borrow-on-demand": {"cpu": "on-demand"},
         "use-all-spot": {"cpu": "spot"}},
    )
    sched_pending(mgr, "new", "eng-beta",
                  [PodSet.build("one", 50, {"cpu": "1"})], t=NOW - 2)
    sched_pending(mgr, "new-alpha", "eng-alpha",
                  [PodSet.build("one", 1, {"cpu": "1"})], t=NOW - 1)
    sched_pending(mgr, "new-gamma", "eng-gamma",
                  [PodSet.build("one", 50, {"cpu": "1"})], t=NOW)
    res = sched.schedule()
    assert admitted_names(res) == ["new", "new-alpha"]
    assert not res.skipped_preemptions


class TestSchedulerPartialAdmission:
    """Partial admission through the real cycle (scheduler_test.go)."""

    def _admitted_counts(self, cache, cq, wl_name):
        wl = cache.cluster_queues[cq].workloads[f"ns/{wl_name}"]
        return {psa.name: psa.count for psa in wl.admission.pod_set_assignments}

    def test_partial_admission_single_variable_pod_set(self):  # :1060
        sched, mgr, cache, _ = sched_env()
        sched_pending(mgr, "new", "sales",
                      [PodSet.build("one", 50, {"cpu": "2"}, min_count=20)])
        res = sched.schedule()
        assert admitted_names(res) == ["new"]
        # 50-cpu quota / 2 cpu per pod -> exactly 25 of the 50 pods
        assert self._admitted_counts(cache, "sales", "new") == {"one": 25}

    def test_partial_admission_preempt_first(self):  # :1089
        sched, mgr, cache, _ = sched_env()
        sched_admitted(cache, "old", "eng-beta",
                       [PodSet.build("one", 10, {"example.com/gpu": "1"})],
                       {"one": {"example.com/gpu": "model-a"}}, prio=-4)
        sched_pending(mgr, "new", "eng-beta",
                      [PodSet.build("one", 20, {"example.com/gpu": "1"},
                                    min_count=10)], prio=4)
        res = sched.schedule()
        # preemption beats scaling down: the old workload is evicted
        # and the new one waits for the eviction round-trip
        victims = {
            t.workload.workload.name
            for e in res.preempting
            for t in e.preemption_targets
        }
        assert victims == {"old"}
        assert admitted_names(res) == []

    def test_partial_admission_multiple_variable_pod_sets(self):  # :1169
        sched, mgr, cache, _ = sched_env()
        sched_pending(mgr, "new", "sales", [
            PodSet.build("one", 20, {"cpu": "1"}),
            PodSet.build("two", 30, {"cpu": "1"}, min_count=10),
            PodSet.build("three", 15, {"cpu": "1"}, min_count=5),
        ])
        res = sched.schedule()
        assert admitted_names(res) == ["new"]
        assert self._admitted_counts(cache, "sales", "new") == {
            "one": 20, "two": 20, "three": 10,
        }


class TestSchedulerResourceValidation:
    """scheduler_test.go: workloads failing in-cycle resource
    validation park with a Pending event (nominate-time LimitRange and
    requests<=limits checks, scheduler.go:361-369)."""

    def _runtime(self):
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.models import LocalQueue as LQ
        from kueue_tpu.utils.clock import FakeClock

        rt = ClusterRuntime(clock=FakeClock(1000.0))
        rt.add_flavor(ResourceFlavor(name="default"))
        rt.add_cluster_queue(ClusterQueue(
            name="sales", namespace_selector={},
            resource_groups=(rg(FlavorQuotas.build("default", {"cpu": "50"})),),
        ))
        rt.add_local_queue(LQ(namespace="sales", name="main",
                              cluster_queue="sales"))
        return rt

    def test_container_violates_limit_range(self):  # :2579
        from kueue_tpu.core.limit_range import LimitRange, LimitRangeItem

        rt = self._runtime()
        rt.limit_ranges["sales/alpha"] = LimitRange(
            name="alpha", namespace="sales",
            items=(LimitRangeItem.build(max={"cpu": "300m"}),),
        )
        wl = Workload(
            namespace="sales", name="new", queue_name="main",
            pod_sets=(PodSet.build("one", 1, {"cpu": "500m"}),),
        )
        rt.add_workload(wl)
        rt.schedule_once()
        assert wl.admission is None
        assert any(
            e.object_key == "sales/new" and "Pending" in e.kind
            for e in rt.events
        )

    def test_requests_exceed_limits(self):  # :2613
        rt = self._runtime()
        wl = Workload(
            namespace="sales", name="new", queue_name="main",
            pod_sets=(PodSet.build("one", 1, {"cpu": "200m"},
                                   limits={"cpu": "100m"}),),
        )
        rt.add_workload(wl)
        rt.schedule_once()
        assert wl.admission is None
        assert any(
            e.object_key == "sales/new"
            and "exceed" in e.message
            for e in rt.events
        )


def run_preemption_drain(admitted, incoming_reqs, target_cq, prio=0,
                         creation=NOW):
    """The DRAIN twin of run_preemption: the incoming head goes through
    run_drain_preempt against the same fixture cluster, and the evicted
    set is the truth-table victim set (the drain's per-cycle semantics
    must reproduce the reference preemption tables end to end — victim
    classification here is the kernel's own, not a forced assignment)."""
    from kueue_tpu.core.drain import run_drain_preempt
    from kueue_tpu.core.queue_manager import QueueManager, queue_order_timestamp
    from kueue_tpu.models import LocalQueue

    cache = preempt_env(admitted)
    mgr = QueueManager(FakeClock(start=NOW + 100))
    for cq in fixture_cqs():
        mgr.add_cluster_queue(cq)
        mgr.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{cq.name}", cluster_queue=cq.name)
        )
    wl = Workload(
        namespace="ns", name="in", queue_name=f"lq-{target_cq}",
        priority=prio, creation_time=creation,
        pod_sets=(PodSet.build("main", 1, incoming_reqs),),
    )
    mgr.add_or_update_workload(wl)
    pending = [
        (w, cq_name)
        for cq_name, pq in mgr.cluster_queues.items()
        for w in pq.snapshot_sorted()
    ]
    outcome = run_drain_preempt(
        take_snapshot(cache), pending, cache.flavors,
        timestamp_fn=lambda w: queue_order_timestamp(w, mgr._ts_policy),
    )
    assert not outcome.fallback
    admitted = {w.name for w, _, _, _ in outcome.admitted}
    return {w.name for w, _, _ in outcome.preempted}, admitted


class TestPreemptionDrainParity:
    """The same preemption_test.go tables, decided by the device DRAIN
    (ops/drain_kernel.solve_drain_preempt) instead of the host
    Preemptor — victim sets must match the reference expectations."""

    def test_preempt_lowest_priority(self):  # :289
        got, admitted = run_preemption_drain(
            [("low", "standalone", {"cpu": "2"}, {"cpu": "default"}, -1, NOW),
             ("mid", "standalone", {"cpu": "2"}, {"cpu": "default"}, 0, NOW),
             ("high", "standalone", {"cpu": "2"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "2"}, "standalone", prio=1)
        assert got == {"low"}
        assert "in" in admitted

    def test_preempt_multiple(self):  # :329
        got, admitted = run_preemption_drain(
            [("low", "standalone", {"cpu": "2"}, {"cpu": "default"}, -1, NOW),
             ("mid", "standalone", {"cpu": "2"}, {"cpu": "default"}, 0, NOW),
             ("high", "standalone", {"cpu": "2"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "3"}, "standalone", prio=1)
        assert got == {"low", "mid"}
        assert "in" in admitted

    def test_no_preemption_for_low_priority(self):  # :370
        got, admitted = run_preemption_drain(
            [("low", "standalone", {"cpu": "3"}, {"cpu": "default"}, -1, NOW),
             ("mid", "standalone", {"cpu": "3"}, {"cpu": "default"}, 0, NOW)],
            {"cpu": "1"}, "standalone", prio=-1)
        assert got == set()
        assert "in" not in admitted  # parks: nobody to preempt

    def test_minimal_set_excludes_low_priority(self):  # :471
        got, admitted = run_preemption_drain(
            [("low", "standalone", {"cpu": "1"}, {"cpu": "default"}, -1, NOW),
             ("mid", "standalone", {"cpu": "2"}, {"cpu": "default"}, 0, NOW),
             ("high", "standalone", {"cpu": "3"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "2"}, "standalone", prio=1)
        assert got == {"mid"}
        assert "in" in admitted

    def test_reclaim_quota_from_borrower(self):  # :556
        got, admitted = run_preemption_drain(
            [("c1-low", "c1", {"cpu": "3"}, {"cpu": "default"}, -1, NOW),
             ("c2-mid", "c2", {"cpu": "3"}, {"cpu": "default"}, 0, NOW),
             ("c2-high", "c2", {"cpu": "6"}, {"cpu": "default"}, 1, NOW)],
            {"cpu": "3"}, "c1", prio=1)
        assert got == {"c2-mid"}
        assert "in" in admitted

    def test_no_workloads_borrowing(self):  # :633
        got, admitted = run_preemption_drain(
            [("c1-high", "c1", {"cpu": "4"}, {"cpu": "default"}, 1, NOW),
             ("c2-low-1", "c2", {"cpu": "4"}, {"cpu": "default"}, -1, NOW)],
            {"cpu": "4"}, "c1", prio=1)
        assert got == set()
        # nothing to reclaim, but the cohort still has free capacity:
        # the head admits by borrowing (preemption_test.go:633 runs the
        # search in isolation; the drain runs the full cycle)
        assert "in" in admitted

    def test_no_reclaim_same_priority_for_lower_priority_policy(self):  # :920
        got, admitted = run_preemption_drain(
            [("c1", "c1", {"cpu": "2"}, {"cpu": "default"}, 0, NOW),
             ("c2-1", "c2", {"cpu": "4"}, {"cpu": "default"}, 0, NOW),
             ("c2-2", "c2", {"cpu": "4"}, {"cpu": "default"}, 0, NOW)],
            {"cpu": "4"}, "c1", prio=0)
        assert got == set()
        assert "in" not in admitted  # parks: same-prio, LowerPriority policy
