"""Metrics, visibility, config and debugger tests."""

import signal

import pytest

from kueue_tpu import features
from kueue_tpu.config import Configuration, load_config, runtime_from_config
from kueue_tpu.debugger import dump
from kueue_tpu.metrics.registry import Counter, Gauge, Histogram, Registry
from kueue_tpu.visibility import pending_workloads_in_cq, pending_workloads_in_lq
from kueue_tpu.models import ClusterQueue, LocalQueue, ResourceFlavor
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.controllers.jobs import BatchJob
from kueue_tpu.utils.clock import FakeClock


class TestRegistry:
    def test_counter_and_labels(self):
        r = Registry()
        c = r.counter("kueue_test_total", "help text", ("result",))
        c.inc(result="success")
        c.inc(2, result="success")
        c.inc(result="inadmissible")
        assert c.value(result="success") == 3
        text = r.expose()
        assert '# TYPE kueue_test_total counter' in text
        assert 'kueue_test_total{result="success"} 3' in text

    def test_label_mismatch_rejected(self):
        c = Counter("x", "h", ("a",))
        with pytest.raises(ValueError):
            c.inc(b="nope")

    def test_gauge_set(self):
        g = Gauge("g", "h", ("q",))
        g.set(5, q="cq")
        g.dec(2, q="cq")
        assert g.value(q="cq") == 3

    def test_histogram_buckets(self):
        h = Histogram("h", "help", (), buckets=(0.1, 1, 10))
        for v in (0.05, 0.5, 5, 50):
            h.observe(v)
        text = "\n".join(h.collect())
        assert 'h_bucket{le="0.1"} 1' in text
        assert 'h_bucket{le="1"} 2' in text
        assert 'h_bucket{le="10"} 3' in text
        assert 'h_bucket{le="+Inf"} 4' in text
        assert h.count() == 4


def run_scenario():
    clock = FakeClock(1000.0)
    rt = ClusterRuntime(clock=clock)
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq", namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": "2"}),)),
            ),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    jobs = []
    for i in range(4):
        j = BatchJob.build("ns", f"j{i}", "lq", parallelism=1, requests={"cpu": "1"})
        rt.add_job(j)
        jobs.append(j)
        clock.advance(1.0)
        rt.run_until_idle()
    return rt, jobs, clock


class TestRuntimeMetrics:
    def test_admission_metrics_reported(self):
        rt, jobs, clock = run_scenario()
        m = rt.metrics
        assert m.admitted_workloads_total.value(cluster_queue="cq") == 2
        assert m.quota_reserved_workloads_total.value(cluster_queue="cq") == 2
        assert m.pending_workloads.value(cluster_queue="cq", status="inadmissible") == 2
        assert m.reserving_active_workloads.value(cluster_queue="cq") == 2
        assert m.admission_attempts_total.value(result="success") >= 2
        text = m.registry.expose()
        assert "kueue_admission_attempt_duration_seconds_bucket" in text

    def test_eviction_metric(self):
        rt, jobs, clock = run_scenario()
        wl = rt.workloads["ns/job-j0"]
        wl.active = False
        rt.run_until_idle()
        assert (
            rt.metrics.evicted_workloads_total.value(
                cluster_queue="cq", reason="Deactivated"
            )
            == 1
        )


class TestVisibility:
    def test_cq_summary_positions(self):
        rt, jobs, clock = run_scenario()
        summary = pending_workloads_in_cq(rt.queues, "cq")
        names = [pw.name for pw in summary.items]
        assert names == ["job-j2", "job-j3"]
        assert [pw.position_in_cluster_queue for pw in summary.items] == [0, 1]
        assert [pw.position_in_local_queue for pw in summary.items] == [0, 1]

    def test_lq_summary(self):
        rt, jobs, clock = run_scenario()
        summary = pending_workloads_in_lq(rt.queues, "ns", "lq")
        assert len(summary.items) == 2
        assert pending_workloads_in_lq(rt.queues, "ns", "nope").items == []

    def test_offset_limit(self):
        rt, jobs, clock = run_scenario()
        summary = pending_workloads_in_cq(rt.queues, "cq", offset=1, limit=1)
        assert [pw.name for pw in summary.items] == ["job-j3"]


class TestConfig:
    def test_defaults(self):
        cfg = load_config({})
        assert cfg.namespace == "kueue-system"
        assert cfg.integrations_frameworks == ("batch/job",)
        assert not cfg.wait_for_pods_ready.enable
        assert cfg.multikueue.worker_lost_timeout_seconds == 900

    def test_full_decode(self):
        cfg = load_config({
            "namespace": "custom",
            "manageJobsWithoutQueueName": True,
            "waitForPodsReady": {
                "enable": True, "timeout": 120,
                "requeuingStrategy": {"backoffLimitCount": 5, "backoffBaseSeconds": 10},
            },
            "integrations": {"frameworks": ["batch/job", "pod"]},
            "fairSharing": {"enable": True},
            "featureGates": {"TopologyAwareScheduling": True},
        })
        assert cfg.wait_for_pods_ready.backoff_limit_count == 5
        assert cfg.fair_sharing.enable
        rt = runtime_from_config(cfg, clock=FakeClock(0.0))
        assert rt.scheduler.fair_sharing
        assert features.enabled("TopologyAwareScheduling")
        features.gates.set("TopologyAwareScheduling", False)  # restore

    def test_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="unknown configuration keys"):
            load_config({"nope": 1})

    def test_unknown_framework_rejected(self):
        with pytest.raises(ValueError, match="unknown integration framework"):
            load_config({"integrations": {"frameworks": ["bogus/kind"]}})

    def test_invalid_pods_ready_timeout(self):
        with pytest.raises(ValueError, match="timeout must be positive"):
            load_config({"waitForPodsReady": {"enable": True, "timeout": -1}})

    def test_unknown_feature_gate(self):
        with pytest.raises(ValueError, match="unknown feature gate"):
            load_config({"featureGates": {"NoSuchGate": True}})


class TestDebugger:
    def test_dump_renders_state(self):
        rt, jobs, clock = run_scenario()
        text = dump(rt)
        assert "ClusterQueue cq" in text
        assert "admitted=2" in text
        assert "inadmissible: " in text
        assert "usage: default/cpu=2000" in text


class TestCycleTracing:
    """Per-cycle phase attribution (the pprof/log-attribution analog)."""

    def _runtime_with_work(self):
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.models import (
            ClusterQueue,
            FlavorQuotas,
            LocalQueue,
            ResourceFlavor,
            Workload,
        )
        from kueue_tpu.models.cluster_queue import ResourceGroup
        from kueue_tpu.models.workload import PodSet

        rt = ClusterRuntime()
        rt.add_flavor(ResourceFlavor(name="default"))
        rt.add_cluster_queue(
            ClusterQueue(
                name="cq", namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",), (FlavorQuotas.build("default", {"cpu": "8"}),)
                    ),
                ),
            )
        )
        rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
        for i in range(3):
            rt.add_workload(
                Workload(
                    namespace="ns", name=f"w{i}", queue_name="lq",
                    pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
                )
            )
        return rt

    def test_traces_recorded_and_observed(self):
        rt = self._runtime_with_work()
        rt.run_until_idle()
        traces = list(rt.scheduler.last_traces)
        assert traces
        t = traces[0]
        assert t.heads >= 1 and t.admitted >= 1
        assert set(t.spans) >= {"snapshot", "nominate", "admit"}
        assert t.total_s > 0
        d = t.to_dict()
        assert d["spansMs"]["nominate"] >= 0
        # histogram observed per phase
        h = rt.metrics.admission_cycle_phase_duration_seconds
        assert h.count(phase="nominate") >= 1
        assert h.count(phase="admit") >= 1

    def test_debugger_includes_traces(self):
        from kueue_tpu.debugger import dump

        rt = self._runtime_with_work()
        rt.run_until_idle()
        text = dump(rt)
        assert "recent cycles" in text and "nominate=" in text

    def test_server_debug_endpoint(self):
        from kueue_tpu.server import KueueClient, KueueServer

        rt = self._runtime_with_work()
        srv = KueueServer(runtime=rt)
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            client.reconcile()
            out = client._request("GET", "/debug/cycles")
            assert out["cycles"]
            assert "spansMs" in out["cycles"][0]
        finally:
            srv.stop()


def test_queue_visibility_snapshots_gated():
    """Deprecated QueueVisibility: gated CQ-status snapshots of the top
    pending heads (clusterqueue_controller.go snapshot worker)."""
    from kueue_tpu.controllers import ClusterRuntime
    from kueue_tpu.features import override
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.workload import PodSet

    rt = ClusterRuntime()
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq", namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": "2"}),)),
            ),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    for i in range(4):
        rt.add_workload(
            Workload(
                namespace="ns", name=f"w{i}", queue_name="lq",
                priority=i, creation_time=float(i),
                pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
            )
        )
    rt.run_until_idle()
    assert rt.cq_pending_snapshots == {}  # gate off by default
    with override("QueueVisibility", True):
        rt.queue_visibility_max_count = 2
        rt.reconcile_once()
        snap = rt.cq_pending_snapshots["cq"]
        assert len(snap) == 2  # truncated to maxCount
        # highest-priority pending head first
        assert snap[0]["positionInClusterQueue"] == 0
    # disabling the gate clears stale data on the next pass
    rt.reconcile_once()
    assert rt.cq_pending_snapshots == {}
