"""Metrics, visibility, config, debugger and event-pipeline tests."""

import re
import signal
import threading
import time

import pytest

from kueue_tpu import features
from kueue_tpu.config import Configuration, load_config, runtime_from_config
from kueue_tpu.debugger import dump
from kueue_tpu.metrics.registry import Counter, Gauge, Histogram, Registry
from kueue_tpu.visibility import pending_workloads_in_cq, pending_workloads_in_lq
from kueue_tpu.models import ClusterQueue, LocalQueue, ResourceFlavor
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.controllers.jobs import BatchJob
from kueue_tpu.utils.clock import FakeClock


class TestRegistry:
    def test_counter_and_labels(self):
        r = Registry()
        c = r.counter("kueue_test_total", "help text", ("result",))
        c.inc(result="success")
        c.inc(2, result="success")
        c.inc(result="inadmissible")
        assert c.value(result="success") == 3
        text = r.expose()
        assert '# TYPE kueue_test_total counter' in text
        assert 'kueue_test_total{result="success"} 3' in text

    def test_label_mismatch_rejected(self):
        c = Counter("x", "h", ("a",))
        with pytest.raises(ValueError):
            c.inc(b="nope")

    def test_gauge_set(self):
        g = Gauge("g", "h", ("q",))
        g.set(5, q="cq")
        g.dec(2, q="cq")
        assert g.value(q="cq") == 3

    def test_histogram_buckets(self):
        h = Histogram("h", "help", (), buckets=(0.1, 1, 10))
        for v in (0.05, 0.5, 5, 50):
            h.observe(v)
        text = "\n".join(h.collect())
        assert 'h_bucket{le="0.1"} 1' in text
        assert 'h_bucket{le="1"} 2' in text
        assert 'h_bucket{le="10"} 3' in text
        assert 'h_bucket{le="+Inf"} 4' in text
        assert h.count() == 4


def run_scenario():
    clock = FakeClock(1000.0)
    rt = ClusterRuntime(clock=clock)
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq", namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": "2"}),)),
            ),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    jobs = []
    for i in range(4):
        j = BatchJob.build("ns", f"j{i}", "lq", parallelism=1, requests={"cpu": "1"})
        rt.add_job(j)
        jobs.append(j)
        clock.advance(1.0)
        rt.run_until_idle()
    return rt, jobs, clock


class TestRuntimeMetrics:
    def test_admission_metrics_reported(self):
        rt, jobs, clock = run_scenario()
        m = rt.metrics
        assert m.admitted_workloads_total.value(cluster_queue="cq") == 2
        assert m.quota_reserved_workloads_total.value(cluster_queue="cq") == 2
        assert m.pending_workloads.value(cluster_queue="cq", status="inadmissible") == 2
        assert m.reserving_active_workloads.value(cluster_queue="cq") == 2
        assert m.admission_attempts_total.value(result="success") >= 2
        text = m.registry.expose()
        assert "kueue_admission_attempt_duration_seconds_bucket" in text

    def test_eviction_metric(self):
        rt, jobs, clock = run_scenario()
        wl = rt.workloads["ns/job-j0"]
        wl.active = False
        rt.run_until_idle()
        assert (
            rt.metrics.evicted_workloads_total.value(
                cluster_queue="cq", reason="Deactivated"
            )
            == 1
        )


class TestVisibility:
    def test_cq_summary_positions(self):
        rt, jobs, clock = run_scenario()
        summary = pending_workloads_in_cq(rt.queues, "cq")
        names = [pw.name for pw in summary.items]
        assert names == ["job-j2", "job-j3"]
        assert [pw.position_in_cluster_queue for pw in summary.items] == [0, 1]
        assert [pw.position_in_local_queue for pw in summary.items] == [0, 1]

    def test_lq_summary(self):
        rt, jobs, clock = run_scenario()
        summary = pending_workloads_in_lq(rt.queues, "ns", "lq")
        assert len(summary.items) == 2
        assert pending_workloads_in_lq(rt.queues, "ns", "nope").items == []

    def test_offset_limit(self):
        rt, jobs, clock = run_scenario()
        summary = pending_workloads_in_cq(rt.queues, "cq", offset=1, limit=1)
        assert [pw.name for pw in summary.items] == ["job-j3"]


class TestConfig:
    def test_defaults(self):
        cfg = load_config({})
        assert cfg.namespace == "kueue-system"
        assert cfg.integrations_frameworks == ("batch/job",)
        assert not cfg.wait_for_pods_ready.enable
        assert cfg.multikueue.worker_lost_timeout_seconds == 900

    def test_full_decode(self):
        cfg = load_config({
            "namespace": "custom",
            "manageJobsWithoutQueueName": True,
            "waitForPodsReady": {
                "enable": True, "timeout": 120,
                "requeuingStrategy": {"backoffLimitCount": 5, "backoffBaseSeconds": 10},
            },
            "integrations": {"frameworks": ["batch/job", "pod"]},
            "fairSharing": {"enable": True},
            "featureGates": {"TopologyAwareScheduling": True},
        })
        assert cfg.wait_for_pods_ready.backoff_limit_count == 5
        assert cfg.fair_sharing.enable
        rt = runtime_from_config(cfg, clock=FakeClock(0.0))
        assert rt.scheduler.fair_sharing
        assert features.enabled("TopologyAwareScheduling")
        features.gates.set("TopologyAwareScheduling", False)  # restore

    def test_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="unknown configuration keys"):
            load_config({"nope": 1})

    def test_unknown_framework_rejected(self):
        with pytest.raises(ValueError, match="unknown integration framework"):
            load_config({"integrations": {"frameworks": ["bogus/kind"]}})

    def test_invalid_pods_ready_timeout(self):
        with pytest.raises(ValueError, match="timeout must be positive"):
            load_config({"waitForPodsReady": {"enable": True, "timeout": -1}})

    def test_unknown_feature_gate(self):
        with pytest.raises(ValueError, match="unknown feature gate"):
            load_config({"featureGates": {"NoSuchGate": True}})


class TestDebugger:
    def test_dump_renders_state(self):
        rt, jobs, clock = run_scenario()
        text = dump(rt)
        assert "ClusterQueue cq" in text
        assert "admitted=2" in text
        assert "inadmissible: " in text
        assert "usage: default/cpu=2000" in text


class TestCycleTracing:
    """Per-cycle phase attribution (the pprof/log-attribution analog)."""

    def _runtime_with_work(self):
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.models import (
            ClusterQueue,
            FlavorQuotas,
            LocalQueue,
            ResourceFlavor,
            Workload,
        )
        from kueue_tpu.models.cluster_queue import ResourceGroup
        from kueue_tpu.models.workload import PodSet

        rt = ClusterRuntime()
        rt.add_flavor(ResourceFlavor(name="default"))
        rt.add_cluster_queue(
            ClusterQueue(
                name="cq", namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",), (FlavorQuotas.build("default", {"cpu": "8"}),)
                    ),
                ),
            )
        )
        rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
        for i in range(3):
            rt.add_workload(
                Workload(
                    namespace="ns", name=f"w{i}", queue_name="lq",
                    pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
                )
            )
        return rt

    def test_traces_recorded_and_observed(self):
        rt = self._runtime_with_work()
        rt.run_until_idle()
        traces = list(rt.scheduler.last_traces)
        assert traces
        t = traces[0]
        assert t.heads >= 1 and t.admitted >= 1
        assert set(t.spans) >= {"snapshot", "nominate", "admit"}
        assert t.total_s > 0
        d = t.to_dict()
        assert d["spansMs"]["nominate"] >= 0
        # histogram observed per phase
        h = rt.metrics.admission_cycle_phase_duration_seconds
        assert h.count(phase="nominate") >= 1
        assert h.count(phase="admit") >= 1

    def test_debugger_includes_traces(self):
        from kueue_tpu.debugger import dump

        rt = self._runtime_with_work()
        rt.run_until_idle()
        text = dump(rt)
        assert "recent cycles" in text and "nominate=" in text

    def test_server_debug_endpoint(self):
        from kueue_tpu.server import KueueClient, KueueServer

        rt = self._runtime_with_work()
        srv = KueueServer(runtime=rt)
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            client.reconcile()
            out = client._request("GET", "/debug/cycles")
            assert out["cycles"]
            assert "spansMs" in out["cycles"][0]
        finally:
            srv.stop()


class TestEventRecorder:
    """K8s-style recorder: series dedup, bounded ring, monotone
    resourceVersion resume (core/events.py)."""

    def _rec(self, **kw):
        from kueue_tpu.core.events import EventRecorder

        return EventRecorder(clock=FakeClock(100.0), **kw)

    def test_dedup_bumps_count_and_restamps(self):
        rec = self._rec()
        e1 = rec.record("Pending", "ns/w1", "no quota")
        assert (e1.count, e1.resource_version) == (1, 1)
        e2 = rec.record("Pending", "ns/w1", "no quota")
        assert e2 is e1  # same series entry, not a duplicate
        assert e2.count == 2
        assert e2.resource_version == 2  # restamped: watchers re-deliver
        assert len(rec) == 1
        # a different message is a different series
        rec.record("Pending", "ns/w1", "other reason")
        assert len(rec) == 2

    def test_ring_bound_evicts_oldest_and_flags_resume_gap(self):
        rec = self._rec(ring_size=4)
        for i in range(6):
            rec.record("Admitted", f"ns/w{i}")
        assert len(rec) == 4
        assert [e.object_key for e in rec] == [
            "ns/w2", "ns/w3", "ns/w4", "ns/w5"
        ]
        # rv=1 predates the ring: the client must relist
        items, too_old = rec.since(1)
        assert too_old
        # rv=2 is exactly the newest evicted version: everything after
        # it is still in the ring — no gap
        items, too_old = rec.since(2)
        assert not too_old
        assert [i["resourceVersion"] for i in items] == [3, 4, 5, 6]

    def test_resource_version_resume_is_exact_suffix(self):
        rec = self._rec()
        for i in range(5):
            rec.record("Admitted", f"ns/w{i}")
        items, too_old = rec.since(3)
        assert not too_old
        assert [i["resourceVersion"] for i in items] == [4, 5]
        assert [i["object"] for i in items] == ["ns/w3", "ns/w4"]
        # a dedup bump re-delivers the bumped event past any resume point
        rec.record("Admitted", "ns/w0")
        items, _ = rec.since(5)
        assert [(i["object"], i["count"]) for i in items] == [("ns/w0", 2)]

    def test_wait_unblocks_on_record(self):
        rec = self._rec()
        out = {}

        def waiter():
            out["r"] = rec.wait(0, timeout=10.0)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        rec.record("Admitted", "ns/w0")
        t.join(timeout=5)
        assert not t.is_alive()
        items, latest, too_old = out["r"]
        assert latest == 1 and not too_old
        assert items[0]["reason"] == "Admitted"


def _watch_runtime():
    rt = ClusterRuntime()
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq", namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": "8"}),)),
            ),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    return rt


def _workload_dict(name="w1", cpu="2"):
    from kueue_tpu import serialization as ser
    from kueue_tpu.models import Workload
    from kueue_tpu.models.workload import PodSet

    return ser.workload_to_dict(
        Workload(
            namespace="ns", name=name, queue_name="lq",
            pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
        )
    )


def _drive_watch(client, subscribe):
    """Subscribe (parked server-side — NO client polling loop), then
    admit a workload and assert the Admitted event is PUSHED to the
    subscriber with a monotone resourceVersion."""
    rv0 = client.events()["resourceVersion"]
    got = []

    def consume():
        for ev in subscribe(rv0):
            got.append(ev)
            if ev["reason"] == "Admitted":
                return

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.2)  # let the subscriber park in the server
    client.apply("workloads", _workload_dict())
    t.join(timeout=15)
    assert not t.is_alive(), "subscriber never received the Admitted event"
    reasons = [e["reason"] for e in got]
    assert "Admitted" in reasons
    rvs = [e["resourceVersion"] for e in got]
    assert rvs == sorted(rvs) and len(set(rvs)) == len(rvs), (
        f"resourceVersions not strictly monotone: {rvs}"
    )
    assert all(rv > rv0 for rv in rvs)


class TestEventWatch:
    """VERDICT next #8 done-criterion: an admission event reaches a
    watch/SSE subscriber with no polling loop in the test."""

    def test_admitted_event_over_watch_plaintext(self):
        from kueue_tpu.server import KueueClient, KueueServer

        srv = KueueServer(runtime=_watch_runtime())
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            _drive_watch(
                client, lambda rv: client.watch("events", resource_version=rv)
            )
        finally:
            srv.stop()

    def test_admitted_event_over_sse_plaintext(self):
        from kueue_tpu.server import KueueClient, KueueServer

        srv = KueueServer(runtime=_watch_runtime())
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            _drive_watch(
                client, lambda rv: client.stream_events(resource_version=rv)
            )
        finally:
            srv.stop()

    def test_admitted_event_over_watch_tls(self, tmp_path):
        pytest.importorskip("cryptography")
        from kueue_tpu.server import KueueClient, KueueServer
        from kueue_tpu.utils.cert import CertRotator

        rot = CertRotator(str(tmp_path))
        srv = KueueServer(runtime=_watch_runtime(), tls=rot)
        port = srv.start()
        try:
            client = KueueClient(
                f"https://127.0.0.1:{port}", ca_cert=rot.ca_path
            )
            _drive_watch(
                client, lambda rv: client.watch("events", resource_version=rv)
            )
            # the SSE tail works over the same TLS connection machinery
            _drive_watch(
                client,
                lambda rv: client.stream_events(resource_version=rv),
            )
        finally:
            srv.stop()

    def test_watch_resume_after_gap_relists(self):
        """A resumer whose resourceVersion fell out of the ring gets
        410 server-side; the client generator relists and continues."""
        from kueue_tpu.server import KueueClient, KueueServer
        from kueue_tpu.server.client import ClientError

        rt = _watch_runtime()
        rt.events.ring_size = 4
        srv = KueueServer(runtime=rt)
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            for i in range(8):
                rt.events.record("Ping", f"ns/w{i}")
            with pytest.raises(ClientError) as ei:
                client._request(
                    "GET",
                    "/apis/kueue/v1beta1/events?watch=1&resourceVersion=1"
                    "&timeoutSeconds=1",
                )
            assert ei.value.status == 410
            # the generator swallows the 410 by relisting
            gen = client.watch("events", resource_version=1)
            ev = next(gen)
            assert ev["resourceVersion"] > 1
        finally:
            srv.stop()

    def test_events_list_route(self):
        from kueue_tpu.server import KueueClient, KueueServer

        srv = KueueServer(runtime=_watch_runtime())
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            client.apply("workloads", _workload_dict())
            out = client.events()
            assert out["resourceVersion"] >= 2
            reasons = {e["reason"] for e in out["items"]}
            assert {"QuotaReserved", "Admitted"} <= reasons
            # resume: nothing newer than the head
            again = client.events(out["resourceVersion"])
            assert again["items"] == []
        finally:
            srv.stop()


class TestEventMetricsMirror:
    def test_events_total_series(self):
        rt, jobs, clock = run_scenario()
        m = rt.metrics
        assert m.events_total.value(kind="Workload", reason="Admitted") == 2
        assert m.events_total.value(kind="Workload", reason="Pending") >= 1
        text = m.registry.expose()
        assert 'kueue_events_total{kind="Workload",reason="Admitted"} 2' in text
        assert "kueue_cycle_total" in text
        assert m.cycle_total.value(resolution="host") >= 1

    def test_drain_trace_phase_attribution(self):
        """The bulk-drain path's CycleTrace carries classify/solve/apply
        spans and device-vs-host attribution (served at /debug/cycles)."""
        from kueue_tpu.models import Workload
        from kueue_tpu.models.workload import PodSet

        rt = ClusterRuntime(bulk_drain_threshold=4)
        rt.add_flavor(ResourceFlavor(name="default"))
        rt.add_cluster_queue(
            ClusterQueue(
                name="cq", namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",), (FlavorQuotas.build("default", {"cpu": "64"}),)
                    ),
                ),
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
        )
        for i in range(8):
            rt.add_workload(
                Workload(
                    namespace="ns", name=f"w{i}", queue_name="lq",
                    creation_time=float(i),
                    pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
                )
            )
        rt.run_until_idle()
        drains = [
            t for t in rt.scheduler.last_traces if t.resolution == "drain"
        ]
        assert drains, "bulk drain never ran"
        t = drains[-1]
        # the pipelined loop (PR 7) adds prefetch/commit spans; round 1
        # additionally carries snapshot/classify attribution
        assert {"solve", "apply", "prefetch", "commit"} <= set(t.spans)
        assert set(drains[0].spans) >= {"snapshot", "classify"}
        assert t.device_s == pytest.approx(t.spans["solve"])
        assert t.host_s == pytest.approx(t.total_s - t.device_s)
        d = t.to_dict()
        assert d["deviceMs"] >= 0 and d["hostMs"] >= 0
        assert rt.metrics.cycle_total.value(resolution="drain") >= 1


# one Prometheus exposition line: name{labels} value
_SERIES_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r" (?:[+-]?(?:\d+(?:\.\d+)?(?:e[+-]?\d+)?|Inf|NaN))$"
)


class TestMetricsExposition:
    """Exposition-format lint: /metrics must stay parseable by a real
    Prometheus scraper (HELP/TYPE preamble, series grammar, histogram
    _bucket/_sum/_count invariants) so registry regressions fail fast."""

    def _labels_of(self, line):
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? ", line)
        assert m, line
        labels = {}
        if m.group(3):
            for part in re.findall(r'([a-zA-Z0-9_]+)="([^"]*)"', m.group(3)):
                labels[part[0]] = part[1]
        return m.group(1), labels

    def test_exposition_grammar_and_histogram_invariants(self):
        rt, jobs, clock = run_scenario()
        text = rt.metrics.registry.expose()
        assert text.endswith("\n")
        lines = text.splitlines()
        typed = {}  # base metric name -> declared type
        helped = set()
        current = None
        for ln in lines:
            if ln.startswith("# HELP "):
                helped.add(ln.split()[2])
                continue
            if ln.startswith("# TYPE "):
                _, _, name, kind = ln.split()
                assert kind in ("counter", "gauge", "histogram")
                typed[name] = kind
                current = name
                continue
            assert _SERIES_RE.match(ln), f"bad series line: {ln!r}"
            base = ln.split("{")[0].split(" ")[0]
            if typed.get(current) == "histogram":
                stripped = re.sub(r"_(bucket|sum|count)$", "", base)
                assert stripped == current, f"{base} outside {current} block"
            else:
                assert base == current, f"{base} outside {current} block"
        # every TYPE had a HELP
        assert set(typed) <= helped

        # histogram invariants per series: cumulative buckets, +Inf ==
        # _count, _sum/_count present
        for name, kind in typed.items():
            if kind != "histogram":
                continue
            buckets = {}  # label-key (minus le) -> [(le, v)]
            counts, sums = {}, {}
            for ln in lines:
                if ln.startswith("#") or " " not in ln:
                    continue
                base, labels = self._labels_of(ln)
                val = float(ln.rsplit(" ", 1)[1].replace("+Inf", "inf"))
                key = tuple(
                    sorted((k, v) for k, v in labels.items() if k != "le")
                )
                if base == f"{name}_bucket":
                    le = labels["le"]
                    buckets.setdefault(key, []).append(
                        (float("inf") if le == "+Inf" else float(le), val)
                    )
                elif base == f"{name}_count":
                    counts[key] = val
                elif base == f"{name}_sum":
                    sums[key] = val
            assert buckets, f"histogram {name} exposed no buckets"
            for key, bs in buckets.items():
                bs.sort()
                vals = [v for _, v in bs]
                assert vals == sorted(vals), (
                    f"{name}{dict(key)}: bucket counts not cumulative"
                )
                assert bs[-1][0] == float("inf")
                assert key in counts and key in sums, (
                    f"{name}{dict(key)}: missing _sum/_count"
                )
                assert bs[-1][1] == counts[key], (
                    f"{name}{dict(key)}: +Inf bucket != _count"
                )

    def test_metric_family_names_lint(self):
        """Static half of this exposition lint, promoted to the
        kueuelint ``metrics-families`` rule (kueue_tpu/analysis):
        family names must be kueue_-prefixed, grammar-valid and unique
        with non-empty HELP strings. The runtime grammar + histogram
        invariants stay in the tests above — they need a live
        registry, not an AST."""
        from kueue_tpu.analysis import lint

        offenders = lint(rules=["metrics-families"])
        assert not offenders, "\n".join(str(f) for f in offenders)

    def test_server_metrics_route_lints(self):
        from kueue_tpu.server import KueueClient, KueueServer

        srv = KueueServer(runtime=_watch_runtime())
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            client.apply("workloads", _workload_dict())
            text = client.metrics_text()
            assert "kueue_events_total" in text
            for ln in text.splitlines():
                if ln.startswith("#") or not ln:
                    continue
                assert _SERIES_RE.match(ln), f"bad series line: {ln!r}"
        finally:
            srv.stop()


def test_queue_visibility_snapshots_gated():
    """Deprecated QueueVisibility: gated CQ-status snapshots of the top
    pending heads (clusterqueue_controller.go snapshot worker)."""
    from kueue_tpu.controllers import ClusterRuntime
    from kueue_tpu.features import override
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.workload import PodSet

    rt = ClusterRuntime()
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq", namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": "2"}),)),
            ),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    for i in range(4):
        rt.add_workload(
            Workload(
                namespace="ns", name=f"w{i}", queue_name="lq",
                priority=i, creation_time=float(i),
                pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
            )
        )
    rt.run_until_idle()
    assert rt.cq_pending_snapshots == {}  # gate off by default
    with override("QueueVisibility", True):
        rt.queue_visibility_max_count = 2
        rt.reconcile_once()
        snap = rt.cq_pending_snapshots["cq"]
        assert len(snap) == 2  # truncated to maxCount
        # highest-priority pending head first
        assert snap[0]["positionInClusterQueue"] == 0
    # disabling the gate clears stale data on the next pass
    rt.reconcile_once()
    assert rt.cq_pending_snapshots == {}
