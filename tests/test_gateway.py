"""Gateway serving tier (kueue_tpu/gateway): write-path coalescing
(one serving-lock section + one group-committed journal sync + ONE
EventRecorder wake per flush window, decisions/journal sequences
bit-identical to the serial path), per-tenant token-bucket
backpressure with fair 429 + Retry-After shedding, apply_batch
partial-failure semantics, client 429 backoff, admission SLOs
(attainment + error-budget burn over the queue-to-admission
histogram), chaos at the new ``gateway.flush_mid_batch`` fault point,
and replica fan-out trees (replicas tailing replicas with hop count +
per-hop lag, converging byte-identically through compaction jumps and
leader handovers).
"""

import json
import threading
import time

import pytest

from kueue_tpu import serialization as ser
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.gateway import (
    GatewayThrottled,
    SLOTracker,
    TenantLimiter,
    TokenBucket,
    WriteGateway,
)
from kueue_tpu.gateway.ratelimit import tenant_key
from kueue_tpu.metrics import Metrics
from kueue_tpu.server import KueueServer
from kueue_tpu.server.client import ClientError, KueueClient
from kueue_tpu.storage import Journal, recover
from kueue_tpu.testing import faults
from kueue_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def cq_dict(name, quota="8"):
    return {
        "name": name,
        "namespaceSelector": {},
        "resourceGroups": [
            {
                "coveredResources": ["cpu"],
                "flavors": [
                    {
                        "name": "default",
                        "resources": [{"name": "cpu", "nominalQuota": quota}],
                    }
                ],
            }
        ],
    }


def wl_wire(name, cpu="1000m", queue="lq-0", ns="ns"):
    return {
        "namespace": ns, "name": name, "queueName": queue,
        "podSets": [{"name": "main", "count": 1,
                     "requests": {"cpu": cpu}}],
    }


def fresh_rt(clock_start=0.0):
    return ClusterRuntime(
        clock=FakeClock(clock_start), use_solver=False,
        bulk_drain_threshold=None,
    )


def seeded_server(tmp_path, name="journal", gateway=None, clock_start=0.0):
    """A journaled leader KueueServer (HTTP not started — the gateway
    and apply paths are driven directly) with one CQ/LQ configured."""
    rt = fresh_rt(clock_start)
    journal = Journal(str(tmp_path / name)).open()
    rt.attach_journal(journal)
    srv = KueueServer(runtime=rt, gateway=gateway)
    srv.apply("resourceflavors", {"name": "default"}, reconcile=False)
    srv.apply("clusterqueues", cq_dict("cq-0"), reconcile=False)
    srv.apply(
        "localqueues",
        {"namespace": "ns", "name": "lq-0", "clusterQueue": "cq-0"},
        reconcile=False,
    )
    rt.run_until_idle()
    return srv, rt, journal


def admitted_keys(rt):
    return sorted(k for k, w in rt.workloads.items() if w.is_admitted)


def journal_sequence(journal):
    """(type, data) stream — the bit-identical comparison key (seq/rv
    ride along implicitly: both runs start from the same base)."""
    return [(r.seq, r.rv, r.type, r.data) for r in journal.records(0)]


# ---- token buckets / tenant limiter ----
class TestTokenBucket:
    def test_burst_then_refill(self):
        clock = FakeClock(0.0)
        b = TokenBucket(rate_per_s=10.0, burst=2.0, clock=clock)
        assert b.try_take() == 0.0
        assert b.try_take() == 0.0
        retry = b.try_take()
        assert retry == pytest.approx(0.1)
        clock.advance(0.1)  # one token refilled
        assert b.try_take() == 0.0
        assert b.try_take() > 0.0

    def test_refill_caps_at_burst(self):
        clock = FakeClock(0.0)
        b = TokenBucket(rate_per_s=100.0, burst=3.0, clock=clock)
        clock.advance(1000.0)
        for _ in range(3):
            assert b.try_take() == 0.0
        assert b.try_take() > 0.0

    def test_rejects_nonpositive_rate(self):
        with pytest.raises(ValueError):
            TokenBucket(rate_per_s=0.0, burst=1.0)


class TestTenantLimiter:
    def test_flooding_tenant_shed_others_unaffected(self):
        clock = FakeClock(0.0)
        lim = TenantLimiter(rate_per_s=1.0, burst=2.0, clock=clock)
        assert lim.check("ns/flood") == 0.0
        assert lim.check("ns/flood") == 0.0
        assert lim.check("ns/flood") > 0.0  # budget spent
        # fairness: an unrelated tenant's bucket is untouched
        assert lim.check("ns/quiet") == 0.0

    def test_lru_bound(self):
        clock = FakeClock(0.0)
        lim = TenantLimiter(rate_per_s=1.0, burst=1.0, clock=clock,
                            max_tenants=2)
        for t in ("a", "b", "c"):
            lim.check(t)
        assert lim.status()["tenants"] == 2

    def test_tenant_key_mapping(self):
        assert tenant_key("workloads", {"namespace": "ns", "queueName": "q"}) \
            == "ns/q"
        assert tenant_key("workloads", {"namespace": "ns"}) == "ns"
        assert tenant_key("localqueues", {"namespace": "ns", "name": "q"}) \
            == "ns"
        assert tenant_key("clusterqueues", {"name": "cq"}) == "_config"


# ---- coalescing correctness (the bit-identical oracle) ----
class TestCoalescingDeterminism:
    N = 6

    def _workload_seq(self):
        # mixed batch: config object first, then workloads that use it
        return [("workloads", wl_wire(f"w-{i}")) for i in range(self.N)]

    def test_flush_bit_identical_to_serial_path(self, tmp_path):
        """The oracle: the SAME arrival window applied (a) through the
        serial batched route (``apply_batch`` — per-item webhook chain
        in arrival order, one reconcile at the end: exactly the
        semantics one gateway flush coalesces N concurrent POSTs into)
        and (b) through one gateway flush window produces bit-identical
        journal record sequences and quiescent state dumps; and the
        per-request serial path converges to the same admitted set and
        workload states at quiescence."""
        srv_a, rt_a, j_a = seeded_server(tmp_path, "ja")
        srv_a.apply_batch(
            {"workloads": [o for _, o in self._workload_seq()]}
        )

        gw = WriteGateway(flush_interval_s=0.001, max_batch=64)
        srv_b, rt_b, j_b = seeded_server(tmp_path, "jb", gateway=gw)
        reqs = [gw._enqueue(s, o) for s, o in self._workload_seq()]
        assert gw.flush_once() == self.N
        assert all(r.done.is_set() and r.error is None for r in reqs)

        assert admitted_keys(rt_a) == admitted_keys(rt_b)
        assert journal_sequence(j_a) == journal_sequence(j_b)
        dump_a = json.dumps(ser.runtime_to_state(rt_a), sort_keys=True)
        dump_b = json.dumps(ser.runtime_to_state(rt_b), sort_keys=True)
        assert dump_a == dump_b
        assert rt_b.check_invariants() == []

        # the per-request serial path (one lock + reconcile per POST)
        # journals admissions interleaved differently but converges to
        # the same decisions and workload states at quiescence
        srv_c, rt_c, _ = seeded_server(tmp_path, "jc")
        for section, obj in self._workload_seq():
            srv_c.apply(section, obj)
        assert admitted_keys(rt_c) == admitted_keys(rt_b)
        wls_b = ser.runtime_to_state(rt_b)["workloads"]
        wls_c = ser.runtime_to_state(rt_c)["workloads"]
        assert json.dumps(wls_b, sort_keys=True) == json.dumps(
            wls_c, sort_keys=True
        )

    def test_concurrent_submits_coalesce_into_one_flush(self, tmp_path):
        gw = WriteGateway(flush_interval_s=0.001, max_batch=64)
        srv, rt, _ = seeded_server(tmp_path, gateway=gw)
        results = {}

        def post(i):
            results[i] = gw.submit("workloads", wl_wire(f"c-{i}"))

        threads = [
            threading.Thread(target=post, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with gw._cv:
                if len(gw._queue) == 4:
                    break
            time.sleep(0.002)
        assert gw.flush_once() == 4
        for t in threads:
            t.join(timeout=5)
        assert sorted(results) == [0, 1, 2, 3]
        assert gw.batches == 1 and gw.last_batch == 4
        assert len(admitted_keys(rt)) == 4

    def test_one_recorder_wake_and_one_fsync_per_window(self, tmp_path):
        """N coalesced appends produce exactly ONE EventRecorder
        notify_all (the satellite's wake-latency contract) and, under
        fsync=always group commit, ONE fsync for the whole window."""
        gw = WriteGateway(flush_interval_s=0.001, max_batch=64)
        rt = fresh_rt()
        journal = Journal(str(tmp_path / "jw"), fsync_policy="always").open()
        rt.attach_journal(journal)
        srv = KueueServer(runtime=rt, gateway=gw)
        srv.apply("resourceflavors", {"name": "default"}, reconcile=False)
        srv.apply("clusterqueues", cq_dict("cq-0"), reconcile=False)
        srv.apply(
            "localqueues",
            {"namespace": "ns", "name": "lq-0", "clusterQueue": "cq-0"},
            reconcile=False,
        )
        rt.run_until_idle()

        got = {}

        def watcher():
            # parked before the flush; must wake with the whole window
            got["items"], got["rv"], _ = rt.events.wait(
                rt.events.resource_version, timeout=10.0
            )

        t = threading.Thread(target=watcher)
        t.start()
        time.sleep(0.05)  # let the watcher park
        wakes0 = rt.events.wakes
        fsyncs0 = journal.stats().fsyncs
        for i in range(5):
            gw._enqueue("workloads", wl_wire(f"e-{i}"))
        gw.flush_once()
        t.join(timeout=10)
        assert rt.events.wakes == wakes0 + 1, (
            "a coalesced flush must wake watchers exactly once"
        )
        assert journal.stats().fsyncs == fsyncs0 + 1, (
            "group commit must fsync once per flush window"
        )
        # the single wake delivered every event of the window
        admitted = [
            e for e in got["items"] if e["reason"] == "Admitted"
        ]
        assert len(admitted) == 5

    def test_flush_rejects_bad_item_applies_rest(self, tmp_path):
        gw = WriteGateway(flush_interval_s=0.001)
        srv, rt, _ = seeded_server(tmp_path, gateway=gw)
        good = gw._enqueue("workloads", wl_wire("ok-1"))
        bad = gw._enqueue("workloads", wl_wire("Bad_Name"))
        good2 = gw._enqueue("workloads", wl_wire("ok-2"))
        gw.flush_once()
        assert good.error is None and good2.error is None
        assert bad.error is not None and bad.error.status == 422
        assert len(admitted_keys(rt)) == 2


# ---- backpressure / shedding ----
class TestBackpressure:
    def test_queue_full_shed(self, tmp_path):
        gw = WriteGateway(flush_interval_s=0.01, max_queue=2,
                          tenant_share_cap=1.0)
        seeded_server(tmp_path, gateway=gw)
        gw._enqueue("workloads", wl_wire("a"))
        gw._enqueue("workloads", wl_wire("b"))
        with pytest.raises(GatewayThrottled) as exc:
            gw._enqueue("workloads", wl_wire("c"))
        assert exc.value.reason == "queue_full"
        assert exc.value.retry_after_s > 0
        assert gw.status()["shed"]["queue_full"] == 1

    def test_tenant_share_cap_is_fair(self, tmp_path):
        gw = WriteGateway(flush_interval_s=0.01, max_queue=10,
                          tenant_share_cap=0.2)  # 2 slots per tenant
        seeded_server(tmp_path, gateway=gw)
        gw._enqueue("workloads", wl_wire("a-0", queue="lq-a"))
        gw._enqueue("workloads", wl_wire("a-1", queue="lq-a"))
        with pytest.raises(GatewayThrottled) as exc:
            gw._enqueue("workloads", wl_wire("a-2", queue="lq-a"))
        assert exc.value.reason == "tenant_share"
        # a different tenant still has room: the flood cannot starve it
        gw._enqueue("workloads", wl_wire("b-0", queue="lq-b"))

    def test_rate_limit_shed_and_429_over_http(self, tmp_path):
        clock = FakeClock(0.0)
        gw = WriteGateway(
            flush_interval_s=0.001,
            limiter=TenantLimiter(rate_per_s=1.0, burst=1.0, clock=clock),
        )
        srv, rt, _ = seeded_server(tmp_path, gateway=gw)
        port = srv.start()
        try:
            url = f"http://127.0.0.1:{port}"
            # no retries: the 429 + Retry-After must surface raw
            raw = KueueClient(url, max_429_retries=0)
            raw.apply("workloads", wl_wire("t-0"))
            with pytest.raises(ClientError) as exc:
                raw.apply("workloads", wl_wire("t-1"))
            assert exc.value.status == 429
            assert exc.value.retry_after_s and exc.value.retry_after_s > 0
            assert raw.throttled_total == 1
            # retries: capped jittered backoff waits out the bucket
            # (the FakeClock refills when the client sleeps)
            sleeps = []

            def fake_sleep(s):
                sleeps.append(s)
                clock.advance(max(s, 1.1))

            retrying = KueueClient(
                url, max_429_retries=3, sleep_fn=fake_sleep,
                backoff_base_s=0.01, backoff_cap_s=2.0,
            )
            retrying.apply("workloads", wl_wire("t-2"))
            with gw._cv:
                pass
            assert retrying.throttled_total >= 1
            assert sleeps, "the client must back off before retrying"
            # Retry-After honored: first sleep ~= the advertised wait
            # (1 token at 1/s), jitter-scaled into [1, 1.1)
            assert 0.9 <= sleeps[0] <= 2.0
            m = rt.metrics
            assert m.gateway_shed_total.value(reason="tenant_rate") >= 2
        finally:
            srv.stop()

    def test_retry_after_backoff_is_capped_and_jittered(self):
        import random

        client = KueueClient(
            "http://127.0.0.1:1", backoff_cap_s=0.5, backoff_jitter=0.1,
            rng=random.Random(7),
        )
        d = client._retry_after_delay("30.0", attempt=0)
        assert 0.5 <= d <= 0.55  # capped then jittered
        d2 = client._retry_after_delay(None, attempt=2)
        assert d2 >= client.backoff_base_s * 4


# ---- apply_batch partial failure (satellite) ----
class TestApplyBatchPartialFailure:
    def test_mixed_batch_lands_good_reports_bad(self, tmp_path):
        srv, rt, _ = seeded_server(tmp_path)
        out = srv.apply_batch(
            {
                "workloads": [
                    wl_wire("good-0"),
                    wl_wire("Bad_Name"),
                    wl_wire("good-1"),
                ]
            }
        )
        assert out["applied"] == {"workloads": 2}
        assert out["rejected"] == {"workloads": 1}
        assert "workloads[1]" in out["firstError"]
        assert sorted(admitted_keys(rt)) == ["ns/good-0", "ns/good-1"]

    def test_gateway_batch_same_semantics(self, tmp_path):
        gw = WriteGateway(flush_interval_s=0.001)
        srv, rt, _ = seeded_server(tmp_path, gateway=gw)
        body = {
            "workloads": [wl_wire("g-0"), wl_wire("Bad_Name"),
                          wl_wire("g-1")]
        }
        done = {}

        def run():
            done["out"] = gw.submit_batch(body)

        t = threading.Thread(target=run)
        t.start()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with gw._cv:
                if len(gw._queue) == 3:
                    break
            time.sleep(0.002)
        gw.flush_once()
        t.join(timeout=5)
        out = done["out"]
        assert out["applied"] == {"workloads": 2}
        assert out["rejected"] == {"workloads": 1}
        assert "Bad_Name" not in json.dumps(sorted(rt.workloads))

    def test_transport_surfaces_rejection_as_remote_rejected(self, tmp_path):
        from kueue_tpu.admissionchecks.multikueue_transport import (
            HTTPTransport,
            RemoteRejected,
        )
        from kueue_tpu.models import Workload
        from kueue_tpu.models.workload import PodSet

        srv, rt, _ = seeded_server(tmp_path)
        port = srv.start()
        try:
            tr = HTTPTransport(f"http://127.0.0.1:{port}")
            good = Workload(
                namespace="ns", name="f-good", queue_name="lq-0",
                pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
            )
            bad = Workload(
                namespace="ns", name="F_BAD", queue_name="lq-0",
                pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
            )
            with pytest.raises(RemoteRejected):
                tr.create_workloads([good, bad])
            # partial semantics: the good copy still landed
            assert "ns/f-good" in rt.workloads
        finally:
            srv.stop()


# ---- chaos: crash inside the coalescing flush ----
class TestGatewayChaos:
    M = 5

    def _serial_reference(self, tmp_path):
        srv, rt, _ = seeded_server(tmp_path, "ref")
        for i in range(self.M):
            srv.apply("workloads", wl_wire(f"x-{i}"))
        return admitted_keys(rt)

    def test_crash_mid_flush_recovers_no_loss_no_dup(self, tmp_path):
        """InjectedCrash between consecutive applies of one coalesced
        flush, at EVERY occurrence: PR-4 journal recovery plus client
        re-submit (at-least-once; records are idempotent upserts)
        converges to the serial reference admitted set — no workload
        lost, none duplicated, invariants clean."""
        reference = self._serial_reference(tmp_path)
        # the fault fires before applies 2..M of a batch
        for occurrence in range(self.M - 1):
            name = f"j-{occurrence}"
            gw = WriteGateway(flush_interval_s=0.001, max_batch=64)
            srv, rt, journal = seeded_server(tmp_path, name, gateway=gw)
            for i in range(self.M):
                gw._enqueue("workloads", wl_wire(f"x-{i}"))
            faults.arm("gateway.flush_mid_batch", "crash", skip=occurrence)
            with pytest.raises(faults.InjectedCrash):
                gw.flush_once()
            faults.reset()
            journal.close()
            # recover the journaled prefix into a fresh plane
            res = recover(None, str(tmp_path / name), runtime=fresh_rt(),
                          strict=True)
            rec_rt = res.runtime
            # the clients that never got an ack re-submit everything
            # (idempotent upserts — already-applied copies are no-ops)
            rec_srv = KueueServer(runtime=rec_rt)
            for i in range(self.M):
                rec_srv.apply("workloads", wl_wire(f"x-{i}"))
            assert admitted_keys(rec_rt) == reference, (
                f"occurrence {occurrence}: recovered admitted set "
                "diverged from the serial reference"
            )
            assert len(rec_rt.workloads) == self.M  # no duplicates
            assert rec_rt.check_invariants() == []
            res.journal.close()

    def test_fault_point_is_registered(self):
        assert "gateway.flush_mid_batch" in faults.list_fault_points()


# ---- admission SLOs ----
class TestSLOTracker:
    def _metrics_with(self, observations, cq="cq-0"):
        m = Metrics()
        for v in observations:
            m.trace_queue_to_admission_seconds.observe(v, cluster_queue=cq)
        return m

    def test_attainment_from_histogram(self):
        clock = FakeClock(0.0)
        m = self._metrics_with([0.5] * 9 + [5.0])
        slo = SLOTracker(m, clock=clock)
        slo.set_target("cq-0", 1.0)
        slo.refresh()
        entry = slo.report()["clusterQueues"][0]
        assert entry["attainment"] == pytest.approx(0.9)
        assert entry["admitted"] == 10
        assert entry["withinTarget"] == 9
        assert m.slo_attainment_ratio.value(cluster_queue="cq-0") \
            == pytest.approx(0.9)

    def test_burn_rate_and_sustained_degraded(self):
        clock = FakeClock(0.0)
        m = self._metrics_with([0.1] * 20)
        slo = SLOTracker(
            m, clock=clock, objective=0.95, burn_window_s=100.0,
            burn_threshold=2.0, sustain_s=10.0,
        )
        slo.set_target("cq-0", 1.0)
        slo.refresh()  # baseline: all good, burn 0
        assert not slo.degraded
        # a bad stretch: 5 of 10 new admissions miss the target ->
        # windowed bad fraction 0.5 -> burn 0.5/0.05 = 10x
        clock.advance(5.0)
        for v in [0.1] * 5 + [9.0] * 5:
            m.trace_queue_to_admission_seconds.observe(
                v, cluster_queue="cq-0"
            )
        slo.refresh()
        entry = slo.report()["clusterQueues"][0]
        assert entry["burnRate"] == pytest.approx(10.0)
        assert not entry["degraded"]  # not sustained yet
        clock.advance(11.0)
        slo.refresh()  # still burning, past sustain_s
        assert slo.degraded
        assert m.slo_degraded.value() == 1
        # recovery: a good stretch drops the burn, degraded clears
        clock.advance(200.0)
        for _ in range(50):
            m.trace_queue_to_admission_seconds.observe(
                0.1, cluster_queue="cq-0"
            )
        slo.refresh()
        clock.advance(1.0)
        slo.refresh()
        assert not slo.degraded

    def test_untargeted_cq_ignored_and_default_target(self):
        clock = FakeClock(0.0)
        m = self._metrics_with([0.5], cq="other")
        slo = SLOTracker(m, clock=clock)
        slo.refresh()
        assert slo.report()["clusterQueues"] == []
        assert not slo.enabled
        slo.configure(default_target_s=1.0)
        assert slo.enabled
        slo.refresh()
        assert [e["clusterQueue"] for e in slo.report()["clusterQueues"]] \
            == ["other"]

    def test_healthz_and_slo_route_degraded(self, tmp_path):
        srv, rt, _ = seeded_server(tmp_path)
        rt.slo.configure(
            default_target_s=0.5, burn_threshold=0.5, sustain_s=0.0,
            burn_window_s=1000.0,
        )
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            # one good admission, then a baseline refresh — the burn
            # window needs a pre-bad-stretch snapshot of the series
            rt.metrics.trace_queue_to_admission_seconds.observe(
                0.1, cluster_queue="cq-0"
            )
            client.healthz()
            rt.clock.advance(5.0)
            for _ in range(10):
                rt.metrics.trace_queue_to_admission_seconds.observe(
                    9.0, cluster_queue="cq-0"
                )
            rt.clock.advance(5.0)
            out = client.slo()
            assert out["enabled"]
            assert out["degraded"]
            health = client.healthz()
            assert health["status"] == "degraded"
            assert health["slo"]["degraded"]
            assert "gateway" not in health  # no gateway attached
            text = client.metrics_text()
            assert "kueue_slo_degraded 1" in text
        finally:
            srv.stop()

    def test_slo_families_exposed_at_zero(self):
        text = Metrics().registry.expose()
        for family in (
            "kueue_gateway_requests_total",
            "kueue_gateway_batches_total",
            "kueue_gateway_shed_total",
            "kueue_gateway_queue_depth",
            "kueue_gateway_batch_size",
            "kueue_gateway_flush_duration_seconds",
            "kueue_slo_target_seconds",
            "kueue_slo_attainment_ratio",
            "kueue_slo_error_budget_burn_rate",
            "kueue_slo_degraded",
        ):
            assert f"# TYPE {family}" in text, family

    def test_zero_exposure_lint_rule(self, tmp_path):
        from tests.test_analysis import run_fixture

        bad = (
            "class M:\n"
            "    def __init__(self, r):\n"
            "        self.x = r.counter('kueue_gateway_oops_total', 'h')\n"
        )
        good = bad + "        self.x.inc(0.0)\n"
        findings = run_fixture(
            tmp_path, {"metrics/m.py": bad}, rules=["metrics-families"],
        )
        assert any("materialized at zero" in f.message for f in findings)
        findings = run_fixture(
            tmp_path, {"metrics/m.py": good}, rules=["metrics-families"],
        )
        assert not findings


# ---- replica fan-out trees ----
class TestFanoutChain:
    @pytest.fixture()
    def chain(self, tmp_path):
        """leader -> r1 -> r2, tails driven manually (deterministic)."""
        from kueue_tpu.replica import ReadReplica

        class Chain:
            def __init__(self):
                self.token = [1]
                self.rt = fresh_rt()
                self.journal = Journal(
                    str(tmp_path / "journal"),
                    segment_max_bytes=100 << 10,
                ).open()
                self.journal.token_provider = lambda: self.token[0]
                self.rt.attach_journal(self.journal)
                self.srv = KueueServer(runtime=self.rt)
                port = self.srv.start()
                self.leader_url = f"http://127.0.0.1:{port}"
                self.leader = KueueClient(self.leader_url)
                self.r1 = ReadReplica(
                    self.leader_url, replica_id="rep-1",
                    build_runtime=fresh_rt,
                )
                self.r1srv = KueueServer(replica=self.r1)
                r1port = self.r1srv.start()
                self.r1_url = f"http://127.0.0.1:{r1port}"
                self.r2 = ReadReplica(
                    self.r1_url, replica_id="rep-2",
                    build_runtime=fresh_rt,
                )
                self.r2srv = KueueServer(replica=self.r2)
                r2port = self.r2srv.start()
                self.r2_url = f"http://127.0.0.1:{r2port}"
                self.leader.apply("resourceflavors", {"name": "default"})
                self.leader.apply("clusterqueues", cq_dict("cq-0"))
                self.leader.apply(
                    "localqueues",
                    {"namespace": "ns", "name": "lq-0",
                     "clusterQueue": "cq-0"},
                )
                self.r1.sync(resync=True)
                self.r2.sync(resync=True)

            def sync(self):
                self.r1.sync()
                self.r2.sync()

            def states(self):
                return [
                    json.dumps(KueueClient(u).state(), sort_keys=True)
                    for u in (self.leader_url, self.r1_url, self.r2_url)
                ]

            def close(self):
                self.r2srv.stop()
                self.r1srv.stop()
                self.srv.stop()
                self.journal.close()

        c = Chain()
        yield c
        c.close()

    def test_two_hop_chain_converges_with_hop_and_path_lag(self, chain):
        for i in range(5):
            chain.leader.apply("workloads", wl_wire(f"wl-{i}"))
        chain.sync()
        a, b, c = chain.states()
        assert a == b == c, "2-hop chain must converge byte-identically"
        # topology: r1 is hop 1 off the leader, r2 hop 2 off r1
        assert chain.r1.tailer.hop == 1
        assert chain.r2.tailer.hop == 2
        assert len(chain.r2.tailer.path_lag()) == 2
        # rosters: the leader sees rep-1 (hop 1); r1 sees rep-2 (hop 2)
        leader_roster = chain.leader.replicas()
        assert leader_roster["role"] == "leader"
        ids = {r["id"]: r for r in leader_roster["items"]}
        assert ids["rep-1"]["hop"] == 1
        r1_roster = KueueClient(chain.r1_url).replicas()
        assert r1_roster["role"] == "replica"
        assert r1_roster["items"][0]["hop"] == 1
        kids = {r["id"]: r for r in r1_roster.get("children", [])}
        assert kids["rep-2"]["hop"] == 2
        r2_status = KueueClient(chain.r2_url).replicas()["items"][0]
        assert r2_status["hop"] == 2
        assert len(r2_status["pathLagSeconds"]) == 2

    def test_watch_served_from_hop_two(self, chain):
        chain.leader.apply("workloads", wl_wire("wl-watch"))
        chain.sync()
        c2 = KueueClient(chain.r2_url)
        out = c2.events()
        assert any(
            e["object"] == "ns/wl-watch" for e in out["items"]
        ), "hop-2 replica must serve the mirrored event stream"
        assert c2.served_by_replica

    def test_compaction_jump_propagates_down_the_chain(self, chain):
        for i in range(4):
            chain.leader.apply("workloads", wl_wire(f"pre-{i}"))
        chain.sync()
        r1_resyncs = chain.r1.tailer.resyncs
        r2_resyncs = chain.r2.tailer.resyncs
        # more writes, then compact the leader's journal past both
        # cursors BEFORE either replica polls again
        for i in range(4):
            chain.leader.apply("workloads", wl_wire(f"post-{i}"))
        chain.journal.sync()
        chain.journal.compact(chain.journal.last_seq)
        chain.sync()
        # r1 hit the compaction hole -> checkpoint re-anchor on the
        # leader; its feed log reset forces r2 to re-anchor on r1
        assert chain.r1.tailer.resyncs == r1_resyncs + 1
        assert chain.r2.tailer.resyncs == r2_resyncs + 1
        a, b, c = chain.states()
        assert a == b == c
        # and the chain keeps tailing incrementally afterwards
        chain.leader.apply("workloads", wl_wire("after-jump"))
        chain.sync()
        a, b, c = chain.states()
        assert a == b == c

    def test_leader_handover_reanchors_the_whole_chain(self, chain):
        for i in range(3):
            chain.leader.apply("workloads", wl_wire(f"t1-{i}"))
        chain.sync()
        assert chain.r1.tailer.max_token == 1
        assert chain.r2.tailer.max_token == 1
        # handover: a new leader tenure bumps the fencing token
        chain.token[0] = 2
        for i in range(3):
            chain.leader.apply("workloads", wl_wire(f"t2-{i}"))
        chain.sync()
        chain.sync()  # post-re-anchor incremental poll
        assert chain.r1.tailer.max_token == 2
        assert chain.r2.tailer.max_token == 2
        a, b, c = chain.states()
        assert a == b == c
        # no resync loop: further appends tail incrementally
        r1_resyncs = chain.r1.tailer.resyncs
        chain.leader.apply("workloads", wl_wire("t2-post"))
        chain.sync()
        assert chain.r1.tailer.resyncs == r1_resyncs
        a, b, c = chain.states()
        assert a == b == c

    def test_kueuectl_replicas_renders_hop_columns(self, chain, capsys):
        from kueue_tpu.cli.__main__ import main as cli_main

        chain.leader.apply("workloads", wl_wire("wl-cli"))
        chain.sync()
        cli_main(["replicas", "--server", chain.leader_url])
        out = capsys.readouterr().out
        assert "HOP" in out and "rep-1" in out
        cli_main(["replicas", "--server", chain.r1_url])
        out = capsys.readouterr().out
        assert "rep-2" in out and "downstream replicas" in out
        assert "PATH-LAG" in out

    def test_kueuectl_slo_renders(self, chain, capsys):
        from kueue_tpu.cli.__main__ import main as cli_main

        chain.rt.slo.configure(default_target_s=1.0)
        chain.rt.metrics.trace_queue_to_admission_seconds.observe(
            0.2, cluster_queue="cq-0"
        )
        cli_main(["slo", "--server", chain.leader_url])
        out = capsys.readouterr().out
        assert "CLUSTERQUEUE" in out and "ATTAINMENT" in out
        assert "cq-0" in out
