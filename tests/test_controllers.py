"""Controller / jobframework lifecycle tests.

Scenario coverage mirrors the reference's envtest suites for
pkg/controller/jobframework/reconciler.go (8-step state machine) and
pkg/controller/core/workload_controller.go (admission-check sync,
deactivation, PodsReady timeout + requeue backoff, max execution time).
"""

import pytest

from kueue_tpu.models import (
    AdmissionCheck,
    ClusterQueue,
    LocalQueue,
    ResourceFlavor,
    WorkloadPriorityClass,
)
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.constants import (
    EVICTED_BY_PREEMPTION,
    AdmissionCheckStateType,
    WorkloadConditionType,
)
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.controllers.jobs import BatchJob, JobSet, ReplicatedJob
from kueue_tpu.controllers.workload_controller import WaitForPodsReadyConfig
from kueue_tpu.utils.clock import FakeClock


def make_runtime(quota="10", flavor_labels=None, **kw):
    clock = FakeClock(start=1000.0)
    rt = ClusterRuntime(clock=clock, **kw)
    rt.add_flavor(ResourceFlavor(name="default", node_labels=flavor_labels or {}))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": quota}),)),
            ),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    return rt, clock


class TestBatchJobLifecycle:
    def test_full_happy_path(self):
        rt, clock = make_runtime(flavor_labels={"cloud/instance": "tpu-v5e"})
        job = BatchJob.build("ns", "train", "lq", parallelism=2, requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()

        wl = rt.workloads["ns/job-train"]
        assert wl.is_admitted
        assert not job.is_suspended()
        # flavor node selector injected on start
        assert job.node_selector == {"cloud/instance": "tpu-v5e"}
        assert job.is_active()

        job.complete(success=True)
        rt.run_until_idle()
        assert wl.is_finished
        assert wl.conditions[WorkloadConditionType.FINISHED].reason == "Succeeded"
        # usage released
        assert rt.cache.usage_for("cq") == {} or all(
            v == 0 for v in rt.cache.usage_for("cq").values()
        )

    def test_unmanaged_job_ignored(self):
        rt, _ = make_runtime()
        job = BatchJob.build("ns", "nolabel", "", parallelism=1, requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        assert rt.workloads == {}
        assert job.is_suspended()

    def test_queued_when_no_quota(self):
        rt, clock = make_runtime(quota="1")
        a = BatchJob.build("ns", "a", "lq", parallelism=1, requests={"cpu": "1"})
        b = BatchJob.build("ns", "b", "lq", parallelism=1, requests={"cpu": "1"})
        rt.add_job(a)
        rt.run_until_idle()  # a's workload is created (and admitted) first
        clock.advance(1.0)
        rt.add_job(b)
        rt.run_until_idle()
        assert not a.is_suspended()
        assert b.is_suspended()
        # finishing a releases quota; b admits on the next loop
        a.complete()
        rt.run_until_idle()
        assert not b.is_suspended()

    def test_unsuspended_job_without_admission_is_stopped(self):
        rt, _ = make_runtime()
        job = BatchJob.build("ns", "rogue", "lq", requests={"cpu": "1"})
        job.suspended = False
        job.active_pods = 1
        rt.add_job(job)
        rt.job_reconciler.reconcile(job)  # first pass creates workload
        rt.job_reconciler.reconcile(job)
        assert job.is_suspended()

    def test_partial_admission_scales_parallelism(self):
        rt, _ = make_runtime(quota="3")
        job = BatchJob.build(
            "ns", "elastic", "lq", parallelism=5, requests={"cpu": "1"},
            min_parallelism=2,
        )
        rt.add_job(job)
        rt.run_until_idle()
        assert not job.is_suspended()
        assert job.parallelism == 3  # scaled down to the quota

    def test_workload_recreated_on_spec_change(self):
        rt, _ = make_runtime()
        job = BatchJob.build("ns", "j", "lq", parallelism=1, requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        wl1 = rt.workloads["ns/job-j"]
        # user scales the suspended^W running job: spec no longer matches
        job.complete()  # finish first case is separate; instead change requests
        job.succeeded = 0
        job.requests = {"cpu": 2000}
        rt.run_until_idle()
        wl2 = rt.workloads["ns/job-j"]
        assert wl2 is not wl1
        assert wl2.pod_sets[0].requests == {"cpu": 2000}

    def test_priority_class_resolution(self):
        rt, _ = make_runtime()
        rt.add_priority_class(WorkloadPriorityClass(name="high", value=1000))
        job = BatchJob.build(
            "ns", "vip", "lq", requests={"cpu": "1"}, priority_class="high"
        )
        rt.add_job(job)
        rt.run_until_idle()
        assert rt.workloads["ns/job-vip"].priority == 1000

    def test_job_deletion_releases_workload(self):
        rt, _ = make_runtime(quota="1")
        a = BatchJob.build("ns", "a", "lq", requests={"cpu": "1"})
        b = BatchJob.build("ns", "b", "lq", requests={"cpu": "1"})
        rt.add_job(a)
        rt.run_until_idle()
        rt.clock.advance(1.0)
        rt.add_job(b)
        rt.run_until_idle()
        assert b.is_suspended()
        rt.delete_job(a.key)
        rt.run_until_idle()
        assert not b.is_suspended()


class TestEviction:
    def test_preemption_eviction_requeues_and_restores(self):
        rt, clock = make_runtime(flavor_labels={"x": "y"})
        job = BatchJob.build("ns", "victim", "lq", requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/job-victim"]
        assert not job.is_suspended()

        # preemptor evicts the workload
        wl.set_condition(
            WorkloadConditionType.EVICTED, True, EVICTED_BY_PREEMPTION,
            "Preempted to accommodate a higher priority Workload",
            now=clock.now(),
        )
        rt.reconcile_once()
        assert job.is_suspended()
        assert job.node_selector == {}  # injected selector restored
        assert not wl.has_quota_reservation
        assert wl.condition_true(WorkloadConditionType.REQUEUED)
        # and it comes back once capacity allows
        rt.run_until_idle()
        assert wl.has_quota_reservation

    def test_deactivation_evicts_without_requeue(self):
        rt, clock = make_runtime()
        job = BatchJob.build("ns", "j", "lq", requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/job-j"]
        wl.active = False
        rt.run_until_idle()
        assert job.is_suspended()
        assert not wl.has_quota_reservation
        assert wl.conditions[WorkloadConditionType.EVICTED].reason == "Deactivated"
        # stays out of the queue while inactive
        assert rt.queues.pending_workloads("cq") == 0


class TestAdmissionChecks:
    def make_checked_runtime(self):
        rt, clock = make_runtime()
        rt.add_admission_check(
            AdmissionCheck(name="prov-check", controller_name="test-controller")
        )
        cq = rt.cache.cluster_queues["cq"].model
        cq.admission_checks = ("prov-check",)
        return rt, clock

    def test_two_phase_admission(self):
        rt, clock = self.make_checked_runtime()
        job = BatchJob.build("ns", "j", "lq", requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/job-j"]
        # phase 1: quota reserved, but not admitted until the check is Ready
        assert wl.has_quota_reservation
        assert not wl.is_admitted
        assert job.is_suspended()
        assert wl.admission_check_states["prov-check"].state == AdmissionCheckStateType.PENDING

        wl.admission_check_states["prov-check"].state = AdmissionCheckStateType.READY
        rt.run_until_idle()
        assert wl.is_admitted
        assert not job.is_suspended()

    def test_retry_check_evicts_and_resets(self):
        rt, clock = self.make_checked_runtime()
        job = BatchJob.build("ns", "j", "lq", requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/job-j"]
        wl.admission_check_states["prov-check"].state = AdmissionCheckStateType.RETRY
        rt.reconcile_once()
        assert wl.conditions[WorkloadConditionType.EVICTED].reason == "AdmissionCheck"
        assert wl.admission_check_states["prov-check"].state == AdmissionCheckStateType.PENDING
        # no retry backoff configured -> BackoffFinished immediately and
        # the workload re-reserves quota on the next cycles
        clock.advance(1.0)
        rt.run_until_idle()
        assert wl.has_quota_reservation

    def test_rejected_check_deactivates(self):
        rt, clock = self.make_checked_runtime()
        job = BatchJob.build("ns", "j", "lq", requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/job-j"]
        wl.admission_check_states["prov-check"].state = AdmissionCheckStateType.REJECTED
        rt.run_until_idle()
        assert not wl.active
        assert not wl.has_quota_reservation
        assert rt.queues.pending_workloads("cq") == 0

    def test_podset_updates_injected_on_start(self):
        rt, clock = self.make_checked_runtime()
        job = BatchJob.build("ns", "j", "lq", requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/job-j"]
        st = wl.admission_check_states["prov-check"]
        st.state = AdmissionCheckStateType.READY
        st.pod_set_updates = {"main": {"node_selector": {"autoscaled": "true"}}}
        rt.run_until_idle()
        assert job.node_selector.get("autoscaled") == "true"


class TestWaitForPodsReady:
    def cfg(self, **kw):
        base = dict(
            enable=True, timeout_seconds=60.0,
            backoff_base_seconds=10.0, backoff_max_seconds=3600.0,
        )
        base.update(kw)
        return WaitForPodsReadyConfig(**base)

    def test_pods_ready_condition_set(self):
        rt, clock = make_runtime(wait_for_pods_ready=self.cfg())
        job = BatchJob.build("ns", "j", "lq", requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/job-j"]
        assert not wl.condition_true(WorkloadConditionType.PODS_READY)
        job.mark_pods_ready()
        rt.run_until_idle()
        assert wl.condition_true(WorkloadConditionType.PODS_READY)

    def test_timeout_evicts_with_backoff(self):
        rt, clock = make_runtime(wait_for_pods_ready=self.cfg())
        job = BatchJob.build("ns", "j", "lq", requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/job-j"]
        assert wl.is_admitted

        clock.advance(61.0)  # past the PodsReady timeout
        rt.reconcile_once()
        assert wl.conditions[WorkloadConditionType.EVICTED].reason == "PodsReadyTimeout"
        rt.reconcile_once()
        assert job.is_suspended()
        assert wl.requeue_state.count == 1
        # requeue is gated by the backoff window (10 * 2^0 = 10s)
        assert wl.requeue_state.requeue_at == pytest.approx(clock.now() + 10.0)
        rt.run_until_idle()
        assert not wl.has_quota_reservation or not wl.is_admitted

        clock.advance(11.0)
        rt.run_until_idle()
        assert wl.is_admitted  # readmitted after the backoff

    def test_backoff_limit_deactivates(self):
        rt, clock = make_runtime(
            wait_for_pods_ready=self.cfg(backoff_limit_count=1)
        )
        job = BatchJob.build("ns", "j", "lq", requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/job-j"]
        for _ in range(3):
            clock.advance(4000.0)
            rt.run_until_idle()
        assert not wl.active


class TestMaxExecutionTime:
    def test_exceeding_max_execution_time_deactivates(self):
        rt, clock = make_runtime()
        job = BatchJob.build("ns", "j", "lq", requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/job-j"]
        wl.maximum_execution_time_seconds = 100
        clock.advance(101.0)
        rt.run_until_idle()
        assert not wl.active
        assert job.is_suspended()


class TestJobSet:
    def test_multi_podset_admission(self):
        rt, _ = make_runtime(quota="10")
        js = JobSet(
            namespace="ns", name="train", queue="lq",
            replicated_jobs=(
                ReplicatedJob.build("driver", replicas=1, parallelism=1, requests={"cpu": "1"}),
                ReplicatedJob.build("workers", replicas=2, parallelism=4, requests={"cpu": "1"}),
            ),
        )
        rt.add_job(js)
        rt.run_until_idle()
        wl = rt.workloads["ns/jobset-train"]
        assert wl.is_admitted
        assert not js.is_suspended()
        assert [ps.count for ps in wl.pod_sets] == [1, 8]
        js.complete()
        rt.run_until_idle()
        assert wl.is_finished

    def test_jobset_too_big_queued(self):
        rt, _ = make_runtime(quota="5")
        js = JobSet(
            namespace="ns", name="big", queue="lq",
            replicated_jobs=(
                ReplicatedJob.build("w", replicas=2, parallelism=4, requests={"cpu": "1"}),
            ),
        )
        rt.add_job(js)
        rt.run_until_idle()
        assert js.is_suspended()


class TestReclaimablePods:
    def test_succeeded_pods_free_quota(self):
        rt, _ = make_runtime(quota="4")
        a = BatchJob.build("ns", "a", "lq", parallelism=4, completions=4, requests={"cpu": "1"})
        rt.add_job(a)
        rt.run_until_idle()
        assert not a.is_suspended()
        b = BatchJob.build("ns", "b", "lq", parallelism=2, requests={"cpu": "1"})
        rt.add_job(b)
        rt.run_until_idle()
        assert b.is_suspended()
        # two of a's pods succeed -> reclaimable -> b fits
        a.succeeded = 2
        rt.run_until_idle()
        assert not b.is_suspended()


class TestWatcherFanOut:
    """clusterqueue_controller.go:137-380 watcher fan-out: objects a CQ
    depends on APPEARING must wake workloads parked on the
    corresponding *NotFound reason."""

    def test_late_flavor_reactivates(self):
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.models import (
            ClusterQueue,
            FlavorQuotas,
            LocalQueue,
            ResourceFlavor,
            Workload,
        )
        from kueue_tpu.models.cluster_queue import ResourceGroup
        from kueue_tpu.models.workload import PodSet

        rt = ClusterRuntime()
        rt.add_cluster_queue(
            ClusterQueue(
                name="cq", namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (FlavorQuotas.build("late-flavor", {"cpu": "8"}),),
                    ),
                ),
            )
        )
        rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
        wl = Workload(
            namespace="ns", name="w", queue_name="lq",
            pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
        )
        rt.add_workload(wl)
        rt.run_until_idle()
        assert not wl.is_admitted
        assert "FlavorNotFound" in rt.cache.cluster_queue_status("cq").reasons
        rt.add_flavor(ResourceFlavor(name="late-flavor"))
        rt.run_until_idle()
        assert wl.is_admitted

    def test_flavor_update_fixing_topology_ref_reactivates(self):
        """A flavor UPDATE (corrected topology_name) must also wake
        parked heads, not just flavor creation."""
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.models import (
            ClusterQueue,
            FlavorQuotas,
            LocalQueue,
            ResourceFlavor,
            Workload,
        )
        from kueue_tpu.models.cluster_queue import ResourceGroup
        from kueue_tpu.models.topology import Topology, TopologyLevel
        from kueue_tpu.models.workload import PodSet
        from kueue_tpu.resources import requests_from_spec
        from kueue_tpu.tas.cache import Node, TASCache

        rt = ClusterRuntime(tas_cache=TASCache())
        rt.add_topology(
            Topology(
                name="real-topo",
                levels=(TopologyLevel("kubernetes.io/hostname"),),
            )
        )
        rt.add_node(
            Node(
                name="n1", labels={"kubernetes.io/hostname": "n1"},
                allocatable=requests_from_spec({"cpu": "8", "pods": "10"}),
            )
        )
        rt.add_flavor(ResourceFlavor(name="f", topology_name="typo-topo"))
        rt.add_cluster_queue(
            ClusterQueue(
                name="cq", namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",), (FlavorQuotas.build("f", {"cpu": "8"}),)
                    ),
                ),
            )
        )
        rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
        wl = Workload(
            namespace="ns", name="w", queue_name="lq",
            pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
        )
        rt.add_workload(wl)
        rt.run_until_idle()
        assert not wl.is_admitted  # TopologyNotFound
        rt.add_flavor(ResourceFlavor(name="f", topology_name="real-topo"))
        rt.run_until_idle()
        assert wl.is_admitted

    def test_late_topology_reactivates(self):
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.models import (
            ClusterQueue,
            FlavorQuotas,
            LocalQueue,
            ResourceFlavor,
            Workload,
        )
        from kueue_tpu.models.cluster_queue import ResourceGroup
        from kueue_tpu.models.topology import Topology, TopologyLevel
        from kueue_tpu.models.workload import PodSet
        from kueue_tpu.resources import requests_from_spec
        from kueue_tpu.tas.cache import Node, TASCache

        rt = ClusterRuntime(tas_cache=TASCache())
        rt.add_flavor(ResourceFlavor(name="tas-f", topology_name="late-topo"))
        rt.add_cluster_queue(
            ClusterQueue(
                name="cq", namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",), (FlavorQuotas.build("tas-f", {"cpu": "8"}),)
                    ),
                ),
            )
        )
        rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
        wl = Workload(
            namespace="ns", name="w", queue_name="lq",
            pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
        )
        rt.add_workload(wl)
        rt.run_until_idle()
        assert not wl.is_admitted
        rt.add_topology(
            Topology(
                name="late-topo",
                levels=(TopologyLevel("kubernetes.io/hostname"),),
            )
        )
        rt.add_node(
            Node(
                name="n1", labels={"kubernetes.io/hostname": "n1"},
                allocatable=requests_from_spec({"cpu": "8", "pods": "10"}),
            )
        )
        rt.run_until_idle()
        assert wl.is_admitted
