"""Elastic capacity plane tests (ISSUE-18).

The acceptance criteria, as tests:

- a seeded trace whose demand outruns static capacity parks its gangs
  forever with elasticity OFF and admits everything exactly once with
  elasticity ON;
- the chooser scores every candidate flavor delta in ONE batched
  plan_kernel launch and matches the host-side argmax oracle;
- crash occurrence-sweeps at the two new fault points
  (``provisioning.mid_flip``, ``elastic.grant_mid_apply``) recover to
  the no-crash admitted set with clean invariants;
- the BookingExpired retry ladder backs off b*2^(n-1) and exhaustion
  lands on a canonical inadmissible reason;
- dynamic federation membership (join / cordon-flap / drain / leave)
  under load preserves exactly-one admission on every plane.
"""

import pytest

from kueue_tpu.admissionchecks import (
    PROVISIONING_CONTROLLER_NAME,
    ProvisioningController,
    ProvisioningRequestConfig,
)
from kueue_tpu.admissionchecks.provisioning import (
    PR_BOOKING_EXPIRED,
    PR_FAILED,
    PR_PENDING,
    PR_PROVISIONED,
    RetryStrategy,
)
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.controllers.jobs import BatchJob
from kueue_tpu.elastic import (
    ElasticCapacityPlane,
    SimulatedProvider,
    attach_elastic_plane,
)
from kueue_tpu.models import (
    AdmissionCheck,
    ClusterQueue,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.constants import (
    AdmissionCheckStateType,
    InadmissibleReason,
    WorkloadConditionType,
    classify_inadmissible_message,
)
from kueue_tpu.models.workload import PodSet
from kueue_tpu.testing import faults
from kueue_tpu.utils.clock import FakeClock


def elastic_config(rt, quota="4"):
    """Flavor + checked CQ + LQ — identical on every boot, so crash
    recovery replays onto the same static config (the server pattern:
    config from flags/file, state from checkpoint + journal)."""
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq", namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",),
                    (FlavorQuotas.build("default", {"cpu": quota}),),
                ),
            ),
        )
    )
    rt.add_local_queue(
        LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
    )
    rt.add_admission_check(
        AdmissionCheck(
            name="prov", controller_name=PROVISIONING_CONTROLLER_NAME,
            parameters="prc",
        )
    )
    rt.cache.cluster_queues["cq"].model.admission_checks = ("prov",)


def wire_provisioning(rt, retry=None):
    ctrl = ProvisioningController(rt)
    ctrl.add_config(
        ProvisioningRequestConfig(
            name="prc", retry_strategy=retry or RetryStrategy(),
        )
    )
    rt.admission_check_controllers.append(ctrl.reconcile)
    return ctrl


def make_elastic(quota="4", provider=None, use_device=False, retry=None):
    clock = FakeClock(1000.0)
    rt = ClusterRuntime(clock=clock, use_solver=False)
    elastic_config(rt, quota=quota)
    ctrl = wire_provisioning(rt, retry=retry)
    provider = provider or SimulatedProvider(
        clock=clock, provision_delay_s=5.0
    )
    plane = ElasticCapacityPlane(rt, ctrl, provider, use_device=use_device)
    rt.admission_check_controllers.append(plane)
    rt.elastic = plane
    return rt, ctrl, plane, clock


def gang(i, pods=3):
    """One gang workload: ``pods`` x 1 cpu, all-or-nothing."""
    return Workload(
        namespace="ns", name=f"g{i}", queue_name="lq", priority=i,
        pod_sets=(PodSet.build("main", pods, {"cpu": "1"}),),
    )


def admitted_keys(rt):
    return {k for k, wl in rt.workloads.items() if wl.is_admitted}


def drive(rt, rounds=40, step_s=6.0, want=None):
    for _ in range(rounds):
        rt.run_until_idle()
        if want is not None and len(admitted_keys(rt)) == want:
            return
        rt.clock.advance(step_s)
    rt.run_until_idle()


# ---- the acceptance trace: demand outruns static capacity ----
class TestDemandOutrunsCapacity:
    N_GANGS = 4  # 4 gangs x 3 cpu against 4 cpu nominal

    def test_parks_forever_without_elasticity(self):
        """No capacity provider: the open-loop ProvisioningRequest
        protocol never flips, so the first gang waits on its check and
        the rest park on quota — forever."""
        clock = FakeClock(1000.0)
        rt = ClusterRuntime(clock=clock, use_solver=False)
        elastic_config(rt)
        wire_provisioning(rt)
        for i in range(self.N_GANGS):
            rt.add_workload(gang(i))
        drive(rt, rounds=25)
        assert admitted_keys(rt) == set()
        assert rt.check_invariants() == []

    def test_admits_everything_exactly_once_with_elasticity(self):
        rt, ctrl, plane, clock = make_elastic()
        for i in range(self.N_GANGS):
            rt.add_workload(gang(i))
        drive(rt, want=self.N_GANGS)
        assert admitted_keys(rt) == {f"ns/g{i}" for i in range(self.N_GANGS)}
        assert rt.check_invariants() == []
        # each admission consumed exactly one grant, never re-applied
        assert len(plane._applied) == self.N_GANGS
        granted = plane.provider.granted_totals()
        assert granted == {"default": {"cpu": 3000 * self.N_GANGS}}
        # the quota the journal records is the POST nominal the cache
        # now carries (replay convergence); amounts are milli-units
        assert plane._current_nominal("cq", "default", "cpu") == (
            4 + 3 * self.N_GANGS
        ) * 1000
        # evented + gauged
        reasons = {e.kind for e in rt.events}
        assert "ElasticCapacityGranted" in reasons
        assert "Provisioned" in reasons

    def test_revoke_withdraws_quota_and_requeues(self):
        """Provider-side reclaim before admission: the journaled
        elastic_revoke shrinks nominal back and the check controller
        walks the workload onto the retry ladder."""
        rt, ctrl, plane, clock = make_elastic()
        rt.add_workload(gang(0))
        drive(rt, rounds=4, want=1)
        assert admitted_keys(rt) == {"ns/g0"}
        request = next(iter(plane._applied))
        # external reclaim (spot preemption)
        assert plane.provider.revoke(request, "spot reclaim")
        drive(rt, rounds=2, step_s=1.0)
        # grant withdrawn: applied set empty, nominal back at base
        assert request not in plane._applied
        assert plane._current_nominal("cq", "default", "cpu") >= 4000
        reasons = {e.kind for e in rt.events}
        assert "CapacityRevoked" in reasons
        assert rt.check_invariants() == []

    def test_capacity_limited_provider_walks_retry_ladder(self):
        """Asks beyond the provider's headroom FAIL like a cloud quota
        denial — the check controller retries with backoff and the
        workload stays pending, never wedged."""
        provider = SimulatedProvider(
            provision_delay_s=5.0,
            capacity_limits={"default": {"cpu": 3000}},  # milli-units
        )
        rt, ctrl, plane, clock = make_elastic(provider=provider)
        provider.clock = clock
        # priority=i: g1 reserves quota first, its ask fits the cap;
        # g0's ask is then denied forever (all-or-nothing headroom)
        rt.add_workload(gang(0))
        rt.add_workload(gang(1))
        drive(rt, rounds=8)
        assert "ns/g1" in admitted_keys(rt)
        assert "ns/g0" not in admitted_keys(rt)
        # the denial surfaced as ProvisioningFailed, not silence
        reasons = {e.kind for e in rt.events}
        assert "ProvisioningFailed" in reasons
        assert rt.check_invariants() == []


# ---- chooser: one batched launch, host-oracle equivalence ----
class TestChooser:
    def _contended(self):
        """Two PRs pending at once with DIFFERENT asks: the small gang
        asks +2, the big one +4; a parked 4-cpu workload is only
        unblocked by the big scale-up, so the chooser must rank the
        big PR first. priority=i, so g2 (4 pods) reserves first, g0
        (2 pods) fills the remainder, g1 (4 pods) parks."""
        clock = FakeClock(1000.0)
        rt = ClusterRuntime(clock=clock, use_solver=False)
        elastic_config(rt, quota="6")
        ctrl = wire_provisioning(rt)
        rt.add_workload(gang(0, pods=2))  # reserves 2
        rt.add_workload(gang(1, pods=4))  # parked: 4 > 0 free
        rt.add_workload(gang(2, pods=4))  # reserves 4 (top priority)
        rt.run_until_idle()
        plane = ElasticCapacityPlane(
            rt, ctrl, SimulatedProvider(clock=clock), use_device=True
        )
        return rt, plane

    def test_batched_launch_matches_host_oracle(self):
        rt, plane = self._contended()
        candidates = plane.pending_candidates()
        assert len(candidates) == 2
        dev_winner, dev_report = plane.choose(candidates, use_device=True)
        dev_choice = dict(plane.last_choice)
        host_winner, host_report = plane.choose(candidates, use_device=False)
        host_choice = dict(plane.last_choice)
        # ONE device launch scores every candidate
        assert dev_report.launches == 1
        assert host_report.launches == 0
        # bit-for-bit the host argmax: same winner, same scores
        assert dev_winner.request == host_winner.request
        assert dev_choice["scores"] == host_choice["scores"]
        # and the winner is the scale-up that unblocks parked work:
        # g2's +4 grant frees room for the parked 4-cpu g1, g0's +2
        # does not
        assert dev_winner.request == "g2-prov-1"
        assert dev_choice["scores"]["g2-prov-1"] > dev_choice["scores"][
            "g0-prov-1"
        ]

    def test_deterministic_tiebreak_on_equal_scores(self):
        """Identical asks score identically: the cheaper delta wins,
        then the request name — stable across backends."""
        clock = FakeClock(1000.0)
        rt = ClusterRuntime(clock=clock, use_solver=False)
        elastic_config(rt, quota="6")
        ctrl = wire_provisioning(rt)
        rt.add_workload(gang(0, pods=3))
        rt.add_workload(gang(1, pods=3))
        rt.run_until_idle()
        plane = ElasticCapacityPlane(
            rt, ctrl, SimulatedProvider(clock=clock), use_device=False
        )
        candidates = plane.pending_candidates()
        assert len(candidates) == 2
        winner, _ = plane.choose(candidates)
        assert winner.request == "g0-prov-1"  # name tiebreak

    def test_single_candidate_skips_the_launch(self):
        """The end-to-end loop with one pending PR at a time performs
        the argmax over one element without any launch."""
        rt, ctrl, plane, clock = make_elastic()
        rt.add_workload(gang(0))
        drive(rt, rounds=4, want=1)
        assert admitted_keys(rt) == {"ns/g0"}
        assert plane.chooser_launches == 0

    def test_loop_uses_batched_chooser_under_contention(self):
        """Multiple simultaneous pending PRs force the batched path in
        the live loop; everything still admits exactly once. The plane
        attaches AFTER both PRs exist (the restart-into-backlog shape),
        so its first submit pass genuinely sees >1 candidate."""
        clock = FakeClock(1000.0)
        rt = ClusterRuntime(clock=clock, use_solver=False)
        elastic_config(rt, quota="6")
        ctrl = wire_provisioning(rt)
        rt.add_workload(gang(0, pods=2))
        rt.add_workload(gang(1, pods=4))
        rt.add_workload(gang(2, pods=4))
        rt.run_until_idle()  # both reservations' PRs now pending
        plane = ElasticCapacityPlane(
            rt, ctrl, SimulatedProvider(clock=clock), use_device=False
        )
        rt.admission_check_controllers.append(plane)
        rt.elastic = plane
        drive(rt, want=3)
        assert admitted_keys(rt) == {"ns/g0", "ns/g1", "ns/g2"}
        assert plane.chooser_launches >= 1
        assert plane.last_choice is not None
        assert rt.check_invariants() == []


# ---- retry ladder ----
class TestRetryLadder:
    def make(self, retry):
        clock = FakeClock(1000.0)
        rt = ClusterRuntime(clock=clock, use_solver=False)
        elastic_config(rt, quota="10")
        ctrl = wire_provisioning(rt, retry=retry)
        return rt, ctrl, clock

    def test_booking_expired_backoff_doubles(self):
        retry = RetryStrategy(
            backoff_limit_count=3, backoff_base_seconds=30.0,
            backoff_max_seconds=1800.0,
        )
        rt, ctrl, clock = self.make(retry)
        job = BatchJob.build("ns", "j", "lq", parallelism=2,
                             requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/job-j"]
        observed = []
        for attempt in (1, 2, 3):
            pr = ctrl.active_request_for(wl, "prov")
            assert pr is not None and pr.attempt == attempt
            pr.state = PR_BOOKING_EXPIRED
            before = clock.now()
            rt.run_until_idle()
            observed.append(ctrl._retry_after[(wl.key, "prov")] - before)
            # mid-ladder: the canonical PENDING-with-backoff state
            st = wl.admission_check_states["prov"]
            assert st.state == AdmissionCheckStateType.PENDING
            clock.advance(observed[-1] + 1.0)
            rt.run_until_idle()
        # b*2^(n-1): 30, 60, 120
        assert observed == [30.0, 60.0, 120.0]

    def test_exhaustion_lands_on_canonical_inadmissible_reason(self):
        retry = RetryStrategy(
            backoff_limit_count=1, backoff_base_seconds=30.0,
        )
        rt, ctrl, clock = self.make(retry)
        job = BatchJob.build("ns", "j", "lq", parallelism=2,
                             requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/job-j"]
        pr1 = ctrl.active_request_for(wl, "prov")
        pr1.state = PR_BOOKING_EXPIRED
        pr1.message = "booking window lapsed"
        rt.run_until_idle()
        clock.advance(31.0)
        rt.run_until_idle()
        pr2 = ctrl.active_request_for(wl, "prov")
        assert pr2.attempt == 2
        pr2.state = PR_FAILED
        rt.run_until_idle()
        # retry budget exhausted -> Rejected -> deactivated + suspended
        st = wl.admission_check_states["prov"]
        assert st.state == AdmissionCheckStateType.REJECTED
        assert not wl.active
        assert job.is_suspended()
        # the terminal eviction carries the CANONICAL inadmissible
        # message — classify maps it onto the enum, never UNKNOWN (the
        # audit lint's contract)
        evicted = wl.conditions[WorkloadConditionType.EVICTED]
        reason = classify_inadmissible_message(evicted.message)
        assert reason == InadmissibleReason.DEACTIVATED
        assert reason != InadmissibleReason.UNKNOWN
        # the exhaustion evented with the budget in the message
        msgs = [
            e.message for e in rt.events
            if e.kind == "ProvisioningFailed"
        ]
        assert any("exhausted" in m for m in msgs)
        # the deactivated workload is OUT of the queues: the scheduler
        # never nominates it again
        res = rt.scheduler.schedule()
        assert wl.key not in {e.workload.key for e in res.requeued}
        assert not wl.is_admitted


# ---- crash sweeps at the two new fault points ----
ELASTIC_CRASH_POINTS = ("provisioning.mid_flip", "elastic.grant_mid_apply")


def boot_elastic(tmp_path, provider, clock_start):
    """The server boot order: static config, recovery replay (grants
    land on top of base quota), journal attach, then the plane — which
    ADOPTS applied grants instead of re-asking the provider."""
    from kueue_tpu.storage import recover

    clock = FakeClock(clock_start)
    rt = ClusterRuntime(clock=clock, use_solver=False)
    elastic_config(rt)
    res = recover(None, str(tmp_path / "journal"), runtime=rt, strict=True)
    rt.attach_journal(res.journal)
    provider.clock = clock  # the provider is EXTERNAL: it survives
    ctrl = wire_provisioning(rt)
    plane = ElasticCapacityPlane(rt, ctrl, provider, use_device=False)
    rt.admission_check_controllers.append(plane)
    rt.elastic = plane
    return rt


def run_elastic_trace(tmp_path, crash_point=None, skip=0, n_gangs=3):
    provider = SimulatedProvider(provision_delay_s=5.0)
    clock_now = [1000.0]
    rt = boot_elastic(tmp_path, provider, clock_now[0])
    for i in range(n_gangs):
        rt.add_workload(gang(i))
    if crash_point is not None:
        faults.arm(crash_point, "crash", skip=skip)
    crashed = False
    rounds = 0
    while rounds < 40:
        try:
            rt.run_until_idle()
            rt.clock.advance(6.0)
            clock_now[0] = rt.clock.now()
            rounds += 1
            if len(admitted_keys(rt)) == n_gangs:
                break
        except faults.InjectedCrash:
            assert not crashed, "fault stayed armed after recovery"
            crashed = True
            faults.reset()
            # process death: rebuild from the journal; the provider —
            # an external autoscaler — keeps its state
            rt = boot_elastic(tmp_path, provider, clock_now[0])
    try:
        rt.run_until_idle()
    finally:
        rt.journal.close()
    return rt, crashed


class TestElasticCrashSweep:
    def _expected(self, tmp_path):
        base = tmp_path / "base"
        base.mkdir()
        rt, crashed = run_elastic_trace(base)
        assert not crashed
        want = admitted_keys(rt)
        assert len(want) == 3
        return want

    @pytest.mark.parametrize("point", ELASTIC_CRASH_POINTS)
    @pytest.mark.parametrize("skip", [0, 1, 2])
    def test_crash_recover_converges(self, tmp_path, point, skip):
        """Crash at every occurrence of both torn windows: recovery
        must converge to the no-crash admitted set with the grant
        applied exactly once (quota equals base + one grant per gang —
        a double-apply would overshoot, a drop would park a gang)."""
        want = self._expected(tmp_path)
        case = tmp_path / f"{point.replace('.', '-')}-{skip}"
        case.mkdir()
        rt, crashed = run_elastic_trace(case, crash_point=point, skip=skip)
        assert admitted_keys(rt) == want
        assert rt.check_invariants() == []
        assert rt.elastic._current_nominal("cq", "default", "cpu") == (
            4 + 3 * len(want)
        ) * 1000
        # recovery adopted the durable grants: the provider was asked
        # for each gang's capacity AT MOST once per submission attempt,
        # and holds exactly the granted total
        assert rt.elastic.provider.granted_totals() == {
            "default": {"cpu": 3000 * len(want)}
        }

    def test_mid_flip_crash_actually_fires(self, tmp_path):
        """Guard against the sweep silently testing nothing."""
        self._expected(tmp_path)
        case = tmp_path / "fires"
        case.mkdir()
        _, crashed = run_elastic_trace(
            case, crash_point="provisioning.mid_flip", skip=0
        )
        assert crashed

    def test_grant_mid_apply_crash_actually_fires(self, tmp_path):
        self._expected(tmp_path)
        case = tmp_path / "fires2"
        case.mkdir()
        _, crashed = run_elastic_trace(
            case, crash_point="elastic.grant_mid_apply", skip=0
        )
        assert crashed


# ---- dynamic federation membership under load ----
class TestMembershipChurn:
    def _federation(self, n_workers=3, quota=10):
        from kueue_tpu.admissionchecks.multikueue import MultiKueueCluster
        from kueue_tpu.federation import FederationDispatcher

        clock = FakeClock(0.0)

        def worker():
            rt = ClusterRuntime(clock=clock, use_solver=False)
            rt.add_flavor(ResourceFlavor(name="default"))
            rt.add_cluster_queue(
                ClusterQueue(
                    name="cq", namespace_selector={},
                    resource_groups=(
                        ResourceGroup(
                            ("cpu",),
                            (
                                FlavorQuotas.build(
                                    "default", {"cpu": str(quota)}
                                ),
                            ),
                        ),
                    ),
                )
            )
            rt.add_local_queue(
                LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
            )
            return rt

        planes = {f"w{i}": worker() for i in range(n_workers)}
        manager = ClusterRuntime(clock=clock)
        disp = FederationDispatcher(
            manager,
            clusters={
                name: MultiKueueCluster(name=name, runtime=rt)
                for name, rt in planes.items()
            },
            drive_inprocess=True,
        )
        return manager, disp, planes, clock, worker, MultiKueueCluster

    def _settle(self, manager, clock, want):
        for _ in range(60):
            manager.run_until_idle()
            clock.advance(1.0)
            if len(admitted_keys(manager)) == want:
                return
        raise AssertionError(
            f"{len(admitted_keys(manager))}/{want} admitted"
        )

    def _assert_exactly_once(self, manager, planes):
        for key in admitted_keys(manager):
            holders = [
                n for n, rt in planes.items() if key in rt.workloads
            ]
            assert len(holders) == 1, f"{key} held by {holders}"
        for name, rt in planes.items():
            assert rt.check_invariants() == [], name
        assert manager.check_invariants() == []

    def test_cordoned_worker_receives_no_new_dispatches(self):
        manager, disp, planes, clock, worker, MKC = self._federation()
        assert disp.cordon("w0")
        for i in range(6):
            manager.add_workload(
                Workload(
                    namespace="ns", name=f"c{i}", queue_name="lq",
                    priority=i,
                    pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
                )
            )
        self._settle(manager, clock, 6)
        assert len(planes["w0"].workloads) == 0
        assert "w0" in disp.health_report()["cordoned"]
        # cordon is operator intent, not degradation
        assert disp.health_report()["degraded"] is False
        self._assert_exactly_once(manager, planes)

    def test_join_drain_flap_preserves_exactly_once(self):
        """The membership-churn chaos suite: workers join at runtime,
        loaded workers drain-ahead and leave, a survivor cordon-flaps —
        every workload stays admitted exactly once on exactly one
        plane, every plane's invariants clean."""
        manager, disp, planes, clock, worker, MKC = self._federation()
        n_wl = 18
        for i in range(n_wl):
            manager.add_workload(
                Workload(
                    namespace="ns", name=f"m{i}", queue_name="lq",
                    priority=i,
                    pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
                )
            )
        self._settle(manager, clock, n_wl)
        # runtime JOIN
        planes["w3"] = worker()
        disp.add_worker(MKC(name="w3", runtime=planes["w3"]))
        # cordon FLAP on a survivor
        assert disp.cordon("w1")
        assert disp.uncordon("w1")
        # drain-ahead scale-down of a loaded worker, then leave
        deposed = disp.drain_worker("w0")
        assert deposed > 0 or len(planes["w0"].workloads) == 0
        self._settle(manager, clock, n_wl)
        assert disp.remove_worker("w0")
        removed = planes.pop("w0")
        self._settle(manager, clock, n_wl)
        live = [
            k for k, wl in removed.workloads.items()
            if not wl.is_finished and wl.is_admitted
        ]
        assert live == [], f"removed worker still runs {live}"
        self._assert_exactly_once(manager, planes)
        # a second churn round against the reshaped roster
        planes["w4"] = worker()
        disp.add_worker(MKC(name="w4", runtime=planes["w4"]))
        assert disp.remove_worker("w1")
        planes.pop("w1")
        self._settle(manager, clock, n_wl)
        self._assert_exactly_once(manager, planes)

    def test_drain_is_strikeless(self):
        """Operator-initiated drain must not quarantine the worker:
        rejoin is clean."""
        manager, disp, planes, clock, worker, MKC = self._federation()
        for i in range(6):
            manager.add_workload(
                Workload(
                    namespace="ns", name=f"s{i}", queue_name="lq",
                    priority=i,
                    pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
                )
            )
        self._settle(manager, clock, 6)
        disp.drain_worker("w0")
        self._settle(manager, clock, 6)
        assert disp.health[
            "w0"
        ].strikes == 0, "drain must not strike the worker"
        # rejoin: uncordon readmits it to dispatch
        assert disp.uncordon("w0")
        assert "w0" not in disp.cordoned


# ---- surfaces ----
class TestSurfaces:
    def test_plan_request_carries_elastic_section(self):
        from kueue_tpu.planner.engine import plan_request

        rt, ctrl, plane, clock = make_elastic()
        rt.add_workload(gang(0))
        rt.run_until_idle()
        out = plan_request(rt, {"target": {"clusterQueue": "cq"}})
        assert out["elastic"]["enabled"] is True
        assert out["elastic"]["provider"] == "SimulatedProvider"

    def test_status_reports_choice_and_grants(self):
        clock = FakeClock(1000.0)
        rt = ClusterRuntime(clock=clock, use_solver=False)
        elastic_config(rt, quota="6")
        ctrl = wire_provisioning(rt)
        rt.add_workload(gang(0, pods=2))
        rt.add_workload(gang(1, pods=4))
        rt.add_workload(gang(2, pods=4))
        rt.run_until_idle()  # two PRs pending before the plane attaches
        plane = ElasticCapacityPlane(
            rt, ctrl, SimulatedProvider(clock=clock), use_device=False
        )
        rt.admission_check_controllers.append(plane)
        rt.elastic = plane
        drive(rt, want=3)
        st = plane.status()
        assert st["enabled"] and st["provider"] == "SimulatedProvider"
        assert st["granted"] == {"default": {"cpu": 10000}}
        assert st["chooserLaunches"] >= 1
        assert st["lastChoice"]["chosen"] in st["appliedRequests"]

    def test_attach_reuses_existing_controller(self):
        clock = FakeClock(1000.0)
        rt = ClusterRuntime(clock=clock, use_solver=False)
        elastic_config(rt)
        ctrl = wire_provisioning(rt)
        plane = attach_elastic_plane(rt, use_device=False)
        assert plane.controller is ctrl
        assert rt.elastic is plane

    def test_metrics_families_materialized_and_move(self):
        rt, ctrl, plane, clock = make_elastic()
        text = rt.metrics.registry.expose()
        assert "kueue_provisioning_requests_total" in text
        assert "kueue_elastic_grants_total" in text
        rt.add_workload(gang(0))
        drive(rt, rounds=4, want=1)
        text = rt.metrics.registry.expose()
        assert 'state="provisioned"' in text
